"""Resilient sweep execution: retry, deadlines, poison-cell quarantine,
journaled resume.

The grouped executor (:func:`blades_tpu.sweeps.run_grouped`) made the
cert/chaos sweeps fast (one compiled program per program-shape group,
PR 12) but brittle in exactly the dimension this box punishes: one
failing cell in a batched group re-raised after stamping ok:false on
every sibling — a whole group's results lost to one poison cell — and
any process death restarted the sweep from zero. This module is the
robustness layer around it, the request-level failure isolation the
ROADMAP's sweep server (item 2) needs before it can serve traffic:

- **Bounded-backoff retry** — a failed execution is retried on the
  shared :func:`~blades_tpu.utils.retry.backoff_delay` curve (the same
  curve the in-process host retries and the supervisor's relaunch budget
  degrade on), with each retry emitted as a schema-locked ``retry``
  record. Timing and compile counters restart per attempt, so a failed
  try's wall and the backoff sleep never pollute the successful
  attempt's accounting. Transient failures (tunnel flake,
  collective-rendezvous deadlock, Unavailable-class backend errors) heal
  without losing work.

- **Per-cell deadlines** (:func:`soft_deadline`) — an execution of C
  cells is bounded by ``cell_deadline_s x C``. Soft by design: SIGALRM
  can only interrupt the interpreter between bytecodes, so a launch stuck
  inside an XLA collective trips the deadline when control returns (or
  never — the supervision heartbeat watchdog is the HARD layer that kills
  the whole process group; docs/robustness.md "Resumable sweeps" sizes
  the two against each other). A tripped deadline is an ordinary
  retryable failure: retry, then degrade.

- **Quarantine by bisection** — when a batched group's retry budget is
  exhausted, the group is split and each half re-executed (the halves
  re-enter the same :func:`~blades_tpu.sweeps._execute_group` body),
  recursively, so a poison cell is isolated while every innocent
  sibling's result is salvaged by the largest passing subgroups. The
  isolated cell gets a final per-cell retry, then a ``quarantine``
  record carrying the exception type + message + the group's program
  fingerprint — an attributable failure, not a flag — and the sweep
  moves on. This is the degrade ladder batched -> subgroup ->
  sequential -> quarantine.

- **Journaled resume** — every completed cell's result is appended to a
  :class:`~blades_tpu.sweeps.journal.SweepJournal` at the cell boundary
  (journal first, telemetry second: a crash between the two re-executes
  the cell rather than losing it). A relaunch under ``BLADES_RESUME=1``
  recovers completed (and quarantined) cells from the journal and
  executes only the remainder; recovered cells re-emit zero-wall
  ``resumed: true`` sweep records so the i-of-N progress trail stays
  monotone and a resumed sweep is distinguishable from a clean one
  (``scripts/sweep_status.py``).

Two executors share ONE set of record-emitting primitives
(``_emit_retry`` / ``_quarantine_cell`` / ``_recover_cell``), so their
trails are identical by construction: :func:`run_grouped_resilient` for
batched program-shape groups (certify's default path) and
:func:`run_cells_resilient` for sweeps whose cells are already their own
execution unit (chaos seeds, certify ``--sequential``).

Failure semantics of the result list: a quarantined cell's slot is
``None`` (drivers render it as an attributable quarantined row, never a
fabricated result); every other slot is the bit-identical result the
plain executor would have produced — re-execution paths re-enter the
same traced body, so salvage never changes numbers
(``tests/test_resilient.py``).

Reference counterpart: none — the reference assumes a permanently
healthy Ray cluster and has no sweep machinery at all
(``src/blades/simulator.py:189-211``).
"""

from __future__ import annotations

import dataclasses
import signal
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence

from blades_tpu.sweeps import SweepCell, _execute_group, plan_groups
from blades_tpu.sweeps.journal import SweepJournal
from blades_tpu.telemetry import recorder as _trecorder
from blades_tpu.telemetry.timeline import _counter_delta


def backoff_delay(attempt: int, base_delay_s: float, max_delay_s: float):
    """The shared ``utils/retry.py`` curve, imported lazily: the
    ``blades_tpu.utils`` package chain pulls jax (same constraint the
    supervisor documents), and this module otherwise runs stdlib-only —
    the simulation service's probe requests execute the full resilient
    ladder without ever importing jax."""
    from blades_tpu.utils.retry import backoff_delay as _delay

    return _delay(attempt, base_delay_s, max_delay_s)

__all__ = [
    "DeadlineExceeded",
    "ResilienceOptions",
    "ResilienceReport",
    "run_cells_resilient",
    "run_grouped_resilient",
    "soft_deadline",
]


class DeadlineExceeded(Exception):
    """A sweep cell/group execution overran its soft deadline."""


def _alarm_usable() -> bool:
    return (
        hasattr(signal, "setitimer")
        and threading.current_thread() is threading.main_thread()
    )


@contextmanager
def soft_deadline(seconds: Optional[float]):
    """Raise :class:`DeadlineExceeded` in the calling (main) thread after
    ``seconds``. Best-effort: the SIGALRM handler runs at the next
    interpreter bytecode, so pure-C blocking (an XLA execute, a stuck
    collective) trips late or not at all — the supervision watchdog owns
    the hard kill. ``None``/``0``, or a non-main-thread caller, disables
    the deadline entirely (yields ``False``)."""
    if not seconds or seconds <= 0 or not _alarm_usable():
        yield False
        return

    def _trip(signum, frame):
        raise DeadlineExceeded(f"exceeded soft deadline of {seconds:.1f}s")

    prev = signal.signal(signal.SIGALRM, _trip)
    signal.setitimer(signal.ITIMER_REAL, float(seconds))
    try:
        yield True
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0.0)
        signal.signal(signal.SIGALRM, prev)


@dataclasses.dataclass
class ResilienceOptions:
    """Knobs for the resilient executors.

    ``attempts`` is the retry budget per *execution unit*: a full
    batched group gets it, bisection halves get one attempt each (the
    transient-flake budget was already spent at group level — a half
    failing twice in a row is a poison signal, not weather), and
    isolated single cells get it again before quarantine.
    ``cell_deadline_s`` scales with the subgroup: a group of C cells
    gets ``C x cell_deadline_s``. ``sleep`` and ``runner`` are test
    injection points (``runner(group, key)`` replaces the real batched
    execution).

    ``should_yield``: polled at cell (per-cell executor) or group
    (batched executor) boundaries AFTER at least one unit of progress;
    a ``True`` stops the sweep with ``report.preempted`` set and every
    remaining slot ``None`` — the caller requeues and a later execution
    recovers the journaled cells and runs only the remainder (the
    service scheduler's cell-boundary preemption,
    ``blades_tpu/service/scheduler.py``). The one-unit-of-progress
    floor makes preemption livelock-free by construction: every slice
    completes at least one journaled cell.

    ``deadline``: who enforces ``cell_deadline_s``. ``"alarm"`` (the
    default) arms the in-process SIGALRM soft deadline — usable only
    from the main thread; when it is NOT usable the executor emits an
    explicit ``deadline_unenforced`` record instead of silently running
    unbounded. ``"external"`` declares that a supervising parent owns
    the deadline (the worker pool,
    ``blades_tpu/service/workers.py``): the executor skips SIGALRM
    entirely — and skips the unenforced note, because the deadline IS
    enforced, just not here.

    ``on_cell_start(label, cells)``: called immediately before every
    execution attempt with the cell label (or the first label of a
    batched group) and the unit's cell count. The worker pool's per-cell
    heartbeat: the worker forwards it over its pipe so the parent can
    arm the external deadline for exactly this unit."""

    attempts: int = 2
    base_delay_s: float = 0.5
    max_delay_s: float = 30.0
    cell_deadline_s: Optional[float] = None
    sleep: Callable[[float], None] = time.sleep
    runner: Optional[Callable[[Sequence[SweepCell], str], list]] = None
    should_yield: Optional[Callable[[], bool]] = None
    deadline: str = "alarm"
    on_cell_start: Optional[Callable[[str, int], None]] = None

    def __post_init__(self):
        # a non-positive budget would skip the attempt loop entirely and
        # quarantine every cell with a fabricated error — and the
        # poisoned quarantines would persist in the journal
        self.attempts = max(1, int(self.attempts))
        if self.deadline not in ("alarm", "external"):
            raise ValueError(
                f"deadline must be 'alarm' or 'external', got "
                f"{self.deadline!r}"
            )

    def alarm_deadline_s(self) -> Optional[float]:
        """The per-cell deadline the IN-PROCESS soft alarm should arm —
        ``None`` under external enforcement."""
        if self.deadline == "external":
            return None
        return self.cell_deadline_s


@dataclasses.dataclass
class ResilienceReport:
    """What the resilient executor had to do beyond plain execution —
    the numbers a degraded/resumed sweep must surface (driver summaries,
    ``sweep_status``): a sweep that retried its way through is NOT the
    same evidence as one that ran clean."""

    retried: int = 0
    degraded_groups: int = 0
    executed: int = 0
    resumed_skipped: int = 0
    #: the sweep stopped at a cell/group boundary because
    #: ``options.should_yield`` asked it to; remaining slots are None
    #: and NOT quarantined — a later execution finishes them
    preempted: bool = False
    quarantined: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list
    )

    def summary(self) -> Dict[str, Any]:
        return {
            "executed": self.executed,
            "resumed_skipped": self.resumed_skipped,
            "retried": self.retried,
            "degraded_groups": self.degraded_groups,
            "quarantined": [q["cell"] for q in self.quarantined],
        }


# -- the shared record-emitting primitives ------------------------------------
# One implementation each, used by BOTH executors, so retry/quarantine/
# resume trails are identical across the batched and per-cell paths by
# construction (the docstring contract tests/test_resilient.py pins).


def _note_deadline_unenforced(
    rec, kind: str, *, deadline_s: float,
) -> None:
    """The satellite fix for the silent-deadline hole: a caller asked
    for an in-process (``deadline="alarm"``) per-cell deadline that
    SIGALRM cannot enforce here (non-main-thread caller, or a platform
    without ``setitimer``). Before this note, the deadline silently
    vanished — a hung cell ran unbounded and the trace showed a sweep
    that LOOKED deadline-protected. Now the trail says so explicitly
    (surfaced by ``scripts/sweep_status.py``)."""
    reason = (
        "no_setitimer" if not hasattr(signal, "setitimer")
        else "non_main_thread"
    )
    rec.event(
        "deadline_unenforced",
        sweep=kind,
        reason=reason,
        deadline_s=float(deadline_s),
        ts=time.time(),
    )
    rec.flush()  # a live status query must see the downgrade


def _emit_retry(
    rec, report: ResilienceReport, kind: str, *, what: str, attempt: int,
    delay: float, exc: BaseException, batch: Optional[str] = None,
    cell: Optional[str] = None,
) -> None:
    report.retried += 1
    fields: Dict[str, Any] = {"sweep": kind}
    if batch is not None:
        fields["batch"] = batch
    if cell is not None:
        fields["cell"] = cell
    rec.event(
        "retry",
        what=what,
        attempt=attempt,
        delay_s=delay,
        error=f"{type(exc).__name__}: {exc}"[:300],
        **fields,
    )
    rec.flush()  # a live status query must see the retry


def _quarantine_cell(
    rec, sweep, journal: Optional[SweepJournal], report: ResilienceReport,
    kind: str, label: str, exc: BaseException, *, attempts: int,
    batch: Optional[str] = None, wall: float = 0.0,
    delta: Optional[Dict[str, Any]] = None,
) -> None:
    """Quarantine one cell: journal entry (a resume must not replay the
    poison), ``quarantine`` event, and a flagged ok:false driver record
    carrying the FINAL attempt's wall and compile counters — the failure
    cost stays visible in the sweep accounting."""
    error = f"{type(exc).__name__}: {exc}"[:300]
    info = {
        "cell": label,
        "error": error,
        "error_type": type(exc).__name__,
        "batch": batch,
        "attempts": attempts,
    }
    report.quarantined.append(info)
    if journal is not None:
        journal.record_quarantine(
            label, error, info["error_type"], batch=batch, attempts=attempts,
        )
    event: Dict[str, Any] = {
        "sweep": kind,
        "cell": label,
        "ts": time.time(),
        "error": error,
        "error_type": info["error_type"],
        "attempts": attempts,
    }
    if batch is not None:
        event["batch"] = batch
    rec.event("quarantine", **event)
    if sweep is not None:
        extra = {"batch": batch} if batch is not None else {}
        sweep.record(
            label, wall, counter_delta=delta, error=error,
            error_type=info["error_type"], quarantined=True, **extra,
        )
    else:
        rec.flush()


def _recover_cell(
    journal: SweepJournal, sweep, report: ResilienceReport, label: str,
    *, batch: Optional[str] = None,
):
    """Recover one journaled cell on resume; returns ``(result, wall)``
    (``(None, 0.0)`` for a journaled quarantine). Re-emits a zero-wall
    ``resumed: true`` driver record — the interrupted attempt already
    recorded (or lost) the real wall; double-stamping it would inflate
    every cross-attempt rollup."""
    report.resumed_skipped += 1
    extra = {"batch": batch} if batch is not None else {}
    entry = journal.entry(label)
    if entry is not None:
        if sweep is not None:
            sweep.record(label, 0.0, resumed=True, **extra)
        return entry["result"], float(entry.get("wall_s", 0.0))
    q = journal.quarantined()[label]
    report.quarantined.append({
        "cell": label,
        "error": q.get("error", ""),
        "error_type": q.get("error_type", "Exception"),
        "batch": q.get("batch", batch),
        "attempts": q.get("attempts"),
    })
    if sweep is not None:
        sweep.record(
            label, 0.0, resumed=True, quarantined=True,
            error=q.get("error", ""),
            error_type=q.get("error_type", "Exception"),
            **extra,
        )
    return None, 0.0


# -- the per-cell executor ----------------------------------------------------


def run_cells_resilient(
    cells,
    run_cell: Callable[[Any], Any],
    *,
    sweep=None,
    journal: Optional[SweepJournal] = None,
    options: Optional[ResilienceOptions] = None,
    kind: Optional[str] = None,
):
    """The per-cell resilient loop for NON-batched sweeps — the degrade
    ladder without bisection, since each cell is already its own
    execution unit: journal recovery, per-attempt retry, soft deadline,
    quarantine, all through the shared primitives above.

    ``scripts/chaos.py`` (one seed per cell) and ``scripts/certify.py
    --sequential`` (one search program per cell) both route through it.

    ``cells``: a sequence of ``(label, payload)``; ``run_cell(payload)``
    executes one cell and returns its (JSON-serializable) result.
    Returns ``(results, walls, report)`` like
    :func:`run_grouped_resilient` — a quarantined cell's slot is None.
    """
    options = options or ResilienceOptions()
    cells = list(cells)
    kind = kind or getattr(sweep, "kind", "sweep")
    rec = getattr(sweep, "rec", None) or _trecorder.get_recorder()
    results: List[Any] = []
    walls: List[float] = []
    report = ResilienceReport()

    cell_ddl = options.alarm_deadline_s()
    if cell_ddl and not _alarm_usable():
        # once per execution, not per cell: the condition is a property
        # of the calling context, and a 100-cell sweep must not bury the
        # trail under 100 identical notes
        _note_deadline_unenforced(rec, kind, deadline_s=cell_ddl)

    progressed = 0
    for label, payload in cells:
        if journal is not None and journal.has(label):
            result, wall = _recover_cell(journal, sweep, report, label)
            results.append(result)
            walls.append(wall)
            continue

        # cell-boundary preemption: yield only after at least one cell
        # of NEW work this invocation (journal recoveries don't count —
        # a slice must always advance the journal, or back-to-back
        # preemptions could spin without progress). Remaining slots pad
        # to None so drivers keep positional alignment.
        if report.preempted or (
            progressed
            and options.should_yield is not None
            and options.should_yield()
        ):
            report.preempted = True
            results.append(None)
            walls.append(0.0)
            continue

        ok = False
        out = None
        last: Optional[BaseException] = None
        wall = 0.0
        delta: Dict[str, Any] = {}
        for attempt in range(1, options.attempts + 1):
            if options.on_cell_start is not None:
                # per attempt, not per cell: the external enforcer's
                # timer must re-arm after a backoff sleep, or the sleep
                # itself would eat the next attempt's budget
                options.on_cell_start(label, 1)
            t0 = time.perf_counter()
            counters0 = _trecorder.process_counters()
            try:
                with soft_deadline(cell_ddl):
                    out = run_cell(payload)
                wall = time.perf_counter() - t0
                delta = _counter_delta(counters0)
                ok = True
                break
            except Exception as e:  # noqa: BLE001 - quarantine, keep going
                last = e
                wall = time.perf_counter() - t0
                delta = _counter_delta(counters0)
                if attempt == options.attempts:
                    break
                delay = backoff_delay(
                    attempt, options.base_delay_s, options.max_delay_s
                )
                _emit_retry(
                    rec, report, kind, what="sweep_cell", attempt=attempt,
                    delay=delay, exc=e, cell=label,
                )
                options.sleep(delay)

        if not ok:
            assert last is not None
            _quarantine_cell(
                rec, sweep, journal, report, kind, label, last,
                attempts=options.attempts, wall=wall, delta=delta,
            )
            results.append(None)
            walls.append(wall)
            progressed += 1
            continue

        if journal is not None:
            journal.record(label, out, wall_s=wall)
        if sweep is not None:
            extra = {"retries": attempt - 1} if attempt > 1 else {}
            sweep.record(label, wall, counter_delta=delta, **extra)
        results.append(out)
        walls.append(wall)
        report.executed += 1
        progressed += 1

    return results, walls, report


# -- the batched (program-shape grouped) executor -----------------------------


def run_grouped_resilient(
    cells: Sequence[SweepCell],
    *,
    grids: Optional[dict] = None,
    use_jit: bool = True,
    sweep=None,
    journal: Optional[SweepJournal] = None,
    options: Optional[ResilienceOptions] = None,
):
    """Execute attack-search cells grouped by program shape, resiliently.

    Drop-in for :func:`blades_tpu.sweeps.run_grouped(..., return_walls=
    True)` with a third return value: ``(results, walls, report)``.
    Results come back in input order; a quarantined cell's slot is
    ``None``; on a clean run with an empty journal the executed programs
    — and therefore the results — are identical to the plain executor's.

    ``sweep``: the driver's :class:`~blades_tpu.telemetry.timeline
    .SweepAccounting` (or None). ``journal``: a
    :class:`~blades_tpu.sweeps.journal.SweepJournal`; cells it already
    holds are recovered, every newly completed cell is journaled at its
    boundary. ``options``: :class:`ResilienceOptions`.
    """
    options = options or ResilienceOptions()
    cells = list(cells)
    results: List[Optional[Dict[str, Any]]] = [None] * len(cells)
    walls: List[float] = [0.0] * len(cells)
    report = ResilienceReport()
    kind = getattr(sweep, "kind", "sweep")
    rec = getattr(sweep, "rec", None) or _trecorder.get_recorder()
    runner = options.runner or (
        lambda group, key: _execute_group(
            group, key, grids=grids, use_jit=use_jit
        )
    )

    _grp_ddl = options.alarm_deadline_s()
    if _grp_ddl and not _alarm_usable():
        # same once-per-execution note as the per-cell executor (the
        # shared-primitives contract: identical trails by construction)
        _note_deadline_unenforced(rec, kind, deadline_s=_grp_ddl)

    def _attempt(idxs: List[int], key: str, attempts: int, fail: dict):
        """Run one subgroup with retry; returns (outs, wall, delta,
        retries_used) or raises the final failure, leaving the final
        attempt's wall/counters in ``fail`` so the quarantine record can
        carry the real failure cost."""
        group = [cells[i] for i in idxs]
        cell_ddl = options.alarm_deadline_s()
        ddl = cell_ddl * len(group) if cell_ddl else None
        last: Optional[BaseException] = None
        for attempt in range(1, attempts + 1):
            if options.on_cell_start is not None:
                options.on_cell_start(group[0].label, len(group))
            t0 = time.perf_counter()
            counters0 = _trecorder.process_counters()
            try:
                with soft_deadline(ddl):
                    outs = runner(group, key)
                wall = time.perf_counter() - t0
                return outs, wall, _counter_delta(counters0), attempt - 1
            except Exception as e:  # noqa: BLE001 - every failure degrades
                last = e
                fail["wall"] = time.perf_counter() - t0
                fail["delta"] = _counter_delta(counters0)
                if attempt == attempts:
                    break
                delay = backoff_delay(
                    attempt, options.base_delay_s, options.max_delay_s
                )
                _emit_retry(
                    rec, report, kind,
                    what="sweep_group" if len(group) > 1 else "sweep_cell",
                    attempt=attempt, delay=delay, exc=e, batch=key,
                    cell=group[0].label if len(group) == 1 else None,
                )
                options.sleep(delay)
        assert last is not None
        raise last

    def _commit(idxs, outs, wall, delta, key, retries_used):
        share = wall / len(idxs)
        exec_share = max(
            0.0,
            wall - delta.get("compile_s", 0.0) - delta.get("trace_s", 0.0),
        ) / len(idxs)
        for j, (i, out) in enumerate(zip(idxs, outs)):
            c = cells[i]
            results[i] = out
            walls[i] = share
            # journal FIRST: a crash between journal append and telemetry
            # flush re-executes the cell on resume; the reverse order
            # would mark it done with no recoverable result
            if journal is not None:
                journal.record(c.label, out, wall_s=share)
            if sweep is not None:
                extra = {"retries": retries_used} if retries_used else {}
                sweep.record(
                    c.label,
                    share,
                    counter_delta=delta if j == 0 else None,
                    execute_s=round(exec_share, 6),
                    batch=key,
                    batch_size=len(idxs),
                    **extra,
                )
        report.executed += len(idxs)

    def _solve(idxs: List[int], key: str, attempts: int):
        fail: dict = {}
        try:
            outs, wall, delta, retries_used = _attempt(
                idxs, key, attempts, fail,
            )
        except Exception as e:  # noqa: BLE001 - isolate, salvage, move on
            if len(idxs) == 1:
                _quarantine_cell(
                    rec, sweep, journal, report, kind,
                    cells[idxs[0]].label, e, attempts=attempts, batch=key,
                    wall=fail.get("wall", 0.0), delta=fail.get("delta"),
                )
                return
            # bisect: isolate the poison cell(s), salvage the siblings in
            # the largest passing subgroups (halves get one attempt —
            # the transient budget was spent above; singletons get the
            # full per-cell budget before quarantine)
            report.degraded_groups += 1
            mid = len(idxs) // 2
            for half in (idxs[:mid], idxs[mid:]):
                _solve(
                    half,
                    key,
                    options.attempts if len(half) == 1 else 1,
                )
            return
        _commit(idxs, outs, wall, delta, key, retries_used)

    progressed = 0
    for key, idxs in plan_groups(cells):
        pending: List[int] = []
        for i in idxs:
            c = cells[i]
            if journal is not None and journal.has(c.label):
                results[i], walls[i] = _recover_cell(
                    journal, sweep, report, c.label, batch=key,
                )
            else:
                pending.append(i)
        if not pending:
            continue
        # group-boundary preemption (same contract as the per-cell
        # executor): yield between journaled groups after at least one
        # group of new work; remaining slots stay None for the caller
        # to resume via the journal
        if report.preempted or (
            progressed
            and options.should_yield is not None
            and options.should_yield()
        ):
            report.preempted = True
            continue
        _solve(pending, key, options.attempts)
        progressed += 1

    return results, walls, report
