"""blades_tpu: a TPU-native (JAX/XLA) framework for simulating Byzantine
attacks and robust-aggregation defenses in federated learning.

Capability parity target: bladesteam/blades (see /root/reference and SURVEY.md).
Design is TPU-first, not a port: a "client" is an index into batched on-device
arrays; one federated round is a single jitted XLA program (vmapped local SGD
-> stacked ``[K, D]`` update matrix -> in-graph attack transforms -> jitted
robust aggregator -> server optimizer step), sharded over a
``jax.sharding.Mesh``.

Public surface (mirrors the reference ``blades`` package):

    from blades_tpu import Simulator
    from blades_tpu.datasets import MNIST, CIFAR10
    from blades_tpu.models.mnist import MLP
"""

from blades_tpu.version import __version__  # noqa: F401

__all__ = ["__version__"]


def _honor_cpu_platform_request() -> None:
    """Re-assert an explicit ``JAX_PLATFORMS=cpu`` request.

    Some accelerator plugins install a sitecustomize that forces
    ``jax_platforms`` back to their own platform at interpreter start,
    silently overriding a user's CPU request. Restoring is scoped to the
    exact value ``"cpu"``: the same sitecustomize also *plants* its own
    platform into the env var when unset, so any broader "honor the env"
    rule would faithfully restore the plugin's override — and fight code
    (like tests/conftest.py) that deliberately set the config after import.
    """
    import os
    import sys

    if os.environ.get("JAX_PLATFORMS", "").strip() != "cpu":
        return
    if "jax" not in sys.modules:
        # jax not imported yet: its own env handling honors the request at
        # import time; importing it here would defeat the lazy design below
        # for pure-CLI paths (leaf tools) that never touch jax
        return
    try:
        jax = sys.modules["jax"]
        if jax.config.jax_platforms != "cpu":
            jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 - never block import over a config knob
        pass


_honor_cpu_platform_request()

# Top-level re-exports resolve lazily (PEP 562) so that importing a
# subpackage (e.g. blades_tpu.aggregators) never pays for the full stack.
_LAZY = {
    "Simulator": ("blades_tpu.simulator", "Simulator"),
    "BladesClient": ("blades_tpu.client", "BladesClient"),
    "ByzantineClient": ("blades_tpu.client", "ByzantineClient"),
    "BladesServer": ("blades_tpu.server", "BladesServer"),
    "RoundEngine": ("blades_tpu.core", "RoundEngine"),
    "ClientOptSpec": ("blades_tpu.core", "ClientOptSpec"),
    "ServerOptSpec": ("blades_tpu.core", "ServerOptSpec"),
    "FaultModel": ("blades_tpu.faults", "FaultModel"),
    "AuditMonitor": ("blades_tpu.audit", "AuditMonitor"),
    "AsyncConfig": ("blades_tpu.asyncfl", "AsyncConfig"),
    "ArrivalProcess": ("blades_tpu.asyncfl", "ArrivalProcess"),
}


def __getattr__(name):  # PEP 562 lazy imports keep subpackage imports light
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module 'blades_tpu' has no attribute {name!r}")
