"""blades_tpu: a TPU-native (JAX/XLA) framework for simulating Byzantine
attacks and robust-aggregation defenses in federated learning.

Capability parity target: bladesteam/blades (see /root/reference and SURVEY.md).
Design is TPU-first, not a port: a "client" is an index into batched on-device
arrays; one federated round is a single jitted XLA program (vmapped local SGD
-> stacked ``[K, D]`` update matrix -> in-graph attack transforms -> jitted
robust aggregator -> server optimizer step), sharded over a
``jax.sharding.Mesh``.

Public surface (mirrors the reference ``blades`` package):

    from blades_tpu import Simulator
    from blades_tpu.datasets import MNIST, CIFAR10
    from blades_tpu.models.mnist import MLP
"""

from blades_tpu.version import __version__  # noqa: F401

__all__ = ["__version__"]

# Top-level re-exports resolve lazily (PEP 562) so that importing a
# subpackage (e.g. blades_tpu.aggregators) never pays for the full stack.
_LAZY = {
    "Simulator": ("blades_tpu.simulator", "Simulator"),
    "BladesClient": ("blades_tpu.client", "BladesClient"),
    "ByzantineClient": ("blades_tpu.client", "ByzantineClient"),
    "BladesServer": ("blades_tpu.server", "BladesServer"),
    "RoundEngine": ("blades_tpu.core", "RoundEngine"),
    "ClientOptSpec": ("blades_tpu.core", "ClientOptSpec"),
    "ServerOptSpec": ("blades_tpu.core", "ServerOptSpec"),
}


def __getattr__(name):  # PEP 562 lazy imports keep subpackage imports light
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module 'blades_tpu' has no attribute {name!r}")
