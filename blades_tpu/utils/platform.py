"""Platform forcing for virtual-device runs.

The TPU plugin's sitecustomize overrides ``jax_platforms`` back to
``"axon,cpu"`` at interpreter start even when the environment requests CPU,
so the env var alone is not enough — the config must be updated after
import. Must run before the first backend touch (``jax.devices()``); once a
backend is initialized the device list is fixed, in which case this is a
best-effort no-op.

This module is the single owner of the virtual-CPU flag recipe: the test
suite (``tests/conftest.py``), the docs example runner (``docs/build.py``),
the bench CPU fallback, and the driver dryrun all build their environment
from the helpers here.

Reference counterpart: none — the reference has no accelerator-platform
plumbing (Ray schedules CPU/GPU actors; ``use_cuda`` is its only knob).
"""

from __future__ import annotations

import os

import jax

# n virtual devices may timeshare few (or one) physical cores; XLA's default
# 40 s collective-rendezvous termination timeout hard-aborts the process
# under that contention
_COLLECTIVE_TIMEOUT_S = 600


def _xla_supports_flag(flag: str) -> bool:
    """Whether the installed jaxlib registers ``flag`` as an XLA flag.

    XLA F-aborts the whole process on *unknown* entries in ``XLA_FLAGS``
    (``parse_flags_from_env.cc``), so a flag must never be passed on spec.
    Registered flags embed their name as a string in the ``xla_extension``
    binary; a substring scan of that file is the only version-agnostic probe
    that does not risk the abort. The verdict is cached in the environment,
    so child processes (docs/build.py examples, bench children, dist
    workers) inherit it without re-scanning.
    """
    cache_key = "_BLADES_XLA_FLAG_" + flag
    cached = os.environ.get(cache_key)
    if cached is not None:
        return cached == "1"
    supported = False
    try:
        import glob
        import mmap

        import jaxlib

        pattern = os.path.join(os.path.dirname(jaxlib.__file__), "xla_extension*.so*")
        for so in glob.glob(pattern):
            with open(so, "rb") as f:
                m = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
                try:
                    supported = m.find(flag.encode()) != -1
                finally:
                    m.close()
            if supported:
                break
    except Exception:  # noqa: BLE001 - unknown layout: assume unsupported
        supported = False
    os.environ[cache_key] = "1" if supported else "0"
    return supported


def virtual_cpu_flags(n_devices: int, existing: str = "") -> str:
    """``XLA_FLAGS`` value for an ``n_devices`` virtual CPU platform.

    Appends to ``existing`` without duplicating flags already present.
    """
    flags = existing
    if "xla_force_host_platform_device_count" not in flags:
        flags = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    if (
        n_devices > 1
        and "collective_call_terminate_timeout" not in flags
        and _xla_supports_flag("xla_cpu_collective_call_terminate_timeout_seconds")
    ):
        flags += (
            " --xla_cpu_collective_call_terminate_timeout_seconds"
            f"={_COLLECTIVE_TIMEOUT_S}"
        )
    return flags


def virtual_cpu_env(n_devices: int) -> dict:
    """Env-var dict for launching a subprocess on a virtual CPU platform."""
    return {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": virtual_cpu_flags(n_devices),
    }


def force_virtual_cpu(n_devices: int) -> None:
    """Force an ``n_devices``-device virtual CPU platform (best effort)."""
    os.environ["XLA_FLAGS"] = virtual_cpu_flags(
        n_devices, os.environ.get("XLA_FLAGS", "")
    )
    # hard assignment, not setdefault: the TPU plugin's sitecustomize plants
    # JAX_PLATFORMS=axon at interpreter start when the var is unset
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already up; caller's device-count checks take over


def apply_env_platform() -> None:
    """Make an explicit ``JAX_PLATFORMS`` env request binding.

    The TPU plugin's sitecustomize rewrites ``jax_platforms`` to
    ``"axon,cpu"`` at interpreter start even when the caller exported
    ``JAX_PLATFORMS=cpu`` — so a CPU-requesting launcher (docs/build.py,
    subprocess harnesses) would still try to initialize the (possibly dead)
    TPU backend first and hang. Scripts that honor the env contract call
    this at startup: re-apply the requested platform set in-process. A
    cpu-only request additionally becomes a virtual-CPU platform with the
    device count taken from ``XLA_FLAGS`` (default 1); any other non-axon
    request (tpu, cuda, ...) is re-applied verbatim."""
    want = os.environ.get("JAX_PLATFORMS", "")
    platforms = [p for p in want.split(",") if p]
    if not platforms or "axon" in platforms:
        return
    if platforms == ["cpu"]:
        import re

        m = re.search(
            r"xla_force_host_platform_device_count=(\d+)",
            os.environ.get("XLA_FLAGS", ""),
        )
        force_virtual_cpu(int(m.group(1)) if m else 1)
        return
    try:
        jax.config.update("jax_platforms", want)
    except RuntimeError:
        pass  # backend already up
