"""Platform forcing for virtual-device runs.

The TPU plugin's sitecustomize overrides ``jax_platforms`` back to
``"axon,cpu"`` at interpreter start even when the environment requests CPU,
so the env var alone is not enough — the config must be updated after
import. Must run before the first backend touch (``jax.devices()``); once a
backend is initialized the device list is fixed, in which case this is a
best-effort no-op.
"""

from __future__ import annotations

import os

import jax


def force_virtual_cpu(n_devices: int) -> None:
    """Force an ``n_devices``-device virtual CPU platform (best effort)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + f" --xla_force_host_platform_device_count={n_devices}").strip()
    if "collective_call_terminate_timeout" not in flags:
        # n virtual devices may timeshare few (or one) physical cores; the
        # default 40s rendezvous termination timeout hard-aborts the
        # process under that contention
        flags += " --xla_cpu_collective_call_terminate_timeout_seconds=600"
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already up; caller's device-count checks take over
