"""Shared offline-gated download plumbing.

One implementation of the fetch contract used by the pretrained-weight
registry (``models/pretrained.py``) and the LEAF dataset downloader
(``leaf/download.py``): honor ``BLADES_TPU_OFFLINE=1`` with an actionable
error, stream to a ``.part`` temp file, atomically rename on success, clean
up and wrap any failure into one RuntimeError naming the manual-placement
path.

Reference counterpart: the confirm-token fetch in
``src/blades/models/utils/download_util.py`` (whole-file); the offline
gate and atomic-rename hardening are new surface.
"""

from __future__ import annotations

import os
import shutil
from typing import Callable, IO

_CHUNK = 32768


def offline() -> bool:
    return os.environ.get("BLADES_TPU_OFFLINE") == "1"


def fetch_to(destination: str, open_stream: Callable[[], IO[bytes]],
             what: str) -> str:
    """Stream ``open_stream()`` into ``destination`` (atomic, gated).

    ``what`` names the resource in error messages (e.g. a URL or Drive id).
    """
    if offline():
        raise RuntimeError(
            f"downloads disabled (BLADES_TPU_OFFLINE=1); fetch {what} on a "
            f"connected machine and place it at {destination}."
        )
    os.makedirs(os.path.dirname(destination) or ".", exist_ok=True)
    tmp = destination + ".part"
    try:
        with open_stream() as resp, open(tmp, "wb") as f:
            shutil.copyfileobj(resp, f, _CHUNK)
        os.replace(tmp, destination)
    except Exception as e:  # noqa: BLE001 - one actionable error per failure
        if os.path.exists(tmp):
            os.remove(tmp)
        raise RuntimeError(
            f"could not download {what} ({type(e).__name__}: {e}); in "
            f"offline environments place the file at {destination} manually."
        ) from e
    return destination
