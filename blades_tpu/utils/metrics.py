"""Metric callables.

Pluggable metric registry mirrors the reference (``{name: fn(output,
target)}``, ``src/blades/simulator.py:57,76``; ``top1_accuracy`` at
``src/blades/utils.py:55-56`` returns percent). Metrics here are pure JAX
functions usable inside jit.
"""

from __future__ import annotations

import jax.numpy as jnp


def accuracy(output: jnp.ndarray, target: jnp.ndarray, topk=(1,)):
    """Precision@k for each k, in percent (reference scale)."""
    maxk = max(topk)
    # [B, maxk] indices of top-k logits
    top_idx = jnp.argsort(output, axis=-1)[:, ::-1][:, :maxk]
    correct = top_idx == target[:, None]
    res = []
    for k in topk:
        res.append(100.0 * jnp.mean(jnp.any(correct[:, :k], axis=-1)))
    return res


def top1_accuracy(output: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    return accuracy(output, target, topk=(1,))[0]
