"""Persistent XLA compilation cache.

The round program is traced once per (K, shapes, mesh) signature and the
compile dominates cold-start wall time (the full CCT round is minutes on a
virtual CPU mesh). A persistent on-disk cache makes every invocation after
the first load in seconds — this de-risks both driver gates (bench warmup,
multichip dryrun) and cuts the test suite's recompile burn.

The reference has no equivalent: its "compile" is torch eager, paid per op.
"""

from __future__ import annotations

import os

import jax

DEFAULT_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache",
)


def enable_compilation_cache(cache_dir: str | None = None) -> str:
    """Turn on the persistent compilation cache (idempotent).

    Caches every program regardless of compile time or size so even the
    small probe jits hit on re-run.
    """
    cache_dir = cache_dir or os.environ.get("BLADES_TPU_CACHE_DIR", DEFAULT_CACHE_DIR)
    os.makedirs(cache_dir, exist_ok=True)
    for name, value in (
        ("jax_compilation_cache_dir", cache_dir),
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(name, value)
        except (AttributeError, ValueError):  # older/newer jax without the knob
            pass
    return cache_dir
