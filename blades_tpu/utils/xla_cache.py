"""Persistent XLA compilation cache.

The round program is traced once per (K, shapes, mesh) signature and the
compile dominates cold-start wall time (the full CCT round is minutes on a
virtual CPU mesh). A persistent on-disk cache makes every invocation after
the first load in seconds — this de-risks both driver gates (bench warmup,
multichip dryrun) and cuts the test suite's recompile burn.

The reference has no equivalent: its "compile" is torch eager, paid per op.
"""

from __future__ import annotations

import os

import jax

def _default_cache_dir() -> str:
    # prefer the repo-local dir when working from a source checkout (fast,
    # self-contained); fall back to the user cache for pip installs where
    # the package parent may be read-only site-packages
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    if os.access(repo, os.W_OK) and not repo.rstrip(os.sep).endswith(
        "site-packages"
    ):
        return os.path.join(repo, ".jax_cache")
    return os.path.join(
        os.path.expanduser("~"), ".cache", "blades_tpu", "jax_cache"
    )


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Turn on the persistent compilation cache (idempotent, best effort).

    Caches every program regardless of compile time or size so even the
    small probe jits hit on re-run. ``BLADES_TPU_NO_CACHE=1`` disables it;
    an unwritable cache location disables it silently rather than failing
    the run.
    """
    # compile/cache accounting rides along whether or not the on-disk cache
    # itself is enabled: every backend compile and every persistent-cache
    # hit/miss lands on the active telemetry recorder (xla.* counters +
    # one "compile" record each — on this box a cold round compile costs
    # minutes, so each is worth a line)
    from blades_tpu.telemetry import install_jax_monitoring

    install_jax_monitoring()
    if os.environ.get("BLADES_TPU_NO_CACHE") == "1":
        return None
    cache_dir = cache_dir or os.environ.get(
        "BLADES_TPU_CACHE_DIR", _default_cache_dir()
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        return None
    for name, value in (
        ("jax_compilation_cache_dir", cache_dir),
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(name, value)
        except (AttributeError, ValueError):  # older/newer jax without the knob
            pass
    return cache_dir
