"""RNG key discipline.

The reference relies on global seeds plus cache/restore of torch/numpy RNG
state around per-client host calls (``src/blades/utils.py:116-124``,
``src/blades/simulator.py:153-165``). JAX keys are explicit, so we define a
documented split tree instead of chasing bit-parity:

    root(seed)
      └─ fold_in(round)                      -> round key
           ├─ fold_in(0)                     -> data-sampling key
           ├─ fold_in(1)                     -> augmentation key
           ├─ fold_in(2)                     -> attack key
           └─ fold_in(client_id)  (vmapped)  -> per-client key

Every stream is a pure function of (seed, round, purpose, client), so any
round is reproducible in isolation — stronger than the reference's
global-state caching.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Purpose tags for fold_in; keep stable across releases for reproducibility.
DATA = 0
AUGMENT = 1
ATTACK = 2
INIT = 3
EVAL = 4
# Client streams branch through a dedicated tag first so that
# fold_in(round_key, client_id) can never collide with a purpose stream.
CLIENTS = 5
AGG = 6
FAULT = 7
# buffered-async arrival process (blades_tpu.asyncfl): per-client integer
# delay draws — its own stream so adding async semantics never perturbs
# the data/attack/fault draws of an existing seed
ARRIVAL = 8


def set_random_seed(seed: int = 0) -> jax.Array:
    """Reference-API parity (``set_random_seed``, ``src/blades/utils.py:116-124``):
    seed the host-side numpy RNG (used by partitioners) and return the JAX
    root key that seeds every device-side stream."""
    import numpy as np

    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


def key_for_round(seed_key: jax.Array, round_idx) -> jax.Array:
    return jax.random.fold_in(seed_key, round_idx)


def key_per_client(round_key: jax.Array, num_clients: int) -> jax.Array:
    """``[K]`` independent per-client keys, vmap-friendly."""
    client_root = jax.random.fold_in(round_key, CLIENTS)
    return jax.vmap(lambda i: jax.random.fold_in(client_root, i))(
        jnp.arange(num_clients)
    )
