"""Bounded-exponential-backoff retry for flaky host-side operations.

The TPU attachment on this box tunnels through a helper that dies for hours
at a time; backend acquisition then fails (or hangs) with transient
``Unavailable``-class errors that poison nothing but the attempt itself.
:func:`retry_call` turns such a flake into a *recorded* retry — each attempt
increments a ``retry.<name>`` telemetry counter and emits a ``retry`` event
on the active recorder (``blades_tpu.telemetry``, zero-dependency, safe to
import before jax) — instead of a hung or dead run. Used by ``bench.py``'s
backend preflight and ``scripts/tpu_capture.py``'s tunnel probe.

Reference counterpart: none — the reference assumes a permanently healthy
Ray cluster and retries nothing (``src/blades/simulator.py:189-211``).
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Tuple, Type, TypeVar

from blades_tpu.telemetry import get_recorder

T = TypeVar("T")


def backoff_delay(
    attempt: int, base_delay: float = 1.0, max_delay: float = 60.0
) -> float:
    """Bounded-exponential delay before retry ``attempt`` (1-based):
    ``min(base_delay * 2**(attempt-1), max_delay)``.

    The single source of the backoff shape, shared by :func:`retry_call`
    (in-process host-side retries) and the run supervisor's relaunch
    budget (``blades_tpu.supervision.supervisor`` — process-level
    retries), so both layers degrade on the same curve."""
    return min(base_delay * 2.0 ** (attempt - 1), max_delay)


def retry_call(
    fn: Callable[[], T],
    *,
    attempts: int = 3,
    base_delay: float = 1.0,
    max_delay: float = 60.0,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    describe: str = "operation",
    on_retry: Optional[Callable[[int, float, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """Call ``fn`` up to ``attempts`` times with exponential backoff.

    Delay before retry ``i`` (1-based) is ``min(base_delay * 2**(i-1),
    max_delay)``. Exceptions not matching ``retry_on`` — and the final
    attempt's failure — propagate unchanged. ``on_retry(attempt, delay,
    exc)`` runs before each sleep (logging hook); every retry is also
    counted on the active telemetry recorder as ``retry.<describe>`` plus a
    ``retry`` event, so a flake that self-healed still shows up in the
    trace/bench payload instead of vanishing.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except retry_on as e:
            if attempt == attempts:
                raise
            delay = backoff_delay(attempt, base_delay, max_delay)
            rec = get_recorder()
            rec.counter(f"retry.{describe}")
            rec.event(
                "retry",
                what=describe,
                attempt=attempt,
                delay_s=delay,
                error=f"{type(e).__name__}: {e}"[:300],
            )
            if on_retry is not None:
                on_retry(attempt, delay, e)
            sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
