"""Checkpoint / resume for federated training state.

The reference has NO checkpointing (SURVEY.md section 5) — its only
persistence is the dataset partition cache and append-only logs. Here the
full :class:`~blades_tpu.core.RoundState` (global params, server optimizer
state, stacked per-client optimizer state, stateful-aggregator carry, attack
state, round index) serializes to a single ``.npz``, so long CIFAR runs can
resume mid-experiment bit-exactly.

Orbax is the heavier alternative for multi-host async checkpointing; a flat
npz keeps zero extra dependencies and is bit-exact for the single-host case.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def checkpoint_file(path: str) -> str:
    """The on-disk filename for ``path`` (``np.savez`` appends ``.npz`` to
    extension-less paths, so every consumer must normalize the same way)."""
    return path if path.endswith(".npz") else path + ".npz"


def save_state(path: str, state: Any) -> None:
    """Serialize a pytree (e.g. RoundState) to ``checkpoint_file(path)``.

    Atomic: the archive is written to ``<path>.tmp`` and moved into place
    with ``os.replace`` (atomic on POSIX), so a process killed mid-save —
    the crash-autosave scenario this checkpoint exists for — can never
    leave a torn file at the checkpoint path; at worst a stale ``.tmp``
    remains next to the intact previous checkpoint.
    """
    path = checkpoint_file(path)
    flat, treedef = jax.tree_util.tree_flatten(state)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(flat)}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    try:
        with open(tmp, "wb") as fh:
            # np.savez only appends ".npz" to bare paths, not file objects
            np.savez(
                fh,
                __treedef__=np.frombuffer(str(treedef).encode(), np.uint8),
                __num_leaves__=np.asarray(len(flat)),
                **arrays,
            )
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def restore_state(path: str, like: Any) -> Any:
    """Restore a pytree saved by :func:`save_state`. ``like`` supplies the
    tree structure (e.g. a freshly built RoundState); the saved treedef,
    leaf count, shapes, and dtypes must all match it.

    A truncated or otherwise unreadable archive raises a clean
    ``ValueError`` naming the file (atomic saves make this unreachable for
    our own writes, but a torn copy/scp or disk corruption should fail
    loudly, not with a zipfile traceback deep in numpy)."""
    fname = checkpoint_file(path)
    try:
        z = np.load(fname)
    except Exception as e:  # noqa: BLE001 - BadZipFile/OSError/pickle errors
        raise ValueError(
            f"checkpoint {fname} is corrupt or unreadable "
            f"(truncated/torn write?): {type(e).__name__}: {e}"
        ) from e
    flat_like, treedef = jax.tree_util.tree_flatten(like)
    try:
        saved_n = int(z["__num_leaves__"]) if "__num_leaves__" in z else None
    except Exception as e:  # noqa: BLE001 - member read on a torn archive
        raise ValueError(
            f"checkpoint {fname} is corrupt or unreadable "
            f"(truncated/torn write?): {type(e).__name__}: {e}"
        ) from e
    if saved_n is not None and saved_n != len(flat_like):
        raise ValueError(
            f"checkpoint has {saved_n} leaves but the current engine state "
            f"has {len(flat_like)} — incompatible config (e.g. persist/"
            "aggregator/attack mismatch)?"
        )
    saved_treedef = bytes(z["__treedef__"]).decode()
    if saved_treedef != str(treedef):
        raise ValueError(
            "checkpoint tree structure differs from the current engine "
            f"state:\n  saved:   {saved_treedef}\n  current: {treedef}"
        )
    try:
        # copy=True is load-bearing: on the CPU backend ``jnp.asarray`` can
        # ZERO-COPY alias the npz-loaded numpy buffer (alignment-dependent,
        # jaxlib-build-dependent), and the round program DONATES its state
        # input — XLA then reuses what it believes is its own buffer as
        # output memory while numpy frees the real owner, so a resumed
        # round reads heap garbage (observed: flaky NaN/1e38 params after
        # resume). Same rule as RoundEngine.init's private params copy.
        flat = [
            jnp.array(z[f"leaf_{i}"], copy=True) for i in range(len(flat_like))
        ]
    except (KeyError, ValueError):
        raise
    except Exception as e:  # noqa: BLE001 - zlib/zipfile on a torn member
        raise ValueError(
            f"checkpoint {fname} is corrupt or unreadable "
            f"(truncated/torn write?): {type(e).__name__}: {e}"
        ) from e
    for i, (new, old) in enumerate(zip(flat, flat_like)):
        if jnp.shape(new) != jnp.shape(old):
            raise ValueError(
                f"checkpoint leaf {i} shape {jnp.shape(new)} != expected "
                f"{jnp.shape(old)} — incompatible config?"
            )
        if new.dtype != jnp.asarray(old).dtype:
            raise ValueError(
                f"checkpoint leaf {i} dtype {new.dtype} != expected "
                f"{jnp.asarray(old).dtype} — incompatible config?"
            )
    return jax.tree_util.tree_unflatten(treedef, flat)
