"""Checkpoint / resume for federated training state.

The reference has NO checkpointing (SURVEY.md section 5) — its only
persistence is the dataset partition cache and append-only logs. Here the
full :class:`~blades_tpu.core.RoundState` (global params, server optimizer
state, stacked per-client optimizer state, stateful-aggregator carry, attack
state, round index) serializes to a single ``.npz``, so long CIFAR runs can
resume mid-experiment bit-exactly.

Orbax is the heavier alternative for multi-host async checkpointing; a flat
npz keeps zero extra dependencies and is bit-exact for the single-host case.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def _flatten_with_paths(tree: Any):
    flat, treedef = jax.tree_util.tree_flatten(tree)
    return flat, treedef


def save_state(path: str, state: Any) -> None:
    """Serialize a pytree (e.g. RoundState) to ``path`` (.npz)."""
    flat, treedef = _flatten_with_paths(state)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(flat)}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    np.savez(path, __treedef__=np.frombuffer(str(treedef).encode(), np.uint8), **arrays)


def restore_state(path: str, like: Any) -> Any:
    """Restore a pytree saved by :func:`save_state`. ``like`` supplies the
    tree structure (e.g. a freshly built RoundState); leaf dtypes/shapes must
    match what was saved."""
    z = np.load(path)
    flat_like, treedef = jax.tree_util.tree_flatten(like)
    n = len(flat_like)
    flat = [jnp.asarray(z[f"leaf_{i}"]) for i in range(n)]
    for i, (new, old) in enumerate(zip(flat, flat_like)):
        if jnp.shape(new) != jnp.shape(old):
            raise ValueError(
                f"checkpoint leaf {i} shape {jnp.shape(new)} != expected "
                f"{jnp.shape(old)} — incompatible config?"
            )
    return jax.tree_util.tree_unflatten(treedef, flat)
