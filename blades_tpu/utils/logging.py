"""Two-file run logging with reference parity.

The reference writes a ``stats`` file (one Python-dict repr per line, typed by
``_meta.type``) and a free-text ``debug`` file, wiping the log dir on init
(``src/blades/utils.py:67-95``). Downstream analysis parses the stats file
line-by-line (``examples/Simulation on MNIST.py:69-83``), so the format is
kept identical.
"""

from __future__ import annotations

import logging
import os
import shutil

_RUN_LOGGERS = ("stats", "debug")

# Crash-recovery artifacts that MUST survive the reference-parity log-dir
# wipe: a killed run is restarted by constructing a fresh Simulator on the
# SAME log_path, and ``resume=True`` then needs the previous attempt's
# ``autosave.npz`` / checkpoint archives (``utils/checkpoint.py``), the
# telemetry trace (the post-mortem trail, appended across attempts), and
# the supervisor's heartbeat file (``blades_tpu/supervision``). Wiping
# them at construction silently degraded every resume-after-kill into a
# from-scratch rerun — undetectable with a deterministic seed, which is
# exactly how it went unnoticed.
_PRESERVE_SUFFIXES = (".npz",)
_PRESERVE_NAMES = ("telemetry.jsonl", "heartbeat")


def initialize_logger(log_root: str) -> None:
    """(Re)create ``log_root`` and attach fresh ``stats``/``debug`` loggers.

    Idempotent: re-initialization detaches and closes only this module's
    two named loggers' handlers before attaching new ones — unlike the
    reference, whose ``logging.shutdown()`` + module reload
    (``src/blades/utils.py:67-73``) nukes every logger in the process
    (including jax's and absl's) and leaks the previous run's file handles.
    File format is unchanged: one bare ``%(message)s`` per line.

    The wipe is recovery-aware: the reference clears the whole dir
    (``src/blades/utils.py:75-79``); here checkpoint archives (``*.npz``),
    the telemetry trace, and the heartbeat file survive so a kill →
    relaunch → ``resume=True`` cycle on the same ``log_path`` actually
    resumes instead of silently restarting (``docs/robustness.md``).
    """
    # teardown first (handlers hold the files open), then wipe the dir
    for name in _RUN_LOGGERS:
        logger = logging.getLogger(name)
        for h in list(logger.handlers):
            logger.removeHandler(h)
            h.close()
        logger.setLevel(logging.INFO)
        # no propagation to the root logger: a root handler (pytest, user
        # basicConfig) would otherwise echo records in its own format
        logger.propagate = False
    if os.path.exists(log_root):
        for entry in os.listdir(log_root):
            if entry.endswith(_PRESERVE_SUFFIXES) or entry in _PRESERVE_NAMES:
                continue
            path = os.path.join(log_root, entry)
            if os.path.isdir(path):
                shutil.rmtree(path)
            else:
                os.unlink(path)
    os.makedirs(log_root, exist_ok=True)
    for name in _RUN_LOGGERS:
        fh = logging.FileHandler(os.path.join(log_root, name))
        fh.setLevel(logging.INFO)
        fh.setFormatter(logging.Formatter("%(message)s"))
        logging.getLogger(name).addHandler(fh)


def read_stats(log_root: str, type_filter: str | None = None) -> list:
    """Parse a ``stats`` file back into dicts (the reference leaves this to
    each consumer, e.g. ``examples/Simulation on MNIST.py:69-83``)."""
    out = []
    with open(os.path.join(log_root, "stats")) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = eval(line, {"__builtins__": {}}, {"nan": float("nan"), "inf": float("inf")})
            if type_filter is None or rec.get("_meta", {}).get("type") == type_filter:
                out.append(rec)
    return out
