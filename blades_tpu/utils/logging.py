"""Two-file run logging with reference parity.

The reference writes a ``stats`` file (one Python-dict repr per line, typed by
``_meta.type``) and a free-text ``debug`` file, wiping the log dir on init
(``src/blades/utils.py:67-95``). Downstream analysis parses the stats file
line-by-line (``examples/Simulation on MNIST.py:69-83``), so the format is
kept identical.
"""

from __future__ import annotations

import logging
import os
import shutil
from importlib import reload


def initialize_logger(log_root: str) -> None:
    """(Re)create ``log_root`` and attach fresh ``stats``/``debug`` loggers."""
    logging.shutdown()
    reload(logging)
    if os.path.exists(log_root):
        shutil.rmtree(log_root)
    os.makedirs(log_root)

    json_logger = logging.getLogger("stats")
    json_logger.setLevel(logging.INFO)
    fh = logging.FileHandler(os.path.join(log_root, "stats"))
    fh.setLevel(logging.INFO)
    fh.setFormatter(logging.Formatter("%(message)s"))
    json_logger.addHandler(fh)

    debug_logger = logging.getLogger("debug")
    debug_logger.setLevel(logging.INFO)
    fh = logging.FileHandler(os.path.join(log_root, "debug"))
    fh.setLevel(logging.INFO)
    fh.setFormatter(logging.Formatter("%(message)s"))
    debug_logger.addHandler(fh)


def read_stats(log_root: str, type_filter: str | None = None) -> list:
    """Parse a ``stats`` file back into dicts (the reference leaves this to
    each consumer, e.g. ``examples/Simulation on MNIST.py:69-83``)."""
    out = []
    with open(os.path.join(log_root, "stats")) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = eval(line, {"__builtins__": {}}, {"nan": float("nan"), "inf": float("inf")})
            if type_filter is None or rec.get("_meta", {}).get("type") == type_filter:
                out.append(rec)
    return out
