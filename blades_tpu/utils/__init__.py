"""Utility namespace (re-exports; reference counterpart:
``src/blades/utils.py`` — split here into per-concern modules, each with
its own citation)."""

from blades_tpu.utils.rng import key_for_round, key_per_client  # noqa: F401
from blades_tpu.utils.logging import initialize_logger  # noqa: F401
from blades_tpu.utils.metrics import top1_accuracy, accuracy  # noqa: F401
