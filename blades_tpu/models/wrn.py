"""WideResNet-28-10 in flax, GroupNorm-normalized (BASELINE.md config 5).

Pre-activation wide residual blocks (Zagoruyko & Komodakis). GroupNorm for
the same pure-function reason as resnet.py. Not in the reference's model
zoo (its CIFAR stable is ResNet-only, ``src/blades/models/cifar10/``);
added for the BASELINE.md config ladder.
"""

from __future__ import annotations

import jax.numpy as jnp
import flax.linen as nn

_he = nn.initializers.kaiming_normal()


def _norm(x: jnp.ndarray, groups: int = 8) -> jnp.ndarray:
    return nn.GroupNorm(num_groups=min(groups, x.shape[-1]))(x)


class WideBlock(nn.Module):
    filters: int
    stride: int = 1
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        y = nn.relu(_norm(x))
        shortcut = x
        if x.shape[-1] != self.filters or self.stride != 1:
            shortcut = nn.Conv(
                self.filters, (1, 1), strides=(self.stride, self.stride),
                use_bias=False, kernel_init=_he,
            )(y)
        y = nn.Conv(
            self.filters, (3, 3), strides=(self.stride, self.stride),
            padding=[(1, 1), (1, 1)], use_bias=False, kernel_init=_he,
        )(y)
        y = nn.relu(_norm(y))
        if self.dropout > 0:
            y = nn.Dropout(self.dropout)(y, deterministic=not train)
        y = nn.Conv(
            self.filters, (3, 3), padding=[(1, 1), (1, 1)],
            use_bias=False, kernel_init=_he,
        )(y)
        return y + shortcut


class WideResNet(nn.Module):
    depth: int = 28
    widen_factor: int = 10
    num_classes: int = 10
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        n = (self.depth - 4) // 6
        widths = [16, 16 * self.widen_factor, 32 * self.widen_factor, 64 * self.widen_factor]
        x = nn.Conv(
            widths[0], (3, 3), padding=[(1, 1), (1, 1)],
            use_bias=False, kernel_init=_he,
        )(x)
        for stage in range(3):
            for b in range(n):
                stride = 2 if stage > 0 and b == 0 else 1
                x = WideBlock(widths[stage + 1], stride, self.dropout)(x, train=train)
        x = nn.relu(_norm(x))
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


def wrn_28_10(num_classes: int = 10, **kw) -> WideResNet:
    return WideResNet(depth=28, widen_factor=10, num_classes=num_classes, **kw)
