"""ResNet-18/34 (CIFAR variant) in flax, GroupNorm-normalized.

The reference has no resnet (its CIFAR model is CCT), but BASELINE.md
configs 2-4 specify ResNet-18 as the 100/1000-client CIFAR-10 workload.
GroupNorm replaces BatchNorm so the model stays a pure ``params -> logits``
function under the vmapped federated client step (see models/__init__.py).
CIFAR stem: 3x3 conv, no max-pool.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import flax.linen as nn

_he = nn.initializers.kaiming_normal()


def _norm(x: jnp.ndarray, groups: int = 8) -> jnp.ndarray:
    return nn.GroupNorm(num_groups=min(groups, x.shape[-1]))(x)


class BasicBlock(nn.Module):
    filters: int
    stride: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        residual = x
        y = nn.Conv(
            self.filters, (3, 3), strides=(self.stride, self.stride),
            padding=[(1, 1), (1, 1)], use_bias=False, kernel_init=_he,
        )(x)
        y = nn.relu(_norm(y))
        y = nn.Conv(
            self.filters, (3, 3), padding=[(1, 1), (1, 1)],
            use_bias=False, kernel_init=_he,
        )(y)
        y = _norm(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters, (1, 1), strides=(self.stride, self.stride),
                use_bias=False, kernel_init=_he,
            )(residual)
            residual = _norm(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 10
    width: int = 64

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        x = nn.Conv(
            self.width, (3, 3), padding=[(1, 1), (1, 1)],
            use_bias=False, kernel_init=_he,
        )(x)
        x = nn.relu(_norm(x))
        filters = self.width
        for stage, blocks in enumerate(self.stage_sizes):
            for b in range(blocks):
                stride = 2 if stage > 0 and b == 0 else 1
                x = BasicBlock(filters, stride)(x)
            filters *= 2
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


def ResNet18(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(stage_sizes=(2, 2, 2, 2), num_classes=num_classes, **kw)


def ResNet34(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes, **kw)
