"""Compact Transformers (CCT / CVT / ViT-Lite) in flax.

Reference: vendored SHI-Labs Compact-Transformers
(``src/blades/models/cifar10/cctnets/``): conv ``Tokenizer``
(``utils/tokenizer.py:6``), pre-norm ``TransformerEncoderLayer`` with
stochastic depth (``utils/transformers.py:76-103``), ``TransformerClassifier``
with sequence pooling (``utils/transformers.py:134-216``). The flagship config
is ``cct_2_3x2_32`` — 2 encoder layers, dim 128, 2 heads, mlp_ratio 1, 3x3
conv tokenizer x2 — wrapped as ``CCTNet``
(``src/blades/models/cifar10/cct.py:6-16``, ~284K params).

TPU notes: NHWC layout, all matmuls MXU-shaped; attention over <=64 tokens is
a single fused softmax(QK^T)V — no flash/ring machinery needed at this
sequence length (SURVEY.md section 5, "long-context: absent by design").
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from blades_tpu.models.common import DropPath

_trunc02 = nn.initializers.truncated_normal(stddev=0.02)
_he = nn.initializers.kaiming_normal()


class Tokenizer(nn.Module):
    """Conv tokenizer (reference ``utils/tokenizer.py:6-49``): n conv layers
    (ReLU + 3x3/2 maxpool for CCT; a single patchify conv for CVT/ViT-Lite),
    flattened to a token sequence."""

    kernel_size: int
    stride: int
    padding: int
    n_conv_layers: int = 1
    n_output_channels: int = 64
    in_planes: int = 64
    max_pool: bool = True
    use_act: bool = True
    pooling_kernel_size: int = 3
    pooling_stride: int = 2
    pooling_padding: int = 1
    conv_bias: bool = False  # CCT: False; CVT/ViT patchify: True (tokenizer.py:16,28)

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        filters = [self.in_planes] * (self.n_conv_layers - 1) + [
            self.n_output_channels
        ]
        for f in filters:
            x = nn.Conv(
                f,
                (self.kernel_size, self.kernel_size),
                strides=(self.stride, self.stride),
                padding=[(self.padding, self.padding)] * 2,
                use_bias=self.conv_bias,
                kernel_init=_he,
            )(x)
            if self.use_act:
                x = nn.relu(x)
            if self.max_pool:
                x = nn.max_pool(
                    x,
                    (self.pooling_kernel_size,) * 2,
                    strides=(self.pooling_stride,) * 2,
                    padding=[(self.pooling_padding,) * 2] * 2,
                )
        return x.reshape(x.shape[0], -1, x.shape[-1])  # [B, N, C]

    def sequence_length(self, height: int, width: int, channels: int = 3) -> int:
        n = height
        for _ in range(self.n_conv_layers):
            n = (n + 2 * self.padding - self.kernel_size) // self.stride + 1
            if self.max_pool:
                n = (
                    n + 2 * self.pooling_padding - self.pooling_kernel_size
                ) // self.pooling_stride + 1
        return n * n


class Attention(nn.Module):
    """MHSA (reference ``utils/transformers.py:8-37``): qkv without bias,
    projection with bias, attention + projection dropout."""

    dim: int
    num_heads: int
    attention_dropout: float = 0.1
    projection_dropout: float = 0.1

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        b, n, c = x.shape
        head_dim = self.dim // self.num_heads
        qkv = nn.Dense(self.dim * 3, use_bias=False, kernel_init=_trunc02)(x)
        qkv = qkv.reshape(b, n, 3, self.num_heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B, N, H, Dh]
        attn = jnp.einsum("bnhd,bmhd->bhnm", q, k) * (head_dim**-0.5)
        attn = jax.nn.softmax(attn, axis=-1)
        attn = nn.Dropout(self.attention_dropout)(attn, deterministic=deterministic)
        out = jnp.einsum("bhnm,bmhd->bnhd", attn, v).reshape(b, n, c)
        out = nn.Dense(self.dim, kernel_init=_trunc02)(out)
        return nn.Dropout(self.projection_dropout)(out, deterministic=deterministic)


class TransformerEncoderLayer(nn.Module):
    """Pre-norm block with the reference's exact residual wiring
    (``utils/transformers.py:99-103``): attn residual, then LayerNorm, then an
    MLP residual branching off the *normed* stream."""

    d_model: int
    nhead: int
    dim_feedforward: int
    dropout: float = 0.1
    attention_dropout: float = 0.1
    drop_path_rate: float = 0.1

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        h = Attention(
            self.d_model,
            self.nhead,
            self.attention_dropout,
            self.dropout,
        )(nn.LayerNorm()(x), deterministic=deterministic)
        x = x + DropPath(self.drop_path_rate)(h, deterministic=deterministic)
        x = nn.LayerNorm()(x)
        h = nn.Dense(self.dim_feedforward, kernel_init=_trunc02)(x)
        h = nn.Dropout(self.dropout)(nn.gelu(h), deterministic=deterministic)
        h = nn.Dense(self.d_model, kernel_init=_trunc02)(h)
        h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        return x + DropPath(self.drop_path_rate)(h, deterministic=deterministic)


def sinusoidal_embedding(n: int, dim: int) -> jnp.ndarray:
    pos = jnp.arange(n)[:, None]
    i = jnp.arange(dim)[None, :]
    angle = pos / jnp.power(10000.0, 2 * (i // 2) / dim)
    pe = jnp.where(i % 2 == 0, jnp.sin(angle), jnp.cos(angle))
    return pe[None]


class CCT(nn.Module):
    """Compact Convolutional Transformer (reference ``cctnets/cct.py:33-88``).

    ``seq_pool=True`` -> attention sequence pooling; ``False`` -> class token
    (ViT-Lite mode). The tokenizer style (conv stack vs patchify) is what
    distinguishes CCT from CVT/ViT-Lite.
    """

    num_classes: int = 10
    img_size: int = 32
    in_channels: int = 3
    embedding_dim: int = 128
    num_layers: int = 2
    num_heads: int = 2
    mlp_ratio: float = 1.0
    kernel_size: int = 3
    stride: Optional[int] = None
    padding: Optional[int] = None
    n_conv_layers: int = 2
    max_pool: bool = True
    use_act: bool = True
    seq_pool: bool = True
    dropout: float = 0.0
    attention_dropout: float = 0.1
    stochastic_depth: float = 0.1
    positional_embedding: str = "learnable"  # learnable | sine | none
    conv_bias: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        det = not train
        stride = (
            self.stride
            if self.stride is not None
            else max(1, (self.kernel_size // 2) - 1)
        )
        padding = (
            self.padding if self.padding is not None else max(1, self.kernel_size // 2)
        )
        tokenizer = Tokenizer(
            kernel_size=self.kernel_size,
            stride=stride,
            padding=padding,
            n_conv_layers=self.n_conv_layers,
            n_output_channels=self.embedding_dim,
            in_planes=64,
            max_pool=self.max_pool,
            use_act=self.use_act,
            conv_bias=self.conv_bias,
        )
        x = tokenizer(x)
        seq_len = x.shape[1]

        if not self.seq_pool:
            cls = self.param(
                "class_emb", nn.initializers.zeros, (1, 1, self.embedding_dim)
            )
            x = jnp.concatenate([jnp.tile(cls, (x.shape[0], 1, 1)), x], axis=1)
            seq_len += 1

        if self.positional_embedding == "learnable":
            pe = self.param(
                "positional_emb",
                nn.initializers.truncated_normal(stddev=0.2),
                (1, seq_len, self.embedding_dim),
            )
            x = x + pe
        elif self.positional_embedding == "sine":
            x = x + sinusoidal_embedding(seq_len, self.embedding_dim)

        x = nn.Dropout(self.dropout)(x, deterministic=det)
        # static (host) linspace: drop-path rates are compile-time constants
        dpr = [
            self.stochastic_depth * i / max(self.num_layers - 1, 1)
            for i in range(self.num_layers)
        ]
        for i in range(self.num_layers):
            x = TransformerEncoderLayer(
                d_model=self.embedding_dim,
                nhead=self.num_heads,
                dim_feedforward=int(self.embedding_dim * self.mlp_ratio),
                dropout=self.dropout,
                attention_dropout=self.attention_dropout,
                drop_path_rate=dpr[i],
            )(x, deterministic=det)
        x = nn.LayerNorm()(x)

        if self.seq_pool:
            # softmax(Wx)^T x over the sequence (utils/transformers.py:209)
            w = nn.Dense(1, kernel_init=_trunc02)(x)  # [B, N, 1]
            w = jax.nn.softmax(w, axis=1)
            x = jnp.einsum("bnl,bnc->bc", w, x)
        else:
            x = x[:, 0]
        return nn.Dense(self.num_classes, kernel_init=_trunc02)(x)


# -- variant factories (reference cctnets/cct.py:121-254, cvt.py, vit.py) -----


def cct_2_3x2_32(num_classes: int = 10, img_size: int = 32, **kw) -> CCT:
    return CCT(
        num_classes=num_classes,
        img_size=img_size,
        num_layers=2,
        num_heads=2,
        mlp_ratio=1.0,
        embedding_dim=128,
        kernel_size=3,
        n_conv_layers=2,
        **kw,
    )


def cct_4_3x2_32(num_classes: int = 10, img_size: int = 32, **kw) -> CCT:
    return CCT(
        num_classes=num_classes,
        img_size=img_size,
        num_layers=4,
        num_heads=2,
        mlp_ratio=1.0,
        embedding_dim=128,
        kernel_size=3,
        n_conv_layers=2,
        **kw,
    )


def cct_6_3x1_32(num_classes: int = 10, img_size: int = 32, **kw) -> CCT:
    return CCT(
        num_classes=num_classes,
        img_size=img_size,
        num_layers=6,
        num_heads=4,
        mlp_ratio=2.0,
        embedding_dim=256,
        kernel_size=3,
        n_conv_layers=1,
        **kw,
    )


def cct_7_3x1_32(num_classes: int = 10, img_size: int = 32, **kw) -> CCT:
    return CCT(
        num_classes=num_classes,
        img_size=img_size,
        num_layers=7,
        num_heads=4,
        mlp_ratio=2.0,
        embedding_dim=256,
        kernel_size=3,
        n_conv_layers=1,
        **kw,
    )


def cvt_7_4_32(num_classes: int = 10, img_size: int = 32, **kw) -> CCT:
    """CVT: patchify tokenizer (4x4 conv, no act/pool) + seq-pool
    (reference ``cctnets/cvt.py:17-58``)."""
    return CCT(
        num_classes=num_classes,
        img_size=img_size,
        num_layers=7,
        num_heads=4,
        mlp_ratio=2.0,
        embedding_dim=256,
        kernel_size=4,
        stride=4,
        padding=0,
        n_conv_layers=1,
        max_pool=False,
        use_act=False,
        conv_bias=True,
        seq_pool=True,
        **kw,
    )


def vit_lite_7_4_32(num_classes: int = 10, img_size: int = 32, **kw) -> CCT:
    """ViT-Lite: patchify tokenizer + class token instead of seq-pool
    (reference ``cctnets/vit.py:17-60``)."""
    return CCT(
        num_classes=num_classes,
        img_size=img_size,
        num_layers=7,
        num_heads=4,
        mlp_ratio=2.0,
        embedding_dim=256,
        kernel_size=4,
        stride=4,
        padding=0,
        n_conv_layers=1,
        max_pool=False,
        use_act=False,
        conv_bias=True,
        seq_pool=False,
        **kw,
    )


# Reference wrapper-class name parity (src/blades/models/cifar10/cct.py:6-16)
CCTNet = cct_2_3x2_32
