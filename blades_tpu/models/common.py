"""Shared model plumbing: the flax-module -> pure-function adapter.

The round engine (``blades_tpu/core/engine.py``) consumes two pure functions,
``train_loss_fn(params, x, y, key)`` and ``eval_logits_fn(params, x)``.
:func:`build_fns` derives both from any flax module (dropout/droppath keyed by
``key`` in train mode, deterministic in eval), replacing the reference's
``model``/``loss_func`` object pair (``src/blades/client.py:100-109``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn


def cross_entropy(logits: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Mean softmax cross-entropy; accepts logits OR log-probs (the reference
    MNIST MLP outputs log_softmax and is trained with CrossEntropyLoss on it,
    ``models/mnist/dnn.py:17-19`` — log_softmax is idempotent here so both
    conventions give identical losses)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    one_hot = jax.nn.one_hot(y, logits.shape[-1], dtype=jnp.float32)
    return -jnp.mean(jnp.sum(one_hot * logp, axis=-1))


@dataclasses.dataclass
class ModelSpec:
    """Bundle of the pure functions the engine needs, plus init.

    ``rebuild_ok``: True when ``train_loss_fn``/``eval_logits_fn`` are the
    stock :func:`build_fns` products (no custom loss or eval logic), so a
    consumer may regenerate them from ``module`` with different build
    options (e.g. ``compute_dtype``) without losing behavior.
    """

    module: Any
    init: Callable[[jax.Array], Any]
    train_loss_fn: Callable
    eval_logits_fn: Callable
    param_count: Optional[int] = None
    rebuild_ok: bool = False


def build_fns(
    module: nn.Module,
    sample_shape: Tuple[int, ...],
    loss: str = "crossentropy",
    param_dtype=jnp.float32,
    input_dtype=None,
    pad_id: Optional[int] = None,
    compute_dtype=None,
) -> ModelSpec:
    """Adapt a flax module to the engine's pure-function interface.

    ``loss='crossentropy'`` matches the reference's only supported loss
    (``client.py:100-104`` raises for anything else). ``input_dtype``
    overrides the dummy-input dtype at init (int32 for token-id text models).
    ``pad_id``: for text models — derive a validity mask ``x != pad_id`` and
    pass it to the module so padded positions never influence attention or
    pooling (the reference's mask plumbing, ``utils/embedder.py:23-28``).
    ``compute_dtype``: mixed precision — e.g. ``jnp.bfloat16`` runs the
    forward/backward in bf16 on the MXU while master params, gradients (via
    the cast's transpose), loss, and the update pipeline stay float32.
    """
    if loss != "crossentropy":
        raise NotImplementedError(f"loss {loss!r} (reference parity: crossentropy only)")

    def _kwargs(x):
        return {"mask": x != pad_id} if pad_id is not None else {}

    def _cast(tree):
        if compute_dtype is None:
            return tree
        return jax.tree_util.tree_map(
            lambda a: a.astype(compute_dtype)
            if jnp.issubdtype(a.dtype, jnp.floating)
            else a,
            tree,
        )

    def init(key: jax.Array):
        dummy = jnp.zeros((1,) + tuple(sample_shape), input_dtype or param_dtype)
        variables = module.init({"params": key}, dummy, train=False, **_kwargs(dummy))
        return variables["params"]

    def train_loss_fn(params, x, y, key):
        logits = module.apply(
            {"params": _cast(params)}, _cast(x), train=True,
            rngs={"dropout": key}, **_kwargs(x)
        )
        top1 = jnp.mean((jnp.argmax(logits, axis=-1) == y).astype(jnp.float32))
        return cross_entropy(logits, y), {"top1": top1}

    def eval_logits_fn(params, x):
        return module.apply({"params": _cast(params)}, _cast(x), train=False, **_kwargs(x))

    return ModelSpec(
        module=module,
        init=init,
        train_loss_fn=train_loss_fn,
        eval_logits_fn=eval_logits_fn,
        rebuild_ok=True,
    )


class DropPath(nn.Module):
    """Per-sample stochastic depth (reference:
    ``cctnets/utils/stochastic_depth.py:28``): drop a residual branch for a
    whole sample with probability ``rate``, rescaling survivors."""

    rate: float = 0.0

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        if self.rate == 0.0 or deterministic:
            return x
        keep = 1.0 - self.rate
        rng = self.make_rng("dropout")
        shape = (x.shape[0],) + (1,) * (x.ndim - 1)
        mask = jax.random.bernoulli(rng, keep, shape)
        return jnp.where(mask, x / keep, jnp.zeros_like(x))


trunc_normal = nn.initializers.truncated_normal
