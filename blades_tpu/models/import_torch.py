"""Torch-checkpoint import for the Compact-Transformer zoo.

The reference ships pretrained CCT weights as torch ``state_dict`` files
fetched by URL (``src/blades/models/cifar10/cctnets/cct.py:13-30,90-118``
via ``load_state_dict_from_url``). This module converts such a state_dict —
loaded from a LOCAL ``.pth`` (this build performs no network downloads) —
into the flax parameter pytree of :class:`blades_tpu.models.cct.CCT`, so a
user migrating from the reference keeps their checkpoints.

Layout conversions:

- conv: torch OIHW -> flax HWIO
- linear: torch ``[out, in]`` -> flax ``[in, out]`` kernels
- LayerNorm ``weight``/``bias`` -> ``scale``/``bias``

Key-structure mapping (torch name -> flax path):

- ``tokenizer.conv_layers.{i}.0.weight`` -> ``Tokenizer_0/Conv_{i}/kernel``
- ``classifier.positional_emb``/``class_emb`` -> top-level params
- ``classifier.blocks.{i}.pre_norm`` -> ``TransformerEncoderLayer_{i}/LayerNorm_0``
- ``classifier.blocks.{i}.self_attn.qkv|proj`` -> ``.../Attention_0/Dense_0|1``
- ``classifier.blocks.{i}.norm1`` -> ``.../LayerNorm_1``
- ``classifier.blocks.{i}.linear1|linear2`` -> ``.../Dense_0|1``
- ``classifier.norm`` -> top-level ``LayerNorm_0``
- ``classifier.attention_pool`` -> first top-level Dense (seq-pool models)
- ``classifier.fc`` -> last top-level Dense
"""

from __future__ import annotations

import logging
import math
from typing import Any, Dict, Mapping

import numpy as np

logger = logging.getLogger("debug")


def _np(t) -> np.ndarray:
    # accepts torch tensors or arrays without importing torch here
    return t.detach().cpu().numpy() if hasattr(t, "detach") else np.asarray(t)


def resize_pos_embed(
    posemb: np.ndarray, new_len: int, num_tokens: int = 0
) -> np.ndarray:
    """Bilinearly rescale a learned positional-embedding grid to a new token
    count (reference ``resize_pos_embed``/``pe_check``,
    ``cctnets/utils/helpers.py:10-36``: loading a checkpoint trained at a
    different input resolution interpolates the square grid; the first
    ``num_tokens`` class-token embeddings pass through untouched).

    ``posemb``: ``[1, n_old, d]`` -> returns ``[1, new_len, d]``.
    """
    import jax

    tok, grid = posemb[:, :num_tokens], posemb[0, num_tokens:]
    gs_old = int(math.sqrt(grid.shape[0]))
    gs_new = int(math.sqrt(new_len - num_tokens))
    if gs_old * gs_old != grid.shape[0] or gs_new * gs_new != new_len - num_tokens:
        raise ValueError(
            f"positional-embedding lengths {grid.shape[0]} -> "
            f"{new_len - num_tokens} are not square grids; cannot interpolate"
        )
    grid = grid.reshape(gs_old, gs_old, -1)
    # half-pixel-centered bilinear resize == torch F.interpolate(bilinear,
    # align_corners=False), the reference's mode (helpers.py:24)
    grid = jax.image.resize(
        grid, (gs_new, gs_new, grid.shape[-1]), method="bilinear"
    )
    grid = np.asarray(grid).reshape(1, gs_new * gs_new, -1)
    return np.concatenate([tok, grid], axis=1)


def torch_cct_to_flax(
    state_dict: Mapping[str, Any],
    params_template: Dict[str, Any],
    pe_resize: bool = True,
    fc_tolerant: bool = True,
) -> Dict[str, Any]:
    """Convert a reference-CCT torch state_dict into our flax param tree.

    ``params_template``: a freshly initialized param tree of the matching
    variant (supplies structure; every leaf must be covered by the
    state_dict and vice versa, or a ``ValueError`` explains the mismatch).

    Load-tolerance semantics mirror the reference's checkpoint loader
    (``cctnets/cct.py:110-116``): ``pe_resize`` bilinearly interpolates a
    positional embedding whose token count differs (``pe_check``);
    ``fc_tolerant`` keeps the template's freshly initialized classifier head
    when the checkpoint's class count differs (``fc_check``). Pass False to
    get strict shape errors instead.
    """
    import jax

    has_pool = any(k.startswith("classifier.attention_pool") for k in state_dict)
    out: Dict[str, Any] = jax.tree_util.tree_map(lambda x: None, params_template)

    def put(path, value):
        node = out
        for p in path[:-1]:
            if not isinstance(node, dict) or p not in node:
                raise ValueError(
                    f"flax param path {path} missing in template — checkpoint "
                    "is for a different model variant (depth/width/pooling)?"
                )
            node = node[p]
        if not isinstance(node, dict) or path[-1] not in node:
            raise ValueError(
                f"flax param path {path} missing in template — checkpoint "
                "is for a different model variant (depth/width/pooling)?"
            )
        node[path[-1]] = value

    for key, t in state_dict.items():
        v = _np(t).astype(np.float32)
        parts = key.split(".")
        if len(parts) < 2:
            raise ValueError(
                f"unrecognized state_dict key {key!r} (not a CCT-zoo "
                "state_dict? unwrap the checkpoint's 'state_dict' entry)"
            )
        if key == "classifier.positional_emb" and "positional_emb" not in out:
            # *_sine reference variants store the fixed sinusoidal table as a
            # parameter (utils/transformers.py:277-280); our sine models
            # compute it, so the key is informational only
            continue
        if parts[0] == "tokenizer":
            # tokenizer.conv_layers.{i}.0.{weight,bias}; weight OIHW -> HWIO
            i = int(parts[2])
            if parts[-1] == "weight":
                put(("Tokenizer_0", f"Conv_{i}", "kernel"), v.transpose(2, 3, 1, 0))
            else:
                put(("Tokenizer_0", f"Conv_{i}", "bias"), v)
        elif key == "classifier.positional_emb":
            put(("positional_emb",), v)
        elif key == "classifier.class_emb":
            put(("class_emb",), v)
        elif parts[1] == "blocks":
            i, sub = int(parts[2]), parts[3]
            layer = f"TransformerEncoderLayer_{i}"
            kind = "scale" if parts[-1] == "weight" else "bias"
            if sub == "pre_norm":
                put((layer, "LayerNorm_0", kind), v)
            elif sub == "norm1":
                put((layer, "LayerNorm_1", kind), v)
            elif sub == "self_attn":
                which = "Dense_0" if parts[4] == "qkv" else "Dense_1"
                if parts[-1] == "weight":
                    put((layer, "Attention_0", which, "kernel"), v.T)
                else:
                    put((layer, "Attention_0", which, "bias"), v)
            elif sub in ("linear1", "linear2"):
                which = "Dense_0" if sub == "linear1" else "Dense_1"
                if parts[-1] == "weight":
                    put((layer, which, "kernel"), v.T)
                else:
                    put((layer, which, "bias"), v)
            else:
                raise ValueError(f"unrecognized block entry {key!r}")
        elif parts[1] == "norm":
            put(("LayerNorm_0", "scale" if parts[-1] == "weight" else "bias"), v)
        elif parts[1] == "attention_pool":
            tgt = ("Dense_0", parts[-1].replace("weight", "kernel"))
            put(tgt, v.T if parts[-1] == "weight" else v)
        elif parts[1] == "fc":
            name = "Dense_1" if has_pool else "Dense_0"
            put(
                (name, parts[-1].replace("weight", "kernel")),
                v.T if parts[-1] == "weight" else v,
            )
        else:
            raise ValueError(f"unrecognized state_dict key {key!r}")

    # pe_check: interpolate a positional embedding trained at a different
    # resolution instead of failing the strict shape check below
    if pe_resize and out.get("positional_emb") is not None:
        tmpl_pe = params_template["positional_emb"]
        cur = out["positional_emb"]
        if tuple(cur.shape) != tuple(tmpl_pe.shape):
            num_tokens = 1 if "class_emb" in params_template else 0
            out["positional_emb"] = resize_pos_embed(
                cur, int(tmpl_pe.shape[1]), num_tokens
            )
            logger.info(
                "resized positional embedding %s -> %s (pe_check)",
                cur.shape, tuple(tmpl_pe.shape),
            )

    # fc_check: a class-count mismatch keeps the fresh head instead of failing
    if fc_tolerant:
        fc_name = "Dense_1" if has_pool else "Dense_0"
        fc_node = out.get(fc_name)
        tmpl_fc = params_template.get(fc_name)
        if isinstance(fc_node, dict) and isinstance(tmpl_fc, dict):
            for leaf in ("kernel", "bias"):
                got, want = fc_node.get(leaf), tmpl_fc.get(leaf)
                if (
                    got is not None
                    and want is not None
                    and tuple(got.shape) != tuple(want.shape)
                ):
                    logger.warning(
                        "Removing %s.%s, number of classes has changed.",
                        fc_name, leaf,
                    )
                    fc_node[leaf] = np.asarray(want)

    # completeness + shape validation against the template
    import jax.numpy as jnp

    def check(path, tmpl_leaf, new_leaf):
        if new_leaf is None:
            raise ValueError(f"state_dict left flax param {path} unfilled")
        if tuple(tmpl_leaf.shape) != tuple(new_leaf.shape):
            raise ValueError(
                f"shape mismatch at {path}: checkpoint {new_leaf.shape} vs "
                f"model {tmpl_leaf.shape}"
            )
        return jnp.asarray(new_leaf)

    flat_t, treedef = jax.tree_util.tree_flatten_with_path(params_template)
    return jax.tree_util.tree_unflatten(
        treedef,
        [
            check(jax.tree_util.keystr(p), leaf, _leaf_at(out, p))
            for p, leaf in flat_t
        ],
    )


def _leaf_at(tree, path):
    node = tree
    for p in path:
        node = node[getattr(p, "key", p)]
    return node


def load_torch_checkpoint(path: str, params_template: Dict[str, Any]):
    """Load a reference ``.pth`` checkpoint file and convert (requires the
    baked-in CPU torch only for deserialization)."""
    import torch

    sd = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(sd, dict) and "state_dict" in sd:
        sd = sd["state_dict"]
    return torch_cct_to_flax(sd, params_template)
