"""Pretrained-weight registry for the Compact-Transformer zoo.

Reference: ``src/blades/models/cifar10/cctnets/cct.py:13-30`` keeps a
per-variant URL table and ``:90-118`` fetches the torch ``state_dict`` with
``load_state_dict_from_url`` at model construction when ``pretrained=True``.
Same contract here: a URL table, an on-disk cache, and a loader that
converts the torch checkpoint into our flax parameter tree
(:mod:`blades_tpu.models.import_torch`).

Offline-first: a checkpoint already present in the cache directory
(``$BLADES_TPU_WEIGHTS`` or ``~/.cache/blades_tpu``) is used without any
network touch; downloading only happens on a cache miss and can be disabled
entirely with ``BLADES_TPU_OFFLINE=1`` (zero-egress environments get a
clear error telling them where to place the file instead).
"""

from __future__ import annotations

import os
from typing import Any, Dict

# reference cctnets/cct.py:13-30, verbatim variant -> URL table
MODEL_URLS: Dict[str, str] = {
    "cct_7_3x1_32":
        "http://ix.cs.uoregon.edu/~alih/compact-transformers/checkpoints/pretrained/cct_7_3x1_32_cifar10_300epochs.pth",
    "cct_7_3x1_32_sine":
        "http://ix.cs.uoregon.edu/~alih/compact-transformers/checkpoints/pretrained/cct_7_3x1_32_sine_cifar10_5000epochs.pth",
    "cct_7_3x1_32_c100":
        "http://ix.cs.uoregon.edu/~alih/compact-transformers/checkpoints/pretrained/cct_7_3x1_32_cifar100_300epochs.pth",
    "cct_7_3x1_32_sine_c100":
        "http://ix.cs.uoregon.edu/~alih/compact-transformers/checkpoints/pretrained/cct_7_3x1_32_sine_cifar100_5000epochs.pth",
    "cct_7_7x2_224_sine":
        "http://ix.cs.uoregon.edu/~alih/compact-transformers/checkpoints/pretrained/cct_7_7x2_224_flowers102.pth",
    "cct_14_7x2_224":
        "http://ix.cs.uoregon.edu/~alih/compact-transformers/checkpoints/pretrained/cct_14_7x2_224_imagenet.pth",
    "cct_14_7x2_384":
        "http://ix.cs.uoregon.edu/~alih/compact-transformers/checkpoints/finetuned/cct_14_7x2_384_imagenet.pth",
    "cct_14_7x2_384_fl":
        "http://ix.cs.uoregon.edu/~alih/compact-transformers/checkpoints/finetuned/cct_14_7x2_384_flowers102.pth",
}


def cache_dir() -> str:
    return os.environ.get(
        "BLADES_TPU_WEIGHTS",
        os.path.join(os.path.expanduser("~"), ".cache", "blades_tpu"),
    )


def weights_path(name: str) -> str:
    """Cache location of a variant's checkpoint (URL basename)."""
    if name not in MODEL_URLS:
        raise ValueError(
            f"no pretrained weights registered for {name!r}; "
            f"available: {sorted(MODEL_URLS)}"
        )
    return os.path.join(cache_dir(), os.path.basename(MODEL_URLS[name]))


def fetch_weights(name: str) -> str:
    """Return the local checkpoint path, downloading on cache miss."""
    from blades_tpu.utils.fetch import fetch_to

    path = weights_path(name)
    if os.path.exists(path):
        return path
    import urllib.request

    url = MODEL_URLS[name]
    return fetch_to(path, lambda: urllib.request.urlopen(url),
                    f"pretrained weights {name!r} from {url}")


def load_pretrained(name: str, params_template: Dict[str, Any]):
    """Pretrained flax params for ``name``, shaped like ``params_template``."""
    from blades_tpu.models.import_torch import load_torch_checkpoint

    return load_torch_checkpoint(fetch_weights(name), params_template)


def pretrained_spec(name: str, module, sample_shape=(32, 32, 3)):
    """A :class:`ModelSpec` whose ``init`` returns the pretrained weights.

    The reference mutates the torch module in place
    (``cct.py:108-116``); in the functional world the natural seam is
    ``init`` — everything downstream (Simulator, RoundEngine) already
    consumes specs, so a pretrained model drops in anywhere a fresh one
    does. A class-count mismatch with the checkpoint head fails with a
    shape error at load (the reference's ``fc_check`` silently re-inits
    the head instead; we refuse — silent partial loads are how wrong
    baselines happen).
    """
    from blades_tpu.models.common import build_fns

    spec = build_fns(module, sample_shape)
    base_init = spec.init

    def init(key):
        return load_pretrained(name, base_init(key))

    spec.init = init
    return spec
