"""Model zoo (flax.linen, NHWC, TPU-first).

Reference counterparts (``src/blades/models/``): MNIST ``MLP``
(``mnist/dnn.py:5-23``), CIFAR-10 Compact-Transformer zoo — ``CCT``
(``cifar10/cctnets/cct.py:33``), ``CVT`` (``cvt.py:17``), ``ViTLite``
(``vit.py:17``) — vendored from SHI-Labs Compact-Transformers. ResNet-18 and
WideResNet-28-10 cover the BASELINE.md workloads (configs 2-5). GroupNorm
replaces BatchNorm in the resnets: running statistics are cross-batch mutable
state that breaks the pure-functional vmapped client step and is known-bad
under non-IID federated data; GroupNorm is the standard FL substitution and
keeps every model a pure ``params -> logits`` function.
"""

from __future__ import annotations

from typing import Callable, Dict

from blades_tpu.models.common import ModelSpec, build_fns
from blades_tpu.models.mlp import MLP, create_mnist_model
from blades_tpu.models.cct import (
    CCT,
    cct_2_3x2_32,
    cct_4_3x2_32,
    cct_6_3x1_32,
    cct_7_3x1_32,
    cvt_7_4_32,
    vit_lite_7_4_32,
    CCTNet,
)
from blades_tpu.models.import_torch import load_torch_checkpoint, torch_cct_to_flax
from blades_tpu.models.pretrained import MODEL_URLS, fetch_weights, load_pretrained
from blades_tpu.models.resnet import ResNet18, ResNet34
from blades_tpu.models.text import (
    TextCCT,
    text_cct_2,
    text_cct_4,
    text_cct_6,
    text_cvt_2,
    text_cvt_4,
    text_cvt_6,
    text_vit_2,
    text_vit_4,
    text_vit_6,
    text_transformer_2,
    text_transformer_4,
    text_transformer_6,
    long_text_transformer,
)
from blades_tpu.models.wrn import WideResNet, wrn_28_10

MODELS: Dict[str, Callable] = {
    "mlp": lambda num_classes=10, **kw: MLP(num_classes=num_classes),
    "cct": lambda num_classes=10, **kw: cct_2_3x2_32(num_classes=num_classes),
    "cctnet": lambda num_classes=10, **kw: cct_2_3x2_32(num_classes=num_classes),
    "cct_2_3x2_32": cct_2_3x2_32,
    "cct_4_3x2_32": cct_4_3x2_32,
    "cct_6_3x1_32": cct_6_3x1_32,
    "cct_7_3x1_32": cct_7_3x1_32,
    "cvt_7_4_32": cvt_7_4_32,
    "vit_lite_7_4_32": vit_lite_7_4_32,
    "resnet18": lambda num_classes=10, **kw: ResNet18(num_classes=num_classes),
    "resnet34": lambda num_classes=10, **kw: ResNet34(num_classes=num_classes),
    "wrn_28_10": wrn_28_10,
    "text_cct_2": text_cct_2,
    "text_cct_4": text_cct_4,
    "text_cct_6": text_cct_6,
    "text_cvt_2": text_cvt_2,
    "text_cvt_4": text_cvt_4,
    "text_cvt_6": text_cvt_6,
    "text_vit_2": text_vit_2,
    "text_vit_4": text_vit_4,
    "text_vit_6": text_vit_6,
    "text_transformer_2": text_transformer_2,
    "long_text_transformer": long_text_transformer,
    "text_transformer_4": text_transformer_4,
    "text_transformer_6": text_transformer_6,
}


def create_model(name: str, num_classes: int = 10, pretrained=False, **kwargs):
    """Resolve a model by name (reference: per-dataset ``create_model()``
    factories, e.g. ``models/mnist/dnn.py:22``).

    ``pretrained=True`` returns a :class:`ModelSpec` whose ``init`` yields
    the registered checkpoint's weights (reference ``pretrained=True``
    kwarg, ``cctnets/cct.py:90-118``); pass a string to pick a different
    registry entry for the same architecture (e.g.
    ``create_model("cct_7_3x1_32", num_classes=100,
    pretrained="cct_7_3x1_32_c100")``). Weights come from the local cache,
    downloading only on a miss (``models/pretrained.py``).
    """
    try:
        factory = MODELS[name]
    except KeyError:
        raise ValueError(f"Unknown model {name!r}; available: {sorted(MODELS)}") from None
    model = factory(num_classes=num_classes, **kwargs)
    if pretrained:
        from blades_tpu.models.pretrained import pretrained_spec

        weights_name = pretrained if isinstance(pretrained, str) else name
        return pretrained_spec(weights_name, model)
    return model


__all__ = [
    "ModelSpec",
    "build_fns",
    "create_model",
    "MODELS",
    "MLP",
    "create_mnist_model",
    "CCT",
    "CCTNet",
    "cct_2_3x2_32",
    "cct_4_3x2_32",
    "cct_6_3x1_32",
    "cct_7_3x1_32",
    "cvt_7_4_32",
    "vit_lite_7_4_32",
    "ResNet18",
    "ResNet34",
    "WideResNet",
    "wrn_28_10",
    "load_torch_checkpoint",
    "MODEL_URLS",
    "fetch_weights",
    "load_pretrained",
    "torch_cct_to_flax",
    "TextCCT",
    "text_cct_2",
    "text_cct_4",
    "text_cct_6",
    "text_cvt_2",
    "text_cvt_4",
    "text_cvt_6",
    "text_vit_2",
    "text_vit_4",
    "text_vit_6",
    "text_transformer_2",
    "long_text_transformer",
    "text_transformer_4",
    "text_transformer_6",
]
