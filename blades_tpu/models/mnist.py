"""Reference-path alias: ``blades.models.mnist`` -> here.

The reference exposes the MNIST model as ``from blades.models.mnist import
MLP`` (``src/blades/models/mnist/dnn.py``); migrating code keeps working
with the package name swapped.
"""

from blades_tpu.models.mlp import MLP, create_mnist_model as create_model

__all__ = ["MLP", "create_model"]
