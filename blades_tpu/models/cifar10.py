"""Reference-path alias: ``blades.models.cifar10`` -> here.

The reference exposes the CIFAR-10 zoo as ``from blades.models.cifar10
import CCTNet`` (``src/blades/models/cifar10/cct.py:6-16``); migrating code
keeps working with the package name swapped.
"""

from blades_tpu.models.cct import (
    CCT,
    CCTNet,
    cct_2_3x2_32,
    cct_4_3x2_32,
    cct_6_3x1_32,
    cct_7_3x1_32,
    cvt_7_4_32,
    vit_lite_7_4_32,
)

__all__ = [
    "CCT",
    "CCTNet",
    "cct_2_3x2_32",
    "cct_4_3x2_32",
    "cct_6_3x1_32",
    "cct_7_3x1_32",
    "cvt_7_4_32",
    "vit_lite_7_4_32",
]
