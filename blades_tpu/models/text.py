"""Text Compact Transformers (TextCCT / TextCVT / TextViT / Transformer-Lite).

Reference: ``src/blades/models/cifar10/cctnets/text/`` — word ``Embedder``
(``utils/embedder.py:4-37``), 1-D conv ``TextTokenizer``
(``utils/tokenizer.py:52-120``), ``MaskedTransformerClassifier`` with
pairwise-masked attention (``utils/transformers.py:39-71,235-322``), and the
factory grids ``text_cct_{2,4,6}`` (``text/cct.py:74-86``),
``text_cvt_{2,4,6}`` (``text/cvt.py:61-73``), ``text_vit_{2,4,6}``
(``text/vit.py:61-73``), ``text_transformer_{2,4,6}``
(``text/transformer.py:45-57``).

Semantics kept: padded positions are zeroed after embedding and after the
tokenizer; the token-level mask is propagated through the conv/pool exactly
as a ones-kernel conv1d + maxpool of the float mask (> 0); attention logits
get the pairwise mask ``m[:, None] & m[None, :]`` filled with -inf before
softmax; class-token mode extends the mask with an always-valid slot.
Deviation: the positional embedding is always sized to the *runtime* token
sequence (the reference sizes sine tables with an extra padding row that
cannot broadcast — a latent crash its no-test policy never caught).

TPU notes: the tokenizer's (k x E) conv is expressed as a 1-D feature-mixing
conv over NWC layout — one MXU matmul per window position; masking is
elementwise ``jnp.where`` fused into the attention softmax by XLA.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from blades_tpu.models.cct import sinusoidal_embedding, _trunc02
from blades_tpu.models.common import DropPath

NEG_INF = -1e9  # mask fill for fp32/bf16 attention logits


class Embedder(nn.Module):
    """Word embedding table (reference ``utils/embedder.py:4-37``); padded
    positions (mask == 0) are zeroed."""

    vocab_size: int = 100_000
    word_embedding_dim: int = 300

    @nn.compact
    def __call__(self, tokens: jnp.ndarray, mask: Optional[jnp.ndarray] = None):
        x = nn.Embed(
            self.vocab_size,
            self.word_embedding_dim,
            embedding_init=nn.initializers.normal(1.0),
        )(tokens)
        if mask is not None:
            x = x * mask[..., None].astype(x.dtype)
        return x, mask


class TextTokenizer(nn.Module):
    """1-D conv tokenizer (reference ``utils/tokenizer.py:52-120``): a single
    conv spanning the full embedding width, optional ReLU, optional 1-D
    maxpool; the boolean mask rides along through the same receptive fields."""

    kernel_size: int
    stride: int
    padding: int
    n_output_channels: int = 128
    max_pool: bool = True
    use_act: bool = True
    pooling_kernel_size: int = 3
    pooling_stride: int = 2
    pooling_padding: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray, mask: Optional[jnp.ndarray] = None):
        # [B, L, E] -> [B, L', C]: conv over the sequence axis, full-width in E
        x = nn.Conv(
            self.n_output_channels,
            (self.kernel_size,),
            strides=(self.stride,),
            padding=[(self.padding, self.padding)],
            use_bias=False,
            kernel_init=nn.initializers.kaiming_normal(),
        )(x)
        if self.use_act:
            x = nn.relu(x)
        if self.max_pool:
            x = nn.max_pool(
                x,
                (self.pooling_kernel_size,),
                strides=(self.pooling_stride,),
                padding=[(self.pooling_padding,) * 2],
            )
        if mask is not None:
            mask = self._forward_mask(mask)
            x = x * mask[..., None].astype(x.dtype)
        return x, mask

    def _forward_mask(self, mask: jnp.ndarray) -> jnp.ndarray:
        """Ones-kernel conv1d + maxpool of the float mask, thresholded > 0
        (reference ``tokenizer.py:78-95``): a token survives if any source
        position in its receptive field was valid."""
        m = mask.astype(jnp.float32)[..., None]  # [B, L, 1]
        ones = jnp.ones((self.kernel_size, 1, 1), jnp.float32)
        m = jax.lax.conv_general_dilated(
            m,
            ones,
            window_strides=(self.stride,),
            padding=[(self.padding, self.padding)],
            dimension_numbers=("NWC", "WIO", "NWC"),
        )
        if self.max_pool:
            m = nn.max_pool(
                m,
                (self.pooling_kernel_size,),
                strides=(self.pooling_stride,),
                padding=[(self.pooling_padding,) * 2],
            )
        return m[..., 0] > 0

    def seq_len(self, seq_len: int) -> int:
        n = (seq_len + 2 * self.padding - self.kernel_size) // self.stride + 1
        if self.max_pool:
            n = (
                n + 2 * self.pooling_padding - self.pooling_kernel_size
            ) // self.pooling_stride + 1
        return n


class MaskedAttention(nn.Module):
    """MHSA with pairwise key/query masking (reference
    ``utils/transformers.py:39-71``).

    When ``ring_mesh`` is set, attention runs sequence-parallel: the N
    axis is sharded over ``ring_mesh[ring_axis]`` and either K/V blocks
    rotate via ``lax.ppermute`` (``seq_parallel="ring"``,
    ``ops/ring_attention.py``) or two all-to-alls bracket a head-parallel
    local attention (``seq_parallel="ulysses"``, ``ops/ulysses.py``).
    Exact same math as the dense path with two deviations: (a) attention
    dropout is skipped (blockwise-rotating dropout masks are not worth the
    complexity for a long-context path that is eval/fine-tune focused), and
    (b) only the key side of the pairwise mask is applied — rows for invalid
    queries are garbage but every consumer (seq-pool / class token) masks
    them out downstream, so logits are identical.
    """

    dim: int
    num_heads: int
    attention_dropout: float = 0.1
    projection_dropout: float = 0.1
    ring_mesh: Optional[object] = None  # jax.sharding.Mesh
    ring_axis: str = "seq"
    seq_parallel: str = "ring"  # "ring" | "ulysses"

    @nn.compact
    def __call__(self, x, mask=None, deterministic: bool = True):
        b, n, c = x.shape
        head_dim = self.dim // self.num_heads
        qkv = nn.Dense(self.dim * 3, use_bias=False, kernel_init=_trunc02)(x)
        qkv = qkv.reshape(b, n, 3, self.num_heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if self.ring_mesh is not None:
            if self.seq_parallel == "ulysses":
                from blades_tpu.ops.ulysses import ulysses_attention as sp_attn
            elif self.seq_parallel == "ring":
                from blades_tpu.ops.ring_attention import ring_attention as sp_attn
            else:  # a typo must not silently run the wrong schedule
                raise ValueError(
                    f"seq_parallel must be 'ring' or 'ulysses', got "
                    f"{self.seq_parallel!r}"
                )

            out = sp_attn(
                q, k, v, self.ring_mesh, self.ring_axis, kv_mask=mask
            ).reshape(b, n, c)
        else:
            attn = jnp.einsum("bnhd,bmhd->bhnm", q, k) * (head_dim**-0.5)
            if mask is not None:
                pair = mask[:, :, None] & mask[:, None, :]  # [B, N, N]
                attn = jnp.where(pair[:, None], attn, NEG_INF)
            attn = jax.nn.softmax(attn, axis=-1)
            attn = nn.Dropout(self.attention_dropout)(
                attn, deterministic=deterministic
            )
            out = jnp.einsum("bhnm,bmhd->bnhd", attn, v).reshape(b, n, c)
        out = nn.Dense(self.dim, kernel_init=_trunc02)(out)
        return nn.Dropout(self.projection_dropout)(out, deterministic=deterministic)


class MaskedTransformerEncoderLayer(nn.Module):
    """Pre-norm block, residual wiring as the image variant
    (``utils/transformers.py:74-103``) plus the mask pass-through."""

    d_model: int
    nhead: int
    dim_feedforward: int
    dropout: float = 0.1
    attention_dropout: float = 0.1
    drop_path_rate: float = 0.1
    ring_mesh: Optional[object] = None
    ring_axis: str = "seq"
    seq_parallel: str = "ring"

    @nn.compact
    def __call__(self, x, mask=None, deterministic: bool = True):
        h = MaskedAttention(
            self.d_model, self.nhead, self.attention_dropout, self.dropout,
            ring_mesh=self.ring_mesh, ring_axis=self.ring_axis,
            seq_parallel=self.seq_parallel,
        )(nn.LayerNorm()(x), mask=mask, deterministic=deterministic)
        x = x + DropPath(self.drop_path_rate)(h, deterministic=deterministic)
        x = nn.LayerNorm()(x)
        h = nn.Dense(self.dim_feedforward, kernel_init=_trunc02)(x)
        h = nn.Dropout(self.dropout)(nn.gelu(h), deterministic=deterministic)
        h = nn.Dense(self.d_model, kernel_init=_trunc02)(h)
        h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        return x + DropPath(self.drop_path_rate)(h, deterministic=deterministic)


class TextCCT(nn.Module):
    """Unified text classifier covering the reference's four text families:

    - ``text_cct_*``: conv tokenizer (ReLU + maxpool) + seq-pool
    - ``text_cvt_*``: patchify tokenizer (no act/pool) + seq-pool
    - ``text_vit_*``: patchify tokenizer + class token
    - ``text_transformer_*``: no tokenizer (word embeddings straight into
      the encoder) + class token
    """

    num_classes: int = 2
    seq_len: Optional[int] = None  # if set, input length is validated
    vocab_size: int = 100_000
    word_embedding_dim: int = 300
    embedding_dim: int = 128
    num_layers: int = 2
    num_heads: int = 2
    mlp_ratio: float = 1.0
    kernel_size: int = 4
    stride: Optional[int] = None
    padding: Optional[int] = None
    use_tokenizer: bool = True
    max_pool: bool = True
    use_act: bool = True
    seq_pool: bool = True
    dropout: float = 0.0
    attention_dropout: float = 0.1
    stochastic_depth: float = 0.1
    positional_embedding: str = "sine"  # sine | learnable | none
    # sequence parallelism: shard the token axis over ring_mesh[ring_axis]
    # and run ring ("ring", ops/ring_attention.py) or all-to-all
    # head-parallel ("ulysses", ops/ulysses.py) attention per encoder layer
    ring_mesh: Optional[object] = None
    ring_axis: str = "seq"
    seq_parallel: str = "ring"

    @nn.compact
    def __call__(self, tokens, mask=None, train: bool = False):
        det = not train
        if self.seq_len is not None and tokens.shape[1] != self.seq_len:
            raise ValueError(
                f"input length {tokens.shape[1]} != configured seq_len "
                f"{self.seq_len}"
            )
        x, mask = Embedder(self.vocab_size, self.word_embedding_dim)(tokens, mask)
        if self.use_tokenizer:
            stride = (
                self.stride
                if self.stride is not None
                else max(1, (self.kernel_size // 2) - 1)
            )
            padding = (
                self.padding
                if self.padding is not None
                else max(1, self.kernel_size // 2)
            )
            x, mask = TextTokenizer(
                kernel_size=self.kernel_size,
                stride=stride,
                padding=padding,
                n_output_channels=self.embedding_dim,
                max_pool=self.max_pool,
                use_act=self.use_act,
            )(x, mask)

        if not self.seq_pool:
            cls = self.param(
                "class_emb", nn.initializers.zeros, (1, 1, x.shape[-1])
            )
            x = jnp.concatenate([jnp.tile(cls, (x.shape[0], 1, 1)), x], axis=1)
            if mask is not None:
                mask = jnp.concatenate(
                    [jnp.ones((mask.shape[0], 1), bool), mask], axis=1
                )
        n = x.shape[1]

        if self.positional_embedding == "learnable":
            pe = self.param(
                "positional_emb",
                nn.initializers.truncated_normal(stddev=0.2),
                (1, n, x.shape[-1]),
            )
            x = x + pe
        elif self.positional_embedding == "sine":
            x = x + sinusoidal_embedding(n, x.shape[-1])

        x = nn.Dropout(self.dropout)(x, deterministic=det)
        dpr = [
            self.stochastic_depth * i / max(self.num_layers - 1, 1)
            for i in range(self.num_layers)
        ]
        for i in range(self.num_layers):
            x = MaskedTransformerEncoderLayer(
                d_model=x.shape[-1],
                nhead=self.num_heads,
                dim_feedforward=int(x.shape[-1] * self.mlp_ratio),
                dropout=self.dropout,
                attention_dropout=self.attention_dropout,
                drop_path_rate=dpr[i],
                ring_mesh=self.ring_mesh,
                ring_axis=self.ring_axis,
                seq_parallel=self.seq_parallel,
            )(x, mask=mask, deterministic=det)
        x = nn.LayerNorm()(x)

        if self.seq_pool:
            w = nn.Dense(1, kernel_init=_trunc02)(x)  # [B, N, 1]
            if mask is not None:
                w = jnp.where(mask[..., None], w, NEG_INF)
            w = jax.nn.softmax(w, axis=1)
            x = jnp.einsum("bnl,bnc->bc", w, x)
        else:
            x = x[:, 0]
        return nn.Dense(self.num_classes, kernel_init=_trunc02)(x)


# -- factories (reference text/{cct,cvt,vit,transformer}.py grids) ------------

_GRID = {2: (2, 2, 1.0, 128), 4: (4, 2, 1.0, 128), 6: (6, 4, 2.0, 256)}


def _text(kind: str, depth: int, num_classes: int = 2, **kw) -> TextCCT:
    layers, heads, ratio, dim = _GRID[depth]
    cfg = dict(
        num_classes=num_classes,
        num_layers=layers,
        num_heads=heads,
        mlp_ratio=ratio,
        embedding_dim=dim,
    )
    if kind == "cct":
        cfg.update(kernel_size=4, max_pool=True, use_act=True, seq_pool=True)
    elif kind == "cvt":
        # patchify: kernel=stride=patch_size, no pad/act/pool (text/cvt.py:27-33)
        cfg.update(
            kernel_size=4, stride=4, padding=0,
            max_pool=False, use_act=False, seq_pool=True,
        )
    elif kind == "vit":
        cfg.update(
            kernel_size=4, stride=4, padding=0,
            max_pool=False, use_act=False, seq_pool=False,
        )
    elif kind == "transformer":
        # no tokenizer: encoder width = word embedding dim (text/transformer.py:22-28)
        cfg.update(use_tokenizer=False, seq_pool=False)
        cfg.pop("embedding_dim")
    cfg.update(kw)
    return TextCCT(**cfg)


def text_cct_2(num_classes: int = 2, **kw) -> TextCCT:
    return _text("cct", 2, num_classes, **kw)


def text_cct_4(num_classes: int = 2, **kw) -> TextCCT:
    return _text("cct", 4, num_classes, **kw)


def text_cct_6(num_classes: int = 2, **kw) -> TextCCT:
    return _text("cct", 6, num_classes, **kw)


def text_cvt_2(num_classes: int = 2, **kw) -> TextCCT:
    return _text("cvt", 2, num_classes, **kw)


def text_cvt_4(num_classes: int = 2, **kw) -> TextCCT:
    return _text("cvt", 4, num_classes, **kw)


def text_cvt_6(num_classes: int = 2, **kw) -> TextCCT:
    return _text("cvt", 6, num_classes, **kw)


def text_vit_2(num_classes: int = 2, **kw) -> TextCCT:
    return _text("vit", 2, num_classes, **kw)


def text_vit_4(num_classes: int = 2, **kw) -> TextCCT:
    return _text("vit", 4, num_classes, **kw)


def text_vit_6(num_classes: int = 2, **kw) -> TextCCT:
    return _text("vit", 6, num_classes, **kw)


def long_text_transformer(
    num_classes: int = 2,
    mesh=None,
    axis_name: str = "seq",
    depth: int = 2,
    **kw,
) -> TextCCT:
    """Long-sequence text classifier: the token axis is sharded over
    ``mesh[axis_name]``.

    Beyond-parity model family (the reference caps attention at <=256 tokens
    on one device, ``cctnets/utils/transformers.py:8-37``). Tokenizer-free
    so the runtime sequence length N is the input length and must be
    divisible by ``mesh[axis_name]``; seq-pool head (no class token — a
    prepended token would break the N-divisibility sharding requires).
    Pass ``seq_parallel="ulysses"`` for all-to-all head-parallel attention
    (``ops/ulysses.py``, needs heads divisible by the axis size) instead of
    the default K/V ring (``ops/ring_attention.py``).
    """
    layers, heads, ratio, _ = _GRID[depth]
    cfg = dict(
        num_classes=num_classes,
        num_layers=layers,
        num_heads=heads,
        mlp_ratio=ratio,
        use_tokenizer=False,
        seq_pool=True,
        ring_mesh=mesh,
        ring_axis=axis_name,
    )
    cfg.update(kw)
    return TextCCT(**cfg)


def text_transformer_2(num_classes: int = 2, **kw) -> TextCCT:
    return _text("transformer", 2, num_classes, **kw)


def text_transformer_4(num_classes: int = 2, **kw) -> TextCCT:
    return _text("transformer", 4, num_classes, **kw)


def text_transformer_6(num_classes: int = 2, **kw) -> TextCCT:
    return _text("transformer", 6, num_classes, **kw)
