"""MNIST MLP, reference-architecture parity.

Reference: ``MLP`` (``src/blades/models/mnist/dnn.py:5-19``):
flatten -> 784->64 relu -> 64->128 relu -> 128->10 log_softmax.
"""

from __future__ import annotations

import jax.numpy as jnp
import flax.linen as nn

from blades_tpu.models.common import build_fns


class MLP(nn.Module):
    num_classes: int = 10
    hidden: tuple = (64, 128)

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        x = x.reshape(x.shape[0], -1)
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
        x = nn.Dense(self.num_classes)(x)
        return nn.log_softmax(x)


def create_mnist_model():
    """Reference ``create_model()`` parity (``dnn.py:22-23``): returns the
    model spec with crossentropy loss wired."""
    return build_fns(MLP(), sample_shape=(28, 28, 1))
