"""Run supervisor: heartbeat watchdog + hang-killing process groups +
degrade-and-resume relaunch policies.

Every documented hang mode of this environment — TPU backend init blocking
forever on a dead tunnel, the 8-device virtual CPU mesh deadlocking in
XLA's collective rendezvous, a timed-out capture orphaning grandchildren
that squat on the single-chip lease — shares one property: the wedged
process never exits and never raises, so in-process recovery (``try``/
``except``, ``utils/retry.py``) cannot see it. The supervisor turns each of
them into a bounded-time, self-recovering event:

1. **Own process group.** The workload launches with
   ``start_new_session=True``, so it and every grandchild it spawns share a
   process group the supervisor can kill *atomically* — no orphan can
   survive holding a pipe or the chip lease.
2. **Heartbeat watchdog.** The workload touches a heartbeat file once per
   round (``supervision.heartbeat``, piggybacked on the telemetry
   flush-once-per-round discipline). Staleness beyond
   ``heartbeat_timeout_s`` — or ``startup_grace_s`` with no first beat, or
   a ``deadline_s`` wall clock — triggers the kill.
3. **Escalated group kill.** SIGTERM (a supervised ``Simulator.run``
   converts it to an exception, so the crash autosave fires), then SIGCONT
   (a SIGSTOP'd-but-healthy child may still honor the TERM), then after
   ``term_grace_s`` SIGKILL — all via ``os.killpg``. The group is verified
   dead by a ``/proc`` scan before the next attempt launches.
4. **Degrade and resume.** Each relaunch runs under ``BLADES_RESUME=1``
   (``Simulator.run`` resumes bit-exactly from the crash autosave /
   latest checkpoint, PR 2) and may apply a :class:`DegradePolicy` — e.g.
   collapse the device mesh to a single device (safe: sharded-vs-unsharded
   equality is a tested invariant, ``tests/test_engine.py``) or disable
   the Pallas kernel path. The retry budget is bounded with the same
   exponential backoff as ``utils/retry.py`` (shared
   :func:`~blades_tpu.utils.retry.backoff_delay`).

Every attempt/kill/degrade/resume event lands in the telemetry trace as a
``supervisor`` record (schema in ``docs/observability.md``) so a
post-mortem reads the full recovery trail next to the run's own spans.

Stdlib-only: importable before jax and from host harnesses
(``scripts/tpu_capture.py`` reuses :func:`kill_process_group`).

Reference counterpart: none — the reference delegates process lifetime to
Ray and retries nothing (``src/blades/simulator.py:189-211``). The design
follows the per-round watchdog / pace-steering architecture of production
FL servers (Bonawitz et al., 2019).
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

from blades_tpu.supervision import heartbeat as hb
from blades_tpu.telemetry import Recorder
from blades_tpu.telemetry import alerts as _alerts
from blades_tpu.telemetry import context as _context
from blades_tpu.telemetry import ledger as _ledger


# -- degradation policies -----------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DegradePolicy:
    """A named set of env overrides a relaunch applies to shed risk.

    Policies are *cumulative*: attempt ``n`` runs under the union of the
    first ``n - 1`` configured policies (later dicts win on key conflict),
    so the workload degrades monotonically instead of oscillating.
    """

    name: str
    env: Dict[str, str]
    note: str = ""


#: Built-in policies, orderable into a degradation ladder. ``single_device``
#: collapses the virtual CPU mesh to one device — it sets the
#: ``xla_force_host_platform_device_count`` flag that
#: ``utils/platform.force_virtual_cpu`` refuses to duplicate, so workloads
#: using the standard recipe inherit the degraded count. ``no_pallas``
#: falls back from the Mosaic/Pallas kernels to plain-XLA extraction
#: (``ops/pallas_trimmed.py``). ``cpu_only`` abandons the accelerator
#: attachment entirely (the tunnel-dead endgame).
POLICIES: Dict[str, DegradePolicy] = {
    p.name: p
    for p in (
        DegradePolicy(
            "single_device",
            {
                "JAX_PLATFORMS": "cpu",
                # supervision is stdlib-only (must run when jax can't even
                # import), so it cannot route through utils/platform's
                # probed recipe; this one flag predates the probe era and
                # is registered on every jaxlib build we've met.
                # blades: allow[XLA001]
                "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            },
            "collapse the device mesh to 1 virtual CPU device "
            "(sharded == unsharded is a tested invariant)",
        ),
        DegradePolicy(
            "no_pallas",
            {"BLADES_TPU_NO_PALLAS": "1"},
            "disable Mosaic/Pallas kernels (plain-XLA extraction path)",
        ),
        DegradePolicy(
            "cpu_only",
            {
                "JAX_PLATFORMS": "cpu",
                "BENCH_FORCE_CPU": "1",
                # same stdlib-only rationale as single_device above
                # blades: allow[XLA001]
                "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            },
            "abandon the accelerator attachment for this attempt",
        ),
    )
}


def resolve_policy(p: Union[str, DegradePolicy, Dict[str, str]]) -> DegradePolicy:
    """A policy spec (registry name, policy object, or raw env dict)."""
    if isinstance(p, DegradePolicy):
        return p
    if isinstance(p, str):
        try:
            return POLICIES[p]
        except KeyError:
            raise ValueError(
                f"unknown degrade policy {p!r} (built-ins: {sorted(POLICIES)})"
            ) from None
    return DegradePolicy("custom", {k: str(v) for k, v in dict(p).items()})


# -- process-group primitives -------------------------------------------------


def list_group(pgid: int) -> List[int]:
    """Live pids in process group ``pgid`` (``/proc`` scan; Linux).

    The supervisor's post-kill verification and the orphan-scan tests both
    use this: ``os.killpg(pgid, 0)`` alone cannot *enumerate* survivors.
    Zombies (reaped-pending) are excluded — they hold no resources.
    """
    pids = []
    try:
        entries = os.listdir("/proc")
    except OSError:
        return pids
    for entry in entries:
        if not entry.isdigit():
            continue
        try:
            with open(f"/proc/{entry}/stat", "rb") as fh:
                stat = fh.read().decode("ascii", "replace")
            # field 2 is "(comm)" which may contain spaces/parens; parse
            # from the LAST ')' — state is field 3, pgrp field 5
            rest = stat[stat.rfind(")") + 2:].split()
            state, pgrp = rest[0], int(rest[2])
        except (OSError, ValueError, IndexError):
            continue
        if pgrp == pgid and state != "Z":
            pids.append(int(entry))
    return pids


def kill_process_group(
    proc: subprocess.Popen,
    term_grace_s: float = 10.0,
    kill_wait_s: float = 10.0,
) -> Dict[str, object]:
    """SIGTERM -> SIGCONT -> (grace) -> SIGKILL the whole group of ``proc``.

    SIGTERM first so a supervised ``Simulator.run`` can fire its crash
    autosave; SIGCONT immediately after so a SIGSTOP'd child still receives
    the pending TERM; SIGKILL after ``term_grace_s`` for anything that
    ignored both (a hung backend init does). Returns a forensics dict:
    ``{"pgid", "escalated" (bool: SIGKILL was needed), "survivors"
    (pids still alive after the escalation window — [] on success)}``.

    Never signals the supervisor's own group (a ``preexec``-failed launch
    can leave ``proc`` sharing our pgid).
    """
    try:
        pgid = os.getpgid(proc.pid)
    except OSError:
        pgid = proc.pid
    info: Dict[str, object] = {"pgid": pgid, "escalated": False, "survivors": []}
    if pgid == os.getpgid(0):
        # same group as us: fall back to single-process kill, never killpg
        proc.kill()
        proc.wait()
        return info

    def _signal_group(sig: int) -> None:
        try:
            os.killpg(pgid, sig)
        except ProcessLookupError:
            pass
        except PermissionError:
            pass

    _signal_group(signal.SIGTERM)
    _signal_group(signal.SIGCONT)
    deadline = time.monotonic() + term_grace_s
    while time.monotonic() < deadline:
        if proc.poll() is not None and not list_group(pgid):
            return info
        time.sleep(0.05)
    info["escalated"] = True
    _signal_group(signal.SIGKILL)
    try:
        proc.wait(timeout=kill_wait_s)
    except subprocess.TimeoutExpired:
        pass
    # grandchildren get reparented to init and reaped asynchronously; give
    # the scan a bounded window before reporting survivors
    deadline = time.monotonic() + kill_wait_s
    survivors = list_group(pgid)
    while survivors and time.monotonic() < deadline:
        time.sleep(0.05)
        survivors = list_group(pgid)
    info["survivors"] = survivors
    return info


# -- the supervisor -----------------------------------------------------------


@dataclasses.dataclass
class AttemptRecord:
    """One launch attempt's outcome (``Supervisor.run`` returns the list)."""

    index: int
    returncode: Optional[int]  # None when the watchdog killed the attempt
    # "exit" | "deadline" | "heartbeat_stale" | "startup_stale" | "alert"
    reason: str
    wall_s: float
    degrade: Tuple[str, ...] = ()
    resumed: bool = False
    survivors: Tuple[int, ...] = ()


@dataclasses.dataclass
class SupervisedResult:
    ok: bool
    returncode: Optional[int]
    attempts: List[AttemptRecord]


class Supervisor:
    """Launch ``cmd`` in its own process group and keep it making progress.

    Parameters
    ----------
    cmd : the workload argv (any Simulator run, ``bench.py``, a dryrun
        gate — anything that either finishes or beats the heartbeat).
    deadline_s : hard wall-clock limit per attempt (None: no limit).
    heartbeat_timeout_s : max staleness between beats once the workload has
        beaten at least once (None: wall-clock supervision only).
    startup_grace_s : time allowed before the FIRST beat — cold XLA
        compiles legitimately take minutes on this box, so the pre-beat
        window needs its own (generous) threshold.
    attempts : total launch budget (first launch + relaunches).
    base_delay_s / max_delay_s : the ``utils/retry.py`` bounded-backoff
        shape applied between attempts.
    degrade : sequence of policy specs (registry names, policy objects, or
        env dicts); relaunch ``n`` applies the first ``n - 1`` cumulatively.
    resume : export ``BLADES_RESUME=1`` on relaunches so ``Simulator.run``
        continues from the autosave instead of restarting.
    kill_on_alert : export ``BLADES_ALERT_FILE`` so a CRITICAL anomaly
        alert (diverging/non-finite loss — ``telemetry/alerts.py``)
        recycles the attempt through the same kill -> degrade -> relaunch
        ladder immediately, instead of waiting for heartbeat staleness.
    telemetry_path : JSONL file the ``supervisor`` records are appended to
        (typically the run's own ``telemetry.jsonl``); None disables.
    heartbeat_file : path the workload beats (exported via
        ``BLADES_HEARTBEAT_FILE``); default ``<telemetry dir>/heartbeat``
        or a pid-scoped file under ``/tmp``.
    stdout / stderr : passed to ``Popen`` — default ``None`` INHERITS the
        supervisor's streams, preserving workload contracts like
        ``bench.py``'s one-JSON-line stdout.
    """

    def __init__(
        self,
        cmd: Sequence[str],
        *,
        deadline_s: Optional[float] = None,
        heartbeat_timeout_s: Optional[float] = None,
        startup_grace_s: float = 900.0,
        attempts: int = 3,
        base_delay_s: float = 1.0,
        max_delay_s: float = 60.0,
        degrade: Sequence[Union[str, DegradePolicy, Dict[str, str]]] = (),
        resume: bool = True,
        kill_on_alert: bool = False,
        telemetry_path: Optional[str] = None,
        heartbeat_file: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        cwd: Optional[str] = None,
        poll_s: float = 0.2,
        term_grace_s: float = 10.0,
        stdout=None,
        stderr=None,
        sleep=time.sleep,
    ):
        if attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {attempts}")
        self.cmd = [str(c) for c in cmd]
        self.deadline_s = deadline_s
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.startup_grace_s = startup_grace_s
        self.attempts = attempts
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.degrade = [resolve_policy(p) for p in degrade]
        self.resume = resume
        self.poll_s = poll_s
        self.term_grace_s = term_grace_s
        self.env = dict(env or {})
        self.cwd = cwd
        self.stdout = stdout
        self.stderr = stderr
        self._sleep = sleep
        if heartbeat_file is None:
            base = (
                os.path.dirname(telemetry_path)
                if telemetry_path
                else f"/tmp/blades_supervisor_{os.getpid()}"
            )
            heartbeat_file = os.path.join(base or ".", "heartbeat")
        self.heartbeat_file = heartbeat_file
        self.kill_on_alert = kill_on_alert
        # the file a CRITICAL alert touches (exported to the child only
        # under kill_on_alert); lives next to the heartbeat file
        self.alert_file = os.path.join(
            os.path.dirname(self.heartbeat_file) or ".", "alert"
        )
        # mint the run identity ONCE: every attempt of this supervised run
        # shares the id; _attempt_env re-exports it with the attempt number
        # so the child traces and the ledger stitch across relaunches.
        # fresh=True: an id a PREVIOUS run in this process minted must not
        # leak into this supervised run (two supervisors in one process are
        # two runs); a genuinely inherited id (a parent harness) is kept
        self.ctx = _context.activate(fresh=True)
        self._rec = Recorder(
            path=telemetry_path,
            enabled=telemetry_path is not None,
            meta={"run": "supervisor", "cmd": self.cmd},
        )

    # -- events ---------------------------------------------------------------

    def _event(self, event: str, **fields) -> None:
        self._rec.event("supervisor", event=event, **fields)
        self._rec.flush()

    # -- one attempt ----------------------------------------------------------

    def _attempt_env(self, attempt: int) -> Tuple[Dict[str, str], List[str]]:
        env = dict(os.environ)
        env.update(self.env)
        env[hb.SUPERVISED_ENV] = "1"
        env[hb.HEARTBEAT_ENV] = self.heartbeat_file
        # one run id across every attempt, attempt number incremented per
        # relaunch (telemetry/context.py): the child recorder stamps both
        # onto every record, so the stitched trace reads attempts 1..n
        env[_context.RUN_ID_ENV] = self.ctx.run_id
        env[_context.ATTEMPT_ENV] = str(attempt)
        if self.kill_on_alert:
            env[_alerts.ALERT_FILE_ENV] = self.alert_file
        if self.heartbeat_timeout_s is not None:
            # let the workload measure its own margin against the kill
            # threshold (heartbeat.beat's heartbeat_margin records)
            env[hb.TIMEOUT_ENV] = str(self.heartbeat_timeout_s)
        applied: List[str] = []
        for policy in self.degrade[: attempt - 1]:
            env.update(policy.env)
            applied.append(policy.name)
        if attempt > 1 and self.resume:
            env[hb.RESUME_ENV] = "1"
        return env, applied

    def _watch(self, proc: subprocess.Popen) -> Tuple[str, Optional[int]]:
        """Poll until exit or a watchdog trip; returns (reason, returncode)."""
        t0 = time.monotonic()
        while True:
            rc = proc.poll()
            if rc is not None:
                return "exit", rc
            now = time.monotonic()
            if self.deadline_s is not None and now - t0 > self.deadline_s:
                return "deadline", None
            if self.kill_on_alert and os.path.exists(self.alert_file):
                # a CRITICAL anomaly alert (telemetry/alerts.py): recycle
                # now — the run is diverging, staleness would waste a
                # whole heartbeat window first
                return "alert", None
            if self.heartbeat_timeout_s is not None:
                age = hb.age_s(self.heartbeat_file)
                if age is None:
                    if now - t0 > self.startup_grace_s:
                        return "startup_stale", None
                elif age > self.heartbeat_timeout_s:
                    return "heartbeat_stale", None
            self._sleep(self.poll_s)

    # -- run ------------------------------------------------------------------

    def run(self) -> SupervisedResult:
        # late import: utils.retry's package chain pulls jax; the
        # supervisor itself must stay cheap/stdlib to import (workload
        # subprocesses and host harnesses import this module pre-jax)
        from blades_tpu.utils.retry import backoff_delay

        records: List[AttemptRecord] = []
        last_proc_rc: Optional[int] = None
        for attempt in range(1, self.attempts + 1):
            env, applied = self._attempt_env(attempt)
            resumed = attempt > 1 and self.resume
            # a beat left over from the previous attempt must not read as
            # fresh liveness for this one — nor may a previous attempt's
            # critical alert instantly kill the relaunch
            for stale in (self.heartbeat_file, self.alert_file):
                try:
                    os.unlink(stale)
                except OSError:
                    pass
            if applied:
                self._event(
                    "degrade", attempt=attempt, policies=applied,
                    env={k: v for p in self.degrade[: attempt - 1]
                         for k, v in p.env.items()},
                )
            self._event(
                "launch", attempt=attempt, cmd=self.cmd,
                degrade=applied, resume=resumed,
                heartbeat_file=self.heartbeat_file,
            )
            t0 = time.monotonic()
            try:
                proc = subprocess.Popen(
                    self.cmd, env=env, cwd=self.cwd, start_new_session=True,
                    stdout=self.stdout, stderr=self.stderr,
                )
            except OSError as e:
                # unlaunchable argv (missing binary, bad cwd, EPERM): not a
                # transient failure a retry or degrade policy can heal —
                # terminate the trail cleanly instead of crashing with the
                # recorder open and no give_up record
                self._event(
                    "launch_failed", attempt=attempt,
                    error=f"{type(e).__name__}: {e}"[:300],
                )
                records.append(AttemptRecord(
                    index=attempt, returncode=None, reason="launch_failed",
                    wall_s=time.monotonic() - t0, degrade=tuple(applied),
                    resumed=resumed,
                ))
                self._event("give_up", attempts=attempt)
                self._rec.close()
                return SupervisedResult(False, 127, records)
            reason, rc = self._watch(proc)
            if reason != "exit":
                # close the trip/exit race: a child that finished in the
                # poll gap (e.g. the watchdog tripped on the final round's
                # long eval compile) must be recorded as its real exit, not
                # killed-and-relaunched — a completed run already deleted
                # its autosave, so a bogus relaunch would redo everything
                rc = proc.poll()
                if rc is not None:
                    reason = "exit"
            survivors: Tuple[int, ...] = ()
            if reason != "exit":
                last = hb.read(self.heartbeat_file) or {}
                alert = None
                if reason == "alert":
                    try:
                        with open(self.alert_file) as fh:
                            alert = json.loads(fh.read())
                    except (OSError, ValueError):
                        pass
                info = kill_process_group(proc, term_grace_s=self.term_grace_s)
                survivors = tuple(info["survivors"])  # type: ignore[arg-type]
                self._event(
                    "kill", attempt=attempt, reason=reason,
                    pgid=info["pgid"], escalated=info["escalated"],
                    survivors=list(survivors),
                    heartbeat_age_s=hb.age_s(self.heartbeat_file),
                    last_round=last.get("round"),
                    **({"alert": alert} if alert else {}),
                )
                # the reaped child never got to write its own ledger exit:
                # record the kill under the SHARED run id + this attempt
                _ledger.record_event(
                    "supervised", "killed",
                    run_id=self.ctx.run_id, attempt=attempt,
                    reason=reason,
                    **({"metrics": {"last_round": last["round"]}}
                       if isinstance(last.get("round"), int) else {}),
                )
                rc = proc.returncode
            last_proc_rc = rc
            wall = time.monotonic() - t0
            rec = AttemptRecord(
                index=attempt,
                returncode=rc if reason == "exit" else None,
                reason=reason, wall_s=wall, degrade=tuple(applied),
                resumed=resumed, survivors=survivors,
            )
            records.append(rec)
            self._event(
                "exit", attempt=attempt, reason=reason, returncode=rc,
                wall_s=round(wall, 3),
            )
            if reason == "exit" and rc == 0:
                self._event("complete", attempts=attempt)
                self._rec.close()
                return SupervisedResult(True, 0, records)
            if attempt < self.attempts:
                delay = backoff_delay(
                    attempt, self.base_delay_s, self.max_delay_s
                )
                self._event(
                    "retry", attempt=attempt, delay_s=delay,
                    resume=self.resume,
                )
                self._sleep(delay)
        self._event("give_up", attempts=self.attempts)
        self._rec.close()
        # the raw process returncode of the final attempt (negative signal
        # number when the watchdog killed it — -15 if the child honored the
        # graceful SIGTERM, -9 only when SIGKILL escalation was needed), so
        # callers scripting on the CLI exit code see the real signal
        return SupervisedResult(False, last_proc_rc, records)


def supervise(cmd: Sequence[str], **kwargs) -> SupervisedResult:
    """One-call form: ``supervise(["python", "bench.py"], deadline_s=3600)``."""
    return Supervisor(cmd, **kwargs).run()


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: ``python -m blades_tpu.supervision [opts] -- cmd args...``.

    The workload's stdout/stderr are inherited (contracts like bench.py's
    one-JSON-line stdout survive); supervisor diagnostics go to stderr.
    Exit code: the workload's final rc, or ``128 + signal`` when the last
    attempt was watchdog-killed.
    """
    import argparse

    parser = argparse.ArgumentParser(
        prog="blades_tpu.supervision",
        description="heartbeat-watchdog run supervisor (docs/robustness.md)",
    )
    parser.add_argument("--deadline", type=float, default=None,
                        help="per-attempt wall-clock limit (s)")
    parser.add_argument("--heartbeat-timeout", type=float, default=None,
                        help="max staleness between round beats (s)")
    parser.add_argument("--startup-grace", type=float, default=900.0,
                        help="time allowed before the first beat (s)")
    parser.add_argument("--attempts", type=int, default=3)
    parser.add_argument("--base-delay", type=float, default=1.0)
    parser.add_argument("--max-delay", type=float, default=60.0)
    parser.add_argument("--term-grace", type=float, default=10.0)
    parser.add_argument("--poll", type=float, default=0.2)
    parser.add_argument("--degrade", action="append", default=[],
                        metavar="POLICY",
                        help=f"degradation ladder entry (built-ins: "
                             f"{sorted(POLICIES)}); repeatable, applied "
                             "cumulatively from the first relaunch on")
    parser.add_argument("--no-resume", action="store_true",
                        help="do not export BLADES_RESUME=1 on relaunches")
    parser.add_argument("--kill-on-alert", action="store_true",
                        help="recycle the attempt (through the degrade "
                             "ladder) the moment the workload emits a "
                             "CRITICAL anomaly alert (telemetry/alerts.py) "
                             "instead of waiting for heartbeat staleness")
    parser.add_argument("--heartbeat-file", default=None)
    parser.add_argument("--telemetry", default=None,
                        help="JSONL file for supervisor records (e.g. the "
                             "run's telemetry.jsonl)")
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="-- workload argv")
    args = parser.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no workload command given (use: ... -- python bench.py)")
    for name in args.degrade:
        if name not in POLICIES:
            parser.error(
                f"unknown --degrade policy {name!r} "
                f"(built-ins: {sorted(POLICIES)})"
            )
    if args.deadline is None and args.heartbeat_timeout is None:
        # without either, _watch never trips: the supervisor degrades to a
        # plain runner and a hung child waits forever — say so up front
        print(
            "[supervisor] warning: neither --deadline nor "
            "--heartbeat-timeout is set; hangs will NOT be detected "
            "(exit-code supervision and retries only)",
            file=sys.stderr,
        )

    result = supervise(
        cmd,
        deadline_s=args.deadline,
        heartbeat_timeout_s=args.heartbeat_timeout,
        startup_grace_s=args.startup_grace,
        attempts=args.attempts,
        base_delay_s=args.base_delay,
        max_delay_s=args.max_delay,
        term_grace_s=args.term_grace,
        poll_s=args.poll,
        degrade=args.degrade,
        resume=not args.no_resume,
        kill_on_alert=args.kill_on_alert,
        heartbeat_file=args.heartbeat_file,
        telemetry_path=args.telemetry,
    )
    for a in result.attempts:
        print(
            f"[supervisor] attempt {a.index}: {a.reason}"
            f" rc={a.returncode} wall={a.wall_s:.1f}s"
            + (f" degrade={list(a.degrade)}" if a.degrade else "")
            + (" resumed" if a.resumed else ""),
            file=sys.stderr,
        )
    if result.ok:
        return 0
    rc = result.returncode
    if rc is None:
        return 128 + signal.SIGKILL
    if rc == 0:
        # final attempt was watchdog-killed but the child trapped SIGTERM
        # and exited 0: the supervision still GAVE UP — never report
        # success for a run the trail records as give_up
        return 1
    return rc if rc > 0 else 128 - rc
