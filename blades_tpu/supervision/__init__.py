"""Run supervision: heartbeat watchdog, hang-killing process groups, and
degrade-and-resume relaunch policies.

Two stdlib-only modules (importable before jax, from any subprocess):

- :mod:`blades_tpu.supervision.heartbeat` — the per-round liveness file a
  supervised workload touches at every telemetry flush (no extra I/O
  cadence) and the supervisor reads for staleness;
- :mod:`blades_tpu.supervision.supervisor` — :class:`Supervisor` /
  :func:`supervise`: launch any workload (Simulator runs, ``bench.py``,
  the dryrun gates) in its own process group, kill the *whole group* on
  heartbeat staleness or deadline (SIGTERM -> SIGCONT -> SIGKILL via
  ``killpg``), and relaunch with ``BLADES_RESUME=1`` under a bounded
  backoff budget, optionally applying :class:`DegradePolicy` env ladders
  (mesh -> 1 device, Pallas -> plain XLA, accelerator -> CPU).

CLI: ``python -m blades_tpu.supervision [opts] -- python bench.py``.

Usage, guarantees, and the chaos suite that exercises them:
``docs/robustness.md``. Telemetry record schema (``supervisor`` /
``heartbeat``): ``docs/observability.md``.

Reference counterpart: none — the reference delegates process lifetime to
an assumed-healthy Ray cluster (``src/blades/simulator.py:189-211``).
"""

# heartbeat is imported eagerly (pure stdlib, and the hot-path import for
# supervised workloads); the supervisor half resolves lazily so that a
# workload importing only `beat` pays zero extra import latency — the
# first beat must land inside the supervisor's startup grace window even
# on a host where importing the full stack takes seconds.
from blades_tpu.supervision.heartbeat import (  # noqa: F401
    HEARTBEAT_ENV,
    RESUME_ENV,
    SUPERVISED_ENV,
    beat,
    heartbeat_path,
)

_LAZY = {
    name: ("blades_tpu.supervision.supervisor", name)
    for name in (
        "POLICIES",
        "AttemptRecord",
        "DegradePolicy",
        "SupervisedResult",
        "Supervisor",
        "kill_process_group",
        "list_group",
        "resolve_policy",
        "supervise",
        "main",
    )
}

__all__ = [
    "HEARTBEAT_ENV",
    "RESUME_ENV",
    "SUPERVISED_ENV",
    "beat",
    "heartbeat_path",
    *sorted(_LAZY),
]


def __getattr__(name):  # PEP 562
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(
        f"module 'blades_tpu.supervision' has no attribute {name!r}"
    )
