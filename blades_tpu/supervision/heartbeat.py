"""Per-round heartbeat file: the liveness signal a supervised run emits.

The hang modes this box exhibits — TPU backend init that blocks forever,
the 8-device CPU mesh deadlocking in XLA's collective rendezvous — are
invisible from inside the hung process: no exception fires, no log line is
written, the process just stops making progress. The only reliable detector
is an *external* watcher reading a progress signal the workload can emit
cheaply. That signal is this heartbeat file: one tiny atomic-enough write
per round, piggybacked on the telemetry flush-once-per-round discipline
(``blades_tpu/telemetry``) so a supervised run performs no extra I/O
cadence beyond what it already does.

Protocol:

- the supervisor (``blades_tpu.supervision.supervisor``) exports
  :data:`HEARTBEAT_ENV` pointing at a file path before launching the
  workload;
- the workload calls :func:`beat` at every round flush (``Simulator.run``
  and ``bench.py``'s child loop do); when the env var is unset this is a
  dict lookup and an early return — unsupervised runs pay nothing;
- the supervisor reads staleness with :func:`age_s` (file mtime), killing
  the workload's whole process group once the age crosses its threshold.

The file body is a single JSON ``heartbeat`` record (schema in
``docs/observability.md``) so a post-mortem can see *where* the run was,
not just *when* it last moved: ``{"t": "heartbeat", "ts": ..., "pid": ...,
"round": N}``.

Stdlib-only (like the telemetry recorder): importable before jax and from
any subprocess. Reference counterpart: none — the reference assumes a
permanently healthy Ray cluster (``src/blades/simulator.py:189-211``);
production FL servers treat per-round watchdogs as first-class
(Bonawitz et al., 2019, *Towards Federated Learning at Scale*).
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

#: Env var the supervisor sets to the heartbeat file path; the workload's
#: :func:`beat` calls are no-ops when it is unset.
HEARTBEAT_ENV = "BLADES_HEARTBEAT_FILE"

#: Env var the supervisor sets to "1" so workloads can opt into
#: supervised-only behavior (e.g. Simulator's SIGTERM -> checkpoint hook).
SUPERVISED_ENV = "BLADES_SUPERVISED"

#: Env var the supervisor sets to "1" on relaunch attempts; Simulator.run
#: treats it as ``resume=True`` so a relaunched run continues from the
#: crash autosave / latest checkpoint instead of restarting from scratch.
RESUME_ENV = "BLADES_RESUME"


def heartbeat_path() -> Optional[str]:
    """The heartbeat file path for this process (None when unsupervised)."""
    return os.environ.get(HEARTBEAT_ENV) or None


def beat(round_idx: Optional[int] = None, path: Optional[str] = None) -> None:
    """Touch the heartbeat file (one small write; mtime is the signal).

    No-op when neither ``path`` nor :data:`HEARTBEAT_ENV` is set. Never
    raises: a full disk or deleted directory must not take down the run the
    heartbeat observes — a stale heartbeat then (correctly) reports the
    environment as unhealthy.
    """
    path = path or heartbeat_path()
    if not path:
        return
    rec = {"t": "heartbeat", "ts": time.time(), "pid": os.getpid()}
    if round_idx is not None:
        rec["round"] = int(round_idx)
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as fh:
            fh.write(json.dumps(rec) + "\n")
    except OSError:
        pass


def read(path: str) -> Optional[dict]:
    """The last-written heartbeat record, or None (missing/torn file)."""
    try:
        with open(path) as fh:
            return json.loads(fh.read())
    except (OSError, ValueError):
        return None


def age_s(path: str, now: Optional[float] = None) -> Optional[float]:
    """Seconds since the heartbeat file was last touched (None: no beat yet).

    Reads the file *mtime*, not the body — a torn write still moves the
    mtime, so a workload killed mid-beat never reads as freshly alive.
    """
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        return None
    return (time.time() if now is None else now) - mtime
