"""Per-round heartbeat file: the liveness signal a supervised run emits.

The hang modes this box exhibits — TPU backend init that blocks forever,
the 8-device CPU mesh deadlocking in XLA's collective rendezvous — are
invisible from inside the hung process: no exception fires, no log line is
written, the process just stops making progress. The only reliable detector
is an *external* watcher reading a progress signal the workload can emit
cheaply. That signal is this heartbeat file: one tiny atomic-enough write
per round, piggybacked on the telemetry flush-once-per-round discipline
(``blades_tpu/telemetry``) so a supervised run performs no extra I/O
cadence beyond what it already does.

Protocol:

- the supervisor (``blades_tpu.supervision.supervisor``) exports
  :data:`HEARTBEAT_ENV` pointing at a file path before launching the
  workload;
- the workload calls :func:`beat` at every round flush (``Simulator.run``
  and ``bench.py``'s child loop do); when the env var is unset this is a
  dict lookup and an early return — unsupervised runs pay nothing;
- the supervisor reads staleness with :func:`age_s` (file mtime), killing
  the workload's whole process group once the age crosses its threshold.

The file body is a single JSON ``heartbeat`` record (schema in
``docs/observability.md``) so a post-mortem can see *where* the run was,
not just *when* it last moved: ``{"t": "heartbeat", "ts": ..., "pid": ...,
"round": N, "interval_s": ...}`` — ``interval_s`` is the measured gap
since this process's previous beat, which also feeds the heartbeat-margin
gauge (:data:`TIMEOUT_ENV`).

Stdlib-only (like the telemetry recorder): importable before jax and from
any subprocess. Reference counterpart: none — the reference assumes a
permanently healthy Ray cluster (``src/blades/simulator.py:189-211``);
production FL servers treat per-round watchdogs as first-class
(Bonawitz et al., 2019, *Towards Federated Learning at Scale*).
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

#: Env var the supervisor sets to the heartbeat file path; the workload's
#: :func:`beat` calls are no-ops when it is unset.
HEARTBEAT_ENV = "BLADES_HEARTBEAT_FILE"

#: Env var the supervisor sets to "1" so workloads can opt into
#: supervised-only behavior (e.g. Simulator's SIGTERM -> checkpoint hook).
SUPERVISED_ENV = "BLADES_SUPERVISED"

#: Env var the supervisor sets to "1" on relaunch attempts; Simulator.run
#: treats it as ``resume=True`` so a relaunched run continues from the
#: crash autosave / latest checkpoint instead of restarting from scratch.
RESUME_ENV = "BLADES_RESUME"

#: Env var the supervisor sets to its ``--heartbeat-timeout`` (seconds) so
#: the workload can measure its own margin: :func:`beat` gauges the
#: time-since-last-beat and emits a ``heartbeat_margin`` warning record
#: when a beat lands within :data:`MARGIN_WARN_FRAC` of the kill
#: threshold — the between-beat cold-compile gap (CLAUDE.md) becomes
#: visible in the trace BEFORE it kills a run.
TIMEOUT_ENV = "BLADES_HEARTBEAT_TIMEOUT"

#: Warn when the observed beat interval exceeds this fraction of the
#: supervisor's timeout (i.e. the beat landed within 25% of being fatal).
MARGIN_WARN_FRAC = 0.75

# wall-clock of this process's previous beat (margin measurement only —
# the supervisor keeps reading file mtime, never this)
_last_beat_ts: Optional[float] = None


def heartbeat_path() -> Optional[str]:
    """The heartbeat file path for this process (None when unsupervised)."""
    return os.environ.get(HEARTBEAT_ENV) or None


def beat(round_idx: Optional[int] = None, path: Optional[str] = None) -> None:
    """Touch the heartbeat file (one small write; mtime is the signal).

    No-op when neither ``path`` nor :data:`HEARTBEAT_ENV` is set. Never
    raises: a full disk or deleted directory must not take down the run the
    heartbeat observes — a stale heartbeat then (correctly) reports the
    environment as unhealthy.
    """
    global _last_beat_ts
    path = path or heartbeat_path()
    if not path:
        return
    now = time.time()
    rec = {"t": "heartbeat", "ts": now, "pid": os.getpid()}
    # run-identity envelope (telemetry/context.py): a post-mortem can match
    # the heartbeat body to the trace/ledger of the attempt that wrote it
    run_id = os.environ.get("BLADES_RUN_ID")
    if run_id:
        rec["run_id"] = run_id
        attempt = os.environ.get("BLADES_ATTEMPT")
        if attempt and attempt.isdigit():
            rec["attempt"] = int(attempt)
    if round_idx is not None:
        rec["round"] = int(round_idx)
    # heartbeat-margin gauge: how close did THIS beat come to the
    # supervisor's staleness threshold? Gauged on the active telemetry
    # recorder (rides the next round record) and escalated to a
    # ``heartbeat_margin`` warning record when the interval ate more than
    # MARGIN_WARN_FRAC of the timeout — so the classic between-beat
    # cold-compile gap is visible in the trace before it kills a run.
    interval = None if _last_beat_ts is None else now - _last_beat_ts
    _last_beat_ts = now
    if interval is not None:
        rec["interval_s"] = round(interval, 3)
        try:
            from blades_tpu.telemetry.recorder import get_recorder

            trec = get_recorder()
            trec.gauge("heartbeat.interval_s", round(interval, 3))
            timeout = float(os.environ.get(TIMEOUT_ENV) or 0) or None
            if timeout:
                trec.gauge("heartbeat.margin_s", round(timeout - interval, 3))
                if interval >= MARGIN_WARN_FRAC * timeout:
                    trec.event(
                        "heartbeat_margin",
                        interval_s=round(interval, 3),
                        timeout_s=timeout,
                        margin_s=round(timeout - interval, 3),
                        **({"round": int(round_idx)}
                           if round_idx is not None else {}),
                    )
        except Exception:  # noqa: BLE001 - liveness must never raise
            pass
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as fh:
            fh.write(json.dumps(rec) + "\n")
    except OSError:
        pass


def read(path: str) -> Optional[dict]:
    """The last-written heartbeat record, or None (missing/torn file)."""
    try:
        with open(path) as fh:
            return json.loads(fh.read())
    except (OSError, ValueError):
        return None


def age_s(path: str, now: Optional[float] = None) -> Optional[float]:
    """Seconds since the heartbeat file was last touched (None: no beat yet).

    Reads the file *mtime*, not the body — a torn write still moves the
    mtime, so a workload killed mid-beat never reads as freshly alive.
    """
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        return None
    return (time.time() if now is None else now) - mtime
