"""``python -m blades_tpu.supervision [opts] -- workload argv...``

See :func:`blades_tpu.supervision.supervisor.main` and
``docs/robustness.md`` ("Run supervision").

Reference counterpart: none — the reference has no process-lifetime
tooling at all (Ray owns its workers, ``src/blades/simulator.py:189-211``).
"""

import sys

from blades_tpu.supervision.supervisor import main

if __name__ == "__main__":
    sys.exit(main())
