"""Runtime robustness contracts: per-round certificates + certified fallback.

The certification sweep (``scripts/certify.py``) measures breakdown
offline; this module watches for it *during* a run. Two cheap certificates
are traced into the SAME jitted round program as training and aggregation
(``core/engine.py`` — zero extra compiles, pinned by the compile-counter
telemetry in ``tests/test_audit.py``):

- ``median_ball`` — the applied aggregate stays within
  ``median_ball_factor`` times the participants' robust spread of their
  coordinate-wise median:
  ``||agg - med|| <= factor * median_i ||u_i - med||``. This is the
  oracle-free form of the (f, c)-resilience bound: the coordinate-wise
  median and the median distance to it are both f < n/2 robust estimates
  of the honest center/spread, so an aggregate that leaves the ball has
  been dragged further than any honest-majority statistic can justify
  (Karimireddy et al., 2021);
- ``envelope`` — the aggregate stays inside the participants'
  pairwise-distance envelope:
  ``max_i ||agg - u_i|| <= envelope_factor * max_ij ||u_i - u_j||``
  (an aggregate outside the delivered point cloud is never justified).

A breach is a per-round boolean; with ``fallback_aggregator=`` set, the
round that breaches applies a safe defense's aggregate instead (computed
in-graph alongside the primary — the swap is a ``where``, so a
breach->fallback round is bit-reproducible under a fixed seed, including
across kill/resume). This composes with the fault layer: certificates run
over the participating subset only, and guard-excluded NaN rows are zeroed
before any certificate arithmetic (masked-row inertness extends to the
audit, ``scripts/chaos.py``).

Reference counterpart: none — the reference applies whatever the
aggregator returns, unconditionally (``src/blades/simulator.py:244``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax.numpy as jnp

from blades_tpu.ops.distances import pairwise_sq_euclidean
from blades_tpu.ops.masked import masked_mean, masked_median, masked_median_1d
from blades_tpu.ops.streaming import chunk_geometry, stack_init, stack_write

CERTIFICATE_NAMES = ("median_ball", "envelope")


def _norm(v):
    return jnp.sqrt(jnp.maximum(jnp.sum(v * v), 0.0))


def _row_dists(rows, point):
    return jnp.sqrt(jnp.maximum(jnp.sum((rows - point[None, :]) ** 2, axis=1), 0.0))


@dataclasses.dataclass(frozen=True)
class AuditMonitor:
    """Round-level robustness certificates with optional certified fallback.

    Parameters
    ----------
    median_ball_factor : the ``c`` of the median-ball certificate.
        Default 3.0 — the same constant the offline (f, c)-resilience
        certification uses (``blades_tpu.audit.contracts.DEFAULT_C``).
    envelope_factor : slack multiplier on the pairwise-distance envelope.
    certificates : which certificates gate the breach flag (both are always
        *recorded*; this selects which ones can trigger fallback).
    fallback_aggregator : registry name or :class:`Aggregator` instance
        swapped in for any round whose enforced certificates breach.
        Must be stateless (the fallback runs from a fresh empty state every
        round — a stateful fallback would need its state threaded through
        rounds it does not own); ``median`` is the canonical choice.

    Instances ride on the engine like a FaultModel: construction-time
    hyperparameters are static under jit, and every method is a pure
    function traced into the round program.
    """

    median_ball_factor: float = 3.0
    envelope_factor: float = 1.0
    certificates: Tuple[str, ...] = ("median_ball", "envelope")
    fallback_aggregator: Any = None

    def __post_init__(self):
        certs = tuple(self.certificates)
        for c in certs:
            if c not in CERTIFICATE_NAMES:
                raise ValueError(
                    f"unknown certificate {c!r}; available: {CERTIFICATE_NAMES}"
                )
        if not certs:
            raise ValueError("certificates must name at least one certificate")
        object.__setattr__(self, "certificates", certs)
        fb = self.fallback_aggregator
        if isinstance(fb, str):
            from blades_tpu.aggregators import get_aggregator

            fb = get_aggregator(fb)
        if fb is not None and getattr(fb, "stateful", False):
            raise ValueError(
                f"fallback aggregator {fb!r} is stateful; the fallback runs "
                "from a fresh state each breached round — use a stateless "
                "defense (median/trimmedmean/geomed)"
            )
        object.__setattr__(self, "fallback_aggregator", fb)

    # -- the in-graph certificate pass ---------------------------------------

    def certify(
        self, updates: jnp.ndarray, agg: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, dict]:
        """Evaluate both certificates on the (participating subset of the)
        update matrix against a candidate aggregate.

        Returns ``(breach, diag)``: a scalar bool (True when any ENFORCED
        certificate fails on a round with >= 1 participant) and the full
        forensic dict. Masked-out rows are zeroed first, so excluded
        NaN/Inf payloads cannot poison the certificate arithmetic.
        """
        k = updates.shape[0]
        m = jnp.ones(k, bool) if mask is None else jnp.asarray(mask).astype(bool)
        safe = jnp.where(m[:, None], updates, 0.0)
        n = jnp.sum(m.astype(jnp.int32))

        med = masked_median(safe, m)
        r_hat = masked_median_1d(_row_dists(safe, med), m)
        dev_med = _norm(agg - med)
        slack_med = 1e-6 * (1.0 + _norm(med))
        median_ok = dev_med <= self.median_ball_factor * r_hat + slack_med

        d2 = pairwise_sq_euclidean(safe)
        pair = m[:, None] & m[None, :]
        diameter = jnp.sqrt(jnp.maximum(jnp.max(jnp.where(pair, d2, 0.0)), 0.0))
        agg_reach = jnp.max(jnp.where(m, _row_dists(safe, agg), 0.0))
        slack_env = 1e-6 * (1.0 + diameter)
        envelope_ok = agg_reach <= self.envelope_factor * diameter + slack_env

        ok = jnp.ones((), bool)
        if "median_ball" in self.certificates:
            ok = ok & median_ok
        if "envelope" in self.certificates:
            ok = ok & envelope_ok
        breach = (n > 0) & ~ok
        diag = {
            "participants": n,
            "cert_median_ball": median_ok.astype(jnp.int32),
            "cert_envelope": envelope_ok.astype(jnp.int32),
            "dev_median": dev_med,
            "spread_median": r_hat,
            "diameter": diameter,
        }
        return breach, diag

    def apply(
        self,
        updates: jnp.ndarray,
        agg: jnp.ndarray,
        *,
        mask: Optional[jnp.ndarray] = None,
        byz_mask: Optional[jnp.ndarray] = None,
        **ctx,
    ) -> Tuple[jnp.ndarray, dict]:
        """Certify ``agg``; on breach, swap in the fallback aggregate (when
        configured). ``ctx`` is the engine's aggregation context (trusted
        mask, flat params, rng key) forwarded to the fallback.

        ``byz_mask`` (the simulator's ground-truth oracle, unavailable in a
        real deployment) adds honest-reference forensics to the diag: the
        applied aggregate's deviation from the honest participating mean
        and the max honest deviation — the two sides of the (f, c) bound,
        recorded per round for the chaos suite's deviation invariant.
        """
        breach, diag = self.certify(updates, agg, mask)
        k = updates.shape[0]
        m = jnp.ones(k, bool) if mask is None else jnp.asarray(mask).astype(bool)
        safe = jnp.where(m[:, None], updates, 0.0)

        final = agg
        fallback_used = jnp.zeros((), bool)
        if self.fallback_aggregator is not None:
            fb, _ = self.fallback_aggregator.aggregate_masked(
                updates, (), mask=mask, **ctx
            )
            final = jnp.where(breach, fb, agg)
            fallback_used = breach

        diag["breach"] = breach.astype(jnp.int32)
        diag["fallback_used"] = fallback_used.astype(jnp.int32)
        diag["agg_norm"] = _norm(final)
        if byz_mask is not None:
            honest = m & ~byz_mask
            nh = jnp.sum(honest.astype(jnp.int32))
            mu_h = masked_mean(safe, honest)
            hd = jnp.max(jnp.where(honest, _row_dists(safe, mu_h), 0.0))
            has_h = nh > 0
            diag["honest_participants"] = nh
            diag["max_honest_dev"] = jnp.where(has_h, hd, 0.0)
            diag["dev_honest"] = jnp.where(has_h, _norm(final - mu_h), 0.0)
            diag["dev_honest_raw"] = jnp.where(has_h, _norm(agg - mu_h), 0.0)
        return final, diag

    # -- streaming (chunk-scanned) certificates -------------------------------
    #
    # At streaming scale the [K, D] matrix the dense certificates read never
    # exists. The streaming form keeps, per chunk: the chunk's coordinate-
    # wise median ([num_chunks, D] stack), each row's distance to ITS chunk
    # median ([num_chunks, chunk] scalars), the chunk radius, and the exact
    # within-chunk diameter. At finalize the two-level median med_s (median
    # of chunk medians) and the triangle inequality
    #     | ||u_i - p|| - ||c_j - p|| |  <=  d_i  <=  ||u_i - p|| + ||c_j - p||
    # give INTERVAL BOUNDS on every dense row statistic against any point p
    # known only post-pass (med_s, the aggregate). Certificates then breach
    # only when confident — dev compared against the spread's UPPER bound,
    # reach's LOWER bound against the diameter's UPPER bound — so a flagged
    # breach is genuine under the chunk approximation, while borderline
    # breaches inside the approximation slack may pass (the tolerant
    # direction; both bounds land in the diag for forensics). Singleton
    # chunks collapse every interval to a point and the streaming
    # certificates equal the dense ones exactly (tested).

    def streaming_init(
        self, num_clients: int, num_chunks: int, chunk_size: int, dim: int
    ) -> dict:
        return {
            "meds": stack_init(num_chunks, (dim,)),
            "counts": jnp.zeros((num_chunks,), jnp.int32),
            "row_dist": stack_init(num_chunks, (chunk_size,)),
            "row_mask": jnp.zeros((num_chunks, chunk_size), bool),
            "radius": jnp.zeros((num_chunks,), jnp.float32),
            "diam": jnp.zeros((num_chunks,), jnp.float32),
        }

    def streaming_update(
        self, astate: dict, slab: jnp.ndarray, *, chunk_mask: jnp.ndarray,
        chunk_index,
    ) -> dict:
        med_c = masked_median(slab, chunk_mask)
        geo = chunk_geometry(slab, chunk_mask, med_c)
        n = jnp.sum(chunk_mask.astype(jnp.int32))
        return {
            "meds": stack_write(astate["meds"], chunk_index,
                                jnp.where(n > 0, med_c, 0.0)),
            "counts": stack_write(astate["counts"], chunk_index, n),
            "row_dist": stack_write(astate["row_dist"], chunk_index,
                                    geo["row_dist"]),
            "row_mask": stack_write(astate["row_mask"], chunk_index,
                                    chunk_mask),
            "radius": stack_write(astate["radius"], chunk_index,
                                  geo["radius"]),
            "diam": stack_write(astate["diam"], chunk_index, geo["diameter"]),
        }

    def streaming_apply(
        self,
        astate: dict,
        agg: jnp.ndarray,
        *,
        fallback_agg: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, dict]:
        """Finalize the streaming certificates against the finalized
        aggregate; on confident breach swap in ``fallback_agg`` (the
        fallback aggregator's own streaming finalize, computed by the
        engine in the same scan). Mirrors :meth:`apply`'s diag schema with
        bound-valued spread/diameter fields plus the explicit lo/hi
        interval forensics; the dense oracle's honest-reference fields
        (``dev_honest``/``max_honest_dev``) need the rows and are dense-only.
        """
        meds, counts = astate["meds"], astate["counts"]
        chunk_ok = counts > 0
        n = jnp.sum(counts)
        med_s = masked_median(meds, chunk_ok)

        # per-chunk center offsets against finalize-time points
        e_med = jnp.where(chunk_ok, _row_dists(meds, med_s), 0.0)  # ||c_j-med||
        e_agg = jnp.where(chunk_ok, _row_dists(meds, agg), 0.0)    # ||c_j-agg||

        d = astate["row_dist"]          # [C, chunk] row -> own-chunk median
        rmask = astate["row_mask"]      # [C, chunk]
        lo = jnp.maximum(d - e_med[:, None], 0.0)
        hi = d + e_med[:, None]
        r_hat_lo = masked_median_1d(lo.reshape(-1), rmask.reshape(-1))
        r_hat_hi = masked_median_1d(hi.reshape(-1), rmask.reshape(-1))

        dev_med = _norm(agg - med_s)
        slack_med = 1e-6 * (1.0 + _norm(med_s))
        median_ok = dev_med <= self.median_ball_factor * r_hat_hi + slack_med

        radius = astate["radius"]
        reach_hi = jnp.max(jnp.where(chunk_ok, e_agg + radius, 0.0))
        reach_lo = jnp.max(
            jnp.where(chunk_ok, jnp.maximum(e_agg - radius, 0.0), 0.0)
        )
        # cross-chunk diameter bounds from center distances +/- radii;
        # the diagonal term (2 r_j) dominates the exact in-chunk diameter,
        # so the pair formula alone is a valid upper bound
        cdist = jnp.sqrt(jnp.maximum(pairwise_sq_euclidean(meds), 0.0))
        pair_ok = chunk_ok[:, None] & chunk_ok[None, :]
        diam_hi = jnp.max(
            jnp.where(
                pair_ok,
                cdist + radius[:, None] + radius[None, :],
                0.0,
            )
        )
        diam_lo = jnp.maximum(
            jnp.max(jnp.where(chunk_ok, astate["diam"], 0.0)),
            jnp.max(
                jnp.where(
                    pair_ok,
                    cdist - radius[:, None] - radius[None, :],
                    0.0,
                )
            ),
        )
        slack_env = 1e-6 * (1.0 + diam_hi)
        envelope_ok = reach_lo <= self.envelope_factor * diam_hi + slack_env

        ok = jnp.ones((), bool)
        if "median_ball" in self.certificates:
            ok = ok & median_ok
        if "envelope" in self.certificates:
            ok = ok & envelope_ok
        breach = (n > 0) & ~ok

        final = agg
        fallback_used = jnp.zeros((), bool)
        if fallback_agg is not None:
            final = jnp.where(breach, fallback_agg, agg)
            fallback_used = breach

        diag = {
            "participants": n,
            "cert_median_ball": median_ok.astype(jnp.int32),
            "cert_envelope": envelope_ok.astype(jnp.int32),
            "dev_median": dev_med,
            "spread_median": r_hat_hi,
            "spread_median_lo": r_hat_lo,
            "diameter": diam_hi,
            "diameter_lo": diam_lo,
            "agg_reach_lo": reach_lo,
            "agg_reach_hi": reach_hi,
            "breach": breach.astype(jnp.int32),
            "fallback_used": fallback_used.astype(jnp.int32),
            "agg_norm": _norm(final),
        }
        return final, diag

    def __repr__(self) -> str:
        parts = [f"certs={'+'.join(self.certificates)}",
                 f"c={self.median_ball_factor}"]
        if self.fallback_aggregator is not None:
            parts.append(f"fallback={self.fallback_aggregator!r}")
        return f"AuditMonitor({', '.join(parts)})"
