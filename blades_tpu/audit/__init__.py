"""Defense certification & breakdown audit (docs/robustness.md).

Three layers over the aggregator registry, all pure functions of the
``[K, D]`` update matrix:

- :mod:`~blades_tpu.audit.contracts` — the jitted contract battery
  (permutation invariance, translation equivariance, empirical
  (f, c)-resilience) every registered aggregator must pass or opt out of
  with a documented reason (``Aggregator.audit_optouts``, enforced by the
  tier-1 registry lint in ``tests/test_audit.py``);
- :mod:`~blades_tpu.audit.attack_search` — the adaptive per-(aggregator, f)
  worst-case attack search behind the committed breakdown matrix
  (``scripts/certify.py`` -> ``results/certification/cert_matrix.json``);
- :mod:`~blades_tpu.audit.monitor` — :class:`AuditMonitor`, the runtime
  per-round certificates + certified graceful fallback traced into the
  jitted round program (``core/engine.py``; ``audit`` telemetry records,
  docs/observability.md).

Reference counterpart: none — the reference neither measures nor reacts to
defense breakdown (``src/blades/simulator.py:244``).
"""

from blades_tpu.audit.attack_search import (
    DEFAULT_GRIDS,
    QUICK_GRIDS,
    TEMPLATE_NAMES,
    search_cell,
    search_cell_staleness,
    search_cells,
    staleness_row_weights,
    synthetic_honest,
)
from blades_tpu.audit.contracts import (
    CONTRACTS,
    DEFAULT_C,
    battery_ctx,
    battery_kwargs,
    battery_search_inputs,
    check_permutation,
    check_resilience,
    check_translation,
    nominal_f,
    resilience_from_cell,
    run_battery,
)
from blades_tpu.audit.monitor import CERTIFICATE_NAMES, AuditMonitor

__all__ = [
    "AuditMonitor",
    "CERTIFICATE_NAMES",
    "CONTRACTS",
    "DEFAULT_C",
    "DEFAULT_GRIDS",
    "QUICK_GRIDS",
    "TEMPLATE_NAMES",
    "battery_ctx",
    "battery_kwargs",
    "battery_search_inputs",
    "resilience_from_cell",
    "check_permutation",
    "check_resilience",
    "check_translation",
    "nominal_f",
    "run_battery",
    "search_cell",
    "search_cell_staleness",
    "search_cells",
    "staleness_row_weights",
    "synthetic_honest",
]
