"""Jitted contract battery for robust aggregators.

Karimireddy et al. (2021, *Learning from History*) frame Byzantine
robustness as a checkable bound rather than a narrative property; this
module makes three such properties executable over the whole aggregator
registry (``blades_tpu/aggregators``), both as tier-1 test properties
(``tests/test_audit.py`` — the registry lint) and as a sweep
(``scripts/certify.py``):

- ``permutation``  — client order cannot matter:
                     ``agg(P u) == agg(u)`` for a random permutation ``P``
                     (any ``[K]``-shaped context array, e.g. FLTrust's
                     ``trusted_mask``, is permuted along);
- ``translation``  — shifting every update shifts the aggregate:
                     ``agg(u + t) == agg(u) + t``. Origin-anchored defenses
                     (cosine trust, norm filters, clipping around a zero
                     momentum) legitimately fail this and declare a
                     documented opt-out (``Aggregator.audit_optouts``);
- ``resilience``   — the empirical (f, c)-bound under the adaptive attack
                     search (``blades_tpu/audit/attack_search``):
                     ``||agg(attacked) - mean(honest)|| <= c * rho`` with
                     ``rho`` the max honest deviation.

Every check is a pure function over a ``[K, D]`` matrix, so the battery
runs eagerly on tiny matrices in the lint (no compile cost) and jitted
inside the certification sweep. Reference counterpart: none — the
reference has no tests and no contract surface at all (SURVEY.md §4).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from blades_tpu.aggregators.base import Aggregator
from blades_tpu.audit.attack_search import (
    QUICK_GRIDS,
    search_cell,
    synthetic_honest,
)

CONTRACTS = ("permutation", "translation", "resilience")

#: default (f, c) resilience constant: any aggregate inside the min-max
#: feasibility envelope is within 3 rho of the honest mean (a malicious
#: point within the honest pairwise diameter is <= 2 rho from some honest
#: update, itself <= rho from the honest mean), so c = 3 is the natural
#: "constant factor of the honest spread" the certificate asks for.
DEFAULT_C = 3.0

_RTOL = 1e-3
_ATOL = 1e-4


def nominal_f(name: str, k: int) -> int:
    """The largest byzantine count the named defense nominally tolerates at
    population ``k`` — the f at which the certification matrix expects the
    cell to pass (docs/robustness.md):

    - ``mean``/``asyncmean``: 0 (breakdown point 0 — one unbounded row
      moves the average arbitrarily);
    - ``krum``/``multikrum``: ``(k - 3) // 2`` (Blanchard et al. need
      ``k >= 2f + 3``);
    - everything else (median family, geometric medians, clustering,
      clipping, filters): honest majority, ``(k - 1) // 2``.
    """
    if name in ("mean", "asyncmean"):
        return 0
    if name in ("krum", "multikrum"):
        return max((k - 3) // 2, 0)
    return max((k - 1) // 2, 0)


def battery_kwargs(name: str, k: int, f: int) -> Dict[str, Any]:
    """Constructor kwargs certifying cell (name, f) at population ``k``.

    Defenses that take a byzantine budget get the cell's ``f``; multikrum's
    selection width shrinks to the Blanchard-safe ``k - 2f - 2``; the
    clipping radii are instantiated at 2x the honest deviation scale of
    :func:`~blades_tpu.audit.attack_search.synthetic_honest` (``spread=1``)
    — tau is a scale hyperparameter, and certifying a radius wildly off the
    data scale would measure the mis-configuration, not the defense.
    """
    if name in ("krum", "trimmedmean", "dnc"):
        return {"num_byzantine": f}
    if name == "multikrum":
        return {"num_byzantine": f, "num_selected": max(k - 2 * f - 2, 1)}
    if name in ("centeredclipping", "asynccenteredclipping"):
        return {"tau": 2.0}
    if name == "byzantinesgd":
        return {"th_A": 10.0, "th_B": 2.0, "th_V": 1.0}
    return {}


def battery_ctx(agg: Aggregator, k: int, d: int, key=None) -> Dict[str, Any]:
    """The aggregation context the battery supplies (mirrors what the
    engine passes every round, ``core/engine.py``): a trusted-client mask
    with the LAST client trusted (honest — byzantine ids are the prefix),
    the flat parameter vector, and an rng key."""
    return {
        "trusted_mask": jnp.zeros(k, bool).at[k - 1].set(True),
        "params_flat": jnp.zeros(d, jnp.float32),
        "key": key if key is not None else jax.random.PRNGKey(7),
    }


def _residual_ok(a, b, scale=0.0):
    res = float(jnp.sqrt(jnp.maximum(jnp.sum((a - b) ** 2), 0.0)))
    ref = float(jnp.sqrt(jnp.maximum(jnp.sum(a * a), 0.0))) + float(scale)
    return res, res <= _ATOL + _RTOL * ref


def _permute_ctx(ctx: dict, perm: jnp.ndarray, k: int) -> dict:
    out = {}
    for name, v in ctx.items():
        arr = jnp.asarray(v) if not isinstance(v, jax.Array) else v
        if (
            getattr(arr, "ndim", 0) >= 1
            and arr.shape[0] == k
            and name not in ("params_flat", "key")
        ):
            out[name] = arr[perm]
        else:
            out[name] = v
    return out


def check_permutation(agg: Aggregator, updates, ctx=None, key=None) -> Dict[str, Any]:
    """``agg(P u) == agg(u)`` for a random permutation P (within float
    tolerance — reduction orders legitimately reorder float sums)."""
    k, d = updates.shape
    ctx = dict(ctx or {})
    key = key if key is not None else jax.random.PRNGKey(11)
    perm = jax.random.permutation(key, k)
    a, _ = agg.aggregate(updates, agg.init_state(k, d), **ctx)
    b, _ = agg.aggregate(updates[perm], agg.init_state(k, d),
                         **_permute_ctx(ctx, perm, k))
    res, ok = _residual_ok(a, b)
    return {"contract": "permutation", "residual": res, "ok": bool(ok)}


def check_translation(agg: Aggregator, updates, ctx=None, key=None) -> Dict[str, Any]:
    """``agg(u + t) == agg(u) + t`` for a random translation t."""
    k, d = updates.shape
    ctx = dict(ctx or {})
    key = key if key is not None else jax.random.PRNGKey(13)
    t = 3.0 * jax.random.normal(key, (d,), updates.dtype) / np.sqrt(d)
    a, _ = agg.aggregate(updates, agg.init_state(k, d), **ctx)
    b, _ = agg.aggregate(updates + t[None, :], agg.init_state(k, d), **ctx)
    res, ok = _residual_ok(a + t, b, scale=float(jnp.linalg.norm(t)))
    return {"contract": "translation", "residual": res, "ok": bool(ok)}


def resilience_from_cell(cell: Dict[str, Any], f: int,
                         c: float = DEFAULT_C) -> Dict[str, Any]:
    """The resilience-contract result dict from a completed
    :func:`~blades_tpu.audit.attack_search.search_cell` result — the
    shared formatting between the sequential battery and a batched sweep
    that served the battery's search cell from a warm program group."""
    return {
        "contract": "resilience",
        "f": int(f),
        "c": float(c),
        "worst_ratio": cell["worst_ratio"],
        "worst_dev": cell["worst_dev"],
        "rho": cell["rho"],
        "templates": cell["templates"],
        "ok": bool(cell["worst_ratio"] <= c),
    }


def check_resilience(
    agg: Aggregator,
    trials_updates,
    f: int,
    *,
    ctx=None,
    c: float = DEFAULT_C,
    grids: Optional[dict] = None,
    use_jit: bool = False,
) -> Dict[str, Any]:
    """Empirical (f, c)-resilience under the adaptive attack search: the
    worst deviation over all templates stays within ``c`` times the honest
    spread."""
    cell = search_cell(agg, trials_updates, f, ctx=ctx, grids=grids,
                       use_jit=use_jit)
    return resilience_from_cell(cell, f, c)


def battery_search_inputs(
    agg: Aggregator,
    k: int,
    d: int,
    *,
    trials: int = 1,
    seed: int = 0,
    name: Optional[str] = None,
    f: Optional[int] = None,
):
    """``(trials_updates, f, ctx)`` for the battery's resilience search —
    the single owner of its key-split rule, shared by :func:`run_battery`
    and the batched certify driver (which groups this search cell with
    the breakdown cells of the same aggregator configuration and passes
    the completed result back via ``run_battery(resilience=...)``)."""
    name = name or type(agg).__name__.lower()
    if f is None:
        f = max(1, nominal_f(name, k))
    key = jax.random.PRNGKey(seed)
    k_data, _k_perm, _k_trans, k_ctx = jax.random.split(key, 4)
    trials_updates = synthetic_honest(k_data, trials, k, d)
    ctx = battery_ctx(agg, k, d, key=k_ctx)
    return trials_updates, f, ctx


def run_battery(
    agg: Aggregator,
    *,
    k: int = 8,
    d: int = 16,
    f: Optional[int] = None,
    name: Optional[str] = None,
    c: float = DEFAULT_C,
    trials: int = 1,
    seed: int = 0,
    grids: Optional[dict] = None,
    use_jit: bool = False,
    resilience: Optional[Dict[str, Any]] = None,
) -> Dict[str, Dict[str, Any]]:
    """Run all three contracts against one aggregator instance; returns
    ``{contract: result}`` with each result carrying ``ok`` plus the
    measured residual/ratio. ``f`` defaults to ``max(1, nominal_f)`` so the
    resilience check is never vacuous — aggregators with breakdown point 0
    (mean) fail it and must declare the documented opt-out.

    ``resilience``: a precomputed resilience-contract result (from
    :func:`resilience_from_cell`) — the batched certify driver computes
    the battery's search cell inside a warm program group
    (``battery_search_inputs`` pins the identical inputs) and passes it
    here instead of paying a per-battery compile.
    """
    name = name or type(agg).__name__.lower()
    if f is None:
        f = max(1, nominal_f(name, k))
    key = jax.random.PRNGKey(seed)
    k_data, k_perm, k_trans, k_ctx = jax.random.split(key, 4)
    trials_updates = synthetic_honest(k_data, trials, k, d)
    u0 = trials_updates[0]
    ctx = battery_ctx(agg, k, d, key=k_ctx)
    return {
        "permutation": check_permutation(agg, u0, ctx, key=k_perm),
        "translation": check_translation(agg, u0, ctx, key=k_trans),
        "resilience": resilience if resilience is not None else (
            check_resilience(
                agg, trials_updates, f, ctx=ctx, c=c,
                grids=grids if grids is not None else QUICK_GRIDS,
                use_jit=use_jit,
            )
        ),
    }
