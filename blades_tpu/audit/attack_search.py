"""Adaptive attack search: per-(aggregator, f) worst-case deviation.

Shejwalkar & Houmansadr (NDSS'21) show that fixed attacks understate how
badly an aggregator breaks — the adversary should *search* over attack
hyperparameters for the worst feasible corruption. This module is that
search, TPU-native: every template is a pure function of the ``[K, D]``
update matrix and a scalar attack parameter, swept inside fixed-shape
``lax`` loops (``lax.map`` grids, ``lax.fori_loop`` bisection) so one
compiled program evaluates the whole search for a cell.

Templates (>= 3 families, the satellites the literature actually uses):

- ``ipm``      — Inner Product Manipulation: byz rows ``-eps * mu_h``,
                 eps swept over a log grid (Xie et al., 2020);
- ``alie``     — A Little Is Enough: byz rows ``mu_h - z * std_h``,
                 z swept over a linear grid (Baruch et al., 2019);
- ``signflip`` — scaled sign flip: byz rows ``-s * u_i``, s log grid;
- ``minmax`` / ``minsum`` — AGR-agnostic envelope attacks: byz rows
                 ``mu_h + gamma * dev`` with gamma found by fixed-iteration
                 bisection against the honest pairwise-distance envelope
                 (reference machinery: ``attackers/minmax.py``), swept over
                 three perturbation directions (-std, -unit(mu), -sign(mu)).

The figure of merit is the empirical (f, c)-resilience of Karimireddy et
al. (2021, *Learning from History*): the aggregate must stay within a
constant factor of the honest updates' spread,

    ||agg(attacked) - mean(honest)|| <= c * max_i ||u_i - mean(honest)||.

``search_cell`` reports, per template, the worst deviation/ratio the search
found; ``scripts/certify.py`` drives it over the whole aggregator registry
to produce the committed breakdown matrix
(``results/certification/cert_matrix.json``, docs/robustness.md).

Reference counterpart: none — the reference ships fixed attacks only and
never measures aggregator breakdown (``src/blades/simulator.py:239-244``).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from blades_tpu.aggregators.base import Aggregator
from blades_tpu.attackers.base import honest_stats
from blades_tpu.ops.distances import pairwise_sq_euclidean
from blades_tpu.telemetry import programs as _programs
from blades_tpu.telemetry import recorder as _trecorder
from blades_tpu.telemetry import timeline as _timeline

TEMPLATE_NAMES = ("ipm", "alie", "signflip", "minmax", "minsum")

#: the full-search grids (scripts/certify.py)
DEFAULT_GRIDS: Dict[str, Any] = {
    "ipm_eps": np.logspace(-1.0, 3.0, 9),
    "alie_z": np.linspace(0.25, 4.0, 8),
    "signflip_s": np.logspace(-1.0, 3.0, 9),
    "n_bisect": 20,
    "gamma_init": 10.0,
}

#: reduced grids for the tier-1 registry lint (tests/test_audit.py)
QUICK_GRIDS: Dict[str, Any] = {
    "ipm_eps": np.asarray([1.0, 100.0]),
    "alie_z": np.asarray([1.5, 3.0]),
    "signflip_s": np.asarray([1.0, 100.0]),
    "n_bisect": 12,
    "gamma_init": 10.0,
}


# -- attack templates ---------------------------------------------------------


def ipm_rows(updates, byz_mask, eps, part_mask=None):
    """Byz rows become ``-eps * mean(honest)`` (IPM with traced epsilon)."""
    mu, _, _ = honest_stats(updates, byz_mask, part_mask)
    return jnp.where(byz_mask[:, None], -eps * mu[None, :], updates)


def alie_rows(updates, byz_mask, z, part_mask=None):
    """Byz rows become ``mu - z * std`` over the honest set (ALIE with
    traced z — the search's analogue of the ppf-derived static z_max)."""
    mu, std, _ = honest_stats(updates, byz_mask, part_mask)
    return jnp.where(byz_mask[:, None], (mu - z * std)[None, :], updates)


def signflip_rows(updates, byz_mask, s, part_mask=None):
    """Byz rows flip and scale their OWN update: ``-s * u_i``."""
    return jnp.where(byz_mask[:, None], -s * updates, updates)


def _unit(v, eps=1e-12):
    return v / jnp.maximum(jnp.sqrt(jnp.sum(v * v)), eps)


def dev_directions(updates, byz_mask, part_mask=None):
    """The ``[3, D]`` min-max/min-sum perturbation directions of the NDSS'21
    paper: negative honest std, negative unit honest mean, negative sign of
    the honest mean."""
    mu, std, _ = honest_stats(updates, byz_mask, part_mask)
    return jnp.stack([-_unit(std), -_unit(mu), -_unit(jnp.sign(mu))])


def _envelope_stats(updates, byz_mask, part_mask):
    """Honest weights + masked pairwise squared distances (the feasibility
    envelope both min-max and min-sum bisect against)."""
    honest_rows = ~byz_mask if part_mask is None else (~byz_mask & part_mask)
    honest_w = honest_rows.astype(updates.dtype)
    sq = pairwise_sq_euclidean(updates) * (honest_w[:, None] * honest_w[None, :])
    return honest_w, sq


def _bisect_gamma(feasible, gamma_init, n_bisect, dtype):
    """Fixed-iteration bisection for the largest feasible attack scale —
    static control flow (``lax.fori_loop``), the jit-friendly form of the
    reference's data-driven loop (``attackers/minmax.py``)."""

    def body(_, carry):
        gamma, step = carry
        gamma = jnp.where(feasible(gamma), gamma + step, gamma - step)
        return gamma, step / 2.0

    gamma0 = jnp.asarray(gamma_init, dtype)
    gamma, _ = lax.fori_loop(0, n_bisect, body, (gamma0, gamma0 / 2.0))
    # the degenerate envelope (one honest row -> all-zero pairwise
    # distances) drives the bisection to ~0; never below it
    return jnp.maximum(gamma, 0.0)


def minmax_rows(updates, byz_mask, dev, part_mask=None,
                n_bisect=20, gamma_init=10.0):
    """Min-Max: largest gamma with max distance from the malicious point to
    any honest update inside the max pairwise honest distance."""
    mu, _, _ = honest_stats(updates, byz_mask, part_mask)
    honest_w, sq = _envelope_stats(updates, byz_mask, part_mask)

    def feasible(gamma):
        mal = mu + gamma * dev
        d = ((updates - mal[None, :]) ** 2).sum(axis=1) * honest_w
        return d.max() <= sq.max()

    gamma = _bisect_gamma(feasible, gamma_init, n_bisect, updates.dtype)
    return jnp.where(byz_mask[:, None], (mu + gamma * dev)[None, :], updates)


def minsum_rows(updates, byz_mask, dev, part_mask=None,
                n_bisect=20, gamma_init=10.0):
    """Min-Sum: largest gamma with the malicious point's summed squared
    distance to the honest set inside the worst honest row's."""
    mu, _, _ = honest_stats(updates, byz_mask, part_mask)
    honest_w, sq = _envelope_stats(updates, byz_mask, part_mask)

    def feasible(gamma):
        mal = mu + gamma * dev
        d = (((updates - mal[None, :]) ** 2).sum(axis=1) * honest_w).sum()
        return d <= sq.sum(axis=1).max()

    gamma = _bisect_gamma(feasible, gamma_init, n_bisect, updates.dtype)
    return jnp.where(byz_mask[:, None], (mu + gamma * dev)[None, :], updates)


# -- the per-cell search ------------------------------------------------------


def honest_reference(updates, byz_mask, part_mask=None):
    """``(mu_h, rho)``: honest mean and max honest deviation from it — the
    two sides of the empirical (f, c)-resilience bound."""
    honest_rows = ~byz_mask if part_mask is None else (~byz_mask & part_mask)
    mu, _, _ = honest_stats(updates, byz_mask, part_mask)
    dev = jnp.sqrt(jnp.maximum(((updates - mu) ** 2).sum(axis=1), 0.0))
    rho = jnp.max(jnp.where(honest_rows, dev, 0.0))
    return mu, rho


def _trial_body(agg: Aggregator, k: int, d: int, g: dict, has_part: bool,
                ctx_keys: Tuple[str, ...]):
    """The per-trial search body, parameterized so that EVERY cell-varying
    input (the trial matrix, the byzantine mask, the participation mask,
    the aggregation context arrays) is traced DATA rather than a closed-
    over constant. One trace of this body therefore serves every cell
    whose program SHAPE matches (same aggregator config / K / D / grids) —
    the batching contract of :func:`search_cells` — and running it under
    ``lax.map`` per item is bit-identical whether the items come from one
    cell or many (the map body is the same trace either way)."""
    n_bisect = int(g["n_bisect"])
    gamma_init = float(g["gamma_init"])

    def body(u, byz_mask, part_mask, ctx_leaves):
        ctx = dict(zip(ctx_keys, ctx_leaves))

        def aggregate(attacked):
            state = agg.init_state(k, d)
            out, _ = agg.aggregate_masked(
                attacked, state, mask=part_mask, **ctx
            )
            return out

        mu_h, rho = honest_reference(u, byz_mask, part_mask)

        def deviation(attacked):
            return jnp.sqrt(
                jnp.maximum(jnp.sum((aggregate(attacked) - mu_h) ** 2), 0.0)
            )

        def sweep(template, grid):
            return jnp.max(
                lax.map(lambda p: deviation(template(u, byz_mask, p, part_mask)),
                        jnp.asarray(grid, u.dtype))
            )

        def sweep_env(template):
            devs = dev_directions(u, byz_mask, part_mask)
            return jnp.max(
                lax.map(
                    lambda dv: deviation(
                        template(u, byz_mask, dv, part_mask,
                                 n_bisect=n_bisect, gamma_init=gamma_init)
                    ),
                    devs,
                )
            )

        per_template = jnp.stack([
            sweep(ipm_rows, g["ipm_eps"]),
            sweep(alie_rows, g["alie_z"]),
            sweep(signflip_rows, g["signflip_s"]),
            sweep_env(minmax_rows),
            sweep_env(minsum_rows),
        ])
        return per_template, rho

    if has_part:
        return body
    return lambda u, byz_mask, ctx_leaves: body(u, byz_mask, None, ctx_leaves)


def _cell_result(devs: np.ndarray, rhos: np.ndarray) -> Dict[str, Any]:
    """``search_cell``'s result dict from one cell's ``[T, 5]`` deviations
    and ``[T]`` honest spreads."""
    devs = np.asarray(devs, dtype=np.float64)
    rhos = np.maximum(np.asarray(rhos, dtype=np.float64), 1e-9)
    ratios = devs / rhos[:, None]
    templates = {
        name: {
            "worst_dev": float(devs[:, i].max()),
            "worst_ratio": float(ratios[:, i].max()),
        }
        for i, name in enumerate(TEMPLATE_NAMES)
    }
    return {
        "templates": templates,
        "worst_dev": float(devs.max()),
        "worst_ratio": float(ratios.max()),
        "rho": float(rhos.mean()),
    }


def search_cells(
    agg: Aggregator,
    cells,
    *,
    grids: Optional[dict] = None,
    use_jit: bool = False,
    batch_label: Optional[str] = None,
) -> list:
    """Worst-case deviation search for MANY cells through ONE program.

    ``cells``: a list of dicts, one per cell — ``{"trials": [T, K, D],
    "f": int, "ctx": dict, "part_mask": None | [K], "label": str}`` — that
    share one program shape: the same aggregator configuration (``agg`` is
    evaluated once per item from a fresh ``init_state``), the same trial
    shape, the same context structure, and uniform part-mask presence
    (:func:`blades_tpu.sweeps.plan_groups` owns the grouping rule; this
    function asserts it). Per-cell parameters — the byzantine mask derived
    from ``f``, the participation mask, the context arrays, the (possibly
    staleness-weighted) trial matrices — enter as stacked traced data, so
    the whole group is one ``lax.map`` over ``C x T`` items inside one
    jitted program: the trace+compile that PR 11 measured at ~81% of every
    sequential cell is paid once per GROUP.

    Bit-exactness: :func:`search_cell` routes through this function with
    ``C = 1``, and a ``lax.map`` item's result depends only on its own
    inputs — so batched results are bit-identical to sequential ones
    (pinned in ``tests/test_sweeps.py``).

    Sweep accounting: one ``sweep`` record per cell with the shared
    ``batch`` key and ``batch_size``, amortized wall, and the group's
    compile counters on the first cell (``telemetry/timeline.py
    .sweep_batch_events``).

    Returns one :func:`search_cell`-shaped result dict per cell, in input
    order.
    """
    cells = list(cells)
    if not cells:
        return []
    t0 = time.perf_counter()
    counters0 = _trecorder.process_counters()
    g = dict(DEFAULT_GRIDS)
    g.update(grids or {})

    trials = [
        c["trials"][None] if c["trials"].ndim == 2 else c["trials"]
        for c in cells
    ]
    t, k, d = trials[0].shape
    for tr in trials[1:]:
        if tr.shape != (t, k, d):
            raise ValueError(
                f"cells in one batch must share the trial shape: "
                f"{tr.shape} != {(t, k, d)}"
            )
    has_part = [c.get("part_mask") is not None for c in cells]
    if any(has_part) != all(has_part):
        raise ValueError(
            "cells in one batch must have uniform part-mask presence"
        )
    has_part = has_part[0]
    ctx_keys = tuple(sorted((cells[0].get("ctx") or {})))
    for c in cells[1:]:
        if tuple(sorted((c.get("ctx") or {}))) != ctx_keys:
            raise ValueError(
                "cells in one batch must share the aggregation-context "
                "structure"
            )

    n = len(cells)
    u = jnp.reshape(jnp.stack(trials), (n * t, k, d))
    byz = jnp.repeat(
        jnp.stack([jnp.arange(k) < c["f"] for c in cells]), t, axis=0
    )
    args = [u, byz]
    if has_part:
        part = jnp.repeat(
            jnp.stack([jnp.asarray(c["part_mask"]).astype(bool)
                       for c in cells]),
            t, axis=0,
        )
        args.append(part)
    ctx_stacks = tuple(
        jnp.repeat(
            jnp.stack([jnp.asarray((c.get("ctx") or {})[key])
                       for c in cells]),
            t, axis=0,
        )
        for key in ctx_keys
    )
    args.append(ctx_stacks)

    body = _trial_body(agg, k, d, g, has_part, ctx_keys)

    def run(*xs):
        return lax.map(lambda item: body(*item), tuple(xs))

    if use_jit:
        run = jax.jit(run)
    # compile provenance: the group's one program, under the plan_groups
    # fingerprint when the driver passed one (run_grouped's batch key)
    with _programs.watch(
        f"attack_search/{type(agg).__name__}",
        fingerprint=batch_label,
        shapes=(n * t, k, d, has_part, ctx_keys),
    ):
        devs, rhos = run(*args)  # [C*T, 5], [C*T]
    devs = np.asarray(devs, np.float64).reshape(n, t, len(TEMPLATE_NAMES))
    rhos = np.asarray(rhos, np.float64).reshape(n, t)
    results = [_cell_result(devs[i], rhos[i]) for i in range(n)]

    wall = time.perf_counter() - t0
    labels = [
        c.get("label") or f"f{c['f']}/k{k}" for c in cells
    ]
    if n == 1:
        _timeline.sweep_cell_event("attack_search", labels[0], wall, counters0)
    else:
        _timeline.sweep_batch_events(
            "attack_search", labels, wall, counters0,
            batch=batch_label or f"batch{n}/k{k}",
        )
    return results


def search_cell(
    agg: Aggregator,
    trials_updates: jnp.ndarray,
    f: int,
    *,
    ctx: Optional[dict] = None,
    grids: Optional[dict] = None,
    part_mask: Optional[jnp.ndarray] = None,
    use_jit: bool = False,
    cell_label: Optional[str] = None,
) -> Dict[str, Any]:
    """Worst-case deviation search for one (aggregator, f) cell.

    ``trials_updates``: ``[T, K, D]`` honest update draws (the search runs
    per trial and reports the worst). ``f`` is static (the aggregator's own
    hyperparameters are static anyway); the byzantine rows are the first
    ``f`` ids, matching the engine convention (``core/engine.py:227``).
    The aggregator is evaluated single-shot from a fresh ``init_state``
    (stateful defenses certify their first-round behavior; docs note).

    This is the single-cell (``C = 1``) form of :func:`search_cells` — the
    same traced body, so a sequential sweep and a batched one produce
    bit-identical numbers per cell.

    Sweep accounting (``telemetry/timeline.py``): each call emits one
    ``sweep`` record — ``cell_label`` (default ``f<f>/k<K>``), wall /
    compile / execute split — onto the ACTIVE recorder, so a driver that
    installed a trace (``scripts/certify.py``) gets per-cell telemetry
    with no wiring here; with the NULL recorder the emit is a no-op.

    Returns ``{"templates": {name: {"worst_dev", "worst_ratio"}},
    "worst_dev", "worst_ratio", "rho"}`` — ratio is deviation over the
    per-trial max honest deviation ``rho`` (floored at 1e-9).
    """
    k = trials_updates.shape[-2]
    return search_cells(
        agg,
        [{
            "trials": trials_updates,
            "f": int(f),
            "ctx": dict(ctx or {}),
            "part_mask": part_mask,
            "label": cell_label or f"f{int(f)}/k{k}",
        }],
        grids=grids,
        use_jit=use_jit,
    )[0]


# -- staleness-aware templates (buffered-async threat model) ------------------
#
# Under the buffered-async engine (blades_tpu/asyncfl) the server
# aggregates STALENESS-WEIGHTED rows. The asynchronous threat model gives
# the adversary a lever the sync battery never measures: byzantine clients
# CONTROL THEIR OWN REPORTING TIME, so they choose the staleness weight
# they will receive — and, since they also control their payload, they can
# pre-scale it by 1/w to cancel any discount ("IPM/ALIE scaled by the
# staleness weight they will receive"). The honest population cannot: real
# stragglers report late and get damped heterogeneously, which DISTORTS
# the honest geometry every defense reasons over (trim fractions, Krum
# neighborhoods, clipping radii). The staleness search therefore evaluates
# the standard template battery on the weighted matrix the server actually
# sees: honest rows scaled by their (normalized, asyncfl/buffer.py)
# staleness weights, byzantine rows unconstrained as always.


def staleness_row_weights(
    k: int,
    f: int,
    *,
    mode: str = "polynomial",
    alpha: float = 0.5,
    tau_max: int = 3,
    tau_byz: int = 0,
    cutoff: Optional[int] = None,
):
    """``(mask, weights, tau)`` for one staleness scenario.

    Honest rows carry a deterministic staleness ladder ``0..tau_max``
    (cycled — a population of mixed-speed clients); byzantine rows all
    report at ``tau_byz`` (0 = the fresh attacker among damped honest
    stragglers, the amplified case; ``tau_max`` = maximal-staleness
    reporting, the attacker hiding behind the straggler excuse).
    Normalization (mean-1 over the included set) and the cutoff-exclusion
    rule are delegated to :class:`blades_tpu.asyncfl.AsyncConfig` — single
    owner of the weighting semantics the engine executes.
    """
    from blades_tpu.asyncfl import AsyncConfig

    byz = jnp.arange(k) < f
    honest_tau = jnp.mod(jnp.maximum(jnp.arange(k) - f, 0), tau_max + 1)
    tau = jnp.where(byz, tau_byz, honest_tau).astype(jnp.int32)
    cfg = AsyncConfig(
        buffer_m=1, staleness=mode, alpha=alpha, cutoff=cutoff
    )
    mask, w = cfg.staleness_mask_weights(tau, jnp.ones(k, bool))
    return mask, w, tau


def search_cell_staleness(
    agg: Aggregator,
    trials_updates: jnp.ndarray,
    f: int,
    *,
    mode: str = "polynomial",
    alpha: float = 0.5,
    tau_max: int = 3,
    tau_byz: int = 0,
    cutoff: Optional[int] = None,
    ctx: Optional[dict] = None,
    grids: Optional[dict] = None,
    use_jit: bool = False,
    cell_label: Optional[str] = None,
) -> Dict[str, Any]:
    """Worst-case deviation search for one (aggregator, f) cell under
    buffered-async staleness weighting (see the section comment above).

    The honest rows of every trial are pre-scaled by their normalized
    staleness weights — the matrix the async server aggregates — and the
    standard five-template adaptive search runs on it (byzantine rows are
    rewritten by the templates, i.e. the weight-compensating adversary).
    The resilience reference (honest mean / max honest deviation) is
    likewise computed on the weighted honest rows: that is the step an
    honest-only staleness-weighted server would have taken. Returns the
    ``search_cell`` result dict plus the scenario fields."""
    if trials_updates.ndim == 2:
        trials_updates = trials_updates[None]
    k = trials_updates.shape[1]
    mask, w, tau = staleness_row_weights(
        k, f, mode=mode, alpha=alpha, tau_max=tau_max, tau_byz=tau_byz,
        cutoff=cutoff,
    )
    weighted = trials_updates * w[None, :, None]
    part = None if bool(jnp.all(mask)) else mask
    out = search_cell(
        agg, weighted, f, ctx=ctx, grids=grids, part_mask=part,
        use_jit=use_jit,
        cell_label=cell_label or f"f{f}/k{k}/tau{tau_byz}",
    )
    out["staleness"] = {
        "mode": mode,
        "alpha": alpha,
        "tau_max": int(tau_max),
        "tau_byz": int(tau_byz),
        **({"cutoff": int(cutoff)} if cutoff is not None else {}),
        "weight_byz": float(w[0]) if f > 0 else None,
        "weight_min": float(jnp.min(jnp.where(mask, w, jnp.inf))),
    }
    return out


def synthetic_honest(
    key: jax.Array, trials: int, k: int, d: int,
    center_scale: float = 2.0, spread: float = 1.0,
) -> jnp.ndarray:
    """``[T, K, D]`` synthetic honest update draws: a shared per-trial
    center of norm ~``center_scale`` plus iid per-row noise of norm
    ~``spread`` — so the max honest deviation ``rho`` is ~``spread`` and
    scale-sensitive defenses (clipping radii, norm filters) can be
    instantiated against a known scale (docs/robustness.md)."""
    kc, ku = jax.random.split(key)
    centers = center_scale * jax.random.normal(kc, (trials, 1, d)) / np.sqrt(d)
    noise = spread * jax.random.normal(ku, (trials, k, d)) / np.sqrt(d)
    return (centers + noise).astype(jnp.float32)
