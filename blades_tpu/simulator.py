"""Simulator: the public orchestrator, reference-API compatible.

Reference: ``Simulator`` (``src/blades/simulator.py:21-457``). Construction
surface (``__init__`` kwargs incl. strict unknown-kwarg error,
``simulator.py:84-88``), ``run()`` signature (``simulator.py:364-377``),
``get_clients`` / ``set_trusted_clients`` / ``register_attackers``
(``simulator.py:138-187``) are all preserved. Ray-era knobs
(``num_actors``, ``num_trainers``, ``gpu_per_actor``, ``mode``, ``use_cuda``)
are accepted and ignored with a debug note — parallelism here comes from the
device mesh, not actor counts.

Execution: rounds run through :class:`blades_tpu.core.RoundEngine` — one
jitted XLA program per round (SURVEY.md section 7), sharded over a
``jax.sharding.Mesh`` when more than one device is visible.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from blades_tpu.aggregators import get_aggregator
from blades_tpu.asyncfl import AsyncConfig
from blades_tpu.attackers import ATTACKS, get_attack
from blades_tpu.attackers.base import Attack, NoAttack
from blades_tpu.audit.monitor import AuditMonitor
from blades_tpu.client import BladesClient, ByzantineClient
from blades_tpu.core import ClientOptSpec, RoundEngine, ServerOptSpec
from blades_tpu.core.engine import multistep_lr
from blades_tpu.datasets.base import BaseDataset
from blades_tpu.datasets.fl import FLDataset
from blades_tpu.faults import FaultModel
from blades_tpu.models.common import ModelSpec, build_fns
from blades_tpu.parallel.mesh import auto_mesh_shape, make_mesh, make_plan
from blades_tpu.server import BladesServer
from blades_tpu.supervision import heartbeat as _heartbeat
from blades_tpu.telemetry import Recorder, install_jax_monitoring, set_recorder
from blades_tpu.telemetry import alerts as _alerts
from blades_tpu.telemetry import timeline as _timeline
from blades_tpu.telemetry import context as _context
from blades_tpu.telemetry import ledger as _ledger
from blades_tpu.telemetry import profiling as _profiling
from blades_tpu.telemetry import programs as _programs
from blades_tpu.telemetry.metric_pack import pack_to_fields
from blades_tpu.utils.checkpoint import checkpoint_file, restore_state, save_state
from blades_tpu.utils.logging import initialize_logger
from blades_tpu.utils.metrics import top1_accuracy

_IGNORED_KWARGS = ("num_actors", "num_trainers", "gpu_per_actor", "mode", "use_cuda")


class SupervisorTermination(BaseException):
    """Raised in the round loop when the run supervisor SIGTERMs a
    supervised run — derives from ``BaseException`` (like
    ``KeyboardInterrupt``) so application-level ``except Exception``
    handlers cannot swallow the shutdown, while the run loop's crash
    autosave still fires before the process dies
    (``blades_tpu/supervision``, docs/robustness.md)."""


class _CompositeAttack(Attack):
    """Applies each registered custom attacker's hooks to its own rows:
    omniscient hooks rewrite that client's rows of the update matrix, and
    batch/grad hooks dispatch per client via ``lax.switch`` on a static
    client->attack table — a mixed population (e.g. one labelflipping and one
    signflipping attacker) runs each client's own transform, matching the
    reference's per-object hook dispatch (``client.py:231-253``,
    ``simulator.py:167-187``)."""

    def __init__(self, entries):
        # entries: list of (client_index, ByzantineClient); attacks built
        # once — they may carry construction-time hyperparameters
        self.entries = entries
        self._attacks = [c.make_attack() for _, c in entries]
        self.trains_dishonestly = any(
            a is not None and a.trains_dishonestly for a in self._attacks
        )
        # dishonest-attack dispatch table: branch 0 = identity; distinct
        # dishonest Attack objects get branches 1..n; each registered client
        # index maps to its attack's branch
        self._branches = []
        branch_of = {}
        self._idx_to_branch = {}
        for (idx, _), a in zip(entries, self._attacks):
            if a is None or not a.trains_dishonestly:
                continue
            if id(a) not in branch_of:
                self._branches.append(a)
                branch_of[id(a)] = len(self._branches)
            self._idx_to_branch[idx] = branch_of[id(a)]

    def init_state(self, num_clients, dim):
        # also materialize the [K] branch table now that K is known
        table = np.zeros(num_clients, np.int32)
        for idx, b in self._idx_to_branch.items():
            table[idx] = b
        self._branch_table = jnp.asarray(table)
        return tuple(
            (a.init_state(num_clients, dim) if a is not None else ())
            for a in self._attacks
        )

    def on_batch(self, x, y, is_byz, *, num_classes, key, client_idx=None):
        if not self._branches or client_idx is None:
            return x, y
        branches = [lambda x_, y_: (x_, y_)] + [
            (
                lambda a: lambda x_, y_: a.on_batch(
                    x_, y_, is_byz, num_classes=num_classes, key=key
                )
            )(a)
            for a in self._branches
        ]
        return jax.lax.switch(self._branch_table[client_idx], branches, x, y)

    def on_grads(self, grads, is_byz, client_idx=None):
        if not self._branches or client_idx is None:
            return grads
        branches = [lambda g: g] + [
            (lambda a: lambda g: a.on_grads(g, is_byz))(a)
            for a in self._branches
        ]
        return jax.lax.switch(self._branch_table[client_idx], branches, grads)

    def on_updates(self, updates, byz_mask, key, state=()):
        # Reference semantics (``simulator.py:239-241`` +
        # ``alieclient.py:27-31``): every omniscient callback excludes the
        # FULL byzantine population from its honest statistics and reads the
        # clients' uploaded (pre-attack) updates — so each attacker here sees
        # the pre-attack snapshot with the engine's full ``byz_mask``, never
        # a one-hot submask, and never another attacker's corruption. Each
        # attacker then writes only its own row of the output.
        pre = updates
        out = updates
        new_states = []
        for (idx, client), st in zip(self.entries, state):
            rewritten, st = client.omniscient_callback(pre, byz_mask, key, st)
            out = out.at[idx].set(rewritten[idx])
            new_states.append(st)
        return out, tuple(new_states)


class Simulator:
    def __init__(
        self,
        dataset: Union[BaseDataset, FLDataset],
        num_byzantine: Optional[int] = 0,
        attack: Optional[str] = None,
        attack_kws: Optional[Dict] = None,
        aggregator: Union[str, Callable] = "mean",
        aggregator_kws: Optional[Dict] = None,
        log_path: str = "./outputs",
        metrics: Optional[dict] = None,
        seed: Optional[int] = None,
        mesh_shape: Optional[tuple] = None,
        num_actors: Optional[int] = 1,
        num_trainers: Optional[int] = 1,
        gpu_per_actor: Optional[float] = 0,
        mode: Optional[str] = "actor",
        use_cuda: Optional[bool] = False,
        **kwargs,
    ):
        if kwargs:
            # parity: strict unknown-kwarg error (simulator.py:84-88)
            unknown = ", ".join(kwargs)
            raise RuntimeError(f"Unknown keyword argument(s): {unknown}")

        self.aggregator = get_aggregator(aggregator, **(aggregator_kws or {}))

        if isinstance(dataset, FLDataset):
            self.dataset = dataset
            self._num_classes = int(jnp.max(dataset.test_y)) + 1
            self._train_bs = 32
        else:
            self.dataset = dataset.get_dls()
            self._num_classes = dataset.num_classes
            self._train_bs = dataset.train_bs

        self.seed = 0 if seed is None else int(seed)
        self.num_byzantine = int(num_byzantine) if attack is not None else 0

        # attack resolution, with auto-filled population hyperparams the
        # reference makes callers pass by hand (e.g. ALIE's num_clients)
        attack_kws = dict(attack_kws or {})
        k = self.dataset.num_clients
        if attack == "alie":
            attack_kws.setdefault("num_clients", k)
            attack_kws.setdefault("num_byzantine", self.num_byzantine)
        if attack == "labelflipping":
            attack_kws.setdefault("num_classes", self._num_classes)
        self.attack = get_attack(attack, **attack_kws)

        initialize_logger(log_path)
        self.log_path = log_path
        # replaced by run() with a file-backed recorder (telemetry.jsonl in
        # the log dir) unless BLADES_TELEMETRY=0
        self.telemetry = Recorder(enabled=False)
        self.metrics = {"top1": top1_accuracy} if metrics is None else metrics
        self.json_logger = logging.getLogger("stats")
        self.debug_logger = logging.getLogger("debug")
        self.debug_logger.info(self.__str__())

        # client handles: first num_byzantine ids are byzantine
        # (simulator.py:118-133)
        self._clients: Dict = {}
        for i, u in enumerate(self.dataset.get_clients()):
            if i < self.num_byzantine:
                self._clients[u] = ByzantineClient(id=u, attack=self.attack)
            else:
                self._clients[u] = BladesClient(id=u)

        # device mesh: shard whenever >1 device is visible
        devices = jax.devices()
        if len(devices) > 1 or mesh_shape is not None:
            if mesh_shape is None:
                mesh_shape = auto_mesh_shape(len(devices), k)
            self.plan = make_plan(make_mesh(devices, mesh_shape))
            if hasattr(self.dataset, "place"):
                # shard the client data store + sampler outputs over the
                # clients axis so rounds start with data already laid out
                self.dataset.place(self.plan.clients)
        else:
            self.plan = None

        self._custom_attack_entries: List = []
        self.server: Optional[BladesServer] = None
        self.engine: Optional[RoundEngine] = None
        for name in _IGNORED_KWARGS:
            val = locals().get(name)
            if val not in (None, 0, 1, "actor", False, 0.0):
                self.debug_logger.info(
                    f"note: {name}={val!r} is a Ray-era knob; parallelism "
                    "comes from the device mesh here and the value is ignored."
                )

    def __str__(self) -> str:
        return (
            f"Simulator(num_clients={self.dataset.num_clients}, "
            f"num_byzantine={self.num_byzantine}, attack={self.attack!r}, "
            f"aggregator={self.aggregator!r})"
        )

    # -- reference API --------------------------------------------------------

    def get_clients(self) -> List[BladesClient]:
        return list(self._clients.values())

    def set_trusted_clients(self, ids: List) -> None:
        """Mark client ids trusted (FLTrust bootstrap; reference
        ``simulator.py:143-151``)."""
        for u in ids:
            self._clients[u].trust()

    def register_attackers(self, clients: List[ByzantineClient]) -> None:
        """Replace the first ``len(clients)`` clients with custom attackers
        (reference ``simulator.py:167-187``). Call before :meth:`run`."""
        users = list(self._clients.keys())
        if len(clients) > len(users):
            raise ValueError("more attackers than clients")
        self._custom_attack_entries = []
        for i, c in enumerate(clients):
            c._id = users[i]
            self._clients[users[i]] = c
            self._custom_attack_entries.append((i, c))
        self.num_byzantine = max(self.num_byzantine, len(clients))

    # -- run ------------------------------------------------------------------

    @staticmethod
    def _resolve_schedule(sched, lr0: float) -> Callable[[int], float]:
        if sched is None:
            return lambda r: lr0
        if callable(sched):
            return sched
        if isinstance(sched, dict):
            return multistep_lr(lr0, sched.get("milestones", ()), sched.get("gamma", 0.5))
        raise TypeError(f"bad lr scheduler {sched!r}")

    @staticmethod
    def _resolve_opt(opt, cls):
        if isinstance(opt, cls):
            return opt
        if isinstance(opt, str):
            name = opt.lower()
            if name in ("sgd", "adam"):
                return cls(name=name)
        raise ValueError(f"Unsupported optimizer {opt!r} (use 'SGD', 'Adam', or a spec)")

    def run(
        self,
        model,
        server_optimizer: Union[str, ServerOptSpec] = "SGD",
        client_optimizer: Union[str, ClientOptSpec] = "SGD",
        loss: Optional[str] = "crossentropy",
        global_rounds: Optional[int] = 1,
        local_steps: Optional[int] = 1,
        validate_interval: Optional[int] = 1,
        test_batch_size: Optional[int] = 64,
        server_lr: Optional[float] = 0.1,
        client_lr: Optional[float] = 0.1,
        server_lr_scheduler=None,
        client_lr_scheduler=None,
        train_batch_size: Optional[int] = None,
        retain_updates: bool = False,
        checkpoint_path: Optional[str] = None,
        checkpoint_interval: int = 0,
        resume: bool = False,
        profile_dir: Optional[str] = None,
        client_chunks: int = 1,
        remat: bool = False,
        compute_dtype: Optional[str] = None,
        on_round_end: Optional[Callable] = None,
        donate_batches: bool = False,
        collect_diagnostics: Optional[bool] = None,
        fault_model: Optional[Union[FaultModel, Dict]] = None,
        audit_monitor: Optional[Union[AuditMonitor, Dict]] = None,
        block_size: int = 1,
        streaming: bool = False,
        round_metrics: Optional[bool] = None,
        async_config: Optional[Union[AsyncConfig, Dict]] = None,
        engine_cache=None,
    ) -> List[float]:
        """Run adversarial training; returns per-round wall times (reference
        ``run`` contract, ``simulator.py:364-457``).

        ``model``: a flax module, a :class:`ModelSpec`, or a registry name.
        ``retain_updates``: copy each round's update rows onto the client
        handles (host transfer; off by default — it is pure observability).
        ``checkpoint_path``/``checkpoint_interval``/``resume``: save the full
        round state every N rounds and resume bit-exactly (absent in the
        reference, SURVEY.md section 5). ``profile_dir``: capture a
        ``jax.profiler`` trace of a ~3-round window starting at the first
        post-compile round of this run (round 2, or the resume round).
        ``client_chunks``/``remat``: HBM control for large populations (see
        RoundEngine). ``compute_dtype``: ``'bfloat16'`` for mixed-precision
        forward/backward (master weights stay float32).
        ``donate_batches``: donate each round's sampled batch buffers to
        the round program (safe with the built-in datasets, whose jitted
        sampler returns fresh arrays every round; leave off for a custom
        dataset that caches and re-serves batch arrays).
        ``collect_diagnostics``: trace the aggregator's forensic pytree
        (Krum selections, trim masks, trust scores) into the round program
        and log per-round ``defense`` records to the telemetry trace;
        default: the ``BLADES_TELEMETRY_DIAG=1`` env knob.
        ``fault_model``: a :class:`blades_tpu.faults.FaultModel` (or a
        kwargs dict for one) injecting system faults — client dropout,
        stale straggler replays, NaN/Inf/bit-flip payload corruption — into
        every round; aggregation runs mask-aware over the participating
        subset, per-round fault/exclusion counts land in the telemetry
        trace (``faults`` records), and any mid-run exception
        auto-checkpoints the state (to ``checkpoint_path``, or
        ``<log_path>/autosave`` when none is set) so ``resume=True``
        restarts bit-exactly. See ``docs/robustness.md``.
        ``audit_monitor``: a :class:`blades_tpu.audit.AuditMonitor` (or a
        kwargs dict for one) tracing per-round robustness certificates —
        aggregate inside the participants' pairwise-distance envelope and
        within a ball of the coordinate-wise median — into the same jitted
        round program (zero extra compiles), with an optional stateless
        ``fallback_aggregator`` swapped in for any breached round.
        Per-round certificate/fallback forensics land in the telemetry
        trace as ``audit`` records (``docs/observability.md``); breach ->
        fallback rounds are bit-reproducible under a fixed seed, including
        across kill/resume.
        ``block_size``: execute rounds in blocks of this many per XLA
        launch (``RoundEngine.run_block``): the dataset's sampler is fused
        into the round program and ``lax.scan`` carries the full round
        state across the block, so the per-round host floor (sampler
        launch, dispatch, blocking metrics fetch, telemetry flush,
        heartbeat) is paid once per block. An R-round block is bit-exact
        against R sequential rounds (tested), so this is a pure scheduling
        choice — but eval / checkpoint / telemetry flush / heartbeat move
        to block boundaries (per-round ``train``/``variance``/telemetry
        records are still emitted, unstacked from the block's ``[R]``
        outputs), and autosave/checkpoint states land on block boundaries
        (resume stays bit-exact; a remainder block handles
        ``rounds % block_size``, so at most 2 block programs compile).
        Falls back to per-round execution (with a debug note) when
        ``retain_updates``/``on_round_end`` need per-round host visibility
        or the dataset has no traceable sampler.
        ``streaming``: chunk-SCAN the round (``RoundEngine`` with
        ``streaming=True``) — the aggregation consumes ``[chunk, D]``
        update slabs through the registry's streaming reduction protocol
        and the dense ``[K, D]`` matrix is never materialized, so peak
        update memory is ``client_chunks``-independent of K (the large-K
        regime; see docs/performance.md "Memory scaling"). Composes with
        ``block_size`` and with mask/corruption fault models; incompatible
        with ``retain_updates``/``on_round_end`` (they read the matrix
        streaming never builds — raises) and with aggregators/attacks
        documented as dense-only (the engine raises at build, naming the
        reason). Per-run ``engine.peak_update_bytes`` /
        ``engine.client_chunks`` / ``engine.chunk_size`` /
        ``engine.streaming`` gauges ride every telemetry round record.
        ``round_metrics``: trace a fixed-shape in-graph
        :class:`~blades_tpu.telemetry.metric_pack.MetricPack` (update-norm
        quantiles/histogram, honest-vs-byzantine cosine-to-aggregate,
        mask/exclusion counts, per-chunk slab extremes) into the round
        body and log one ``metrics`` telemetry record per round — the
        per-round visibility that survives ``block_size>1`` and
        ``streaming=True`` fusion (the pack rides the scans as stacked
        outputs and is unstacked here). Default: the
        ``BLADES_ROUND_METRICS=1`` env knob; off compiles the exact
        pre-metrics program.
        ``async_config``: a :class:`blades_tpu.asyncfl.AsyncConfig` (or a
        kwargs dict for one — its ``arrivals`` entry may itself be an
        :class:`~blades_tpu.asyncfl.ArrivalProcess` kwargs dict) switching
        the run to **buffered-asynchronous** (FedBuff-style) rounds:
        clients arrive on a seeded fixed-shape schedule, train against the
        model version they downloaded, and each round the server
        aggregates the buffered first-``buffer_m`` arrivals with
        staleness-weighted rows — still one jitted program per round
        (``docs/robustness.md`` "Asynchronous scenarios"). Composes with
        ``block_size``, fault models (dropout/corruption; stragglers are
        replaced by real staleness and raise), the audit monitor (the
        certificates run over the staleness-weighted buffer), and
        crash-autosave/bit-exact resume (the buffer rides the checkpoint).
        Incompatible with ``streaming=True``. One ``async`` telemetry
        record per round (arrivals, buffer fill, fire flag, staleness
        moments; ``docs/observability.md``).

        Telemetry (``docs/observability.md``): unless ``BLADES_TELEMETRY=0``,
        a span/counter trace of the run is appended to
        ``<log_path>/telemetry.jsonl`` — per-round span tree (sample /
        dispatch / sync / eval / checkpoint), XLA compile + persistent-cache
        accounting, and defense forensics — flushed once per round.
        Summarize with ``python scripts/trace_summary.py``. The first
        round (or block) additionally records a measured program profile
        (XLA cost-model flops / bytes accessed and, where the backend
        exposes it, the compiled temp/argument/output buffer budget) as a
        ``memory`` record next to the analytical
        ``engine.peak_update_bytes`` gauge, and device allocator
        watermarks land as ``mem.*`` gauges at every flush point on
        backends that report them (``blades_tpu/telemetry/profiling.py``;
        ``BLADES_PROGRAM_PROFILE=0`` disables the per-program record).
        ``BLADES_PROFILE`` (alias ``BLADES_TELEMETRY_PROFILE_DIR``) is an
        env knob for ``profile_dir`` (a guarded ~3-round ``jax.profiler``
        capture that degrades to a recorded no-op where tracing is
        unavailable) for real-TPU windows.

        Supervision (``docs/robustness.md``): under the run supervisor
        (``python -m blades_tpu.supervision -- ...``) the loop touches the
        ``BLADES_HEARTBEAT_FILE`` liveness file at every round flush,
        honors ``BLADES_RESUME=1`` as ``resume=True`` (so a relaunch
        continues from the crash autosave), and converts the supervisor's
        SIGTERM into :class:`SupervisorTermination` so the crash autosave
        fires before the process group is reaped.
        """
        from blades_tpu.utils.xla_cache import enable_compilation_cache

        enable_compilation_cache()
        # supervised relaunches resume without the caller threading the
        # flag through (the supervisor restarts the same command line)
        resume = resume or os.environ.get(_heartbeat.RESUME_ENV) == "1"
        if collect_diagnostics is None:
            collect_diagnostics = os.environ.get("BLADES_TELEMETRY_DIAG") == "1"
        if round_metrics is None:
            round_metrics = os.environ.get("BLADES_ROUND_METRICS") == "1"
        profile_dir = profile_dir or _profiling.profile_dir_from_env()
        if isinstance(fault_model, dict):
            fault_model = FaultModel(**fault_model)
        if isinstance(audit_monitor, dict):
            audit_monitor = AuditMonitor(**audit_monitor)
        if isinstance(async_config, dict):
            async_config = AsyncConfig(**async_config)
        # validate BEFORE any process-wide state changes below (the
        # supervised SIGTERM handler install): a config error must raise
        # clean, not leak a signal handler to a caller that catches it
        if streaming and (retain_updates or on_round_end is not None):
            raise ValueError(
                "streaming=True never materializes the [K, D] update matrix "
                "that retain_updates/on_round_end read; run dense for those"
            )
        # run identity (telemetry/context.py): a fresh top-level run mints
        # a new run_id (and exports it, so subprocesses correlate); a
        # supervised relaunch inherits the supervisor's id + attempt — all
        # attempts of one supervised run stitch under one id
        _context.activate(fresh=True)
        # canonical config -> stable fingerprint: "same experiment,
        # different run" becomes a string equality in the ledger/trace
        # (trace_summary --compare refuses to silently diff unrelated runs)
        run_config = {
            "kind": "simulator",
            "num_clients": self.dataset.num_clients,
            "num_byzantine": self.num_byzantine,
            "attack": repr(self.attack),
            "aggregator": repr(self.aggregator),
            "seed": self.seed,
            "model": model if isinstance(model, str) else type(model).__name__,
            "global_rounds": global_rounds,
            "local_steps": local_steps,
            "train_batch_size": train_batch_size or self._train_bs,
            "client_lr": client_lr,
            "server_lr": server_lr,
            "client_chunks": client_chunks,
            "block_size": block_size,
            "streaming": streaming,
            **({"fault_model": repr(fault_model)} if fault_model else {}),
            **(
                {"async_config": repr(async_config)}
                if async_config is not None
                else {}
            ),
        }
        config_fp = _ledger.config_fingerprint(run_config)
        trace_path = os.path.join(self.log_path, "telemetry.jsonl")
        # the log-dir wipe preserves the trace for kill -> relaunch
        # post-mortems, but a FRESH unsupervised run is a NEW experiment:
        # starting a new trace keeps per-run consumers (trace_summary,
        # chaos invariant checks) from double-counting a previous run's
        # records. Supervised attempt 1 must NOT truncate — the supervisor
        # already appended its launch record there.
        if not resume and os.environ.get(_heartbeat.SUPERVISED_ENV) != "1":
            try:
                os.unlink(trace_path)
            except OSError:
                pass
        rec = Recorder(
            path=trace_path,
            meta={
                "run": "simulator",
                "config_fingerprint": config_fp,
                "num_clients": self.dataset.num_clients,
                "num_byzantine": self.num_byzantine,
                "attack": repr(self.attack),
                "aggregator": repr(self.aggregator),
                "global_rounds": global_rounds,
                "local_steps": local_steps,
                **(
                    {"fault_model": repr(fault_model)}
                    if fault_model is not None
                    else {}
                ),
                **(
                    {"audit_monitor": repr(audit_monitor)}
                    if audit_monitor is not None
                    else {}
                ),
                **(
                    {"async_config": repr(async_config)}
                    if async_config is not None
                    else {}
                ),
            },
        )
        self.telemetry = rec
        set_recorder(rec)  # engine spans + jax compile events land here
        install_jax_monitoring()
        # dispatch accounting (telemetry/timeline.py): a previous run's
        # unemitted launch splits must not leak into this run's round 1
        _timeline.reset()
        # anomaly alerting (telemetry/alerts.py): rule evaluation rides the
        # records the run already emits at the existing flush cadence; a
        # critical alert (non-finite/diverging loss) can recycle a
        # supervised run via BLADES_ALERT_FILE. No-op when telemetry is off.
        self.alert_engine = _alerts.install(rec)
        # create the trace file (meta record) NOW: a run killed mid-compile
        # — the documented tunnel-hang scenario — must still leave a trace
        # to post-mortem, not depend on surviving to the first round flush
        rec.flush()
        # run ledger (telemetry/ledger.py): one `started` record now, one
        # terminal record on the way out — the run is addressable in
        # results/ledger.jsonl whatever happens next
        ledger_entry = _ledger.run_started(
            "simulator", config=run_config, artifacts=[trace_path],
        )
        # the build/warm-up span (model spec, engine construction,
        # checkpoint restore, eval warm-up) is the documented cold-
        # compile crash/hang window; it precedes the round loop's own
        # handlers, so it needs its own terminal-ledger protection —
        # a run killed mid-compile must not stay 'open' forever
        try:
            # compile provenance: model-spec build + param init dispatch a
            # long tail of tiny eager-op compiles — attribute them to one
            # "model init" program instead of the unattributed bucket
            # (they are real build cost the tiling invariant must cover)
            with _programs.watch(
                f"model/{model if isinstance(model, str) else 'custom'}/init",
                shapes=tuple(self.dataset.train_x.shape[2:]),
            ):
                spec = self._model_spec(model, loss, compute_dtype)
                batch_size = train_batch_size or self._train_bs

                key = jax.random.PRNGKey(self.seed)
                params = spec.init(jax.random.fold_in(key, 17))

                trusted = jnp.asarray(
                    [c.is_trusted() for c in self.get_clients()], dtype=bool
                )
            attack = self.attack
            if self._custom_attack_entries:
                attack = _CompositeAttack(self._custom_attack_entries)

            # ONE kwargs dict feeds both the RoundEngine constructor and
            # the cache fingerprint below: a future constructor arg that
            # changes the program shape cannot drift out of the key.
            engine_kwargs = dict(
                num_clients=self.dataset.num_clients,
                num_byzantine=self.num_byzantine,
                attack=attack,
                aggregator=self.aggregator,
                client_opt=self._resolve_opt(client_optimizer, ClientOptSpec),
                server_opt=self._resolve_opt(server_optimizer, ServerOptSpec),
                num_classes=self._num_classes,
                trusted_mask=trusted,
                plan=self.plan,
                client_chunks=client_chunks,
                remat=remat,
                # the [K, D] matrix only needs to be a program output when
                # someone will read it back (client update views / the
                # on_round_end observability hook, which documents
                # engine.last_updates); otherwise keep it in-graph — an
                # output persists in HBM across rounds
                keep_updates=retain_updates or on_round_end is not None,
                donate_batches=donate_batches,
                collect_diagnostics=collect_diagnostics,
                fault_model=fault_model,
                audit_monitor=audit_monitor,
                streaming=streaming,
                round_metrics=round_metrics,
                async_config=async_config,
            )

            # warm-program reuse (blades_tpu/sweeps.EngineCache): sweep
            # drivers that run many Simulators in one process key the
            # built engine by its program-shape fingerprint — a scenario
            # whose static config matches an earlier one (the chaos
            # NaN<->Inf twin: the corrupt fill is a traced state leaf)
            # reuses the warm compiled round/eval programs instead of
            # paying a fresh trace+compile. Configs whose identity cannot
            # be fingerprinted safely bypass the cache: callable models,
            # composite custom attacks, and any config object carrying a
            # bare callable (closures collapse to their qualname — two
            # different lambdas would collide).
            engine_key = None
            if (
                engine_cache is not None
                and isinstance(model, str)
                and not self._custom_attack_entries
            ):
                from blades_tpu.sweeps import (
                    contains_callables,
                    program_fingerprint,
                    static_fingerprint,
                )

                # the plan by its MESH configuration (axis names, shape,
                # device ids) — device objects themselves are process
                # handles, but two Simulators in one process over the same
                # mesh compile the same sharded program
                plan_fp = None
                if self.plan is not None:
                    mesh = self.plan.clients.mesh
                    plan_fp = {
                        "axis_names": [str(a) for a in mesh.axis_names],
                        "shape": [int(s) for s in mesh.devices.shape],
                        "devices": [int(d.id) for d in mesh.devices.flat],
                    }
                key_parts = {
                    "model": model,
                    "loss": loss,
                    "compute_dtype": str(compute_dtype),
                    **{k: v for k, v in engine_kwargs.items() if k != "plan"},
                    "plan": plan_fp,
                }
                fp_view = static_fingerprint(key_parts)
                if not contains_callables(fp_view):
                    engine_key = program_fingerprint(view=fp_view)
            cached = (
                engine_cache.get(engine_key)
                if engine_key is not None
                else None
            )
            if cached is not None:
                self.engine = cached
                # the per-run swappable surface: the fill value is traced
                # state (faults/model.py), so an equal-PROGRAM fault model
                # with a different fill (the inertness twin) rebinds here
                # and engine.init() below mints ITS state leaves
                self.engine.fault_model = fault_model
                rec.event("engine_cache", hit=1, key=engine_key)
            else:
                t_build = time.perf_counter()
                # compile provenance: constructor-time eager dispatches
                # (unravel builders, mask precomputation) are build cost
                # of THIS engine identity, not unattributed noise
                with _programs.watch(
                    "engine/construct",
                    fingerprint=(
                        f"{engine_key}:construct" if engine_key else None
                    ),
                ):
                    self.engine = RoundEngine(
                        spec.train_loss_fn,
                        spec.eval_logits_fn,
                        params,
                        **engine_kwargs,
                    )
                # compile provenance (telemetry/programs.py): the engine's
                # programs share the EngineCache fingerprint dialect, so a
                # `program` record and a `cache_stats` entry name the same
                # identity
                self.engine.program_fingerprint = engine_key
                if engine_key is not None:
                    engine_cache.put(
                        engine_key, self.engine,
                        build_s=time.perf_counter() - t_build,
                    )
            # memory observability: the round program's peak update-matrix
            # footprint rides every round record as gauges (streaming rounds
            # must show [chunk, D], dense rounds [K, D] — trace_summary.py
            # surfaces the max, so a regression to dense peaks is visible)
            rec.gauge("engine.peak_update_bytes", self.engine.peak_update_bytes)
            rec.gauge("engine.client_chunks", self.engine.client_chunks)
            rec.gauge("engine.chunk_size", self.engine.chunk_size)
            rec.gauge("engine.streaming", int(self.engine.streaming))
            if async_config is not None:
                # async semantics gauges: every round record is
                # self-describing about the buffer threshold in force
                rec.gauge("engine.async", 1)
                rec.gauge("engine.async_buffer_m", self.engine.async_buffer_m)
            # supervised runs: SIGTERM (the supervisor's first escalation step)
            # becomes an in-loop exception so the crash autosave below fires
            # before SIGKILL; main-thread only (signal.signal's constraint).
            # Installed only AFTER every config-validation error can have
            # raised (this call + RoundEngine construction above): a build-time
            # ValueError must never leak the handler process-wide.
            prev_sigterm = None
            if (
                os.environ.get(_heartbeat.SUPERVISED_ENV) == "1"
                and threading.current_thread() is threading.main_thread()
            ):
                def _on_sigterm(signum, frame):
                    raise SupervisorTermination(
                        "SIGTERM from run supervisor"
                    )

                try:
                    prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
                except (ValueError, OSError):
                    prev_sigterm = None
            state = self.engine.init(params)

            # crash-autosave target: the explicit checkpoint path when given,
            # else a fixed path in the log dir — a mid-run exception (OOM, XLA
            # abort, Ctrl-C on a hung compile) must leave a resumable state, not
            # lose hours of rounds
            autosave_path = checkpoint_path or os.path.join(self.log_path, "autosave")

            start_round = 1
            if resume:
                for cand in dict.fromkeys((checkpoint_path, autosave_path)):
                    if cand and os.path.exists(checkpoint_file(cand)):
                        state = self.engine.place_state(restore_state(cand, state))
                        start_round = int(state.round_idx) + 1
                        self.debug_logger.info(
                            f"resumed from {cand} at round {start_round}"
                        )
                        break
            elif checkpoint_path is None:
                # fresh run: invalidate any leftover IMPLICIT crash autosave in
                # this log dir NOW (the recovery-aware log-dir wipe preserves
                # *.npz) — otherwise a supervised relaunch of THIS run
                # (BLADES_RESUME=1) could resume from a previous experiment's
                # stale state if this attempt dies before its first autosave.
                # Never touches a user-configured checkpoint_path.
                try:
                    stale = checkpoint_file(autosave_path)
                    if os.path.exists(stale):
                        os.unlink(stale)
                        self.debug_logger.info(
                            f"fresh run: removed stale crash autosave {stale}"
                        )
                except OSError:
                    pass
            self.server = BladesServer(self.engine, state, self.aggregator)

            client_lr_fn = self._resolve_schedule(client_lr_scheduler, client_lr)
            server_lr_fn = self._resolve_schedule(server_lr_scheduler, server_lr)

            # round-block scheduling: fuse the sampler into the round program and
            # scan block_size rounds per XLA launch (RoundEngine.run_block)
            block_size = max(1, int(block_size))
            sampler = None
            if block_size > 1 and (retain_updates or on_round_end is not None):
                self.debug_logger.info(
                    "block_size>1 disabled: retain_updates/on_round_end need "
                    "per-round host visibility"
                )
                block_size = 1
            if block_size > 1:
                if hasattr(self.dataset, "traceable_sampler"):
                    sampler = self.dataset.traceable_sampler(
                        local_steps, batch_size
                    )
                else:
                    self.debug_logger.info(
                        "block_size>1 disabled: dataset has no traceable_sampler"
                    )
                    block_size = 1

            data_key = jax.random.fold_in(key, 23)
            round_times: List[float] = []
            global_start = time.time()
            # profile a ~3-round window, skipping the round-1 compile when the
            # run is long enough to allow it
            prof_first = min(max(start_round, 2), global_rounds)
            prof_last = min(prof_first + 2, global_rounds)
            trace_active = False
            # eagerly build the eval executable so its first cold compile never
            # lands mid-run (the classic between-heartbeat gap under
            # supervision, and a mid-block stall under round-block scheduling);
            # skipped when this run will never evaluate
            if (global_rounds // validate_interval) * validate_interval >= start_round:
                with rec.span("eval_warmup"):
                    self.engine.warm_eval(
                        state.params,
                        self.dataset.test_x,
                        self.dataset.test_y,
                        batch_size=test_batch_size,
                    )
        except BaseException as e:  # noqa: BLE001 - provenance, then re-raise
            ledger_entry.ended(
                "crashed" if isinstance(e, Exception) else "killed",
                error=f"{type(e).__name__}: {e}"[:300],
                metrics={"rounds_completed": 0},
            )
            raise
        try:
            if block_size > 1:
                self._run_blocks(
                    state=state,
                    rec=rec,
                    sampler=sampler,
                    block_size=block_size,
                    start_round=start_round,
                    global_rounds=global_rounds,
                    local_steps=local_steps,
                    validate_interval=validate_interval,
                    test_batch_size=test_batch_size,
                    checkpoint_path=checkpoint_path,
                    checkpoint_interval=checkpoint_interval,
                    client_lr_fn=client_lr_fn,
                    server_lr_fn=server_lr_fn,
                    data_key=data_key,
                    key=key,
                    round_times=round_times,
                    global_start=global_start,
                    profile_dir=profile_dir,
                    prof_first=prof_first,
                    prof_last=prof_last,
                )
                state = self.server.state
            else:
                for rnd in range(start_round, global_rounds + 1):
                    if profile_dir and rnd == prof_first:
                        # guarded capture: degrades to a recorded no-op on
                        # backends/attachment modes without profiler support
                        trace_active = _profiling.start_capture(
                            profile_dir, rec
                        )
                    round_start = time.time()
                    with rec.span("round"):
                        with rec.span("sample"):
                            cx, cy = self.dataset.sample_round(
                                jax.random.fold_in(data_key, rnd), local_steps,
                                batch_size,
                            )
                        c_lr = client_lr_fn(rnd - 1)
                        s_lr = server_lr_fn(rnd - 1)
                        # emits the nested round/dispatch span
                        state, m = self.engine.run_round(state, cx, cy, c_lr, s_lr, key)
                        self.server.state = state

                        with rec.span("sync"):
                            # device execution of the async round program lands
                            # here (log_train's float() conversions used to
                            # absorb it)
                            jax.block_until_ready(m)
                        # close the dispatch-accounting window: ready time
                        # is measured from dispatch-return to here (NOT the
                        # bare block call) — on the 1-core box the executor
                        # preempts the host thread, so execution wall lands
                        # on whatever host line runs next, and only the
                        # full enqueue->blocked window attributes it
                        # honestly to the device side
                        _timeline.launch_ready()
                        self.log_train(rnd, local_steps, m)
                        self.log_variance(rnd, m)
                        self._log_defense(rnd)
                        self._log_faults(rnd)
                        self._log_audit(rnd)
                        self._log_metrics(rnd)
                        self._log_async(rnd)
                        if rnd == start_round:
                            # one measured program profile per run: XLA
                            # cost/memory analysis of the exact compiled
                            # round program (cache-hit compile; `memory`
                            # record next to the analytical
                            # engine.peak_update_bytes gauge)
                            with rec.span("program_profile"), \
                                    _programs.watch("profiling/round"):
                                _profiling.record_program_profile(
                                    "round", self.engine._round_jit,
                                    state, cx, cy,
                                    jnp.asarray(c_lr, jnp.float32),
                                    jnp.asarray(s_lr, jnp.float32),
                                    key, rec=rec,
                                )
                        if retain_updates:
                            # populate reference-parity client.get_update() views
                            for i, c in enumerate(self.get_clients()):
                                c.save_update(self.engine.last_updates[i])
                        if on_round_end is not None:
                            # observability hook: (round, state, metrics); the
                            # round's post-attack update matrix is
                            # engine.last_updates
                            on_round_end(rnd, state, m)

                        if rnd % validate_interval == 0:
                            with rec.span("eval"):
                                ev = self.evaluate(rnd, test_batch_size)
                            self.debug_logger.info(
                                f"Test global round {rnd}, loss: {ev['Loss']}, "
                                f"top1: {ev['top1']}"
                            )

                        if trace_active and rnd == prof_last:
                            jax.block_until_ready(state.params)
                            _profiling.stop_capture(profile_dir, rec)
                            trace_active = False
                        if (
                            checkpoint_path
                            and checkpoint_interval
                            and rnd % checkpoint_interval == 0
                        ):
                            with rec.span("checkpoint"):
                                save_state(checkpoint_path, state)

                    wall = time.time() - round_start
                    round_times.append(wall)
                    # measured allocator watermarks (no-op on backends
                    # without memory_stats) ride the round record's gauges
                    _profiling.record_live_bytes(rec)
                    # dispatch accounting: one aggregated `timeline` record
                    # per launch kind, joining this round's single flush
                    _timeline.emit(rec, round_idx=rnd)
                    # per-round summary + the round's single buffered trace write
                    rec.round_record(
                        rnd,
                        wall_s=wall,
                        train_loss=float(m.train_loss),
                        train_top1=float(m.train_top1),
                    )
                    rec.flush()
                    # supervised runs: liveness beat piggybacked on the round
                    # flush (no-op when BLADES_HEARTBEAT_FILE is unset)
                    _heartbeat.beat(round_idx=rnd)
                    self.debug_logger.info(
                        f"E={rnd}; Client learning rate = {c_lr}; "
                        f"Time cost = {time.time() - global_start}"
                    )
            # the run completed: a leftover CRASH autosave (implicit path
            # only — never a user-configured checkpoint) is now stale, and
            # a later resume=True must not silently re-train from it
            if checkpoint_path is None:
                try:
                    stale = checkpoint_file(autosave_path)
                    if os.path.exists(stale):
                        os.unlink(stale)
                        self.debug_logger.info(
                            f"run complete: removed stale crash autosave {stale}"
                        )
                except OSError:
                    pass
        except BaseException as e:  # noqa: BLE001 - incl. KeyboardInterrupt
            # auto-checkpoint on ANY mid-run failure: `self.server.state` is
            # the last fully completed round's (or block's) state — both
            # loops assign it only after the round/block program returns —
            # so the save is always consistent. Best-effort — a poisoned
            # device buffer must not mask the original exception with a
            # save error.
            crash_state = self.server.state
            try:
                with rec.span("crash_checkpoint"):
                    save_state(autosave_path, crash_state)
                rec.event(
                    "crash_checkpoint",
                    path=checkpoint_file(autosave_path),
                    round=int(crash_state.round_idx),
                    error=f"{type(e).__name__}: {e}"[:300],
                )
                self.debug_logger.info(
                    f"crash at round {len(round_times) + start_round}: state "
                    f"auto-checkpointed to {checkpoint_file(autosave_path)}; "
                    "rerun with resume=True to continue bit-exactly"
                )
            except Exception as save_err:  # noqa: BLE001
                rec.event("crash_checkpoint_failed", error=str(save_err)[:300])
            # outcome vocabulary: a real error is `crashed`; an interrupt
            # or termination (KeyboardInterrupt, SupervisorTermination,
            # SystemExit — BaseExceptions, not Exceptions) is `killed`,
            # so runs.py can tell a buggy run from an aborted one
            ledger_entry.ended(
                "crashed" if isinstance(e, Exception) else "killed",
                error=f"{type(e).__name__}: {e}"[:300],
                metrics={"rounds_completed": len(round_times)},
            )
            raise
        finally:
            # also reached when a round raises (OOM, XLA abort, Ctrl-C on a
            # hung compile): whatever was recorded up to the failure reaches
            # the trace. run_end terminates this run's records — anything
            # after it is ambient post-run activity (the jax.monitoring
            # listeners stay installed for the life of the process).
            rec.event("run_end", rounds_completed=len(round_times))
            rec.flush()
            # terminal ledger record; idempotent — a crash/kill above
            # already recorded its outcome and this no-ops
            ledger_entry.ended(
                "finished",
                metrics={
                    "rounds_completed": len(round_times),
                    **(
                        {
                            "rounds_per_sec": round(
                                len(round_times) / sum(round_times), 4
                            )
                        }
                        if round_times and sum(round_times) > 0
                        else {}
                    ),
                },
            )
            if prev_sigterm is not None:
                try:
                    signal.signal(signal.SIGTERM, prev_sigterm)
                except (ValueError, OSError):
                    pass
        return round_times

    def _run_blocks(
        self,
        *,
        state,
        rec,
        sampler,
        block_size,
        start_round,
        global_rounds,
        local_steps,
        validate_interval,
        test_batch_size,
        checkpoint_path,
        checkpoint_interval,
        client_lr_fn,
        server_lr_fn,
        data_key,
        key,
        round_times,
        global_start,
        profile_dir,
        prof_first,
        prof_last,
    ) -> None:
        """Round-block scheduling: execute ``[start_round, global_rounds]``
        in blocks of ``block_size`` rounds per XLA launch
        (``RoundEngine.run_block``), a remainder block absorbing
        ``rounds % block_size`` — at most 2 compiled block programs per
        run. Per-round ``train``/``variance``/``defense``/``faults``/
        ``audit`` records are unstacked from the block's ``[R]`` outputs
        (schema unchanged); eval, checkpoint, the telemetry flush, and the
        supervision heartbeat run once per block, at the boundary — so
        checkpoints/autosaves always hold block-boundary states and resume
        stays bit-exact. Appends per-round amortized wall times to
        ``round_times`` and leaves the final state on ``self.server``."""
        trace_active = False

        def slice_round(tree, i):
            return jax.tree_util.tree_map(lambda a: a[i], tree)

        profiled = False
        rnd = start_round
        while rnd <= global_rounds:
            bs = min(block_size, global_rounds - rnd + 1)
            rounds = range(rnd, rnd + bs)
            if profile_dir and not trace_active and rnd <= prof_first < rnd + bs:
                trace_active = _profiling.start_capture(profile_dir, rec)
            block_start = time.time()
            with rec.span("block", rounds=bs):
                sample_keys = jnp.stack(
                    [jax.random.fold_in(data_key, r) for r in rounds]
                )
                c_lrs = [client_lr_fn(r - 1) for r in rounds]
                s_lrs = [server_lr_fn(r - 1) for r in rounds]
                # emits the nested block/dispatch span
                state, ms, diags = self.engine.run_block(
                    state, sample_keys, c_lrs, s_lrs, key, sampler=sampler
                )
                self.server.state = state
                with rec.span("sync"):
                    # device execution of the whole async block lands here
                    jax.block_until_ready(ms)
                # enqueue-return -> blocked window (see the per-round loop)
                _timeline.launch_ready()
                for i, r in enumerate(rounds):
                    mi = slice_round(ms, i)
                    self.log_train(r, local_steps, mi)
                    self.log_variance(r, mi)
                    if diags["defense"] is not None:
                        self._log_defense(r, diag=slice_round(diags["defense"], i))
                    if diags["faults"] is not None:
                        self._log_faults(r, diag=slice_round(diags["faults"], i))
                    if diags["audit"] is not None:
                        self._log_audit(r, diag=slice_round(diags["audit"], i))
                    if diags["metrics"] is not None:
                        # in-graph MetricPack, unstacked from the block's
                        # [R]-leading scan outputs: per-round records
                        # survive fused execution
                        self._log_metrics(
                            r, pack=slice_round(diags["metrics"], i)
                        )
                    if diags["async"] is not None:
                        self._log_async(r, diag=slice_round(diags["async"], i))

                if not profiled:
                    # one measured program profile per run (the scanned
                    # block program; cache-hit compile, `memory` record)
                    profiled = True
                    with rec.span("program_profile"):
                        _profiling.record_program_profile(
                            "block", self.engine._block_jit,
                            state, sample_keys,
                            jnp.asarray(c_lrs, jnp.float32),
                            jnp.asarray(s_lrs, jnp.float32),
                            key, rec=rec,
                        )

                if any(r % validate_interval == 0 for r in rounds):
                    with rec.span("eval"):
                        ev = self.evaluate(rounds[-1], test_batch_size)
                    self.debug_logger.info(
                        f"Test global round {rounds[-1]}, loss: {ev['Loss']}, "
                        f"top1: {ev['top1']}"
                    )

                if trace_active and rounds[-1] >= prof_last:
                    jax.block_until_ready(state.params)
                    _profiling.stop_capture(profile_dir, rec)
                    trace_active = False
                if (
                    checkpoint_path
                    and checkpoint_interval
                    and any(r % checkpoint_interval == 0 for r in rounds)
                ):
                    with rec.span("checkpoint"):
                        save_state(checkpoint_path, state)

            wall = time.time() - block_start
            # allocator watermarks at the block boundary (the streaming/
            # block flush point) — no-op without backend memory_stats
            _profiling.record_live_bytes(rec)
            # dispatch accounting: one `timeline` record per block
            # boundary, joining the block's single flush below
            _timeline.emit(rec, round_idx=rounds[-1])
            for i, r in enumerate(rounds):
                round_times.append(wall / bs)
                # per-round summaries (amortized wall), ONE buffered trace
                # write per block
                rec.round_record(
                    r,
                    wall_s=wall / bs,
                    train_loss=float(ms.train_loss[i]),
                    train_top1=float(ms.train_top1[i]),
                )
            rec.flush()
            # supervised runs: one liveness beat per block boundary — size
            # the supervisor's --heartbeat-timeout to cover a whole block
            # plus its compile (docs/robustness.md)
            _heartbeat.beat(round_idx=rounds[-1])
            self.debug_logger.info(
                f"E={rounds[0]}-{rounds[-1]}; block={bs}; "
                f"Client learning rate = {c_lrs[-1]}; "
                f"Time cost = {time.time() - global_start}"
            )
            rnd += bs

    def _model_spec(self, model, loss, compute_dtype=None) -> ModelSpec:
        if isinstance(model, ModelSpec):
            if compute_dtype is None:
                return model
            # the caller asked for a build option the prebuilt spec doesn't
            # carry (e.g. pretrained spec + bfloat16): rebuild the pure
            # functions around the same module with the requested options,
            # keeping the spec's init (which may hold pretrained weights) —
            # but only when the spec's fns are stock build_fns products;
            # silently replacing a custom loss/eval fn would train the
            # wrong objective with no error
            if not model.rebuild_ok:
                raise ValueError(
                    "compute_dtype was requested but this ModelSpec carries "
                    "custom train/eval functions that a rebuild would "
                    "discard; build the spec with the desired compute_dtype "
                    "instead (build_fns(..., compute_dtype=...))"
                )
            rebuilt = self._build_spec(model.module, loss, compute_dtype)
            rebuilt.init = model.init
            return rebuilt
        if isinstance(model, str):
            from blades_tpu.models import create_model

            model = create_model(model, num_classes=self._num_classes)
        return self._build_spec(model, loss, compute_dtype)

    def _build_spec(self, module, loss, compute_dtype) -> ModelSpec:
        sample_shape = tuple(self.dataset.train_x.shape[2:])
        # model inputs are whatever the dataset feeds the engine: post-
        # normalize floats for images, raw int token ids for text
        x0 = self.dataset.train_x[:1, :1]
        if self.dataset.normalize is not None:
            x0 = self.dataset.normalize(x0)
        input_dtype = jnp.int32 if jnp.issubdtype(x0.dtype, jnp.integer) else x0.dtype
        return build_fns(
            module,
            sample_shape,
            loss=loss or "crossentropy",
            input_dtype=input_dtype,
            pad_id=getattr(self.dataset, "pad_id", None),
            compute_dtype=jnp.dtype(compute_dtype) if compute_dtype else None,
        )

    # -- logging (stats-file schema parity, simulator.py:309-362) -------------

    def log_train(self, rnd: int, local_steps: int, m) -> None:
        r = {
            "_meta": {"type": "train"},
            "Round": rnd,
            "B": local_steps,
            "Loss": float(m.train_loss),
            "top1": float(m.train_top1),
        }
        self.json_logger.info(r)
        self.debug_logger.info(
            f"[Round{rnd:3d}] Loss: {r['Loss']:.4f} top1={r['top1']:8.4f}"
        )

    def log_variance(self, rnd: int, m) -> None:
        r = {
            "_meta": {"type": "variance"},
            "Round": rnd,
            "avg": float(m.update_variance),
            "norm": float(m.update_variance_norm),
        }
        self.json_logger.info(r)

    def _log_defense(self, rnd: int, diag=None) -> None:
        """Aggregator forensics -> one ``defense`` telemetry record per
        round: the raw diagnostics pytree plus byz-overlap summaries — how
        much of what the defense selected/trimmed/clipped/trusted was
        actually byzantine (ground truth the simulator knows but a real
        deployment would not). ``diag`` overrides the engine's last-round
        pytree (the block loop passes each round's slice of the stacked
        ``[R]`` diagnostics). No reference counterpart: the reference
        records nothing about defense decisions (``simulator.py:244`` just
        applies the aggregate)."""
        diag = self.engine.last_diagnostics if diag is None else diag
        if not diag or not self.telemetry.enabled:
            return
        byz = np.asarray(self.engine.byz_mask)
        fields = {}
        for name, v in diag.items():
            arr = np.asarray(v)
            fields[name] = arr.tolist() if arr.ndim else arr.item()
        overlap = {}
        if "selected" in diag:  # krum/multikrum: fraction of selections byz
            sel = np.asarray(diag["selected"])
            overlap["byz_selected_frac"] = float(byz[sel].mean())
        if "trim_counts" in diag:  # trimmedmean: byz share of trimmed slots
            tc = np.asarray(diag["trim_counts"], dtype=np.float64)
            tot = tc.sum()
            overlap["byz_trim_frac"] = float(tc[byz].sum() / tot) if tot else 0.0
        if "clipped" in diag:  # centeredclipping: who hit the clip radius
            cl = np.asarray(diag["clipped"])
            overlap["byz_clipped_frac"] = (
                float(cl[byz].mean()) if byz.any() else 0.0
            )
            overlap["honest_clipped_frac"] = (
                float(cl[~byz].mean()) if (~byz).any() else 0.0
            )
        if "trust_scores" in diag:  # fltrust: byz share of total trust mass
            ts = np.asarray(diag["trust_scores"], dtype=np.float64)
            tot = ts.sum()
            overlap["byz_trust_frac"] = (
                float(ts[byz].sum() / tot) if tot > 0 else 0.0
            )
        for name, value in overlap.items():
            self.telemetry.gauge(f"defense.{name}", value)
        self.telemetry.event(
            "defense", round=rnd, agg=repr(self.aggregator), **fields, **overlap
        )

    def _log_faults(self, rnd: int, diag=None) -> None:
        """Fault-injection forensics -> one ``faults`` telemetry record per
        round: participants, dropouts, stale replays, expired stragglers,
        corrupted payloads, and non-finite exclusions (``blades_tpu.faults``
        diagnostics; ``diag`` = one round's slice under round-block
        scheduling). The counts also land as gauges so every ``round``
        record carries the latest values. Reference counterpart: none — the
        reference has no system-fault surface."""
        if diag is None:
            diag = getattr(self.engine, "last_fault_diag", None)
        if not diag or not self.telemetry.enabled:
            return
        fields = {name: int(np.asarray(v)) for name, v in diag.items()}
        for name, value in fields.items():
            self.telemetry.gauge(f"faults.{name}", value)
        self.telemetry.event("faults", round=rnd, **fields)

    def _log_audit(self, rnd: int, diag=None) -> None:
        """Runtime-audit forensics -> one ``audit`` telemetry record per
        round: certificate verdicts (median-ball, envelope), breach /
        fallback flags, and the oracle honest-deviation fields (the two
        sides of the (f, c)-resilience bound — ground truth the simulator
        knows but a real deployment would not; ``diag`` = one round's slice
        under round-block scheduling). The headline flags also land as
        gauges so every ``round`` record carries the latest values.
        Reference counterpart: none (``src/blades/simulator.py:244``
        applies whatever the aggregator returns, unaudited)."""
        if diag is None:
            diag = getattr(self.engine, "last_audit_diag", None)
        if not diag or not self.telemetry.enabled:
            return
        fields = {}
        for name, v in diag.items():
            arr = np.asarray(v)
            fields[name] = arr.item() if arr.ndim == 0 else arr.tolist()
        for name in ("breach", "fallback_used", "dev_honest"):
            if name in fields:
                self.telemetry.gauge(f"audit.{name}", fields[name])
        self.telemetry.counter("audit.breaches", fields.get("breach", 0))
        self.telemetry.event(
            "audit", round=rnd, agg=repr(self.aggregator), **fields
        )

    def _log_async(self, rnd: int, diag=None) -> None:
        """Buffered-async forensics -> one ``async`` telemetry record per
        round: arrivals, deposits, buffer fill, the fire flag, aggregated
        row count, cumulative fires, staleness moments over the fired set,
        the minimum normalized staleness weight, and cutoff exclusions
        (``blades_tpu/asyncfl``; ``diag`` = one round's slice under
        round-block scheduling). The buffer/fire headline also lands as
        gauges so every ``round`` record carries the latest values.
        Reference counterpart: none — the reference is strictly
        synchronous (``src/blades/simulator.py:203-247``)."""
        if diag is None:
            diag = getattr(self.engine, "last_async_diag", None)
        if not diag or not self.telemetry.enabled:
            return
        fields = {}
        for name, v in diag.items():
            arr = np.asarray(v)
            fields[name] = (
                float(arr) if arr.dtype.kind == "f" else int(arr)
            )
        for name in ("buffer_count", "fired", "mean_staleness"):
            self.telemetry.gauge(f"async.{name}", fields[name])
        self.telemetry.counter("async.fires", fields.get("fired", 0))
        self.telemetry.event("async", round=rnd, **fields)

    def _log_metrics(self, rnd: int, pack=None) -> None:
        """In-graph round metrics -> one ``metrics`` telemetry record per
        round: update-norm quantiles + fixed-log-bin histogram,
        honest-vs-byzantine cosine-to-aggregate, participation/exclusion
        counts, and per-chunk slab extremes — computed INSIDE the compiled
        round body (``telemetry/metric_pack.py``), so the record survives
        round-block and streaming fusion unchanged (``pack`` = one round's
        slice of the block's stacked packs). The headline geometry fields
        also land as gauges so every ``round`` record carries the latest.
        Reference counterpart: none (``src/blades/simulator.py:453-455``
        records loss/wall-time only)."""
        if pack is None:
            pack = getattr(self.engine, "last_metric_pack", None)
        if pack is None or pack == () or not self.telemetry.enabled:
            return
        fields = pack_to_fields(pack)
        for name in ("cos_honest", "cos_byz", "norm_median", "participants"):
            self.telemetry.gauge(f"metrics.{name}", fields[name])
        self.telemetry.event("metrics", round=rnd, **fields)

    def evaluate(self, rnd: int, batch_size: int = 64) -> Dict:
        """Reference test flow (``test_actor`` -> ``log_validate``,
        ``simulator.py:282-307,324-335``): every client evaluates the global
        model on its own test shard (one ``client_validation`` record each,
        ``client.py:144-176``), then the data-size-weighted average is logged
        as the ``test`` record. One batched forward pass computes all of it;
        shards are the clients' real test partitions carried by the dataset
        (``FLDataset.client_test_slices``; reference keeps one test set per
        client, ``src/blades/datasets/dataset.py:80-115``)."""
        losses, correct = self.engine.evaluate_per_sample(
            self.server.state,
            self.dataset.test_x,
            self.dataset.test_y,
            batch_size=batch_size,
        )
        n = losses.shape[0]
        if hasattr(self.dataset, "client_test_slices"):
            shards = self.dataset.client_test_slices()
        else:
            shards = np.array_split(np.arange(n), self.dataset.num_clients)
        for u, idx in zip(self._clients, shards):
            if len(idx) == 0:
                continue
            r = {
                "_meta": {"type": "client_validation"},
                "E": rnd,
                "id": u,
                "Length": int(len(idx)),
                "Loss": float(losses[idx].mean()),
                "top1": float(correct[idx].mean()),
            }
            self.json_logger.info(r)
        ev = {"Loss": float(losses.mean()), "top1": float(correct.mean())}
        r = {
            "_meta": {"type": "test"},
            "Round": rnd,
            "top1": ev["top1"],
            "Length": n,
            "Loss": ev["Loss"],
        }
        self.json_logger.info(r)
        return ev
