"""IPM (Inner Product Manipulation) omniscient attack.

Reference: ``IpmClient`` (``src/blades/attackers/ipmclient.py:4-16``): every
byzantine row becomes ``-epsilon * mean(honest updates)``. One masked
reduction + where on the device-resident update matrix.
"""

from __future__ import annotations

import jax.numpy as jnp

from blades_tpu.attackers.base import Attack, honest_stats


class Ipm(Attack):
    # omniscient: byzantine rows are built from the honest-population mean
    update_locality = "population"

    def __init__(self, epsilon: float = 0.5):
        self.epsilon = float(epsilon)

    def on_updates(self, updates, byz_mask, key, state=()):
        mu, _, _ = honest_stats(updates, byz_mask)
        malicious = -self.epsilon * mu
        return jnp.where(byz_mask[:, None], malicious[None, :], updates), state
