"""ALIE ("A Little Is Enough") omniscient attack.

Reference: ``AlieClient`` (``src/blades/attackers/alieclient.py:8-37``):
z_max = ``norm.ppf((n - f - s) / (n - f))`` with ``s = floor(n/2 + 1) - f``;
each byzantine row becomes ``mu - z_max * std`` where mu/std are per-coordinate
moments over the *honest* updates. The ppf is resolved at construction (static
Python float), so the attack itself is two masked reductions plus a where —
no host round-trip, unlike the reference's per-round ``omniscient_callback``.
"""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
from scipy.stats import norm

from blades_tpu.attackers.base import Attack, honest_stats


class Alie(Attack):
    # omniscient: byzantine rows are built from honest-population moments
    update_locality = "population"

    def __init__(
        self,
        num_clients: Optional[int] = None,
        num_byzantine: Optional[int] = None,
        z: Optional[float] = None,
    ):
        self.num_clients = num_clients
        self.num_byzantine = num_byzantine
        self._z = z

    def _z_max(self, n: int, f: int) -> float:
        if self._z is not None:
            return float(self._z)
        s = math.floor(n / 2 + 1) - f
        cdf_value = (n - f - s) / (n - f)
        # feasibility edge: when f exceeds the paper's supported-majority
        # regime (f > floor(n/2 + 1), e.g. f = n - 1), s goes negative and
        # the cdf exceeds 1, where ppf returns NaN — which would silently
        # NaN every byzantine row. Clamp into the open unit interval so
        # degenerate populations still produce a finite (if extreme) z
        # (pinned by tests/test_attackers.py). The bounds are epsilons, not
        # 0.5: valid configs legitimately sit below 0.5 (even n with f=1
        # gives cdf (n/2 - 1)/(n - 1) < 0.5) and must keep the reference's
        # exact ppf value.
        cdf_value = min(max(cdf_value, 1e-9), 1.0 - 1e-9)
        return float(norm.ppf(cdf_value))

    def on_updates(self, updates, byz_mask, key, state=()):
        n = self.num_clients if self.num_clients is not None else updates.shape[0]
        f = (
            self.num_byzantine
            if self.num_byzantine is not None
            else int(byz_mask.sum())  # only reachable outside jit
        )
        z_max = self._z_max(int(n), int(f))
        mu, std, _ = honest_stats(updates, byz_mask)
        malicious = mu - z_max * std
        return jnp.where(byz_mask[:, None], malicious[None, :], updates), state
