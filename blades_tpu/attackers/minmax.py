"""Min-Max / Min-Sum AGR-agnostic attacks (Shejwalkar & Houmansadr, NDSS'21).

Not in the reference's shipped five, but standard companions in the Byzantine
literature the reference targets; included for a superset of attack coverage.
Each byzantine row becomes ``mu + gamma * dev`` where ``dev`` is a unit
perturbation direction (negative std direction, as in the paper's "std"
variant) and ``gamma`` is the largest scale keeping the malicious update
within the honest updates' pairwise-distance envelope:

  * minmax: max distance from malicious to any honest update <= max pairwise
    honest distance.
  * minsum: sum of squared distances from malicious to honest updates <= max
    over honest i of sum_j ||u_i - u_j||^2.

The gamma search is a fixed-iteration bisection under ``lax.fori_loop`` —
compiler-friendly static control flow instead of the reference's data-driven
Python loops.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from blades_tpu.attackers.base import Attack, honest_stats
from blades_tpu.ops.distances import pairwise_sq_euclidean


class _GammaScaled(Attack):
    # omniscient: the gamma search spans the full honest population
    update_locality = "population"
    n_bisect: int = 20
    gamma_init: float = 10.0

    def _objective(self, malicious, updates, honest_w, sq_dists):
        raise NotImplementedError

    def on_updates(self, updates, byz_mask, key, state=()):
        mu, std, _ = honest_stats(updates, byz_mask)
        dev = -std  # "std" perturbation variant
        honest_w = (~byz_mask).astype(updates.dtype)
        sq = pairwise_sq_euclidean(updates)
        # mask non-honest rows/cols out of the envelope statistics
        pair_mask = honest_w[:, None] * honest_w[None, :]
        sq = sq * pair_mask

        def feasible(gamma):
            return self._objective(mu + gamma * dev, updates, honest_w, sq)

        def body(_, carry):
            gamma, step = carry
            ok = feasible(gamma)
            gamma = jnp.where(ok, gamma + step, gamma - step)
            return gamma, step / 2.0

        gamma0 = jnp.asarray(self.gamma_init, updates.dtype)
        gamma, _ = lax.fori_loop(0, self.n_bisect, body, (gamma0, gamma0 / 2.0))
        malicious = mu + gamma * dev
        return jnp.where(byz_mask[:, None], malicious[None, :], updates), state


class Minmax(_GammaScaled):
    def _objective(self, malicious, updates, honest_w, sq):
        d = ((updates - malicious[None, :]) ** 2).sum(axis=1) * honest_w
        return d.max() <= sq.max()


class Minsum(_GammaScaled):
    def _objective(self, malicious, updates, honest_w, sq):
        d = (((updates - malicious[None, :]) ** 2).sum(axis=1) * honest_w).sum()
        return d <= sq.sum(axis=1).max()
