"""Byzantine attack registry.

The reference resolves attacks by convention-based dynamic import:
``"xyz" -> blades.attackers.xyzclient.XyzClient``
(``src/blades/simulator.py:118-133``), shipping noise, labelflipping,
signflipping, alie, ipm. All those names resolve here, plus minmax/minsum
(AGR-tailored attacks from the same literature family).

TPU-native design: an attack is NOT a client object with host callbacks — it
is a set of *pure functions* hooked into the single jitted round program
(SURVEY.md section 7 step 4):

  * ``on_batch``    — corrupt (x, y) inside the vmapped train step, gated by a
                      per-client byzantine flag (reference:
                      ``on_train_batch_begin``, ``client.py:178-193``).
  * ``on_grads``    — corrupt per-step gradients (reference: signflipping's
                      overridden ``local_training``).
  * ``on_updates``  — rewrite rows of the on-device ``[K, D]`` update matrix
                      after local training; omniscient attacks read the honest
                      rows for free since everything is one array (reference:
                      ``omniscient_callback`` host round-trip,
                      ``simulator.py:239-241``).
"""

from __future__ import annotations

from typing import Callable, Dict, Type, Union

from blades_tpu.attackers.base import Attack, NoAttack
from blades_tpu.attackers.noise import Noise
from blades_tpu.attackers.labelflipping import Labelflipping
from blades_tpu.attackers.signflipping import Signflipping
from blades_tpu.attackers.alie import Alie
from blades_tpu.attackers.ipm import Ipm
from blades_tpu.attackers.minmax import Minmax, Minsum

ATTACKS: Dict[str, Type[Attack]] = {
    "noise": Noise,
    "labelflipping": Labelflipping,
    "signflipping": Signflipping,
    "alie": Alie,
    "ipm": Ipm,
    "minmax": Minmax,
    "minsum": Minsum,
}


def get_attack(name: Union[str, Attack, None], **kwargs) -> Attack:
    """Resolve an attack by registry name (reference naming parity) or pass
    through a custom :class:`Attack` instance."""
    if name is None:
        return NoAttack()
    if isinstance(name, Attack):
        return name
    try:
        cls = ATTACKS[name]
    except KeyError:
        raise ValueError(
            f"Unknown attack {name!r}; available: {sorted(ATTACKS)}"
        ) from None
    return cls(**kwargs)


__all__ = [
    "Attack",
    "NoAttack",
    "Noise",
    "Labelflipping",
    "Signflipping",
    "Alie",
    "Ipm",
    "Minmax",
    "Minsum",
    "ATTACKS",
    "get_attack",
]
