"""Sign-flipping attack: byzantine clients negate every gradient step.

Reference: ``SignflippingClient.local_training``
(``src/blades/attackers/signflippingclient.py:6-20``) re-implements the local
loop with ``p.grad = -p.grad`` before each optimizer step. Here it is a signed
scale on the gradient pytree, gated by the per-client flag under vmap.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from blades_tpu.attackers.base import Attack


class Signflipping(Attack):
    trains_dishonestly = True

    def on_grads(self, grads, is_byz, client_idx=None):
        sign = jnp.where(is_byz, -1.0, 1.0)
        return jax.tree_util.tree_map(lambda g: g * sign.astype(g.dtype), grads)
