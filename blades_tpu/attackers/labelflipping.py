"""Label-flipping attack: ``y -> num_classes - 1 - y`` on byzantine clients.

Reference: ``LabelflippingClient.on_train_batch_begin``
(``src/blades/attackers/labelflippingclient.py:12-26``). Here the flip is a
``jnp.where`` gated by the per-client byzantine flag inside the vmapped train
step, so honest and byzantine clients share one compiled program.
"""

from __future__ import annotations

import jax.numpy as jnp

from blades_tpu.attackers.base import Attack


class Labelflipping(Attack):
    trains_dishonestly = True

    def __init__(self, num_classes: int = 10):
        self.num_classes = int(num_classes)

    def on_batch(self, x, y, is_byz, *, num_classes, key, client_idx=None):
        n = num_classes or self.num_classes
        return x, jnp.where(is_byz, n - 1 - y, y)
