"""Attack base class: three pure hooks into the jitted round program.

Reference counterpart: ``ByzantineClient`` (``src/blades/client.py:231-253``),
whose subclasses override host-side lifecycle callbacks. Here each hook is a
pure function traced into XLA; the byzantine population is a boolean mask over
the client axis, so honest and byzantine clients run the *same* compiled
program (no divergent Python control flow, which is what makes the round a
single ``vmap``-able computation).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp


class Attack:
    """Base class for Byzantine attacks (all hooks default to identity).

    Hyperparameters are plain Python attributes (static under jit). Hooks:

    ``on_batch(x, y, is_byz, num_classes, key, client_idx)``
        Per-train-step data corruption inside the vmapped client step.
        ``is_byz`` is a scalar bool and ``client_idx`` a scalar int32 for the
        current client (under vmap); built-in uniform attacks ignore
        ``client_idx``, per-client composites dispatch on it.

    ``on_grads(grads, is_byz, client_idx)``
        Per-step gradient corruption (pytree in, pytree out).

    ``on_updates(updates, byz_mask, key, state)``
        Post-training rewrite of the ``[K, D]`` update matrix. ``byz_mask`` is
        a ``[K]`` bool vector. Returns ``(updates, new_state)``.
    """

    #: True if any hook other than on_updates is non-trivial (lets the engine
    #: skip dead code in the compiled program).
    trains_dishonestly: bool = False

    #: What ``on_updates`` reads: ``"row"`` when each output row depends
    #: only on its own input row (+ the mask/key) — such attacks apply
    #: per-chunk in the streaming engine with identical semantics;
    #: ``"population"`` when byzantine rows are computed from
    #: full-population statistics (ALIE/IPM/minmax honest moments), which
    #: the streaming engine cannot provide (it never materializes
    #: ``[K, D]``) and therefore rejects at build time.
    update_locality: str = "row"

    def init_state(self, num_clients: int, dim: int) -> Any:
        return ()

    def on_batch(
        self,
        x: jnp.ndarray,
        y: jnp.ndarray,
        is_byz: jnp.ndarray,
        *,
        num_classes: int,
        key: jax.Array,
        client_idx: jnp.ndarray = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        return x, y

    def on_grads(
        self, grads: Any, is_byz: jnp.ndarray, client_idx: jnp.ndarray = None
    ) -> Any:
        return grads

    def on_updates(
        self,
        updates: jnp.ndarray,
        byz_mask: jnp.ndarray,
        key: jax.Array,
        state: Any = (),
    ) -> Tuple[jnp.ndarray, Any]:
        return updates, state

    def __repr__(self) -> str:
        return type(self).__name__


class NoAttack(Attack):
    """All clients honest (reference: ``attack=None`` forces
    ``num_byzantine=0``, ``simulator.py:118-121``)."""


def honest_stats(
    updates: jnp.ndarray, byz_mask: jnp.ndarray, part_mask: jnp.ndarray = None
):
    """Masked per-coordinate mean and unbiased std over honest rows.

    Omniscient attacks (ALIE/IPM/minmax) need moments of the honest updates;
    with everything resident in one ``[K, D]`` device array this is two masked
    reductions instead of the reference's host-side loop over client objects
    (``alieclient.py:25-36``). Unbiased (ddof=1) std matches ``torch.std``.

    ``part_mask`` optionally restricts the honest set to the participating
    clients (partial participation, ``blades_tpu/faults``): the audit attack
    search (``blades_tpu/audit``) models an adversary that only observes the
    updates actually delivered this round. Degenerate honest sets stay
    finite: zero honest rows yield ``mu = std = 0`` (the attack collapses to
    the zero template), a single honest row yields ``std = 0``.
    """
    honest_rows = ~byz_mask if part_mask is None else (~byz_mask & part_mask)
    honest = honest_rows.astype(updates.dtype)[:, None]
    n = jnp.maximum(honest.sum(), 1.0)
    mu = (updates * honest).sum(axis=0) / n
    var = ((updates - mu) ** 2 * honest).sum(axis=0) / jnp.maximum(n - 1.0, 1.0)
    return mu, jnp.sqrt(var), n
