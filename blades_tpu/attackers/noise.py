"""Noise attack: byzantine rows replaced by i.i.d. Gaussian noise.

Reference: ``NoiseClient`` (``src/blades/attackers/noiseclient.py:8-25``)
uploads ``Normal(mean=0.1, std=0.1)`` of the update's shape from
``omniscient_callback``. Here it is a single masked ``jnp.where`` on the
update matrix.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from blades_tpu.attackers.base import Attack


class Noise(Attack):
    def __init__(self, mean: float = 0.1, std: float = 0.1):
        self.mean = float(mean)
        self.std = float(std)

    def on_updates(self, updates, byz_mask, key, state=()):
        noise = self.mean + self.std * jax.random.normal(
            key, updates.shape, updates.dtype
        )
        return jnp.where(byz_mask[:, None], noise, updates), state
