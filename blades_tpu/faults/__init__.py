"""Fault injection + graceful degradation for federated rounds.

:class:`FaultModel` injects system faults (client dropout, stale straggler
replays, NaN/Inf/bit-flip payload corruption) into the jitted round as
masks/``where``\\s; the mask-aware aggregation surface
(``Aggregator.aggregate_masked``) and the server-side non-finite guard let
every defense survive what the model injects. See ``docs/robustness.md``.

Reference counterpart: none — the reference models adversarial failure only
(``src/blades/simulator.py:213-244``); system faults are new surface.
"""

from blades_tpu.faults.model import FaultModel

__all__ = ["FaultModel"]
