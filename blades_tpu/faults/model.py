"""Seeded, jit-compatible system-fault injection for the federated round.

Byzantine attacks (``blades_tpu/attackers``) model *adversarial* failure;
this module models the *system* faults that dominate real deployments —
client dropout, stragglers replaying stale updates, and corrupted payloads
(NaN/Inf rows, bit-flip-style noise) — plus the server-side non-finite
guard that keeps them from poisoning the global model. Everything is
expressed as masks and ``where``\\s over the on-device ``[K, D]`` update
matrix inside the jitted round program (``core/engine.py``): no Python-side
branching, so the sharded round stays one compiled XLA program and every
fault draw is a pure function of ``(seed, round)`` — reproducible and
therefore bit-exactly resumable from a checkpoint.

Reference counterpart: none — the reference simulator trains every client
every round and assumes every upload is well-formed
(``src/blades/simulator.py:213-244``); it has no dropout, staleness, or
payload-fault surface at all. Partial participation semantics follow the
FedAvg client-subsampling setting (McMahan et al., 2017) and the
robustness-under-subsampling analysis of Karimireddy et al., 2022.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class FaultModel:
    """Per-round fault plan: who participates, who is stale, what is corrupt.

    Construction-time hyperparameters are static under jit (the model object
    rides on the engine like an :class:`~blades_tpu.aggregators.Aggregator`);
    all randomness flows from the engine's round key through the dedicated
    ``rng.FAULT`` stream, so a resumed run replays the exact fault history.

    Parameters
    ----------
    dropout_rate : i.i.d. per-client probability of dropping out each round.
    participation_schedule : optional ``[period, K]`` bool array — a
        deterministic participation plan (row ``r % period`` is round ``r``'s
        availability mask). Overrides ``dropout_rate`` when given.
    straggler_rate : probability a (non-dropped) client is a straggler this
        round. A straggler re-sends its buffered update from the last round
        it reported fresh (bounded stale-update buffer carried in
        ``RoundState.fault_state``); once the buffered update is older than
        ``max_staleness`` rounds — or the client never reported — the
        straggler is dropped instead of replaying arbitrarily stale state.
    max_staleness : staleness bound (rounds) on the replay buffer.
    corrupt_rate : i.i.d. probability a *delivered* update row is corrupted.
    corrupt_clients : static client ids whose delivered rows are ALWAYS
        corrupted (deterministic faulty hardware).
    corrupt_mode : ``"nan"`` | ``"inf"`` | ``"bitflip"``. ``nan``/``inf``
        overwrite the whole row; ``bitflip`` flips the sign and scales by
        ``bitflip_scale`` on a random ``bitflip_frac`` of coordinates
        (exponent-bit-flip shaped noise, still finite).
    guard_nonfinite : server-side guard — rows containing any NaN/Inf are
        excluded from the participation mask before aggregation (the
        aggregator then never touches the poisoned payload). Exclusion
        counts surface in the per-round fault diagnostics.
    """

    dropout_rate: float = 0.0
    participation_schedule: Optional[Any] = None
    straggler_rate: float = 0.0
    max_staleness: int = 1
    corrupt_rate: float = 0.0
    corrupt_clients: Tuple[int, ...] = ()
    corrupt_mode: str = "nan"
    bitflip_scale: float = 2.0 ** 15
    bitflip_frac: float = 0.01
    guard_nonfinite: bool = True

    def __post_init__(self):
        if self.corrupt_mode not in ("nan", "inf", "bitflip"):
            raise ValueError(f"unknown corrupt_mode {self.corrupt_mode!r}")
        if self.participation_schedule is not None:
            sched = np.asarray(self.participation_schedule, dtype=bool)
            if sched.ndim != 2:
                raise ValueError(
                    "participation_schedule must be [period, num_clients]"
                )
            object.__setattr__(self, "participation_schedule", sched)
        object.__setattr__(
            self, "corrupt_clients", tuple(int(c) for c in self.corrupt_clients)
        )

    # -- state ---------------------------------------------------------------

    @property
    def has_stragglers(self) -> bool:
        return self.straggler_rate > 0.0

    @property
    def value_corruption(self) -> bool:
        """True when whole-row value corruption (NaN/Inf fill) is actually
        configured. The fill value is then TRACED STATE, not a compiled
        constant — so the ``nan`` and ``inf`` configurations share one
        compiled round program (they differ only in a state leaf), which
        is what lets a warm-program cache (``blades_tpu/sweeps``) serve a
        chaos scenario and its NaN<->Inf inertness twin from one build."""
        return self.corrupt_mode in ("nan", "inf") and bool(
            self.corrupt_rate > 0.0 or self.corrupt_clients
        )

    @property
    def _fill_value(self) -> float:
        return float("nan") if self.corrupt_mode == "nan" else float("inf")

    def init_state(self, num_clients: int, dim: int) -> Any:
        """Stale-update replay buffer + (when value corruption is
        configured) the traced corrupt fill scalar; the empty pytree when
        neither is on, so fault-free configs pay nothing in
        state/checkpoint size."""
        state = {}
        if self.has_stragglers:
            state.update({
                "stale": jnp.zeros((num_clients, dim), jnp.float32),
                "age": jnp.zeros((num_clients,), jnp.int32),
                "has": jnp.zeros((num_clients,), bool),
            })
        if self.value_corruption:
            state["fill"] = jnp.asarray(self._fill_value, jnp.float32)
        return state if state else ()

    def static_fingerprint(self) -> Any:
        """The PROGRAM-shape view of this config (``blades_tpu.sweeps
        .static_fingerprint`` calls this): every field that changes the
        traced program, with the NaN/Inf fill collapsed to ``"value"``
        when it is traced state — two configs mapping equal here compile
        to the same program and may share a warm engine."""
        fields = dataclasses.asdict(self)
        if self.value_corruption:
            fields["corrupt_mode"] = "value"
        sched = fields.get("participation_schedule")
        if sched is not None:
            fields["participation_schedule"] = [
                [bool(v) for v in row] for row in np.asarray(sched)
            ]
        return fields

    # -- the in-graph fault pass ----------------------------------------------

    def apply(
        self, updates: jnp.ndarray, state: Any, key: jax.Array, round_idx
    ) -> Tuple[jnp.ndarray, jnp.ndarray, Any, dict]:
        """Inject this round's faults into the post-attack update matrix.

        Returns ``(updates, participation_mask, new_state, diagnostics)``:
        the (possibly corrupted / stale-replayed) matrix, the boolean ``[K]``
        mask of rows the server actually aggregates, the advanced replay
        buffer, and a dict of int32 fault counters (participants, dropped,
        stale replays, stragglers dropped for exceeding ``max_staleness``,
        corrupted rows, rows excluded by the non-finite guard).
        """
        k = updates.shape[0]
        kd, ks, kc, kb = jax.random.split(key, 4)

        if self.participation_schedule is not None:
            sched = jnp.asarray(self.participation_schedule)
            drop = ~sched[jnp.mod(round_idx, sched.shape[0])]
        elif self.dropout_rate > 0.0:
            drop = jax.random.bernoulli(kd, self.dropout_rate, (k,))
        else:
            drop = jnp.zeros((k,), bool)

        if self.has_stragglers:
            straggle = jax.random.bernoulli(ks, self.straggler_rate, (k,)) & ~drop
            age = state["age"] + 1  # buffered update ages one round
            stale_ok = straggle & state["has"] & (age <= self.max_staleness)
            fresh = ~drop & ~straggle
            out = jnp.where(
                stale_ok[:, None], state["stale"].astype(updates.dtype), updates
            )
            part = fresh | stale_ok
            new_state = {
                **(
                    {"fill": state["fill"]}
                    if self.value_corruption and "fill" in state
                    else {}
                ),
                "stale": jnp.where(
                    fresh[:, None], updates.astype(jnp.float32), state["stale"]
                ),
                "age": jnp.where(fresh, 0, age),
                "has": state["has"] | fresh,
            }
            n_stale = jnp.sum(stale_ok.astype(jnp.int32))
            n_expired = jnp.sum((straggle & ~stale_ok).astype(jnp.int32))
        else:
            fresh = ~drop
            part = fresh
            out = updates
            new_state = state
            n_stale = n_expired = jnp.asarray(0, jnp.int32)

        corrupt = jnp.zeros((k,), bool)
        if self.corrupt_rate > 0.0:
            corrupt |= jax.random.bernoulli(kc, self.corrupt_rate, (k,))
        if self.corrupt_clients:
            ids = jnp.asarray(self.corrupt_clients, jnp.int32)
            corrupt |= jnp.any(
                jnp.arange(k, dtype=jnp.int32)[:, None] == ids[None, :], axis=1
            )
        corrupt &= part  # only delivered payloads can arrive corrupted
        if self.value_corruption:
            # the fill rides the state as a TRACED scalar (init_state), so
            # the nan and inf configurations are one compiled program — a
            # warm-program cache serves the chaos inertness twin for free.
            # Direct callers that hand-roll a state without the fill leaf
            # (ad-hoc apply() use, pre-existing tests) get the constant.
            fill = (
                state["fill"]
                if isinstance(state, dict) and "fill" in state
                else jnp.asarray(self._fill_value, jnp.float32)
            )
            out = jnp.where(corrupt[:, None], fill.astype(out.dtype), out)
        elif self.corrupt_mode in ("nan", "inf"):
            # no corruption configured: the mask is statically all-False,
            # keep the constant (and the pre-existing compiled program)
            out = jnp.where(corrupt[:, None], self._fill_value, out)
        else:  # bitflip: sign-flip + power-of-two scale on a coord subset
            flip = jax.random.bernoulli(kb, self.bitflip_frac, out.shape)
            flipped = jnp.where(flip, -self.bitflip_scale * out, out)
            out = jnp.where(corrupt[:, None], flipped, out)

        excluded = jnp.zeros((k,), bool)
        if self.guard_nonfinite:
            finite = jnp.all(jnp.isfinite(out), axis=1)
            excluded = part & ~finite
            part = part & finite

        diag = {
            "participants": jnp.sum(part.astype(jnp.int32)),
            "dropped": jnp.sum(drop.astype(jnp.int32)),
            "stale_replayed": n_stale,
            "stragglers_expired": n_expired,
            "corrupted": jnp.sum(corrupt.astype(jnp.int32)),
            "excluded_nonfinite": jnp.sum(excluded.astype(jnp.int32)),
        }
        return out, part, new_state, diag

    # -- streaming (chunk-scanned) fault pass ---------------------------------

    def plan_streaming(
        self, num_clients: int, key: jax.Array, round_idx
    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jax.Array]:
        """[K]-level fault decisions for the chunk-scanned round
        (``core/engine.py`` with ``streaming=True``): returns
        ``(participation, dropped, corrupt, corrupt_key)``. The mask draws
        split the round key exactly like :meth:`apply`, so dropout /
        schedule / corruption-victim decisions are bit-identical to the
        dense path's; only the BITFLIP payload noise differs (it is drawn
        per chunk from ``fold_in(corrupt_key, chunk_index)`` inside
        :meth:`corrupt_chunk` rather than as one ``[K, D]`` draw).
        Stragglers are a dense-only feature — their replay buffer is
        ``[K, D]`` state, the memory the streaming engine exists to avoid.
        """
        if self.has_stragglers:
            raise ValueError(
                "straggler replay buffers are [K, D] state; the streaming "
                "round supports participation/corruption faults only"
            )
        k = num_clients
        kd, ks, kc, kb = jax.random.split(key, 4)
        del ks  # the straggler stream, reserved to keep draw parity

        if self.participation_schedule is not None:
            sched = jnp.asarray(self.participation_schedule)
            drop = ~sched[jnp.mod(round_idx, sched.shape[0])]
        elif self.dropout_rate > 0.0:
            drop = jax.random.bernoulli(kd, self.dropout_rate, (k,))
        else:
            drop = jnp.zeros((k,), bool)
        part = ~drop

        corrupt = jnp.zeros((k,), bool)
        if self.corrupt_rate > 0.0:
            corrupt |= jax.random.bernoulli(kc, self.corrupt_rate, (k,))
        if self.corrupt_clients:
            ids = jnp.asarray(self.corrupt_clients, jnp.int32)
            corrupt |= jnp.any(
                jnp.arange(k, dtype=jnp.int32)[:, None] == ids[None, :], axis=1
            )
        corrupt &= part  # only delivered payloads can arrive corrupted
        return part, drop, corrupt, kb

    def corrupt_chunk(
        self, slab: jnp.ndarray, corrupt: jnp.ndarray, key: jax.Array,
        fill: Any = None,
    ) -> jnp.ndarray:
        """Row-local payload corruption for one ``[chunk, D]`` slab
        (``corrupt`` is the chunk's slice of the planned victim mask).
        ``fill``: the traced fill scalar from the fault state when value
        corruption is configured (the streaming engine passes
        ``fault_state['fill']``); ``None`` keeps the static constant."""
        if self.corrupt_mode in ("nan", "inf"):
            value = (
                fill.astype(slab.dtype) if fill is not None
                else self._fill_value
            )
            return jnp.where(corrupt[:, None], value, slab)
        flip = jax.random.bernoulli(key, self.bitflip_frac, slab.shape)
        flipped = jnp.where(flip, -self.bitflip_scale * slab, slab)
        return jnp.where(corrupt[:, None], flipped, slab)

    def __repr__(self) -> str:
        parts = []
        if self.participation_schedule is not None:
            parts.append(f"schedule[{self.participation_schedule.shape[0]}]")
        elif self.dropout_rate:
            parts.append(f"drop={self.dropout_rate}")
        if self.straggler_rate:
            parts.append(
                f"straggle={self.straggler_rate}(s<={self.max_staleness})"
            )
        if self.corrupt_rate or self.corrupt_clients:
            parts.append(
                f"corrupt[{self.corrupt_mode}]="
                f"{self.corrupt_rate or list(self.corrupt_clients)}"
            )
        return f"FaultModel({', '.join(parts) or 'noop'})"
