"""Server handle.

Reference: ``BladesServer`` (``src/blades/server.py:6-75``) owns the global
model + optimizer and applies the aggregate as a pseudo-gradient
(``p.grad = -x``). Here the server step is traced inside the round program
(``core/engine.py``); this object is the host-side view exposing the same
accessors.
"""

from __future__ import annotations

from typing import Any


class BladesServer:
    def __init__(self, engine, state, aggregator):
        self._engine = engine
        self.state = state
        self.aggregator = aggregator

    def get_model(self) -> Any:
        """Current global params pytree (reference returns the nn.Module)."""
        return self.state.params

    def get_opt(self) -> Any:
        """Server optimizer state (reference returns the torch optimizer)."""
        return self.state.server_opt_state

    def zero_grad(self, set_to_none: bool = False) -> None:
        """No-op: there are no persistent grads in a functional step; kept
        for reference API parity (``server.py:39-52``)."""

    def apply_update(self, update, server_lr: float = 0.1) -> None:
        """Host-side escape hatch applying an aggregated ``[D]`` vector as a
        pseudo-gradient step outside the jitted round (parity with
        ``server.py:54-75``; the fused path in core/engine.py is preferred)."""
        import jax

        grad_tree = self._engine.unravel(-update)
        server_updates, opt_state = self._engine._server_tx.update(
            grad_tree, self.state.server_opt_state, self.state.params
        )
        params = jax.tree_util.tree_map(
            lambda p, u: p - server_lr * u.astype(p.dtype),
            self.state.params,
            server_updates,
        )
        self.state = self.state._replace(params=params, server_opt_state=opt_state)
