"""Seeded, fixed-shape client arrival process for buffered-async rounds.

Production federated clients do not report in lockstep: each client
downloads the current global model, trains for a device-dependent amount
of wall time, and reports whenever it finishes (FedBuff, Nguyen et al.,
AISTATS 2022). This module models that timing as a **fixed-shape, seeded
process inside the jitted round program**: every client carries an integer
``countdown`` (server rounds until its in-flight update arrives); a client
whose countdown hits zero *arrives* this round, deposits its update into
the server buffer (``blades_tpu/asyncfl/buffer.py``), immediately
re-downloads the current model, and draws a fresh delay from the dedicated
``rng.ARRIVAL`` stream — all masks and ``where``\\s, no data-dependent
shapes, exactly the discipline of the fault layer
(``blades_tpu/faults/model.py``).

Delay distributions (``kind``):

- ``"zero"`` — every delay is 0: clients arrive every round (the
  degenerate sync-equivalent process; with ``buffer_m == K`` and constant
  staleness weighting the buffered round is bit-identical to the sync
  round, ``tests/test_asyncfl.py``);
- ``"fixed"`` — a static per-client delay vector (deterministic
  heterogeneity: fast phones vs slow phones);
- ``"uniform"`` — i.i.d. integer delays uniform on
  ``[min_delay, max_delay]`` per (client, cycle);
- ``"geometric"`` — geometric-ish delays with mean ``mean_delay``,
  clipped to ``max_delay`` (the long-tail straggler shape).

Every draw is a pure function of ``(seed, round, client)`` via
``fold_in(fold_in(round_key, rng.ARRIVAL), client)``, so any round's
arrival pattern is reproducible in isolation and a resumed run replays the
exact arrival history (the bit-exact resume contract).

Reference counterpart: none — the reference simulator is strictly
synchronous (``src/blades/simulator.py:203-247`` trains every client every
round and blocks on all of them); its async aggregator classes
(``_BaseAsyncAggregator``, ``mean.py:42-60``) are unreachable dead code
with no arrival semantics at all.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from blades_tpu.utils import rng

_KINDS = ("zero", "fixed", "uniform", "geometric")


@dataclasses.dataclass(frozen=True)
class ArrivalProcess:
    """Per-client delay distribution -> integer arrival-round offsets.

    Construction-time hyperparameters are static under jit (the process
    object rides on the engine like a :class:`~blades_tpu.faults.FaultModel`).

    Parameters
    ----------
    kind : one of ``"zero" | "fixed" | "uniform" | "geometric"``.
    max_delay : static upper bound on any delay draw (rounds). Also sizes
        the engine's version-lagged parameter history (``max_delay + 1``
        ring slots), so it is a memory knob: ``[max_delay + 1, D]`` floats.
    min_delay : lower bound for ``"uniform"``.
    mean_delay : mean for ``"geometric"``.
    delays : static per-client delay vector for ``"fixed"`` (length K,
        each entry clipped to ``[0, max_delay]``).
    """

    kind: str = "zero"
    max_delay: int = 0
    min_delay: int = 0
    mean_delay: float = 1.0
    delays: Optional[Tuple[int, ...]] = None

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown arrival kind {self.kind!r}; one of {_KINDS}")
        if self.max_delay < 0:
            raise ValueError(f"max_delay must be >= 0, got {self.max_delay}")
        if self.kind == "zero" and self.max_delay != 0:
            object.__setattr__(self, "max_delay", 0)
        if self.kind == "fixed":
            if self.delays is None:
                raise ValueError("kind='fixed' needs a per-client `delays` vector")
            d = tuple(int(x) for x in self.delays)
            if any(x < 0 for x in d):
                raise ValueError("fixed delays must be >= 0")
            object.__setattr__(self, "delays", d)
            object.__setattr__(
                self, "max_delay", max(self.max_delay, max(d, default=0))
            )
        if not (0 <= self.min_delay <= self.max_delay) and self.kind == "uniform":
            raise ValueError(
                f"uniform needs 0 <= min_delay <= max_delay, got "
                f"[{self.min_delay}, {self.max_delay}]"
            )

    # -- the in-graph draw -----------------------------------------------------

    def draw(self, round_key: jax.Array, num_clients: int) -> jnp.ndarray:
        """``[K]`` int32 delay draws for clients (re)downloading this
        round — consumed only at entries where the arrival mask is True,
        but drawn fixed-shape for every client so the program never
        branches on data. Pure function of ``(round_key, client)`` through
        the dedicated ``rng.ARRIVAL`` stream."""
        k = int(num_clients)
        if self.kind == "zero":
            return jnp.zeros((k,), jnp.int32)
        if self.kind == "fixed":
            if len(self.delays) != k:
                raise ValueError(
                    f"fixed delays length {len(self.delays)} != "
                    f"num_clients {k}"
                )
            # static table (already validated/clipped in __post_init__)
            return jnp.asarray(self.delays, jnp.int32)
        akey = jax.random.fold_in(round_key, rng.ARRIVAL)
        keys = jax.vmap(lambda i: jax.random.fold_in(akey, i))(jnp.arange(k))
        if self.kind == "uniform":
            return jax.vmap(
                lambda kk: jax.random.randint(
                    kk, (), self.min_delay, self.max_delay + 1, jnp.int32
                )
            )(keys)
        # geometric: floor(log(u) / log(1 - p)) with p = 1 / (1 + mean),
        # clipped into [0, max_delay] — the standard inverse-CDF draw,
        # fixed-shape and branch-free
        p = 1.0 / (1.0 + float(self.mean_delay))
        u = jax.vmap(
            lambda kk: jax.random.uniform(
                kk, (), jnp.float32, 1e-7, 1.0
            )
        )(keys)
        g = jnp.floor(jnp.log(u) / jnp.log1p(-p)).astype(jnp.int32)
        return jnp.clip(g, 0, self.max_delay)

    @property
    def history_len(self) -> int:
        """Ring-buffer depth of the version-lagged parameter history the
        engine must carry: a client arriving with delay ``d <= max_delay``
        trains against the model published ``d`` rounds ago, so
        ``max_delay + 1`` slots always cover the gather."""
        return int(self.max_delay) + 1

    def __repr__(self) -> str:
        if self.kind == "zero":
            return "ArrivalProcess(zero)"
        if self.kind == "fixed":
            return f"ArrivalProcess(fixed, max={self.max_delay})"
        if self.kind == "uniform":
            return (
                f"ArrivalProcess(uniform[{self.min_delay},{self.max_delay}])"
            )
        return (
            f"ArrivalProcess(geometric(mean={self.mean_delay}, "
            f"max={self.max_delay}))"
        )
