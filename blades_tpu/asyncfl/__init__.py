"""Buffered-asynchronous federated rounds (FedBuff-style), in-graph.

The engine's second round semantics: clients arrive on a seeded,
fixed-shape schedule (``arrivals.py``), the server buffers the first-M
arrivals and aggregates them with pluggable staleness weighting
(``buffer.py``), and the whole tick — version-lagged training, deposit,
fire, staleness-weighted robust aggregation, audited server step — is one
jitted XLA program (``engine.py``) dispatched by
:class:`blades_tpu.core.RoundEngine` when built with ``async_config=``
(:class:`Simulator.run(async_config=...) <blades_tpu.Simulator>` threads
it through). Degenerate configuration (``buffer_m=K``, zero delays,
constant weighting) is bit-identical to the synchronous round across the
full aggregator registry (``tests/test_asyncfl.py``).

Reference counterpart: none — the reference simulator is strictly
synchronous (``src/blades/simulator.py:203-247``); its unreachable
``_BaseAsyncAggregator`` family (``src/blades/aggregators/mean.py:42-87``)
gets real arrival/buffer/staleness semantics here. Protocol: FedBuff
(Nguyen et al., AISTATS 2022).
"""

from blades_tpu.asyncfl.arrivals import ArrivalProcess
from blades_tpu.asyncfl.buffer import STALENESS_MODES, AsyncConfig

__all__ = ["ArrivalProcess", "AsyncConfig", "STALENESS_MODES"]
