"""FedBuff-style bounded server buffer + pluggable staleness weighting.

The buffered-asynchronous server (FedBuff, Nguyen et al., AISTATS 2022)
does not wait for all K clients: arriving updates accumulate in a buffer,
and when the **first-M threshold** is met the server aggregates the
buffered set — each update weighted by a function of its *staleness*
``tau`` (server rounds since that client downloaded the model it trained
against) — applies the step, and drains the buffer.

TPU-native design decisions (all fixed-shape, all carried in
``RoundState.async_state`` so crash-autosave/resume is bit-exact with a
non-empty buffer):

- **per-client buffer slots** — a client has at most one update in flight
  (it re-downloads only when it arrives), so the bounded buffer is a
  ``[K, D]`` matrix + ``[K]`` occupancy mask indexed by client id; a
  round-granular simulation can deposit several arrivals at once, and the
  fire drains the whole buffer (first-M is the *trigger*, not an exact
  take-M — documented round-granularity semantics);
- **staleness weighting as a mask-compatible per-row weight** — weights
  are **normalized to mean 1 over the aggregated set**
  (``w_i * n / sum(w)``), applied by scaling rows before the registry's
  mask-aware aggregation. Every registered aggregator therefore composes
  unchanged through ``Aggregator.aggregate_masked``; for the mean family
  the estimator is exactly FedBuff's weighted mean
  ``sum(w_i d_i) / sum(w_i)``, robust defenses see a soft staleness
  discount that leaves the honest scale invariant, and **constant
  weighting is the literal identity** (no multiply is traced), which is
  what makes the degenerate sync-equivalence bit-exact;
- **version-lagged training** — arriving clients trained against the
  model *version they downloaded*; the engine carries a
  ``[max_delay + 1, D]`` ring of published flat params
  (``blades_tpu/asyncfl/engine.py``) and gathers per-client rows by
  version, statically skipped when ``max_delay == 0``.

Weighting modes (``staleness``): ``"constant"`` (w = 1 — the semantics
the registry's ``asyncmean`` entry names, ``aggregators/decentralized.py``),
``"polynomial"`` (``w = 1 / (1 + tau)^alpha``, FedBuff's default shape),
``"cutoff"`` (updates staler than ``cutoff`` rounds are *excluded from
the participation mask* — weight-0 as exclusion, so masked-row inertness
carries over).

Reference counterpart: none — the reference has no buffer or staleness
semantics; its ``_BaseAsyncAggregator`` family (``src/blades/aggregators/
mean.py:42-87``) damps absent workers by 1/K but is unreachable from its
synchronous simulator.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple, Union

import jax.numpy as jnp

from blades_tpu.asyncfl.arrivals import ArrivalProcess

STALENESS_MODES = ("constant", "polynomial", "cutoff")


@dataclasses.dataclass(frozen=True)
class AsyncConfig:
    """Buffered-asynchronous round semantics for the engine.

    Parameters
    ----------
    buffer_m : the first-M aggregation threshold — the server fires (and
        steps) on any round whose buffer holds at least this many updates.
        Clamped into ``[1, K]`` at engine build.
    arrivals : the seeded :class:`~blades_tpu.asyncfl.arrivals.ArrivalProcess`
        (or a kwargs dict for one).
    staleness : ``"constant" | "polynomial" | "cutoff"`` (see module
        docstring).
    alpha : polynomial exponent (``w = (1 + tau)^-alpha``).
    cutoff : staleness bound for ``"cutoff"`` (rounds; buffered updates
        with ``tau > cutoff`` are excluded from aggregation).
    """

    buffer_m: int = 1
    arrivals: Union[ArrivalProcess, Dict] = ArrivalProcess()
    staleness: str = "constant"
    alpha: float = 0.5
    cutoff: Optional[int] = None

    def __post_init__(self):
        if isinstance(self.arrivals, dict):
            object.__setattr__(self, "arrivals", ArrivalProcess(**self.arrivals))
        if self.staleness not in STALENESS_MODES:
            raise ValueError(
                f"unknown staleness mode {self.staleness!r}; one of "
                f"{STALENESS_MODES}"
            )
        if self.buffer_m < 1:
            raise ValueError(f"buffer_m must be >= 1, got {self.buffer_m}")
        if self.staleness == "cutoff":
            if self.cutoff is None:
                raise ValueError(
                    "staleness='cutoff' needs an integer `cutoff`"
                )
            if int(self.cutoff) < 0:
                # a negative bound would exclude even fresh (tau=0) rows —
                # and the zero-delay static specialization (asyncfl/
                # engine.py) is only a faithful shortcut when tau=0 rows
                # are included
                raise ValueError(
                    f"cutoff must be >= 0, got {self.cutoff}"
                )

    # -- fixed-shape async state ----------------------------------------------

    def init_state(self, num_clients: int, dim: int) -> Dict[str, Any]:
        """Initial ``RoundState.async_state`` pytree. Everything a resumed
        run needs to replay the async dynamics bit-exactly: the buffer +
        occupancy, per-client download versions and arrival countdowns,
        the cumulative fire counter, and (only when the process can lag)
        the ``[max_delay + 1, D]`` published-params ring.

        Countdown starts at 0 for every client — round 0 is a warm
        synchronous start (every client downloaded version 0 and reports
        immediately); the arrival process staggers them from round 1 on.
        """
        k, d = int(num_clients), int(dim)
        state: Dict[str, Any] = {
            "buf": jnp.zeros((k, d), jnp.float32),
            "buf_mask": jnp.zeros((k,), bool),
            # download version of the update sitting in each buffer slot
            # (staleness base at fire time; the in-flight `version` below
            # moves on when the client re-downloads)
            "buf_version": jnp.zeros((k,), jnp.int32),
            "version": jnp.zeros((k,), jnp.int32),
            "countdown": jnp.zeros((k,), jnp.int32),
            "fires": jnp.zeros((), jnp.int32),
        }
        if self.arrivals.max_delay > 0:
            state["hist"] = jnp.zeros(
                (self.arrivals.history_len, d), jnp.float32
            )
        return state

    # -- staleness weighting ---------------------------------------------------

    def staleness_mask_weights(
        self, tau: jnp.ndarray, mask: jnp.ndarray
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """``(agg_mask, weights)`` for one fire: the participation mask
        after the cutoff rule and the **mean-1-normalized** per-row
        weights over it.

        ``tau`` is the ``[K]`` int staleness vector (current round minus
        download version; junk at masked-out entries), ``mask`` the
        buffered-occupancy mask. Constant mode returns exact ones (the
        caller statically skips the row multiply — bit-exact degenerate
        equivalence); polynomial returns ``w_i * n / sum(w)`` so the
        honest update scale is weighting-invariant; cutoff excludes stale
        rows from the mask instead of down-weighting them (exclusion
        composes with the registry's masked-row inertness contract).
        """
        mask = jnp.asarray(mask).astype(bool)
        if self.staleness == "cutoff":
            agg_mask = mask & (tau <= jnp.asarray(self.cutoff, tau.dtype))
            return agg_mask, jnp.ones(tau.shape, jnp.float32)
        if self.staleness == "constant":
            return mask, jnp.ones(tau.shape, jnp.float32)
        # polynomial: 1 / (1 + tau)^alpha, normalized to mean 1 over mask
        raw = jnp.power(
            1.0 + jnp.maximum(tau, 0).astype(jnp.float32), -float(self.alpha)
        )
        raw = jnp.where(mask, raw, 0.0)
        n = jnp.sum(mask.astype(jnp.float32))
        denom = jnp.maximum(jnp.sum(raw), 1e-12)
        w = raw * (jnp.maximum(n, 1.0) / denom)
        return mask, jnp.where(mask, w, 1.0)

    @property
    def weights_are_identity(self) -> bool:
        """Static: True when no row multiply needs tracing (constant and
        cutoff modes — cutoff acts through the mask)."""
        return self.staleness in ("constant", "cutoff")

    def __repr__(self) -> str:
        parts = [f"m={self.buffer_m}", repr(self.arrivals)]
        if self.staleness == "polynomial":
            parts.append(f"poly(a={self.alpha})")
        elif self.staleness == "cutoff":
            parts.append(f"cutoff({self.cutoff})")
        else:
            parts.append("constant")
        return f"AsyncConfig({', '.join(parts)})"
