"""The buffered-asynchronous round body: FedBuff semantics as ONE jitted
fixed-shape program, sibling of the engine's dense/streaming bodies.

``async_round(engine, ...)`` is traced by
:meth:`blades_tpu.core.RoundEngine._round` whenever the engine was built
with ``async_config=``; it returns the same output structure as the sync
bodies (plus the async diagnostics slot), so ``run_round`` / ``run_block``
— and therefore round-block scanning, crash-autosave, bit-exact resume,
telemetry and the compile-count gates — ride unchanged.

One server round (one tick of the async clock), all masks and ``where``\\s:

1. **publish** — when the arrival process can lag (``max_delay > 0``),
   write the current flat params into the ``[max_delay + 1, D]`` version
   ring and gather each client's download version back out, so arriving
   clients train against the model *they* downloaded (version-lagged
   params as fixed-shape state). ``max_delay == 0`` statically skips the
   ring and trains from the live params through the exact same code path
   as the sync round;
2. **train + attack + faults** — every client trains fixed-shape (the
   non-arriving clients' work is masked out, the fault layer's discipline);
   the attack's ``on_updates`` hook and the optional
   :class:`~blades_tpu.faults.FaultModel` apply exactly as in the sync
   body. A fault-dropped arrival is *lost* (the client re-downloads and
   moves on) — dropout composes with arrival timing;
3. **deposit** — arriving, delivered updates land in their client's buffer
   slot (one slot per client: a client has at most one update in flight;
   newest wins). The slot records the download version for staleness;
4. **fire** — when the buffer holds >= ``buffer_m`` updates the server
   aggregates the buffered set through the registry's mask-aware surface
   (``Aggregator.aggregate_masked``) over rows scaled by the normalized
   staleness weights (``asyncfl/buffer.py``), runs the
   :class:`~blades_tpu.audit.AuditMonitor` certificates over those SAME
   staleness-weighted rows, applies the (possibly fallback) aggregate as
   the pseudo-gradient, and drains the buffer. Non-fired ticks leave
   params, server-opt state and aggregator state bit-untouched (gated
   ``where``\\s);
5. **re-download** — arrived clients take the post-step model (version
   ``t + 1``) and draw a fresh delay from the ``rng.ARRIVAL`` stream.

**Static sync specialization** (the bit-exactness anchor): with zero-delay
arrivals and no fault model, the schedule is *statically* synchronous —
every client arrives every tick with staleness 0, the deposit mask is
all-true by construction, the tick always fires, and every staleness mode
weighs fresh rows at exactly 1. The body detects this at trace time and
routes aggregation/audit/metrics through the **identical unmasked calls
the sync body traces** (no mask selects, no gating ``where``\\s, no
weight multiplies anywhere near the defense arithmetic), because XLA's
fusion is free to contract a mathematically-identity masked expression
(e.g. ``sum(u * mask) / n`` with FMA) 1 ulp away from the plain reduction
— close is not the contract. ``buffer_m=K`` + zero delays + constant
weighting is therefore bit-identical to the sync round across the full
aggregator registry (``tests/test_asyncfl.py``), structurally rather than
by compiler luck; any delay, fault model, or ``buffer_m`` that can leave
a tick unfired exercises the general masked path.

Reference counterpart: none — the reference simulator is strictly
synchronous (``src/blades/simulator.py:203-247``); FedBuff semantics
follow Nguyen et al. (AISTATS 2022), staleness weighting the polynomial
family surveyed there and in the asynchronous-SGD robustness line
(Zeno++ / BASGD).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from blades_tpu.ops.pytree import ravel
from blades_tpu.telemetry.metric_pack import pack_dense
from blades_tpu.utils import rng


def _tree_where(pred, new: Any, old: Any) -> Any:
    """Gate a whole pytree on a scalar bool (fired -> advanced state)."""
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(pred, a, b), new, old
    )


def _rows_where(mask: jnp.ndarray, new: Any, old: Any) -> Any:
    """Per-client gate along the leading K axis of every leaf."""

    def pick(a, b):
        m = mask.reshape((mask.shape[0],) + (1,) * (a.ndim - 1))
        return jnp.where(m, a, b)

    return jax.tree_util.tree_map(pick, new, old)


def async_round(engine, state, cx, cy, client_lr, server_lr, key):
    """One buffered-asynchronous server tick (see module docstring).

    Output structure matches the sync bodies:
    ``(new_state, metrics, updates-or-(), agg_diag, fault_diag,
    audit_diag, metric_pack, async_diag)``.
    """
    from blades_tpu.core.engine import RoundMetrics, RoundState

    cfg = engine.async_config
    astate = state.async_state
    k = engine.num_clients
    t = state.round_idx
    round_key = rng.key_for_round(key, t)
    client_keys = rng.key_per_client(round_key, k)
    attack_key = jax.random.fold_in(round_key, rng.ATTACK)
    # statically-synchronous schedule: zero delays + no faults => every
    # tick is a full arrival, a guaranteed fire, staleness 0, weight 1
    # (see "Static sync specialization" in the module docstring)
    static_sync = cfg.arrivals.kind == "zero" and engine.fault_model is None

    if engine.plan is not None:
        cx = lax.with_sharding_constraint(cx, engine.plan.clients)
        cy = lax.with_sharding_constraint(cy, engine.plan.clients)

    # -- 1. publish the current model version + gather download versions ----
    lagged_flat = None
    hist = astate.get("hist")
    if hist is not None:
        h = hist.shape[0]
        hist = lax.dynamic_update_index_in_dim(
            hist, ravel(state.params).astype(hist.dtype), jnp.mod(t, h), axis=0
        )
        # per-client start params: the version each client downloaded
        # (ring depth covers every reachable lag, arrivals.history_len)
        lagged_flat = jnp.take(
            hist, jnp.mod(astate["version"], h), axis=0
        )
        if engine.plan is not None:
            # clients-axis constraint ONLY (the model-axis reshard
            # miscompile rule, core/engine.py)
            lagged_flat = lax.with_sharding_constraint(
                lagged_flat, engine.plan.clients
            )

    # -- 2. fixed-shape training of all K clients + attack + faults ---------
    updates, new_client_opt, losses, top1s = engine._train_clients(
        state.params, state.client_opt_state, client_lr, cx, cy,
        client_keys, lagged_flat=lagged_flat,
    )
    updates = jnp.nan_to_num(updates)
    if engine.plan is not None:
        updates = lax.with_sharding_constraint(updates, engine.plan.clients)
    updates, attack_state = engine.attack.on_updates(
        updates, engine.byz_mask, attack_key, state.attack_state
    )

    sent_updates = updates
    fault_state = state.fault_state
    fault_diag = {}
    part_mask = None
    if engine.fault_model is not None:
        fault_key = jax.random.fold_in(round_key, rng.FAULT)
        updates, part_mask, fault_state, fault_diag = engine.fault_model.apply(
            updates, fault_state, fault_key, t
        )

    # -- 3. deposit into per-client buffer slots ----------------------------
    if static_sync:
        arriving = jnp.ones(k, bool)
        deposit = arriving
        buf = updates  # all-true deposit: the buffer IS this tick's matrix
        buf_mask = arriving
        buf_version = astate["version"]
        n_deposit = jnp.asarray(k, jnp.int32)
        count = jnp.asarray(k, jnp.int32)
        fired = jnp.ones((), bool)  # buffer_m clamps to [1, K]
    else:
        arriving = astate["countdown"] <= 0
        deposit = arriving if part_mask is None else (arriving & part_mask)
        buf = jnp.where(deposit[:, None], updates, astate["buf"])
        buf_mask = astate["buf_mask"] | deposit
        buf_version = jnp.where(
            deposit, astate["version"], astate["buf_version"]
        )
        n_deposit = jnp.sum(deposit.astype(jnp.int32))
        count = jnp.sum(buf_mask.astype(jnp.int32))
        fired = count >= jnp.asarray(engine.async_buffer_m, count.dtype)
    if engine.plan is not None:
        buf = lax.with_sharding_constraint(buf, engine.plan.clients)

    # -- 4. staleness-weighted aggregation + audit, gated on fire -----------
    agg_ctx = dict(
        trusted_mask=engine.trusted_mask,
        params_flat=ravel(state.params),
        key=jax.random.fold_in(round_key, rng.AGG),
    )
    if static_sync:
        # staleness is 0 by construction and w(0) normalizes to exactly 1
        # in every mode; route through the IDENTICAL unmasked calls the
        # sync body traces (bit-exact degenerate equivalence, see module
        # docstring)
        tau = jnp.zeros((k,), jnp.int32)
        agg_mask = buf_mask
        weights = jnp.ones((k,), jnp.float32)
        weighted = buf
        n_agg = count
        if engine.collect_diagnostics:
            agg, agg_state, agg_diag = (
                engine.aggregator.aggregate_with_diagnostics(
                    buf, state.agg_state, **agg_ctx
                )
            )
        else:
            agg, agg_state = engine.aggregator.aggregate(
                buf, state.agg_state, **agg_ctx
            )
            agg_diag = {}
        audit_diag = {}
        if engine.audit_monitor is not None:
            agg, audit_diag = engine.audit_monitor.apply(
                buf, agg, mask=None, byz_mask=engine.byz_mask, **agg_ctx
            )
    else:
        tau = (t - buf_version).astype(jnp.int32)
        agg_mask, weights = cfg.staleness_mask_weights(tau, buf_mask)
        # constant/cutoff: statically NO row multiply (exact identity)
        weighted = (
            buf if cfg.weights_are_identity else buf * weights[:, None]
        )
        if engine.collect_diagnostics:
            agg, agg_state, agg_diag = (
                engine.aggregator.aggregate_masked_with_diagnostics(
                    weighted, state.agg_state, mask=agg_mask, **agg_ctx
                )
            )
        else:
            agg, agg_state = engine.aggregator.aggregate_masked(
                weighted, state.agg_state, mask=agg_mask, **agg_ctx
            )
            agg_diag = {}
        n_agg = jnp.sum(agg_mask.astype(jnp.int32))
        # graceful skip: an empty aggregated set applies the zero
        # pseudo-gradient (the sync body's zero-participant rule)
        agg = jnp.where(n_agg > 0, agg, jnp.zeros_like(agg))

        audit_diag = {}
        if engine.audit_monitor is not None:
            # certificates over the staleness-weighted rows the defense
            # actually consumed; the oracle honest-reference fields compare
            # against the honest mean of that same weighted set
            agg, audit_diag = engine.audit_monitor.apply(
                weighted, agg, mask=agg_mask, byz_mask=engine.byz_mask,
                **agg_ctx,
            )

        # gate everything the fire owns: a non-fired tick must leave
        # model, server-opt and aggregator state bit-untouched
        agg = jnp.where(fired, agg, jnp.zeros_like(agg))
        agg_state = _tree_where(fired, agg_state, state.agg_state)
        if audit_diag:
            # a breach on a tick that never fired swapped nothing in
            audit_diag = dict(audit_diag)
            audit_diag["breach"] = (
                audit_diag["breach"] * fired.astype(jnp.int32)
            )
            audit_diag["fallback_used"] = (
                audit_diag["fallback_used"] * fired.astype(jnp.int32)
            )
            audit_diag["agg_norm"] = jnp.linalg.norm(agg)

    metric_pack = ()
    if engine.round_metrics:
        # the pack folds the matrix the defense consumed against the
        # aggregate the server APPLIES — same contract as the sync bodies
        metric_pack = pack_dense(
            weighted, agg_mask, engine.byz_mask, agg,
            engine.client_chunks, engine.chunk_size,
        )

    grad_tree = engine.unravel(-agg)
    server_updates, server_opt_state = engine._server_tx.update(
        grad_tree, state.server_opt_state, state.params
    )
    params = jax.tree_util.tree_map(
        lambda p, u: p - server_lr * u.astype(p.dtype),
        state.params,
        server_updates,
    )
    if not static_sync:
        params = _tree_where(fired, params, state.params)
        server_opt_state = _tree_where(
            fired, server_opt_state, state.server_opt_state
        )
        # client-side state advances only for clients that really trained
        # (arrived) this tick — the fixed-shape work of the others is
        # discarded
        if engine.client_opt.persist:
            new_client_opt = _rows_where(
                arriving, new_client_opt, state.client_opt_state
            )

    # -- 5. drain on fire; arrived clients re-download + redraw delays ------
    new_delays = cfg.arrivals.draw(round_key, k)
    fired_i = fired.astype(jnp.int32)
    t_next = (t + 1).astype(astate["version"].dtype)
    new_astate = dict(astate)
    new_astate["buf"] = buf
    new_astate["buf_mask"] = buf_mask & ~fired
    new_astate["buf_version"] = buf_version
    new_astate["version"] = jnp.where(
        arriving, t_next, astate["version"]
    )
    new_astate["countdown"] = jnp.where(
        arriving, new_delays, jnp.maximum(astate["countdown"] - 1, 0)
    )
    new_astate["fires"] = astate["fires"] + fired_i
    if hist is not None:
        new_astate["hist"] = hist

    agg_w = agg_mask.astype(jnp.float32)
    mean_tau = jnp.where(
        fired & (n_agg > 0),
        jnp.sum(tau.astype(jnp.float32) * agg_w)
        / jnp.maximum(n_agg.astype(jnp.float32), 1.0),
        0.0,
    )
    max_tau = jnp.where(
        fired, jnp.max(jnp.where(agg_mask, tau, 0)), 0
    ).astype(jnp.int32)
    async_diag = {
        "arrivals": jnp.sum(arriving.astype(jnp.int32)),
        "deposited": n_deposit,
        "buffer_count": count,
        "fired": fired_i,
        "aggregated": jnp.where(fired, n_agg, 0).astype(jnp.int32),
        "fires_total": new_astate["fires"],
        "mean_staleness": mean_tau,
        "max_staleness": max_tau,
        "stale_excluded": jnp.sum((buf_mask & ~agg_mask).astype(jnp.int32)),
        "weight_min": jnp.where(
            fired & (n_agg > 0),
            jnp.min(jnp.where(agg_mask, weights, jnp.inf)),
            1.0,
        ),
    }

    honest = (~engine.byz_mask).astype(losses.dtype)
    n_honest = jnp.maximum(honest.sum(), 1.0)
    var = sent_updates.var(axis=0)
    metrics = RoundMetrics(
        train_loss=(losses * honest).sum() / n_honest,
        train_loss_all=losses.mean(),
        train_top1=(top1s * honest).sum() / n_honest,
        update_variance=var.mean(),
        update_variance_norm=jnp.linalg.norm(var),
        agg_norm=jnp.linalg.norm(agg),
    )
    new_state = RoundState(
        params=params,
        server_opt_state=server_opt_state,
        client_opt_state=(
            new_client_opt if engine.client_opt.persist else ()
        ),
        agg_state=agg_state,
        attack_state=attack_state,
        round_idx=state.round_idx + 1,
        fault_state=fault_state,
        async_state=new_astate,
    )
    return (
        new_state,
        metrics,
        # same rule as the dense body: under a fault model the observable
        # matrix is what the server RECEIVED (corruption applied), not
        # what the clients sent
        updates if engine.keep_updates else (),
        agg_diag,
        fault_diag,
        audit_diag,
        metric_pack,
        async_diag,
    )
