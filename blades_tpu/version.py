"""Package version (reference counterpart: none — the reference keeps
its version in setuptools metadata only)."""

__version__ = "0.1.0"
