"""Geometric median via smoothed Weiszfeld (Chen et al., 2017).

Reference: ``Geomed`` (``src/blades/aggregators/geomed.py:35-84``): start from
the mean, iterate ``w_i <- max(eps, a_i / max(eps, |z - x_i|))`` (normalized),
``z <- sum_i w_i x_i``, stopping when the weighted objective improves by less
than ``ftol`` relatively, or after ``maxiter`` rounds. The reference runs this
as a host-side Python loop with one ``.item()`` device sync per client per
iteration; here it is a single ``lax.while_loop`` with batched distance
computations, so the entire solve stays on device.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator, TwoLevelStreaming


def weiszfeld(
    updates: jnp.ndarray,
    init_weights: Optional[jnp.ndarray] = None,
    maxiter: int = 100,
    eps: float = 1e-6,
    ftol: float = 1e-10,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Solve ``argmin_z sum_i a_i |z - x_i|`` over rows of ``updates``.

    ``mask`` restricts the solve to the participating rows (``None`` is the
    full population, bit-identical to the pre-mask behavior): masked-out
    rows start at zero weight and the ``eps`` weight floor — which would
    otherwise resurrect them — is re-masked every iteration.
    """
    k = updates.shape[0]
    msk = None if mask is None else mask.astype(updates.dtype)
    if init_weights is None:
        if msk is None:
            alphas0 = jnp.full((k,), 1.0 / k, dtype=updates.dtype)
        else:
            alphas0 = msk / jnp.maximum(jnp.sum(msk), 1.0)
    else:
        alphas0 = init_weights.astype(updates.dtype)
        if msk is not None:
            alphas0 = alphas0 * msk

    def dists(z):
        return jnp.sqrt(jnp.maximum(jnp.sum((updates - z) ** 2, axis=1), 0.0))

    if msk is None:
        z0 = jnp.mean(updates, axis=0)
    else:
        z0 = jnp.sum(updates * msk[:, None], axis=0) / jnp.maximum(
            jnp.sum(msk), 1.0
        )
    obj0 = jnp.sum(alphas0 * dists(z0))

    def cond(carry):
        i, _, _, obj, prev_obj = carry
        not_converged = jnp.abs(prev_obj - obj) >= ftol * obj
        return jnp.logical_and(i < maxiter, not_converged)

    def body(carry):
        i, z, alphas, obj, _ = carry
        d = dists(z)
        w = jnp.maximum(eps, alphas / jnp.maximum(eps, d))
        if msk is not None:
            w = w * msk
        w = w / jnp.sum(w)
        z_new = w @ updates
        obj_new = jnp.sum(w * dists(z_new))
        return i + 1, z_new, w, obj_new, obj

    _, z, _, _, _ = jax.lax.while_loop(
        cond, body, (jnp.array(0), z0, alphas0, obj0, jnp.inf)
    )
    return z


class Geomed(TwoLevelStreaming, Aggregator):
    """Streaming form: two-level — an exact Weiszfeld solve *within* each
    chunk, then a participant-count-weighted Weiszfeld across the chunk
    geometric medians (each Weiszfeld step consumes the ``[num_chunks, D]``
    chunk stack, never the rows). The exact single-pass form does not
    exist: Weiszfeld re-weights every ROW by its distance to the current
    iterate, which is known only after the full pass — a single-pass state
    would have to retain the rows, i.e. be ``[K, D]``. Both levels return
    convex combinations of delivered rows (hull-bounded in
    ``tests/test_streaming.py``); the chunk medians' ~1/sqrt(chunk)
    concentration is the classic median-of-means argument."""

    def __init__(self, maxiter: int = 100, eps: float = 1e-6, ftol: float = 1e-10):
        self.maxiter = maxiter
        self.eps = eps
        self.ftol = ftol

    def aggregate(self, updates, state=(), *, weights=None, **ctx):
        z = weiszfeld(
            updates,
            init_weights=weights,
            maxiter=self.maxiter,
            eps=self.eps,
            ftol=self.ftol,
        )
        return z, state

    def _masked_aggregate(self, updates, state, *, mask, weights=None, **ctx):
        z = weiszfeld(
            updates,
            init_weights=weights,
            maxiter=self.maxiter,
            eps=self.eps,
            ftol=self.ftol,
            mask=mask,
        )
        n = jnp.sum(mask.astype(updates.dtype))
        return jnp.where(n > 0, z, jnp.zeros_like(z)), state

    def _combine_chunk_aggs(self, aggs, counts, state, **ctx):
        # count-weighted recombination: a chunk median representing n_j
        # rows enters the across-chunk solve with mass n_j (the Weiszfeld
        # alphas), so unequal participation does not skew the result
        w = counts.astype(aggs.dtype)
        total = jnp.sum(w)
        z = weiszfeld(
            aggs,
            init_weights=w / jnp.maximum(total, 1.0),
            maxiter=self.maxiter,
            eps=self.eps,
            ftol=self.ftol,
            mask=counts > 0,
        )
        return jnp.where(total > 0, z, jnp.zeros_like(z)), state
