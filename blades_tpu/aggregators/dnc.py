"""Divide-and-Conquer aggregation (Shejwalkar & Houmansadr, NDSS 2021).

Not present in the reference's aggregator package but named in the driver
benchmark configs (BASELINE.md config 5), so it is a first-class defense here.

Per iteration: subsample ``sub_dim`` coordinates, mean-center the submatrix,
estimate its top right-singular vector by power iteration (jit-friendly, no
full SVD), score each client by its squared projection onto that direction,
and flag the ``c * f`` highest-scoring clients as outliers. The final
aggregate is the mean of clients that survive every iteration.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator


def _top_singular_dir(x: jnp.ndarray, iters: int, key: jax.Array) -> jnp.ndarray:
    """Top right-singular vector of ``x [K, d]`` via power iteration on x^T x."""
    v = jax.random.normal(key, (x.shape[1],), dtype=x.dtype)
    v = v / jnp.sqrt(jnp.sum(v**2))

    def body(_, v):
        v = x.T @ (x @ v)
        return v / jnp.sqrt(jnp.maximum(jnp.sum(v**2), 1e-24))

    return jax.lax.fori_loop(0, iters, body, v)


class Dnc(Aggregator):
    # streaming opt-out (tests/test_streaming.py registry lint): each
    # iteration scores every row by its projection onto the top singular
    # direction of the full centered submatrix — the direction exists only
    # after the whole population is seen, and the scoring pass must then
    # revisit every row (and the next iteration repeats both passes on the
    # surviving set).
    streaming_optouts = {
        "streaming": "outlier scores project every row onto a population-"
                     "level principal direction known only after the full "
                     "pass; each of num_iters rounds needs a fresh "
                     "two-pass sweep",
    }

    def __init__(
        self,
        num_byzantine: int = 5,
        sub_dim: int = 10000,
        num_iters: int = 5,
        filter_frac: float = 1.0,
        power_iters: int = 10,
    ):
        self.f = num_byzantine
        self.sub_dim = sub_dim
        self.num_iters = num_iters
        self.filter_frac = filter_frac
        self.power_iters = power_iters

    def aggregate(self, updates, state=(), *, key=None, **ctx):
        return self._aggregate_impl(updates, state, key, None)

    def _masked_aggregate(self, updates, state, *, mask, key=None, **ctx):
        return self._aggregate_impl(updates, state, key, mask)

    def _aggregate_impl(self, updates, state, key, mask):
        """``mask=None`` is the full-population program. Under partial
        participation the principal direction and the outlier scores are
        computed over participants only (absent rows contribute zero to the
        centered submatrix), and the ``c*f`` removal budget still targets
        the largest PARTICIPANT scores (absent rows score ``-inf``)."""
        if key is None:
            key = jax.random.key(0)
        k, d = updates.shape
        sub_dim = min(self.sub_dim, d)
        n_remove = int(self.filter_frac * self.f)
        n_remove = min(n_remove, k - 1)

        def one_iter(carry, subkey):
            good = carry
            k_idx, k_init = jax.random.split(subkey)
            idx = jax.random.choice(k_idx, d, shape=(sub_dim,), replace=False)
            sub = updates[:, idx]
            if mask is None:
                centered = sub - jnp.mean(sub, axis=0)
            else:
                m = mask.astype(sub.dtype)
                mean = jnp.sum(sub * m[:, None], axis=0) / jnp.maximum(
                    jnp.sum(m), 1.0
                )
                centered = jnp.where(mask[:, None], sub - mean, 0.0)
            v = _top_singular_dir(centered, self.power_iters, k_init)
            scores = (centered @ v) ** 2
            if mask is not None:
                scores = jnp.where(mask, scores, -jnp.inf)
            # keep everyone except the n_remove largest scores
            cutoff = jnp.sort(scores)[k - n_remove - 1]
            good = good & (scores <= cutoff)
            return good, None

        keys = jax.random.split(key, self.num_iters)
        good0 = jnp.ones((k,), dtype=bool) if mask is None else mask
        good, _ = jax.lax.scan(one_iter, good0, keys)
        w = good.astype(updates.dtype)
        return (w @ updates) / jnp.maximum(jnp.sum(w), 1.0), state

    def __repr__(self):
        return f"DnC (f={self.f}, iters={self.num_iters})"
