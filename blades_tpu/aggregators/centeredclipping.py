"""Centered clipping (Karimireddy et al., ICML 2021).

Reference: ``Centeredclipping`` (``src/blades/aggregators/centeredclipping.py:13-58``):
keeps a momentum center ``v`` across rounds and iterates
``v <- v + mean_i clip(u_i - v, tau)`` for ``n_iter`` inner rounds, where
``clip(x) = x * min(1, tau/|x|)``.

The reference mutates ``self.momentum``; here the momentum is explicit
aggregator state threaded through the jitted round, which is what makes the
defense compilable and checkpointable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator
from blades_tpu.ops.streaming import stack_init, stack_write, weighted_stack_mean


class Centeredclipping(Aggregator):
    """Streaming form: each chunk runs the full ``n_iter`` clipping
    iteration from the SHARED round-start momentum ``v0`` (the aggregator
    state — known before the pass), producing a chunk momentum; finalize
    is the participant-count-weighted mean of chunk momenta. For
    ``n_iter == 1`` this is EXACT: the single iteration is
    ``v0 + mean_i clip(u_i - v0)``, and the count-weighted mean of chunk
    means recombines it exactly (``streaming_exact`` reflects that). For
    ``n_iter > 1`` later iterations re-clip every row around an updated
    center known only after a full pass, so the chunk-local iteration is a
    documented two-level approximation (bounded in
    ``tests/test_streaming.py``)."""

    stateful = True

    def __init__(self, tau: float = 10.0, n_iter: int = 5):
        self.tau = tau
        self.n_iter = n_iter

    def init_state(self, num_clients: int, dim: int):
        return jnp.zeros((dim,), dtype=jnp.float32)

    def aggregate(self, updates, state, **ctx):
        tau = self.tau

        def clip_rows(v):
            norms = jnp.sqrt(jnp.maximum(jnp.sum(v * v, axis=1), 1e-24))
            scale = jnp.minimum(1.0, tau / norms)
            return v * scale[:, None]

        def body(_, momentum):
            return momentum + jnp.mean(clip_rows(updates - momentum), axis=0)

        momentum = jax.lax.fori_loop(0, self.n_iter, body, state.astype(updates.dtype))
        return momentum, momentum

    def _masked_aggregate(self, updates, state, *, mask, **ctx):
        # masked mean of the clipped differences: absent clients neither
        # pull the momentum nor damp it (unlike the async variant, which
        # deliberately keeps K in the denominator)
        tau = self.tau
        m = mask.astype(updates.dtype)
        denom = jnp.maximum(jnp.sum(m), 1.0)

        def clip_rows(v):
            norms = jnp.sqrt(jnp.maximum(jnp.sum(v * v, axis=1), 1e-24))
            scale = jnp.minimum(1.0, tau / norms)
            return v * scale[:, None]

        def body(_, momentum):
            clipped = clip_rows(updates - momentum)
            return momentum + jnp.sum(clipped * m[:, None], axis=0) / denom

        momentum = jax.lax.fori_loop(0, self.n_iter, body, state.astype(updates.dtype))
        return momentum, momentum

    @property
    def streaming_exact(self):  # type: ignore[override]
        # one inner iteration decomposes exactly over chunks (see class
        # docstring); more re-center against a mid-pass statistic
        return self.n_iter == 1

    def streaming_init(self, num_clients, num_chunks, chunk_size, dim, state=()):
        v0 = (
            jnp.zeros((dim,), jnp.float32)
            if state is None or (isinstance(state, tuple) and state == ())
            else jnp.asarray(state)
        )
        return {
            "v0": v0,
            "momenta": stack_init(num_chunks, (dim,)),
            "counts": jnp.zeros((num_chunks,), jnp.int32),
        }

    def streaming_update(
        self, sstate, chunk_updates, *, chunk_mask, chunk_index, **ctx
    ):
        m_j, _ = self._masked_aggregate(
            chunk_updates, sstate["v0"], mask=chunk_mask
        )
        n = jnp.sum(chunk_mask.astype(jnp.int32))
        return {
            "v0": sstate["v0"],
            "momenta": stack_write(sstate["momenta"], chunk_index, m_j),
            "counts": stack_write(sstate["counts"], chunk_index, n),
        }

    def streaming_finalize(self, sstate, state=(), **ctx):
        total = jnp.sum(sstate["counts"])
        if sstate["momenta"].shape[0] == 1:
            # single chunk: its momentum IS the result (the weighted mean
            # would multiply-and-divide by the count — same value, different
            # bits; the short-circuit keeps num_chunks=1 bit-exact)
            v = sstate["momenta"][0]
        else:
            v = weighted_stack_mean(sstate["momenta"], sstate["counts"])
        # an empty round moves nothing: momentum (and therefore the next
        # round's state) stays at v0, matching the dense masked path
        momentum = jnp.where(total > 0, v, sstate["v0"])
        return momentum, momentum

    def diagnostics(self, updates, state=(), **ctx):
        """Forensics: per-client distance from the incoming momentum center
        and whether the clip engaged (``|u_i - v| > tau``) on the first
        inner iteration — which clients the defense had to restrain."""
        v = state.astype(updates.dtype)
        norms = jnp.sqrt(jnp.maximum(jnp.sum((updates - v) ** 2, axis=1), 1e-24))
        return {"clip_norms": norms, "clipped": norms > self.tau}

    def __repr__(self):
        return f"Clipping (tau={self.tau}, n_iter={self.n_iter})"
