"""Centered clipping (Karimireddy et al., ICML 2021).

Reference: ``Centeredclipping`` (``src/blades/aggregators/centeredclipping.py:13-58``):
keeps a momentum center ``v`` across rounds and iterates
``v <- v + mean_i clip(u_i - v, tau)`` for ``n_iter`` inner rounds, where
``clip(x) = x * min(1, tau/|x|)``.

The reference mutates ``self.momentum``; here the momentum is explicit
aggregator state threaded through the jitted round, which is what makes the
defense compilable and checkpointable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator


class Centeredclipping(Aggregator):
    stateful = True

    def __init__(self, tau: float = 10.0, n_iter: int = 5):
        self.tau = tau
        self.n_iter = n_iter

    def init_state(self, num_clients: int, dim: int):
        return jnp.zeros((dim,), dtype=jnp.float32)

    def aggregate(self, updates, state, **ctx):
        tau = self.tau

        def clip_rows(v):
            norms = jnp.sqrt(jnp.maximum(jnp.sum(v * v, axis=1), 1e-24))
            scale = jnp.minimum(1.0, tau / norms)
            return v * scale[:, None]

        def body(_, momentum):
            return momentum + jnp.mean(clip_rows(updates - momentum), axis=0)

        momentum = jax.lax.fori_loop(0, self.n_iter, body, state.astype(updates.dtype))
        return momentum, momentum

    def _masked_aggregate(self, updates, state, *, mask, **ctx):
        # masked mean of the clipped differences: absent clients neither
        # pull the momentum nor damp it (unlike the async variant, which
        # deliberately keeps K in the denominator)
        tau = self.tau
        m = mask.astype(updates.dtype)
        denom = jnp.maximum(jnp.sum(m), 1.0)

        def clip_rows(v):
            norms = jnp.sqrt(jnp.maximum(jnp.sum(v * v, axis=1), 1e-24))
            scale = jnp.minimum(1.0, tau / norms)
            return v * scale[:, None]

        def body(_, momentum):
            clipped = clip_rows(updates - momentum)
            return momentum + jnp.sum(clipped * m[:, None], axis=0) / denom

        momentum = jax.lax.fori_loop(0, self.n_iter, body, state.astype(updates.dtype))
        return momentum, momentum

    def diagnostics(self, updates, state=(), **ctx):
        """Forensics: per-client distance from the incoming momentum center
        and whether the clip engaged (``|u_i - v| > tau``) on the first
        inner iteration — which clients the defense had to restrain."""
        v = state.astype(updates.dtype)
        norms = jnp.sqrt(jnp.maximum(jnp.sum((updates - v) ** 2, axis=1), 1e-24))
        return {"clip_norms": norms, "clipped": norms > self.tau}

    def __repr__(self):
        return f"Clipping (tau={self.tau}, n_iter={self.n_iter})"
