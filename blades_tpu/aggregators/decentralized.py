"""Decentralized (gossip) aggregation and the async aggregator variants.

Reference counterparts, all unexported internals:

- ``_DecentralizedAggregator`` (``src/blades/aggregators/mean.py:89-116``):
  each node combines its own update with its neighbors' using one row of a
  mixing matrix — a Python loop over edge objects, run once per node.
- ``_AnchorClipping`` (``aggregators/centeredclipping.py:52-104``): the
  gossip variant of centered clipping — every incoming update is clipped
  toward a per-node anchor that tracks the node's own parameter trajectory.
- ``_BaseAsyncAggregator`` / ``_AsyncMean`` / ``_AsyncCenteredClipping``
  (``mean.py:42-87``, ``centeredclipping.py:106-137``): aggregation when
  only a subset of workers reported this round; missing entries still count
  in the denominator (the deliberate 1/n damping of the async setting).

TPU-native design: the per-node loops collapse into dense linear algebra on
the ``[K, D]`` update matrix. One gossip step for ALL nodes simultaneously is
a single mixing matmul ``W @ U`` ([K,K]x[K,D] — MXU-shaped, sharded along
both axes by the mesh plan), instead of K Python loops over neighbor lists.
Async participation is a boolean ``present`` mask: absent rows are zeroed
and the denominator stays K.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from blades_tpu.aggregators.base import Aggregator


# -- mixing-matrix builders (host-side, numpy) --------------------------------


def ring_adjacency(k: int) -> np.ndarray:
    """Ring topology: node i <-> i±1 (mod k)."""
    a = np.zeros((k, k), bool)
    idx = np.arange(k)
    a[idx, (idx + 1) % k] = True
    a[idx, (idx - 1) % k] = True
    np.fill_diagonal(a, False)
    return a


def torus_adjacency(rows: int, cols: int) -> np.ndarray:
    """2-D torus: node (r, c) <-> its 4 wrap-around grid neighbors."""
    k = rows * cols
    a = np.zeros((k, k), bool)
    for r in range(rows):
        for c in range(cols):
            i = r * cols + c
            for rr, cc in ((r - 1, c), (r + 1, c), (r, c - 1), (r, c + 1)):
                j = (rr % rows) * cols + (cc % cols)
                if j != i:
                    a[i, j] = True
    return a


def fully_connected_adjacency(k: int) -> np.ndarray:
    a = np.ones((k, k), bool)
    np.fill_diagonal(a, False)
    return a


def metropolis_weights(adjacency: np.ndarray) -> np.ndarray:
    """Metropolis–Hastings mixing matrix: symmetric, doubly stochastic for
    any undirected graph — W[i,j] = 1/(1+max(deg_i, deg_j)) on edges, the
    leftover mass on the diagonal."""
    adj = np.asarray(adjacency, bool)
    if not (adj == adj.T).all():
        raise ValueError("adjacency must be symmetric (undirected graph)")
    deg = adj.sum(axis=1)
    w = np.where(adj, 1.0 / (1.0 + np.maximum(deg[:, None], deg[None, :])), 0.0)
    np.fill_diagonal(w, 1.0 - w.sum(axis=1))
    return w


# -- decentralized aggregators ------------------------------------------------


class DecentralizedMixing(Aggregator):
    """One gossip round for every node at once: ``new_updates = W @ updates``
    (reference ``_DecentralizedAggregator.__call__`` looped per node over
    ``self.node.edges``; here all K rows mix in one matmul).

    Unlike server aggregators this returns a ``[K, D]`` matrix — each node's
    own mixture — so it plugs into decentralized training loops rather than
    the server step. ``aggregate`` still returns the mixing-weighted global
    view's row-mean so the class stays usable in the standard engine.
    """

    def __init__(self, weights: np.ndarray):
        self.weights = jnp.asarray(weights, jnp.float32)

    def mix(self, updates: jnp.ndarray) -> jnp.ndarray:
        return self.weights @ updates

    def aggregate(self, updates, state=(), **ctx):
        return self.mix(updates).mean(axis=0), state

    def __repr__(self):
        return f"DecentralizedMixing(K={self.weights.shape[0]})"


class AnchorClipping(DecentralizedMixing):
    """Gossip centered clipping (reference ``_AnchorClipping``): every
    incoming update is pulled toward the receiving node's anchor by a
    clipped difference, then mixed. Anchors track each node's cumulative
    applied update (the reference wraps ``opt.step`` to accumulate parameter
    deltas; here the accumulation is explicit state, updated with the mixed
    result each round).

    State: anchors ``[K, D]``.
    """

    stateful = True

    def __init__(self, weights: np.ndarray, tau: float = 10.0):
        super().__init__(weights)
        self.tau = float(tau)

    def init_state(self, num_clients: int, dim: int):
        return jnp.zeros((num_clients, dim), jnp.float32)

    def mix_with_state(
        self, updates: jnp.ndarray, anchors: jnp.ndarray
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        # The reference clips every incoming update toward the RECEIVER's
        # anchor: clipped[r,s] = a_r + (u_s - a_r) * S[r,s] with
        # S[r,s] = min(1, tau/||u_s - a_r||). Naively that is a [K,K,D]
        # tensor; instead compute pairwise norms by the gram identity
        # ||u_s - a_r||^2 = ||u_s||^2 - 2 a_r.u_s + ||a_r||^2 (one matmul)
        # and fold the scales into the mixing weights, so everything is
        # [K,K] matrices and [K,K]x[K,D] matmuls — no K^2 D intermediate.
        sq = jnp.maximum(
            jnp.sum(updates**2, axis=1)[None, :]
            - 2.0 * anchors @ updates.T
            + jnp.sum(anchors**2, axis=1)[:, None],
            0.0,
        )  # [Kr, Ks]
        scale = jnp.minimum(1.0, self.tau / jnp.maximum(jnp.sqrt(sq), 1e-12))
        ws = self.weights * scale  # [Kr, Ks]
        # sum_s W[r,s] * (a_r + (u_s - a_r) S[r,s])
        #   = a_r * (rowsum(W) - rowsum(W*S)) + (W*S) @ U
        coeff = self.weights.sum(axis=1) - ws.sum(axis=1)  # [Kr]
        mixed = coeff[:, None] * anchors + ws @ updates
        return mixed, anchors + mixed

    def aggregate(self, updates, state=(), **ctx):
        anchors = state
        mixed, anchors = self.mix_with_state(updates, anchors)
        return mixed.mean(axis=0), anchors

    def __repr__(self):
        return f"AnchorClipping(tau={self.tau})"


# -- async aggregators --------------------------------------------------------


class Asyncmean(Aggregator):
    """Async mean (reference ``_AsyncMean``,
    ``src/blades/aggregators/mean.py:42-76``): absent workers contribute
    zero but stay in the denominator — ``sum(present updates) / K``.

    Under the buffered-asynchronous engine (``blades_tpu/asyncfl``) this
    is the **constant-staleness-weighted FedBuff server mean with 1/K
    damping**: each fire aggregates the buffered arrivals through
    :meth:`_masked_aggregate` (the participation mask IS the buffer
    occupancy), staleness weighting ``"constant"`` leaves every buffered
    row at weight 1, and the fixed-K denominator damps the applied step by
    ``n_buffered / K`` — the deliberate under-step of the asynchronous
    setting (a fire fed by few arrivals moves the model proportionally
    less). ``buffer_m = K`` + zero delays recovers plain ``Mean``
    numerically (``sum(u)/K`` vs ``mean(u)`` trace different XLA
    reductions; the BIT-exact contract is async-asyncmean == sync-
    asyncmean, the registry-wide degenerate-equivalence invariant), and
    ``buffer_m < K`` steps are damped by exactly ``n/K`` — both pinned by
    ``tests/test_asyncfl.py``. The reference's class is unreachable dead
    code from its synchronous Simulator; here the registry entry names the
    semantics the async engine actually executes.
    """

    # certification opt-out (blades_tpu.audit): an (async) mean — breakdown
    # point 0, same as Mean (see aggregators/mean.py).
    audit_optouts = {
        "resilience": "breakdown point 0: one unbounded byzantine row moves "
                      "the (async) average arbitrarily far",
    }

    # exact streaming form: sum of present rows / K is a running sum with a
    # static denominator
    streaming_exact = True

    def aggregate(self, updates, state=(), *, present: Optional[jnp.ndarray] = None, **ctx):
        k = updates.shape[0]
        if present is None:
            return updates.mean(axis=0), state
        u = jnp.where(present[:, None], updates, 0.0)
        return u.sum(axis=0) / k, state

    def _masked_aggregate(self, updates, state, *, mask, **ctx):
        # the participation mask IS the async `present` mask; the 1/K
        # damping of absent workers is this family's defining semantics,
        # so it is kept (aggregate_masked already zeroed absent rows)
        return updates.sum(axis=0) / updates.shape[0], state

    def streaming_init(self, num_clients, num_chunks, chunk_size, dim, state=()):
        return {
            "sum": jnp.zeros((dim,), jnp.float32),
            # the static 1/K damping denominator (K = true population, not
            # the padded chunk total)
            "k": jnp.asarray(num_clients, jnp.float32),
        }

    def streaming_update(
        self, sstate, chunk_updates, *, chunk_mask, chunk_index, **ctx
    ):
        w = chunk_mask.astype(chunk_updates.dtype)
        return {
            "sum": sstate["sum"] + jnp.sum(chunk_updates * w[:, None], axis=0),
            "k": sstate["k"],
        }

    def streaming_finalize(self, sstate, state=(), **ctx):
        return sstate["sum"] / sstate["k"], state

    def __repr__(self):
        return "Asyncmean"


class Asynccenteredclipping(Aggregator):
    """Async centered clipping (reference ``_AsyncCenteredClipping``):
    momentum center, clipped differences of the present workers only, but
    damped by 1/K rather than 1/|present|."""

    stateful = True

    # certification opt-out (blades_tpu.audit): one clipping iteration
    # around an origin-initialized momentum — a global translation changes
    # which differences the radius clips, so the single-step aggregate does
    # not translate (the synchronous Centeredclipping converges over n_iter
    # inner steps and passes; this variant deliberately under-steps).
    audit_optouts = {
        "translation": "single clipping step around the origin-anchored "
                       "momentum; the 1/K-damped under-step does not "
                       "translate with the updates",
    }

    def __init__(self, tau: float = 10.0, n_iter: int = 1):
        self.tau = float(tau)
        self.n_iter = int(n_iter)

    def init_state(self, num_clients: int, dim: int):
        return jnp.zeros((dim,), jnp.float32)

    def aggregate(self, updates, state=(), *, present: Optional[jnp.ndarray] = None, **ctx):
        momentum = state
        k = updates.shape[0]
        if present is None:
            present = jnp.ones(k, bool)
        for _ in range(self.n_iter):
            diff = updates - momentum[None, :]
            norm = jnp.linalg.norm(diff, axis=1, keepdims=True)
            clipped = diff * jnp.minimum(1.0, self.tau / jnp.maximum(norm, 1e-12))
            clipped = jnp.where(present[:, None], clipped, 0.0)
            momentum = momentum + clipped.sum(axis=0) / k
        return momentum, momentum

    def _masked_aggregate(self, updates, state, *, mask, **ctx):
        # participation mask -> async `present` mask (1/K damping kept:
        # that deliberate under-step on absences is the async semantics)
        return self.aggregate(updates, state, present=mask)

    @property
    def streaming_exact(self):  # type: ignore[override]
        return self.n_iter == 1

    def supports_streaming(self) -> bool:  # type: ignore[override]
        # exact single-pass form exists ONLY for n_iter=1 (see
        # streaming_init); declaring non-support for n_iter>1 makes the
        # engine reject the config at BUILD time with the documented
        # reason instead of dying mid-trace
        return self.n_iter == 1

    @property
    def streaming_optouts(self):  # type: ignore[override]
        if self.n_iter == 1:
            return {}
        return {
            "streaming": "n_iter>1 re-clips every row against a mid-pass "
                         "center; only the n_iter=1 running clipped sum "
                         "is a single-pass form",
        }

    def streaming_init(self, num_clients, num_chunks, chunk_size, dim, state=()):
        # exact single-pass form for the default n_iter=1: the one
        # iteration is v0 + sum_i clip(u_i - v0) / K, and clip depends only
        # on the round-start momentum — a running clipped sum. More inner
        # iterations would re-clip against a mid-pass center; nobody runs
        # the async variant that way, so it stays unimplemented rather than
        # silently approximated.
        if self.n_iter != 1:
            raise NotImplementedError(self._no_streaming_msg())
        v0 = (
            jnp.zeros((dim,), jnp.float32)
            if state is None or (isinstance(state, tuple) and state == ())
            else jnp.asarray(state)
        )
        return {
            "v0": v0,
            "clip_sum": jnp.zeros((dim,), jnp.float32),
            "k": jnp.asarray(num_clients, jnp.float32),
        }

    def streaming_update(
        self, sstate, chunk_updates, *, chunk_mask, chunk_index, **ctx
    ):
        diff = chunk_updates - sstate["v0"][None, :]
        norm = jnp.linalg.norm(diff, axis=1, keepdims=True)
        clipped = diff * jnp.minimum(1.0, self.tau / jnp.maximum(norm, 1e-12))
        clipped = jnp.where(chunk_mask[:, None], clipped, 0.0)
        return {
            "v0": sstate["v0"],
            "clip_sum": sstate["clip_sum"] + clipped.sum(axis=0),
            "k": sstate["k"],
        }

    def streaming_finalize(self, sstate, state=(), **ctx):
        momentum = sstate["v0"] + sstate["clip_sum"] / sstate["k"]
        return momentum, momentum

    def __repr__(self):
        return f"Asynccenteredclipping(tau={self.tau}, n_iter={self.n_iter})"
