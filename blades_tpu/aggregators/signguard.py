"""SignGuard (Xu et al., ICDCS 2022) — sign-statistics + norm filtering.

Extra defense beyond the reference's catalog (the reference exports eight
schemes, ``src/blades/aggregators/__init__.py``); included because it is a
standard member of the robust-aggregation family this framework targets.

Two filters, both on-device:
  1. norm filter: keep clients whose L2 norm lies within
     ``[lower * median_norm, upper * median_norm]``;
  2. sign filter: cluster clients on their (pos, zero, neg) gradient-sign
     statistics with complete-linkage 2-clustering and keep the majority.
The aggregate is the mean of clients passing both, with norms clipped to the
median.
"""

from __future__ import annotations

import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator
from blades_tpu.ops.clustering import complete_linkage_two_clusters


class Signguard(Aggregator):
    def __init__(self, lower: float = 0.1, upper: float = 3.0):
        self.lower = lower
        self.upper = upper

    def aggregate(self, updates, state=(), **ctx):
        k = updates.shape[0]
        norms = jnp.sqrt(jnp.maximum(jnp.sum(updates**2, axis=1), 1e-24))
        med = jnp.median(norms)
        norm_ok = (norms >= self.lower * med) & (norms <= self.upper * med)

        sign = jnp.sign(updates)
        feats = jnp.stack(
            [
                jnp.mean(sign > 0, axis=1),
                jnp.mean(sign == 0, axis=1),
                jnp.mean(sign < 0, axis=1),
            ],
            axis=1,
        )
        dist = jnp.sqrt(
            jnp.maximum(
                jnp.sum((feats[:, None, :] - feats[None, :, :]) ** 2, axis=-1), 0.0
            )
        )
        labels = complete_linkage_two_clusters(dist)
        size1 = jnp.sum(labels)
        majority = jnp.where(size1 > k - size1, 1, 0)
        sign_ok = labels == majority

        keep = (norm_ok & sign_ok).astype(updates.dtype)
        clip = jnp.minimum(1.0, med / norms)
        clipped = updates * clip[:, None]
        return (keep @ clipped) / jnp.maximum(jnp.sum(keep), 1.0), state
