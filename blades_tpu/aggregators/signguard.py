"""SignGuard (Xu et al., ICDCS 2022) — sign-statistics + norm filtering.

Extra defense beyond the reference's catalog (the reference exports eight
schemes, ``src/blades/aggregators/__init__.py``); included because it is a
standard member of the robust-aggregation family this framework targets.

Two filters, both on-device:
  1. norm filter: keep clients whose L2 norm lies within
     ``[lower * median_norm, upper * median_norm]``;
  2. sign filter: cluster clients on their (pos, zero, neg) gradient-sign
     statistics with complete-linkage 2-clustering and keep the majority.
The aggregate is the mean of clients passing both, with norms clipped to the
median.
"""

from __future__ import annotations

import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator, TwoLevelStreaming
from blades_tpu.ops.clustering import complete_linkage_two_clusters
from blades_tpu.ops.masked import masked_median_1d


class Signguard(TwoLevelStreaming, Aggregator):
    """Streaming form: two-level — norm-band + sign-cluster filtering
    within each chunk (chunk-local median norm as the band anchor), then
    the same filters over the chunk aggregates. The full-population median
    norm and majority sign-cluster are known only after the pass, so the
    exact form would need a second visit to every row."""
    # certification opt-out (blades_tpu.audit): the norm band and the
    # (pos, zero, neg) sign statistics are origin-anchored — translating
    # every update changes both filters' features, so exact translation
    # equivariance cannot hold (resilience still certifies; cert matrix).
    audit_optouts = {
        "translation": "norm-band and gradient-sign statistics are "
                       "origin-anchored; a global translation changes which "
                       "clients the filters keep",
    }

    def __init__(self, lower: float = 0.1, upper: float = 3.0):
        self.lower = lower
        self.upper = upper

    def aggregate(self, updates, state=(), **ctx):
        return self._aggregate_impl(updates, state, None)

    def _masked_aggregate(self, updates, state, *, mask, **ctx):
        return self._aggregate_impl(updates, state, mask)

    def _aggregate_impl(self, updates, state, mask):
        """``mask=None`` is the full-population program. Under partial
        participation the norm statistics and the majority vote run over
        participants only; absent rows enter the sign-feature linkage at
        zero distance to everyone (neutral for complete linkage — see
        ``Clustering._masked_aggregate``) and are excluded from the final
        average."""
        k = updates.shape[0]
        norms = jnp.sqrt(jnp.maximum(jnp.sum(updates**2, axis=1), 1e-24))
        med = jnp.median(norms) if mask is None else masked_median_1d(norms, mask)
        norm_ok = (norms >= self.lower * med) & (norms <= self.upper * med)

        sign = jnp.sign(updates)
        feats = jnp.stack(
            [
                jnp.mean(sign > 0, axis=1),
                jnp.mean(sign == 0, axis=1),
                jnp.mean(sign < 0, axis=1),
            ],
            axis=1,
        )
        dist = jnp.sqrt(
            jnp.maximum(
                jnp.sum((feats[:, None, :] - feats[None, :, :]) ** 2, axis=-1), 0.0
            )
        )
        if mask is not None:
            out_pair = (~mask[:, None] | ~mask[None, :]) & ~jnp.eye(k, dtype=bool)
            dist = jnp.where(out_pair, 0.0, dist)
        labels = complete_linkage_two_clusters(dist)
        if mask is None:
            size1 = jnp.sum(labels)
            majority = jnp.where(size1 > k - size1, 1, 0)
        else:
            mi = mask.astype(labels.dtype)
            size1 = jnp.sum(mi * labels)
            majority = jnp.where(size1 > jnp.sum(mi) - size1, 1, 0)
        sign_ok = labels == majority

        keep = (norm_ok & sign_ok).astype(updates.dtype)
        if mask is not None:
            keep = keep * mask.astype(updates.dtype)
        clip = jnp.minimum(1.0, med / norms)
        clipped = updates * clip[:, None]
        return (keep @ clipped) / jnp.maximum(jnp.sum(keep), 1.0), state
