"""Krum / Multi-Krum (Blanchard et al., NeurIPS 2017).

Reference: ``Krum`` (``src/blades/aggregators/krum.py:9-125``), which builds
pairwise distances with O(K^2) Python dict loops (``krum.py:73-91``) and
scores each client by the sum of its ``n - f - 2`` smallest distances
(``krum.py:9-26``). Here the distance matrix is a single MXU matmul
(``|a-b|^2 = |a|^2 + |b|^2 - 2ab^T``) and scoring is one sort — the whole
defense is an XLA program.

Fidelity note: the reference squares the *already squared* distances when
scoring (``krum.py:22`` on top of ``krum.py:91``), i.e. ranks by sums of
``d^4``. The paper specifies squared Euclidean distance; we default to the
paper (``distance_power=2``) and expose ``distance_power=4`` for bit-parity
with the reference's accidental behavior.
"""

from __future__ import annotations

import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator, TwoLevelStreaming
from blades_tpu.ops.distances import pairwise_sq_euclidean


class Krum(TwoLevelStreaming, Aggregator):
    """Streaming form: two-level — Krum-select within each chunk (``f``/``m``
    clamped so the ``n >= 2f + 2`` neighborhood fits the chunk population),
    then Krum again over the chunk winners. A byzantine row must win its
    chunk AND the across-chunk selection; both levels return means of real
    delivered rows, so the two-level result stays in the participants'
    convex hull (bounded in ``tests/test_streaming.py``)."""
    def __init__(
        self,
        num_clients: int = None,
        num_byzantine: int = 5,
        num_selected: int = 1,
        distance_power: int = 2,
    ):
        # num_clients accepted for reference ctor parity (`krum.py:118`) but
        # derived from the update matrix at trace time.
        self.f = num_byzantine
        self.m = num_selected
        self.distance_power = distance_power

    def scores(self, updates: jnp.ndarray) -> jnp.ndarray:
        k = updates.shape[0]
        if 2 * self.f + 2 > k:
            raise ValueError(
                f"Too many Byzantine workers: 2*{self.f}+2 > {k}"
            )
        d2 = pairwise_sq_euclidean(updates)
        if self.distance_power == 4:
            d2 = d2 * d2
        # exclude self-distance by pushing the diagonal to +inf before sorting
        d2 = d2 + jnp.diag(jnp.full((k,), jnp.inf, dtype=updates.dtype))
        nearest = jnp.sort(d2, axis=1)[:, : k - self.f - 2]
        return jnp.sum(nearest, axis=1)

    def _select(self, updates):
        """Shared by aggregate + diagnostics: ``(scores [K], selected [m])``."""
        scores = self.scores(updates)
        return scores, jnp.argsort(scores)[: self.m]

    def aggregate(self, updates, state=(), **ctx):
        _, top_m = self._select(updates)
        # the reference sums the selected updates (`krum.py:120`) but only
        # ever runs m=1 (`krum.py:114`), where sum == mean == the single
        # closest vector. The Multi-Krum paper averages the m selected
        # updates, so for the m>1 surface the reference never exposes we
        # follow the paper — a sum would scale the pseudo-gradient by m.
        return jnp.mean(updates[top_m], axis=0), state

    def _masked_scores(self, updates, mask):
        """Krum scores over the participating subset: pair distances to
        masked-out rows are sentineled to ``+inf`` (they sort past every
        real neighbor), each participant sums its ``n - f - 2`` nearest
        participant distances (``n`` = traced participant count), and
        masked-out rows score ``+inf`` so selection can never pick them.

        Breakdown-point caveat (docs/robustness.md): Krum's guarantee needs
        ``n >= 2f + 3``. Under dropout ``n`` is traced, so the static
        reference guard can only check the full K; when dropout pushes the
        round below the bound the neighbor count clamps at 1 and Krum
        degrades to nearest-neighbor selection among participants rather
        than failing the compiled program.
        """
        k = updates.shape[0]
        if 2 * self.f + 2 > k:
            raise ValueError(f"Too many Byzantine workers: 2*{self.f}+2 > {k}")
        n = jnp.sum(mask.astype(jnp.int32))
        d2 = pairwise_sq_euclidean(updates)
        if self.distance_power == 4:
            d2 = d2 * d2
        pair_ok = mask[:, None] & mask[None, :]
        eye = jnp.eye(k, dtype=bool)
        d2 = jnp.where(pair_ok & ~eye, d2, jnp.inf)
        s = jnp.sort(d2, axis=1)
        nn = jnp.maximum(n - self.f - 2, 1)
        # drop the +inf sentinels from the sum as well as ranks past nn:
        # when n is so low that a participant has fewer real neighbors than
        # nn (n=1: none at all), its score stays FINITE — strictly below
        # every masked-out row's +inf, so selection still prefers
        # participants instead of tying at inf with zeroed absent rows.
        # All-ones: every kept prefix entry is finite (the lone inf per row
        # is the self-distance, sorted last), so the filter is a no-op.
        keep = (jnp.arange(k)[None, :] < nn) & jnp.isfinite(s)
        scores = jnp.sum(jnp.where(keep, s, 0.0), axis=1)
        return jnp.where(mask, scores, jnp.inf), n

    def _masked_aggregate(self, updates, state, *, mask, **ctx):
        scores, n = self._masked_scores(updates, mask)
        top_m = jnp.argsort(scores)[: self.m]
        # fewer participants than m: weight only the first min(m, n) ranks
        # (non-participants score +inf and sort last, so they never land in
        # the weighted prefix). The mean-then-rescale form keeps the full-
        # participation case bit-identical to the unmasked jnp.mean (the
        # rescale is exactly *1.0 when m_eff == m).
        m_eff = jnp.minimum(self.m, jnp.maximum(n, 1))
        w = (jnp.arange(self.m) < m_eff).astype(updates.dtype)
        sel = updates[top_m] * w[:, None]
        scale = jnp.asarray(self.m, updates.dtype) / m_eff.astype(updates.dtype)
        return jnp.mean(sel, axis=0) * scale, state

    def _level_clone(self, k: int) -> "Krum":
        """Krum instance whose ``f``/``m`` fit a ``k``-row level of the
        two-level streaming hierarchy (``2f + 2 <= k``, ``m <= k``)."""
        f = min(self.f, max((k - 2) // 2, 0))
        m = min(self.m, k)
        if (f, m) == (self.f, self.m):
            return self
        return Krum(
            num_byzantine=f, num_selected=m, distance_power=self.distance_power
        )

    def _chunk_aggregate(self, slab, *, chunk_mask, **ctx):
        agg, _ = self._level_clone(slab.shape[0])._masked_aggregate(
            slab, (), mask=chunk_mask
        )
        return agg

    def _combine_chunk_aggs(self, aggs, counts, state, **ctx):
        agg, _ = self._level_clone(aggs.shape[0])._masked_aggregate(
            aggs, (), mask=counts > 0
        )
        return jnp.where(jnp.sum(counts) > 0, agg, jnp.zeros_like(agg)), state

    def diagnostics(self, updates, state=(), **ctx):
        """Forensics: the full per-client score vector and the ``m``
        selected client indices — which clients the defense trusted this
        round (the quantity Krum-analysis papers reason about; same
        ``_select`` call as :meth:`aggregate`, so the recorded selection is
        by construction the one applied)."""
        scores, top_m = self._select(updates)
        return {"scores": scores, "selected": top_m.astype(jnp.int32)}

    def __repr__(self):
        return f"Krum (m={self.m})"


class Multikrum(Krum):
    """Multi-Krum: select the m best-scoring clients (m > 1)."""

    def __init__(
        self,
        num_clients: int = None,
        num_byzantine: int = 5,
        num_selected: int = 5,
        distance_power: int = 2,
    ):
        super().__init__(num_clients, num_byzantine, num_selected, distance_power)
