"""Krum / Multi-Krum (Blanchard et al., NeurIPS 2017).

Reference: ``Krum`` (``src/blades/aggregators/krum.py:9-125``), which builds
pairwise distances with O(K^2) Python dict loops (``krum.py:73-91``) and
scores each client by the sum of its ``n - f - 2`` smallest distances
(``krum.py:9-26``). Here the distance matrix is a single MXU matmul
(``|a-b|^2 = |a|^2 + |b|^2 - 2ab^T``) and scoring is one sort — the whole
defense is an XLA program.

Fidelity note: the reference squares the *already squared* distances when
scoring (``krum.py:22`` on top of ``krum.py:91``), i.e. ranks by sums of
``d^4``. The paper specifies squared Euclidean distance; we default to the
paper (``distance_power=2``) and expose ``distance_power=4`` for bit-parity
with the reference's accidental behavior.
"""

from __future__ import annotations

import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator
from blades_tpu.ops.distances import pairwise_sq_euclidean


class Krum(Aggregator):
    def __init__(
        self,
        num_clients: int = None,
        num_byzantine: int = 5,
        num_selected: int = 1,
        distance_power: int = 2,
    ):
        # num_clients accepted for reference ctor parity (`krum.py:118`) but
        # derived from the update matrix at trace time.
        self.f = num_byzantine
        self.m = num_selected
        self.distance_power = distance_power

    def scores(self, updates: jnp.ndarray) -> jnp.ndarray:
        k = updates.shape[0]
        if 2 * self.f + 2 > k:
            raise ValueError(
                f"Too many Byzantine workers: 2*{self.f}+2 > {k}"
            )
        d2 = pairwise_sq_euclidean(updates)
        if self.distance_power == 4:
            d2 = d2 * d2
        # exclude self-distance by pushing the diagonal to +inf before sorting
        d2 = d2 + jnp.diag(jnp.full((k,), jnp.inf, dtype=updates.dtype))
        nearest = jnp.sort(d2, axis=1)[:, : k - self.f - 2]
        return jnp.sum(nearest, axis=1)

    def _select(self, updates):
        """Shared by aggregate + diagnostics: ``(scores [K], selected [m])``."""
        scores = self.scores(updates)
        return scores, jnp.argsort(scores)[: self.m]

    def aggregate(self, updates, state=(), **ctx):
        _, top_m = self._select(updates)
        # the reference sums the selected updates (`krum.py:120`) but only
        # ever runs m=1 (`krum.py:114`), where sum == mean == the single
        # closest vector. The Multi-Krum paper averages the m selected
        # updates, so for the m>1 surface the reference never exposes we
        # follow the paper — a sum would scale the pseudo-gradient by m.
        return jnp.mean(updates[top_m], axis=0), state

    def diagnostics(self, updates, state=(), **ctx):
        """Forensics: the full per-client score vector and the ``m``
        selected client indices — which clients the defense trusted this
        round (the quantity Krum-analysis papers reason about; same
        ``_select`` call as :meth:`aggregate`, so the recorded selection is
        by construction the one applied)."""
        scores, top_m = self._select(updates)
        return {"scores": scores, "selected": top_m.astype(jnp.int32)}

    def __repr__(self):
        return f"Krum (m={self.m})"


class Multikrum(Krum):
    """Multi-Krum: select the m best-scoring clients (m > 1)."""

    def __init__(
        self,
        num_clients: int = None,
        num_byzantine: int = 5,
        num_selected: int = 5,
        distance_power: int = 2,
    ):
        super().__init__(num_clients, num_byzantine, num_selected, distance_power)
