"""ByzantineSGD filter (Alistarh et al., NeurIPS 2018).

Reference: ``ByzantineSGD`` (``src/blades/aggregators/byzantinesgd.py:8-80``)
— unexported there, implemented here for full catalog coverage. Per-worker
scalar accumulators ``A_i += <u_i, theta - theta_0>`` and vector accumulators
``B_i += u_i`` feed three median-distance filters (thresholds th_A/th_B/th_V);
workers failing any filter are permanently removed from the good set.

State (A, B, good mask, initial params) is explicit jit state; the current
flat parameter vector arrives via the ``params_flat`` context. The
``vector_median`` scan (first worker within ``threshold`` of more than half
the others, ``byzantinesgd.py:35-43``) becomes a masked matrix reduction.
"""

from __future__ import annotations

import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator
from blades_tpu.ops.distances import pairwise_sq_euclidean


def _vector_median_idx(vs: jnp.ndarray, threshold: float) -> jnp.ndarray:
    """Index of the first row within ``threshold`` of > half the rows."""
    d = jnp.sqrt(pairwise_sq_euclidean(vs))
    counts = jnp.sum(d <= threshold, axis=1)  # includes self, as the reference does
    ok = counts > vs.shape[0] / 2
    return jnp.argmax(ok)  # first eligible index (0 if none — reference raises)


class Byzantinesgd(Aggregator):
    stateful = True

    # streaming opt-out (tests/test_streaming.py registry lint): the
    # defense's own cross-round state is the per-client [K, D] accumulator
    # matrix B, and its filters take vector medians ACROSS clients of B and
    # of the raw updates — the memory the streaming engine exists to avoid
    # is this defense's definition, not an implementation detail.
    streaming_optouts = {
        "streaming": "per-client B accumulators are themselves [K, D] "
                     "state and the median-distance filters compare every "
                     "client against every other; the defense is "
                     "inherently dense in K",
    }

    def __init__(self, th_A: float = 1.0, th_B: float = 1.0, th_V: float = 1.0):
        self.th_A = th_A
        self.th_B = th_B
        self.th_V = th_V

    def init_state(self, num_clients: int, dim: int):
        # fixed pytree structure across calls (jit/scan carry contract): the
        # initial parameter snapshot is captured on the first call, flagged
        # by `initialized` rather than a None sentinel.
        return {
            "A": jnp.zeros((num_clients,), dtype=jnp.float32),
            "B": jnp.zeros((num_clients, dim), dtype=jnp.float32),
            "good": jnp.ones((num_clients,), dtype=bool),
            "init_params": jnp.zeros((dim,), dtype=jnp.float32),
            "initialized": jnp.zeros((), dtype=bool),
        }

    def aggregate(self, updates, state, *, params_flat=None, **ctx):
        return self._aggregate_impl(updates, state, params_flat, None)

    def _masked_aggregate(self, updates, state, *, mask, params_flat=None, **ctx):
        return self._aggregate_impl(updates, state, params_flat, mask)

    def _aggregate_impl(self, updates, state, params_flat, mask):
        """``mask=None`` is the full-population program. Under partial
        participation an absent client's A/B accumulators FREEZE (no upload
        to accumulate), the filters still run on the frozen values (the
        reference filter is history-based, so this is its natural
        extension), and the final average weights good ∩ participating."""
        if params_flat is None:
            raise ValueError("byzantinesgd needs params_flat context")
        init_params = jnp.where(
            state["initialized"], state["init_params"], params_flat
        )
        model_diff = params_flat - init_params

        inc_a = updates @ model_diff
        inc_b = updates
        if mask is not None:
            inc_a = jnp.where(mask, inc_a, 0.0)
            inc_b = jnp.where(mask[:, None], inc_b, 0.0)
        A = state["A"] + inc_a
        B = state["B"] + inc_b

        A_med = jnp.median(A)
        B_med = B[_vector_median_idx(B, self.th_B)]
        g_med = updates[_vector_median_idx(updates, 2 * self.th_V)]

        a_ok = jnp.abs(A - A_med) <= self.th_A
        b_ok = jnp.sqrt(jnp.sum((B - B_med) ** 2, axis=1)) <= self.th_B
        g_ok = jnp.sqrt(jnp.sum((updates - g_med) ** 2, axis=1)) <= 4 * self.th_V
        good = state["good"] & a_ok & b_ok & g_ok

        w = good.astype(updates.dtype)
        if mask is not None:
            w = w * mask.astype(updates.dtype)
        agg = (w @ updates) / jnp.maximum(jnp.sum(w), 1.0)
        new_state = {
            "A": A,
            "B": B,
            "good": good,
            "init_params": init_params,
            "initialized": jnp.ones((), dtype=bool),
        }
        return agg, new_state
