"""Coordinate-wise trimmed mean (Yin et al., 2018).

Reference: ``Trimmedmean`` (``src/blades/aggregators/trimmedmean.py:9-45``):
drop the largest and smallest ``b`` values per coordinate via two ``topk``
calls, average the rest; ``b`` auto-shrinks when ``K - 2b <= 0``
(``trimmedmean.py:29-36``). On TPU the selection runs as a one-HBM-pass
pallas kernel (``ops/pallas_trimmed.py``); elsewhere it is one sort along
the client axis plus a static slice.
"""

from __future__ import annotations

import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator
from blades_tpu.ops.pallas_trimmed import trimmed_mean


class Trimmedmean(Aggregator):
    def __init__(self, num_byzantine: int = 5, nb: int = None):
        # `nb` mirrors the reference ctor arg name (`trimmedmean.py:24`).
        self.b = nb if nb is not None else num_byzantine

    def aggregate(self, updates, state=(), **ctx):
        k = updates.shape[0]
        b = self.b
        while k - 2 * b <= 0:  # trace-time auto-shrink, parity with reference
            b -= 1
        if b < 0:
            raise RuntimeError(f"cannot trim {self.b} from {k} clients")
        return trimmed_mean(updates, b), state

    def __repr__(self):
        return f"Trimmed Mean (b={self.b})"
