"""Coordinate-wise trimmed mean (Yin et al., 2018).

Reference: ``Trimmedmean`` (``src/blades/aggregators/trimmedmean.py:9-45``):
drop the largest and smallest ``b`` values per coordinate via two ``topk``
calls, average the rest; ``b`` auto-shrinks when ``K - 2b <= 0``
(``trimmedmean.py:29-36``). On TPU the selection runs as a one-HBM-pass
pallas kernel (``ops/pallas_trimmed.py``); elsewhere it is one sort along
the client axis plus a static slice.
"""

from __future__ import annotations

import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator, TwoLevelStreaming
from blades_tpu.ops.masked import masked_trimmed_mean
from blades_tpu.ops.pallas_trimmed import trimmed_mean


class Trimmedmean(TwoLevelStreaming, Aggregator):
    """Streaming form: two-level — trim ``b`` (auto-shrunk to the chunk
    population by ``_effective_b``) within each chunk, then trim again
    across the chunk aggregates. Byzantine values must survive a
    chunk-local trim AND an across-chunk trim to reach the result; the
    two-level estimate stays within the participants' per-coordinate range
    (bounded in ``tests/test_streaming.py``)."""
    def __init__(self, num_byzantine: int = 5, nb: int = None):
        # `nb` mirrors the reference ctor arg name (`trimmedmean.py:24`).
        self.b = nb if nb is not None else num_byzantine

    def _effective_b(self, k: int) -> int:
        b = self.b
        while k - 2 * b <= 0:  # trace-time auto-shrink, parity with reference
            b -= 1
        if b < 0:
            raise RuntimeError(f"cannot trim {self.b} from {k} clients")
        return b

    def aggregate(self, updates, state=(), **ctx):
        return trimmed_mean(updates, self._effective_b(updates.shape[0])), state

    def _masked_aggregate(self, updates, state, *, mask, **ctx):
        # rank-mask trim over the participating subset; b additionally
        # clamps against the traced participant count (under dropout the
        # trim narrows toward the masked median instead of dying)
        b = self._effective_b(updates.shape[0])
        return masked_trimmed_mean(updates, mask, b), state

    def diagnostics(self, updates, state=(), **ctx):
        """Forensics: per-client count of coordinates where that client's
        value was trimmed (rank < b or rank >= K-b along the client axis),
        plus the effective b. A client whose rows are trimmed at nearly
        every coordinate is what the defense *treats* as an outlier — under
        attack, compare against the ground-truth byzantine mask
        (``byz_trim_frac`` in the telemetry round records).

        Costs one [K, D] double-argsort the aggregate itself does not need —
        only traced when diagnostics are requested."""
        k = updates.shape[0]
        b = self._effective_b(k)
        ranks = jnp.argsort(jnp.argsort(updates, axis=0), axis=0)
        trimmed = (ranks < b) | (ranks >= k - b)
        return {
            "trim_counts": trimmed.sum(axis=1).astype(jnp.int32),
            "trim_b": jnp.asarray(b, jnp.int32),
        }

    def __repr__(self):
        return f"Trimmed Mean (b={self.b})"
