"""FLTrust (Cao et al., NDSS 2021).

Reference: ``Fltrust`` (``src/blades/aggregators/fltrust.py:8-38``): requires
exactly one trusted client; trust score of every untrusted update is
``relu(cos_sim(trusted, u))`` (cosine eps 1e-6, matching torch's
``CosineSimilarity``), each untrusted update is rescaled to the trusted
update's norm, and the result is the trust-weighted average over the
*untrusted* population.

Here the trusted client is identified by the ``trusted_mask`` context array
(set via ``Simulator.set_trusted_clients``, reference
``simulator.py:143-151``) and the whole defense is masked arithmetic over the
``[K, D]`` matrix — no Python-side client filtering.
"""

from __future__ import annotations

import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator


class Fltrust(Aggregator):
    # certification opt-out (blades_tpu.audit): trust scores are cosine
    # similarities to the trusted update and every update is rescaled to the
    # trusted norm — both origin-anchored, so translating all updates does
    # not translate the aggregate (by design: the server's root-of-trust
    # direction is absolute, not relative).
    audit_optouts = {
        "translation": "cosine trust scores and trusted-norm rescaling are "
                       "origin-anchored; the defense is deliberately not "
                       "translation-equivariant",
    }

    # streaming opt-out (tests/test_streaming.py registry lint): every
    # row's trust weight is its cosine against the TRUSTED row's update —
    # chunks delivered before the trusted client's chunk cannot be scored
    # in a single pass, and retaining them until it arrives is the dense
    # [K, D] matrix again.
    streaming_optouts = {
        "streaming": "trust reweighting pairs every row with the trusted "
                     "update, which may arrive in any chunk; a single pass "
                     "cannot revisit rows delivered before it",
    }

    def __call__(self, inputs, **ctx):
        # host-side guard mirroring the reference's `assert len(trusted) == 1`
        mask = ctx.get("trusted_mask")
        if mask is not None and int(jnp.sum(jnp.asarray(mask))) != 1:
            raise ValueError("fltrust requires exactly one trusted client")
        return super().__call__(inputs, **ctx)

    @staticmethod
    def _trust_scores(updates, trusted_mask):
        """Shared by aggregate + diagnostics (one formula, one place):
        returns ``(ts, t_norm, norms)`` — relu'd cosine trust per client
        (0 for the trusted client itself), the trusted update's norm, and
        every client's norm."""
        trusted_mask = jnp.asarray(trusted_mask).astype(bool)
        trusted = updates[jnp.argmax(trusted_mask)]
        t_norm = jnp.sqrt(jnp.sum(trusted**2))
        norms = jnp.sqrt(jnp.maximum(jnp.sum(updates**2, axis=1), 0.0))
        cos = (updates @ trusted) / jnp.maximum(norms * t_norm, 1e-6)
        ts = jnp.maximum(cos, 0.0) * (~trusted_mask)  # relu + exclude trusted
        return ts, t_norm, norms

    def aggregate(self, updates, state=(), *, trusted_mask=None, **ctx):
        if trusted_mask is None:
            raise ValueError(
                "fltrust requires a trusted_mask (set_trusted_clients)"
            )
        ts, t_norm, norms = self._trust_scores(updates, trusted_mask)
        rescaled = updates * (t_norm / jnp.maximum(norms, 1e-24))[:, None]
        # when every untrusted update opposes the trusted one (all trust
        # scores zero) the reference divides 0/0 -> NaN; return the zero
        # vector instead (skip the round) — safer and still "no information
        # accepted from untrusted clients".
        return (ts @ rescaled) / jnp.maximum(jnp.sum(ts), 1e-12), state

    def _masked_aggregate(self, updates, state, *, mask, trusted_mask=None, **ctx):
        if trusted_mask is None:
            raise ValueError(
                "fltrust requires a trusted_mask (set_trusted_clients)"
            )
        # absent clients earn zero trust; when the TRUSTED client itself
        # drops, its zeroed row has zero norm, every cosine collapses to 0,
        # and the round degrades to the zero update (skip) — the documented
        # all-trust-zero fallback above, reached through the same arithmetic
        ts, t_norm, norms = self._trust_scores(updates, trusted_mask)
        ts = ts * mask.astype(updates.dtype)
        rescaled = updates * (t_norm / jnp.maximum(norms, 1e-24))[:, None]
        return (ts @ rescaled) / jnp.maximum(jnp.sum(ts), 1e-12), state

    def diagnostics(self, updates, state=(), *, trusted_mask=None, **ctx):
        """Forensics: the per-client trust scores — exactly the weights
        :meth:`aggregate` applies this round (same ``_trust_scores`` call,
        so the two cannot diverge)."""
        if trusted_mask is None:
            return {}
        ts, _, _ = self._trust_scores(updates, trusted_mask)
        return {"trust_scores": ts}
