"""Clipped clustering (Li et al., TechRxiv 2022).

Reference: ``Clippedclustering`` (``src/blades/aggregators/clippedclustering.py:20-66``):
clip each update to the median of *historical* L2 norms (the history grows
unboundedly, ``clippedclustering.py:34,41-43``), then cluster on cosine
distance (diag 0, NaN -> 2) and average the majority cluster.

The unbounded Python list is replaced by a fixed-capacity ring buffer carried
as explicit jit state; with the default capacity the buffer only wraps after
``history_cap / K`` rounds (65k scalars ~ 256 KB), beyond any reference run
length. Clipping uses the same ``min(1, tau / (|u| + 1e-6))`` coefficient as
the reference's ``clip_tensor_norm_`` (``aggregators/torch_utils.py:96-107``),
applied only to rows whose norm exceeds the threshold.
"""

from __future__ import annotations

import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator
from blades_tpu.aggregators.clustering import Clustering
from blades_tpu.ops.masked import masked_median_1d
from blades_tpu.ops.streaming import stack_init, stack_write


class Clippedclustering(Aggregator):
    """Streaming form (two-level, documented deviations): the clip
    threshold is the median of the norm history *as of round start* — one
    round LAGGED relative to the dense path, which appends the current
    round's norms before taking the median (the current norms are only all
    known after the pass; on the very first round the empty history yields
    an infinite threshold, i.e. no clipping). Rows are clipped chunk-
    locally against that threshold, clustered chunk-locally, and the chunk
    aggregates are clustered again at finalize; the ring buffer ingests
    exactly ``num_clients`` entries per round in pass order (the final
    chunk's zero-pad slots are skipped), with two chunk-local deviations
    from the dense write rule: absent slots record the CHUNK participant
    median rather than the round median (same neutrality argument,
    chunk-local scope), and a zero-participant chunk suppresses its own
    write where the dense path suppresses only fully-empty rounds."""

    stateful = True

    # certification opt-out (blades_tpu.audit): norm clipping to the
    # historical-median radius and cosine-distance clustering are both
    # origin-anchored — translating every update changes the clip set and
    # the cluster features (resilience certifies; cert matrix).
    audit_optouts = {
        "translation": "median-norm clipping and cosine-distance clustering "
                       "are origin-anchored; a global translation changes "
                       "the clip and cluster decisions",
    }

    def __init__(self, tau: float = None, history_cap: int = 65536):
        self.tau = tau
        self.history_cap = history_cap
        self._clustering = Clustering(metric="distance")

    def init_state(self, num_clients: int, dim: int):
        # `pos` is the ring write pointer (wraps); `count` the clamped number
        # of live entries used for the masked median.
        return {
            "norms": jnp.zeros((self.history_cap,), dtype=jnp.float32),
            "pos": jnp.zeros((), dtype=jnp.int32),
            "count": jnp.zeros((), dtype=jnp.int32),
        }

    def _masked_median(self, norms, n):
        """Median of the first ``n`` live entries (numpy convention: midpoint
        of the two central order statistics for even n)."""
        cap = norms.shape[0]
        filled = jnp.arange(cap) < n
        s = jnp.sort(jnp.where(filled, norms, jnp.inf))
        lo = s[jnp.maximum((n - 1) // 2, 0)]
        hi = s[jnp.maximum(n // 2, 0)]
        return (lo + hi) / 2.0

    def aggregate(self, updates, state, **ctx):
        k = updates.shape[0]
        norms = jnp.sqrt(jnp.maximum(jnp.sum(updates**2, axis=1), 0.0))

        # append this round's K norms into the ring buffer
        cap = self.history_cap
        idx = (state["pos"] + jnp.arange(k)) % cap
        hist = state["norms"].at[idx].set(norms.astype(jnp.float32))
        pos = (state["pos"] + k) % cap
        count = jnp.minimum(state["count"] + k, cap)
        new_state = {"norms": hist, "pos": pos, "count": count}

        if self.tau is not None:
            threshold = jnp.asarray(self.tau, dtype=updates.dtype)
        else:
            threshold = self._masked_median(hist, count).astype(updates.dtype)

        coef = jnp.minimum(1.0, threshold / (norms + 1e-6))
        clipped = jnp.where((norms > threshold)[:, None], updates * coef[:, None], updates)

        agg, _ = self._clustering.aggregate(clipped)
        return agg, new_state

    def _masked_aggregate(self, updates, state, *, mask, **ctx):
        k = updates.shape[0]
        norms = jnp.sqrt(jnp.maximum(jnp.sum(updates**2, axis=1), 0.0))

        # ring-buffer discipline under dropout: the write pattern stays
        # static (k slots per round) so the compiled program is fixed;
        # absent clients' slots record this round's PARTICIPANT median —
        # exactly neutral for the buffer's only consumer (the median
        # threshold) instead of polluting history with zeros. A round with
        # NO participants has no median to record: the whole buffer update
        # (values, write pointer, live count) is suppressed via where, so
        # empty rounds cannot drag the clipping threshold toward zero.
        n = jnp.sum(mask.astype(jnp.int32))
        any_part = n > 0
        med_round = masked_median_1d(norms, mask)
        writes = jnp.where(mask, norms, med_round).astype(jnp.float32)
        cap = self.history_cap
        idx = (state["pos"] + jnp.arange(k)) % cap
        hist = jnp.where(
            any_part, state["norms"].at[idx].set(writes), state["norms"]
        )
        pos = jnp.where(any_part, (state["pos"] + k) % cap, state["pos"])
        count = jnp.where(
            any_part, jnp.minimum(state["count"] + k, cap), state["count"]
        )
        new_state = {"norms": hist, "pos": pos, "count": count}

        if self.tau is not None:
            threshold = jnp.asarray(self.tau, dtype=updates.dtype)
        else:
            threshold = self._masked_median(hist, count).astype(updates.dtype)

        coef = jnp.minimum(1.0, threshold / (norms + 1e-6))
        clipped = jnp.where(
            (norms > threshold)[:, None], updates * coef[:, None], updates
        )
        agg, _ = self._clustering._masked_aggregate(clipped, (), mask=mask)
        return agg, new_state

    # -- streaming (see class docstring for the documented deviations) -------

    def streaming_init(self, num_clients, num_chunks, chunk_size, dim, state=()):
        if self.tau is not None:
            thresh = jnp.asarray(self.tau, jnp.float32)
        else:
            # round-start (lagged) threshold: the dense path's median also
            # includes THIS round's norms, which a single pass cannot know
            thresh = self._masked_median(state["norms"], state["count"])
        # the final chunk's zero-pad rows must NOT ingest phantom history
        # entries: exactly num_clients norms enter the ring per round,
        # matching the dense path's write count
        pad = num_chunks * chunk_size - num_clients
        return {
            "thresh": thresh,
            "hist": state["norms"],
            "pos": state["pos"],
            "count": state["count"],
            "pad": jnp.asarray(pad, jnp.int32),
            "last": jnp.asarray(num_chunks - 1, jnp.int32),
            "aggs": stack_init(num_chunks, (dim,)),
            "counts": jnp.zeros((num_chunks,), jnp.int32),
        }

    def streaming_update(
        self, sstate, chunk_updates, *, chunk_mask, chunk_index, **ctx
    ):
        k = chunk_updates.shape[0]
        norms = jnp.sqrt(jnp.maximum(jnp.sum(chunk_updates**2, axis=1), 0.0))
        n = jnp.sum(chunk_mask.astype(jnp.int32))
        any_part = n > 0

        # ring-buffer ingest in pass order; absent clients' slots record
        # the chunk participant median (neutral for the buffer's only
        # consumer), the final chunk's zero-pad slots are skipped entirely
        # (write count per round == num_clients, dense parity), and empty
        # chunks suppress the whole write
        med_chunk = masked_median_1d(norms, chunk_mask)
        writes = jnp.where(chunk_mask, norms, med_chunk).astype(jnp.float32)
        n_slots = k - jnp.where(
            chunk_index == sstate["last"], sstate["pad"], 0
        )
        slot_ok = jnp.arange(k) < n_slots
        cap = self.history_cap
        idx = (sstate["pos"] + jnp.arange(k)) % cap
        vals = jnp.where(slot_ok, writes, sstate["hist"][idx])
        hist = jnp.where(
            any_part, sstate["hist"].at[idx].set(vals), sstate["hist"]
        )
        pos = jnp.where(
            any_part, (sstate["pos"] + n_slots) % cap, sstate["pos"]
        )
        count = jnp.where(
            any_part,
            jnp.minimum(sstate["count"] + n_slots, cap),
            sstate["count"],
        )

        thresh = sstate["thresh"].astype(chunk_updates.dtype)
        coef = jnp.minimum(1.0, thresh / (norms + 1e-6))
        clipped = jnp.where(
            (norms > thresh)[:, None],
            chunk_updates * coef[:, None],
            chunk_updates,
        )
        if k == 1:
            agg = clipped[0]
        else:
            agg, _ = self._clustering._masked_aggregate(
                clipped, (), mask=chunk_mask
            )
        agg = jnp.where(any_part, agg, jnp.zeros_like(agg))
        return {
            "thresh": sstate["thresh"],
            "hist": hist,
            "pos": pos,
            "count": count,
            "pad": sstate["pad"],
            "last": sstate["last"],
            "aggs": stack_write(sstate["aggs"], chunk_index, agg),
            "counts": stack_write(sstate["counts"], chunk_index, n),
        }

    def streaming_finalize(self, sstate, state=(), **ctx):
        aggs, counts = sstate["aggs"], sstate["counts"]
        new_state = {
            "norms": sstate["hist"],
            "pos": sstate["pos"],
            "count": sstate["count"],
        }
        if aggs.shape[0] == 1:
            agg = jnp.where(counts[0] > 0, aggs[0], jnp.zeros_like(aggs[0]))
            return agg, new_state
        agg, _ = self._clustering._masked_aggregate(aggs, (), mask=counts > 0)
        return (
            jnp.where(jnp.sum(counts) > 0, agg, jnp.zeros_like(agg)),
            new_state,
        )
