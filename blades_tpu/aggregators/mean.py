"""Sample mean. Reference: ``Mean`` (``src/blades/aggregators/mean.py:62-76``)."""

from __future__ import annotations

import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator
from blades_tpu.ops.masked import masked_mean


class Mean(Aggregator):
    r"""Computes the sample mean over client updates: one XLA row reduction."""

    # certification opt-out (blades_tpu.audit): averaging has breakdown
    # point 0 — a single unbounded row moves the aggregate arbitrarily, so
    # the empirical (f, c)-resilience bound cannot hold for any f >= 1 (the
    # cert matrix records the breakdown; docs/robustness.md).
    audit_optouts = {
        "resilience": "breakdown point 0: one unbounded byzantine row moves "
                      "the average arbitrarily far from the honest mean",
    }

    def aggregate(self, updates, state=(), **ctx):
        return jnp.mean(updates, axis=0), state

    def _masked_aggregate(self, updates, state, *, mask, **ctx):
        return masked_mean(updates, mask), state
