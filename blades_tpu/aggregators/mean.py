"""Sample mean. Reference: ``Mean`` (``src/blades/aggregators/mean.py:62-76``)."""

from __future__ import annotations

import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator
from blades_tpu.ops.masked import masked_mean


class Mean(Aggregator):
    r"""Computes the sample mean over client updates: one XLA row reduction."""

    def aggregate(self, updates, state=(), **ctx):
        return jnp.mean(updates, axis=0), state

    def _masked_aggregate(self, updates, state, *, mask, **ctx):
        return masked_mean(updates, mask), state
