"""Sample mean. Reference: ``Mean`` (``src/blades/aggregators/mean.py:62-76``)."""

from __future__ import annotations

import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator
from blades_tpu.ops.masked import masked_mean


class Mean(Aggregator):
    r"""Computes the sample mean over client updates: one XLA row reduction."""

    # certification opt-out (blades_tpu.audit): averaging has breakdown
    # point 0 — a single unbounded row moves the aggregate arbitrarily, so
    # the empirical (f, c)-resilience bound cannot hold for any f >= 1 (the
    # cert matrix records the breakdown; docs/robustness.md).
    audit_optouts = {
        "resilience": "breakdown point 0: one unbounded byzantine row moves "
                      "the average arbitrarily far from the honest mean",
    }

    # exact streaming form: a mean is a running (sum, count) carry — the
    # finalized estimator is the dense one, chunking only re-associates
    # the floating-point summation
    streaming_exact = True

    def aggregate(self, updates, state=(), **ctx):
        return jnp.mean(updates, axis=0), state

    def _masked_aggregate(self, updates, state, *, mask, **ctx):
        return masked_mean(updates, mask), state

    def streaming_init(self, num_clients, num_chunks, chunk_size, dim, state=()):
        # bare (sum, count) carry — no sumsq; the variance moments are the
        # engine's metrics concern, not the mean's
        return {
            "sum": jnp.zeros((dim,), jnp.float32),
            "count": jnp.zeros((), jnp.float32),
        }

    def streaming_update(
        self, sstate, chunk_updates, *, chunk_mask, chunk_index, **ctx
    ):
        w = chunk_mask.astype(chunk_updates.dtype)
        return {
            "sum": sstate["sum"] + jnp.sum(chunk_updates * w[:, None], axis=0),
            "count": sstate["count"] + jnp.sum(w),
        }

    def streaming_finalize(self, sstate, state=(), **ctx):
        return sstate["sum"] / jnp.maximum(sstate["count"], 1.0), state
