"""Coordinate-wise median (Yin et al., 2018).

Reference: ``Median`` (``src/blades/aggregators/median.py:9-25``). The
reference symmetrizes torch's lower-median — ``(med(x) - med(-x)) / 2`` — to
obtain the midpoint for even K; ``jnp.median`` already returns the midpoint,
so the two are numerically identical.
"""

from __future__ import annotations

import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator, TwoLevelStreaming
from blades_tpu.ops.masked import masked_median


class Median(TwoLevelStreaming, Aggregator):
    """Streaming form: two-level median-of-chunk-medians (the classic
    median-of-means-style hierarchy) — each level is the same f < n/2
    robust statistic, and the result stays within the participants'
    per-coordinate range (bounded in ``tests/test_streaming.py``)."""

    def aggregate(self, updates, state=(), **ctx):
        return jnp.median(updates, axis=0), state

    def _masked_aggregate(self, updates, state, *, mask, **ctx):
        # sentinel sort over the participating subset (ops/masked.py)
        return masked_median(updates, mask), state
