"""Clustering defense (Sattler et al., 2020).

Reference: ``Clustering`` (``src/blades/aggregators/clustering.py:13-44``):
build the K x K matrix ``M[i,j] = cosine_similarity(u_i, u_j)`` with diagonal
1 and NaN -> -1 (``clustering.py:26-35``), run complete-linkage agglomerative
clustering into two groups, and average the majority cluster.

Fidelity note: the reference feeds the *similarity* matrix to
``AgglomerativeClustering`` as a precomputed *distance* (``clustering.py:38``),
so the most-similar pairs merge last. We reproduce that exact matrix by
default (``metric='similarity'``); ``metric='distance'`` gives the intended
cosine-distance clustering (which is what ``Clippedclustering`` uses).
"""

from __future__ import annotations

import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator, TwoLevelStreaming
from blades_tpu.ops.clustering import complete_linkage_two_clusters, majority_cluster_mean
from blades_tpu.ops.distances import pairwise_cosine_similarity


class Clustering(TwoLevelStreaming, Aggregator):
    """Streaming form: two-level — linkage + majority-mean within each
    chunk, then the same clustering over the chunk aggregates. The linkage
    needs the full pairwise matrix of its level's population, which is
    exactly what the hierarchy keeps small (``chunk^2`` then
    ``num_chunks^2``)."""

    # certification opt-outs (blades_tpu.audit): cosine features are
    # origin-anchored (no translation equivariance), and the DEFAULT
    # reference-parity metric feeds the similarity matrix to the linkage as
    # a distance (the fidelity note above) — under the adaptive attack
    # search the inverted linkage merges large-magnitude opposed rows first
    # and the majority cluster absorbs the byzantine rows, so resilience
    # genuinely breaks (recorded in results/certification/cert_matrix.json;
    # the intended ``metric='distance'`` variant certifies — the matrix
    # carries both rows).
    audit_optouts = {
        "translation": "cosine-similarity features are origin-anchored; a "
                       "global translation changes the cluster assignment",
        "resilience": "default metric='similarity' reproduces the "
                      "reference's inverted similarity-as-distance linkage, "
                      "which breaks under magnitude attacks; "
                      "metric='distance' certifies (see cert matrix)",
    }

    def __init__(self, metric: str = "similarity"):
        if metric not in ("similarity", "distance"):
            raise ValueError(metric)
        self.metric = metric
        if metric == "distance":
            # the intended-metric variant certifies resilience (the class
            # dict above describes the reference-parity DEFAULT); cosine
            # features stay origin-anchored either way, so the translation
            # opt-out carries over. Instance attribute shadows the class
            # dict — certification reads the instance (scripts/certify.py).
            self.audit_optouts = {
                "translation": type(self).audit_optouts["translation"],
            }

    def _matrix(self, updates):
        sim = pairwise_cosine_similarity(updates)
        # zero-norm updates have undefined cosine; the reference's scipy path
        # yields NaN there, mapped to -1 similarity / 2 distance
        # (clustering.py:34, clippedclustering.py:59). Our normalized matmul
        # clamps norms instead of producing NaN, so apply the mapping
        # explicitly to zero rows.
        zero = jnp.sum(updates * updates, axis=-1) == 0.0
        undef = zero[:, None] | zero[None, :]
        eye = jnp.eye(sim.shape[0], dtype=bool)
        if self.metric == "similarity":
            # parity: diag = 1 - cosine_dist(x,x) = 1
            m = jnp.where(undef, -1.0, sim)
            return jnp.where(eye, 1.0, m)
        m = jnp.where(undef, 2.0, 1.0 - sim)
        return jnp.where(eye, 0.0, m)

    def aggregate(self, updates, state=(), **ctx):
        labels = complete_linkage_two_clusters(self._matrix(updates))
        return majority_cluster_mean(updates, labels), state

    def _masked_aggregate(self, updates, state, *, mask, **ctx):
        # Masked-out rows get the metric's MINIMUM value against everyone:
        # they merge into some real cluster at zero linkage cost, which is
        # exactly neutral for complete linkage (cluster-to-cluster heights
        # are maxima, and the minimum can never be one), then majority and
        # mean count participants only. Static shapes throughout — no
        # data-dependent compaction.
        k = updates.shape[0]
        m = self._matrix(updates)
        first = -1.0 if self.metric == "similarity" else 0.0
        out_pair = (~mask[:, None] | ~mask[None, :]) & ~jnp.eye(k, dtype=bool)
        labels = complete_linkage_two_clusters(jnp.where(out_pair, first, m))
        mf = mask.astype(updates.dtype)
        size1 = jnp.sum(mf * labels)
        size0 = jnp.sum(mf) - size1
        majority = jnp.where(size1 > size0, 1, 0)
        sel = (labels == majority).astype(updates.dtype) * mf
        return (sel @ updates) / jnp.maximum(jnp.sum(sel), 1.0), state
