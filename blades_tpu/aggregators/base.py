"""Aggregator framework.

Reference counterpart: ``_BaseAggregator`` (``src/blades/aggregators/mean.py:9-40``),
whose instances are host-side callables ``List[client|tensor] -> tensor`` that
run on the driver in pure Python — the serial bottleneck called out in
SURVEY.md section 3 ("Where work actually happens").

TPU-native design: an aggregator is a *pure function* over the on-device
``[K, D]`` update matrix,

    aggregate(updates, state, **ctx) -> (aggregated [D], new_state)

traced inside the same jitted round program as local training, so defenses
compile to XLA reductions and never leave the device. Stateful defenses
(centered clipping's momentum, clipped clustering's norm history) thread
explicit state instead of mutating ``self`` — that is what makes them
jit-compatible and checkpointable.

``__call__`` is a host-side convenience wrapper with reference-call parity
(accepts a stacked matrix, a list of vectors, or a list of client handles,
mirroring ``_get_updates`` at ``mean.py:21-28``) that maintains the state
internally and jit-caches the apply function.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from blades_tpu.ops.streaming import chunk_layout, stack_init, stack_write


class Aggregator:
    """Base class for robust aggregators.

    Subclasses implement :meth:`aggregate`. Construction-time hyperparameters
    are plain Python attributes (static under jit).
    """

    #: set by subclasses that carry state across rounds
    stateful: bool = False

    #: Certification-contract opt-outs (``blades_tpu.audit``, enforced by
    #: the tier-1 registry lint in ``tests/test_audit.py``): a mapping of
    #: contract name (``"permutation"`` | ``"translation"`` |
    #: ``"resilience"``) to a documented reason. Every registered aggregator
    #: must either PASS each contract of the battery or carry an explicit
    #: reason here — a new defense cannot silently skip certification.
    #: Class-level and never mutated; subclasses override with their own
    #: literal dict.
    audit_optouts: dict = {}

    #: Streaming-protocol opt-outs (chunk-scanned aggregation, enforced by
    #: the tier-1 registry lint in ``tests/test_streaming.py``): a mapping
    #: ``{"streaming": reason}`` documenting WHY a defense cannot consume
    #: the update matrix as a single pass of ``[chunk, D]`` slabs (e.g. it
    #: must pair every row with a statistic known only after the full
    #: pass). Every registered aggregator either implements the streaming
    #: path or carries an explicit reason here.
    streaming_optouts: dict = {}

    #: True when the streaming form computes the SAME estimator as the
    #: dense :meth:`aggregate` (differences bounded by floating-point
    #: re-association of chunk partial sums); False for documented
    #: *two-level* forms ("aggregate the chunk-aggregates"), whose
    #: approximation error the streaming test suite bounds instead.
    streaming_exact: bool = False

    def init_state(self, num_clients: int, dim: int) -> Any:
        """Initial carry for stateful aggregators; ``()`` when stateless."""
        return ()

    def aggregate(
        self,
        updates: jnp.ndarray,
        state: Any = (),
        *,
        byz_mask: Optional[jnp.ndarray] = None,
        trusted_mask: Optional[jnp.ndarray] = None,
        params_flat: Optional[jnp.ndarray] = None,
        key: Optional[jax.Array] = None,
    ) -> Tuple[jnp.ndarray, Any]:
        raise NotImplementedError

    # -- graceful degradation (partial participation) -------------------------

    def aggregate_masked(
        self,
        updates: jnp.ndarray,
        state: Any = (),
        *,
        mask: Optional[jnp.ndarray] = None,
        **ctx,
    ) -> Tuple[jnp.ndarray, Any]:
        """:meth:`aggregate` over the participating subset of clients.

        ``mask`` is a boolean ``[K]`` participation mask (``blades_tpu.faults``):
        masked-out rows must not influence the result in ANY way — their
        payload may be stale garbage or NaN/Inf. The wrapper zeroes them
        before dispatching to :meth:`_masked_aggregate`, so implementations
        only reason about *weighting* (sentinel sorts, rank masks, masked
        reductions), never about non-finite payloads.

        Contracts pinned by ``tests/test_faults.py`` for every registered
        aggregator: (1) an all-ones mask is bit-identical to the unmasked
        :meth:`aggregate`; (2) the content of a masked-out row cannot change
        the result. ``mask=None`` statically routes to the unmasked path
        (the engine without a fault model compiles the exact same program
        as before this API existed).
        """
        if mask is None:
            return self.aggregate(updates, state, **ctx)
        mask, safe = self._sanitize(updates, mask)
        return self._masked_aggregate(safe, state, mask=mask, **ctx)

    @staticmethod
    def _sanitize(updates, mask):
        """Boolean-ize the mask and zero masked-out rows (single owner of
        the rule that excluded payloads never reach defense arithmetic)."""
        mask = jnp.asarray(mask).astype(bool)
        return mask, jnp.where(mask[:, None], updates, 0.0)

    def _masked_aggregate(
        self, updates: jnp.ndarray, state: Any, *, mask: jnp.ndarray, **ctx
    ) -> Tuple[jnp.ndarray, Any]:
        """Mask-aware core; ``updates`` arrives with masked-out rows zeroed.

        Every registered aggregator overrides this (enforced by the tier-1
        mask-API test) — the base raises so a new defense cannot silently
        ship without graceful degradation under partial participation.
        """
        raise NotImplementedError(
            f"{type(self).__name__} does not implement mask-aware "
            "aggregation (_masked_aggregate); see docs/robustness.md"
        )

    def aggregate_masked_with_diagnostics(
        self,
        updates: jnp.ndarray,
        state: Any = (),
        *,
        mask: Optional[jnp.ndarray] = None,
        **ctx,
    ) -> Tuple[jnp.ndarray, Any, dict]:
        """:meth:`aggregate_masked` + :meth:`diagnostics`, one traceable call
        (the engine's ``collect_diagnostics`` path under a fault model).

        Diagnostics run on the SANITIZED matrix (masked-out rows zeroed) —
        a corrupted NaN row the guard excluded must not NaN the forensic
        scores the telemetry records either."""
        if mask is None:
            agg, new_state = self.aggregate(updates, state, **ctx)
            return agg, new_state, self.diagnostics(updates, state, **ctx)
        mask, safe = self._sanitize(updates, mask)
        agg, new_state = self._masked_aggregate(safe, state, mask=mask, **ctx)
        return agg, new_state, self.diagnostics(safe, state, mask=mask, **ctx)

    # -- streaming (chunk-scanned) aggregation --------------------------------
    #
    # The dense surfaces above consume the full [K, D] update matrix; the
    # streaming protocol consumes it as a single ordered pass of [chunk, D]
    # slabs so the engine never materializes [K, D] (core/engine.py with
    # ``streaming=True``; peak update memory [chunk, D] + the [num_chunks,
    # ...] summaries carried in the stream state). Contract:
    #
    #   sstate = agg.streaming_init(num_clients, num_chunks, chunk_size,
    #                               dim, state)
    #   for j in range(num_chunks):           # inside lax.scan in the engine
    #       sstate = agg.streaming_update(sstate, slab_j, chunk_mask=m_j,
    #                                     chunk_index=j, **ctx)
    #   agg_vec, new_state = agg.streaming_finalize(sstate, state, **ctx)
    #
    # Slabs arrive SANITIZED (masked-out rows zeroed, same `_sanitize` rule
    # as the mask API) and the chunk mask covers both fault-excluded rows
    # and the engine's padded final chunk. `streaming_exact` declares
    # whether the finalized aggregate is the dense estimator (mean-family)
    # or a documented two-level approximation (`TwoLevelStreaming`).

    def supports_streaming(self) -> bool:
        """True when this aggregator implements the streaming protocol."""
        return type(self).streaming_update is not Aggregator.streaming_update

    def streaming_init(
        self, num_clients: int, num_chunks: int, chunk_size: int, dim: int,
        state: Any = (),
    ) -> Any:
        """Initial streaming reduction state (fixed shapes, scan-carry safe).
        ``state`` is the aggregator's cross-round state at round start (the
        momentum/ring-buffer the streaming pass may need)."""
        raise NotImplementedError(self._no_streaming_msg())

    def streaming_update(
        self,
        sstate: Any,
        chunk_updates: jnp.ndarray,
        *,
        chunk_mask: jnp.ndarray,
        chunk_index: jnp.ndarray,
        **ctx,
    ) -> Any:
        """Fold one sanitized ``[chunk, D]`` slab into the stream state."""
        raise NotImplementedError(self._no_streaming_msg())

    def streaming_finalize(
        self, sstate: Any, state: Any = (), **ctx
    ) -> Tuple[jnp.ndarray, Any]:
        """Finalize ``(aggregate [D], new cross-round state)`` from the
        stream state after every chunk has been consumed."""
        raise NotImplementedError(self._no_streaming_msg())

    def _no_streaming_msg(self) -> str:
        reason = self.streaming_optouts.get("streaming")
        why = f" ({reason})" if reason else ""
        return (
            f"{type(self).__name__} does not implement streaming "
            f"aggregation{why}; use the dense path or a streaming-capable "
            "defense (docs/performance.md, 'Memory scaling')"
        )

    def aggregate_streaming(
        self,
        updates: jnp.ndarray,
        state: Any = (),
        *,
        num_chunks: int = 1,
        mask: Optional[jnp.ndarray] = None,
        **ctx,
    ) -> Tuple[jnp.ndarray, Any]:
        """Reference driver for the streaming protocol over a dense matrix.

        Chunks the ``[K, D]`` matrix exactly the way the engine's chunk
        scan does (``ceil(K / num_chunks)`` rows per chunk, padded final
        chunk masked out) and runs init → update per chunk → finalize.
        This is the semantic definition the streaming tests pin against
        the dense path — and a host-side convenience for auditing a
        defense's streaming form outside the engine.
        """
        k, d = updates.shape
        c, chunk, pad = chunk_layout(k, num_chunks)
        mask = (
            jnp.ones(k, bool) if mask is None else jnp.asarray(mask).astype(bool)
        )
        if pad:
            updates = jnp.pad(updates, ((0, pad), (0, 0)))
            mask = jnp.pad(mask, (0, pad))
        sstate = self.streaming_init(k, c, chunk, d, state)
        for j in range(c):
            rows = slice(j * chunk, (j + 1) * chunk)
            m_c, safe = self._sanitize(updates[rows], mask[rows])
            sstate = self.streaming_update(
                sstate, safe, chunk_mask=m_c,
                chunk_index=jnp.asarray(j, jnp.int32), **ctx,
            )
        return self.streaming_finalize(sstate, state, **ctx)

    # -- forensics ------------------------------------------------------------

    def diagnostics(self, updates: jnp.ndarray, state: Any = (), **ctx) -> dict:
        """Per-round forensic pytree: *what the defense decided* (Krum
        selection indices/scores, trimmed-mean trim-mask summary, clipping
        norms, FLTrust trust scores — the signals the Byzantine-robustness
        literature reasons about but no Blades-lineage codebase records).

        Must be jit-compatible: a dict of fixed-shape arrays, traced inside
        the round program alongside :meth:`aggregate` (XLA CSE dedupes the
        shared subexpressions, so overriding this costs nothing the defense
        did not already compute unless the summary itself is extra work).
        Base implementation: no diagnostics.
        """
        return {}

    def aggregate_with_diagnostics(
        self, updates: jnp.ndarray, state: Any = (), **ctx
    ) -> Tuple[jnp.ndarray, Any, dict]:
        """:meth:`aggregate` + :meth:`diagnostics` over the same inputs,
        as one traceable call (``core/engine.py`` uses this when the engine
        is built with ``collect_diagnostics=True``)."""
        agg, new_state = self.aggregate(updates, state, **ctx)
        return agg, new_state, self.diagnostics(updates, state, **ctx)

    # -- host-side convenience ------------------------------------------------

    def _coerce(self, inputs) -> jnp.ndarray:
        """Normalize inputs to a stacked ``[K, D]`` matrix (parity with the
        reference's ``_get_updates``)."""
        if isinstance(inputs, (list, tuple)):
            if len(inputs) and hasattr(inputs[0], "get_update"):
                inputs = [c.get_update() for c in inputs]
            return jnp.stack([jnp.asarray(u) for u in inputs], axis=0)
        return jnp.asarray(inputs)

    def __call__(self, inputs, **ctx) -> jnp.ndarray:
        updates = self._coerce(inputs)
        if not hasattr(self, "_state"):
            self._state = self.init_state(*updates.shape)
        agg, self._state = self.aggregate(updates, self._state, **ctx)
        return agg

    def reset(self) -> None:
        if hasattr(self, "_state"):
            del self._state

    def __repr__(self) -> str:
        return type(self).__name__


class TwoLevelStreaming:
    """Generic *two-level* streaming form: run the defense chunk-locally,
    then run it again over the ``[num_chunks, D]`` stack of chunk
    aggregates ("aggregate the chunk-aggregates").

    This is the standard hierarchical approximation for order-statistic
    defenses with no exact single-pass form (median-of-medians,
    chunk-local trimming/Krum): every level applies the SAME robust rule,
    so a byzantine minority must first capture a chunk and then a majority
    of chunk aggregates to move the result. It is NOT the dense estimator —
    the deviation is bounded by the tests in ``tests/test_streaming.py``
    (the two-level result of hull-valued defenses stays inside the
    participants' convex hull, so ``|two_level - dense|`` is bounded by the
    update diameter; on concentrated honest updates the two agree to the
    honest spread).

    Mix in BEFORE :class:`Aggregator` and override, when needed:

    - :meth:`_chunk_aggregate` — the chunk-local statistic (default: the
      defense's own ``_masked_aggregate`` from a fresh empty state);
    - :meth:`_combine_chunk_aggs` — the finalize-level recombination
      (default: the defense's own ``_masked_aggregate`` over the stack,
      empty chunks masked out).

    Single-row levels short-circuit (``chunk_size == 1`` /
    ``num_chunks == 1``): a one-row population's robust aggregate is the
    row itself, and several defenses' full machinery (Krum neighborhoods,
    2-clustering) is undefined there.
    """

    def streaming_init(self, num_clients, num_chunks, chunk_size, dim, state=()):
        return {
            "aggs": stack_init(num_chunks, (dim,)),
            "counts": jnp.zeros((num_chunks,), jnp.int32),
        }

    def streaming_update(
        self, sstate, chunk_updates, *, chunk_mask, chunk_index, **ctx
    ):
        n = jnp.sum(chunk_mask.astype(jnp.int32))
        if chunk_updates.shape[0] == 1:
            agg = chunk_updates[0]
        else:
            agg = self._chunk_aggregate(chunk_updates, chunk_mask=chunk_mask, **ctx)
        agg = jnp.where(n > 0, agg, jnp.zeros_like(agg))
        return {
            "aggs": stack_write(sstate["aggs"], chunk_index, agg),
            "counts": stack_write(sstate["counts"], chunk_index, n),
        }

    def streaming_finalize(self, sstate, state=(), **ctx):
        aggs, counts = sstate["aggs"], sstate["counts"]
        if aggs.shape[0] == 1:
            agg = jnp.where(counts[0] > 0, aggs[0], jnp.zeros_like(aggs[0]))
            return agg, state
        return self._combine_chunk_aggs(aggs, counts, state, **ctx)

    def _chunk_aggregate(self, slab, *, chunk_mask, **ctx):
        agg, _ = self._masked_aggregate(slab, (), mask=chunk_mask, **ctx)
        return agg

    def _combine_chunk_aggs(self, aggs, counts, state, **ctx):
        agg, _ = self._masked_aggregate(aggs, (), mask=counts > 0, **ctx)
        return jnp.where(jnp.sum(counts) > 0, agg, jnp.zeros_like(agg)), state
