"""Aggregator framework.

Reference counterpart: ``_BaseAggregator`` (``src/blades/aggregators/mean.py:9-40``),
whose instances are host-side callables ``List[client|tensor] -> tensor`` that
run on the driver in pure Python — the serial bottleneck called out in
SURVEY.md section 3 ("Where work actually happens").

TPU-native design: an aggregator is a *pure function* over the on-device
``[K, D]`` update matrix,

    aggregate(updates, state, **ctx) -> (aggregated [D], new_state)

traced inside the same jitted round program as local training, so defenses
compile to XLA reductions and never leave the device. Stateful defenses
(centered clipping's momentum, clipped clustering's norm history) thread
explicit state instead of mutating ``self`` — that is what makes them
jit-compatible and checkpointable.

``__call__`` is a host-side convenience wrapper with reference-call parity
(accepts a stacked matrix, a list of vectors, or a list of client handles,
mirroring ``_get_updates`` at ``mean.py:21-28``) that maintains the state
internally and jit-caches the apply function.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp


class Aggregator:
    """Base class for robust aggregators.

    Subclasses implement :meth:`aggregate`. Construction-time hyperparameters
    are plain Python attributes (static under jit).
    """

    #: set by subclasses that carry state across rounds
    stateful: bool = False

    #: Certification-contract opt-outs (``blades_tpu.audit``, enforced by
    #: the tier-1 registry lint in ``tests/test_audit.py``): a mapping of
    #: contract name (``"permutation"`` | ``"translation"`` |
    #: ``"resilience"``) to a documented reason. Every registered aggregator
    #: must either PASS each contract of the battery or carry an explicit
    #: reason here — a new defense cannot silently skip certification.
    #: Class-level and never mutated; subclasses override with their own
    #: literal dict.
    audit_optouts: dict = {}

    def init_state(self, num_clients: int, dim: int) -> Any:
        """Initial carry for stateful aggregators; ``()`` when stateless."""
        return ()

    def aggregate(
        self,
        updates: jnp.ndarray,
        state: Any = (),
        *,
        byz_mask: Optional[jnp.ndarray] = None,
        trusted_mask: Optional[jnp.ndarray] = None,
        params_flat: Optional[jnp.ndarray] = None,
        key: Optional[jax.Array] = None,
    ) -> Tuple[jnp.ndarray, Any]:
        raise NotImplementedError

    # -- graceful degradation (partial participation) -------------------------

    def aggregate_masked(
        self,
        updates: jnp.ndarray,
        state: Any = (),
        *,
        mask: Optional[jnp.ndarray] = None,
        **ctx,
    ) -> Tuple[jnp.ndarray, Any]:
        """:meth:`aggregate` over the participating subset of clients.

        ``mask`` is a boolean ``[K]`` participation mask (``blades_tpu.faults``):
        masked-out rows must not influence the result in ANY way — their
        payload may be stale garbage or NaN/Inf. The wrapper zeroes them
        before dispatching to :meth:`_masked_aggregate`, so implementations
        only reason about *weighting* (sentinel sorts, rank masks, masked
        reductions), never about non-finite payloads.

        Contracts pinned by ``tests/test_faults.py`` for every registered
        aggregator: (1) an all-ones mask is bit-identical to the unmasked
        :meth:`aggregate`; (2) the content of a masked-out row cannot change
        the result. ``mask=None`` statically routes to the unmasked path
        (the engine without a fault model compiles the exact same program
        as before this API existed).
        """
        if mask is None:
            return self.aggregate(updates, state, **ctx)
        mask, safe = self._sanitize(updates, mask)
        return self._masked_aggregate(safe, state, mask=mask, **ctx)

    @staticmethod
    def _sanitize(updates, mask):
        """Boolean-ize the mask and zero masked-out rows (single owner of
        the rule that excluded payloads never reach defense arithmetic)."""
        mask = jnp.asarray(mask).astype(bool)
        return mask, jnp.where(mask[:, None], updates, 0.0)

    def _masked_aggregate(
        self, updates: jnp.ndarray, state: Any, *, mask: jnp.ndarray, **ctx
    ) -> Tuple[jnp.ndarray, Any]:
        """Mask-aware core; ``updates`` arrives with masked-out rows zeroed.

        Every registered aggregator overrides this (enforced by the tier-1
        mask-API test) — the base raises so a new defense cannot silently
        ship without graceful degradation under partial participation.
        """
        raise NotImplementedError(
            f"{type(self).__name__} does not implement mask-aware "
            "aggregation (_masked_aggregate); see docs/robustness.md"
        )

    def aggregate_masked_with_diagnostics(
        self,
        updates: jnp.ndarray,
        state: Any = (),
        *,
        mask: Optional[jnp.ndarray] = None,
        **ctx,
    ) -> Tuple[jnp.ndarray, Any, dict]:
        """:meth:`aggregate_masked` + :meth:`diagnostics`, one traceable call
        (the engine's ``collect_diagnostics`` path under a fault model).

        Diagnostics run on the SANITIZED matrix (masked-out rows zeroed) —
        a corrupted NaN row the guard excluded must not NaN the forensic
        scores the telemetry records either."""
        if mask is None:
            agg, new_state = self.aggregate(updates, state, **ctx)
            return agg, new_state, self.diagnostics(updates, state, **ctx)
        mask, safe = self._sanitize(updates, mask)
        agg, new_state = self._masked_aggregate(safe, state, mask=mask, **ctx)
        return agg, new_state, self.diagnostics(safe, state, mask=mask, **ctx)

    # -- forensics ------------------------------------------------------------

    def diagnostics(self, updates: jnp.ndarray, state: Any = (), **ctx) -> dict:
        """Per-round forensic pytree: *what the defense decided* (Krum
        selection indices/scores, trimmed-mean trim-mask summary, clipping
        norms, FLTrust trust scores — the signals the Byzantine-robustness
        literature reasons about but no Blades-lineage codebase records).

        Must be jit-compatible: a dict of fixed-shape arrays, traced inside
        the round program alongside :meth:`aggregate` (XLA CSE dedupes the
        shared subexpressions, so overriding this costs nothing the defense
        did not already compute unless the summary itself is extra work).
        Base implementation: no diagnostics.
        """
        return {}

    def aggregate_with_diagnostics(
        self, updates: jnp.ndarray, state: Any = (), **ctx
    ) -> Tuple[jnp.ndarray, Any, dict]:
        """:meth:`aggregate` + :meth:`diagnostics` over the same inputs,
        as one traceable call (``core/engine.py`` uses this when the engine
        is built with ``collect_diagnostics=True``)."""
        agg, new_state = self.aggregate(updates, state, **ctx)
        return agg, new_state, self.diagnostics(updates, state, **ctx)

    # -- host-side convenience ------------------------------------------------

    def _coerce(self, inputs) -> jnp.ndarray:
        """Normalize inputs to a stacked ``[K, D]`` matrix (parity with the
        reference's ``_get_updates``)."""
        if isinstance(inputs, (list, tuple)):
            if len(inputs) and hasattr(inputs[0], "get_update"):
                inputs = [c.get_update() for c in inputs]
            return jnp.stack([jnp.asarray(u) for u in inputs], axis=0)
        return jnp.asarray(inputs)

    def __call__(self, inputs, **ctx) -> jnp.ndarray:
        updates = self._coerce(inputs)
        if not hasattr(self, "_state"):
            self._state = self.init_state(*updates.shape)
        agg, self._state = self.aggregate(updates, self._state, **ctx)
        return agg

    def reset(self) -> None:
        if hasattr(self, "_state"):
            del self._state

    def __repr__(self) -> str:
        return type(self).__name__
