"""Auto-weighted geometric median (Li et al., IEEE IoT-J 2021).

Reference: ``Autogm`` (``src/blades/aggregators/autogm.py:15-65``). Outer loop
re-solves the client weights ``alpha`` from the distance ranking through an
``eta`` threshold search (``autogm.py:50-59``), inner loop is a Weiszfeld
geometric-median solve; converges on the penalized objective
``sum_i a_i |z - x_i| + lamb |alpha|^2 / 2``.

Fidelity note: the reference intends to scan distances in ascending order but
sorts ``enumerate(distance)`` by *index* (``autogm.py:52`` — the key is the
identity on ``(idx, dist)`` tuples), so its eta search runs in client order.
We implement the paper's sorted search; the fixed point is the same when the
search converges, and the sorted form is what the eta derivation assumes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from blades_tpu.aggregators.base import Aggregator, TwoLevelStreaming
from blades_tpu.aggregators.geomed import weiszfeld


class Autogm(TwoLevelStreaming, Aggregator):
    """Streaming form: two-level — the full auto-weighted solve within each
    chunk (``lamb`` defaulting to the chunk population, its own K-scaling
    rule applied per level), then again across the chunk aggregates. Same
    rationale as :class:`~blades_tpu.aggregators.geomed.Geomed`: the
    weight search re-ranks every row against the current iterate, so no
    exact single-pass state smaller than the rows exists."""
    def __init__(
        self,
        lamb: float = None,
        maxiter: int = 100,
        eps: float = 1e-6,
        ftol: float = 1e-10,
        inner_maxiter: int = 100,
    ):
        self.lamb = lamb
        self.maxiter = maxiter
        self.eps = eps
        self.ftol = ftol
        self.inner_maxiter = inner_maxiter

    def aggregate(self, updates, state=(), **ctx):
        return self._aggregate_impl(updates, state, mask=None)

    def _masked_aggregate(self, updates, state, *, mask, **ctx):
        return self._aggregate_impl(updates, state, mask=mask)

    def _aggregate_impl(self, updates, state, mask):
        """Shared solve; ``mask`` restricts the weight search and the inner
        Weiszfeld solves to the participating rows (``None`` = all, the
        pre-mask program). ``lamb`` stays ``K``-scaled even under dropout —
        the penalty is a static hyperparameter, not a population statistic.
        """
        k = updates.shape[0]
        lamb = float(k) if self.lamb is None else self.lamb
        msk = None if mask is None else mask.astype(updates.dtype)
        n = (
            jnp.asarray(k, jnp.int32)
            if mask is None
            else jnp.sum(mask.astype(jnp.int32))
        )

        def dists(z):
            return jnp.sqrt(jnp.maximum(jnp.sum((updates - z) ** 2, axis=1), 0.0))

        def solve_gm(alpha):
            return weiszfeld(
                updates,
                init_weights=alpha,
                maxiter=self.inner_maxiter,
                eps=self.eps,
                ftol=self.ftol,
                mask=mask,
            )

        def global_obj(z, alpha):
            return jnp.sum(alpha * dists(z)) + lamb * jnp.sum(alpha**2) / 2.0

        if msk is None:
            alpha0 = jnp.full((k,), 1.0 / k, dtype=updates.dtype)
        else:
            alpha0 = msk / jnp.maximum(jnp.sum(msk), 1.0)
        z0 = solve_gm(alpha0)
        obj0 = global_obj(z0, alpha0)

        def cond(carry):
            i, _, _, obj, prev_obj = carry
            return jnp.logical_and(
                i < self.maxiter, jnp.abs(prev_obj - obj) >= self.ftol * obj
            )

        def body(carry):
            i, z, alpha, obj, _ = carry
            d = dists(z)
            # masked rows sort past every participant; their -inf slack in
            # the eta test invalidates their prefix positions automatically
            d_sorted = jnp.sort(d if msk is None else jnp.where(mask, d, jnp.inf))
            # eta_p = (sum of p+1 smallest distances + lamb) / (p + 1); the
            # optimal eta is the last one in the maximal valid prefix
            # (eta_p >= d_(p)), cf. `autogm.py:53-59`.
            p1 = jnp.arange(1, k + 1, dtype=d.dtype)
            summable = (
                d_sorted
                if msk is None
                else jnp.where(jnp.arange(k) < n, d_sorted, 0.0)
            )
            etas = (jnp.cumsum(summable) + lamb) / p1
            valid = jnp.cumprod((etas - d_sorted >= 0).astype(jnp.int32))
            count = jnp.sum(valid)
            eta_opt = jnp.where(count > 0, etas[jnp.maximum(count - 1, 0)], 1e16)
            alpha_new = jnp.maximum(eta_opt - d, 0.0) / lamb
            if msk is not None:
                alpha_new = alpha_new * msk
            z_new = solve_gm(alpha_new)
            obj_new = global_obj(z_new, alpha_new)
            return i + 1, z_new, alpha_new, obj_new, obj

        _, z, _, _, _ = jax.lax.while_loop(
            cond, body, (jnp.array(0), z0, alpha0, obj0, jnp.inf)
        )
        if msk is not None:
            z = jnp.where(n > 0, z, jnp.zeros_like(z))
        return z, state
