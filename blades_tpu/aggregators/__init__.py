"""Robust-aggregator registry.

The reference resolves aggregator names by convention-based dynamic import:
``"xyz" -> blades.aggregators.xyz.Xyz`` (``src/blades/simulator.py:110-116``),
exporting mean, median, trimmedmean, krum, geomed, autogm, centeredclipping,
clustering, clippedclustering (``aggregators/__init__.py``) plus unexported
fltrust/byzantinesgd. All of those names resolve here too, plus dnc,
multikrum, and signguard.
"""

from __future__ import annotations

from typing import Callable, Dict, Type, Union

from blades_tpu.aggregators.base import Aggregator, TwoLevelStreaming
from blades_tpu.aggregators.mean import Mean
from blades_tpu.aggregators.median import Median
from blades_tpu.aggregators.trimmedmean import Trimmedmean
from blades_tpu.aggregators.krum import Krum, Multikrum
from blades_tpu.aggregators.geomed import Geomed
from blades_tpu.aggregators.autogm import Autogm
from blades_tpu.aggregators.centeredclipping import Centeredclipping
from blades_tpu.aggregators.clustering import Clustering
from blades_tpu.aggregators.clippedclustering import Clippedclustering
from blades_tpu.aggregators.fltrust import Fltrust
from blades_tpu.aggregators.byzantinesgd import Byzantinesgd
from blades_tpu.aggregators.dnc import Dnc
from blades_tpu.aggregators.signguard import Signguard
from blades_tpu.aggregators.decentralized import (
    AnchorClipping,
    Asynccenteredclipping,
    Asyncmean,
    DecentralizedMixing,
    fully_connected_adjacency,
    metropolis_weights,
    ring_adjacency,
    torus_adjacency,
)

AGGREGATORS: Dict[str, Type[Aggregator]] = {
    "mean": Mean,
    "median": Median,
    "trimmedmean": Trimmedmean,
    "krum": Krum,
    "multikrum": Multikrum,
    "geomed": Geomed,
    "autogm": Autogm,
    "centeredclipping": Centeredclipping,
    "clustering": Clustering,
    "clippedclustering": Clippedclustering,
    "fltrust": Fltrust,
    "byzantinesgd": Byzantinesgd,
    "dnc": Dnc,
    "signguard": Signguard,
    "asyncmean": Asyncmean,
    "asynccenteredclipping": Asynccenteredclipping,
}


def get_aggregator(name_or_fn: Union[str, Aggregator, Callable], **kwargs) -> Aggregator:
    """Resolve a name or pass through a custom aggregator callable/instance."""
    if isinstance(name_or_fn, Aggregator):
        return name_or_fn
    if callable(name_or_fn) and not isinstance(name_or_fn, str):
        return _wrap_callable(name_or_fn)
    try:
        cls = AGGREGATORS[name_or_fn]
    except KeyError:
        raise ValueError(
            f"Unknown aggregator {name_or_fn!r}; available: {sorted(AGGREGATORS)}"
        ) from None
    return cls(**kwargs)


def _wrap_callable(fn: Callable) -> Aggregator:
    """Adapt a bare ``updates -> vector`` function (the reference accepts
    custom aggregators as plain callables, ``simulator.py:110-116``)."""

    class _Custom(Aggregator):
        def aggregate(self, updates, state=(), **ctx):
            return fn(updates), state

        def __repr__(self):
            return getattr(fn, "__name__", "custom")

    return _Custom()


def register_aggregator(name: str, cls: Type[Aggregator]) -> None:
    """Extension hook for user-defined defenses."""
    AGGREGATORS[name] = cls


__all__ = [
    "Aggregator", "TwoLevelStreaming",
    "Mean", "Median", "Trimmedmean", "Krum", "Multikrum",
    "Geomed", "Autogm", "Centeredclipping", "Clustering", "Clippedclustering",
    "Fltrust", "Byzantinesgd", "Dnc", "Signguard",
    "DecentralizedMixing", "AnchorClipping", "Asyncmean",
    "Asynccenteredclipping", "ring_adjacency", "torus_adjacency",
    "fully_connected_adjacency", "metropolis_weights",
    "AGGREGATORS", "get_aggregator", "register_aggregator",
]
