"""Device-side primitive ops namespace (re-exports; reference
counterpart: none — the reference has no op layer, its defenses run as
host-side torch; per-module citations live in each op file)."""

from blades_tpu.ops.pytree import (  # noqa: F401
    flat_dim,
    make_unraveler,
    ravel,
    tree_stack,
    tree_unstack,
)
from blades_tpu.ops.distances import (  # noqa: F401
    pairwise_sq_euclidean,
    pairwise_cosine_similarity,
)
