"""Batched distance kernels for robust aggregation.

The reference computes pairwise distances with O(K^2) Python dict-of-dict
loops (``src/blades/aggregators/krum.py:73-91``) and per-pair
``scipy.spatial.distance.cosine`` calls
(``src/blades/aggregators/clustering.py:28-33``). On TPU both are a single
MXU matmul over the ``[K, D]`` update matrix.
"""

from __future__ import annotations

import jax.numpy as jnp


def pairwise_sq_euclidean(x: jnp.ndarray) -> jnp.ndarray:
    """``[K, D] -> [K, K]`` matrix of squared Euclidean distances.

    Uses ``|a-b|^2 = |a|^2 + |b|^2 - 2 a.b`` so the O(K^2 D) work is one
    matmul on the MXU; clamps tiny negatives from cancellation.
    """
    sq = jnp.sum(x * x, axis=-1)
    gram = x @ x.T
    d2 = sq[:, None] + sq[None, :] - 2.0 * gram
    return jnp.maximum(d2, 0.0)


def pairwise_cosine_similarity(x: jnp.ndarray, eps: float = 1e-12) -> jnp.ndarray:
    """``[K, D] -> [K, K]`` cosine-similarity matrix via one normalized matmul."""
    norms = jnp.sqrt(jnp.sum(x * x, axis=-1))
    xn = x / jnp.maximum(norms, eps)[:, None]
    sim = xn @ xn.T
    return jnp.clip(sim, -1.0, 1.0)
