"""Pytree <-> flat-vector utilities.

In the reference, every client flattens ``named_parameters`` into a single 1-D
CPU tensor (``src/blades/client.py:216-228``) and the server slices the
aggregated vector back into per-parameter grads
(``src/blades/server.py:66-74``). Here the same mapping is a pair of pure
functions built once from a template pytree: ``ravel`` (tree -> ``[D]``) and an
``unravel`` closure (``[D]`` -> tree), both jit-friendly, so the ``[K, D]``
update matrix lives on device and the reshape is free for XLA to fuse.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree


def ravel(tree: Any) -> jnp.ndarray:
    """Flatten a pytree of arrays into a single 1-D vector."""
    flat, _ = ravel_pytree(tree)
    return flat


def make_unraveler(template: Any) -> Tuple[int, Callable[[jnp.ndarray], Any]]:
    """Return ``(D, unravel)`` for the given template pytree.

    ``unravel`` maps a ``[D]`` vector back to the template's structure; it is a
    pure function safe to close over inside jit.
    """
    flat, unravel = ravel_pytree(template)
    return int(flat.shape[0]), unravel


def flat_dim(tree: Any) -> int:
    """Number of scalar parameters in the pytree."""
    return int(sum(jnp.size(x) for x in jax.tree_util.tree_leaves(tree)))


def tree_stack(trees: list) -> Any:
    """Stack a list of identical-structure pytrees along a new leading axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def tree_unstack(tree: Any, num: int) -> list:
    """Inverse of :func:`tree_stack`."""
    return [jax.tree_util.tree_map(lambda x: x[i], tree) for i in range(num)]
