"""Masked (participation-aware) reductions over the ``[K, D]`` update matrix.

Building blocks for graceful degradation under partial participation
(``blades_tpu/faults``): every reduction here takes a boolean ``[K]``
participation mask and computes the statistic over the participating subset
only — with **static shapes** (jit/SPMD-safe), via sentinel sorting and
rank masks instead of data-dependent gathers.

Bit-compatibility contract (pinned by ``tests/test_faults.py``): with an
all-ones mask every helper reproduces the corresponding unmasked reduction
bit-exactly — masked terms enter sums only as exact identities (``x * 1.0``,
``x + 0.0``, ``where(True, x, _)``), divisors carry the same value, and
rank masks reproduce the unmasked tie-breaking (stable argsort == dropping
sorted elements).

Reference counterpart: none — the reference aggregates a fixed, always-
present client population (``src/blades/simulator.py:244``); its only
partial-participation surface is the unreachable ``_BaseAsyncAggregator``
family (``aggregators/mean.py:42-87``).
"""

from __future__ import annotations

import jax.numpy as jnp


def participant_count(mask: jnp.ndarray) -> jnp.ndarray:
    """Number of participating clients, int32 scalar."""
    return jnp.sum(mask.astype(jnp.int32))


def masked_mean(updates: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Row-mean over participating rows; zero vector when none participate."""
    m = mask.astype(updates.dtype)
    n = jnp.sum(m)
    return jnp.sum(updates * m[:, None], axis=0) / jnp.maximum(n, 1.0)


def masked_median(updates: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Coordinate-wise median over participating rows (numpy midpoint
    convention for even counts), via sentinel sort: masked-out rows are
    pushed to ``+inf`` so the first ``n`` order statistics per coordinate
    are exactly the participants'."""
    n = participant_count(mask)
    s = jnp.sort(jnp.where(mask[:, None], updates, jnp.inf), axis=0)
    lo = s[jnp.maximum((n - 1) // 2, 0)]
    hi = s[jnp.maximum(n // 2, 0)]
    mid = (lo + hi) / 2.0
    return jnp.where(n > 0, mid, jnp.zeros_like(mid))


def masked_median_1d(values: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Scalar median of the participating entries of a ``[K]`` vector."""
    return masked_median(values[:, None], mask)[0]


def masked_trimmed_mean(
    updates: jnp.ndarray, mask: jnp.ndarray, b: int
) -> jnp.ndarray:
    """Coordinate-wise trimmed mean over participating rows.

    Rank-mask formulation: per coordinate, rank the participants (masked-out
    rows sentineled to ``+inf`` rank past them), drop the ``b_eff`` smallest
    and largest ranks among the ``n`` participants, and mean the survivors —
    summed in ROW order, matching the survivor-sum of the unmasked
    extraction kernel (``ops/pallas_trimmed.py:_trim_survivor_mean``)
    bit-exactly when the mask is all ones.

    Graceful degradation: ``b`` (static, pre-shrunk against the full K) is
    further clamped to the traced participant count so ``n - 2*b_eff >= 1``
    whenever ``n >= 1`` — under heavy dropout the trim narrows toward the
    masked median instead of trimming the population to nothing.
    """
    k = updates.shape[0]
    n = participant_count(mask)
    b_eff = jnp.minimum(jnp.asarray(b, jnp.int32), jnp.maximum((n - 1) // 2, 0))
    sentinel = jnp.where(mask[:, None], updates, jnp.inf)
    # rank of each row per coordinate among ascending values (stable: ties
    # broken by row index, same survivors-by-value as dropping sorted slots)
    ranks = jnp.argsort(jnp.argsort(sentinel, axis=0), axis=0)
    keep = (ranks >= b_eff) & (ranks < n - b_eff)
    denom = jnp.maximum(n - 2 * b_eff, 1).astype(updates.dtype)
    return jnp.sum(jnp.where(keep, updates, 0.0), axis=0) / denom
