"""Ulysses-style sequence parallelism: all-to-all head-parallel attention.

The second canonical long-context strategy next to ring attention
(``ops/ring_attention.py``; the reference has neither — its attention runs
over <=256 tokens on one device, ``cctnets/utils/transformers.py:8-37``).
Instead of rotating K/V blocks around a ring, two ``lax.all_to_all``
reshards bracket a fully local attention:

1. sequence-sharded ``[B, N/P, H, Dh]`` → all-to-all (split heads, gather
   sequence) → ``[B, N, H/P, Dh]``: each device now holds the FULL
   sequence for its H/P heads;
2. plain full-softmax attention per device — no online-softmax recurrence,
   no per-step collectives;
3. all-to-all back (split sequence, gather heads) → ``[B, N/P, H, Dh]``.

Trade-off vs the ring: two bulk all-to-alls (ICI-friendly, overlap-free)
instead of P ``ppermute`` hops interleaved with compute, and O(N) (not
O(N/P)) activation memory for the local attention — the right choice when
heads are plentiful and the per-device sequence fits, while the ring wins
at extreme N. Requires ``H % P == 0``.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from blades_tpu.ops.ring_attention import NEG_INF, shard_map_seq_attention


def _ulysses_body(q, k, v, kv_mask, axis_name: str, scale: float):
    """Per-device program: reshard to head-parallel, attend, reshard back."""
    # [B, N/P, H, Dh] -> [B, N, H/P, Dh]: split the head axis across
    # devices, concatenate the received sequence blocks
    q = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    k = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    v = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)

    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if kv_mask is not None:
        # each device holds [B, N/P] of the mask; attention needs all N
        full_mask = lax.all_gather(
            kv_mask, axis_name, axis=1, tiled=True
        )  # [B, N]
        s = jnp.where(full_mask[:, None, None, :], s, NEG_INF)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))

    # cast BEFORE the reverse reshard: under bf16 inputs this halves the
    # bytes the second all-to-all moves over ICI
    out = out.astype(q.dtype)
    # [B, N, H/P, Dh] -> [B, N/P, H, Dh]
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str,
    kv_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Exact multi-head attention, sequence axis sharded over
    ``mesh[axis_name]``, computed head-parallel via two all-to-alls.

    Same contract as :func:`ring_attention`: ``q``/``k``/``v`` are
    ``[B, N, H, Dh]`` with N divisible by the axis size; additionally H
    must be divisible by the axis size. ``kv_mask``: optional ``[B, N]``
    bool validity mask. Returns ``[B, N, H, Dh]`` sharded like ``q``.
    """
    n_dev = mesh.shape[axis_name]
    if q.shape[2] % n_dev:
        raise ValueError(
            f"ulysses needs heads ({q.shape[2]}) divisible by the "
            f"'{axis_name}' axis size ({n_dev}); use ring_attention instead"
        )
    return shard_map_seq_attention(
        _ulysses_body, mesh, axis_name, q, k, v, kv_mask
    )
