"""Complete-linkage agglomerative clustering, in pure JAX.

The reference delegates to sklearn's ``AgglomerativeClustering(linkage=
'complete', n_clusters=2)`` on a precomputed K x K matrix
(``src/blades/aggregators/clustering.py:38-40``), which is not jittable and
forces a device->host round trip per round. Since K <= ~1000, the O(K^3)
masked-matrix formulation below is trivial work for a TPU and keeps the whole
defense inside the compiled round program.

Algorithm: maintain the pairwise cluster-distance matrix. For K-2 steps, find
the closest active pair (i < j), merge j into i with complete linkage
(``d(i∪j, c) = max(d_ic, d_jc)``), deactivate j, and relabel members of j to
i. Two clusters remain; labels are canonicalized to {0, 1} with cluster 0
containing point 0 (sklearn's numbering differs, but the *partition* — which
is all the defenses consume — is identical up to distance ties).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def complete_linkage_two_clusters(dist: jnp.ndarray) -> jnp.ndarray:
    """``[K, K]`` symmetric distance matrix -> binary labels ``[K]``.

    Returns labels in {0, 1}; label 0 is the cluster containing point 0.
    """
    k = dist.shape[0]
    big = jnp.asarray(jnp.finfo(dist.dtype).max, dtype=dist.dtype)
    # mask the diagonal; inactive rows/cols are pushed to +big as we merge
    d0 = jnp.where(jnp.eye(k, dtype=bool), big, dist)
    active0 = jnp.ones((k,), dtype=bool)
    labels0 = jnp.arange(k)

    def body(_, carry):
        d, active, labels = carry
        masked = jnp.where(active[:, None] & active[None, :], d, big)
        flat = jnp.argmin(masked)
        a, b = flat // k, flat % k
        i, j = jnp.minimum(a, b), jnp.maximum(a, b)
        # complete linkage: new cluster's distance to c is max(d_ic, d_jc)
        merged_row = jnp.maximum(d[i], d[j])
        d = d.at[i, :].set(merged_row).at[:, i].set(merged_row)
        d = d.at[i, i].set(big)
        active = active.at[j].set(False)
        labels = jnp.where(labels == j, i, labels)
        return d, active, labels

    _, _, labels = jax.lax.fori_loop(0, k - 2, body, (d0, active0, labels0))
    # two representative ids remain; canonicalize to {0, 1}
    rep0 = labels[0]
    return jnp.where(labels == rep0, 0, 1)


def majority_cluster_mean(updates: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean of the rows in the larger cluster (ties -> cluster 0, the one
    containing client 0 — the reference breaks ties toward sklearn's label 0,
    ``clustering.py:41``)."""
    size1 = jnp.sum(labels)
    k = labels.shape[0]
    majority = jnp.where(size1 > k - size1, 1, 0)
    mask = (labels == majority).astype(updates.dtype)
    return (mask @ updates) / jnp.sum(mask)
