"""Pallas TPU kernel: one-pass coordinate-wise trimmed mean over ``[K, D]``.

The XLA lowering of trimmed mean (``jnp.sort`` along the client axis,
``aggregators/trimmedmean.py``) is a multi-pass bitonic sort over the full
``K x D`` update matrix — at the north-star scale (K=1000, CCT D≈284k that is
~1.1 GB of HBM traffic per sort pass. The trim count ``b`` is small (the
byzantine budget), so selecting the b largest / b smallest per coordinate by
**iterative extremum extraction inside VMEM** needs exactly ONE read of the
matrix from HBM:

  grid over D-tiles → load ``[K, T]`` block into VMEM once →
  2b rounds of (per-lane max/argmax, mask, accumulate) on the VPU →
  out = (column_sum - top_b_sum - bottom_b_sum) / (K - 2b)

Ties are broken by masking exactly the argmax row per lane, mirroring what
dropping one sorted element does.

``trimmed_mean`` falls back to the sort path off-TPU, when ``2b >= K``, or
when a ``[K, T]`` block would not fit VMEM; ``interpret=True`` runs the
kernel in interpreter mode (used by CPU tests to validate the kernel logic
itself).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# input-block float budget. The kernel's live VMEM is ~4x the block: the f32
# block itself, the int32 iota, the masked temp, the bool mask, plus pallas's
# double-buffered input — 500k floats => ~8 MB of ~16 MB VMEM/core.
_VMEM_BUDGET_FLOATS = 500_000
_LANES = 128


def _kernel(u_ref, out_ref, *, b: int, k: int):
    x = u_ref[...].astype(jnp.float32)  # [K, T]
    rows = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)

    def extract(removed, sign):
        # mark b extrema of `sign` (+1: maxima, -1: minima) as removed,
        # skipping rows already removed by the other pass
        def body(_, rem):
            masked = jnp.where(rem, -jnp.inf, sign * x)
            idx = jnp.argmax(masked, axis=0)  # [T]
            return rem | (rows == idx[None, :])

        return jax.lax.fori_loop(0, b, body, removed)

    removed = extract(jnp.zeros(x.shape, bool), 1.0)
    removed = extract(removed, -1.0)
    # sum the SURVIVORS — never summing the trimmed extremes keeps byzantine
    # magnitudes (1e30, inf-scale) out of the arithmetic entirely, exactly
    # like the sort-and-slice path
    out_ref[...] = jnp.sum(jnp.where(removed, 0.0, x), axis=0) / (k - 2 * b)


def _block_width(k: int) -> int:
    t = max(_LANES, (_VMEM_BUDGET_FLOATS // max(k, 1)) // _LANES * _LANES)
    return min(t, 4096)


@functools.partial(jax.jit, static_argnames=("b", "interpret"))
def _trimmed_mean_pallas(updates: jnp.ndarray, b: int, interpret: bool = False):
    k, d = updates.shape
    t = _block_width(k)
    pad = (-d) % t
    u = jnp.pad(updates, ((0, 0), (0, pad))) if pad else updates
    dp = d + pad
    out = pl.pallas_call(
        functools.partial(_kernel, b=b, k=k),
        grid=(dp // t,),
        in_specs=[pl.BlockSpec((k, t), lambda i: (0, i))],
        out_specs=pl.BlockSpec((t,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((dp,), jnp.float32),
        interpret=interpret,
    )(u)
    return out[:d]


def trimmed_mean(
    updates: jnp.ndarray,
    b: int,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Coordinate-wise mean of the middle ``K - 2b`` values per coordinate.

    Dispatches to the pallas kernel on TPU (or when ``interpret`` is set);
    otherwise the ``jnp.sort`` path — both numerically identical.
    """
    k, _ = updates.shape
    if b == 0:
        return jnp.mean(updates, axis=0)
    use_kernel = interpret if interpret is not None else (
        jax.default_backend() == "tpu" and k * _LANES <= _VMEM_BUDGET_FLOATS
    )
    if use_kernel and k - 2 * b > 0:
        return _trimmed_mean_pallas(updates, b, interpret=bool(interpret))
    s = jnp.sort(updates, axis=0)
    return jnp.mean(s[b : k - b], axis=0)
