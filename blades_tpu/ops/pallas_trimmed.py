"""Pallas TPU kernel: one-pass coordinate-wise trimmed mean over ``[K, D]``.

The XLA lowering of trimmed mean (``jnp.sort`` along the client axis,
``aggregators/trimmedmean.py``) is a multi-pass bitonic sort over the full
``K x D`` update matrix — at the north-star scale (K=1000, CCT D≈284k that is
~1.1 GB of HBM traffic per sort pass. The trim count ``b`` is small (the
byzantine budget), so selecting the b largest / b smallest per coordinate by
**iterative extremum extraction inside VMEM** needs exactly ONE read of the
matrix from HBM:

  grid over D-tiles → load ``[K, T]`` block into VMEM once →
  2b rounds of (per-lane max/argmax, mask, accumulate) on the VPU →
  out = (column_sum - top_b_sum - bottom_b_sum) / (K - 2b)

Ties are broken by masking exactly the argmax row per lane, mirroring what
dropping one sorted element does.

``trimmed_mean`` falls back to the sort path off-TPU, when ``2b >= K``,
when a ``[K, T]`` block would not fit VMEM, or when the Pallas/Mosaic
toolchain itself cannot compile on this backend (probed once, eagerly, on
first TPU dispatch — some TPU attachment modes proxy compilation through a
helper that rejects Mosaic programs, and a kernel that cannot compile must
not poison the whole round program's compile). ``interpret=True`` runs the
kernel in interpreter mode (used by CPU tests to validate the kernel logic
itself); ``BLADES_TPU_NO_PALLAS=1`` forces the sort path.

Reference counterpart: the two-``topk`` host-side selection in
``src/blades/aggregators/trimmedmean.py:29-44``; the kernelization itself
is new surface (the reference has no device kernels).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# input-block float budget. The kernel's live VMEM is ~4x the block: the f32
# block itself, the int32 iota, the masked temp, the bool mask, plus pallas's
# double-buffered input — 500k floats => ~8 MB of ~16 MB VMEM/core.
_VMEM_BUDGET_FLOATS = 500_000
_LANES = 128
# the extraction loop is unrolled (some Mosaic toolchains reject loop
# constructs in-kernel), so program size is linear in b — cap it to keep
# compiles bounded; larger trim budgets take the sort path
_MAX_UNROLL_B = 16


def _trim_survivor_mean(x: jnp.ndarray, b: int, k: int) -> jnp.ndarray:
    """Shared extraction core: mean of the rows surviving a 2b-extremum trim.

    Marks b maxima then b minima per column as removed (each pass retires
    exactly ONE row per column — ties break the way dropping one sorted
    element does), then sums the SURVIVORS: never summing the trimmed
    extremes keeps byzantine magnitudes (1e30, inf-scale) out of the
    arithmetic entirely, exactly like the sort-and-slice path. b is static
    and small, so unroll in Python: cheaper than a loop construct, and some
    Mosaic toolchains reject fori_loop inside a kernel. Pure jnp ops only —
    runs identically inside the Pallas kernel and as a plain XLA program.
    """
    rows = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    removed = jnp.zeros(x.shape, bool)
    for sign in (1.0, -1.0):
        for _ in range(b):
            masked = jnp.where(removed, -jnp.inf, sign * x)
            idx = jnp.argmax(masked, axis=0)
            removed = removed | (rows == idx[None, :])
    return jnp.sum(jnp.where(removed, 0.0, x), axis=0) / (k - 2 * b)


def _kernel(u_ref, out_ref, *, b: int, k: int):
    x = u_ref[...].astype(jnp.float32)  # [K, T]
    out_ref[...] = _trim_survivor_mean(x, b, k)


def _block_width(k: int) -> int:
    # prefer 1024-multiples: some Mosaic toolchains only compile multi-block
    # grids when the lane dimension is >= 1024 (empirically mapped against a
    # remote-compile helper; narrower multi-block widths were rejected)
    t = (_VMEM_BUDGET_FLOATS // max(k, 1)) // 1024 * 1024
    if t == 0:
        t = max(_LANES, (_VMEM_BUDGET_FLOATS // max(k, 1)) // _LANES * _LANES)
    return min(t, 4096)


@functools.partial(jax.jit, static_argnames=("b", "interpret"))
def _trimmed_mean_pallas(updates: jnp.ndarray, b: int, interpret: bool = False):
    k, d = updates.shape
    t = _block_width(k)
    pad = (-d) % t
    u = jnp.pad(updates, ((0, 0), (0, pad))) if pad else updates
    dp = d + pad
    out = pl.pallas_call(
        functools.partial(_kernel, b=b, k=k),
        grid=(dp // t,),
        in_specs=[pl.BlockSpec((k, t), lambda i: (0, i))],
        out_specs=pl.BlockSpec((t,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((dp,), jnp.float32),
        interpret=interpret,
    )(u)
    return out[:d]


_PROBE_CACHE: dict = {}


def _pallas_ok(k: int, d: int, b: int, dtype) -> bool:
    """Exact-shape probe: can Mosaic compile THIS kernel on this backend?

    A failing kernel inside the jitted round program fails the WHOLE round
    compile, so AOT-lower-and-compile the exact standalone program first
    (concrete shapes/dtype only — safe to run even while an outer jit is
    tracing). The observed failure mode this guards against: TPU
    attachment modes whose remote compile helper 500s on some Mosaic
    programs (narrow multi-block grids) while plain XLA works. The
    fallback costs one failed compile attempt per (k, d, b, dtype)
    signature per process; with the persistent compilation cache enabled
    (``utils/xla_cache.py`` — on in every shipped entry point) the probe
    executable is reused across processes. Necessary, not sufficient: the
    probe compiles the single-device program, so a toolchain that rejects
    only the SPMD-partitioned variant inside a sharded round program can
    still fail the round compile — ``BLADES_TPU_NO_PALLAS=1`` is the
    escape hatch for that case.
    """
    if os.environ.get("BLADES_TPU_NO_PALLAS") == "1":
        return False
    key = (k, d, b, jnp.dtype(dtype).name)
    if key not in _PROBE_CACHE:
        try:
            _trimmed_mean_pallas.lower(
                jax.ShapeDtypeStruct((k, d), dtype), b
            ).compile()
            _PROBE_CACHE[key] = True
        except Exception as e:  # Mosaic/compile-helper failure: fall back
            import warnings

            warnings.warn(
                f"pallas trimmed-mean kernel failed to compile for "
                f"(K={k}, D={d}, b={b}); falling back to the plain-XLA "
                f"extraction path for this shape. "
                f"Cause: {type(e).__name__}: {str(e)[:200]}"
            )
            _PROBE_CACHE[key] = False
    return _PROBE_CACHE[key]


def _trimmed_mean_extract(updates: jnp.ndarray, b: int) -> jnp.ndarray:
    """Pure-XLA unrolled extremum extraction — the kernel's algorithm
    (``_trim_survivor_mean``) without Pallas.

    ``2b`` masked argmax passes + one masked sum ≈ ``(2b+1)·K·D·4`` bytes
    of HBM traffic, versus the multi-pass bitonic sort ``jnp.sort`` lowers
    to over the K axis (~``log²K`` compare-exchange stages, each a full
    read+write of the matrix). At the north-star shape (K=1000, D≈284k,
    b=5) that is ~11 passes instead of ~100.
    """
    return _trim_survivor_mean(updates.astype(jnp.float32), b, updates.shape[0])


def trimmed_mean(
    updates: jnp.ndarray,
    b: int,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Coordinate-wise mean of the middle ``K - 2b`` values per coordinate.

    Dispatches to the pallas kernel on TPU (or when ``interpret`` is set);
    else unrolled extraction in plain XLA for small ``b``; else the
    ``jnp.sort`` path — all numerically identical.
    """
    k, _ = updates.shape
    if b == 0:
        return jnp.mean(updates, axis=0)
    use_kernel = interpret if interpret is not None else (
        jax.default_backend() == "tpu"
        and k - 2 * b > 0  # must precede the probe: never compile a dead kernel
        and b <= _MAX_UNROLL_B
        and k * _LANES <= _VMEM_BUDGET_FLOATS
        and _pallas_ok(k, updates.shape[1], b, updates.dtype)
    )
    if use_kernel and k - 2 * b > 0:
        return _trimmed_mean_pallas(updates, b, interpret=bool(interpret))
    if k - 2 * b > 0 and b <= _MAX_UNROLL_B:
        return _trimmed_mean_extract(updates, b)
    s = jnp.sort(updates, axis=0)
    return jnp.mean(s[b : k - b], axis=0)
