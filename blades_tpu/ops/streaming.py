"""Streaming (chunk-scanned) reductions over the client axis.

The dense engine materializes the full ``[K, D]`` post-attack update matrix
before aggregating — which caps K at device memory regardless of how the
*training* activations are chunked. This module holds the building blocks
of the streaming alternative: the engine ``lax.scan``\\s the per-chunk
train+attack+fault body and feeds each ``[chunk, D]`` slab into a small
**running reduction state**, so peak update memory is ``[chunk, D]`` (plus
``[num_chunks, ...]`` chunk summaries) independent of K.

Three families of primitives:

- **running moments** — mask-aware count / sum / sum-of-squares carries for
  streaming means and the engine's per-coordinate variance metrics
  (one-pass ``E[x^2] - E[x]^2``, clamped at zero);
- **chunk stacks** — fixed-shape ``[num_chunks, ...]`` accumulators written
  one chunk-local summary per scan step (``lax.dynamic_update_index_in_dim``),
  the carrier of every *two-level* aggregate ("aggregate the
  chunk-aggregates", ``aggregators/base.py``);
- **chunk geometry sketches** — per-chunk center / radius / diameter and
  per-row distance-to-chunk-center scalars, from which the streaming
  :class:`~blades_tpu.audit.monitor.AuditMonitor` certificates derive
  triangle-inequality interval bounds on the dense row statistics
  (``|u_i - p| ∈ d_i ± |c_j - p|`` for any point ``p`` fixed at finalize).

Everything is a pure fixed-shape function (jit/scan-safe); masks follow the
``ops/masked.py`` discipline — masked-out rows enter sums only as exact
identities, so an all-ones mask reproduces the unmasked arithmetic
bit-exactly.

Reference counterpart: none — the reference aggregates host-side lists of
full update vectors (``src/blades/aggregators/mean.py:21-28``); its client
axis is capped by driver RAM long before 10^4. The chunk-the-batch-axis
discipline follows the hybrid-sharding exemplars in SNIPPETS.md, applied to
the client axis instead of the data axis.
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
from jax import lax


# -- chunk layout -------------------------------------------------------------


def chunk_layout(num_rows: int, num_chunks: int):
    """``(num_chunks, chunk_size, pad)`` for the padded chunk layout.

    Single owner of the layout rule shared by the engine
    (``RoundEngine.__init__``), the host-side protocol driver
    (``Aggregator.aggregate_streaming``) and the streaming tests: the
    chunk count clamps to the population, chunks are ceil-sized, and the
    count is renormalized against the ceil size so no chunk is 100%
    padding (``pad < chunk_size`` always).
    """
    c = max(1, min(int(num_chunks), int(num_rows)))
    chunk = -(-int(num_rows) // c)
    c = -(-int(num_rows) // chunk)
    return c, chunk, c * chunk - int(num_rows)


# -- running moments ----------------------------------------------------------


def moments_init(dim: int, dtype=jnp.float32) -> Dict[str, Any]:
    """Zero running-moment carry for a ``[*, dim]`` stream."""
    return {
        "sum": jnp.zeros((dim,), dtype),
        "sumsq": jnp.zeros((dim,), dtype),
        "count": jnp.zeros((), dtype),
    }


def moments_update(
    m: Dict[str, Any], rows: jnp.ndarray, mask: jnp.ndarray
) -> Dict[str, Any]:
    """Fold a ``[chunk, D]`` slab into the carry (masked rows contribute 0)."""
    w = mask.astype(rows.dtype)[:, None]
    return {
        "sum": m["sum"] + jnp.sum(rows * w, axis=0),
        "sumsq": m["sumsq"] + jnp.sum(rows * rows * w, axis=0),
        "count": m["count"] + jnp.sum(mask.astype(m["count"].dtype)),
    }


def moments_mean(m: Dict[str, Any]) -> jnp.ndarray:
    """Streaming mean; zero vector when the stream was empty."""
    return m["sum"] / jnp.maximum(m["count"], 1.0)


def moments_var(m: Dict[str, Any]) -> jnp.ndarray:
    """One-pass population variance ``E[x^2] - E[x]^2`` per coordinate.

    Numerically this is the textbook one-pass form (catastrophic
    cancellation possible when ``|mean| >> std``), clamped at zero — it
    feeds *metrics* (``update_variance`` telemetry), never defense
    arithmetic, and the documented streaming-metrics tolerance covers it.
    """
    mu = moments_mean(m)
    return jnp.maximum(m["sumsq"] / jnp.maximum(m["count"], 1.0) - mu * mu, 0.0)


# -- chunk stacks -------------------------------------------------------------


def stack_init(num_chunks: int, shape, dtype=jnp.float32) -> jnp.ndarray:
    """Zero ``[num_chunks, *shape]`` accumulator for per-chunk summaries."""
    return jnp.zeros((num_chunks,) + tuple(shape), dtype)


def stack_write(stack: jnp.ndarray, chunk_index, value: jnp.ndarray) -> jnp.ndarray:
    """Write one chunk's summary at a traced index (scan-carry friendly)."""
    return lax.dynamic_update_index_in_dim(
        stack, value.astype(stack.dtype), chunk_index, axis=0
    )


def weighted_stack_mean(stack: jnp.ndarray, counts: jnp.ndarray) -> jnp.ndarray:
    """Count-weighted mean of chunk summaries: ``sum_j n_j a_j / sum_j n_j``.

    The exact recombination for any chunk summary that is itself a
    participant mean (``mean == weighted mean of chunk means``); zero vector
    when no chunk had participants.
    """
    w = counts.astype(stack.dtype)
    num = jnp.sum(stack * w[:, None], axis=0)
    return num / jnp.maximum(jnp.sum(w), 1.0)


# -- chunk geometry sketches --------------------------------------------------


def chunk_geometry(
    slab: jnp.ndarray, mask: jnp.ndarray, center: jnp.ndarray
) -> Dict[str, Any]:
    """Per-chunk geometry summary against a chunk-local ``center``.

    Returns ``row_dist [chunk]`` (distance of each participating row to the
    center; 0 for masked rows), ``radius`` (max row distance) and
    ``diameter`` (exact max pairwise distance *within* the chunk — a
    ``[chunk, chunk]`` matrix, cheap at chunk scale). These are the
    sufficient statistics for the streaming audit certificates'
    triangle-inequality bounds.
    """
    diff = slab - center[None, :]
    d = jnp.sqrt(jnp.maximum(jnp.sum(diff * diff, axis=1), 0.0))
    d = jnp.where(mask, d, 0.0)
    # within-chunk pairwise distances (chunk^2 — small by construction)
    sq = jnp.sum(slab * slab, axis=1)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (slab @ slab.T)
    pair = mask[:, None] & mask[None, :]
    diam = jnp.sqrt(jnp.maximum(jnp.max(jnp.where(pair, d2, 0.0)), 0.0))
    return {
        "row_dist": d,
        "radius": jnp.max(d),
        "diameter": diam,
    }
