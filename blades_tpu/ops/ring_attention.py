"""Ring attention: sequence-parallel exact attention over the device mesh.

The reference has no long-context machinery at all — its attention runs over
<=256 tokens on one device (``cctnets/utils/transformers.py:8-37``; SURVEY.md
section 5 "long-context: absent by design"). This module makes long sequences
first-class on TPU: the sequence axis is sharded across a mesh axis, every
device keeps its Q block resident, and K/V blocks rotate around the ring via
``lax.ppermute`` (neighbor hops over ICI) while an online-softmax accumulator
(running max ``m``, normalizer ``l``, output ``o`` — the flash-attention
recurrence) folds in one block per step. Exact attention, O(N/P) activation
memory per device, compute/communication overlapped by XLA.

Layout: ``[B, N, H, Dh]`` with N sharded. The optional ``kv_mask``
(``[B, N]`` bool, True = valid token) rides the ring with its K/V block, so
padded positions are excluded exactly as in single-device masked attention.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _block_update(q, k, v, kv_mask, m, l, o, scale):
    """Fold one K/V block into the online-softmax accumulator."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # [B, H, Nq, Nk]
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, NEG_INF)
    m_cur = jnp.max(s, axis=-1)  # [B, H, Nq]
    m_new = jnp.maximum(m, m_cur)
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m - m_new)  # rescale of the old accumulator
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v)
    return m_new, l_new, o_new


def _ring_body(q, k, v, kv_mask, axis_name: str, scale: float):
    """Per-device program: rotate K/V (and mask) around the ring."""
    n_dev = lax.psum(1, axis_name)
    b, nq, h, d = q.shape
    m = jnp.full((b, h, nq), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, nq), jnp.float32)
    o = jnp.zeros((b, h, nq, d), jnp.float32)

    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def step(_, carry):
        k_blk, v_blk, mask_blk, m, l, o = carry
        m, l, o = _block_update(q, k_blk, v_blk, mask_blk, m, l, o, scale)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        if mask_blk is not None:
            mask_blk = lax.ppermute(mask_blk, axis_name, perm)
        return k_blk, v_blk, mask_blk, m, l, o

    _, _, _, m, l, o = lax.fori_loop(0, n_dev, step, (k, v, kv_mask, m, l, o))
    # [B, H, Nq, D] -> [B, Nq, H, D]; guard fully-masked rows (l == 0)
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def shard_map_seq_attention(body_fn, mesh: Mesh, axis_name: str,
                            q, k, v, kv_mask):
    """Shared shard_map harness for sequence-parallel attention bodies.

    ``body_fn(q, k, v, kv_mask, axis_name=..., scale=...)`` runs per-device
    on ``[B, N/P, H, Dh]`` blocks; used by both the ring
    (:func:`ring_attention`) and the all-to-all (:mod:`ops.ulysses`)
    schedules so the jax version shims live in exactly one place.
    """
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    # the replication-check kwarg was renamed check_rep -> check_vma in
    # jax 0.8; disable it under either name (the per-device carries are
    # intentionally device-varying)
    import inspect

    smap_params = inspect.signature(shard_map).parameters
    if "check_vma" in smap_params:
        check_kw = {"check_vma": False}
    elif "check_rep" in smap_params:
        check_kw = {"check_rep": False}
    else:
        check_kw = {}

    scale = q.shape[-1] ** -0.5
    seq = P(None, axis_name, None, None)
    mask_spec = P(None, axis_name)
    in_specs = (seq, seq, seq) + ((mask_spec,) if kv_mask is not None else ())
    fn = functools.partial(body_fn, axis_name=axis_name, scale=scale)

    if kv_mask is not None:
        body = lambda q_, k_, v_, mk: fn(q_, k_, v_, mk)
        args = (q, k, v, kv_mask)
    else:
        body = lambda q_, k_, v_: fn(q_, k_, v_, None)
        args = (q, k, v)

    return shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=seq, **check_kw
    )(*args)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str,
    kv_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Exact multi-head attention with the sequence axis sharded over
    ``mesh[axis_name]``.

    ``q``/``k``/``v``: ``[B, N, H, Dh]`` (N divisible by the axis size);
    ``kv_mask``: optional ``[B, N]`` bool validity mask. Returns ``[B, N, H,
    Dh]`` sharded like ``q``.
    """
    return shard_map_seq_attention(_ring_body, mesh, axis_name, q, k, v, kv_mask)


def attention_reference(q, k, v, kv_mask=None):
    """Single-device full-softmax attention (testing oracle)."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
