"""Seeded chaos suite (``scripts/chaos.py``): randomized-but-reproducible
fault weather crossed with the aggregator registry, asserting the PR-2
robustness invariants end to end — finite loss or explicit skip,
masked-row inertness (NaN <-> Inf content swaps cannot move the model),
and SIGKILL-at-a-random-round + supervised resume being bit-exact.

Tier-1 runs a reduced slice (two scenarios + one inertness twin); the full
>= 20-scenario sweep and the subprocess supervised scenarios carry the
``slow`` marker (tier-1 excludes them via ``-m 'not slow'``). The full
sweep's committed evidence lives in ``results/chaos_sweep.json``.

Reference counterpart: none — the reference has no fault surface and no
tests (SURVEY.md section 4).
"""

import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHAOS = os.path.join(REPO, "scripts", "chaos.py")

spec = importlib.util.spec_from_file_location("chaos_under_test", CHAOS)
chaos = importlib.util.module_from_spec(spec)
spec.loader.exec_module(chaos)

# the tier-1 slice: one clean-dropout scenario and one whole-row-NaN
# scenario (whose inertness twin is also exercised); the other 22+ run in
# the slow sweep
TIER1_SEEDS = (1, 3)


def test_scenarios_deterministic_and_serializable():
    for seed in range(24):
        a, b = chaos.make_scenario(seed), chaos.make_scenario(seed)
        assert a == b
        json.dumps(a)  # child mode rebuilds scenarios from the seed alone


def test_sweep_covers_every_pool_aggregator():
    aggs = {chaos.make_scenario(s)["agg"] for s in range(24)}
    assert aggs == set(chaos.AGG_POOL)
    assert len(chaos.AGG_POOL) + 6 <= 24  # >= 20 scenarios, registry covered


def test_inertness_twin_only_for_whole_row_corruption():
    for seed in range(24):
        scn = chaos.make_scenario(seed)
        twin = chaos.inertness_variant(scn)
        mode = scn["fault"].get("corrupt_mode")
        if mode in ("nan", "inf"):
            assert twin is not None
            assert twin["fault"]["corrupt_mode"] != mode
            unchanged = {k: v for k, v in twin.items() if k != "fault"}
            assert unchanged == {k: v for k, v in scn.items() if k != "fault"}
        else:
            assert twin is None


@pytest.mark.parametrize("seed", TIER1_SEEDS)
def test_scenario_invariants_tier1(seed, tmp_path):
    scn = chaos.make_scenario(seed)
    log = str(tmp_path / f"s{seed}")
    sim, params = chaos.run_scenario(scn, log)
    violations = chaos.check_invariants(scn, log, params)
    assert violations == []
    ev = sim.evaluate(scn["rounds"], 64)
    assert np.isfinite(ev["Loss"])


def test_inertness_twin_bit_identical_tier1(tmp_path):
    """End-to-end masked-row inertness: seed 1 corrupts a delivered row
    with NaN; the twin corrupts the same row (same RNG draws) with Inf.
    Both are excluded by the non-finite guard, so the final parameters
    must not differ by a single bit."""
    scn = chaos.make_scenario(1)
    assert scn["fault"]["corrupt_mode"] == "nan"  # scenario table pin
    twin = chaos.inertness_variant(scn)
    _, p_nan = chaos.run_scenario(scn, str(tmp_path / "nan"))
    _, p_inf = chaos.run_scenario(twin, str(tmp_path / "inf"))
    np.testing.assert_array_equal(p_nan, p_inf)


def test_async_scenario_invariants_tier1(tmp_path):
    """Invariant 7, tier-1 slice: a buffered-async chaos scenario (seed 5
    — every 6th seed runs FedBuff-style rounds under its fault weather)
    completes with all invariants intact, its per-round `async` records'
    buffer arithmetic self-consistent, AND the same scenario through
    Simulator.run(block_size=2) lands on bit-identical final parameters
    (the async state — buffer, versions, countdowns, lag ring — rides the
    round-block scan like every other RoundState leaf)."""
    scn = chaos.make_scenario(5)
    assert scn.get("async") is not None  # scenario table pin
    assert "straggler_rate" not in scn["fault"]  # replaced by staleness
    log = str(tmp_path / "s5")
    sim, params = chaos.run_scenario(scn, log)
    violations = chaos.check_invariants(scn, log, params)
    assert violations == []
    ev = sim.evaluate(scn["rounds"], 64)
    assert np.isfinite(ev["Loss"])
    _, p_blk = chaos.run_scenario(scn, str(tmp_path / "blk"), block_size=2)
    np.testing.assert_array_equal(params, p_blk)


def test_block_scheduling_neutral_under_faults_tier1(tmp_path):
    """Invariant 6, tier-1 slice: the same chaos scenario run through
    Simulator.run(block_size=2) — the scanned round-block program with the
    sampler fused in, composed with this scenario's fault weather and the
    record-only audit monitor — produces bit-identical final parameters
    (3 rounds at block 2 also exercises the remainder block)."""
    scn = chaos.make_scenario(1)
    _, p_seq = chaos.run_scenario(scn, str(tmp_path / "seq"))
    _, p_blk = chaos.run_scenario(scn, str(tmp_path / "blk"), block_size=2)
    np.testing.assert_array_equal(p_seq, p_blk)


# --------------------------------------------------------------- full sweep


@pytest.mark.slow
def test_full_sweep_zero_violations(tmp_path):
    """>= 20 seeded fault x aggregator scenarios, zero invariant
    violations (the committed evidence run: results/chaos_sweep.json)."""
    summary = chaos.sweep(24, str(tmp_path))
    assert summary["scenarios"] == 24
    assert set(summary["aggregators_covered"]) == set(chaos.AGG_POOL)
    assert summary["inertness_pairs"] >= 8
    assert summary["violations"] == []


@pytest.mark.slow
def test_service_chaos_full_slice(tmp_path):
    """The full simulation-service chaos slice (reduced slice runs
    tier-1 in tests/test_service.py): poison isolation, backpressure,
    deadline-tripped hang, drain-no-loss, tenant flood, preempt-resume,
    worker-crash/worker-hang containment, plus the supervised
    SIGKILL-resume drill — the committed evidence run behind
    results/chaos_sweep.json's `service` block."""
    summary = chaos.service_chaos(str(tmp_path), full=True)
    assert summary["ok"], json.dumps(summary, indent=1)
    names = [s["name"] for s in summary["scenarios"]]
    assert "sigkill_resume" in names and len(names) == 9
    assert "worker_crash" in names and "worker_hang" in names


@pytest.mark.slow
def test_supervised_sigkill_resume_bit_exact(tmp_path):
    """A chaos child SIGKILLs itself (no autosave, no cleanup — the
    hardest crash) at round 2; the supervisor relaunches with
    BLADES_RESUME=1 and the resumed run's final params match the
    uninterrupted run bit-for-bit (per-round atomic checkpoints)."""
    from blades_tpu.supervision import Supervisor

    env = dict(os.environ, CHAOS_DEVICES="1")
    ref_params = tmp_path / "ref.npy"
    p = subprocess.run(
        [sys.executable, CHAOS, "--child", "--seed", "1",
         "--out", str(tmp_path / "ref"), "--params-out", str(ref_params)],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=420,
    )
    assert p.returncode == 0, (p.stdout, p.stderr)

    sup_params = tmp_path / "sup.npy"
    telem = str(tmp_path / "sup" / "telemetry.jsonl")
    result = Supervisor(
        [sys.executable, CHAOS, "--child", "--seed", "1",
         "--out", str(tmp_path / "sup"), "--params-out", str(sup_params),
         "--kill-at", "2"],
        attempts=2, base_delay_s=0.1, poll_s=0.2, telemetry_path=telem,
        heartbeat_file=str(tmp_path / "hb"), env={"CHAOS_DEVICES": "1"},
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    ).run()
    assert result.ok
    assert result.attempts[0].reason == "exit"
    assert result.attempts[0].returncode == -9  # SIGKILL'd itself
    assert result.attempts[1].resumed
    np.testing.assert_array_equal(np.load(ref_params), np.load(sup_params))


@pytest.mark.slow
def test_bench_one_json_line_under_supervisor(tmp_path):
    """bench.py's one-JSON-line contract holds under the supervisor: the
    inherited stdout carries exactly the payload line (CPU fallback here —
    clearly labeled by bench itself)."""
    env = dict(os.environ)
    env.update({
        "BENCH_PROBE_TIMEOUT": "120", "BENCH_SMOKE_TIMEOUT": "420",
        "JAX_PLATFORMS": "cpu",
    })
    p = subprocess.run(
        [sys.executable, "-m", "blades_tpu.supervision", "--attempts", "1",
         "--deadline", "900", "--", sys.executable, "bench.py"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=900,
    )
    lines = [l for l in p.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, p.stdout
    payload = json.loads(lines[0])
    assert payload["metric"].endswith("rounds_per_sec")


@pytest.mark.slow
def test_graft_entry_gate_under_supervisor():
    """The driver's single-chip compile gate still passes when wrapped in
    the supervisor (deadline-only supervision; heartbeats are optional)."""
    code = (
        "import __graft_entry__ as g, jax; fn, args = g.entry(); "
        "out = jax.jit(fn)(*args); jax.block_until_ready(out); print('GATE_OK')"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-m", "blades_tpu.supervision", "--attempts", "1",
         "--deadline", "600", "--", sys.executable, "-c", code],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=700,
    )
    assert p.returncode == 0, (p.stdout, p.stderr)
    assert "GATE_OK" in p.stdout
