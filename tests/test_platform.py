"""Platform forcing: the env contract must be binding in a fresh process.

The TPU plugin's sitecustomize rewrites ``jax_platforms`` to ``axon,cpu``
at interpreter start, which made ``JAX_PLATFORMS=cpu python ...`` hang on
a dead tunnel (backend init blocks forever). ``apply_env_platform()`` is
the in-process re-assertion every example runs at startup; this test
proves it in a real subprocess — the only place the sitecustomize
interaction exists.
"""

import os
import subprocess
import sys


def test_apply_env_platform_binds_cpu_request():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=3"
    out = subprocess.run(
        [
            sys.executable,
            "-c",
            "import sys; sys.path.insert(0, %r)\n"
            "from blades_tpu.utils.platform import apply_env_platform\n"
            "apply_env_platform()\n"
            "import jax\n"
            "print('RESULT', jax.default_backend(), jax.device_count())"
            % os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr[-1500:]
    line = [l for l in out.stdout.splitlines() if l.startswith("RESULT")][-1]
    assert line.split() == ["RESULT", "cpu", "3"]
