"""Model-zoo tests: shapes, param-count parity, gradient flow, determinism."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.models import MODELS, build_fns, create_model
from blades_tpu.ops.pytree import flat_dim

SHAPES = {
    "mlp": (28, 28, 1),
    "cct_2_3x2_32": (32, 32, 3),
    "cvt_7_4_32": (32, 32, 3),
    "vit_lite_7_4_32": (32, 32, 3),
    "resnet18": (32, 32, 3),
    "wrn_28_10": (32, 32, 3),
}


@pytest.mark.parametrize("name", ["mlp", "cct_2_3x2_32", "resnet18"])
def test_forward_backward(name):
    spec = build_fns(create_model(name), SHAPES[name])
    p = spec.init(jax.random.PRNGKey(0))
    x = jnp.zeros((2,) + SHAPES[name])
    y = jnp.array([0, 1])
    logits = spec.eval_logits_fn(p, x)
    assert logits.shape == (2, 10)
    (loss, aux), g = jax.value_and_grad(
        lambda pp: spec.train_loss_fn(pp, x, y, jax.random.PRNGKey(1)),
        has_aux=True,
    )(p)
    assert np.isfinite(float(loss))
    assert 0.0 <= float(aux["top1"]) <= 1.0
    gnorm = sum(float(jnp.abs(l).sum()) for l in jax.tree_util.tree_leaves(g))
    assert gnorm > 0


def test_mlp_architecture_parity():
    """784->64->128->10 log_softmax (reference dnn.py:5-19)."""
    spec = build_fns(create_model("mlp"), (28, 28, 1))
    p = spec.init(jax.random.PRNGKey(0))
    expect = 784 * 64 + 64 + 64 * 128 + 128 + 128 * 10 + 10
    assert flat_dim(p) == expect
    logits = spec.eval_logits_fn(p, jnp.zeros((1, 28, 28, 1)))
    # log_softmax output: logsumexp == 0
    assert abs(float(jax.scipy.special.logsumexp(logits, axis=-1)[0])) < 1e-5


def test_cct2_param_count_parity():
    """cct_2_3x2_32 is ~284K params in the reference zoo."""
    spec = build_fns(create_model("cct_2_3x2_32"), (32, 32, 3))
    d = flat_dim(spec.init(jax.random.PRNGKey(0)))
    assert 270_000 < d < 300_000, d


def test_dropout_train_vs_eval():
    spec = build_fns(create_model("cct_2_3x2_32"), (32, 32, 3))
    p = spec.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 32, 32, 3))
    e1 = spec.eval_logits_fn(p, x)
    e2 = spec.eval_logits_fn(p, x)
    np.testing.assert_array_equal(e1, e2)  # eval deterministic
    y = jnp.zeros(4, jnp.int32)
    l1, _ = spec.train_loss_fn(p, x, y, jax.random.PRNGKey(3))
    l2, _ = spec.train_loss_fn(p, x, y, jax.random.PRNGKey(4))
    assert float(l1) != float(l2)  # train stochastic (dropout/droppath)


def test_registry_complete():
    for name in ["mlp", "cct", "cctnet", "resnet18", "wrn_28_10", "cvt_7_4_32"]:
        assert name in MODELS
    with pytest.raises(ValueError):
        create_model("nope")


def test_wrn_and_cvt_build():
    for name in ["cvt_7_4_32", "vit_lite_7_4_32"]:
        spec = build_fns(create_model(name), (32, 32, 3))
        p = spec.init(jax.random.PRNGKey(0))
        out = spec.eval_logits_fn(p, jnp.zeros((1, 32, 32, 3)))
        assert out.shape == (1, 10)
