"""Model-zoo tests: shapes, param-count parity, gradient flow, determinism."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.models import MODELS, build_fns, create_model
from blades_tpu.ops.pytree import flat_dim

SHAPES = {
    "mlp": (28, 28, 1),
    "cct_2_3x2_32": (32, 32, 3),
    "cvt_7_4_32": (32, 32, 3),
    "vit_lite_7_4_32": (32, 32, 3),
    "resnet18": (32, 32, 3),
    "wrn_28_10": (32, 32, 3),
}


@pytest.mark.parametrize("name", ["mlp", "cct_2_3x2_32", "resnet18"])
def test_forward_backward(name):
    spec = build_fns(create_model(name), SHAPES[name])
    p = spec.init(jax.random.PRNGKey(0))
    x = jnp.zeros((2,) + SHAPES[name])
    y = jnp.array([0, 1])
    logits = spec.eval_logits_fn(p, x)
    assert logits.shape == (2, 10)
    (loss, aux), g = jax.value_and_grad(
        lambda pp: spec.train_loss_fn(pp, x, y, jax.random.PRNGKey(1)),
        has_aux=True,
    )(p)
    assert np.isfinite(float(loss))
    assert 0.0 <= float(aux["top1"]) <= 1.0
    gnorm = sum(float(jnp.abs(l).sum()) for l in jax.tree_util.tree_leaves(g))
    assert gnorm > 0


def test_mlp_architecture_parity():
    """784->64->128->10 log_softmax (reference dnn.py:5-19)."""
    spec = build_fns(create_model("mlp"), (28, 28, 1))
    p = spec.init(jax.random.PRNGKey(0))
    expect = 784 * 64 + 64 + 64 * 128 + 128 + 128 * 10 + 10
    assert flat_dim(p) == expect
    logits = spec.eval_logits_fn(p, jnp.zeros((1, 28, 28, 1)))
    # log_softmax output: logsumexp == 0
    assert abs(float(jax.scipy.special.logsumexp(logits, axis=-1)[0])) < 1e-5


def test_cct2_param_count_parity():
    """cct_2_3x2_32 is ~284K params in the reference zoo."""
    spec = build_fns(create_model("cct_2_3x2_32"), (32, 32, 3))
    d = flat_dim(spec.init(jax.random.PRNGKey(0)))
    assert 270_000 < d < 300_000, d


def test_dropout_train_vs_eval():
    spec = build_fns(create_model("cct_2_3x2_32"), (32, 32, 3))
    p = spec.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 32, 32, 3))
    e1 = spec.eval_logits_fn(p, x)
    e2 = spec.eval_logits_fn(p, x)
    np.testing.assert_array_equal(e1, e2)  # eval deterministic
    y = jnp.zeros(4, jnp.int32)
    l1, _ = spec.train_loss_fn(p, x, y, jax.random.PRNGKey(3))
    l2, _ = spec.train_loss_fn(p, x, y, jax.random.PRNGKey(4))
    assert float(l1) != float(l2)  # train stochastic (dropout/droppath)


def test_registry_complete():
    for name in ["mlp", "cct", "cctnet", "resnet18", "wrn_28_10", "cvt_7_4_32"]:
        assert name in MODELS
    with pytest.raises(ValueError):
        create_model("nope")


def test_wrn_and_cvt_build():
    for name in ["cvt_7_4_32", "vit_lite_7_4_32"]:
        spec = build_fns(create_model(name), (32, 32, 3))
        p = spec.init(jax.random.PRNGKey(0))
        out = spec.eval_logits_fn(p, jnp.zeros((1, 32, 32, 3)))
        assert out.shape == (1, 10)


# -- text family (reference cctnets/text/, masked transformers) ---------------


def _text_spec(factory, seq_len=16, vocab=50, **kw):
    from blades_tpu.models import common

    module = factory(num_classes=2, seq_len=seq_len, vocab_size=vocab, **kw)
    return common.build_fns(module, (seq_len,), input_dtype=jnp.int32)


@pytest.mark.parametrize(
    "name",
    ["text_cct_2", "text_cvt_2", "text_vit_2", "text_transformer_2"],
)
def test_text_forward_backward(name):
    from blades_tpu.models import MODELS

    spec = _text_spec(MODELS[name])
    p = spec.init(jax.random.PRNGKey(0))
    x = jax.random.randint(jax.random.PRNGKey(1), (3, 16), 0, 50)
    logits = spec.eval_logits_fn(p, x)
    assert logits.shape == (3, 2)
    y = jnp.array([0, 1, 0])
    (loss, aux), g = jax.value_and_grad(
        lambda pp: spec.train_loss_fn(pp, x, y, jax.random.PRNGKey(2)),
        has_aux=True,
    )(p)
    assert np.isfinite(float(loss))
    gnorm = sum(float(jnp.abs(l).sum()) for l in jax.tree_util.tree_leaves(g))
    assert gnorm > 0


@pytest.mark.parametrize("name", ["text_cct_2", "text_vit_2", "text_transformer_2"])
def test_text_mask_invariance(name):
    """Padded positions must not influence the logits when masked."""
    from blades_tpu.models import MODELS

    module = MODELS[name](num_classes=2, seq_len=12, vocab_size=40)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 12), 2, 40)
    mask = jnp.arange(12)[None, :] < jnp.array([[7], [12]])  # first row padded
    p = module.init(
        {"params": jax.random.PRNGKey(0)}, tokens, mask=mask, train=False
    )["params"]
    out1 = module.apply({"params": p}, tokens, mask=mask, train=False)
    # scramble the padded region; masked output must be identical
    garbage = jnp.where(mask, tokens, (tokens * 7 + 3) % 40)
    out2 = module.apply({"params": p}, garbage, mask=mask, train=False)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=1e-5)


def test_text_tokenizer_mask_matches_torch():
    """Mask propagation == torch conv1d(ones)/maxpool1d thresholding
    (reference tokenizer.py:78-95), cross-checked against torch directly."""
    import torch
    import torch.nn.functional as F

    from blades_tpu.models.text import TextTokenizer

    tok = TextTokenizer(kernel_size=4, stride=1, padding=2,
                        n_output_channels=8, max_pool=True)
    mask = np.zeros((3, 17), bool)
    mask[0, :5] = True
    mask[1, 3:11] = True
    mask[2, :] = True
    ours = tok._forward_mask(jnp.asarray(mask))

    m = torch.tensor(mask, dtype=torch.float32).unsqueeze(1)
    w = torch.ones((1, 1, 4))
    ref = F.conv1d(m, w, None, 1, 2, 1, 1)
    ref = F.max_pool1d(ref, 3, 2, 1, 1, False, False)
    ref = (ref.squeeze(1) > 0).numpy()
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_text_seq_len_formula():
    from blades_tpu.models.text import TextTokenizer

    for k, s, pd, mp in [(4, 1, 2, True), (4, 4, 0, False), (2, 1, 1, True)]:
        tok = TextTokenizer(kernel_size=k, stride=s, padding=pd,
                            n_output_channels=4, max_pool=mp)
        x = jnp.zeros((1, 64, 30))
        out, _ = tok.init_with_output(jax.random.PRNGKey(0), x)
        assert out[0].shape[1] == tok.seq_len(64), (k, s, pd, mp)


def test_bf16_mixed_precision_close_to_fp32():
    """compute_dtype=bfloat16: fp32 master params, bf16 forward/backward;
    loss and grads must stay finite, fp32-typed, and close to the fp32 path."""
    from blades_tpu.models import create_model

    f32 = build_fns(create_model("cct_2_3x2_32"), (32, 32, 3))
    b16 = build_fns(create_model("cct_2_3x2_32"), (32, 32, 3),
                    compute_dtype=jnp.bfloat16)
    p = f32.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    y = jnp.array([0, 1, 2, 3])

    def grad_of(spec):
        (l, _), g = jax.value_and_grad(
            lambda pp: spec.train_loss_fn(pp, x, y, jax.random.PRNGKey(2)),
            has_aux=True,
        )(p)
        return float(l), g

    l32, g32 = grad_of(f32)
    l16, g16 = grad_of(b16)
    assert abs(l32 - l16) / max(abs(l32), 1e-6) < 0.05
    leaves16 = jax.tree_util.tree_leaves(g16)
    assert all(l.dtype == jnp.float32 for l in leaves16)
    n32 = float(jnp.sqrt(sum(jnp.sum(a**2) for a in jax.tree_util.tree_leaves(g32))))
    n16 = float(jnp.sqrt(sum(jnp.sum(a**2) for a in leaves16)))
    assert abs(n32 - n16) / max(n32, 1e-6) < 0.15
