"""Dataset layer tests: partition semantics, round sampling, augmentation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.datasets import (
    CustomTensorDataset,
    FLDataset,
    Synthetic,
    partition_dirichlet,
    partition_iid,
)
from blades_tpu.datasets.augment import (
    cifar_train_transform,
    make_normalizer,
    random_crop,
    random_hflip,
)


def test_partition_iid_balanced():
    x = np.arange(100).reshape(100, 1)
    y = np.arange(100) % 10
    xs, ys = partition_iid(x, y, num_clients=10, seed=0)
    assert len(xs) == 10
    assert all(len(a) == 10 for a in xs)
    # all samples present exactly once
    assert sorted(np.concatenate(xs).ravel().tolist()) == list(range(100))


def test_partition_dirichlet_skew_and_coverage():
    rng = np.random.RandomState(0)
    x = rng.randn(1000, 3)
    y = rng.randint(0, 10, 1000)
    xs, ys = partition_dirichlet(x, y, num_clients=20, alpha=0.1, seed=0)
    sizes = np.array([len(a) for a in xs])
    assert sizes.sum() == 1000
    assert sizes.min() >= 1
    # alpha=0.1 must be visibly non-IID: client class histograms skewed
    hists = np.stack(
        [np.bincount(b, minlength=10) / max(len(b), 1) for b in ys]
    )
    assert hists.max(axis=1).mean() > 0.35  # IID would be ~0.1


def test_fldataset_sampling_without_replacement():
    k, n = 4, 12
    train_x = np.tile(np.arange(n, dtype=np.float32)[None, :, None], (k, 1, 1))
    train_y = np.tile(np.arange(n, dtype=np.int32)[None], (k, 1))
    ds = FLDataset(train_x, train_y, np.full(k, n), train_x[0], train_y[0])
    # one epoch's worth: every sample exactly once per client
    cx, cy = ds.sample_round(jax.random.PRNGKey(0), local_steps=3, batch_size=4)
    assert cx.shape == (k, 3, 4, 1)
    for c in range(k):
        seen = sorted(np.asarray(cy[c]).ravel().tolist())
        assert seen == list(range(n))


def test_fldataset_wraparound_past_epoch():
    k, n = 2, 3
    train_x = np.zeros((k, n, 1), np.float32)
    train_y = np.tile(np.arange(n, dtype=np.int32)[None], (k, 1))
    ds = FLDataset(train_x, train_y, np.full(k, n), train_x[0], train_y[0])
    _, cy = ds.sample_round(jax.random.PRNGKey(0), local_steps=2, batch_size=3)
    for c in range(k):
        flat = np.asarray(cy[c]).ravel()
        # 6 draws over 3 samples -> each appears exactly twice (wraparound)
        assert sorted(np.bincount(flat, minlength=n).tolist()) == [2, 2, 2]


def test_fldataset_padding_never_sampled():
    k = 2
    train_x = np.zeros((k, 10, 1), np.float32)
    train_y = np.full((k, 10), -1, np.int32)
    train_y[:, :4] = np.arange(4)
    ds = FLDataset(train_x, train_y, np.array([4, 4]), train_x[0], train_y[0])
    _, cy = ds.sample_round(jax.random.PRNGKey(3), local_steps=5, batch_size=2)
    assert int(cy.min()) >= 0  # -1 padding rows never drawn


def test_sampling_deterministic_in_key():
    ds = Synthetic(num_clients=4, train_size=64, cache=False).get_dls()
    a = ds.sample_round(jax.random.PRNGKey(5), 2, 4)
    b = ds.sample_round(jax.random.PRNGKey(5), 2, 4)
    np.testing.assert_array_equal(a[0], b[0])
    c = ds.sample_round(jax.random.PRNGKey(6), 2, 4)
    assert not np.array_equal(a[1], c[1])


def test_synthetic_learnable_signal():
    ds = Synthetic(num_clients=2, train_size=200, noise=0.1, cache=False).get_dls()
    assert ds.train_x.shape[2:] == (28, 28, 1)
    assert int(ds.test_y.max()) <= 9


def test_custom_tensor_dataset():
    x = np.random.randn(60, 4).astype(np.float32)
    y = (np.arange(60) % 3).astype(np.int32)
    ds = CustomTensorDataset(x, y, num_clients=6, iid=True)
    fl = ds.get_dls()
    assert fl.num_clients == 6
    assert ds.num_classes == 3


def test_augment_shapes_and_normalize():
    key = jax.random.PRNGKey(0)
    img = jnp.asarray(np.random.randint(0, 256, (32, 32, 3), np.uint8))
    out = cifar_train_transform(key, img)
    assert out.shape == (32, 32, 3)
    norm = make_normalizer((0.5, 0.5, 0.5), (0.5, 0.5, 0.5))
    z = norm(img)
    assert z.dtype == jnp.float32
    assert abs(float(z.mean())) < 0.2  # roughly centered


def test_hflip_is_flip():
    img = jnp.arange(12.0).reshape(2, 2, 3)
    flipped = random_hflip(jax.random.PRNGKey(0), img, p=1.0)
    np.testing.assert_array_equal(flipped, img[:, ::-1, :])


def test_get_train_data_parity_api():
    from blades_tpu.datasets import Synthetic

    fl = Synthetic(num_clients=4, train_size=200, test_size=40, cache=False).get_dls()
    batches = fl.get_train_data(fl.get_clients()[1], num_batches=3, batch_size=8)
    assert len(batches) == 3
    x, y = batches[0]
    assert x.shape[0] == 8 and y.shape == (8,)
    # per-client test shard (reference keeps one test set per client);
    # union recoverable with u_id=None
    tx, ty = fl.get_all_test_data(0)
    assert tx.shape[0] == ty.shape[0] == 10
    tx, ty = fl.get_all_test_data(None)
    assert tx.shape[0] == ty.shape[0] == 40


def test_get_train_data_without_replacement_epochs():
    """The epoch stream covers every sample exactly once before reshuffling
    (reference generator semantics, ``basedataset.py:58-86``)."""
    from blades_tpu.datasets.fl import FLDataset

    n = 20
    xs = [np.arange(n, dtype=np.float32).reshape(n, 1)]
    ys = [np.arange(n, dtype=np.int32)]
    fl = FLDataset.from_client_arrays(xs, ys, xs[0][:4], ys[0][:4])
    # one epoch = ceil(20/8) = 3 batches, last partial (len 4)
    batches = fl.get_train_data(0, num_batches=3, batch_size=8)
    seen = np.concatenate([np.asarray(y) for _, y in batches])
    assert len(batches[2][1]) == 4
    assert sorted(seen.tolist()) == list(range(n))  # without replacement
    # next epoch: again a full cover, (almost surely) different order
    batches2 = fl.get_train_data(0, num_batches=3, batch_size=8)
    seen2 = np.concatenate([np.asarray(y) for _, y in batches2])
    assert sorted(seen2.tolist()) == list(range(n))


def test_per_client_test_shards_non_even():
    """client_validation shard metrics must come from each client's REAL
    test shard, including under a non-even split."""
    from blades_tpu.datasets.fl import FLDataset

    xs = [np.ones((5, 2), np.float32) * i for i in range(3)]
    ys = [np.full(5, i, np.int32) for i in range(3)]
    test_xs = [np.ones((j + 1, 2), np.float32) * 10 * j for j in range(3)]
    test_ys = [np.full(j + 1, j, np.int32) for j in range(3)]
    fl = FLDataset.from_client_arrays(xs, ys, test_xs, test_ys)
    assert fl.test_counts.tolist() == [1, 2, 3]
    slices = fl.client_test_slices()
    assert [len(s) for s in slices] == [1, 2, 3]
    for j in range(3):
        tx, ty = fl.get_all_test_data(j)
        np.testing.assert_array_equal(np.asarray(ty), test_ys[j])
        np.testing.assert_array_equal(np.asarray(tx), test_xs[j])


def test_set_random_seed_returns_key():
    from blades_tpu.utils.rng import set_random_seed
    import numpy as np

    k = set_random_seed(7)
    a = np.random.rand(3)
    set_random_seed(7)
    b = np.random.rand(3)
    np.testing.assert_array_equal(a, b)
    import jax
    import jax.numpy as jnp

    # a valid PRNG key: either new-style typed key or legacy uint32[2]
    is_typed = jnp.issubdtype(k.dtype, jax.dtypes.prng_key)
    assert is_typed or (k.shape == (2,) and k.dtype == jnp.uint32)


def test_fldataset_place_shards_over_clients():
    """place() lays the client store and sampler outputs out over the
    clients mesh axis — no per-round resharding at the jit boundary."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from blades_tpu.datasets.fl import FLDataset

    k, n = 8, 12
    xs = [np.random.rand(n, 4, 4, 1).astype(np.float32) for _ in range(k)]
    ys = [np.random.randint(0, 3, n).astype(np.int32) for _ in range(k)]
    fl = FLDataset.from_client_arrays(xs, ys, xs[0][:2], ys[0][:2])
    mesh = Mesh(np.array(jax.devices()).reshape(8, 1), ("clients", "model"))
    sharding = NamedSharding(mesh, P("clients"))
    fl.place(sharding)
    assert fl.train_x.sharding.is_equivalent_to(sharding, fl.train_x.ndim)
    cx, cy = fl.sample_round(jax.random.PRNGKey(0), 2, 4)
    assert cx.shape == (k, 2, 4, 4, 4, 1)
    assert cx.sharding.is_equivalent_to(sharding, cx.ndim)
    assert cy.sharding.is_equivalent_to(sharding, cy.ndim)
