"""Defense certification & breakdown audit tests (``blades_tpu/audit``).

Pins the contracts the subsystem is built on:

1. **Registry lint** — every registered aggregator passes the contract
   battery (permutation / translation / empirical resilience) or carries
   an explicit, documented opt-out (``Aggregator.audit_optouts``) — a new
   defense cannot silently skip certification;
2. **Breakdown matrix semantics** — the adaptive attack search finds
   mean's breakdown at any f >= 1 while median/krum certify at nominal f
   (the committed evidence: ``results/certification/cert_matrix.json``),
   and ``scripts/certify.py`` honors the one-JSON-line contract;
3. **Runtime audit** — certificates + fallback live inside the SAME
   jitted round program (zero extra compiles after round 1, pinned via
   the compile-counter telemetry), compose with the fault layer's masks
   (excluded NaN rows are inert to the certificates), and a
   breach->fallback round is bit-reproducible under a fixed seed,
   including across kill/resume.

The reference has no counterpart for any of this — it neither measures
nor reacts to defense breakdown (``src/blades/simulator.py:244``).
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu import AuditMonitor, Simulator
from blades_tpu.aggregators import AGGREGATORS, get_aggregator
from blades_tpu.audit import (
    CONTRACTS,
    DEFAULT_C,
    QUICK_GRIDS,
    battery_ctx,
    battery_kwargs,
    nominal_f,
    run_battery,
    search_cell,
    synthetic_honest,
)
from blades_tpu.datasets import Synthetic
from blades_tpu.ops.pytree import ravel

K, D = 8, 16

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_agg(name):
    f = max(1, nominal_f(name, K))
    return get_aggregator(name, **battery_kwargs(name, K, f)), f


# ------------------------------------------------------------ registry lint


@pytest.mark.parametrize("name", sorted(AGGREGATORS))
def test_registered_aggregator_passes_battery_or_opts_out(name):
    """Tier-1 certification lint: each contract either PASSES or is covered
    by an explicit, documented opt-out on the class."""
    agg, f = _lint_agg(name)
    results = run_battery(agg, k=K, d=D, f=f, name=name)
    optouts = getattr(type(agg), "audit_optouts", {}) or {}
    for contract, res in results.items():
        if res["ok"]:
            continue
        assert contract in optouts, (
            f"{name} FAILS the {contract} contract "
            f"(measured {res.get('residual', res.get('worst_ratio'))}) "
            "without an audit_optouts entry — declare a documented opt-out "
            "or fix the defense (docs/robustness.md, Certification)"
        )


@pytest.mark.parametrize("name", sorted(AGGREGATORS))
def test_audit_optouts_are_documented_and_valid(name):
    """Opt-outs name real contracts and carry a real reason (not a
    placeholder) — the 'documented' half of the lint."""
    optouts = getattr(AGGREGATORS[name], "audit_optouts", {}) or {}
    for contract, reason in optouts.items():
        assert contract in CONTRACTS, (
            f"{name}: unknown contract {contract!r} in audit_optouts"
        )
        assert isinstance(reason, str) and len(reason.strip()) >= 20, (
            f"{name}: opt-out for {contract!r} needs a documented reason"
        )


def test_base_aggregator_has_no_optouts():
    from blades_tpu.aggregators.base import Aggregator

    assert Aggregator.audit_optouts == {}


# ----------------------------------------------------- breakdown semantics


def test_mean_breaks_at_f1_median_certifies_at_nominal():
    """The acceptance pair: the adaptive search drags mean far outside the
    resilience bound at f=1 while median stays certified at its nominal
    f — the same verdicts the committed cert matrix records."""
    trials = synthetic_honest(jax.random.PRNGKey(0), 1, K, D)
    ctx = battery_ctx(None, K, D)
    mean_cell = search_cell(get_aggregator("mean"), trials, 1,
                            ctx=ctx, grids=QUICK_GRIDS)
    # far past the bound even on the reduced lint grids (eps <= 100); the
    # committed matrix's full grids push it past 300x
    assert mean_cell["worst_ratio"] > DEFAULT_C * 3
    med_cell = search_cell(get_aggregator("median"), trials,
                           nominal_f("median", K), ctx=ctx, grids=QUICK_GRIDS)
    assert med_cell["worst_ratio"] <= DEFAULT_C


def test_search_cell_accepts_single_trial_matrix():
    u = synthetic_honest(jax.random.PRNGKey(1), 1, K, D)[0]
    cell = search_cell(get_aggregator("median"), u, 2, grids=QUICK_GRIDS)
    assert set(cell["templates"]) == {"ipm", "alie", "signflip",
                                      "minmax", "minsum"}
    assert np.isfinite(cell["worst_ratio"])


def test_committed_cert_matrix_matches_acceptance():
    """The committed evidence artifact carries the full pool x f grid with
    >= 3 templates per cell, mean broken for every f >= 1, and
    median/krum/centeredclipping certified through their nominal f."""
    path = os.path.join(REPO, "results", "certification", "cert_matrix.json")
    m = json.load(open(path))
    assert m["ok"] is True
    assert m["templates_per_cell"] >= 3
    by = {(c["agg"], c["f"]): c for c in m["cells"]}
    f_max = m["f_max"]
    assert f_max == (m["clients"] - 1) // 2
    for f in range(1, f_max + 1):
        assert not by[("mean", f)]["certified"]
    for name in ("median", "krum", "centeredclipping"):
        for f in range(nominal_f(name, m["clients"]) + 1):
            assert by[(name, f)]["certified"], f"{name} must certify at f={f}"
    # every pooled aggregator is present at every f
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "chaos_for_audit", os.path.join(REPO, "scripts", "chaos.py"))
    chaos = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(chaos)
    for agg in chaos.AGG_POOL:
        for f in range(f_max + 1):
            assert (agg, f) in by, f"cert matrix missing cell ({agg}, {f})"


def test_certify_script_one_json_line(tmp_path, capsys, monkeypatch):
    """scripts/certify.py stdout is EXACTLY one parseable JSON line (the
    bench.py discipline) — both on success and on an internal error."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "certify_under_test", os.path.join(REPO, "scripts", "certify.py"))
    certify = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(certify)

    monkeypatch.setattr(sys, "argv", [
        "certify.py", "--quick", "--aggs", "mean", "median",
        "--clients", "6", "--dim", "8", "--trials", "1",
        "--out", str(tmp_path / "cert"),
    ])
    rc = certify.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, f"expected one JSON line, got {out}"
    payload = json.loads(out[0])
    assert rc == 0 and payload["ok"] is True
    assert payload["metric"] == "defense_certification"
    matrix = json.load(open(tmp_path / "cert" / "cert_matrix.json"))
    assert {c["agg"] for c in matrix["cells"]} == {"mean", "median"}

    # error path: still one JSON line, rc != 0
    monkeypatch.setattr(sys, "argv", [
        "certify.py", "--aggs", "nosuchaggregator",
        "--out", str(tmp_path / "cert2"),
    ])
    rc = certify.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1 and rc == 1
    err = json.loads(out[0])
    assert err["ok"] is False and "error" in err


# ----------------------------------------------------------- monitor units


def _benign(seed=0):
    return synthetic_honest(jax.random.PRNGKey(seed), 1, K, D)[0]


def test_monitor_no_breach_on_benign_mean():
    u = _benign()
    agg = jnp.mean(u, axis=0)
    breach, diag = AuditMonitor().certify(u, agg)
    assert not bool(breach)
    assert int(diag["cert_median_ball"]) == 1
    assert int(diag["cert_envelope"]) == 1


def test_monitor_breach_on_dragged_aggregate():
    u = _benign()
    dragged = jnp.mean(u, axis=0) + 100.0
    breach, diag = AuditMonitor().certify(u, dragged)
    assert bool(breach)
    assert int(diag["cert_median_ball"]) == 0


def test_monitor_masked_nan_rows_inert():
    """Guard-excluded NaN rows are zeroed before certificate arithmetic:
    the verdicts match the excluded-zeros run bit-exactly and stay finite
    (the audit extension of the masked-row inertness contract)."""
    u = np.asarray(_benign())
    mask = jnp.asarray([True] * 6 + [False] * 2)
    poisoned = u.copy()
    poisoned[6:] = np.nan
    agg = jnp.mean(jnp.asarray(u[:6]), axis=0)
    mon = AuditMonitor(fallback_aggregator="median")
    f1, d1 = mon.apply(jnp.asarray(u), agg, mask=mask,
                       byz_mask=jnp.zeros(K, bool))
    f2, d2 = mon.apply(jnp.asarray(poisoned), agg, mask=mask,
                       byz_mask=jnp.zeros(K, bool))
    np.testing.assert_array_equal(np.asarray(f1), np.asarray(f2))
    for k_ in d1:
        np.testing.assert_array_equal(np.asarray(d1[k_]), np.asarray(d2[k_]))
        assert np.isfinite(np.asarray(d2[k_], dtype=np.float64)).all()


def test_monitor_fallback_swap_and_zero_participants():
    u = _benign()
    dragged = jnp.mean(u, axis=0) + 100.0
    mon = AuditMonitor(fallback_aggregator="median")
    final, diag = mon.apply(u, dragged)
    assert int(diag["breach"]) == 1 and int(diag["fallback_used"]) == 1
    np.testing.assert_allclose(
        np.asarray(final), np.median(np.asarray(u), axis=0), rtol=1e-6
    )
    # zero participants: never a breach (nothing to certify against)
    final0, diag0 = mon.apply(u, jnp.zeros(D), mask=jnp.zeros(K, bool))
    assert int(diag0["breach"]) == 0 and int(diag0["fallback_used"]) == 0
    np.testing.assert_array_equal(np.asarray(final0), np.zeros(D))


def test_monitor_certify_jittable():
    mon = AuditMonitor(fallback_aggregator="median")

    @jax.jit
    def run(u, agg, mask):
        return mon.apply(u, agg, mask=mask, byz_mask=jnp.zeros(K, bool))

    u = _benign()
    final, diag = run(u, jnp.mean(u, axis=0), jnp.ones(K, bool))
    assert np.isfinite(np.asarray(final)).all()
    assert int(diag["breach"]) == 0


def test_monitor_validation():
    with pytest.raises(ValueError, match="stateful"):
        AuditMonitor(fallback_aggregator="centeredclipping")
    with pytest.raises(ValueError, match="certificate"):
        AuditMonitor(certificates=("frobnicate",))
    with pytest.raises(ValueError, match="certificate"):
        AuditMonitor(certificates=())
    mon = AuditMonitor(fallback_aggregator="median")
    assert "fallback" in repr(mon)


# -------------------------------------------------------- engine/simulator


def _sim(tmp_path, sub, seed=3, **kws):
    return Simulator(
        dataset=Synthetic(num_clients=K, train_size=400, test_size=80,
                          noise=0.3, cache=False),
        log_path=str(tmp_path / sub), seed=seed,
        aggregator="mean", attack="ipm", attack_kws={"epsilon": 50.0},
        num_byzantine=2, **kws,
    )


AUDIT_KW = dict(audit_monitor=dict(fallback_aggregator="median"))
RUN_KW = dict(local_steps=1, train_batch_size=8, client_lr=0.2,
              server_lr=1.0, validate_interval=100)


def test_audit_records_fallback_and_zero_extra_compiles(tmp_path):
    """The acceptance round: mean + strong IPM + median fallback. Every
    round records an audit entry, breach == fallback_used, the applied
    deviation improves on the raw one, and — the zero-extra-compiles pin —
    the round program compiled to EXACTLY ONE executable (certificates and
    fallback live inside it; a separate audit program would be a second
    jit cache entry) and the compile-counter telemetry shows no compiles
    from round 3 on (round 2 may legitimately re-specialize once when the
    mesh re-lays-out the round-1 outputs — the pre-audit runs do the same;
    a breach-flag-dependent recompile would fire EVERY breached round and
    trip this)."""
    sim = _sim(tmp_path, "audited")
    rounds = 4
    sim.run("mlp", global_rounds=rounds, **RUN_KW, **AUDIT_KW)
    cache_size = getattr(sim.engine._round_jit, "_cache_size", None)
    if cache_size is not None:
        assert cache_size() <= 2  # 1 + the one-time mesh re-layout entry

    trace = os.path.join(str(tmp_path / "audited"), "telemetry.jsonl")
    recs = [json.loads(l) for l in open(trace)]
    audits = [r for r in recs if r.get("t") == "audit"]
    assert len(audits) == rounds
    for r in audits:
        assert r["breach"] == 1 and r["fallback_used"] == 1
        assert r["cert_median_ball"] == 0  # IPM drags mean out of the ball
        assert np.isfinite(r["dev_honest"])
        assert r["dev_honest"] < r["dev_honest_raw"]  # fallback helped
    meta = recs[0]
    assert meta["t"] == "meta" and "AuditMonitor" in meta.get(
        "audit_monitor", "")
    # gauges mirrored onto round records; breaches counted
    round_recs = [r for r in recs if r.get("t") == "round"]
    assert round_recs and all(
        r["gauges"].get("audit.breach") == 1 for r in round_recs
    )
    # ZERO extra compiles: from round 3 on (breach -> fallback every
    # round) no xla compile lands in any round's counter delta. A
    # per-breach recompile or a separate audit program would show up here.
    for r in round_recs[2:]:
        assert r["counters"].get("xla.compiles", 0) == 0, (
            f"round {r['round']} recompiled the round program under audit"
        )


def test_breach_fallback_bit_reproducible_incl_resume(tmp_path):
    """Acceptance: a breach->fallback round is bit-reproducible under a
    fixed seed — rerun AND kill/resume reproduce the uninterrupted final
    params exactly, composing with the fault layer's masks."""
    fault = dict(dropout_rate=0.3)
    kw = dict(global_rounds=4, fault_model=fault, **RUN_KW, **AUDIT_KW)

    a = _sim(tmp_path, "a")
    a.run("mlp", **kw)
    ref = np.asarray(ravel(a.server.state.params))
    trace = os.path.join(str(tmp_path / "a"), "telemetry.jsonl")
    audits = [json.loads(l) for l in open(trace)
              if json.loads(l).get("t") == "audit"]
    assert any(r["fallback_used"] for r in audits), "no breach to reproduce"

    b = _sim(tmp_path, "b")
    b.run("mlp", **kw)
    np.testing.assert_array_equal(
        ref, np.asarray(ravel(b.server.state.params))
    )

    def boom(rnd, state, m):
        if rnd == 2:
            raise RuntimeError("simulated kill")

    c = _sim(tmp_path, "c")
    with pytest.raises(RuntimeError, match="simulated kill"):
        c.run("mlp", **kw, on_round_end=boom)
    assert os.path.exists(os.path.join(str(tmp_path / "c"), "autosave.npz"))
    d = _sim(tmp_path, "c")  # same log dir -> same autosave
    times = d.run("mlp", **kw, resume=True)
    assert len(times) == 2  # only rounds 3..4 re-ran
    np.testing.assert_array_equal(
        ref, np.asarray(ravel(d.server.state.params))
    )


def test_no_audit_monitor_unchanged(tmp_path):
    """Without a monitor: no audit records, last_audit_diag None — the
    pre-audit program."""
    sim = _sim(tmp_path, "noaudit")
    sim.run("mlp", global_rounds=1, **RUN_KW)
    assert sim.engine.last_audit_diag is None
    trace = os.path.join(str(tmp_path / "noaudit"), "telemetry.jsonl")
    recs = [json.loads(l) for l in open(trace)]
    assert not any(r.get("t") == "audit" for r in recs)


# ----------------------------------------------------------- trace summary


def test_trace_summary_audit_section():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "trace_summary_audit", os.path.join(REPO, "scripts",
                                            "trace_summary.py"))
    ts = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ts)

    records = [
        {"t": "meta", "aggregator": "mean"},
        {"t": "audit", "round": 1, "breach": 1, "fallback_used": 1,
         "dev_honest": 0.2, "max_honest_dev": 0.4, "honest_participants": 6},
        {"t": "audit", "round": 2, "breach": 0, "fallback_used": 0,
         "dev_honest": 0.1, "max_honest_dev": 0.4, "honest_participants": 6},
        # degenerate round (1 honest participant, zero spread): must be
        # skipped from the ratio, not divided by epsilon into ~1e8
        {"t": "audit", "round": 3, "breach": 0, "fallback_used": 0,
         "dev_honest": 0.3, "max_honest_dev": 0.0, "honest_participants": 1},
        {"t": "round", "round": 1, "wall_s": 0.1},
    ]
    s = ts.summarize(records)
    aud = s["audit"]
    assert aud["rounds_audited"] == 3
    assert aud["breaches"] == 1 and aud["fallback_rounds"] == 1
    assert aud["max_dev_ratio"] == pytest.approx(0.5)
    table = ts.format_table(s)
    assert "audit:" in table
