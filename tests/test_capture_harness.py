"""Tests for the resumable TPU evidence capture (scripts/tpu_capture.py).

The capture harness is load-bearing for the round's perf evidence: it must
accumulate artifacts across sub-minute tunnel windows without burning
attempts on transient failures, settling CPU fallbacks as TPU evidence,
or livelocking the watcher. These tests drive the real module with a
stubbed ``run()`` (no subprocesses, no jax import) — pure stdlib, fast.

Reference counterpart: none (the reference has no hardware-evidence
harness; its perf story is qualitative, README.rst:37-42).
"""
import importlib.util
import json
import os
import subprocess
import time

import pytest

SCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts", "tpu_capture.py",
)

GOOD_CHILD = (
    'BENCH_CHILD_RESULT {"rounds_per_sec": 9.9, "platform": "tpu"}'
)


@pytest.fixture
def cap(tmp_path):
    spec = importlib.util.spec_from_file_location("cap_under_test", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.OUT = str(tmp_path)
    mod.ROWS = str(tmp_path / "rows.jsonl")
    mod.PROBES = str(tmp_path / "tunnel_probes.jsonl")  # not the repo's
    mod.HEAD_FAILS = str(tmp_path / "headline_attempts.jsonl")
    mod.STAGES_PATH = str(tmp_path / "stages.json")
    mod.STAGE_FAILS = str(tmp_path / "stages_attempts.jsonl")
    mod.REPO = str(tmp_path)
    (tmp_path / "results").mkdir()
    return mod


def good_run(cmd, timeout, env=None):
    if "-c" in cmd:
        return 0, "ALIVE tpu", ""
    if cmd[-1].endswith("bench.py") and (env or {}).get("BENCH_CHILD") != 1:
        return 0, json.dumps({"value": 1.3, "platform": "tpu"}), ""
    if cmd[-1].endswith("stage_timing.py"):
        return 0, 'STAGES {"sampler_s": 1.0, "platform": "tpu"}', ""
    return 0, GOOD_CHILD, ""


def write_rows(cap, rows):
    with open(cap.ROWS, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def run_main(cap):
    try:
        cap.main()
        return 0
    except SystemExit as e:
        return e.code


def test_happy_path_completes_first_window(cap, tmp_path):
    cap.run = good_run
    assert run_main(cap) == 0
    headline = json.load(open(tmp_path / "headline.json"))
    assert headline["value"] == 1.3
    # bench_tpu.json (the prior-capture carry) is refreshed
    assert json.load(open(tmp_path / "results" / "bench_tpu.json"))[
        "value"
    ] == 1.3
    settled, attempted = cap.scan_rows()
    assert attempted and set(settled) == attempted


def test_resume_skips_settled_rows(cap):
    cap.run = good_run
    run_main(cap)
    calls = []

    def count_run(cmd, timeout, env=None):
        calls.append(cmd)
        return good_run(cmd, timeout, env)

    cap.run = count_run
    cap._DONE = None
    assert run_main(cap) == 0
    # second window: everything settled, zero bench children spawned
    assert not any(c[-1].endswith("bench.py") for c in calls)


def test_tunnel_death_excluded_from_cap(cap):
    state = {"alive": True}

    def dies_mid(cmd, timeout, env=None):
        if "-c" in cmd:
            return (0, "ALIVE tpu", "") if state["alive"] else (1, "", "")
        if cmd[-1].endswith("bench.py") and (env or {}).get(
            "BENCH_CHILD"
        ) != 1:
            return 0, json.dumps({"value": 1.3, "platform": "tpu"}), ""
        state["alive"] = False
        return 1, "", "backend went away"

    cap.run = dies_mid
    assert run_main(cap) == 2
    rows = [json.loads(line) for line in open(cap.ROWS)]
    assert rows[0]["tunnel_died"] is True
    settled, attempted = cap.scan_rows()
    assert not settled and attempted  # retried, not capped


def test_transient_errors_retried_without_cap(cap):
    write_rows(cap, [
        {"name": "t", "error": "preflight: timeout after 1500s"}
        for _ in range(10)
    ])
    settled, attempted = cap.scan_rows()
    assert "t" in attempted and "t" not in settled


def test_deterministic_errors_capped(cap):
    write_rows(cap, [
        {"name": "d", "error": "build: KeyError: bogus"}
        for _ in range(cap.MAX_ATTEMPTS)
    ])
    settled, _ = cap.scan_rows()
    assert settled["d"]["gave_up"] is True


def test_oom_settles_first_attempt_even_via_partial_output(cap):
    cap.tunnel_alive = lambda timeout=90: True

    def oom_then_hang(cmd, timeout, env=None):
        return None, "RESOURCE_EXHAUSTED: Out of memory\n<dump>", "x"

    cap.run = oom_then_hang
    row = cap.child_row("big_k")
    assert row["oom"] is True
    settled, _ = cap.scan_rows()
    assert "big_k" in settled


def test_cpu_fallback_never_settles_as_evidence(cap):
    write_rows(cap, [
        {"name": "x", "rounds_per_sec": 5.0, "platform": "cpu"}
        for _ in range(cap.MAX_ATTEMPTS)
    ])
    settled, _ = cap.scan_rows()
    assert settled["x"].get("gave_up") is True
    assert not cap.measured(settled["x"])


def test_headline_cap_and_cpu_rejection(cap, tmp_path):
    # a cpu headline.json is never "done"
    with open(tmp_path / "headline.json", "w") as f:
        json.dump({"value": 0.016, "platform": "cpu"}, f)
    assert not cap._headline_done()
    # ... until MAX_ATTEMPTS deterministic failures are recorded
    with open(cap.HEAD_FAILS, "w") as f:
        for _ in range(cap.MAX_ATTEMPTS):
            f.write('{"error": "deterministic"}\n')
    assert cap._headline_done()


def test_deterministic_headline_failure_still_collects_sections(cap):
    def headline_fails(cmd, timeout, env=None):
        if "-c" in cmd:
            return 0, "ALIVE tpu", ""
        if cmd[-1].endswith("bench.py") and (env or {}).get(
            "BENCH_CHILD"
        ) != 1:
            return 0, json.dumps(
                {"value": None, "platform": "cpu", "error": "stage: boom"}
            ), ""
        if cmd[-1].endswith("stage_timing.py"):
            return 0, 'STAGES {"sampler_s": 1.0, "platform": "tpu"}', ""
        return 0, GOOD_CHILD, ""

    cap.run = headline_fails
    assert run_main(cap) == 2  # headline pending
    # sections 2-4 all ran despite the headline failure
    settled, attempted = cap.scan_rows()
    assert len(settled) > 10
    assert cap._stages_done()
    assert cap._headline_attempts() == 1


def test_transient_headline_failure_not_counted(cap):
    def headline_transient(cmd, timeout, env=None):
        if "-c" in cmd:
            return 0, "ALIVE tpu", ""
        if cmd[-1].endswith("bench.py") and (env or {}).get(
            "BENCH_CHILD"
        ) != 1:
            return 0, json.dumps(
                {"value": None, "platform": "cpu",
                 "error": "probe: timeout after 240s"}
            ), ""
        if cmd[-1].endswith("stage_timing.py"):
            return 0, 'STAGES {"sampler_s": 1.0, "platform": "tpu"}', ""
        return 0, GOOD_CHILD, ""

    cap.run = headline_transient
    assert run_main(cap) == 2
    assert cap._headline_attempts() == 0


def test_truncated_result_line_survives(cap):
    cap.tunnel_alive = lambda timeout=90: True

    def trunc(cmd, timeout, env=None):
        return None, 'BENCH_CHILD_RESULT {"rounds_per_sec": 9.', \
            "\ntimeout after 1500s"

    cap.run = trunc
    row = cap.child_row("x")
    assert "error" in row and "rounds_per_sec" not in row


def test_first_probe_trusted_under_env(cap, monkeypatch):
    probes = []
    cap.tunnel_alive = lambda timeout=90: (probes.append(1), True)[1]
    monkeypatch.setenv("TUNNEL_PROBED", "1")
    cap.require_tunnel()
    assert probes == []
    cap._last_alive = 0.0  # expire the cache so the next call must probe
    cap.require_tunnel()
    assert probes == [1]


def test_ladder_does_not_descend_on_cpu_number(cap):
    assert not cap.measured({"rounds_per_sec": 5.0, "platform": "cpu"})
    assert cap.measured({"rounds_per_sec": 5.0, "platform": "tpu"})
    assert cap.measured({"rounds_per_sec": 5.0, "platform": "axon"})


def test_config_tagged_settle_never_settles_headline(cap, tmp_path):
    """ADVICE medium #2: a reduced-K ladder settle (bench tags it with
    `config`) must not persist as headline.json/bench_tpu.json — it is
    kept as a labeled interim artifact, counted as a failed attempt, and
    the full-K headline stays pending for later windows."""
    # the predicate itself rejects config-tagged payloads
    assert not cap._on_tpu(
        {"value": 2.0, "platform": "tpu", "config": "tpu_k100"}
    )
    smoke_settle = json.dumps(
        {"value": 2.0, "platform": "tpu", "config": "tpu_k100",
         "attempt_errors": "full: timeout after 2400s"}
    )

    def reduced_headline(cmd, timeout, env=None):
        if "-c" in cmd:
            return 0, "ALIVE tpu", ""
        if cmd[-1].endswith("bench.py") and (env or {}).get(
            "BENCH_CHILD"
        ) != 1:
            return 0, smoke_settle, ""
        if cmd[-1].endswith("stage_timing.py"):
            return 0, 'STAGES {"sampler_s": 1.0, "platform": "tpu"}', ""
        return 0, GOOD_CHILD, ""

    cap.run = reduced_headline
    assert run_main(cap) == 2  # headline still pending
    assert not os.path.exists(tmp_path / "headline.json")
    assert not os.path.exists(tmp_path / "results" / "bench_tpu.json")
    interim = json.load(open(tmp_path / "headline_interim.json"))
    assert interim["interim"] is True and interim["config"] == "tpu_k100"
    # counted toward the give-up cap (a transient-marker attempt_errors
    # string must not exempt it: its full-K attempt already timed out)
    assert cap._headline_attempts() == 1
    assert not cap._headline_done()


def test_config_tagged_settle_counted_even_when_tunnel_dies(cap, tmp_path):
    """The reduced-K settle's full-K attempt already burned its ladder:
    it must consume an attempt BEFORE the tunnel post-probe, or a flap
    right after the settle would let every later window re-burn the
    ~40-min ladder forever."""
    state = {"probes": 0}

    def settle_then_tunnel_dies(cmd, timeout, env=None):
        if "-c" in cmd:
            state["probes"] += 1  # pre-flight alive, dead after the settle
            return (0, "ALIVE tpu", "") if state["probes"] == 1 else (1, "", "")
        return 0, json.dumps(
            {"value": 2.0, "platform": "tpu", "config": "tpu_k100"}
        ), ""

    cap.run = settle_then_tunnel_dies
    assert run_main(cap) == 2  # bailed for the watcher
    assert cap._headline_attempts() == 1  # ...but the attempt is recorded
    assert os.path.exists(tmp_path / "headline_interim.json")


def test_config_tagged_headline_json_not_done(cap, tmp_path):
    """A config-tagged headline.json from an older capture must read as
    NOT settled, so later windows retry the full-K headline."""
    with open(tmp_path / "headline.json", "w") as f:
        json.dump({"value": 2.0, "platform": "tpu", "config": "tpu_k100"}, f)
    assert not cap._headline_done()


def test_run_kills_whole_process_group_on_timeout(cap):
    """ADVICE medium #1: a timed-out child's grandchild (inheriting the
    stdout pipe, like an orphaned bench subprocess hung in backend init)
    must not wedge communicate() nor survive holding the chip lease —
    run() kills the entire process group and still returns the partial
    output."""
    marker = "600.125"  # unique sleep arg to scan for survivors
    t0 = time.monotonic()
    rc, out, err = cap.run(
        ["/bin/sh", "-c",
         f"echo PARTIAL; sleep {marker} & trap '' TERM; sleep 600"],
        timeout=1,
    )
    assert time.monotonic() - t0 < 25.0  # no indefinite communicate() wedge
    assert rc is None
    assert "timeout after 1" in err
    assert "PARTIAL" in out  # pre-timeout output still collected
    time.sleep(0.3)
    scan = subprocess.run(["pgrep", "-f", f"sleep {marker}"],
                          capture_output=True)
    assert scan.returncode != 0, "grandchild survived the group kill"
