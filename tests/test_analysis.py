"""Tier-1 gate for the static-analysis subsystem (``blades_tpu/analysis``).

Pins both directions of every Tier-A rule — each rule FIRES on its seeded
fixture mini-repo (``tests/fixtures/analysis/<ruleid>/``, no false
negatives) and the full rule set is SILENT on HEAD (no false positives) —
plus the CLI's one-JSON-line contract, the pragma/baseline waiver
machinery, the import-order subprocess contracts the IMP rules lint
statically, and the Tier-B compiled-program audit on the real round /
block / streaming programs."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from blades_tpu.analysis import RepoIndex, all_rules, run_rules  # noqa: E402

FIXTURES = os.path.join(REPO, "tests", "fixtures", "analysis")

RULE_IDS = [
    "ALIAS001", "XLA001", "IMP001", "IMP002", "SYNC001",
    "PAL001", "TEL001", "JSON001", "CITE001", "SCHEMA001",
]


def _cli(*argv, env_extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "blades_tpu.analysis", *argv],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300,
    )


# -- Tier A: rule-set health ---------------------------------------------------


def test_rule_registry_has_at_least_eight_distinct_rules():
    rules = all_rules()
    ids = [r.id for r in rules]
    assert len(set(ids)) == len(ids), "duplicate rule ids"
    assert len(ids) >= 8, ids
    for r in rules:
        assert r.rationale, f"{r.id} lacks an incident rationale"
        assert r.severity in ("error", "warning"), r.id


def test_tier_a_silent_on_head():
    """The no-false-positive direction: the full rule set over the real
    repo reports zero unwaived violations (waivers must carry a pragma,
    which keeps them visible and counted)."""
    violations, waived = run_rules(RepoIndex(REPO), all_rules())
    assert violations == [], "\n".join(str(v) for v in violations)
    # the two supervisor XLA001 waivers are deliberate and documented
    for v in waived:
        assert v.rule == "XLA001" and "supervision" in v.path, str(v)


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_each_rule_fires_on_its_fixture(rule_id):
    """The no-false-negative direction: every rule detects the exact
    violation its fixture mini-repo seeds — and nothing else fires there,
    so each fixture pins one rule's behavior, not rule interactions."""
    root = os.path.join(FIXTURES, rule_id.lower())
    assert os.path.isdir(root), f"missing fixture tree {root}"
    violations, _ = run_rules(RepoIndex(root), all_rules())
    assert [v.rule for v in violations] == [rule_id], [
        str(v) for v in violations
    ]
    # the seeded line is marked in the fixture source
    mod = violations[0]
    src = open(os.path.join(root, mod.path)).read().splitlines()
    window = "\n".join(src[max(0, mod.line - 3): mod.line + 2])
    assert "VIOLATION" in window, (
        f"{rule_id} fired at {mod.path}:{mod.line}, away from the "
        f"seeded marker:\n{window}"
    )


def test_sync001_reaches_loop_and_cond_branch_bodies(tmp_path):
    """Regression (review finding): lax.fori_loop takes its body at
    args[2] and lax.cond its false branch at args[2] — host syncs there
    must not slip past root detection."""
    pkg = tmp_path / "blades_tpu" / "core"
    pkg.mkdir(parents=True)
    (pkg / "loops.py").write_text(textwrap.dedent(
        '''\
        """Doc. Reference counterpart: none — test module."""
        import jax.numpy as jnp
        from jax import lax


        def run(n, x, p):
            def body(i, c):
                return c + c.item()

            def tf(v):
                return v

            def ff(v):
                return v * v.item()

            return lax.fori_loop(0, n, body, x) + lax.cond(p, tf, ff, x)
        '''
    ))
    violations, _ = run_rules(RepoIndex(str(tmp_path)), all_rules())
    hits = [v for v in violations if v.rule == "SYNC001"]
    assert len(hits) == 2, [str(v) for v in violations]
    assert {"body", "ff"} == {
        v.message.split("jit-reachable `")[1].split("`")[0] for v in hits
    }, [v.message for v in hits]


def test_tel001_sanctions_helpers_nested_in_flush(tmp_path):
    """Regression (review finding): a write helper lexically nested
    inside flush IS the sanctioned sink path; I/O nested in any other
    method is flagged exactly once (no ast.walk double-count)."""
    pkg = tmp_path / "blades_tpu" / "telemetry"
    pkg.mkdir(parents=True)
    (pkg / "recorder.py").write_text(textwrap.dedent(
        '''\
        """Doc. Reference counterpart: none — test module."""


        class Recorder:
            def flush(self):
                def _do(batch):
                    self._fh.write(batch)

                _do("x")

            def span_exit(self):
                def _leak(rec):
                    self._fh.write(rec)

                _leak("y")
        '''
    ))
    violations, _ = run_rules(RepoIndex(str(tmp_path)), all_rules())
    hits = [v for v in violations if v.rule == "TEL001"]
    assert len(hits) == 1, [str(v) for v in hits]
    assert "_leak" in hits[0].message


def test_imp_rules_catch_relative_imports(tmp_path):
    """Regression (review finding): the relative spelling of a contract
    breach (`from . import metric_pack`, `from ..utils.platform import
    ...`) must fire the same as the absolute one — in-package code is
    exactly where the relative form is idiomatic."""
    tel = tmp_path / "blades_tpu" / "telemetry"
    tel.mkdir(parents=True)
    (tel / "__init__.py").write_text(
        '"""Doc. Reference counterpart: none — test module."""\n'
        "from . import metric_pack\n"
    )
    (tel / "schema.py").write_text(
        '"""Doc. Reference counterpart: none — test module."""\n'
        "from .metric_pack import pack_update\n"
    )
    sup = tmp_path / "blades_tpu" / "supervision"
    sup.mkdir()
    (sup / "__init__.py").write_text(
        '"""Doc. Reference counterpart: none — test module."""\n'
        "from ..utils.platform import force_virtual_cpu\n"
    )
    violations, _ = run_rules(RepoIndex(str(tmp_path)), all_rules())
    by_rule = {}
    for v in violations:
        by_rule.setdefault(v.rule, []).append(v.path)
    assert set(by_rule) == {"IMP001", "IMP002"}, [str(v) for v in violations]
    # the telemetry __init__ case belongs to IMP002 alone (one rule per
    # incident); the other contracted files fire IMP001
    assert by_rule["IMP002"] == ["blades_tpu/telemetry/__init__.py"]
    assert sorted(by_rule["IMP001"]) == [
        "blades_tpu/supervision/__init__.py",
        "blades_tpu/telemetry/schema.py",
    ]


def test_imp001_covers_run_identity_modules(tmp_path):
    """PR 9 surface: the run-identity layer (`telemetry/{context,ledger,
    alerts}.py`) entered the pre-jax contract set — a module-scope jax
    import in any of them must fire IMP001 (the fire direction; HEAD
    silence is test_tier_a_silent_on_head)."""
    tel = tmp_path / "blades_tpu" / "telemetry"
    tel.mkdir(parents=True)
    for name in ("context", "ledger", "alerts"):
        (tel / f"{name}.py").write_text(
            '"""Doc. Reference counterpart: none — test module."""\n'
            "import jax\n"
        )
    violations, _ = run_rules(RepoIndex(str(tmp_path)), all_rules())
    assert sorted(v.path for v in violations if v.rule == "IMP001") == [
        "blades_tpu/telemetry/alerts.py",
        "blades_tpu/telemetry/context.py",
        "blades_tpu/telemetry/ledger.py",
    ], [str(v) for v in violations]


def test_sync001_covers_asyncfl_device_scope(tmp_path):
    """PR 10 surface: `blades_tpu/asyncfl/` entered the SYNC001 device-code
    scope with its traced entry points (`async_round`, the arrival `draw`,
    `staleness_mask_weights`) as protocol roots — a host sync in any of
    them must fire (the fire direction; HEAD silence is
    test_tier_a_silent_on_head)."""
    pkg = tmp_path / "blades_tpu" / "asyncfl"
    pkg.mkdir(parents=True)
    (pkg / "engine.py").write_text(textwrap.dedent(
        '''\
        """Doc. Reference counterpart: none — test module."""
        import jax.numpy as jnp


        def async_round(engine, state):
            count = jnp.sum(state["buf_mask"])
            return count.item()  # VIOLATION


        def _helper_not_a_root(x):
            return x.item()  # unreachable: never referenced by a root
        '''
    ))
    (pkg / "arrivals.py").write_text(textwrap.dedent(
        '''\
        """Doc. Reference counterpart: none — test module."""
        import numpy as np


        class ArrivalProcess:
            def draw(self, key, k):
                return np.asarray(key)  # VIOLATION
        '''
    ))
    violations, _ = run_rules(RepoIndex(str(tmp_path)), all_rules())
    hits = [v for v in violations if v.rule == "SYNC001"]
    assert sorted(v.path for v in hits) == [
        "blades_tpu/asyncfl/arrivals.py",
        "blades_tpu/asyncfl/engine.py",
    ], [str(v) for v in violations]
    assert {"async_round", "draw"} == {
        v.message.split("jit-reachable `")[1].split("`")[0] for v in hits
    }


def test_imp001_rejects_asyncfl_from_prejax_contract_files(tmp_path):
    """PR 10 surface: `blades_tpu.asyncfl` is a known jax-importing
    module — a module-scope import of it from a pre-jax contracted file
    (here telemetry/context.py) must fire IMP001."""
    tel = tmp_path / "blades_tpu" / "telemetry"
    tel.mkdir(parents=True)
    (tel / "context.py").write_text(
        '"""Doc. Reference counterpart: none — test module."""\n'
        "from blades_tpu.asyncfl import AsyncConfig\n"
    )
    violations, _ = run_rules(RepoIndex(str(tmp_path)), all_rules())
    assert [v.rule for v in violations] == ["IMP001"], [
        str(v) for v in violations
    ]
    assert "blades_tpu.asyncfl" in violations[0].message


def test_repo_index_scans_asyncfl():
    """The RepoIndex scope pin: the real asyncfl modules are in the
    lintable file set (a future roots change silently dropping them would
    turn the whole PR-10 device surface lint-invisible)."""
    rels = {m.rel for m in RepoIndex(REPO).files}
    assert {
        "blades_tpu/asyncfl/__init__.py",
        "blades_tpu/asyncfl/arrivals.py",
        "blades_tpu/asyncfl/buffer.py",
        "blades_tpu/asyncfl/engine.py",
    } <= rels


def test_json001_covers_runs_script(tmp_path):
    """PR 9 surface: `scripts/runs.py` (the ledger query CLI) entered the
    one-JSON-line contract set — a main() without the catch-all funnel
    must fire JSON001."""
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    (scripts / "runs.py").write_text(textwrap.dedent(
        '''\
        """Doc. Reference counterpart: none — test module."""
        import json


        def main():
            print(json.dumps({"ok": True}))  # no try/except catch-all
        '''
    ))
    violations, _ = run_rules(RepoIndex(str(tmp_path)), all_rules())
    assert [v.rule for v in violations] == ["JSON001"], [
        str(v) for v in violations
    ]


def test_schema001_sees_new_record_emitters_on_head():
    """PR 9 surface: the static emit scan must actually SEE the new
    emitters — `alert` (telemetry/alerts.py via rec.event) and `ledger`
    (telemetry/ledger.py via {"t": ...} literals). Without this, schema
    coverage of the new types would rest on the declaration alone."""
    from blades_tpu.analysis.rules.schema_drift import emitted_types

    emitted = {t for t, _, _ in emitted_types(RepoIndex(REPO))}
    assert {"alert", "ledger"} <= emitted, sorted(emitted)


def test_imp001_covers_timeline_module(tmp_path):
    """PR 11 surface: the dispatch/sweep accounting module
    (`telemetry/timeline.py`) entered the pre-jax contract set — a
    module-scope jax import there must fire IMP001 (fire direction;
    HEAD silence is test_tier_a_silent_on_head, runtime side is
    test_import_timeline_before_jax)."""
    tel = tmp_path / "blades_tpu" / "telemetry"
    tel.mkdir(parents=True)
    (tel / "timeline.py").write_text(
        '"""Doc. Reference counterpart: none — test module."""\n'
        "import jax\n"
    )
    violations, _ = run_rules(RepoIndex(str(tmp_path)), all_rules())
    assert [v.rule for v in violations] == ["IMP001"], [
        str(v) for v in violations
    ]
    assert violations[0].path == "blades_tpu/telemetry/timeline.py"


def test_json001_covers_sweep_status_script(tmp_path):
    """PR 11 surface: `scripts/sweep_status.py` (the live sweep query
    CLI) entered the one-JSON-line contract set — a main() without the
    catch-all funnel must fire JSON001."""
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    (scripts / "sweep_status.py").write_text(textwrap.dedent(
        '''\
        """Doc. Reference counterpart: none — test module."""
        import json


        def main():
            print(json.dumps({"ok": True}))  # no try/except catch-all
        '''
    ))
    violations, _ = run_rules(RepoIndex(str(tmp_path)), all_rules())
    assert [v.rule for v in violations] == ["JSON001"], [
        str(v) for v in violations
    ]


def test_schema001_sees_timeline_and_sweep_emitters_on_head():
    """PR 11 surface: the static emit scan sees the dispatch-accounting
    emitters — `timeline` (timeline.emit via rec.event) and `sweep`
    (SweepAccounting cells + attack_search's sweep_cell_event) — so the
    v3 schema types cannot silently lose their emitters (or vice versa)."""
    from blades_tpu.analysis.rules.schema_drift import emitted_types

    emitted = {t for t, _, _ in emitted_types(RepoIndex(REPO))}
    assert {"timeline", "sweep"} <= emitted, sorted(emitted)


def test_imp001_covers_service_modules(tmp_path):
    """PR 14 surface: the simulation-service package (`blades_tpu/
    service/` — client, protocol, spool, server, __init__) entered the
    pre-jax contract set: clients submit from hosts where the tunnel is
    down and a probe-only server must start jax-free. A module-scope jax
    import in any of them must fire IMP001 (fire direction; HEAD silence
    is test_tier_a_silent_on_head, runtime side is
    test_import_service_before_jax)."""
    svc = tmp_path / "blades_tpu" / "service"
    svc.mkdir(parents=True)
    for name in ("__init__", "protocol", "client", "spool", "server"):
        (svc / f"{name}.py").write_text(
            '"""Doc. Reference counterpart: none — test module."""\n'
            "import jax\n"
        )
    violations, _ = run_rules(RepoIndex(str(tmp_path)), all_rules())
    assert sorted(v.path for v in violations if v.rule == "IMP001") == [
        "blades_tpu/service/__init__.py",
        "blades_tpu/service/client.py",
        "blades_tpu/service/protocol.py",
        "blades_tpu/service/server.py",
        "blades_tpu/service/spool.py",
    ], [str(v) for v in violations]


def test_json001_covers_serve_script(tmp_path):
    """PR 14 surface: `scripts/serve.py` (the service CLI) entered the
    one-JSON-line contract set — a main() without the catch-all funnel
    must fire JSON001 (runtime side:
    tests/test_service.py::test_serve_cli_one_json_line_on_error)."""
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    (scripts / "serve.py").write_text(textwrap.dedent(
        '''\
        """Doc. Reference counterpart: none — test module."""
        import json


        def main():
            print(json.dumps({"ok": True}))  # no try/except catch-all
        '''
    ))
    violations, _ = run_rules(RepoIndex(str(tmp_path)), all_rules())
    assert [v.rule for v in violations] == ["JSON001"], [
        str(v) for v in violations
    ]


def test_schema001_sees_service_emitters(tmp_path):
    """PR 14 surface, both directions: the static emit scan SEES the
    service/request emitters on HEAD (declaration can't outlive its
    emitters), and an undeclared service-record emit in a fixture tree
    fires SCHEMA001 (a new record type cannot land without moving the
    schema)."""
    from blades_tpu.analysis.rules.schema_drift import emitted_types

    emitted = {t for t, _, _ in emitted_types(RepoIndex(REPO))}
    assert {"service", "request"} <= emitted, sorted(emitted)

    svc = tmp_path / "blades_tpu" / "service"
    svc.mkdir(parents=True)
    (svc / "server.py").write_text(textwrap.dedent(
        '''\
        """Doc. Reference counterpart: none — test module."""


        def emit(rec):
            rec.event("service", event="health")
        '''
    ))
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "telemetry_schema.json").write_text(
        json.dumps({"types": {"meta": {}}})
    )
    violations, _ = run_rules(RepoIndex(str(tmp_path)), all_rules())
    hits = [v for v in violations if v.rule == "SCHEMA001"]
    assert len(hits) == 1 and "'service'" in hits[0].message, [
        str(v) for v in violations
    ]


def test_imp001_covers_reqpath_module(tmp_path):
    """PR 15 surface: the request-path accounting module
    (`telemetry/reqpath.py`) entered the pre-jax contract set — it is
    consumed by the probe-only server and every metrics/status query
    surface. A module-scope jax import there must fire IMP001 (fire
    direction; HEAD silence is test_tier_a_silent_on_head, runtime side
    is test_import_reqpath_before_jax)."""
    tel = tmp_path / "blades_tpu" / "telemetry"
    tel.mkdir(parents=True)
    (tel / "reqpath.py").write_text(
        '"""Doc. Reference counterpart: none — test module."""\n'
        "import jax\n"
    )
    violations, _ = run_rules(RepoIndex(str(tmp_path)), all_rules())
    assert [v.rule for v in violations] == ["IMP001"], [
        str(v) for v in violations
    ]
    assert violations[0].path == "blades_tpu/telemetry/reqpath.py"


def test_schema001_sees_metrics_snapshot_emitter(tmp_path):
    """PR 15 surface, both directions: the static emit scan SEES the
    `metrics_snapshot` emitter on HEAD (the v6 declaration cannot
    outlive its emitter), and an undeclared metrics_snapshot emit in a
    fixture tree fires SCHEMA001."""
    from blades_tpu.analysis.rules.schema_drift import emitted_types

    emitted = {t for t, _, _ in emitted_types(RepoIndex(REPO))}
    assert "metrics_snapshot" in emitted, sorted(emitted)

    svc = tmp_path / "blades_tpu" / "service"
    svc.mkdir(parents=True)
    (svc / "server.py").write_text(textwrap.dedent(
        '''\
        """Doc. Reference counterpart: none — test module."""


        def emit(rec):
            rec.event("metrics_snapshot", uptime_s=1.0)
        '''
    ))
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "telemetry_schema.json").write_text(
        json.dumps({"types": {"meta": {}}})
    )
    violations, _ = run_rules(RepoIndex(str(tmp_path)), all_rules())
    hits = [v for v in violations if v.rule == "SCHEMA001"]
    assert len(hits) == 1 and "'metrics_snapshot'" in hits[0].message, [
        str(v) for v in violations
    ]


def test_alias001_catches_with_statement_load(tmp_path):
    """Regression (review finding): `with np.load(path) as z:` is the
    documented numpy idiom for NpzFile and must taint the bound archive
    like an assignment does."""
    pkg = tmp_path / "blades_tpu"
    pkg.mkdir()
    (pkg / "restore.py").write_text(textwrap.dedent(
        '''\
        """Doc. Reference counterpart: none — test module."""
        import numpy as np
        import jax.numpy as jnp


        def restore(path):
            with np.load(path) as z:
                return jnp.asarray(z["params"])
        '''
    ))
    violations, _ = run_rules(RepoIndex(str(tmp_path)), all_rules())
    assert [v.rule for v in violations] == ["ALIAS001"], [
        str(v) for v in violations
    ]


def test_alias001_reports_nested_function_once(tmp_path):
    """Regression (review finding): a violation in a nested def was
    reported twice (once standalone, once via the enclosing function's
    walk). Closure taint must still be seen — exactly once."""
    pkg = tmp_path / "blades_tpu"
    pkg.mkdir()
    (pkg / "restore.py").write_text(textwrap.dedent(
        '''\
        """Doc. Reference counterpart: none — test module."""
        import numpy as np
        import jax.numpy as jnp


        def restore(path):
            z = np.load(path)

            def leaf(name):
                return jnp.asarray(z[name])

            return leaf("params")
        '''
    ))
    violations, _ = run_rules(RepoIndex(str(tmp_path)), all_rules())
    hits = [v for v in violations if v.rule == "ALIAS001"]
    assert len(hits) == 1, [str(v) for v in hits]


def test_citation_shim_reports_unparseable_module(tmp_path):
    """Regression (review finding): the shim must stay loud on a module
    that does not parse (the old standalone script crashed there; the
    rule path reports PARSE000)."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import check_citations

    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    msg = check_citations.check_module(str(broken))
    assert msg is not None and "does not parse" in msg


def test_unparseable_file_fails_the_gate(tmp_path):
    pkg = tmp_path / "blades_tpu"
    pkg.mkdir()
    (pkg / "broken.py").write_text("def f(:\n")
    violations, _ = run_rules(RepoIndex(str(tmp_path)), all_rules())
    assert any(v.rule == "PARSE000" for v in violations), violations


def test_pragma_waives_and_is_counted(tmp_path):
    pkg = tmp_path / "blades_tpu"
    pkg.mkdir()
    (pkg / "launch.py").write_text(textwrap.dedent(
        '''\
        """Doc. Reference counterpart: none — test module."""
        # justified: test of the pragma machinery
        # blades: allow[XLA001]
        ENV = {"XLA_FLAGS": "--xla_pragma_test_flag=1"}
        '''
    ))
    violations, waived = run_rules(RepoIndex(str(tmp_path)), all_rules())
    assert violations == [], [str(v) for v in violations]
    assert [w.rule for w in waived] == ["XLA001"]


# -- CLI: one-JSON-line contract + baseline waivers ----------------------------


def test_cli_tier_a_emits_exactly_one_json_line_and_passes():
    proc = _cli("--check", "--tier", "a")
    out_lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(out_lines) == 1, proc.stdout
    payload = json.loads(out_lines[0])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert payload["metric"] == "static_analysis"
    assert payload["ok"] is True
    assert payload["violations"] == 0
    assert len(payload["rules"]) >= 8
    assert payload["waived_pragma"] == 2  # the supervisor XLA001 pair


def test_cli_failure_is_still_one_json_line(tmp_path):
    """The self-hosted JSON001 contract: even a broken invocation (a
    malformed baseline file) emits one parseable error line, rc != 0."""
    bad = tmp_path / "baseline.json"
    bad.write_text("{not json")
    proc = _cli("--check", "--tier", "a", "--baseline", str(bad))
    out_lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert proc.returncode != 0
    assert len(out_lines) == 1, proc.stdout
    payload = json.loads(out_lines[0])
    assert payload["ok"] is False and "error" in payload


def test_cli_reports_violations_on_fixture_and_baseline_waives(tmp_path):
    root = os.path.join(FIXTURES, "cite001")
    proc = _cli("--check", "--tier", "a", "--root", root)
    assert proc.returncode == 1
    payload = json.loads(proc.stdout.strip())
    assert payload["rules"]["CITE001"] == 1
    assert "CITE001" in proc.stderr

    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({"waivers": ["CITE001:blades_tpu/bare.py"]}))
    proc = _cli(
        "--check", "--tier", "a", "--root", root, "--baseline", str(baseline)
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout.strip())
    assert payload["ok"] is True
    assert payload["waived_baseline"] == 1
    assert payload["rules"]["CITE001"] == 0
    assert "waived[baseline]" in proc.stderr


def test_cli_write_baseline_round_trips(tmp_path):
    root = os.path.join(FIXTURES, "cite001")
    baseline = tmp_path / "baseline.json"
    proc = _cli(
        "--check", "--tier", "a", "--root", root,
        "--baseline", str(baseline), "--write-baseline",
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    waivers = json.loads(baseline.read_text())["waivers"]
    assert waivers == ["CITE001:blades_tpu/bare.py"]
    proc = _cli(
        "--check", "--tier", "a", "--root", root, "--baseline", str(baseline)
    )
    assert json.loads(proc.stdout.strip())["ok"] is True


def test_cli_write_baseline_accepts_bare_filename(tmp_path, monkeypatch, capsys):
    """Regression (review finding): a cwd-relative --baseline path (the
    natural operator invocation) crashed os.makedirs('') instead of
    writing the file."""
    from blades_tpu.analysis.__main__ import main

    monkeypatch.chdir(tmp_path)
    rc = main([
        "--check", "--tier", "a",
        "--root", os.path.join(FIXTURES, "cite001"),
        "--baseline", "baseline.json", "--write-baseline",
    ])
    payload = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and payload["ok"] is True
    waivers = json.loads((tmp_path / "baseline.json").read_text())["waivers"]
    assert waivers == ["CITE001:blades_tpu/bare.py"]


# -- import-order contracts (the runtime side of IMP001/IMP002) ----------------


def _import_probe(stmt: str):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-c",
         f"{stmt}; import sys; assert 'jax' not in sys.modules, 'jax leaked'"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120,
    )


def test_import_telemetry_before_jax():
    """CLAUDE.md contract, previously unenforced: importing the telemetry
    package must not pull jax into the process."""
    proc = _import_probe("import blades_tpu.telemetry")
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_import_supervision_before_jax():
    proc = _import_probe("import blades_tpu.supervision")
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_import_run_identity_modules_before_jax():
    """The run-identity layer (context/ledger/alerts) is consumed by
    stdlib-only harnesses (supervisor, tpu_capture, runs.py) — importing
    it must never pull in jax."""
    proc = _import_probe(
        "import blades_tpu.telemetry.context, blades_tpu.telemetry.ledger, "
        "blades_tpu.telemetry.alerts"
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_import_timeline_before_jax():
    """PR 11 contract: the dispatch/sweep accounting layer must be
    importable (and its sweep-status consumer runnable) before jax —
    sweep progress is queried from hosts where the tunnel is down."""
    proc = _import_probe("import blades_tpu.telemetry.timeline")
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_import_service_before_jax():
    """PR 14 contract: the simulation-service package — and the server
    module itself — must be importable (and a probe-only request loop
    runnable) without jax entering the process; the jax-importing
    simulate handler stays behind function-scope imports."""
    proc = _import_probe(
        "import blades_tpu.service, blades_tpu.service.server, "
        "blades_tpu.service.handlers"
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_import_reqpath_before_jax():
    """PR 15 contract: the request-path accounting layer must be
    importable without jax — serving metrics are queried from hosts
    where the tunnel is down, and the probe-only server folds every
    request into it jax-free."""
    proc = _import_probe("import blades_tpu.telemetry.reqpath")
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_import_scheduler_before_jax():
    """PR 17 contract: the multi-tenant scheduler sits on the listener's
    admission path (overflow verdicts, deadline estimates) — a scheduling
    decision must never be the import that drags jax into a probe-only
    server."""
    proc = _import_probe(
        "from blades_tpu.service.scheduler import ("
        "TenantScheduler, CostEstimator, ScheduledRequest); "
        "s = TenantScheduler(max_queue=2, tenant_quota=1); "
        "s.put(ScheduledRequest(request_id='r', request={})); "
        "assert s.overflow('anon')['scope'] == 'tenant'; "
        "assert CostEstimator(lambda: None, lambda: None)"
        ".verdict(5, 0.001) == ('no_estimate', None)"
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_import_workers_before_jax():
    """PR 19 contract: the worker-pool layer (parent dispatch/kill loop
    AND the worker child's entry module) must import jax-free — the
    parent never pays jax init, and a worker must reach its `ready`
    frame in interpreter-import time."""
    proc = _import_probe(
        "import blades_tpu.service.workers, blades_tpu.service.worker"
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_import_analysis_tier_a_before_jax():
    """Tier A must lint (not just import) without jax — it is the gate
    that still works when the accelerator tunnel is down."""
    proc = _import_probe(
        "from blades_tpu.analysis import RepoIndex, run_rules, all_rules; "
        f"vs, w = run_rules(RepoIndex({REPO!r}), all_rules()); "
        "assert vs == [], vs"
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


# -- Tier B: compiled-program audit --------------------------------------------


@pytest.fixture(scope="module")
def tier_b_result():
    from blades_tpu.analysis.program_audit import run_tier_b

    return run_tier_b(force_platform=False)


def test_tier_b_all_invariants_hold(tier_b_result):
    failed = [c for c in tier_b_result["checks"] if not c["ok"]]
    assert tier_b_result["ok"] is True, failed
    assert tier_b_result["violations"] == 0


def test_tier_b_covers_all_programs_and_invariants(tier_b_result):
    """The acceptance surface: donation, dtype, sharding-axis, and
    retrace-stability each verified, across round, block, streaming, and
    buffered-async programs."""
    checks = {(c["check"], c["program"]) for c in tier_b_result["checks"]}
    kinds = {c for c, _ in checks}
    assert kinds == {
        "donation", "dtype_f64", "sharding_axis", "retrace_stability"
    }, kinds
    for program in ("round", "block", "streaming", "async",
                    "experiment_batch"):
        assert ("donation", program) in checks
        assert ("dtype_f64", program) in checks
        assert ("retrace_stability", program) in checks
    # the miscompile-guard axis check runs on the SHARDED trace of every
    # body that builds a rank-2 client-axis value (both round bodies, the
    # async buffer/lag-gather body, and the experiment-axis map body)
    assert ("sharding_axis", "round_sharded") in checks
    assert ("sharding_axis", "streaming_sharded") in checks
    assert ("sharding_axis", "async_sharded") in checks
    assert ("sharding_axis", "experiment_batch_sharded") in checks


def test_tier_b_sharding_axis_fires_on_model_axis_in_experiment_map():
    """The fire direction for the experiment-axis program's audit: a
    model-axis constraint on a rank-2 value INSIDE the experiment
    ``lax.map`` body must be caught (the walk descends into map/scan
    sub-jaxprs — a constraint the batch axis hides from the top level is
    exactly the regression this check exists for)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from blades_tpu.analysis.program_audit import check_sharding_axis
    from blades_tpu.parallel.mesh import make_mesh

    devices = jax.devices()
    if len(devices) < 2:
        import pytest

        pytest.skip("needs a >=2-device mesh")
    mesh = make_mesh(devices[:2], (1, 2))

    def bad_batched(stack):
        def one(u):
            with mesh:
                return lax.with_sharding_constraint(
                    u, jax.sharding.NamedSharding(mesh, P("clients", "model"))
                )

        return lax.map(one, stack)

    closed = jax.make_jaxpr(bad_batched)(jnp.zeros((2, 8, 16)))
    res = check_sharding_axis("experiment_batch_sharded", closed)
    assert res["ok"] is False
    assert "partitions axis>0" in res["detail"]


def test_tier_b_donation_detail_names_the_alias_map(tier_b_result):
    for c in tier_b_result["checks"]:
        if c["check"] == "donation":
            assert "input_output_alias" in c["detail"], c
        if c["check"] == "retrace_stability":
            assert "must be 0" in c["detail"], c
