"""Run-logging tests: idempotent ``initialize_logger`` (the reference's
``logging`` module-reload hack replaced by explicit handler teardown,
``src/blades/utils.py:67-95``) and stats-file parse parity with the
reference's consumer loop (``examples/Simulation on MNIST.py:69-83``,
ported as ``read_stats``)."""

import logging
import os

from blades_tpu.utils.logging import initialize_logger, read_stats


def test_reinit_replaces_handlers_not_stacks(tmp_path):
    root1 = str(tmp_path / "a")
    root2 = str(tmp_path / "b")
    initialize_logger(root1)
    stats = logging.getLogger("stats")
    assert len(stats.handlers) == 1
    stats.info({"_meta": {"type": "test"}, "Round": 1, "top1": 0.5})
    initialize_logger(root2)
    assert len(stats.handlers) == 1  # replaced, never stacked
    stats.info({"_meta": {"type": "test"}, "Round": 2, "top1": 0.7})
    # each run's file holds only its own records (no cross-run duplication)
    assert [r["Round"] for r in read_stats(root1, "test")] == [1]
    assert [r["Round"] for r in read_stats(root2, "test")] == [2]


def test_reinit_same_dir_wipes_and_keeps_writing(tmp_path):
    """Handlers are closed BEFORE the dir wipe, so re-initializing the same
    path can't leave records going to an unlinked file descriptor."""
    root = str(tmp_path / "out")
    initialize_logger(root)
    logging.getLogger("stats").info({"_meta": {"type": "t"}, "x": 1})
    initialize_logger(root)
    logging.getLogger("stats").info({"_meta": {"type": "t"}, "x": 2})
    assert [r["x"] for r in read_stats(root)] == [2]


def test_reinit_preserves_crash_recovery_artifacts(tmp_path):
    """The log-dir wipe must NOT destroy what a kill -> relaunch ->
    resume=True cycle needs: checkpoint archives (*.npz incl. the crash
    autosave), the telemetry trace, and the supervisor heartbeat file.
    Regression: the unconditional rmtree silently degraded every
    resume-after-kill into a from-scratch rerun (undetectable under a
    deterministic seed)."""
    root = str(tmp_path / "out")
    initialize_logger(root)
    keep = ["autosave.npz", "ck.npz", "telemetry.jsonl", "heartbeat"]
    for name in keep + ["stats", "scratch.txt"]:
        with open(os.path.join(root, name), "w") as f:
            f.write("x")
    os.makedirs(os.path.join(root, "profile"))
    initialize_logger(root)
    for name in keep:
        assert os.path.exists(os.path.join(root, name)), name
    assert not os.path.exists(os.path.join(root, "scratch.txt"))
    assert not os.path.exists(os.path.join(root, "profile"))
    assert open(os.path.join(root, "stats")).read() == ""  # fresh handler


def test_stats_format_byte_compatible(tmp_path):
    """The on-disk format is the reference's: one bare dict repr per line
    (what ``read_stats``/the MNIST example's ``read_json`` parse)."""
    root = str(tmp_path / "out")
    initialize_logger(root)
    rec = {"_meta": {"type": "test"}, "Round": 3, "top1": 0.25, "Loss": 1.5}
    logging.getLogger("stats").info(rec)
    raw = open(os.path.join(root, "stats")).read()
    assert raw == repr(rec) + "\n"
    logging.getLogger("debug").info("free text line")
    assert open(os.path.join(root, "debug")).read() == "free text line\n"


def test_no_propagation_to_root(tmp_path, capsys):
    """A root handler (pytest's, a user basicConfig) must not duplicate or
    reformat stats records."""
    root = str(tmp_path / "out")
    initialize_logger(root)
    assert logging.getLogger("stats").propagate is False
    assert logging.getLogger("debug").propagate is False
