"""Driver-contract tests for __graft_entry__.py."""

import sys
import os

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft


def test_entry_compiles():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 10)


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)
