"""Fault-injection + graceful-degradation tests (``blades_tpu/faults``).

Pins the three contracts the subsystem is built on:

1. **Mask-API coverage** — every registered aggregator implements
   mask-aware aggregation (a new defense cannot silently regress graceful
   degradation under partial participation);
2. **Mask semantics** — an all-ones mask is BIT-identical to the unmasked
   path, and a masked-out row's content (NaN, Inf, 1e30 garbage) cannot
   change the result;
3. **End-to-end survival** — a CPU-mesh simulation with client dropout +
   NaN-injecting faulty clients under krum/median/trimmedmean completes
   with finite loss, logs per-round fault counts to the telemetry trace,
   and a mid-run kill resumes bit-exactly from the crash autosave.

The reference has no counterpart for any of this (it assumes a fixed,
always-healthy client population, ``src/blades/simulator.py:213-244``).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu import FaultModel, Simulator
from blades_tpu.aggregators import AGGREGATORS, get_aggregator
from blades_tpu.aggregators.base import Aggregator
from blades_tpu.datasets import Synthetic
from blades_tpu.ops.masked import masked_mean, masked_median, masked_trimmed_mean
from blades_tpu.ops.pytree import ravel

K, D = 9, 11


def rand_updates(seed=0, k=K, d=D):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(k, d)).astype(np.float32)


def _agg(name):
    kw = {"num_byzantine": 2} if name in (
        "trimmedmean", "krum", "multikrum", "dnc"
    ) else {}
    return get_aggregator(name, **kw)


def _ctx(name, k=K, d=D):
    if name == "dnc":
        return {"key": jax.random.key(3)}
    if name == "byzantinesgd":
        return {"params_flat": jnp.zeros(d)}
    if name == "fltrust":
        # trusted client participates in every mask these tests use
        return {"trusted_mask": jnp.zeros(k, bool).at[3].set(True)}
    return {}


# ------------------------------------------------------- mask-API coverage


@pytest.mark.parametrize("name", sorted(AGGREGATORS))
def test_registered_aggregator_exposes_mask_api(name):
    """CI lint: every registry entry overrides ``_masked_aggregate`` — the
    base raises, so an aggregator registered without the mask-aware API
    fails here instead of failing a fault-model run at trace time."""
    cls = AGGREGATORS[name]
    assert cls._masked_aggregate is not Aggregator._masked_aggregate, (
        f"{name} does not implement mask-aware aggregation"
    )


def test_base_masked_aggregate_raises():
    class Bare(Aggregator):
        def aggregate(self, updates, state=(), **ctx):
            return jnp.mean(updates, axis=0), state

    with pytest.raises(NotImplementedError, match="mask-aware"):
        Bare().aggregate_masked(
            jnp.zeros((4, 3)), mask=jnp.ones(4, bool)
        )


def test_mask_none_routes_to_unmasked_path():
    u = jnp.asarray(rand_updates())
    agg = get_aggregator("mean")
    a, _ = agg.aggregate_masked(u, mask=None)
    b, _ = agg.aggregate(u)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------ mask semantics


@pytest.mark.parametrize("name", sorted(AGGREGATORS))
def test_all_ones_mask_bit_identical(name):
    """aggregate_masked with an all-ones mask must reproduce the unmasked
    aggregate BIT-exactly — the masked program only ever adds exact
    identities (* 1.0, + 0.0, where(True, x, _)) around the same
    reductions."""
    u = jnp.asarray(rand_updates(seed=1))
    agg = _agg(name)
    state = agg.init_state(K, D)
    ref, _ = agg.aggregate(u, state, **_ctx(name))
    got, _ = agg.aggregate_masked(u, state, mask=jnp.ones(K, bool), **_ctx(name))
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


@pytest.mark.parametrize("name", sorted(AGGREGATORS))
@pytest.mark.parametrize("garbage", [np.nan, np.inf, 1e30])
def test_masked_out_row_cannot_change_result(name, garbage):
    """The content of a masked-out row is irrelevant: NaN / Inf / huge
    garbage in excluded rows yields the exact result of excluded-zeros —
    and in particular a masked-out NaN row cannot poison the aggregate."""
    base = rand_updates(seed=2)
    mask = jnp.asarray([True] * 6 + [False] * 3)
    poisoned = base.copy()
    poisoned[6:] = garbage

    a_ref = _agg(name)
    ref, _ = a_ref.aggregate_masked(
        jnp.asarray(base), a_ref.init_state(K, D), mask=mask, **_ctx(name)
    )
    a_poi = _agg(name)
    got, _ = a_poi.aggregate_masked(
        jnp.asarray(poisoned), a_poi.init_state(K, D), mask=mask, **_ctx(name)
    )
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
    assert np.isfinite(np.asarray(got)).all()


@pytest.mark.parametrize("name", sorted(AGGREGATORS))
def test_masked_aggregate_jit_and_zero_participants(name):
    """The masked path traces under jit (the engine's fault branch) and a
    zero-participant mask still yields a finite vector (the engine
    additionally zeroes it — graceful skip, never NaN)."""
    u = jnp.asarray(rand_updates(seed=3))
    agg = _agg(name)
    state = agg.init_state(K, D)

    @jax.jit
    def run(u, state, mask):
        return agg.aggregate_masked(u, state, mask=mask, **_ctx(name))

    out, _ = run(u, state, jnp.asarray([True] * 5 + [False] * 4))
    assert out.shape == (D,) and np.isfinite(np.asarray(out)).all()
    if name == "fltrust":
        return  # zero-mask drops the trusted client; covered below
    zero, _ = run(u, state, jnp.zeros(K, bool))
    assert np.isfinite(np.asarray(zero)).all()


def test_masked_diagnostics_finite_with_nan_masked_rows():
    """Forensics under faults: aggregate_masked_with_diagnostics runs
    diagnostics on the SANITIZED matrix — a guard-excluded NaN row must not
    NaN the recorded defense scores."""
    u = rand_updates(seed=14)
    u_nan = u.copy()
    u_nan[6:] = np.nan
    mask = jnp.asarray([True] * 6 + [False] * 3)
    agg = _agg("krum")
    out, _, diag = agg.aggregate_masked_with_diagnostics(
        jnp.asarray(u_nan), mask=mask
    )
    assert np.isfinite(np.asarray(out)).all()
    assert np.isfinite(np.asarray(diag["scores"])).all()
    # and the trimmed-mean trim counts stay finite ints too
    _, _, tdiag = _agg("trimmedmean").aggregate_masked_with_diagnostics(
        jnp.asarray(u_nan), mask=mask
    )
    assert np.isfinite(np.asarray(tdiag["trim_counts"], dtype=np.float64)).all()


def test_masked_krum_single_participant_returns_its_update():
    """n=1: the lone participant has no finite neighbors, but its score
    must stay finite (below the +inf of masked-out rows) so selection
    returns ITS update, not a zeroed absent row."""
    u = rand_updates(seed=15)
    mask = jnp.zeros(K, bool).at[4].set(True)
    out, _ = _agg("krum").aggregate_masked(jnp.asarray(u), mask=mask)
    np.testing.assert_allclose(np.asarray(out), u[4], rtol=1e-6)


def test_clippedclustering_empty_round_freezes_history():
    """A zero-participant round must not advance the norm-history ring
    buffer (k zeros would drag the clipping threshold toward 0)."""
    from blades_tpu.aggregators import Clippedclustering

    agg = Clippedclustering()
    st = agg.init_state(K, D)
    u = jnp.asarray(rand_updates(seed=16))
    _, st1 = agg.aggregate_masked(u, st, mask=jnp.ones(K, bool))
    _, st2 = agg.aggregate_masked(u, st1, mask=jnp.zeros(K, bool))
    assert int(st2["count"]) == int(st1["count"])
    assert int(st2["pos"]) == int(st1["pos"])
    np.testing.assert_array_equal(
        np.asarray(st2["norms"]), np.asarray(st1["norms"])
    )


def test_fltrust_degrades_to_skip_when_trusted_client_drops():
    u = jnp.asarray(rand_updates(seed=4))
    mask = jnp.ones(K, bool).at[3].set(False)  # trusted client absent
    out, _ = get_aggregator("fltrust").aggregate_masked(
        u, mask=mask, trusted_mask=jnp.zeros(K, bool).at[3].set(True)
    )
    np.testing.assert_allclose(np.asarray(out), np.zeros(D), atol=1e-7)


# ------------------------------------------------- masked reduction closed forms


def test_masked_mean_median_trimmed_closed_forms():
    u = rand_updates(seed=5)
    mask_np = np.array([True, False, True, True, False, True, True, True, False])
    sub = u[mask_np]
    m = jnp.asarray(mask_np)
    np.testing.assert_allclose(
        np.asarray(masked_mean(jnp.asarray(u), m)), sub.mean(0), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(masked_median(jnp.asarray(u), m)),
        np.median(sub, axis=0),
        rtol=1e-6,
    )
    b = 2
    expected = np.mean(np.sort(sub, axis=0)[b : len(sub) - b], axis=0)
    np.testing.assert_allclose(
        np.asarray(masked_trimmed_mean(jnp.asarray(u), m, b)),
        expected,
        rtol=1e-5,
    )


def test_masked_trimmed_mean_b_clamps_under_heavy_dropout():
    # 3 participants with b=2 would trim everyone; the clamp narrows the
    # trim to b_eff=1 (toward the masked median) instead
    u = rand_updates(seed=6)
    mask = jnp.asarray([True, True, True] + [False] * 6)
    out = np.asarray(masked_trimmed_mean(jnp.asarray(u), mask, 2))
    np.testing.assert_allclose(out, np.median(u[:3], axis=0), rtol=1e-5)


def test_masked_krum_selects_among_participants_only():
    # planted far outliers are PARTICIPATING; tight benign cluster partially
    # masked — krum must select a participating benign row
    rng = np.random.default_rng(7)
    benign = rng.normal(size=(6, 4)).astype(np.float32) * 0.1
    outliers = np.full((3, 4), 50.0, dtype=np.float32)
    u = jnp.asarray(np.vstack([outliers, benign]))
    mask = jnp.asarray([True, True, True, False, True, True, True, True, True])
    out, _ = _agg("krum").aggregate_masked(u, mask=mask)
    dists = np.linalg.norm(benign[1:] - np.asarray(out), axis=1)
    assert dists.min() < 1e-5  # one of the participating benign rows


# ------------------------------------------------------------- FaultModel unit


def test_fault_model_validation():
    with pytest.raises(ValueError, match="corrupt_mode"):
        FaultModel(corrupt_mode="frobnicate")
    with pytest.raises(ValueError, match="participation_schedule"):
        FaultModel(participation_schedule=np.ones(4, bool))


def test_fault_model_deterministic_and_seeded():
    fm = FaultModel(dropout_rate=0.4, corrupt_rate=0.2)
    u = jnp.asarray(rand_updates(seed=8))
    key = jax.random.PRNGKey(0)
    out1 = fm.apply(u, fm.init_state(K, D), key, 3)
    out2 = fm.apply(u, fm.init_state(K, D), key, 3)
    for a, b in zip(jax.tree_util.tree_leaves(out1), jax.tree_util.tree_leaves(out2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fault_model_participation_schedule():
    sched = np.zeros((2, K), bool)
    sched[0, :4] = True  # even rounds: clients 0-3
    sched[1, 4:] = True  # odd rounds: clients 4-8
    fm = FaultModel(participation_schedule=sched)
    u = jnp.asarray(rand_updates(seed=9))
    _, m0, _, d0 = fm.apply(u, (), jax.random.PRNGKey(0), 0)
    _, m1, _, _ = fm.apply(u, (), jax.random.PRNGKey(0), 1)
    assert np.asarray(m0).tolist() == sched[0].tolist()
    assert np.asarray(m1).tolist() == sched[1].tolist()
    assert int(d0["participants"]) == 4 and int(d0["dropped"]) == 5


def test_fault_model_straggler_replays_stale_update():
    """A straggler re-sends its buffered update; once the buffer exceeds
    max_staleness the straggler is dropped instead."""
    fm = FaultModel(straggler_rate=1.0, max_staleness=2)
    u1 = jnp.asarray(rand_updates(seed=10))
    u2 = jnp.asarray(rand_updates(seed=11))
    key = jax.random.PRNGKey(0)
    st = fm.init_state(K, D)
    # round 0: everyone straggles but the buffer is empty -> all expire
    out0, m0, st, d0 = fm.apply(u1, st, key, 0)
    assert int(d0["participants"]) == 0
    assert int(d0["stragglers_expired"]) == K
    # fill the buffer: straggler_rate keyed per round; use a model with
    # stragglers off for the fill round by feeding fresh state manually
    fill = FaultModel(straggler_rate=1e-9, max_staleness=2)
    st = fm.init_state(K, D)
    _, m_fill, st, _ = fill.apply(u1, st, key, 1)
    assert int(np.asarray(m_fill).sum()) == K  # all fresh, buffer filled
    # now everyone straggles: the round delivers u1 (stale), not u2
    out2, m2, st2, d2 = fm.apply(u2, st, key, 2)
    assert int(d2["stale_replayed"]) == K
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(u1))
    # two more all-straggler rounds exceed max_staleness=2 -> dropped
    _, _, st3, d3 = fm.apply(u2, st2, key, 3)
    assert int(d3["stale_replayed"]) == K  # age 2 <= 2, still ok
    _, m4, _, d4 = fm.apply(u2, st3, key, 4)
    assert int(d4["stale_replayed"]) == 0
    assert int(d4["stragglers_expired"]) == K
    assert int(np.asarray(m4).sum()) == 0


@pytest.mark.parametrize("mode,pred", [
    ("nan", lambda r: np.isnan(r).all()),
    ("inf", lambda r: np.isinf(r).all()),
    ("bitflip", lambda r: np.isfinite(r).all()),
])
def test_fault_model_corruption_modes(mode, pred):
    fm = FaultModel(corrupt_clients=(0, 1), corrupt_mode=mode,
                    guard_nonfinite=False)
    u = jnp.asarray(rand_updates(seed=12))
    out, mask, _, diag = fm.apply(u, (), jax.random.PRNGKey(0), 0)
    out = np.asarray(out)
    assert int(diag["corrupted"]) == 2
    assert pred(out[0]) and pred(out[1])
    np.testing.assert_array_equal(out[2:], np.asarray(u)[2:])
    assert np.asarray(mask).all()  # guard off: corrupted rows still "present"


def test_nonfinite_guard_excludes_poisoned_rows():
    fm = FaultModel(corrupt_clients=(0, 1), corrupt_mode="nan")
    u = jnp.asarray(rand_updates(seed=13))
    out, mask, _, diag = fm.apply(u, (), jax.random.PRNGKey(0), 0)
    assert int(diag["excluded_nonfinite"]) == 2
    assert int(diag["participants"]) == K - 2
    assert not bool(np.asarray(mask)[0]) and not bool(np.asarray(mask)[1])
    # and the masked aggregation of the guarded round is finite + unpoisoned
    agg, _ = get_aggregator("median").aggregate_masked(out, mask=mask)
    np.testing.assert_allclose(
        np.asarray(agg), np.median(np.asarray(u)[2:], axis=0), rtol=1e-6
    )


# ------------------------------------------------------------- end to end


def _sim(tmp_path, sub, agg_name, agg_kws=None, num_clients=8, seed=0):
    ds = Synthetic(num_clients=num_clients, train_size=400, test_size=80,
                   noise=0.3, cache=False)
    return Simulator(ds, log_path=str(tmp_path / sub), seed=seed,
                     aggregator=agg_name, aggregator_kws=agg_kws or {})


FAULTS = dict(dropout_rate=0.3, corrupt_clients=(0, 1), corrupt_mode="nan")


@pytest.mark.parametrize("agg_name,agg_kws", [
    ("krum", {"num_byzantine": 2}),
    ("median", {}),
    ("trimmedmean", {"num_byzantine": 2}),
])
def test_simulation_survives_dropout_and_nan_clients(tmp_path, agg_name, agg_kws):
    """The acceptance scenario: 30% dropout + 2 NaN-injecting faulty
    clients; all rounds complete, the loss stays finite, and per-round
    fault/exclusion counts land in telemetry.jsonl."""
    sim = _sim(tmp_path, agg_name, agg_name, agg_kws)
    rounds = 3
    times = sim.run("mlp", global_rounds=rounds, local_steps=1,
                    train_batch_size=8, validate_interval=rounds,
                    fault_model=FaultModel(**FAULTS))
    assert len(times) == rounds
    ev = sim.evaluate(rounds, 64)
    assert np.isfinite(ev["Loss"])
    assert np.isfinite(np.asarray(ravel(sim.server.state.params))).all()

    trace = os.path.join(str(tmp_path / agg_name), "telemetry.jsonl")
    recs = [json.loads(l) for l in open(trace)]
    fault_recs = [r for r in recs if r.get("t") == "faults"]
    assert len(fault_recs) == rounds
    for r in fault_recs:
        assert {"participants", "dropped", "corrupted",
                "excluded_nonfinite"} <= set(r)
    # the NaN clients were excluded whenever they participated
    assert all(r["excluded_nonfinite"] <= 2 for r in fault_recs)
    assert any(r["excluded_nonfinite"] > 0 for r in fault_recs)
    assert any(r["dropped"] > 0 for r in fault_recs)
    meta = recs[0]
    assert meta["t"] == "meta" and "FaultModel" in meta.get("fault_model", "")


def test_fault_run_accepts_kwargs_dict(tmp_path):
    sim = _sim(tmp_path, "dictfm", "mean")
    sim.run("mlp", global_rounds=1, local_steps=1, train_batch_size=8,
            validate_interval=1, fault_model=dict(dropout_rate=0.5))
    assert int(sim.engine.last_fault_diag["dropped"]) >= 0
    assert sim.engine.fault_model.dropout_rate == 0.5


def test_mid_run_kill_resumes_bit_exactly_under_faults(tmp_path):
    """Kill the run mid-flight (exception after round 2): the crash
    autosave must appear in the log dir and resume=True must reproduce the
    uninterrupted run's final params bit-exactly — fault schedule, stale
    buffers and all."""
    kw = dict(global_rounds=4, local_steps=1, train_batch_size=8,
              validate_interval=100,
              fault_model=FaultModel(straggler_rate=0.3, max_staleness=2,
                                     **FAULTS))
    a = _sim(tmp_path, "a", "median", seed=5)
    a.run("mlp", **kw)
    ref = np.asarray(ravel(a.server.state.params))

    def boom(rnd, state, m):
        if rnd == 2:
            raise RuntimeError("simulated kill")

    b = _sim(tmp_path, "b", "median", seed=5)
    with pytest.raises(RuntimeError, match="simulated kill"):
        b.run("mlp", **kw, on_round_end=boom)
    autosave = os.path.join(str(tmp_path / "b"), "autosave.npz")
    assert os.path.exists(autosave), "crash autosave missing"
    trace = os.path.join(str(tmp_path / "b"), "telemetry.jsonl")
    recs = [json.loads(l) for l in open(trace)]
    assert any(r.get("t") == "crash_checkpoint" for r in recs)

    # a FRESH (resume=False) run on the same log dir must invalidate the
    # stale autosave UP FRONT — checked from inside round 1, because the
    # run-completion cleanup also unlinks it at the end (asserting after
    # the run would pass vacuously). A supervised relaunch (BLADES_RESUME=1)
    # of a run that dies pre-autosave must never resume another
    # experiment's state.
    seen = {}

    def probe(rnd, state, m):
        if rnd == 1:
            seen["autosave_at_round1"] = os.path.exists(autosave)

    d = _sim(tmp_path, "b", "median", seed=5)
    d.run("mlp", **dict(kw, global_rounds=1), on_round_end=probe)
    assert seen["autosave_at_round1"] is False, (
        "fresh run did not invalidate the stale crash autosave before "
        "its first round"
    )

    # recreate the crash so the resume path below still has its autosave
    b2 = _sim(tmp_path, "b", "median", seed=5)
    with pytest.raises(RuntimeError, match="simulated kill"):
        b2.run("mlp", **kw, on_round_end=boom)

    c = _sim(tmp_path, "b", "median", seed=5)  # same log dir -> same autosave
    assert os.path.exists(autosave), (
        "constructing the resuming Simulator must not wipe the autosave "
        "(utils/logging.py preserves *.npz across the log-dir wipe)"
    )
    times = c.run("mlp", **kw, resume=True)
    # ACTUAL resumption: only rounds 3..4 ran (a silent from-scratch rerun
    # would return 4 wall times and still match params bit-for-bit)
    assert len(times) == 2
    out = np.asarray(ravel(c.server.state.params))
    np.testing.assert_array_equal(ref, out)
    # the completed resume consumed the crash autosave: a later resume=True
    # must not silently re-train from the stale round-2 state
    assert not os.path.exists(autosave)


# --------------------------------------------------------- host-level retry


def test_retry_call_backoff_and_recording():
    from blades_tpu.telemetry import Recorder, set_recorder
    from blades_tpu.utils.retry import retry_call

    rec = Recorder(enabled=True)
    prev = set_recorder(rec)
    try:
        sleeps, attempts = [], []

        def flaky():
            attempts.append(1)
            if len(attempts) < 3:
                raise OSError("tunnel flake")
            return "up"

        out = retry_call(
            flaky, attempts=4, base_delay=1.0, max_delay=30.0,
            describe="tpu_tunnel", sleep=sleeps.append,
        )
        assert out == "up" and len(attempts) == 3
        assert sleeps == [1.0, 2.0]  # bounded exponential backoff
        snap = rec.snapshot()["counters"]
        assert snap["retry.tpu_tunnel"] == 2  # the flakes were RECORDED
        assert sum(1 for r in rec.records if r.get("t") == "retry") == 2
    finally:
        set_recorder(prev)


def test_retry_call_exhaustion_and_selectivity():
    from blades_tpu.utils.retry import retry_call

    with pytest.raises(OSError, match="dead"):
        retry_call(lambda: (_ for _ in ()).throw(OSError("dead")),
                   attempts=2, sleep=lambda _: None)
    # non-matching exceptions propagate immediately (no retry)
    calls = []

    def typed():
        calls.append(1)
        raise KeyError("no")

    with pytest.raises(KeyError):
        retry_call(typed, attempts=5, retry_on=(OSError,), sleep=lambda _: None)
    assert len(calls) == 1


def test_no_fault_model_unchanged(tmp_path):
    """Without a fault model the run carries no fault state, emits no fault
    records, and last_fault_diag stays None — the pre-fault program."""
    sim = _sim(tmp_path, "nofm", "mean")
    sim.run("mlp", global_rounds=1, local_steps=1, train_batch_size=8,
            validate_interval=1)
    assert sim.engine.last_fault_diag is None
    assert sim.server.state.fault_state == ()
    trace = os.path.join(str(tmp_path / "nofm"), "telemetry.jsonl")
    recs = [json.loads(l) for l in open(trace)]
    assert not any(r.get("t") == "faults" for r in recs)
