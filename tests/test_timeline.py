"""Dispatch & sweep accounting tests (``blades_tpu/telemetry/timeline.py``).

Pins the tentpole contracts of the accounting layer: per-launch
host-enqueue vs device-ready splits present and self-consistent with the
span tree under all three round semantics plus buffered-async; the
recorder's flush-once-per-round discipline unchanged with accounting on;
``BLADES_TELEMETRY=0`` a true no-op with zero added compiles; sweep
accounting's per-cell records, live status CLI, and the per-cell
heartbeat beat that keeps supervised sweeps alive between Simulator
flushes.

Reference counterpart: none — the reference records only whole-round
wall time (``src/blades/simulator.py:453-455``).
"""

import json
import os
import sys

import pytest

from blades_tpu.telemetry import Recorder, get_recorder, set_recorder
from blades_tpu.telemetry import recorder as recorder_mod
from blades_tpu.telemetry import timeline
from blades_tpu.telemetry.schema import load_schema, validate_records

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

from trace_summary import load_records, summarize  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_timeline_state():
    prev = get_recorder()
    timeline.reset()
    yield
    timeline.reset()
    set_recorder(prev)


# ------------------------------------------------------------ unit semantics


def test_launch_split_and_counter_join():
    """A launch window splits into enqueue/ready and joins the process
    compile-counter delta incurred inside it to the emitted record."""
    rec = Recorder(enabled=True)
    set_recorder(rec)
    base = dict(recorder_mod._PROCESS_COUNTERS)
    try:
        timeline.launch_begin("round", rounds=1, attrs={"streaming": 1})
        recorder_mod._PROCESS_COUNTERS["xla.compiles"] = (
            recorder_mod._PROCESS_COUNTERS.get("xla.compiles", 0) + 2
        )
        recorder_mod._PROCESS_COUNTERS["xla.compile_s"] = (
            recorder_mod._PROCESS_COUNTERS.get("xla.compile_s", 0.0) + 0.5
        )
        timeline.launch_enqueued()
        timeline.launch_ready(0.25)
        timeline.emit(rec, round_idx=7)
    finally:
        recorder_mod._PROCESS_COUNTERS.clear()
        recorder_mod._PROCESS_COUNTERS.update(base)
    recs = [r for r in rec.records if r["t"] == "timeline"]
    assert len(recs) == 1
    r = recs[0]
    assert r["kind"] == "round" and r["launches"] == 1 and r["rounds"] == 1
    assert r["ready_s"] == pytest.approx(0.25)
    assert r["enqueue_s"] >= 0.0 and r["round"] == 7
    assert r["compiles"] == 2 and r["compile_s"] == pytest.approx(0.5)
    assert r["streaming"] == 1
    assert 0.0 <= r["dispatch_share"] <= 1.0
    assert validate_records(recs, load_schema()) == []
    # emit drained the accumulator: a second emit adds nothing
    timeline.emit(rec)
    assert len([r for r in rec.records if r["t"] == "timeline"]) == 1


def test_disabled_recorder_makes_hooks_free(monkeypatch):
    """With the NULL recorder active the hooks never read the clock and
    never accumulate — the BLADES_TELEMETRY=0 zero-work contract."""
    set_recorder(None)  # NULL_RECORDER

    def boom(*a, **k):
        raise AssertionError("disabled accounting touched the clock")

    monkeypatch.setattr(timeline.time, "perf_counter", boom)
    timeline.launch_begin("round")
    timeline.launch_enqueued()
    timeline.launch_ready()
    timeline.emit()
    assert timeline._acc == {} and timeline._open_launch is None


def test_unsynced_launch_folds_with_zero_ready():
    """A caller that never blocks (bench-style loop): the next
    launch_begin folds the open launch with ready_s == 0 — we never
    observed its device wait, so we do not invent one."""
    rec = Recorder(enabled=True)
    set_recorder(rec)
    timeline.launch_begin("round")
    timeline.launch_enqueued()
    timeline.launch_begin("round")  # folds the first, unsynced
    timeline.launch_enqueued()
    timeline.launch_ready(0.1)
    timeline.emit(rec)
    r = [x for x in rec.records if x["t"] == "timeline"][0]
    assert r["launches"] == 2
    assert r["ready_s"] == pytest.approx(0.1)


# ----------------------------------------------- engine/simulator integration


def _run(tmp_path, **run_kw):
    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic

    ds = Synthetic(num_clients=6, train_size=240, test_size=60, noise=0.3,
                   cache=False)
    log = str(tmp_path / "out")
    sim = Simulator(ds, log_path=log, seed=0,
                    aggregator=run_kw.pop("agg", "median"))
    sim.run("mlp", global_rounds=run_kw.pop("rounds", 2), local_steps=1,
            train_batch_size=8, client_lr=0.2,
            validate_interval=99, **run_kw)
    trace = os.path.join(log, "telemetry.jsonl")
    return load_records(trace) if os.path.exists(trace) else []


@pytest.mark.parametrize("mode,run_kw,kind", [
    ("dense", {}, "round"),
    ("streaming", {"streaming": True, "client_chunks": 3}, "round"),
    ("block", {"block_size": 2}, "block"),
    ("async", {"async_config": {"buffer_m": 3,
                                "arrivals": {"kind": "uniform",
                                             "max_delay": 2}}}, "round"),
])
def test_timeline_records_all_round_semantics(tmp_path, mode, run_kw, kind):
    """Acceptance (a): timeline records present and self-consistent under
    dense, streaming, block, and buffered-async execution — the summed
    enqueue matches the dispatch span tree and ready stays inside the
    sync span (both are perf_counter measurements of the same intervals)."""
    records = _run(tmp_path, **run_kw)
    tls = [r for r in records if r["t"] == "timeline"]
    assert tls, "no timeline records emitted"
    assert {r["kind"] for r in tls} == {kind}
    assert validate_records(tls, load_schema()) == []
    for r in tls:
        assert r["launches"] >= 1 and r["rounds"] >= 1
        assert r["enqueue_s"] > 0.0 and r["ready_s"] >= 0.0
        assert 0.0 <= r["dispatch_share"] <= 1.0
        assert r["streaming"] == int(mode == "streaming")
        assert r["async"] == int(mode == "async")
    # one record per flush point: per round (dense) or per block
    n_flush_points = len([r for r in records if r["t"] == "round"])
    if kind == "block":
        n_flush_points = len(
            [r for r in records if r["t"] == "span" and r["path"] == "block"]
        )
    assert len(tls) == n_flush_points
    # self-consistency: enqueue total ~= dispatch span total, and the
    # whole launch window (enqueue + ready, which runs dispatch ->
    # blocked) fits inside the enclosing round/block span total
    spans = summarize(records)["spans"]
    disp_key = f"{kind}/dispatch" if kind == "block" else "round/dispatch"
    disp = spans[disp_key]["total_s"]
    enq = sum(r["enqueue_s"] for r in tls)
    rdy = sum(r["ready_s"] for r in tls)
    assert enq == pytest.approx(disp, rel=0.05, abs=0.05)
    outer = spans["block" if kind == "block" else "round"]["total_s"]
    assert enq + rdy <= outer + 0.05


def test_flush_discipline_unchanged_with_accounting(tmp_path, monkeypatch):
    """Acceptance (b): accounting on, a block+streaming run still flushes
    once per block boundary (plus the documented fixed points) — timeline
    records join the existing batch, never add a flush."""
    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic

    flushes = []
    real_flush = Recorder.flush

    def counting_flush(self):
        if self.path is not None:
            flushes.append(len(self._pending))
        return real_flush(self)

    monkeypatch.setattr(Recorder, "flush", counting_flush)
    ds = Synthetic(num_clients=6, train_size=240, test_size=60, cache=False)
    log = str(tmp_path / "out")
    sim = Simulator(ds, log_path=log, seed=0, aggregator="median")
    sim.run("mlp", global_rounds=4, local_steps=1, train_batch_size=8,
            validate_interval=4, streaming=True, client_chunks=3,
            block_size=2)
    assert sim.telemetry.dropped == 0
    # same bound as the pre-accounting flush-discipline pin
    # (tests/test_telemetry.py): meta + 2 block boundaries + run_end
    # (+ at most one recorder-swap flush)
    assert len(flushes) <= 5
    recs = load_records(os.path.join(log, "telemetry.jsonl"))
    assert len([r for r in recs if r["t"] == "timeline"]) == 2


def test_telemetry_off_is_noop_with_zero_added_compiles(tmp_path, monkeypatch):
    """Acceptance (c): BLADES_TELEMETRY=0 is a true no-op — no trace, no
    accumulator state — and the accounting adds ZERO compiles: pinned at
    the engine level (the test_metric_pack discipline) by compiling the
    SAME round program with accounting active vs disabled and asserting
    equal compile counts, with warm re-runs adding zero either way."""
    import jax
    import numpy as np

    from blades_tpu.aggregators import get_aggregator
    from blades_tpu.core import RoundEngine
    from blades_tpu.datasets.fl import FLDataset
    from blades_tpu.models.common import build_fns
    from blades_tpu.models.mlp import MLP
    from blades_tpu.telemetry.recorder import (
        install_jax_monitoring,
        process_counters,
    )

    assert install_jax_monitoring()
    rng = np.random.RandomState(0)
    k, samples, dimx = 6, 24, 8
    ds = FLDataset(
        rng.randn(k, samples, dimx).astype(np.float32),
        rng.randint(0, 2, (k, samples)).astype(np.int32),
        np.full(k, samples, np.int32),
        rng.randn(samples, dimx).astype(np.float32),
        rng.randint(0, 2, samples).astype(np.int32),
    )
    spec = build_fns(MLP(hidden=(8,), num_classes=2), sample_shape=(dimx,))
    params = spec.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(3)
    cx, cy = ds.sample_round(jax.random.fold_in(key, 1), 1, 4)

    def compiles():
        return process_counters().get("xla.compiles", 0)

    def one_engine_two_rounds():
        eng = RoundEngine(
            spec.train_loss_fn, spec.eval_logits_fn, params,
            num_clients=k, aggregator=get_aggregator("mean"), num_classes=2,
            keep_updates=False,
        )
        st = eng.init(params)
        before = compiles()
        st, _ = eng.run_round(st, cx, cy, 0.2, 1.0, key)
        first = compiles() - before
        before = compiles()
        st, _ = eng.run_round(st, cx, cy, 0.2, 1.0, key)
        return first, compiles() - before

    # accounting ACTIVE: an enabled recorder makes every run_round open a
    # launch window
    set_recorder(Recorder(enabled=True))
    on_first, on_rerun = one_engine_two_rounds()
    assert on_rerun == 0  # warm re-dispatch retraces nothing

    # accounting DISABLED (BLADES_TELEMETRY=0 path: recorder disabled)
    monkeypatch.setenv("BLADES_TELEMETRY", "0")
    set_recorder(Recorder())  # env-resolved: disabled
    timeline.reset()
    off_first, off_rerun = one_engine_two_rounds()
    assert off_rerun == 0
    # host-side accounting cannot change what compiles: same program count
    assert on_first == off_first
    assert timeline._acc == {} and timeline._open_launch is None


# ------------------------------------------------------------ sweep accounting


def test_sweep_accounting_records_progress_and_flushes(tmp_path, monkeypatch):
    """Per-cell records carry i-of-N/ETA/splits, validate against the
    schema, and each cell boundary performs one flush (file grows) and
    one heartbeat beat — the supervised-sweep liveness satellite."""
    from blades_tpu.supervision import heartbeat as hb

    hb_file = str(tmp_path / "hb")
    monkeypatch.setenv(hb.HEARTBEAT_ENV, hb_file)
    monkeypatch.setattr(hb, "_last_beat_ts", None)
    trace = str(tmp_path / "sweep_trace.jsonl")
    sw = timeline.SweepAccounting("unit", total=3, path=trace)
    sizes = []
    for i in range(3):
        with sw.cell(f"cell{i}"):
            pass
        sizes.append(os.path.getsize(trace))
        # the heartbeat file was touched at THIS cell boundary and carries
        # the cell index — a short-timeout supervisor watching the sweep
        # sees progress every cell, not every Simulator flush
        body = hb.read(hb_file)
        assert body is not None and body["round"] == i + 1
    assert sizes == sorted(sizes) and sizes[0] < sizes[1] < sizes[2]
    sw.close()
    records = load_records(trace)
    cells = [r for r in records if r["t"] == "sweep"]
    assert [c["i"] for c in cells] == [1, 2, 3]
    assert all(c["total"] == 3 and c["sweep"] == "unit" for c in cells)
    assert cells[-1]["eta_s"] == 0.0
    assert all(c["wall_s"] >= c["execute_s"] >= 0.0 for c in cells)
    assert validate_records(records, load_schema()) == []
    assert sw.summary()["cells"] == 3


def test_sweep_cell_error_is_recorded_and_reraised(tmp_path):
    trace = str(tmp_path / "t.jsonl")
    sw = timeline.SweepAccounting("unit", total=1, path=trace)
    with pytest.raises(RuntimeError, match="boom"):
        with sw.cell("bad"):
            raise RuntimeError("boom")
    sw.close()
    cells = [r for r in load_records(trace) if r["t"] == "sweep"]
    assert cells[0]["ok"] is False and "boom" in cells[0]["error"]
    assert validate_records(cells, load_schema()) == []


def test_certify_slice_writes_schema_valid_sweep_trace(tmp_path, capsys,
                                                      monkeypatch):
    """Satellite (schema v3): a REAL sweep trace — a tiny in-process
    certify run — validates against the committed schema, carries both
    the driver's cells (i-of-N complete) and the attack_search sub-cells,
    and is summarized by sweep_status.py (one JSON line)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "certify_for_timeline", os.path.join(REPO, "scripts", "certify.py"))
    certify = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(certify)
    monkeypatch.setattr(sys, "argv", [
        "certify.py", "--quick", "--aggs", "mean",
        "--clients", "6", "--dim", "8", "--trials", "1", "--no-async",
        "--out", str(tmp_path / "cert"),
    ])
    rc = certify.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert rc == 0 and len(out) == 1
    payload = json.loads(out[0])
    trace = str(tmp_path / "cert" / "sweep_trace.jsonl")
    assert payload["sweep_cells"] == 4  # battery + f0..f2
    assert os.path.exists(trace)
    records = load_records(trace)
    assert validate_records(records, load_schema()) == []
    fams = {r.get("sweep") for r in records if r["t"] == "sweep"}
    assert fams == {"certify", "attack_search"}
    drv = [r for r in records
           if r["t"] == "sweep" and r.get("sweep") == "certify"]
    assert [c["i"] for c in drv] == [1, 2, 3, 4]
    assert all(c["total"] == 4 for c in drv)

    import sweep_status

    assert sweep_status.main([trace]) == 0
    status = json.loads(capsys.readouterr().out.strip())
    assert status["ok"] is True
    cert = status["sweeps"]["certify"]
    assert cert["cells"] == 4 and cert["total"] == 4 and cert["frac"] == 1.0
    assert cert["per_cell_overhead_s"] >= 0.0
    assert "last_cell" in cert and "last_cell_age_s" in cert
    # directory form resolves <dir>/sweep_trace.jsonl
    assert sweep_status.main([str(tmp_path / "cert")]) == 0
    capsys.readouterr()


def test_sweep_status_error_path_one_json_line(tmp_path, capsys):
    import sweep_status

    rc = sweep_status.main([str(tmp_path / "nope.jsonl")])
    out = capsys.readouterr().out.strip().splitlines()
    assert rc == 1 and len(out) == 1
    payload = json.loads(out[0])
    assert payload["ok"] is False and "error" in payload


# ------------------------------------------------------- consumer surfaces


def test_trace_summary_dispatch_and_sweep_sections(tmp_path, capsys):
    """trace_summary grows the dispatch-accounting rollup: per-kind
    enqueue/ready splits, the overall dispatch share, and per-sweep-family
    cell costs — table, JSON, and --compare forms."""
    import trace_summary

    def mk(path, enq, rdy):
        rec = Recorder(enabled=True, path=path)
        rec.event("timeline", kind="round", launches=2, rounds=2,
                  enqueue_s=enq, ready_s=rdy,
                  dispatch_share=enq / (enq + rdy), compile_s=0.5, compiles=1)
        rec.event("sweep", sweep="certify", cell="mean/f0", wall_s=1.0,
                  execute_s=0.25, compile_s=0.6, i=1, total=4, eta_s=3.0)
        rec.round_record(1, wall_s=0.2)
        rec.round_record(2, wall_s=0.2)
        rec.close()

    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    mk(a, 0.8, 0.2)
    mk(b, 0.2, 0.8)
    sa = trace_summary.summarize(trace_summary.load_records(a))
    assert sa["dispatch"]["dispatch_share"] == pytest.approx(0.8)
    assert sa["dispatch"]["by_kind"]["round"]["launches"] == 2
    assert sa["sweep"]["certify"]["cells"] == 1
    assert sa["sweep"]["certify"]["per_cell_overhead_s"] == pytest.approx(0.75)
    table = trace_summary.format_table(sa)
    assert "dispatch accounting" in table and "sweep[certify]" in table
    assert trace_summary.main(["--compare", a, b]) == 0
    out = capsys.readouterr().out
    assert "dispatch_share" in out and "sweep[certify] overhead" in out


def test_runs_cli_surfaces_sweep_progress(tmp_path, capsys, monkeypatch):
    """Satellite: `runs.py --run-id` on a sweep run reports cells
    completed/total and the last cell's key/timestamp from the sweep
    records reached via the run's registered trace artifact."""
    import runs as runs_cli

    from blades_tpu.telemetry import context as _context
    from blades_tpu.telemetry import ledger as _ledger

    ledger = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv(_ledger.LEDGER_ENV, ledger)
    monkeypatch.setenv(_context.RUN_ID_ENV, "testsweep-1")
    monkeypatch.setenv(_context.ATTEMPT_ENV, "1")
    trace = str(tmp_path / "sweep_trace.jsonl")
    entry = _ledger.run_started("certify", config={"kind": "certify"},
                                artifacts=[trace])
    sw = timeline.SweepAccounting("certify", total=5, path=trace)
    for i in range(3):
        with sw.cell(f"agg/f{i}"):
            # library-level sub-cells share the trace (certify's real
            # traces interleave one `attack_search` record per cell);
            # they carry no i-of-N marker and must NOT inflate progress
            timeline.sweep_cell_event(
                "attack_search", f"f{i}/k6", 0.1, {}, rec=sw.rec,
            )
    sw.close()
    entry.ended("finished", artifacts=[trace])  # duplicate registration
    assert runs_cli.main(["--run-id", "testsweep-1"]) == 0
    payload = json.loads(capsys.readouterr().out.strip())
    assert payload["found"] is True
    prog = payload["sweep_progress"]
    # 3 driver cells — not 6 (sub-cells) and not doubled by the repeated
    # artifact registration (max i, not record count)
    assert prog["cells_completed"] == 3 and prog["total"] == 5
    assert prog["last_cell"] == "agg/f2" and prog["frac"] == 0.6
    assert "last_cell_age_s" in prog


def test_perf_report_ingests_dispatch_rows_and_gates(tmp_path, capsys):
    """Acceptance: perf_report derives the dispatch metrics from
    results/dispatch-style rows, passes against a matching baseline, and
    FAILS --check on a synthetic dispatch-share / per-cell-overhead
    regression."""
    import perf_report

    repo = tmp_path / "repo"
    disp = repo / "results" / "dispatch"
    disp.mkdir(parents=True)
    rows = [
        {"name": "k100_stream", "clients": 100, "streaming": True,
         "rounds_per_sec": 2.0, "dispatch_share": 0.6,
         "enqueue_s_per_round": 0.3, "ready_s_per_round": 0.2},
        {"name": "k10000_stream", "clients": 10000, "streaming": True,
         "rounds_per_sec": 0.2, "dispatch_share": 0.8,
         "enqueue_s_per_round": 4.0, "ready_s_per_round": 1.0},
        {"name": "cert_slice", "value": 0.5, "cells": 8,
         "mean_cell_s": 0.5, "per_cell_overhead_s": 0.4},
    ]
    with open(disp / "rows.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    report = perf_report.build_report(str(repo), [])
    derived = report["derived"]
    assert derived["dispatch_share_top_k"] == 0.8
    assert derived["sweep_per_cell_overhead_s"] == 0.4
    assert [r["clients"] for r in derived["dispatch_ladder"]] == [100, 10000]
    md = perf_report.markdown_table(report["rows"], derived)
    assert "Dispatch accounting" in md and "dispatch share" in md

    # matching baseline: green
    baseline = {
        "thresholds": perf_report.DEFAULT_THRESHOLDS,
        "rows": {
            "dispatch/k10000_stream": {"rounds_per_sec": 0.2,
                                       "dispatch_share": 0.8},
            "dispatch/cert_slice": {"per_cell_overhead_s": 0.4},
        },
    }
    assert perf_report.check_regressions(
        report["rows"], derived, baseline) == []
    # synthetic regression: share creeps past the absolute threshold,
    # overhead past its fraction
    tight = json.loads(json.dumps(baseline))
    tight["rows"]["dispatch/k10000_stream"]["dispatch_share"] = 0.6
    tight["rows"]["dispatch/cert_slice"]["per_cell_overhead_s"] = 0.2
    regs = perf_report.check_regressions(report["rows"], derived, tight)
    assert len(regs) == 2
    assert any("dispatch_share" in r for r in regs)
    assert any("per_cell_overhead_s" in r for r in regs)


def test_committed_dispatch_baseline_is_gated():
    """The committed measured baseline exists, carries the K-ladder +
    cert-slice rows with real splits, and the committed perf baseline
    gates them (the --check green acceptance is pinned by
    tests/test_perf_report.py's pass-on-committed test)."""
    rows_path = os.path.join(REPO, "results", "dispatch", "rows.jsonl")
    assert os.path.exists(rows_path), "results/dispatch/rows.jsonl missing"
    rows = [json.loads(l) for l in open(rows_path) if l.strip()]
    by_name = {r["name"]: r for r in rows}
    for name in ("k100_stream", "k1000_stream", "k10000_stream"):
        r = by_name[name]
        assert 0.0 < r["dispatch_share"] <= 1.0
        assert r["enqueue_s_per_round"] > 0.0
        assert r["streaming"] is True
    assert by_name["cert_slice"]["per_cell_overhead_s"] > 0.0
    baseline = json.load(
        open(os.path.join(REPO, "results", "perf_report", "baseline.json"))
    )
    gated = baseline["rows"]
    assert gated["dispatch/k10000_stream"]["dispatch_share"] == pytest.approx(
        by_name["k10000_stream"]["dispatch_share"]
    )
    assert gated["dispatch/cert_slice"]["per_cell_overhead_s"] > 0.0
    assert "dispatch_share_abs" in baseline["thresholds"]
    assert "per_cell_overhead_frac" in baseline["thresholds"]
