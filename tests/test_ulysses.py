"""Ulysses all-to-all attention vs full-softmax oracle on the 8-device
CPU mesh (sibling of test_ring_attention.py — same contract, different
collective schedule)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from blades_tpu.ops.ring_attention import attention_reference
from blades_tpu.ops.ulysses import ulysses_attention

SEQ = "seq"


def _mesh():
    return Mesh(np.array(jax.devices()), (SEQ,))


def _qkv(key, b=2, n=64, h=8, d=16):
    ks = jax.random.split(key, 3)
    shape = (b, n, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


def test_matches_full_attention():
    mesh = _mesh()
    q, k, v = _qkv(jax.random.PRNGKey(0))
    out = ulysses_attention(q, k, v, mesh, SEQ)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_matches_full_attention_with_mask():
    mesh = _mesh()
    q, k, v = _qkv(jax.random.PRNGKey(1), b=3, n=32)
    lens = jnp.array([[5], [32], [17]])
    mask = jnp.arange(32)[None, :] < lens
    out = ulysses_attention(q, k, v, mesh, SEQ, kv_mask=mask)
    ref = attention_reference(q, k, v, kv_mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_sharded_inputs_stay_sharded():
    mesh = _mesh()
    q, k, v = _qkv(jax.random.PRNGKey(2), n=128)
    spec = NamedSharding(mesh, P(None, SEQ, None, None))
    q, k, v = (jax.device_put(t, spec) for t in (q, k, v))
    out = jax.jit(
        lambda a, b_, c: ulysses_attention(a, b_, c, mesh, SEQ)
    )(q, k, v)
    assert out.sharding.spec == spec.spec
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_gradients_flow():
    mesh = _mesh()
    q, k, v = _qkv(jax.random.PRNGKey(3), n=16)

    def loss_uly(q_, k_, v_):
        return jnp.sum(ulysses_attention(q_, k_, v_, mesh, SEQ) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(attention_reference(q_, k_, v_) ** 2)

    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_uly, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_rejects_indivisible_heads():
    mesh = _mesh()
    q, k, v = _qkv(jax.random.PRNGKey(4), h=6)  # 6 % 8 != 0
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, mesh, SEQ)


def test_long_text_transformer_consumes_ulysses():
    """seq_parallel='ulysses' routes the long-context model through the
    all-to-all path and matches the dense model's logits."""
    from blades_tpu.models import long_text_transformer

    mesh = _mesh()
    # ulysses needs heads % axis size == 0: 8 heads over 8 devices, and the
    # tokenizer-free width (word_embedding_dim) must be head-divisible
    kw = dict(num_classes=4, num_heads=8, word_embedding_dim=128)
    model_uly = long_text_transformer(
        mesh=mesh, seq_parallel="ulysses", **kw
    )
    model_full = long_text_transformer(mesh=None, **kw)

    key = jax.random.PRNGKey(3)
    tokens = jax.random.randint(key, (2, 64), 0, 1000)
    lens = jnp.array([[40], [64]])
    mask = jnp.arange(64)[None, :] < lens

    params = model_full.init(jax.random.PRNGKey(0), tokens, mask)
    out_full = model_full.apply(params, tokens, mask)
    out_uly = model_uly.apply(params, tokens, mask)
    assert out_uly.shape == (2, 4)
    np.testing.assert_allclose(
        np.asarray(out_uly), np.asarray(out_full), atol=3e-5
    )
