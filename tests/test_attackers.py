"""Attack-suite unit tests against the reference's closed-form semantics
(src/blades/attackers/*.py; see SURVEY.md section 4 — the reference has no
tests, so expectations come from the attack definitions themselves)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.stats import norm

from blades_tpu.attackers import ATTACKS, get_attack
from blades_tpu.attackers.base import NoAttack, honest_stats

K, D, F = 10, 6, 3
KEY = jax.random.PRNGKey(0)


@pytest.fixture
def updates():
    return jax.random.normal(jax.random.PRNGKey(1), (K, D))


@pytest.fixture
def byz_mask():
    return jnp.arange(K) < F


def test_registry_names():
    # reference ships these five (simulator.py:30-32)
    for name in ["noise", "labelflipping", "signflipping", "alie", "ipm"]:
        assert name in ATTACKS


def test_noattack_identity(updates, byz_mask):
    out, _ = NoAttack().on_updates(updates, byz_mask, KEY)
    np.testing.assert_array_equal(out, updates)


def test_noise_replaces_only_byzantine_rows(updates, byz_mask):
    out, _ = get_attack("noise", mean=0.1, std=0.1).on_updates(updates, byz_mask, KEY)
    np.testing.assert_array_equal(out[F:], updates[F:])
    assert not np.allclose(out[:F], updates[:F])
    # large-sample moments: N(0.1, 0.1) (noiseclient.py:22-25)
    big, _ = get_attack("noise").on_updates(
        jnp.zeros((4, 20000)), jnp.ones(4, bool), KEY
    )
    assert abs(float(big.mean()) - 0.1) < 0.01
    assert abs(float(big.std()) - 0.1) < 0.01


def test_ipm_closed_form(updates, byz_mask):
    eps = 0.5
    out, _ = get_attack("ipm", epsilon=eps).on_updates(updates, byz_mask, KEY)
    honest_mean = updates[F:].mean(axis=0)
    np.testing.assert_allclose(out[:F], jnp.tile(-eps * honest_mean, (F, 1)), rtol=1e-5)
    np.testing.assert_array_equal(out[F:], updates[F:])


def test_alie_closed_form(updates, byz_mask):
    atk = get_attack("alie", num_clients=K, num_byzantine=F)
    out, _ = atk.on_updates(updates, byz_mask, KEY)
    honest = np.asarray(updates[F:])
    mu = honest.mean(axis=0)
    std = honest.std(axis=0, ddof=1)  # torch.std is unbiased
    s = np.floor(K / 2 + 1) - F
    z = norm.ppf((K - F - s) / (K - F))
    np.testing.assert_allclose(out[:F], np.tile(mu - z * std, (F, 1)), rtol=1e-4)
    np.testing.assert_array_equal(out[F:], updates[F:])


def test_alie_explicit_z():
    atk = get_attack("alie", num_clients=K, num_byzantine=F, z=1.5)
    assert atk._z_max(K, F) == 1.5


def test_labelflipping_batch_hook():
    atk = get_attack("labelflipping", num_classes=10)
    y = jnp.array([0, 3, 9])
    _, y_byz = atk.on_batch(None, y, jnp.asarray(True), num_classes=10, key=KEY)
    np.testing.assert_array_equal(y_byz, [9, 6, 0])
    _, y_hon = atk.on_batch(None, y, jnp.asarray(False), num_classes=10, key=KEY)
    np.testing.assert_array_equal(y_hon, y)


def test_signflipping_grad_hook():
    atk = get_attack("signflipping")
    grads = {"w": jnp.ones((2, 2)), "b": -jnp.ones(2)}
    flipped = atk.on_grads(grads, jnp.asarray(True))
    np.testing.assert_array_equal(flipped["w"], -jnp.ones((2, 2)))
    kept = atk.on_grads(grads, jnp.asarray(False))
    np.testing.assert_array_equal(kept["w"], jnp.ones((2, 2)))


def test_minmax_within_envelope(updates, byz_mask):
    out, _ = get_attack("minmax").on_updates(updates, byz_mask, KEY)
    honest = np.asarray(updates[F:])
    mal = np.asarray(out[0])
    max_pair = max(
        np.sum((a - b) ** 2) for a in honest for b in honest
    )
    d = max(np.sum((mal - h) ** 2) for h in honest)
    assert d <= max_pair * 1.05  # bisection tolerance


def test_honest_stats_masking(updates, byz_mask):
    mu, std, n = honest_stats(updates, byz_mask)
    np.testing.assert_allclose(mu, np.asarray(updates[F:]).mean(axis=0), rtol=1e-5)
    np.testing.assert_allclose(
        std, np.asarray(updates[F:]).std(axis=0, ddof=1), rtol=1e-5
    )
    assert float(n) == K - F


def test_attacks_jittable(updates, byz_mask):
    for name in ATTACKS:
        kw = {"num_clients": K, "num_byzantine": F} if name == "alie" else {}
        atk = get_attack(name, **kw)
        out, _ = jax.jit(lambda u, m, k: atk.on_updates(u, m, k, ()))(
            updates, byz_mask, KEY
        )
        assert out.shape == updates.shape
        assert bool(jnp.all(jnp.isfinite(out)))


# ------------------------------------------------- feasibility edge cases
# (blades_tpu/audit rides on these attacks; the search must stay finite on
# degenerate populations — ISSUE 4 satellite)


@pytest.mark.parametrize("name", ["minmax", "minsum"])
def test_gamma_bisection_degenerate_envelope(name, updates):
    """f = K-1 leaves ONE honest client: every honest pairwise distance is
    zero (a degenerate envelope), the honest std is zero, and the bisection
    must converge to gamma ~ 0 — the malicious rows collapse onto the lone
    honest update instead of going NaN."""
    byz = jnp.arange(K) < K - 1
    out, _ = get_attack(name).on_updates(updates, byz, KEY)
    out = np.asarray(out)
    assert np.isfinite(out).all()
    # std over one honest row is 0, so mu + gamma*dev == mu == the honest row
    np.testing.assert_allclose(out[0], np.asarray(updates[-1]), rtol=1e-5)
    np.testing.assert_array_equal(out[-1], np.asarray(updates[-1]))


def test_alie_z_clamp_degenerate_population():
    """f = n-1 pushes the ALIE cdf argument above 1 (s goes negative),
    where norm.ppf returns NaN; the clamp keeps z finite so the attack
    degrades instead of NaN-ing every byzantine row."""
    atk = get_attack("alie", num_clients=K, num_byzantine=K - 1)
    z = atk._z_max(K, K - 1)
    assert np.isfinite(z)
    # and the clamp must NOT touch valid configs whose cdf is legitimately
    # below 0.5 (even n, f=1: cdf = (n/2 - 1)/(n - 1)) — reference parity
    z_small_f = get_attack("alie", num_clients=K, num_byzantine=1)._z_max(K, 1)
    assert z_small_f == pytest.approx(float(norm.ppf(4 / 9)))
    u = jax.random.normal(jax.random.PRNGKey(5), (K, D))
    out, _ = atk.on_updates(u, jnp.arange(K) < K - 1, KEY)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_honest_stats_with_participation_mask(updates, byz_mask):
    """The audit attack search models an adversary that only sees the
    delivered updates: honest stats restricted to a participation mask
    must match numpy over the honest & participating subset."""
    part = jnp.asarray([True, True, False, True, True, False, True, True,
                        True, False])
    mu, std, n = honest_stats(updates, byz_mask, part)
    rows = np.asarray(updates)[np.asarray(~byz_mask & part)]
    np.testing.assert_allclose(mu, rows.mean(axis=0), rtol=1e-5)
    np.testing.assert_allclose(std, rows.std(axis=0, ddof=1), rtol=1e-5)
    assert float(n) == len(rows)


def test_honest_stats_zero_honest_participants_finite(updates, byz_mask):
    """All honest clients masked out: the stats collapse to zero instead of
    0/0 NaN (the attack search's degenerate-participation guard)."""
    part = jnp.asarray(byz_mask)  # only byzantine rows delivered
    mu, std, n = honest_stats(updates, byz_mask, part)
    np.testing.assert_array_equal(np.asarray(mu), np.zeros(D, np.float32))
    assert bool(jnp.all(jnp.isfinite(std)))


@pytest.mark.parametrize("template", ["ipm", "alie"])
def test_audit_templates_under_masked_honest_set(template, updates, byz_mask):
    """ALIE/IPM audit templates under partial participation: byzantine rows
    are built from the PARTICIPATING honest moments only."""
    from blades_tpu.audit.attack_search import alie_rows, ipm_rows

    part = jnp.asarray([True] * 5 + [False] * 5)
    fn = {"ipm": lambda: ipm_rows(updates, byz_mask, 2.0, part),
          "alie": lambda: alie_rows(updates, byz_mask, 1.5, part)}[template]
    out = np.asarray(fn())
    assert np.isfinite(out).all()
    rows = np.asarray(updates)[np.asarray(~byz_mask & part)]
    mu, std = rows.mean(axis=0), rows.std(axis=0, ddof=1)
    expect = -2.0 * mu if template == "ipm" else mu - 1.5 * std
    np.testing.assert_allclose(out[0], expect, rtol=1e-4, atol=1e-6)
    # honest rows untouched
    np.testing.assert_array_equal(out[F:], np.asarray(updates[F:]))
