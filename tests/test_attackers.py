"""Attack-suite unit tests against the reference's closed-form semantics
(src/blades/attackers/*.py; see SURVEY.md section 4 — the reference has no
tests, so expectations come from the attack definitions themselves)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.stats import norm

from blades_tpu.attackers import ATTACKS, get_attack
from blades_tpu.attackers.base import NoAttack, honest_stats

K, D, F = 10, 6, 3
KEY = jax.random.PRNGKey(0)


@pytest.fixture
def updates():
    return jax.random.normal(jax.random.PRNGKey(1), (K, D))


@pytest.fixture
def byz_mask():
    return jnp.arange(K) < F


def test_registry_names():
    # reference ships these five (simulator.py:30-32)
    for name in ["noise", "labelflipping", "signflipping", "alie", "ipm"]:
        assert name in ATTACKS


def test_noattack_identity(updates, byz_mask):
    out, _ = NoAttack().on_updates(updates, byz_mask, KEY)
    np.testing.assert_array_equal(out, updates)


def test_noise_replaces_only_byzantine_rows(updates, byz_mask):
    out, _ = get_attack("noise", mean=0.1, std=0.1).on_updates(updates, byz_mask, KEY)
    np.testing.assert_array_equal(out[F:], updates[F:])
    assert not np.allclose(out[:F], updates[:F])
    # large-sample moments: N(0.1, 0.1) (noiseclient.py:22-25)
    big, _ = get_attack("noise").on_updates(
        jnp.zeros((4, 20000)), jnp.ones(4, bool), KEY
    )
    assert abs(float(big.mean()) - 0.1) < 0.01
    assert abs(float(big.std()) - 0.1) < 0.01


def test_ipm_closed_form(updates, byz_mask):
    eps = 0.5
    out, _ = get_attack("ipm", epsilon=eps).on_updates(updates, byz_mask, KEY)
    honest_mean = updates[F:].mean(axis=0)
    np.testing.assert_allclose(out[:F], jnp.tile(-eps * honest_mean, (F, 1)), rtol=1e-5)
    np.testing.assert_array_equal(out[F:], updates[F:])


def test_alie_closed_form(updates, byz_mask):
    atk = get_attack("alie", num_clients=K, num_byzantine=F)
    out, _ = atk.on_updates(updates, byz_mask, KEY)
    honest = np.asarray(updates[F:])
    mu = honest.mean(axis=0)
    std = honest.std(axis=0, ddof=1)  # torch.std is unbiased
    s = np.floor(K / 2 + 1) - F
    z = norm.ppf((K - F - s) / (K - F))
    np.testing.assert_allclose(out[:F], np.tile(mu - z * std, (F, 1)), rtol=1e-4)
    np.testing.assert_array_equal(out[F:], updates[F:])


def test_alie_explicit_z():
    atk = get_attack("alie", num_clients=K, num_byzantine=F, z=1.5)
    assert atk._z_max(K, F) == 1.5


def test_labelflipping_batch_hook():
    atk = get_attack("labelflipping", num_classes=10)
    y = jnp.array([0, 3, 9])
    _, y_byz = atk.on_batch(None, y, jnp.asarray(True), num_classes=10, key=KEY)
    np.testing.assert_array_equal(y_byz, [9, 6, 0])
    _, y_hon = atk.on_batch(None, y, jnp.asarray(False), num_classes=10, key=KEY)
    np.testing.assert_array_equal(y_hon, y)


def test_signflipping_grad_hook():
    atk = get_attack("signflipping")
    grads = {"w": jnp.ones((2, 2)), "b": -jnp.ones(2)}
    flipped = atk.on_grads(grads, jnp.asarray(True))
    np.testing.assert_array_equal(flipped["w"], -jnp.ones((2, 2)))
    kept = atk.on_grads(grads, jnp.asarray(False))
    np.testing.assert_array_equal(kept["w"], jnp.ones((2, 2)))


def test_minmax_within_envelope(updates, byz_mask):
    out, _ = get_attack("minmax").on_updates(updates, byz_mask, KEY)
    honest = np.asarray(updates[F:])
    mal = np.asarray(out[0])
    max_pair = max(
        np.sum((a - b) ** 2) for a in honest for b in honest
    )
    d = max(np.sum((mal - h) ** 2) for h in honest)
    assert d <= max_pair * 1.05  # bisection tolerance


def test_honest_stats_masking(updates, byz_mask):
    mu, std, n = honest_stats(updates, byz_mask)
    np.testing.assert_allclose(mu, np.asarray(updates[F:]).mean(axis=0), rtol=1e-5)
    np.testing.assert_allclose(
        std, np.asarray(updates[F:]).std(axis=0, ddof=1), rtol=1e-5
    )
    assert float(n) == K - F


def test_attacks_jittable(updates, byz_mask):
    for name in ATTACKS:
        kw = {"num_clients": K, "num_byzantine": F} if name == "alie" else {}
        atk = get_attack(name, **kw)
        out, _ = jax.jit(lambda u, m, k: atk.on_updates(u, m, k, ()))(
            updates, byz_mask, KEY
        )
        assert out.shape == updates.shape
        assert bool(jnp.all(jnp.isfinite(out)))
