"""Warm-program sweep serving tests (blades_tpu/sweeps + the batched
certify driver): grouping correctness (different program shapes NEVER
silently batch), batched == sequential bit-identity, batch-stamped sweep
records, the engine cache, and the batched status rollups."""

import argparse
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.aggregators import get_aggregator
from blades_tpu.audit import (
    QUICK_GRIDS,
    battery_ctx,
    search_cell,
    synthetic_honest,
)
from blades_tpu.audit.attack_search import search_cells
from blades_tpu.sweeps import (
    EngineCache,
    SweepCell,
    group_key,
    plan_groups,
    program_fingerprint,
    run_grouped,
    static_fingerprint,
)

K, D, T = 6, 8, 2


@pytest.fixture(scope="module")
def trials():
    return synthetic_honest(jax.random.PRNGKey(0), T, K, D)


@pytest.fixture(scope="module")
def ctx():
    return battery_ctx(None, K, D, key=jax.random.PRNGKey(3))


# -- fingerprints / grouping ---------------------------------------------------


def test_static_fingerprint_separates_aggregator_configs():
    """Every constructor attribute participates by VALUE: an f-clamped
    defense at a different f is a different program shape."""
    a = static_fingerprint(get_aggregator("trimmedmean", num_byzantine=1))
    b = static_fingerprint(get_aggregator("trimmedmean", num_byzantine=2))
    c = static_fingerprint(get_aggregator("trimmedmean", num_byzantine=1))
    assert a == c
    assert a != b
    # distinct classes never collide, even with empty attr dicts
    assert static_fingerprint(get_aggregator("mean")) != static_fingerprint(
        get_aggregator("median")
    )


def test_static_fingerprint_arrays_by_value():
    x = np.arange(4, dtype=np.float32)
    y = np.arange(4, dtype=np.float32)
    z = y + 1
    assert static_fingerprint(x) == static_fingerprint(y)
    assert static_fingerprint(x) != static_fingerprint(z)


def test_fault_model_fingerprint_collapses_traced_fill():
    """NaN and Inf value-corruption configs are ONE program (the fill is a
    traced state leaf) — and bitflip is not, and an unconfigured
    corruption keeps its literal mode (the fill stays a compiled
    constant there)."""
    from blades_tpu.faults import FaultModel

    nan = FaultModel(corrupt_clients=(1,), corrupt_mode="nan")
    inf = FaultModel(corrupt_clients=(1,), corrupt_mode="inf")
    bit = FaultModel(corrupt_clients=(1,), corrupt_mode="bitflip")
    assert static_fingerprint(nan) == static_fingerprint(inf)
    assert static_fingerprint(nan) != static_fingerprint(bit)
    # no corruption configured -> mode stays literal (all-False mask,
    # constant fill: programs differ, and neither is ever exercised)
    off_nan = FaultModel(dropout_rate=0.3, corrupt_mode="nan")
    off_inf = FaultModel(dropout_rate=0.3, corrupt_mode="inf")
    assert static_fingerprint(off_nan) != static_fingerprint(off_inf)


def test_plan_groups_never_mixes_program_shapes(trials, ctx):
    """Cells with different K, different f-clamps (static aggregator
    kwargs), different context structure, or different part-mask presence
    land in different groups — grouping is by program shape, not by
    label."""
    small = synthetic_honest(jax.random.PRNGKey(1), T, 4, D)
    cells = [
        SweepCell("tm1/f1", get_aggregator("trimmedmean", num_byzantine=1),
                  trials, 1, ctx),
        SweepCell("tm1/f2", get_aggregator("trimmedmean", num_byzantine=1),
                  trials, 2, ctx),
        SweepCell("tm2", get_aggregator("trimmedmean", num_byzantine=2),
                  trials, 2, ctx),
        SweepCell("k4", get_aggregator("trimmedmean", num_byzantine=1),
                  small, 1, battery_ctx(None, 4, D)),
        SweepCell("masked", get_aggregator("trimmedmean", num_byzantine=1),
                  trials, 1, ctx, part_mask=jnp.ones(K, bool)),
        SweepCell("noctx", get_aggregator("trimmedmean", num_byzantine=1),
                  trials, 1, {}),
    ]
    groups = plan_groups(cells)
    assert [idx for _, idx in groups] == [[0, 1], [2], [3], [4], [5]]
    # stateful defenses with different hyperparams separate too
    s1 = SweepCell("cc1", get_aggregator("centeredclipping", tau=1.0),
                   trials, 1, ctx)
    s2 = SweepCell("cc2", get_aggregator("centeredclipping", tau=2.0),
                   trials, 1, ctx)
    assert group_key(s1) != group_key(s2)


def test_search_cells_rejects_mixed_shapes(trials, ctx):
    agg = get_aggregator("median")
    small = synthetic_honest(jax.random.PRNGKey(1), T, 4, D)
    with pytest.raises(ValueError, match="trial shape"):
        search_cells(agg, [
            dict(trials=trials, f=1, ctx=ctx, part_mask=None, label="a"),
            dict(trials=small, f=1, ctx=ctx, part_mask=None, label="b"),
        ], grids=QUICK_GRIDS)
    with pytest.raises(ValueError, match="part-mask"):
        search_cells(agg, [
            dict(trials=trials, f=1, ctx=ctx, part_mask=None, label="a"),
            dict(trials=trials, f=1, ctx=ctx,
                 part_mask=jnp.ones(K, bool), label="b"),
        ], grids=QUICK_GRIDS)


# -- batched == sequential -----------------------------------------------------


def test_batched_cells_bit_identical_to_sequential(trials, ctx):
    """The serving contract: one grouped program produces the exact dicts
    the per-cell programs produce, in input order."""
    agg = get_aggregator("median")
    cells = [
        dict(trials=trials, f=f, ctx=ctx, part_mask=None, label=f"f{f}")
        for f in range(3)
    ]
    batched = search_cells(agg, cells, grids=QUICK_GRIDS, use_jit=True)
    for f in range(3):
        solo = search_cell(agg, trials, f, ctx=ctx, grids=QUICK_GRIDS,
                           use_jit=True)
        assert batched[f] == solo


def test_run_grouped_returns_input_order_and_walls(trials, ctx):
    cells = [
        SweepCell("m/f1", get_aggregator("median"), trials, 1, ctx),
        SweepCell("tm/f1", get_aggregator("trimmedmean", num_byzantine=1),
                  trials, 1, ctx),
        SweepCell("m/f2", get_aggregator("median"), trials, 2, ctx),
    ]
    results, walls = run_grouped(cells, grids=QUICK_GRIDS, use_jit=True,
                                 return_walls=True)
    assert len(results) == len(walls) == 3
    assert results[0] == search_cell(cells[0].agg, trials, 1, ctx=ctx,
                                     grids=QUICK_GRIDS, use_jit=True)
    assert results[2] == search_cell(cells[2].agg, trials, 2, ctx=ctx,
                                     grids=QUICK_GRIDS, use_jit=True)
    assert all(w > 0 for w in walls)
    # grouped cells share one wall: the median pair split one group
    assert walls[0] == walls[2]


def test_batched_certify_slice_matches_sequential(tmp_path):
    """End-to-end: the batched certify driver produces a bit-identical
    matrix to the sequential path (timing fields stripped) on a mixed
    slice with staleness columns, and reports itself as batched."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import certify

    def mkargs(sequential):
        return argparse.Namespace(
            clients=6, dim=8, trials=2, seed=0, c=None,
            aggs=["mean", "median"], quick=True, no_async=False,
            tau_max=2, no_jit=False, sequential=sequential,
            out=str(tmp_path),
        )

    seq = certify.certify_matrix(mkargs(True))
    bat = certify.certify_matrix(mkargs(False))
    assert seq["batched"] is False and bat["batched"] is True

    def strip(m):
        m = json.loads(json.dumps(m))
        m.pop("batched")
        for row in m["cells"] + m["async_cells"]:
            row.pop("search_s")
        return m

    assert strip(seq) == strip(bat)


# -- sweep records / rollups ---------------------------------------------------


def test_batched_sweep_records_stamp_batch_and_validate(tmp_path, trials, ctx):
    """Grouped cells emit one schema-valid `sweep` record each, sharing a
    `batch` key with batch_size, amortized walls that sum to the group
    wall, and counters on the first record only."""
    from blades_tpu.telemetry import Recorder, get_recorder, set_recorder
    from blades_tpu.telemetry.schema import validate_trace

    trace = str(tmp_path / "trace.jsonl")
    rec = Recorder(path=trace, enabled=True)
    prev = get_recorder()
    set_recorder(rec)
    try:
        search_cells(get_aggregator("median"), [
            dict(trials=trials, f=f, ctx=ctx, part_mask=None, label=f"f{f}")
            for f in range(3)
        ], grids=QUICK_GRIDS, use_jit=True, batch_label="g1")
    finally:
        set_recorder(prev)
        rec.close()
    records = [json.loads(line) for line in open(trace) if line.strip()]
    sweeps = [r for r in records if r.get("t") == "sweep"]
    assert len(sweeps) == 3
    assert all(r["batch"] == "g1" and r["batch_size"] == 3 for r in sweeps)
    assert {r["cell"] for r in sweeps} == {"f0", "f1", "f2"}
    errors = validate_trace(trace)
    assert not errors, errors


def test_sweep_status_reports_batched_groups():
    """summarize_sweeps counts programs (batches + unbatched cells), not
    cells, for the amortization ratio."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    from sweep_status import summarize_sweeps

    records = [
        {"t": "sweep", "sweep": "certify", "cell": f"c{i}", "wall_s": 1.0,
         "execute_s": 0.5, "ts": 100.0 + i, "i": i + 1, "total": 6,
         "batch": "b1" if i < 4 else None, "batch_size": 4 if i < 4 else None}
        for i in range(6)
    ]
    for r in records:
        if r["batch"] is None:
            r.pop("batch")
            r.pop("batch_size")
    fam = summarize_sweeps(records)["sweeps"]["certify"]
    assert fam["batched_cells"] == 4
    assert fam["batches"] == 1
    # 6 cells over (1 batch + 2 unbatched) = 3 programs
    assert fam["cells_per_program"] == 2.0


def test_runs_sweep_progress_reports_batches():
    import sys
    import time

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    from runs import sweep_progress

    import tempfile

    with tempfile.TemporaryDirectory() as td:
        trace = os.path.join(td, "sweep_trace.jsonl")
        now = time.time()
        with open(trace, "w") as f:
            for i in range(4):
                f.write(json.dumps({
                    "t": "sweep", "sweep": "certify", "cell": f"c{i}",
                    "wall_s": 1.0, "ts": now, "i": i + 1, "total": 4,
                    **({"batch": "g", "batch_size": 3} if i < 3 else {}),
                }) + "\n")
        trail = [{"artifacts": [trace]}]
        out = sweep_progress(trail, repo=td)
    assert out["cells_completed"] == 4
    assert out["batched_cells"] == 3
    assert out["batches"] == 1
    assert out["cells_per_program"] == 2.0


# -- engine cache --------------------------------------------------------------


def test_engine_cache_hits_and_stats():
    cache = EngineCache()
    assert cache.get("k1") is None
    cache.put("k1", "engine")
    assert cache.get("k1") == "engine"
    st = cache.stats()
    assert (st["entries"], st["hits"], st["misses"], st["evictions"]) == (
        1, 1, 1, 0)
    # per-fingerprint stats (compile provenance): the miss seeded the
    # per-key entry, the hit incremented it
    assert st["by_key"]["k1"]["hits"] == 1
    assert st["by_key"]["k1"]["misses"] == 1


def test_program_fingerprint_stable_across_equal_configs():
    from blades_tpu.faults import FaultModel

    a = program_fingerprint(
        model="mlp", fault=FaultModel(corrupt_clients=(0,),
                                      corrupt_mode="nan"),
        agg=get_aggregator("median"),
    )
    b = program_fingerprint(
        model="mlp", fault=FaultModel(corrupt_clients=(0,),
                                      corrupt_mode="inf"),
        agg=get_aggregator("median"),
    )
    c = program_fingerprint(
        model="mlp", fault=FaultModel(corrupt_clients=(1,),
                                      corrupt_mode="nan"),
        agg=get_aggregator("median"),
    )
    assert a == b  # the traced-fill twins: one program
    assert a != c  # victim ids are static constants: different program


def test_simulator_engine_cache_twin_reuse(tmp_path):
    """A Simulator pair differing only in nan<->inf corrupt fill shares
    one warm engine (cache hit) and still lands bit-identical params —
    the chaos inertness contract served from the cache."""
    from blades_tpu.datasets import Synthetic
    from blades_tpu.ops.pytree import ravel
    from blades_tpu.simulator import Simulator

    cache = EngineCache()
    params = {}
    for mode in ("nan", "inf"):
        sim = Simulator(
            dataset=Synthetic(num_clients=K, train_size=80, test_size=20,
                              noise=0.3, cache=False),
            aggregator="median",
            log_path=str(tmp_path / mode),
            seed=5,
        )
        sim.run(
            "mlp", global_rounds=2, local_steps=1, train_batch_size=8,
            client_lr=0.2, validate_interval=3,
            fault_model={"corrupt_clients": [1], "corrupt_mode": mode},
            engine_cache=cache,
        )
        params[mode] = np.asarray(ravel(sim.server.state.params))
    assert cache.stats()["hits"] == 1
    assert cache.stats()["misses"] == 1
    np.testing.assert_array_equal(params["nan"], params["inf"])


# -- the slow e2e: a mixed full slice ------------------------------------------


@pytest.mark.slow
def test_mixed_certify_slice_bit_identical_e2e(tmp_path):
    """ROADMAP item 2's e2e: a mixed batch of sweep requests — stateful
    defenses, f-clamped defenses, a configured variant, staleness
    columns — through the warm-program batched driver returns
    bit-identical JSON to the sequential path."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import certify

    def mkargs(sequential):
        return argparse.Namespace(
            clients=8, dim=16, trials=2, seed=1, c=None,
            aggs=["mean", "median", "trimmedmean", "krum",
                  "centeredclipping", "clustering:distance",
                  "byzantinesgd", "fltrust"],
            quick=True, no_async=False, tau_max=3, no_jit=False,
            sequential=sequential, out=str(tmp_path),
        )

    seq = certify.certify_matrix(mkargs(True))
    bat = certify.certify_matrix(mkargs(False))

    def strip(m):
        m = json.loads(json.dumps(m))
        m.pop("batched")
        for row in m["cells"] + m["async_cells"]:
            row.pop("search_s")
        return m

    assert strip(seq) == strip(bat)
