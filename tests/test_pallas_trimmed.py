"""Pallas trimmed-mean kernel: interpreter-mode validation against the sort
path (the kernel itself runs natively on TPU; CPU CI exercises the identical
logic through the pallas interpreter)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.ops import pallas_trimmed
from blades_tpu.ops.pallas_trimmed import (
    _MAX_UNROLL_B,
    _block_width,
    _pallas_ok,
    trimmed_mean,
)


def _ref(u, b):
    s = np.sort(u, axis=0)
    return s[b : u.shape[0] - b].mean(axis=0)


@pytest.mark.parametrize("k,d,b", [(10, 257, 2), (32, 1000, 5), (9, 64, 1)])
def test_kernel_matches_sort(k, d, b):
    rng = np.random.RandomState(0)
    u = rng.randn(k, d).astype(np.float32) * 10
    out = trimmed_mean(jnp.asarray(u), b, interpret=True)
    np.testing.assert_allclose(np.asarray(out), _ref(u, b), rtol=1e-5, atol=1e-5)


def test_kernel_handles_ties_like_sort():
    # duplicated extrema: dropping one occurrence per extraction == sorting
    u = np.array([[5.0, 1.0], [5.0, 1.0], [0.0, 1.0], [-5.0, 0.0],
                  [-5.0, 0.0], [2.0, 0.5]], np.float32)
    out = trimmed_mean(jnp.asarray(u), 2, interpret=True)
    np.testing.assert_allclose(np.asarray(out), _ref(u, 2), atol=1e-6)


def test_b_zero_is_mean():
    u = np.random.RandomState(1).randn(7, 33).astype(np.float32)
    out = trimmed_mean(jnp.asarray(u), 0)
    # rtol 1e-5, not 1e-6: the XLA lowering is free to reassociate the
    # K-sum, and fp32 summation order drifts ~2e-6 between XLA builds
    np.testing.assert_allclose(np.asarray(out), u.mean(axis=0), rtol=1e-5)


def test_block_width_respects_vmem():
    assert _block_width(1000) * 1000 <= 2_000_000
    assert _block_width(1000) % 128 == 0
    assert _block_width(10) == 4096  # capped


def test_block_width_prefers_1024_multiples():
    # multi-block grids only compile on some Mosaic toolchains when the
    # lane dim is a 1024 multiple; snap whenever the VMEM budget allows
    for k in (10, 100, 400):
        assert _block_width(k) % 1024 == 0
    # k too large for a 1024-wide block: falls back to 128 alignment
    assert _block_width(1000) % 128 == 0


def test_no_pallas_env_disables_kernel(monkeypatch):
    monkeypatch.setenv("BLADES_TPU_NO_PALLAS", "1")
    assert _pallas_ok(16, 256, 2, jnp.float32) is False


def test_probe_failure_warns_and_caches(monkeypatch):
    calls = []

    def boom(*a, **k):
        calls.append(1)
        raise RuntimeError("Mosaic says no")

    monkeypatch.setattr(
        pallas_trimmed._trimmed_mean_pallas, "lower", boom, raising=False
    )
    pallas_trimmed._PROBE_CACHE.clear()
    with pytest.warns(UserWarning, match="falling back to the plain-XLA"):
        assert _pallas_ok(17, 999, 3, jnp.float32) is False
    assert _pallas_ok(17, 999, 3, jnp.float32) is False  # cached: no re-probe
    assert len(calls) == 1
    pallas_trimmed._PROBE_CACHE.clear()


@pytest.mark.parametrize("k,d,b", [(10, 257, 2), (32, 1000, 5), (6, 2, 2)])
def test_extract_path_matches_sort(k, d, b):
    from blades_tpu.ops.pallas_trimmed import _trimmed_mean_extract

    rng = np.random.RandomState(3)
    u = (rng.randn(k, d) * 10).astype(np.float32)
    out = _trimmed_mean_extract(jnp.asarray(u), b)
    np.testing.assert_allclose(np.asarray(out), _ref(u, b), rtol=1e-5, atol=1e-5)


def test_extract_path_handles_ties_and_extremes():
    from blades_tpu.ops.pallas_trimmed import _trimmed_mean_extract

    u = np.array([[5.0, 1.0], [5.0, 1.0], [0.0, 1.0], [-5.0, 0.0],
                  [-5.0, 0.0], [2.0, 0.5]], np.float32)
    out = _trimmed_mean_extract(jnp.asarray(u), 2)
    np.testing.assert_allclose(np.asarray(out), _ref(u, 2), atol=1e-6)
    v = np.random.RandomState(5).randn(10, 33).astype(np.float32)
    v[0], v[1], v[2] = 1e30, -3e38, 3e38
    out = _trimmed_mean_extract(jnp.asarray(v), 3)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), _ref(v, 3), rtol=1e-5, atol=1e-5)


def test_large_b_takes_sort_path_without_probing(monkeypatch):
    """b above the unroll cap must never reach the probe (program size is
    linear in b; a 200-stage kernel compile would be pathological)."""
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")

    def fail(*a, **k):  # pragma: no cover - reached only on regression
        raise AssertionError("probe must not run for b > _MAX_UNROLL_B")

    monkeypatch.setattr(pallas_trimmed, "_pallas_ok", fail)
    k = 3 * _MAX_UNROLL_B
    u = np.random.RandomState(2).randn(k, 64).astype(np.float32)
    out = trimmed_mean(jnp.asarray(u), _MAX_UNROLL_B + 1)
    np.testing.assert_allclose(
        np.asarray(out), _ref(u, _MAX_UNROLL_B + 1), rtol=1e-5, atol=1e-5
    )


def test_byzantine_magnitudes_do_not_poison_arithmetic():
    """Extreme rows (1e30, f32-overflow scale) must be trimmed OUT of the
    arithmetic, not summed and subtracted (catastrophic cancellation)."""
    rng = np.random.RandomState(4)
    u = rng.randn(10, 65).astype(np.float32)
    u[0] = 1e30
    u[1] = -3e38
    u[2] = 3e38  # sum of column would overflow f32
    out = trimmed_mean(jnp.asarray(u), 3, interpret=True)
    expect = _ref(u, 3)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5, atol=1e-5)
