"""Pallas trimmed-mean kernel: interpreter-mode validation against the sort
path (the kernel itself runs natively on TPU; CPU CI exercises the identical
logic through the pallas interpreter)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.ops.pallas_trimmed import trimmed_mean, _block_width


def _ref(u, b):
    s = np.sort(u, axis=0)
    return s[b : u.shape[0] - b].mean(axis=0)


@pytest.mark.parametrize("k,d,b", [(10, 257, 2), (32, 1000, 5), (9, 64, 1)])
def test_kernel_matches_sort(k, d, b):
    rng = np.random.RandomState(0)
    u = rng.randn(k, d).astype(np.float32) * 10
    out = trimmed_mean(jnp.asarray(u), b, interpret=True)
    np.testing.assert_allclose(np.asarray(out), _ref(u, b), rtol=1e-5, atol=1e-5)


def test_kernel_handles_ties_like_sort():
    # duplicated extrema: dropping one occurrence per extraction == sorting
    u = np.array([[5.0, 1.0], [5.0, 1.0], [0.0, 1.0], [-5.0, 0.0],
                  [-5.0, 0.0], [2.0, 0.5]], np.float32)
    out = trimmed_mean(jnp.asarray(u), 2, interpret=True)
    np.testing.assert_allclose(np.asarray(out), _ref(u, 2), atol=1e-6)


def test_b_zero_is_mean():
    u = np.random.RandomState(1).randn(7, 33).astype(np.float32)
    out = trimmed_mean(jnp.asarray(u), 0)
    np.testing.assert_allclose(np.asarray(out), u.mean(axis=0), rtol=1e-6)


def test_block_width_respects_vmem():
    assert _block_width(1000) * 1000 <= 2_000_000
    assert _block_width(1000) % 128 == 0
    assert _block_width(10) == 4096  # capped


def test_byzantine_magnitudes_do_not_poison_arithmetic():
    """Extreme rows (1e30, f32-overflow scale) must be trimmed OUT of the
    arithmetic, not summed and subtracted (catastrophic cancellation)."""
    rng = np.random.RandomState(4)
    u = rng.randn(10, 65).astype(np.float32)
    u[0] = 1e30
    u[1] = -3e38
    u[2] = 3e38  # sum of column would overflow f32
    out = trimmed_mean(jnp.asarray(u), 3, interpret=True)
    expect = _ref(u, 3)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5, atol=1e-5)
