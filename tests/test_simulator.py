"""Simulator facade tests: API parity, logging schema, custom attacks,
trusted clients, schedulers (reference surface: simulator.py:44-187,364-457)."""

import ast
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu import ByzantineClient, Simulator
from blades_tpu.attackers.base import Attack
from blades_tpu.datasets import Synthetic


def _sim(tmp_path, **kw):
    ds = Synthetic(
        num_clients=6, train_size=600, test_size=120, noise=0.3, cache=False
    )
    defaults = dict(log_path=str(tmp_path / "out"), seed=0)
    defaults.update(kw)
    return Simulator(ds, **defaults)


def test_unknown_kwarg_raises(tmp_path):
    with pytest.raises(RuntimeError, match="Unknown keyword"):
        _sim(tmp_path, bogus_flag=1)


def test_get_clients_and_byzantine_prefix(tmp_path):
    sim = _sim(tmp_path, num_byzantine=2, attack="ipm")
    clients = sim.get_clients()
    assert len(clients) == 6
    assert [c.is_byzantine() for c in clients] == [True, True] + [False] * 4


def test_attack_none_forces_zero_byzantine(tmp_path):
    # parity: simulator.py:118-121
    sim = _sim(tmp_path, num_byzantine=3, attack=None)
    assert sim.num_byzantine == 0


def test_run_writes_stats_log(tmp_path):
    sim = _sim(tmp_path, num_byzantine=2, attack="alie", aggregator="trimmedmean")
    times = sim.run(
        "mlp", global_rounds=3, local_steps=2, client_lr=0.2,
        validate_interval=1, train_batch_size=8,
    )
    assert len(times) == 3
    lines = open(os.path.join(sim.json_logger.handlers[0].baseFilename)).readlines()
    recs = [ast.literal_eval(l) for l in lines]
    types = {r["_meta"]["type"] for r in recs}
    assert types == {"train", "variance", "test", "client_validation"}
    test_recs = [r for r in recs if r["_meta"]["type"] == "test"]
    assert {"Round", "top1", "Length", "Loss"} <= set(test_recs[0])
    cv = [r for r in recs if r["_meta"]["type"] == "client_validation"]
    # one record per client per validation round (reference client.py:147-152)
    assert len(cv) % 6 == 0 and {"E", "Length", "Loss", "top1"} <= set(cv[0])
    # the test record is the Length-weighted average of the client records
    last_round_cv = [r for r in cv if r["E"] == test_recs[-1]["Round"]]
    w = sum(r["Length"] for r in last_round_cv)
    avg = sum(r["top1"] * r["Length"] for r in last_round_cv) / w
    assert abs(avg - test_recs[-1]["top1"]) < 1e-6  # f32 shard-mean roundoff
    assert len({r["id"] for r in last_round_cv}) == len(last_round_cv)


def test_learning_happens(tmp_path):
    sim = _sim(tmp_path, aggregator="mean")
    sim.run("mlp", global_rounds=15, local_steps=2, client_lr=0.5,
            server_lr=1.0, validate_interval=15, train_batch_size=16)
    ev = sim.evaluate(15, 64)
    assert ev["top1"] > 0.3


def test_custom_attacker_registration(tmp_path):
    class ZeroAttack(Attack):
        def on_updates(self, updates, byz_mask, key, state=()):
            return jnp.where(byz_mask[:, None], 0.0, updates), state

    class ZeroClient(ByzantineClient):
        def make_attack(self):
            return ZeroAttack()

    sim = _sim(tmp_path)
    sim.register_attackers([ZeroClient(), ZeroClient()])
    assert sim.num_byzantine == 2
    sim.run("mlp", global_rounds=2, local_steps=1, train_batch_size=8,
            validate_interval=2, retain_updates=True)
    u = np.asarray(sim.engine.last_updates)
    assert np.allclose(u[:2], 0.0)
    assert not np.allclose(u[2:], 0.0)
    # client handles got their update rows
    assert np.allclose(np.asarray(sim.get_clients()[0].get_update()), 0.0)


def test_mixed_custom_attackers_dispatch_per_client(tmp_path):
    """A labelflipping and a signflipping attacker registered TOGETHER each
    get their own in-graph batch/grad hook (reference runs each client
    object's own hooks, client.py:231-253). Row 0 must match a uniform
    labelflipping run, row 1 must be the exact negation of its honest
    counterpart (signflipping at local_steps=1), rows 2+ untouched."""
    from blades_tpu.attackers import get_attack

    class LFClient(ByzantineClient):
        def make_attack(self):
            return get_attack("labelflipping", num_classes=2)

    class SFClient(ByzantineClient):
        def make_attack(self):
            return get_attack("signflipping")

    run_kw = dict(global_rounds=1, local_steps=1, train_batch_size=8,
                  validate_interval=1, retain_updates=True)

    sim_h = _sim(tmp_path / "h", seed=5)
    sim_h.run("mlp", **run_kw)
    u_honest = np.asarray(sim_h.engine.last_updates)

    sim_l = _sim(tmp_path / "l", seed=5, num_byzantine=1, attack="labelflipping")
    sim_l.run("mlp", **run_kw)
    u_uniform_lf = np.asarray(sim_l.engine.last_updates)

    sim_m = _sim(tmp_path / "m", seed=5)
    lf, sf = LFClient(), SFClient()
    sim_m.register_attackers([lf, sf])
    sim_m.run("mlp", **run_kw)
    u_mixed = np.asarray(sim_m.engine.last_updates)

    # row 0: labelflipping, identical to the uniform-labelflipping row
    np.testing.assert_allclose(u_mixed[0], u_uniform_lf[0], rtol=1e-5, atol=1e-7)
    assert not np.allclose(u_mixed[0], u_honest[0])
    # row 1: signflipping = exact negation of the honest update at 1 step
    np.testing.assert_allclose(u_mixed[1], -u_honest[1], rtol=1e-5, atol=1e-7)
    # rows 2+: honest, bit-identical data path
    np.testing.assert_allclose(u_mixed[2:], u_honest[2:], rtol=1e-6, atol=1e-8)


def test_trusted_clients_flow_to_fltrust(tmp_path):
    sim = _sim(tmp_path, aggregator="fltrust")
    sim.set_trusted_clients([0])
    assert sim.get_clients()[0].is_trusted()
    sim.run("mlp", global_rounds=2, local_steps=1, train_batch_size=8,
            validate_interval=2)


def test_byzantinesgd_runs_in_engine(tmp_path):
    """The model-trajectory context (params_flat) reaches stateful defenses
    that need it inside the jitted round."""
    sim = _sim(tmp_path, aggregator="byzantinesgd",
               aggregator_kws={"th_A": 1e6, "th_B": 1e6, "th_V": 1e6})
    sim.run("mlp", global_rounds=2, local_steps=1, train_batch_size=8,
            validate_interval=2)
    agg_state = sim.server.state.agg_state
    assert bool(agg_state["initialized"])  # params_flat context arrived
    assert bool(jnp.all(agg_state["good"]))  # huge thresholds: none filtered


def test_lr_scheduler_dict(tmp_path):
    sim = _sim(tmp_path)
    fn = sim._resolve_schedule({"milestones": [1], "gamma": 0.1}, 1.0)
    assert fn(0) == 1.0 and fn(1) == pytest.approx(0.1)


def test_adam_client_optimizer(tmp_path):
    from blades_tpu.core import ClientOptSpec

    sim = _sim(tmp_path)
    sim.run("mlp", client_optimizer=ClientOptSpec(name="adam", persist=True),
            global_rounds=2, local_steps=1, client_lr=1e-3,
            train_batch_size=8, validate_interval=2)


def test_text_model_end_to_end(tmp_path):
    """Text family through the full facade: token dataset -> masked text
    model -> attack -> aggregation (the reference never wires its text zoo
    into training at all)."""
    from blades_tpu.datasets import SyntheticText

    # seq_len 8 (not 16): this is the single most expensive tier-1 test —
    # the text-CCT round program costs ~3 min of single-core trace+lowering
    # that the persistent compile cache cannot absorb, and the smaller
    # attention shapes shave ~25 s without touching what the test pins
    # (facade wiring + separability: top1 lands ~0.58 vs the 0.4 bar)
    ds = SyntheticText(
        num_clients=4, vocab_size=80, seq_len=8, train_size=200,
        test_size=60, cache=False,
    )
    sim = Simulator(ds, log_path=str(tmp_path / "out"), seed=0,
                    num_byzantine=1, attack="signflipping",
                    aggregator="median")
    sim.run("text_cct_2", global_rounds=4, local_steps=2, client_lr=0.3,
            server_lr=1.0, validate_interval=4, train_batch_size=16)
    ev = sim.evaluate(4, 64)
    assert np.isfinite(ev["Loss"])
    # class-conditional unigrams are separable: must beat chance-ish quickly
    assert ev["top1"] > 0.4


def test_bf16_run(tmp_path):
    sim = _sim(tmp_path, aggregator="mean")
    sim.run("mlp", global_rounds=2, local_steps=1, train_batch_size=8,
            validate_interval=2, compute_dtype="bfloat16")


def test_updates_dropped_by_default_kept_when_consumed(tmp_path):
    """The [K, D] matrix is only a program output when someone reads it:
    default run() leaves last_updates None; retain_updates=True populates
    it (engine.py keep_updates)."""
    sim = _sim(tmp_path / "off")
    sim.run("mlp", global_rounds=1, local_steps=1, train_batch_size=8,
            validate_interval=1)
    assert sim.engine.keep_updates is False
    assert sim.engine.last_updates is None

    sim2 = _sim(tmp_path / "on")
    sim2.run("mlp", global_rounds=1, local_steps=1, train_batch_size=8,
             validate_interval=1, retain_updates=True)
    assert sim2.engine.last_updates is not None


def test_block_run_matches_sequential_and_keeps_round_records(tmp_path):
    """run(block_size=3) over 5 rounds (full block + remainder — the at-
    most-2-programs shape set): bit-identical final params vs the
    per-round path, per-round train/variance stats records and telemetry
    round records all still present, spans at block granularity."""
    import json

    run_kw = dict(global_rounds=5, local_steps=1, train_batch_size=8,
                  validate_interval=5, client_lr=0.3)
    sim_a = _sim(tmp_path / "a", seed=7, num_byzantine=2, attack="ipm",
                 aggregator="median")
    times_a = sim_a.run("mlp", **run_kw)
    ref = np.asarray(jnp.concatenate([
        x.ravel() for x in jax.tree_util.tree_leaves(sim_a.server.state.params)
    ]))

    sim_b = _sim(tmp_path / "b", seed=7, num_byzantine=2, attack="ipm",
                 aggregator="median")
    times_b = sim_b.run("mlp", block_size=3, **run_kw)
    out = np.asarray(jnp.concatenate([
        x.ravel() for x in jax.tree_util.tree_leaves(sim_b.server.state.params)
    ]))
    np.testing.assert_array_equal(ref, out)
    assert len(times_a) == len(times_b) == 5  # per-round walls (amortized)

    # stats-file schema parity: one train + one variance record per ROUND
    lines = open(sim_b.json_logger.handlers[0].baseFilename).readlines()
    recs = [ast.literal_eval(l) for l in lines]
    train = [r for r in recs if r["_meta"]["type"] == "train"]
    assert [r["Round"] for r in train] == [1, 2, 3, 4, 5]

    # telemetry: per-round round records; spans at block granularity
    trecs = [json.loads(l)
             for l in open(os.path.join(sim_b.log_path, "telemetry.jsonl"))]
    rounds = [r for r in trecs if r["t"] == "round"]
    assert [r["round"] for r in rounds] == [1, 2, 3, 4, 5]
    spans = [r for r in trecs if r["t"] == "span"]
    paths = {s["path"] for s in spans}
    assert "block" in paths and "block/dispatch" in paths
    assert "eval_warmup" in paths  # eager eval build, recorded as a span
    blocks = [s for s in spans if s["path"] == "block"]
    assert sorted(s["rounds"] for s in blocks) == [2, 3]  # full + remainder


def test_block_size_falls_back_when_hooks_need_rounds(tmp_path):
    """retain_updates/on_round_end need per-round host visibility: the run
    silently (debug-noted) drops to per-round execution and the hook fires
    every round."""
    seen = []
    sim = _sim(tmp_path)
    sim.run("mlp", global_rounds=3, local_steps=1, train_batch_size=8,
            validate_interval=3, block_size=3,
            on_round_end=lambda r, s, m: seen.append(r))
    assert seen == [1, 2, 3]
    assert sim.engine.last_updates is not None  # per-round path kept them


def test_run_with_donated_batches_matches(tmp_path):
    """run(donate_batches=True) must produce the same training as the
    default (built-in datasets sample fresh buffers every round, so
    donation only changes buffer lifetime, not values)."""
    sim_a = _sim(tmp_path / "a", seed=4)
    sim_a.run("mlp", global_rounds=2, local_steps=1, train_batch_size=8,
              validate_interval=2)
    ev_a = sim_a.evaluate(2, 64)

    sim_b = _sim(tmp_path / "b", seed=4)
    sim_b.run("mlp", global_rounds=2, local_steps=1, train_batch_size=8,
              validate_interval=2, donate_batches=True)
    ev_b = sim_b.evaluate(2, 64)
    assert ev_a == ev_b
