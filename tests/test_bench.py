"""bench.py attempt-ladder logic, with the child subprocesses mocked.

The real children are exercised by the driver (BENCH_r*.json) and the
gate-robustness runs; these tests pin the parent's contract: exactly one
JSON line on stdout in every world, correct fallback routing, and labels
that prevent a fallback number from masquerading as the TPU headline.
"""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def bench(monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run_main(bench, monkeypatch, capsys, script):
    """script: list of (result, err) returned by successive _run_child calls."""
    calls = []
    seq = iter(script)

    def fake_run_child(overrides, timeout_s):
        calls.append(dict(overrides))
        return next(seq)

    monkeypatch.setattr(bench, "_run_child", fake_run_child)
    code = 0
    try:
        bench.main()
    except SystemExit as e:
        code = e.code
    line = capsys.readouterr().out.strip().splitlines()[-1]
    return json.loads(line), calls, code


def test_tpu_headline(bench, monkeypatch, capsys):
    probe = ({"probe": "ok", "platform": "axon", "n_devices": 1}, None)
    full = ({"rounds_per_sec": 5.0, "clients": 1000, "platform": "axon"}, None)
    payload, calls, code = run_main(bench, monkeypatch, capsys, [probe, full])
    assert code == 0
    assert payload["value"] == 5.0
    assert "config" not in payload  # the real headline carries no fallback label
    assert payload["vs_baseline"] is not None


def test_async_row_labeled_non_headline(bench, monkeypatch, capsys):
    """A buffered-async measurement (PR 10) never rides the clean headline:
    the payload is labeled `_asyncM<m>`, vs_baseline is nulled, and the
    async fields (buffer_m / staleness cadence) pass through."""
    probe = ({"probe": "ok", "platform": "axon", "n_devices": 1}, None)
    full = ({"rounds_per_sec": 3.0, "clients": 1000, "platform": "axon",
             "async": True, "buffer_m": 500, "staleness": "polynomial",
             "agg_fires_per_round": 0.8, "mean_staleness": 1.25}, None)
    payload, _, code = run_main(bench, monkeypatch, capsys, [probe, full])
    assert code == 0
    assert payload["config"].endswith("_asyncM500")
    assert payload["vs_baseline"] is None
    assert payload["async"] is True
    assert payload["buffer_m"] == 500
    assert payload["agg_fires_per_round"] == 0.8
    assert payload["mean_staleness"] == 1.25
    assert payload["staleness"] == "polynomial"


def test_full_timeout_skips_retry_and_falls_to_smoke(bench, monkeypatch, capsys):
    probe = ({"probe": "ok", "platform": "axon", "n_devices": 1}, None)
    full_to = (None, "timeout after 1500s")
    smoke = ({"rounds_per_sec": 8.0, "clients": 100, "platform": "axon"}, None)
    payload, calls, code = run_main(
        bench, monkeypatch, capsys, [probe, full_to, smoke]
    )
    assert code == 0
    assert len(calls) == 3  # probe, full, smoke — the identical retry skipped
    assert payload["config"] == "axon_k100"
    assert "timeout" in payload["attempt_errors"]


def test_probe_failure_routes_to_cpu_smoke(bench, monkeypatch, capsys):
    probe = (None, "timeout after 240s")
    cpu = ({"rounds_per_sec": 0.02, "clients": 8, "platform": "cpu"}, None)
    payload, calls, code = run_main(bench, monkeypatch, capsys, [probe, cpu])
    assert code == 0
    assert payload["config"] == "cpu_k8"
    assert calls[-1]["BENCH_FORCE_CPU"] == 1


def test_cpu_only_probe_routes_to_cpu_smoke(bench, monkeypatch, capsys):
    """A successful probe on a CPU-only host must not run the full ladder."""
    probe = ({"probe": "ok", "platform": "cpu", "n_devices": 1}, None)
    cpu = ({"rounds_per_sec": 0.02, "clients": 8, "platform": "cpu"}, None)
    payload, calls, code = run_main(bench, monkeypatch, capsys, [probe, cpu])
    assert code == 0
    assert payload["config"] == "cpu_k8"
    assert len(calls) == 2


def test_total_failure_emits_error_json(bench, monkeypatch, capsys):
    probe = (None, "timeout after 240s")
    cpu = (None, "sampler: JaxRuntimeError: boom")
    payload, calls, code = run_main(bench, monkeypatch, capsys, [probe, cpu])
    assert code == 1
    assert payload["value"] is None
    assert "boom" in payload["error"]
    assert payload["metric"]  # the line is still schema-complete

def test_efficiency_fields_on_tpu_and_fallback(bench, monkeypatch, capsys):
    """The JSON contract carries tflops_sustained + mfu on every path
    (VERDICT r4 #8): computed from the child's cost-model TFLOP on an
    accelerator, null-mfu on the CPU fallback, null-both when the child
    could not read the cost model."""
    probe = ({"probe": "ok", "platform": "axon", "n_devices": 1}, None)
    full = (
        {"rounds_per_sec": 2.0, "clients": 1000, "platform": "axon",
         "tflop_per_round": 6.92},
        None,
    )
    payload, _, _ = run_main(bench, monkeypatch, capsys, [probe, full])
    assert payload["tflops_sustained"] == round(6.92 * 2.0, 6)
    assert payload["mfu"] == round(6.92 * 2.0 / bench.PEAK_TFLOPS_V5E, 4)

    probe_down = (None, "timeout after 240s")
    cpu = (
        {"rounds_per_sec": 0.02, "clients": 8, "platform": "cpu",
         "tflop_per_round": 0.01},
        None,
    )
    payload, _, _ = run_main(bench, monkeypatch, capsys, [probe_down, cpu])
    assert payload["tflops_sustained"] == round(0.01 * 0.02, 6)
    assert payload["mfu"] is None  # no meaningful peak off-accelerator

    cpu_no_ca = ({"rounds_per_sec": 0.02, "clients": 8, "platform": "cpu"}, None)
    payload, _, _ = run_main(bench, monkeypatch, capsys, [probe_down, cpu_no_ca])
    assert payload["tflops_sustained"] is None and payload["mfu"] is None

def test_telemetry_subdict_rides_the_one_json_line(bench, monkeypatch, capsys):
    """The child's compile/cache/agg accounting appears as a compact
    ``telemetry`` sub-dict in the payload without breaking the exactly-one-
    JSON-line contract; when an (old/failed) child omits it, the parent
    never fabricates one."""
    telem = {"compile_s": 12.3, "compiles": 3, "cache_hits": 2,
             "cache_misses": 1, "agg_s": 0.004}
    probe = ({"probe": "ok", "platform": "axon", "n_devices": 1}, None)
    full = ({"rounds_per_sec": 5.0, "clients": 1000, "platform": "axon",
             "telemetry": telem}, None)
    calls = []
    seq = iter([probe, full])
    monkeypatch.setattr(
        bench, "_run_child", lambda o, t: (calls.append(o), next(seq))[1]
    )
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1  # the contract: exactly one line on stdout
    payload = json.loads(out[0])
    assert payload["telemetry"] == telem

    # child without the sub-dict (e.g. pre-telemetry payload): key absent
    probe = ({"probe": "ok", "platform": "axon", "n_devices": 1}, None)
    full = ({"rounds_per_sec": 5.0, "clients": 1000, "platform": "axon"}, None)
    payload, _, code = run_main(bench, monkeypatch, capsys, [probe, full])
    assert code == 0 and "telemetry" not in payload


def test_parent_crash_still_emits_one_json_line(bench, monkeypatch, capsys):
    """The one-JSON-line contract survives a bug in the parent ladder
    itself: an unexpected exception becomes a single parseable error line
    with a ``stage`` field, never a traceback-only death."""
    def explode(overrides, timeout_s):
        raise RuntimeError("ladder bug")

    monkeypatch.setattr(bench, "_run_child", explode)
    with pytest.raises(SystemExit) as exc:
        bench.main()
    assert exc.value.code == 1
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    payload = json.loads(out[0])
    assert payload["value"] is None
    assert payload["stage"] == "parent"
    assert "ladder bug" in payload["error"]
    assert payload["metric"]  # schema-complete


def test_total_failure_carries_ladder_stage(bench, monkeypatch, capsys):
    probe = (None, "timeout after 240s")
    cpu = (None, "sampler: JaxRuntimeError: boom")
    payload, _, code = run_main(bench, monkeypatch, capsys, [probe, cpu])
    assert code == 1 and payload["stage"] == "ladder"


def test_tunnel_down_hint_skips_probe(bench, monkeypatch, capsys):
    """BLADES_TUNNEL_DOWN=1 skips the liveness probe's full timeout budget
    and drops straight to the labeled cpu_k8 fallback — a harness that
    already paid for the tunnel-down knowledge should not pay again."""
    monkeypatch.setenv("BLADES_TUNNEL_DOWN", "1")
    cpu = ({"rounds_per_sec": 0.02, "clients": 8, "platform": "cpu"}, None)
    payload, calls, code = run_main(bench, monkeypatch, capsys, [cpu])
    assert code == 0
    assert len(calls) == 1  # no probe child at all
    assert calls[0]["BENCH_FORCE_CPU"] == 1
    # an inherited BENCH_BLOCK must not inflate the pinned smoke rounds
    assert calls[0]["BENCH_BLOCK"] == 1
    assert payload["config"] == "cpu_k8"
    assert "BLADES_TUNNEL_DOWN" in payload["attempt_errors"]


def test_block_fields_ride_the_payload(bench, monkeypatch, capsys):
    """Round-block amortization fields (block_size, rounds_per_launch)
    pass through; a block>1 run is labeled non-headline (its timing is
    amortized, not per-round cadence) while block_size=1 keeps the clean
    headline."""
    probe = ({"probe": "ok", "platform": "axon", "n_devices": 1}, None)
    blk = ({"rounds_per_sec": 9.0, "clients": 1000, "platform": "axon",
            "block_size": 8, "rounds_per_launch": 8.0}, None)
    payload, _, code = run_main(bench, monkeypatch, capsys, [probe, blk])
    assert code == 0
    assert payload["block_size"] == 8
    assert payload["rounds_per_launch"] == 8.0
    assert payload["config"].endswith("_blk8")
    assert payload["vs_baseline"] is None

    probe = ({"probe": "ok", "platform": "axon", "n_devices": 1}, None)
    one = ({"rounds_per_sec": 5.0, "clients": 1000, "platform": "axon",
            "block_size": 1, "rounds_per_launch": 1.0}, None)
    payload, _, code = run_main(bench, monkeypatch, capsys, [probe, one])
    assert code == 0
    assert payload["block_size"] == 1
    assert "config" not in payload  # per-round path stays the headline
    assert payload["vs_baseline"] is not None


def test_make_agg_signature_dispatch(bench):
    """num_byzantine is forwarded only to constructors that declare it;
    no-arg aggregators (object.__init__) must neither crash nor silently
    claim kwargs were applied."""
    from blades_tpu.aggregators import get_aggregator

    agg, kw = bench._make_agg(get_aggregator, "median", 4, True)
    assert kw == {}
    agg, kw = bench._make_agg(get_aggregator, "trimmedmean", 4, True)
    assert kw == {"num_byzantine": 4}
    assert agg.b == 4
    _, kw = bench._make_agg(get_aggregator, "krum", 4, False)
    assert kw == {}  # headline path: defaults, nothing forwarded
