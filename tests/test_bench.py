"""bench.py attempt-ladder logic, with the child subprocesses mocked.

The real children are exercised by the driver (BENCH_r*.json) and the
gate-robustness runs; these tests pin the parent's contract: exactly one
JSON line on stdout in every world, correct fallback routing, and labels
that prevent a fallback number from masquerading as the TPU headline.
"""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def bench(monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run_main(bench, monkeypatch, capsys, script):
    """script: list of (result, err) returned by successive _run_child calls."""
    calls = []
    seq = iter(script)

    def fake_run_child(overrides, timeout_s):
        calls.append(dict(overrides))
        return next(seq)

    monkeypatch.setattr(bench, "_run_child", fake_run_child)
    code = 0
    try:
        bench.main()
    except SystemExit as e:
        code = e.code
    line = capsys.readouterr().out.strip().splitlines()[-1]
    return json.loads(line), calls, code


def test_tpu_headline(bench, monkeypatch, capsys):
    probe = ({"probe": "ok", "platform": "axon", "n_devices": 1}, None)
    full = ({"rounds_per_sec": 5.0, "clients": 1000, "platform": "axon"}, None)
    payload, calls, code = run_main(bench, monkeypatch, capsys, [probe, full])
    assert code == 0
    assert payload["value"] == 5.0
    assert "config" not in payload  # the real headline carries no fallback label
    assert payload["vs_baseline"] is not None


def test_full_timeout_skips_retry_and_falls_to_smoke(bench, monkeypatch, capsys):
    probe = ({"probe": "ok", "platform": "axon", "n_devices": 1}, None)
    full_to = (None, "timeout after 1500s")
    smoke = ({"rounds_per_sec": 8.0, "clients": 100, "platform": "axon"}, None)
    payload, calls, code = run_main(
        bench, monkeypatch, capsys, [probe, full_to, smoke]
    )
    assert code == 0
    assert len(calls) == 3  # probe, full, smoke — the identical retry skipped
    assert payload["config"] == "axon_k100"
    assert "timeout" in payload["attempt_errors"]


def test_probe_failure_routes_to_cpu_smoke(bench, monkeypatch, capsys):
    probe = (None, "timeout after 240s")
    cpu = ({"rounds_per_sec": 0.02, "clients": 8, "platform": "cpu"}, None)
    payload, calls, code = run_main(bench, monkeypatch, capsys, [probe, cpu])
    assert code == 0
    assert payload["config"] == "cpu_k8"
    assert calls[-1]["BENCH_FORCE_CPU"] == 1


def test_cpu_only_probe_routes_to_cpu_smoke(bench, monkeypatch, capsys):
    """A successful probe on a CPU-only host must not run the full ladder."""
    probe = ({"probe": "ok", "platform": "cpu", "n_devices": 1}, None)
    cpu = ({"rounds_per_sec": 0.02, "clients": 8, "platform": "cpu"}, None)
    payload, calls, code = run_main(bench, monkeypatch, capsys, [probe, cpu])
    assert code == 0
    assert payload["config"] == "cpu_k8"
    assert len(calls) == 2


def test_total_failure_emits_error_json(bench, monkeypatch, capsys):
    probe = (None, "timeout after 240s")
    cpu = (None, "sampler: JaxRuntimeError: boom")
    payload, calls, code = run_main(bench, monkeypatch, capsys, [probe, cpu])
    assert code == 1
    assert payload["value"] is None
    assert "boom" in payload["error"]
    assert payload["metric"]  # the line is still schema-complete
