"""Request-path accounting (`blades_tpu/telemetry/reqpath.py`): the
split math (queue-wait + build + execute tiles each request's wall),
warm/cold classification from the compile mirror, exact fixed-bin
histogram percentiles on synthetic streams, the rolling metrics
registry's counters/high-water marks, and the schema lock on the
snapshot record shape.

All tests drive injectable clocks/counters — no server, no jax, no
sleeping.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from blades_tpu.telemetry.reqpath import (  # noqa: E402
    Histogram,
    MetricsRegistry,
    RequestPath,
)
from blades_tpu.telemetry.schema import load_schema, validate_records  # noqa: E402


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# -- split math ----------------------------------------------------------------


def test_split_tiles_the_request_wall():
    """queue_wait_s + build_s + execute_s == total_s, with the stamps
    driving each term: 2 s of queue wait, then 5 s of execution of which
    1.5 s was trace+compile (the compile-mirror delta)."""
    clk = FakeClock()
    path = RequestPath("r1", op="simulate", client="t0", clock=clk)
    clk.advance(0.5)
    path.stamp("spooled")
    path.stamp("queued")
    clk.advance(1.5)  # 2.0 s total between admitted and started
    path.start(counters={"xla.compiles": 10, "xla.compile_s": 40.0,
                         "xla.trace_s": 8.0})
    clk.advance(5.0)
    fields = path.finish(counters={"xla.compiles": 12,
                                   "xla.compile_s": 41.0,
                                   "xla.trace_s": 8.5})
    assert fields["queue_wait_s"] == 2.0
    assert fields["build_s"] == 1.5
    assert fields["execute_s"] == 3.5
    assert fields["total_s"] == 7.0
    assert (
        fields["queue_wait_s"] + fields["build_s"] + fields["execute_s"]
        == fields["total_s"]
    )
    assert fields["warm"] is False and fields["compiles"] == 2


def test_warm_cold_classification_via_compile_mirror():
    """Zero compile-count delta across the execution window == warm; a
    warm request's build share is zero and its wall is pure execute."""
    clk = FakeClock()
    c0 = {"xla.compiles": 7, "xla.compile_s": 30.0, "xla.trace_s": 5.0}
    path = RequestPath("r2", clock=clk)
    path.start(counters=c0)
    clk.advance(0.25)
    fields = path.finish(counters=dict(c0))
    assert fields["warm"] is True and fields["compiles"] == 0
    assert fields["build_s"] == 0.0
    assert fields["execute_s"] == 0.25 and fields["total_s"] == 0.25


def test_build_clamped_to_execution_wall():
    """A compile-seconds delta larger than the observed wall (another
    thread compiling concurrently) must clamp: execute_s never goes
    negative and the tiling invariant holds."""
    clk = FakeClock()
    path = RequestPath("r3", clock=clk)
    path.start(counters={"xla.compiles": 0, "xla.compile_s": 0.0})
    clk.advance(1.0)
    fields = path.finish(counters={"xla.compiles": 3,
                                   "xla.compile_s": 9.0})
    assert fields["build_s"] == 1.0 and fields["execute_s"] == 0.0
    assert fields["total_s"] == 1.0 and fields["warm"] is False


def test_never_started_request_is_all_queue_wait():
    clk = FakeClock()
    path = RequestPath("r4", clock=clk)
    clk.advance(3.0)
    fields = path.finish()
    assert fields["queue_wait_s"] == 3.0 and fields["total_s"] == 3.0
    assert fields["build_s"] == 0.0 and fields["execute_s"] == 0.0


# -- histogram -----------------------------------------------------------------


def test_histogram_percentile_edges_exact_on_synthetic_stream():
    """A 100-observation stream placed on known bins: percentiles report
    the exact upper edge of the rank's bin (1-2-5 ladder)."""
    h = Histogram()
    for _ in range(50):
        h.observe(0.0008)   # bin (0, 0.001]
    for _ in range(40):
        h.observe(0.09)     # bin (0.05, 0.1]
    for _ in range(9):
        h.observe(4.0)      # bin (2, 5]
    h.observe(90.0)         # bin (50, 100]
    assert h.count == 100
    assert h.percentile(0.50) == 0.001
    assert h.percentile(0.90) == 0.1
    assert h.percentile(0.99) == 5.0
    assert h.percentile(1.00) == 100.0
    d = h.to_dict()
    assert d["p50_s"] == 0.001 and d["p90_s"] == 0.1 and d["p99_s"] == 5.0
    assert d["max_s"] == 90.0 and d["count"] == 100


def test_histogram_overflow_bin_reports_observed_max():
    h = Histogram()
    h.observe(50000.0)  # beyond the last edge
    h.observe(0.01)
    assert h.percentile(0.99) == 50000.0  # overflow: observed max
    assert h.percentile(0.5) == 0.01


def test_histogram_empty_and_degenerate_values():
    h = Histogram()
    assert h.percentile(0.99) is None
    assert h.to_dict() == {"count": 0}
    h.observe(-1.0)          # clock skew folds to 0
    h.observe(float("nan"))  # never poisons the bins
    assert h.count == 2 and h.percentile(0.99) == Histogram.EDGES[0]


# -- registry ------------------------------------------------------------------


def test_registry_counters_rejections_and_hwm():
    clk = FakeClock()
    reg = MetricsRegistry(clock=clk)
    p = reg.admit("a", op="probe", client="tenant1")
    reg.queue_depth(3)
    reg.queue_depth(1)  # high-water mark keeps the max
    clk.advance(1.0)
    p.start(counters={"xla.compiles": 0})
    reg.cell("a")
    reg.cell("a")
    clk.advance(2.0)
    fields = reg.finish("a", outcome="quarantined", retried=2,
                        quarantined_cells=1,
                        counters={"xla.compiles": 0})
    assert fields["warm"] is True
    reg.reject("backpressure", op="probe", client="tenant2")
    reg.reject("backpressure", op="probe", client="tenant2")
    reg.reject("draining", op="simulate", client="tenant1")
    snap = reg.snapshot()
    assert snap["requests"] == {
        "admitted": 1, "served": 1, "failed": 0, "rejected": 3,
        "quarantined": 1, "warm": 1, "cold": 0,
    }
    assert snap["cells"] == {"done": 2, "retried": 2, "quarantined": 1}
    assert snap["rejected_by_reason"] == {"backpressure": 2, "draining": 1}
    assert snap["queue"]["depth_hwm"] == 3
    # counter row + the per-tenant latency histograms merged in (the
    # victim-p99 evidence source — by_client rows are counters PLUS
    # `latency`/`warm_latency` once the tenant has a finished request)
    t1 = snap["by_client"]["tenant1"]
    assert {k: t1[k] for k in ("admitted", "served", "rejected")} == {
        "admitted": 1, "served": 1, "rejected": 1,
    }
    assert t1["latency"]["count"] == 1
    assert t1["warm_latency"]["count"] == 1  # the request was warm
    assert snap["by_client"]["tenant2"] == {"rejected": 2}
    assert snap["by_op"]["probe"]["served"] == 1
    # split sums: 1 s queue wait + 2 s execute
    assert snap["split"]["queue_wait_s"] == 1.0
    assert snap["split"]["total_s"] == 3.0
    assert snap["split"]["queue_wait_share"] == round(1.0 / 3.0, 6)
    # unknown ids never fail accounting
    assert reg.finish("ghost") == {}


def test_registry_error_outcome_counts_failed_not_served():
    reg = MetricsRegistry(clock=FakeClock())
    reg.admit("a", op="probe")
    reg.finish("a", outcome="error")
    snap = reg.snapshot()
    assert snap["requests"]["failed"] == 1
    assert snap["requests"]["served"] == 0
    # never started: classified neither warm nor cold
    assert snap["requests"]["warm"] == 0 and snap["requests"]["cold"] == 0


def test_snapshot_record_validates_against_committed_schema():
    """The registry snapshot IS the `metrics_snapshot` record body: it
    must carry exactly the schema-declared fields (the closed v6 type),
    so the server can splat it into `event()` unchanged."""
    reg = MetricsRegistry(clock=FakeClock())
    p = reg.admit("a", op="probe")
    p.start(counters={})
    reg.finish("a", counters={})
    rec = {"t": "metrics_snapshot", "ts": 1.0, **reg.snapshot()}
    schema = load_schema()
    assert validate_records([rec], schema) == []
    # the snapshot is JSON-serializable as-is (the wire reply body)
    json.dumps(rec)
