"""Torch-checkpoint import: structural mapping + numerical forward parity.

The numerics test instantiates the reference's own torch CCT (read-only
mount at /root/reference) with random weights, converts its state_dict, and
compares logits — validating both the converter and our flax CCT
implementation against the reference behavior. Skipped when the reference
tree isn't mounted.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.models import cct_2_3x2_32, vit_lite_7_4_32
from blades_tpu.models.common import build_fns
from blades_tpu.models.import_torch import torch_cct_to_flax

REF = "/root/reference/src"


def test_rejects_mismatched_checkpoint():
    spec = build_fns(cct_2_3x2_32(num_classes=10), (32, 32, 3))
    p = spec.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError):
        torch_cct_to_flax({"bogus.key": np.zeros(3)}, p)
    with pytest.raises(ValueError):
        torch_cct_to_flax({}, p)  # nothing filled


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_cct2_forward_parity_with_reference():
    import sys

    sys.path.insert(0, REF)
    import torch

    from blades.models.cifar10.cctnets.cct import cct_2_3x2_32 as torch_cct

    tm = torch_cct(pretrained=False, progress=False, num_classes=10, img_size=32)
    tm.eval()
    spec = build_fns(cct_2_3x2_32(num_classes=10), (32, 32, 3))
    template = spec.init(jax.random.PRNGKey(0))
    params = torch_cct_to_flax(tm.state_dict(), template)

    x = np.random.RandomState(0).randn(4, 32, 32, 3).astype(np.float32)
    with torch.no_grad():
        ref = tm(torch.tensor(x).permute(0, 3, 1, 2)).numpy()
    ours = np.asarray(spec.eval_logits_fn(params, jnp.asarray(x)))
    # erf-vs-tanh GELU and LayerNorm-eps differences bound the residual
    np.testing.assert_allclose(ours, ref, atol=5e-3, rtol=1e-2)


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_vit_lite_forward_parity_with_reference():
    """Class-token (no seq-pool) variant: exercises class_emb + fc->Dense_0."""
    import sys

    sys.path.insert(0, REF)
    import torch

    from blades.models.cifar10.cctnets.vit import ViTLite

    # the reference's vit_7_4_32 factory crashes (double positional_embedding
    # kwarg); build the same config directly
    tm = ViTLite(img_size=32, kernel_size=4, num_layers=7, num_heads=4,
                 mlp_ratio=2.0, embedding_dim=256, num_classes=10,
                 positional_embedding="learnable")
    tm.eval()
    spec = build_fns(vit_lite_7_4_32(num_classes=10), (32, 32, 3))
    template = spec.init(jax.random.PRNGKey(0))
    params = torch_cct_to_flax(tm.state_dict(), template)

    x = np.random.RandomState(1).randn(3, 32, 32, 3).astype(np.float32)
    with torch.no_grad():
        ref = tm(torch.tensor(x).permute(0, 3, 1, 2)).numpy()
    ours = np.asarray(spec.eval_logits_fn(params, jnp.asarray(x)))
    np.testing.assert_allclose(ours, ref, atol=5e-3, rtol=1e-2)


def test_variant_mismatch_raises_value_error():
    """Wrong-depth checkpoints and non-CCT keys fail with ValueError."""
    spec = build_fns(cct_2_3x2_32(num_classes=10), (32, 32, 3))
    p = spec.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="different model variant"):
        torch_cct_to_flax(
            {"classifier.blocks.5.pre_norm.weight": np.zeros(128)}, p
        )
    with pytest.raises(ValueError, match="unrecognized state_dict key"):
        torch_cct_to_flax({"epoch": np.zeros(1)}, p)


def test_pretrained_registry_offline_cached(tmp_path, monkeypatch):
    """create_model(..., pretrained=True) must load from the local cache
    with no network touch (reference URL registry, cctnets/cct.py:13-30)."""
    from blades_tpu.models import MODEL_URLS, create_model
    from blades_tpu.models.pretrained import weights_path

    monkeypatch.setenv("BLADES_TPU_WEIGHTS", str(tmp_path))
    monkeypatch.setenv("BLADES_TPU_OFFLINE", "1")

    # cache miss while offline: clear, actionable error
    with pytest.raises(RuntimeError, match="BLADES_TPU_OFFLINE"):
        create_model("cct_7_3x1_32", pretrained=True).init(jax.random.PRNGKey(0))

    # unknown variant: registry error names the options
    with pytest.raises(ValueError, match="available"):
        create_model("cct_2_3x2_32", pretrained=True).init(jax.random.PRNGKey(0))

    if not os.path.isdir(REF):
        pytest.skip("reference not mounted; cannot fabricate a checkpoint")
    import sys

    sys.path.insert(0, REF)
    import torch

    from blades.models.cifar10.cctnets.cct import cct_7_3x1_32 as torch_cct

    tm = torch_cct(pretrained=False, progress=False, num_classes=10, img_size=32)
    tm.eval()
    torch.save(tm.state_dict(), weights_path("cct_7_3x1_32"))

    spec = create_model("cct_7_3x1_32", pretrained=True)
    params = spec.init(jax.random.PRNGKey(0))

    x = np.random.RandomState(1).randn(2, 32, 32, 3).astype(np.float32)
    with torch.no_grad():
        ref = tm(torch.tensor(x).permute(0, 3, 1, 2)).numpy()
    ours = np.asarray(spec.eval_logits_fn(params, jnp.asarray(x)))
    np.testing.assert_allclose(ours, ref, atol=5e-3, rtol=1e-2)
