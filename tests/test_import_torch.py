"""Torch-checkpoint import: structural mapping + numerical forward parity.

The numerics test instantiates the reference's own torch CCT (read-only
mount at /root/reference) with random weights, converts its state_dict, and
compares logits — validating both the converter and our flax CCT
implementation against the reference behavior. Skipped when the reference
tree isn't mounted.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.models import cct_2_3x2_32, vit_lite_7_4_32
from blades_tpu.models.common import build_fns
from blades_tpu.models.import_torch import torch_cct_to_flax

REF = "/root/reference/src"


def test_rejects_mismatched_checkpoint():
    spec = build_fns(cct_2_3x2_32(num_classes=10), (32, 32, 3))
    p = spec.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError):
        torch_cct_to_flax({"bogus.key": np.zeros(3)}, p)
    with pytest.raises(ValueError):
        torch_cct_to_flax({}, p)  # nothing filled


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_cct2_forward_parity_with_reference():
    import sys

    sys.path.insert(0, REF)
    import torch

    from blades.models.cifar10.cctnets.cct import cct_2_3x2_32 as torch_cct

    tm = torch_cct(pretrained=False, progress=False, num_classes=10, img_size=32)
    tm.eval()
    spec = build_fns(cct_2_3x2_32(num_classes=10), (32, 32, 3))
    template = spec.init(jax.random.PRNGKey(0))
    params = torch_cct_to_flax(tm.state_dict(), template)

    x = np.random.RandomState(0).randn(4, 32, 32, 3).astype(np.float32)
    with torch.no_grad():
        ref = tm(torch.tensor(x).permute(0, 3, 1, 2)).numpy()
    ours = np.asarray(spec.eval_logits_fn(params, jnp.asarray(x)))
    # erf-vs-tanh GELU and LayerNorm-eps differences bound the residual
    np.testing.assert_allclose(ours, ref, atol=5e-3, rtol=1e-2)


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_vit_lite_forward_parity_with_reference():
    """Class-token (no seq-pool) variant: exercises class_emb + fc->Dense_0."""
    import sys

    sys.path.insert(0, REF)
    import torch

    from blades.models.cifar10.cctnets.vit import ViTLite

    # the reference's vit_7_4_32 factory crashes (double positional_embedding
    # kwarg); build the same config directly
    tm = ViTLite(img_size=32, kernel_size=4, num_layers=7, num_heads=4,
                 mlp_ratio=2.0, embedding_dim=256, num_classes=10,
                 positional_embedding="learnable")
    tm.eval()
    spec = build_fns(vit_lite_7_4_32(num_classes=10), (32, 32, 3))
    template = spec.init(jax.random.PRNGKey(0))
    params = torch_cct_to_flax(tm.state_dict(), template)

    x = np.random.RandomState(1).randn(3, 32, 32, 3).astype(np.float32)
    with torch.no_grad():
        ref = tm(torch.tensor(x).permute(0, 3, 1, 2)).numpy()
    ours = np.asarray(spec.eval_logits_fn(params, jnp.asarray(x)))
    np.testing.assert_allclose(ours, ref, atol=5e-3, rtol=1e-2)


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_pe_resize_matches_reference_pe_check():
    """Loading a checkpoint trained at a different input resolution must
    interpolate the positional-embedding grid exactly like the reference's
    ``pe_check`` (cctnets/utils/helpers.py:10-36)."""
    import sys

    sys.path.insert(0, REF)
    from blades.models.cifar10.cctnets.cct import cct_2_3x2_32 as torch_cct
    from blades.models.cifar10.cctnets.utils.helpers import pe_check

    tm24 = torch_cct(pretrained=False, progress=False, num_classes=10, img_size=24)
    sd = tm24.state_dict()
    spec32 = build_fns(cct_2_3x2_32(num_classes=10), (32, 32, 3))
    template = spec32.init(jax.random.PRNGKey(0))

    # strict mode: shape mismatch must be an error
    with pytest.raises(ValueError, match="shape mismatch|positional"):
        torch_cct_to_flax(sd, template, pe_resize=False)

    params = torch_cct_to_flax(sd, template)  # pe_resize on by default

    tm32 = torch_cct(pretrained=False, progress=False, num_classes=10, img_size=32)
    sd_ref = {k: v.clone() for k, v in tm24.state_dict().items()}
    sd_ref = pe_check(tm32, sd_ref)
    np.testing.assert_allclose(
        np.asarray(params["positional_emb"]),
        sd_ref["classifier.positional_emb"].detach().numpy(),
        rtol=1e-4,
        atol=1e-5,
    )


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_pe_resize_class_token_variant_matches_reference():
    """num_tokens=1 path: the class-token embedding passes through untouched
    while the grid is interpolated (helpers.py:16-18)."""
    import sys

    sys.path.insert(0, REF)
    import torch

    from blades.models.cifar10.cctnets.utils.helpers import resize_pos_embed

    from blades_tpu.models.import_torch import resize_pos_embed as ours

    rng = np.random.RandomState(0)
    pe = rng.randn(1, 1 + 49, 8).astype(np.float32)
    new = torch.zeros(1, 1 + 81, 8)
    theirs = resize_pos_embed(torch.from_numpy(pe.copy()), new, num_tokens=1)
    mine = ours(pe, 1 + 81, num_tokens=1)
    np.testing.assert_allclose(mine, theirs.numpy(), rtol=1e-4, atol=1e-5)
    # class token untouched
    np.testing.assert_array_equal(mine[:, 0], pe[:, 0])


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_fc_mismatch_keeps_fresh_head():
    """A checkpoint with a different class count keeps the template's fresh
    classifier head (reference ``fc_check``, helpers.py:39-45) while every
    other layer loads from the checkpoint."""
    import sys

    sys.path.insert(0, REF)
    from blades.models.cifar10.cctnets.cct import cct_2_3x2_32 as torch_cct

    tm100 = torch_cct(pretrained=False, progress=False, num_classes=100, img_size=32)
    sd = tm100.state_dict()
    spec10 = build_fns(cct_2_3x2_32(num_classes=10), (32, 32, 3))
    template = spec10.init(jax.random.PRNGKey(0))

    with pytest.raises(ValueError, match="shape mismatch"):
        torch_cct_to_flax(sd, template, fc_tolerant=False)

    params = torch_cct_to_flax(sd, template)
    fc_name = "Dense_1" if "Dense_1" in template else "Dense_0"
    # head: fresh init from the template
    np.testing.assert_array_equal(
        np.asarray(params[fc_name]["kernel"]), np.asarray(template[fc_name]["kernel"])
    )
    # everything else: from the checkpoint (spot-check the first tokenizer conv)
    np.testing.assert_allclose(
        np.asarray(params["Tokenizer_0"]["Conv_0"]["kernel"]),
        sd["tokenizer.conv_layers.0.0.weight"].detach().numpy().transpose(2, 3, 1, 0),
        rtol=1e-6,
    )


def test_variant_mismatch_raises_value_error():
    """Wrong-depth checkpoints and non-CCT keys fail with ValueError."""
    spec = build_fns(cct_2_3x2_32(num_classes=10), (32, 32, 3))
    p = spec.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="different model variant"):
        torch_cct_to_flax(
            {"classifier.blocks.5.pre_norm.weight": np.zeros(128)}, p
        )
    with pytest.raises(ValueError, match="unrecognized state_dict key"):
        torch_cct_to_flax({"epoch": np.zeros(1)}, p)


def test_pretrained_registry_offline_cached(tmp_path, monkeypatch):
    """create_model(..., pretrained=True) must load from the local cache
    with no network touch (reference URL registry, cctnets/cct.py:13-30)."""
    from blades_tpu.models import MODEL_URLS, create_model
    from blades_tpu.models.pretrained import weights_path

    monkeypatch.setenv("BLADES_TPU_WEIGHTS", str(tmp_path))
    monkeypatch.setenv("BLADES_TPU_OFFLINE", "1")

    # cache miss while offline: clear, actionable error
    with pytest.raises(RuntimeError, match="BLADES_TPU_OFFLINE"):
        create_model("cct_7_3x1_32", pretrained=True).init(jax.random.PRNGKey(0))

    # unknown variant: registry error names the options
    with pytest.raises(ValueError, match="available"):
        create_model("cct_2_3x2_32", pretrained=True).init(jax.random.PRNGKey(0))

    if not os.path.isdir(REF):
        pytest.skip("reference not mounted; cannot fabricate a checkpoint")
    import sys

    sys.path.insert(0, REF)
    import torch

    from blades.models.cifar10.cctnets.cct import cct_7_3x1_32 as torch_cct

    tm = torch_cct(pretrained=False, progress=False, num_classes=10, img_size=32)
    tm.eval()
    torch.save(tm.state_dict(), weights_path("cct_7_3x1_32"))

    spec = create_model("cct_7_3x1_32", pretrained=True)
    params = spec.init(jax.random.PRNGKey(0))

    x = np.random.RandomState(1).randn(2, 32, 32, 3).astype(np.float32)
    with torch.no_grad():
        ref = tm(torch.tensor(x).permute(0, 3, 1, 2)).numpy()
    ours = np.asarray(spec.eval_logits_fn(params, jnp.asarray(x)))
    np.testing.assert_allclose(ours, ref, atol=5e-3, rtol=1e-2)
