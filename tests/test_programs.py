"""Compile-provenance tests (``blades_tpu/telemetry/programs.py``): the
per-program build ledger that attributes every trace/lower/compile to a
fingerprint, a cause, and a cache outcome.

Three layers, mirroring the module's contract:

- **registry semantics** (synthetic events, no jax): outcome and cause
  classification, warm-once emission, the unattributed bucket, the
  bounded in-process ledger, reset;
- **the tiling invariant** (real jax): on a multi-program run every
  watched dispatch's trace+lower+compile seconds land in exactly one
  scope, and the attributed share of the process-wide
  ``recorder.process_counters()`` mirror stays ≥ 95% (the ISSUE 16
  acceptance bar);
- **surfaces**: the schema-v7 ``program``/``cache_stats`` records
  validate, every committed trace under ``results/`` still validates,
  and the trace_summary / sweep_status rollups read the new records.

The reference has no compile accounting at all
(``src/blades/simulator.py:453-455`` records whole-round wall only);
the acceptance bar comes from ISSUE 16.
"""

import glob
import json
import os
import sys

import pytest

from blades_tpu.telemetry import (
    Recorder,
    get_recorder,
    set_recorder,
)
from blades_tpu.telemetry import programs
from blades_tpu.telemetry import recorder as recorder_mod
from blades_tpu.telemetry import schema as tschema

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from sweep_status import summarize_programs  # noqa: E402
from trace_summary import summarize  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_registry():
    prev = get_recorder()
    programs.reset()
    yield
    set_recorder(prev)
    programs.reset()


def _synthetic_build(trace_s=0.2, compile_s=0.5, compiles=1):
    """Feed one build's worth of counter events into the open scope the
    way install_jax_monitoring's listeners would."""
    if trace_s:
        programs._observe("xla.trace_s", trace_s)
    if compile_s:
        programs._observe("xla.compile_s", compile_s)
    if compiles:
        programs._observe("xla.compiles", compiles)


# ------------------------------------------------------- registry semantics


def test_cold_build_emits_program_record_with_cause():
    rec = Recorder(enabled=True)
    set_recorder(rec)
    with programs.watch("t/round", shapes=(4, 8), donation=(0,)):
        _synthetic_build()
    recs = [r for r in rec.records if r["t"] == "program"]
    assert len(recs) == 1
    r = recs[0]
    assert r["program"] == "t/round"
    assert r["outcome"] == "cold"
    assert r["cause"] == "new-fingerprint"
    assert r["compiles"] == 1 and r["compile_s"] == 0.5
    assert len(r["fingerprint"]) == 12  # derived sha256 prefix
    # deterministic fallback fingerprint: same identity -> same fp
    assert r["fingerprint"] == programs.derive_fingerprint(
        "t/round", programs._key_str((4, 8)), programs._key_str((0,))
    )


def test_warm_reuse_emits_at_most_once_per_program():
    rec = Recorder(enabled=True)
    set_recorder(rec)
    with programs.watch("t/round", fingerprint="fp1"):
        _synthetic_build()
    for _ in range(3):  # three warm dispatches, no build events
        with programs.watch("t/round", fingerprint="fp1"):
            pass
    recs = [r for r in rec.records if r["t"] == "program"]
    assert [r["outcome"] for r in recs] == ["cold", "warm-reuse"]
    assert "cause" not in recs[1]
    snap = programs.snapshot()
    assert snap["programs"]["fp1"]["warm"] == 3
    assert snap["programs"]["fp1"]["builds"] == 1


def test_persistent_cache_hit_outcome():
    # traced+lowered but zero backend compiles: the single-core cost the
    # persistent XLA cache does NOT absorb
    with programs.watch("t/cached", fingerprint="fpc"):
        programs._observe("xla.trace_s", 0.3)
        programs._observe("xla.cache_hits", 1)
    ev = programs.events()[-1]
    assert ev["outcome"] == "persistent-cache-hit"
    assert ev["cause"] == "new-fingerprint"
    assert ev["cache_hits"] == 1


def test_shape_and_donation_change_causes():
    with programs.watch("t/f", shapes=(4,), donation=(0,)):
        _synthetic_build()
    with programs.watch("t/f", shapes=(8,), donation=(0,)):
        _synthetic_build()
    with programs.watch("t/f", shapes=(8,), donation=()):
        _synthetic_build()
    causes = [e["cause"] for e in programs.events()]
    assert causes == ["new-fingerprint", "shape-change", "donation-change"]


def test_eviction_cause_via_note_eviction():
    with programs.watch("t/g", fingerprint="fpg", shapes=(4,)):
        _synthetic_build()
    programs.note_eviction("fpg")
    with programs.watch("t/g", fingerprint="fpg", shapes=(4,)):
        _synthetic_build()
    assert programs.events()[-1]["cause"] == "cache-eviction"
    # rebuilding the SAME (fingerprint, shapes) again is an eviction too,
    # even without an explicit note (the executable must have been lost)
    with programs.watch("t/g", fingerprint="fpg", shapes=(4,)):
        _synthetic_build()
    assert programs.events()[-1]["cause"] == "cache-eviction"


def test_cause_hint_wins_for_first_build():
    with programs.watch("t/eval", cause_hint="first-eval"):
        _synthetic_build()
    assert programs.events()[-1]["cause"] == "first-eval"


def test_unattributed_bucket_and_coverage():
    with programs.watch("t/h"):
        programs._observe("xla.trace_s", 0.9)
        programs._observe("xla.compiles", 1)
    # a build with NO open scope folds into the unattributed bucket
    programs._observe("xla.trace_s", 0.1)
    snap = programs.snapshot()
    assert snap["attributed"]["trace_s"] == pytest.approx(0.9)
    assert snap["unattributed"]["trace_s"] == pytest.approx(0.1)
    assert snap["coverage"] == pytest.approx(0.9)


def test_nested_scopes_attribute_to_innermost():
    with programs.watch("t/outer", fingerprint="fpo"):
        with programs.watch("t/inner", fingerprint="fpi"):
            _synthetic_build()
    snap = programs.snapshot()
    assert snap["programs"]["fpi"]["builds"] == 1
    assert snap["programs"]["fpo"]["builds"] == 0  # warm-reuse only


def test_events_ledger_is_bounded(monkeypatch):
    monkeypatch.setattr(programs, "_MAX_EVENTS", 8)
    for i in range(20):
        with programs.watch(f"t/p{i}"):
            _synthetic_build()
    assert len(programs.events()) <= 8
    assert programs.snapshot()["dropped"] > 0
    # the survivors are the NEWEST records
    assert programs.events()[-1]["program"] == "t/p19"


def test_disabled_recorder_emits_nothing_but_ledger_keeps_accounting():
    set_recorder(None)  # NULL recorder: disabled
    with programs.watch("t/quiet", fingerprint="fpq"):
        _synthetic_build()
    assert get_recorder().records == []
    assert programs.events()[-1]["fingerprint"] == "fpq"
    assert programs.snapshot()["programs"]["fpq"]["builds"] == 1


def test_reset_clears_everything():
    with programs.watch("t/r"):
        _synthetic_build()
    programs._observe("xla.trace_s", 0.1)
    programs.reset()
    snap = programs.snapshot()
    assert snap["programs"] == {} and snap["emitted"] == 0
    assert snap["attributed"] == {} and snap["unattributed"] == {}
    assert snap["coverage"] == 1.0


def test_program_and_cache_stats_records_validate_against_schema():
    rec = Recorder(enabled=True)
    set_recorder(rec)
    with programs.watch("t/s", shapes=(4,), donation=(0,)):
        _synthetic_build()
    from blades_tpu.sweeps import EngineCache

    cache = EngineCache()
    cache.put("k1", object(), build_s=0.5)
    cache.get("k1")
    rec.event("cache_stats", ts=1.0, **cache.stats())
    sch = tschema.load_schema()
    for r in rec.records:
        errs = tschema.validate_record(r, sch)
        assert not errs, (r, errs)


# ------------------------------------------------------------- EngineCache


def test_engine_cache_per_key_stats_and_lru_eviction():
    from blades_tpu.sweeps import EngineCache

    cache = EngineCache(max_entries=2)
    cache.put("a", 1, build_s=0.5)
    cache.put("b", 2, build_s=0.7)
    assert cache.get("a") == 1
    assert cache.get("missing") is None
    cache.put("c", 3)  # evicts b (a was just used)
    st = cache.stats()
    assert st["entries"] == 2 and st["evictions"] == 1
    assert st["hits"] == 1 and st["misses"] == 1
    # stats persist across eviction (b keeps its history for the
    # affinity signal even after its entry is dropped)
    assert set(st["by_key"]) >= {"a", "b", "c"}
    assert st["by_key"]["a"]["hits"] == 1
    assert st["by_key"]["a"]["build_s"] == 0.5
    assert cache.get("b") is None  # evicted
    # the eviction was reported to the provenance registry: the next
    # build of that fingerprint is attributed cache-eviction
    with programs.watch("t/engine", fingerprint="b"):
        _synthetic_build()
    assert programs.events()[-1]["cause"] == "cache-eviction"


# ------------------------------------------------- tiling invariant (jax)


def test_tiling_invariant_on_multi_program_run(tmp_path):
    """ISSUE 16 acceptance: on a fresh multi-program run (engine round +
    eval + dataset sampler programs), the per-program trace+lower+compile
    seconds sum to >= 95% of the process-wide ``xla.*`` mirror over the
    same window — every watched dispatch's build cost lands in exactly
    one scope."""
    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic
    from blades_tpu.sweeps import EngineCache

    before = recorder_mod.process_counters()
    programs.reset()
    ds = Synthetic(num_clients=4, train_size=64, test_size=32, noise=0.3,
                   cache=False)
    cache = EngineCache()
    sim = Simulator(ds, log_path=str(tmp_path / "out"), seed=0,
                    aggregator="mean")
    sim.run("mlp", global_rounds=2, local_steps=1, client_lr=0.2,
            validate_interval=1, train_batch_size=8, engine_cache=cache)
    after = recorder_mod.process_counters()
    mirror = sum(
        after.get(f"xla.{k}", 0.0) - before.get(f"xla.{k}", 0.0)
        for k in ("trace_s", "lower_s", "compile_s")
    )
    snap = programs.snapshot()
    attributed = sum(
        snap["attributed"].get(k, 0.0) for k in programs.SECONDS_FIELDS
    )
    assert mirror > 0, "run compiled nothing — the fixture is broken"
    assert attributed >= 0.95 * mirror, (
        f"attributed {attributed:.3f}s < 95% of mirror {mirror:.3f}s "
        f"(snapshot: {snap['attributed']} vs {snap['unattributed']})"
    )
    assert snap["coverage"] >= 0.95
    # the expected program population: round + eval + sampler, each with
    # a build outcome and a classified cause
    labels = {v["program"] for v in snap["programs"].values()
              if v["builds"]}
    assert "engine/round" in labels
    assert "dataset/sample_round" in labels
    assert any(lbl.startswith("engine/eval") for lbl in labels)
    first_build = {}
    for e in programs.events():
        if e["outcome"] != "warm-reuse":
            first_build.setdefault(e["program"], e)
    assert first_build["engine/round"]["cause"] == "new-fingerprint"
    assert any(e.get("cause") == "first-eval"
               for e in programs.events() if "eval" in e["program"])
    # any LATER rebuild of an already-built identity must carry an
    # attributed cause, never a bare new-fingerprint (the whole point:
    # an unexplained recompile is nameable, e.g. the 8-device CPU mesh's
    # donated-state second-round rebuild surfaces as cache-eviction)
    for e in programs.events():
        if (e["outcome"] != "warm-reuse"
                and e is not first_build[e["program"]]):
            assert e.get("cause") in programs.CAUSES
    # the trace carries the same records, schema-valid
    trace = os.path.join(str(tmp_path / "out"), "telemetry.jsonl")
    errs = tschema.validate_trace(trace)
    assert not errs, errs[:3]
    trace_recs = [json.loads(l) for l in open(trace) if l.strip()]
    prog_recs = [r for r in trace_recs if r.get("t") == "program"]
    assert {r["program"] for r in prog_recs} >= {"engine/round",
                                                 "dataset/sample_round"}
    # second run from the warm engine cache: the round program is
    # warm-reused (zero build-outcome records for it), and the cache's
    # hit stats agree with the emitted engine_cache hit records
    n_before = len(programs.events())
    sim2 = Simulator(ds, log_path=str(tmp_path / "out2"), seed=0,
                     aggregator="mean")
    sim2.run("mlp", global_rounds=1, local_steps=1, client_lr=0.2,
             validate_interval=1, train_batch_size=8, engine_cache=cache)
    window = programs.events()[n_before:]
    assert not any(
        e["outcome"] != "warm-reuse" and e["program"] == "engine/round"
        for e in window
    ), f"warm engine round rebuilt: {window}"
    st = cache.stats()
    trace2 = os.path.join(str(tmp_path / "out2"), "telemetry.jsonl")
    hit_recs = [
        r for p in (trace, trace2) for r in
        (json.loads(l) for l in open(p) if l.strip())
        if r.get("t") == "engine_cache"
    ]
    assert st["hits"] == len(hit_recs) == 1
    assert st["misses"] == 1 and st["entries"] == 1
    (key_stats,) = st["by_key"].values()
    assert key_stats["hits"] == 1 and key_stats["build_s"] > 0

    # surface rollups read the records
    roll = summarize_programs(trace_recs)
    assert roll is not None and roll["programs"] >= 2
    assert roll["top"][0]["build_s"] >= roll["top"][-1]["build_s"]
    summary = summarize(trace_recs)
    prov = summary["provenance"]
    assert prov["builds"] >= 2 and prov["cold"] >= 1


# ------------------------------------------------------- committed traces


def test_every_committed_trace_validates_against_schema():
    """Satellite 1: sweep every committed trace under results/ through
    the schema checker — a schema bump that strands an older committed
    artifact fails here, not in the next debugging session."""
    paths = sorted(
        glob.glob(os.path.join(REPO, "results", "**", "*.jsonl"),
                  recursive=True)
    )
    traced = [
        p for p in paths
        if os.path.basename(p) in (
            "telemetry.jsonl", "sweep_trace.jsonl", "service_trace.jsonl"
        )
    ]
    assert traced, "no committed traces found under results/"
    sch = tschema.load_schema()
    for p in traced:
        errs = tschema.validate_trace(p, sch)
        assert not errs, (p, errs[:3])
