"""Decentralized/gossip + async aggregator tests (reference internals
``_DecentralizedAggregator``, ``_AnchorClipping``, ``_AsyncMean``,
``_AsyncCenteredClipping`` — mean.py:42-116, centeredclipping.py:52-137)."""

import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.aggregators import (
    AnchorClipping,
    Asynccenteredclipping,
    Asyncmean,
    DecentralizedMixing,
    fully_connected_adjacency,
    get_aggregator,
    metropolis_weights,
    ring_adjacency,
    torus_adjacency,
)


def test_metropolis_weights_doubly_stochastic():
    for adj in (ring_adjacency(7), torus_adjacency(3, 4), fully_connected_adjacency(5)):
        w = metropolis_weights(adj)
        np.testing.assert_allclose(w.sum(axis=0), 1.0, atol=1e-12)
        np.testing.assert_allclose(w.sum(axis=1), 1.0, atol=1e-12)
        np.testing.assert_allclose(w, w.T, atol=1e-12)
        assert (w >= 0).all()
        # off-graph entries must be zero
        assert (w[~adj & ~np.eye(len(adj), dtype=bool)] == 0).all()


def test_mixing_matches_per_node_loop():
    """W @ U row i == sum_j W[i,j] u_j (the reference's per-node loop)."""
    rng = np.random.RandomState(0)
    w = metropolis_weights(ring_adjacency(6))
    u = rng.randn(6, 11).astype(np.float32)
    mixed = DecentralizedMixing(w).mix(jnp.asarray(u))
    for i in range(6):
        expect = sum(w[i, j] * u[j] for j in range(6))
        np.testing.assert_allclose(np.asarray(mixed[i]), expect, rtol=1e-5)


def test_gossip_reaches_consensus():
    """Repeated mixing with a doubly-stochastic W over a connected graph
    converges every row to the global average."""
    rng = np.random.RandomState(1)
    u = rng.randn(8, 5).astype(np.float32)
    mixer = DecentralizedMixing(metropolis_weights(ring_adjacency(8)))
    x = jnp.asarray(u)
    for _ in range(200):
        x = mixer.mix(x)
    np.testing.assert_allclose(
        np.asarray(x), np.tile(u.mean(axis=0), (8, 1)), atol=1e-4
    )


def test_anchor_clipping_limits_outlier_influence():
    """With anchors at 0 and a huge outlier row, each clipped contribution
    has norm <= tau, so the mixed result stays bounded."""
    k, d, tau = 6, 9, 1.0
    w = metropolis_weights(fully_connected_adjacency(k))
    agg = AnchorClipping(w, tau=tau)
    anchors = agg.init_state(k, d)
    u = np.zeros((k, d), np.float32)
    u[0] = 1e6  # byzantine blow-up
    mixed, new_anchors = agg.mix_with_state(jnp.asarray(u), anchors)
    assert float(jnp.abs(mixed).max()) <= tau + 1e-5
    # anchors advanced by the mixed result
    np.testing.assert_allclose(np.asarray(new_anchors), np.asarray(mixed), atol=1e-6)


def test_async_mean_denominator_is_total():
    u = jnp.asarray(np.arange(12, dtype=np.float32).reshape(4, 3))
    present = jnp.array([True, True, False, False])
    agg = get_aggregator("asyncmean")
    out, _ = agg.aggregate(u, (), present=present)
    np.testing.assert_allclose(np.asarray(out), (u[0] + u[1]) / 4.0)
    full, _ = agg.aggregate(u, ())
    np.testing.assert_allclose(np.asarray(full), np.asarray(u.mean(axis=0)))


def test_async_centered_clipping_damps_by_total():
    k, d = 4, 6
    rng = np.random.RandomState(2)
    u = rng.randn(k, d).astype(np.float32) * 0.1
    present = jnp.array([True, False, True, True])
    agg = get_aggregator("asynccenteredclipping", tau=10.0)
    state = agg.init_state(k, d)
    out, state = agg.aggregate(jnp.asarray(u), state, present=present)
    expect = u[[0, 2, 3]].sum(axis=0) / k  # small updates: no clipping active
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5)
    # stateful: momentum carried to the next round
    out2, _ = agg.aggregate(jnp.zeros((k, d)), state, present=present)
    assert np.abs(np.asarray(out2)).sum() < np.abs(np.asarray(out)).sum() + 1e-6


def test_metropolis_rejects_directed_graph():
    adj = ring_adjacency(5)
    adj[0, 1] = False  # break symmetry
    with pytest.raises(ValueError):
        metropolis_weights(adj)


def test_anchor_clipping_matches_naive_pairwise():
    """Gram-trick mixing == the direct [K,K,D] computation."""
    rng = np.random.RandomState(3)
    k, d, tau = 5, 7, 0.7
    w = metropolis_weights(ring_adjacency(k))
    u = rng.randn(k, d).astype(np.float32)
    a = rng.randn(k, d).astype(np.float32) * 0.5
    agg = AnchorClipping(w, tau=tau)
    mixed, _ = agg.mix_with_state(jnp.asarray(u), jnp.asarray(a))
    # naive reference computation
    expect = np.zeros((k, d), np.float32)
    for r in range(k):
        for s in range(k):
            diff = u[s] - a[r]
            scl = min(1.0, tau / max(np.linalg.norm(diff), 1e-12))
            expect[r] += w[r, s] * (a[r] + diff * scl)
    np.testing.assert_allclose(np.asarray(mixed), expect, rtol=2e-3, atol=2e-4)
