"""Experiment-script smoke tests: the flag system and config surfaces.

The reference's scripts are its only "CLI" (SURVEY.md C16/L6); these tests
pin the parity pieces that are cheap to check without a training run —
``parse_arguments`` defaults, derived per-attack/per-aggregator kwarg
dicts (ref ``scripts/args.py:32-43``), and the config-encoding log-dir
name (ref ``args.py:44-56``).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

from args import parse_arguments  # noqa: E402


def test_defaults_match_reference():
    o = parse_arguments([])
    assert o.global_round == 400 and o.local_round == 50
    assert o.agg == "clippedclustering" and o.attack == "signflipping"
    assert o.num_clients == 20 and o.num_byzantine == 8


def test_budget_aggs_receive_byzantine_count():
    o = parse_arguments(["--num_byzantine", "3"])
    for name in ("trimmedmean", "krum", "multikrum", "dnc"):
        assert o.agg_args[name] == {"num_byzantine": 3}
    assert o.attack_args["ipm"] == {"epsilon": 0.5}


def test_log_dir_encodes_config():
    o = parse_arguments(["--dataset", "cifar10", "--attack", "alie",
                         "--agg", "median", "--num_byzantine", "5"])
    assert "cifar10" in o.log_dir
    assert "b5" in o.log_dir
    assert "alie" in o.log_dir and "median" in o.log_dir


def test_compat_flags_accepted():
    # GPU-era knobs parse without error and change nothing else
    o = parse_arguments(["--use-cuda", "--num_gpus", "4", "--num_actors", "10"])
    assert o.num_gpus == 4  # accepted, ignored downstream
