"""Citation-convention lint as a tier-1 test.

CLAUDE.md convention: every ``blades_tpu/`` module docstring cites its
reference counterpart as ``file:line`` (the judge checks parity against
SURVEY.md §2). ``scripts/check_citations.py`` is the single owner of the
rule; running it from the suite makes drift fail fast."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

import check_citations  # noqa: E402


def test_every_module_cites_its_reference():
    violations = check_citations.check_all()
    assert violations == [], "\n".join(violations)


def test_lint_catches_a_bare_module(tmp_path):
    """The lint actually bites: a module with no docstring, and one that
    never mentions the reference, are both violations."""
    bare = tmp_path / "bare.py"
    bare.write_text("x = 1\n")
    assert check_citations.check_module(str(bare)) is not None
    chatty = tmp_path / "chatty.py"
    chatty.write_text('"""Does things with arrays."""\n')
    assert check_citations.check_module(str(chatty)) is not None
    cited = tmp_path / "cited.py"
    cited.write_text('"""Reference: ``src/blades/simulator.py:453-455``."""\n')
    assert check_citations.check_module(str(cited)) is None
