"""Load the reference implementation standalone for differential testing.

The reference stack (torch + scipy + sklearn) is fully installed in this
image; the only missing dependency is ``ray``, imported solely at
``src/blades/client.py:6`` for trainer-mode ``train.torch.prepare_model``.
We install a minimal fake ``ray.train`` and set ``blades.__path__`` to the
reference source tree, so every other reference module — the real
``BladesClient``/``ByzantineClient``, all aggregators, all attacker clients —
loads and runs verbatim. Differential tests then feed identical inputs to the
reference's actual code and to blades_tpu.

Environment shim (behavior-preserving): sklearn >= 1.4 removed the
``affinity=`` kwarg of ``AgglomerativeClustering`` (renamed ``metric=`` in
1.2); the reference (``aggregators/clustering.py:39``,
``clippedclustering.py:60``) passes ``affinity='precomputed'``. The shim maps
the kwarg name only.
"""

from __future__ import annotations

import importlib
import sys
import types

REF_SRC = "/root/reference/src"


class _AggloCompat:
    """sklearn AgglomerativeClustering with the pre-1.4 ``affinity=`` kwarg."""

    def __init__(self, *args, affinity=None, **kwargs):
        from sklearn.cluster import AgglomerativeClustering

        if affinity is not None:
            kwargs["metric"] = affinity
        self._inner = AgglomerativeClustering(*args, **kwargs)

    def fit(self, X):
        self._inner.fit(X)
        self.labels_ = self._inner.labels_
        return self


def load_reference():
    """Import the reference ``blades`` package from /root/reference/src.

    Returns the ``blades`` namespace module with ``client``, ``aggregators``
    (incl. unexported ``fltrust``/``byzantinesgd``) and ``attackers.*client``
    submodules loaded.
    """
    existing = sys.modules.get("blades")
    if existing is not None and getattr(existing, "__ref_loaded__", False):
        return existing

    # torch >= 1.13 removed torch._six; the reference's torch_utils.py:7
    # only takes ``inf`` from it
    if "torch._six" not in sys.modules:
        six = types.ModuleType("torch._six")
        six.inf = float("inf")
        sys.modules["torch._six"] = six

    ray = types.ModuleType("ray")
    ray_train = types.ModuleType("ray.train")
    ray_train.torch = types.SimpleNamespace(prepare_model=lambda m, **k: m)
    ray.train = ray_train
    sys.modules["ray"] = ray
    sys.modules["ray.train"] = ray_train

    blades = types.ModuleType("blades")
    blades.__path__ = [REF_SRC + "/blades"]
    blades.__ref_loaded__ = True
    sys.modules["blades"] = blades

    blades.client = importlib.import_module("blades.client")
    blades.aggregators = importlib.import_module("blades.aggregators")
    # not re-exported by the reference __init__ — load explicitly
    importlib.import_module("blades.aggregators.centeredclipping")
    importlib.import_module("blades.aggregators.fltrust")
    importlib.import_module("blades.aggregators.byzantinesgd")
    blades.aggregators.clustering.AgglomerativeClustering = _AggloCompat
    blades.aggregators.clippedclustering.AgglomerativeClustering = _AggloCompat

    blades.attackers = importlib.import_module("blades.attackers")
    for name in ("alie", "ipm", "noise", "labelflipping", "signflipping"):
        importlib.import_module(f"blades.attackers.{name}client")
    return blades
