"""Ring attention vs full-softmax oracle on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from blades_tpu.ops.ring_attention import attention_reference, ring_attention

SEQ = "seq"


def _mesh():
    return Mesh(np.array(jax.devices()), (SEQ,))


def _qkv(key, b=2, n=64, h=4, d=16):
    ks = jax.random.split(key, 3)
    shape = (b, n, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


def test_matches_full_attention():
    mesh = _mesh()
    q, k, v = _qkv(jax.random.PRNGKey(0))
    out = ring_attention(q, k, v, mesh, SEQ)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_matches_full_attention_with_mask():
    mesh = _mesh()
    q, k, v = _qkv(jax.random.PRNGKey(1), b=3, n=32)
    lens = jnp.array([[5], [32], [17]])
    mask = jnp.arange(32)[None, :] < lens
    out = ring_attention(q, k, v, mesh, SEQ, kv_mask=mask)
    ref = attention_reference(q, k, v, kv_mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_sharded_inputs_stay_sharded():
    mesh = _mesh()
    q, k, v = _qkv(jax.random.PRNGKey(2), n=128)
    spec = NamedSharding(mesh, P(None, SEQ, None, None))
    q, k, v = (jax.device_put(t, spec) for t in (q, k, v))
    out = jax.jit(
        lambda a, b_, c: ring_attention(a, b_, c, mesh, SEQ)
    )(q, k, v)
    assert out.sharding.spec == spec.spec
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_gradients_flow():
    mesh = _mesh()
    q, k, v = _qkv(jax.random.PRNGKey(3), n=16)

    def loss_ring(q_, k_, v_):
        return jnp.sum(ring_attention(q_, k_, v_, mesh, SEQ) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(attention_reference(q_, k_, v_) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_long_sequence_memory_shape():
    """N=1024 over 8 devices: each device sees N/8 of Q and one rotating
    K/V block — the whole [N, N] score matrix never materializes."""
    mesh = _mesh()
    q, k, v = _qkv(jax.random.PRNGKey(4), b=1, n=1024, h=2, d=8)
    out = ring_attention(q, k, v, mesh, SEQ)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)


def test_long_text_transformer_consumes_ring():
    """The long-context model family routes through ring attention and
    matches the dense-attention model bit-for-bit in structure (same params,
    same logits up to fp tolerance)."""
    from blades_tpu.models import long_text_transformer
    from blades_tpu.models.text import TextCCT

    mesh = _mesh()
    model_ring = long_text_transformer(num_classes=4, mesh=mesh)
    model_full = long_text_transformer(num_classes=4, mesh=None)
    assert isinstance(model_ring, TextCCT) and model_ring.ring_mesh is mesh

    key = jax.random.PRNGKey(3)
    tokens = jax.random.randint(key, (2, 64), 0, 1000)
    lens = jnp.array([[40], [64]])
    mask = jnp.arange(64)[None, :] < lens

    params = model_full.init(jax.random.PRNGKey(0), tokens, mask)
    out_full = model_full.apply(params, tokens, mask)
    out_ring = model_ring.apply(params, tokens, mask)
    assert out_ring.shape == (2, 4)
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_full), atol=3e-5
    )
