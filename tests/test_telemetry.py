"""Telemetry subsystem tests: recorder semantics, the disabled-is-free
contract, XLA compile accounting, simulator trace integration, aggregator
forensics under attack, and the trace_summary CLI.

The reference has nothing to test here (it logs only whole-round wall time,
``src/blades/simulator.py:453-455``); the acceptance bar instead comes from
ISSUE/docs: the round-span total must track the engine-reported round wall
time within 10%, and defense decisions must be recorded per round.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.telemetry import (
    NULL_RECORDER,
    Recorder,
    get_recorder,
    install_jax_monitoring,
    set_recorder,
)
from blades_tpu.telemetry import recorder as recorder_mod

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

from trace_summary import format_table, load_records, summarize  # noqa: E402


@pytest.fixture(autouse=True)
def _restore_global_recorder():
    prev = get_recorder()
    yield
    set_recorder(prev)


# ------------------------------------------------------------------- recorder


def test_span_nesting_builds_paths():
    rec = Recorder(enabled=True)
    with rec.span("round"):
        with rec.span("dispatch"):
            pass
        with rec.span("sync", round=3):
            pass
    paths = [r["path"] for r in rec.records if r["t"] == "span"]
    assert paths == ["round/dispatch", "round/sync", "round"]
    sync = [r for r in rec.records if r.get("path") == "round/sync"][0]
    assert sync["round"] == 3 and sync["dur_s"] >= 0.0


def test_counters_round_record_deltas_and_cumulative():
    rec = Recorder(enabled=True)
    rec.counter("x")
    rec.counter("x")
    rec.counter("secs", 0.5)
    rec.round_record(1, wall_s=0.1)
    rec.counter("x")
    rec.round_record(2, wall_s=0.2)
    rounds = [r for r in rec.records if r["t"] == "round"]
    assert rounds[0]["counters"] == {"x": 2, "secs": 0.5}
    assert rounds[1]["counters"] == {"x": 1}  # delta, not cumulative
    assert rec.counters == {"x": 3, "secs": 0.5}  # cumulative survives


def test_flush_writes_jsonl_once(tmp_path):
    path = str(tmp_path / "t" / "trace.jsonl")
    rec = Recorder(enabled=True, path=path)
    with rec.span("a"):
        pass
    rec.counter("c")
    rec.round_record(1)
    rec.flush()
    lines = [json.loads(l) for l in open(path)]
    assert [l["t"] for l in lines] == ["meta", "span", "round"]
    rec.flush()  # nothing pending: no duplicate writes
    assert len(open(path).readlines()) == 3
    rec.event("late", k=1)
    rec.flush()
    assert json.loads(open(path).readlines()[-1])["t"] == "late"


def test_disabled_recorder_does_zero_work(tmp_path, monkeypatch):
    """The hot-path contract (single-core box): BLADES_TELEMETRY=0 means no
    clock reads, no file opens, no writes — proven by making them raise."""
    monkeypatch.setenv("BLADES_TELEMETRY", "0")
    path = str(tmp_path / "never.jsonl")
    rec = Recorder(path=path)  # env-resolved: disabled
    assert rec.enabled is False

    def boom(*a, **k):
        raise AssertionError("disabled recorder touched the system")

    monkeypatch.setattr(recorder_mod.time, "perf_counter", boom)
    monkeypatch.setattr(recorder_mod.time, "time", boom)
    monkeypatch.setattr("builtins.open", boom)
    monkeypatch.setattr(recorder_mod.os, "makedirs", boom)
    with rec.span("round"):
        with rec.span("dispatch"):
            pass
    rec.counter("x")
    rec.gauge("g", 1)
    rec.event("e")
    rec.round_record(1, wall_s=0.1)
    rec.flush()
    rec.close()
    monkeypatch.undo()
    assert not os.path.exists(path)
    assert rec.records == [] and rec.counters == {}


def test_flush_sink_errors_never_propagate(tmp_path):
    """Telemetry must not take down the run it observes: an unwritable sink
    turns the batch into `dropped`, and a later flush retries."""
    target = tmp_path / "dir_is_a_file"
    target.write_text("")  # makedirs(path/..) will EEXIST-as-file below
    rec = Recorder(enabled=True, path=str(target / "sub" / "t.jsonl"))
    rec.event("x")
    rec.flush()  # OSError swallowed
    assert rec.dropped >= 1
    rec.event("y")
    rec.flush()
    assert rec.dropped >= 2  # still failing, still not raising


def test_crashed_run_still_leaves_a_trace(tmp_path):
    """A run that dies mid-round must leave meta + whatever was recorded +
    run_end in the trace (the post-mortem the subsystem exists for)."""
    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic

    ds = Synthetic(num_clients=4, train_size=200, test_size=40, cache=False)
    log = str(tmp_path / "out")
    sim = Simulator(ds, log_path=log, seed=0, aggregator="mean")

    def boom(rnd, state, m):
        raise RuntimeError("mid-round crash")

    with pytest.raises(RuntimeError, match="mid-round crash"):
        sim.run("mlp", global_rounds=3, local_steps=1, train_batch_size=8,
                validate_interval=3, on_round_end=boom)
    records = load_records(os.path.join(log, "telemetry.jsonl"))
    types = [r["t"] for r in records]
    assert types[0] == "meta"
    assert "compile" in types  # the pre-crash compiles made it to disk
    assert types[-1] == "run_end"
    assert records[-1]["rounds_completed"] == 0


def test_memory_only_buffer_is_bounded():
    rec = Recorder(enabled=True, max_buffer=10)
    for i in range(100):
        rec.event("e", i=i)
    assert len(rec.records) <= 10
    assert rec.dropped > 0
    # newest records survive
    assert rec.records[-1]["i"] == 99


def test_set_recorder_flushes_previous(tmp_path):
    path = str(tmp_path / "prev.jsonl")
    prev = Recorder(enabled=True, path=path)
    set_recorder(prev)
    prev.event("pending")
    set_recorder(Recorder(enabled=False))
    assert any(json.loads(l)["t"] == "pending" for l in open(path))
    assert get_recorder().enabled is False


def test_null_recorder_is_disabled():
    assert NULL_RECORDER.enabled is False


def test_jax_monitoring_counts_compiles():
    assert install_jax_monitoring()
    rec = Recorder(enabled=True)
    set_recorder(rec)
    # a closure jax has never seen -> guaranteed fresh backend compile
    salt = float(np.random.default_rng().integers(1, 2**31))
    jax.jit(lambda x: x * salt + 1.0)(jnp.arange(7.0)).block_until_ready()
    assert rec.counters.get("xla.compiles", 0) >= 1
    assert rec.counters.get("xla.compile_s", 0.0) > 0.0
    assert any(r["t"] == "compile" for r in rec.records)


# --------------------------------------------------- simulator trace + summary


def _run_sim(tmp_path, agg, agg_kws=None, rounds=2, **sim_kw):
    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic

    ds = Synthetic(
        num_clients=6, train_size=600, test_size=120, noise=0.3, cache=False
    )
    log = str(tmp_path / "out")
    sim = Simulator(
        ds, log_path=log, seed=0, aggregator=agg,
        aggregator_kws=agg_kws or {}, **sim_kw,
    )
    times = sim.run(
        "mlp", global_rounds=rounds, local_steps=2, client_lr=0.2,
        train_batch_size=8, validate_interval=1, collect_diagnostics=True,
    )
    return sim, times, os.path.join(log, "telemetry.jsonl")


def test_simulator_trace_round_total_tracks_wall_time(tmp_path):
    """Acceptance: a fresh 2-round MLP run's trace_summary round-span total
    is within 10% of the engine-reported round wall time; span tree +
    per-round records + compile accounting are all present; the reference
    stats file keeps its schema."""
    sim, times, trace = _run_sim(
        tmp_path, "trimmedmean", {"num_byzantine": 2},
        num_byzantine=2, attack="alie",
    )
    records = load_records(trace)
    summary = summarize(records)
    assert summary["rounds"]["count"] == 2
    round_total = summary["spans"]["round"]["total_s"]
    wall_total = sum(times)
    assert abs(round_total - wall_total) / wall_total < 0.10
    for stage in ("round/sample", "round/dispatch", "round/sync", "round/eval"):
        assert stage in summary["spans"], stage
    # compile accounting flowed through jax.monitoring
    assert summary["counters"].get("xla.compiles", 0) >= 1
    # the table renders (the CLI's happy path)
    table = format_table(summary)
    assert "round/dispatch" in table and "compiles:" in table
    # stats-file parity is untouched by telemetry (reference schema)
    from blades_tpu.utils.logging import read_stats

    types = {r["_meta"]["type"] for r in read_stats(str(tmp_path / "out"))}
    assert types == {"train", "variance", "test", "client_validation"}


def test_trimmedmean_forensics_under_alie_in_jsonl(tmp_path):
    sim, _, trace = _run_sim(
        tmp_path, "trimmedmean", {"num_byzantine": 2},
        num_byzantine=2, attack="alie",
    )
    defenses = [r for r in load_records(trace) if r["t"] == "defense"]
    assert len(defenses) == 2  # one per round
    d = defenses[0]
    assert len(d["trim_counts"]) == 6 and d["trim_b"] == 2
    assert 0.0 <= d["byz_trim_frac"] <= 1.0


def test_krum_forensics_under_alie_in_jsonl(tmp_path):
    sim, _, trace = _run_sim(
        tmp_path, "krum", {"num_byzantine": 2},
        num_byzantine=2, attack="alie",
    )
    defenses = [r for r in load_records(trace) if r["t"] == "defense"]
    assert len(defenses) == 2
    d = defenses[0]
    assert len(d["scores"]) == 6 and len(d["selected"]) == 1
    assert 0.0 <= d["byz_selected_frac"] <= 1.0
    # krum's pick is recorded AND consistent: the selected client exists
    assert 0 <= d["selected"][0] < 6


def test_telemetry_disabled_writes_no_trace(tmp_path, monkeypatch):
    monkeypatch.setenv("BLADES_TELEMETRY", "0")
    sim, times, trace = _run_sim(tmp_path, "mean", rounds=1)
    assert not os.path.exists(trace)
    assert len(times) == 1  # the run itself is unaffected
    # stats logging still works with telemetry off
    from blades_tpu.utils.logging import read_stats

    assert read_stats(str(tmp_path / "out"), "test")


def test_trace_summary_memory_section(tmp_path):
    """The engine memory gauges ride round records and surface as the
    summary's `memory` section (docs/performance.md 'Memory scaling'):
    max peak bytes + the layout fields from the latest round."""
    path = str(tmp_path / "mem.jsonl")
    rec = Recorder(enabled=True, path=path)
    rec.gauge("engine.peak_update_bytes", 123456)
    rec.gauge("engine.client_chunks", 4)
    rec.gauge("engine.chunk_size", 25)
    rec.gauge("engine.streaming", 1)
    rec.round_record(1, wall_s=0.1)
    rec.round_record(2, wall_s=0.1)
    rec.close()
    summary = summarize(load_records(path))
    assert summary["memory"] == {
        "peak_update_bytes": 123456,
        "streaming": 1,
        "client_chunks": 4,
        "chunk_size": 25,
    }
    assert "peak_update_bytes=123456" in format_table(summary)


def test_simulator_streaming_run_gauges_memory(tmp_path):
    """E2E: a streaming simulator run records [chunk, D]-scale
    peak_update_bytes (vs the dense [K, D]) in its trace, and the padded
    non-divisor chunk count runs end to end."""
    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic

    log = str(tmp_path / "out")
    sim = Simulator(
        dataset=Synthetic(
            num_clients=6, train_size=240, test_size=60, noise=0.3,
            cache=False,
        ),
        aggregator="median",
        log_path=log,
    )
    sim.run(
        "mlp", global_rounds=1, local_steps=1, client_lr=0.2,
        train_batch_size=4, validate_interval=1,
        # 6 % 4 != 0: ceil chunks of 2, renormalized to 3 chunks
        client_chunks=4, streaming=True,
    )
    summary = summarize(load_records(os.path.join(log, "telemetry.jsonl")))
    mem = summary["memory"]
    assert mem["streaming"] == 1 and mem["client_chunks"] == 3
    assert mem["chunk_size"] == 2
    assert mem["peak_update_bytes"] == 2 * sim.engine.dim * 4
    # retain_updates needs the matrix streaming never builds
    with pytest.raises(ValueError, match="retain_updates"):
        sim.run(
            "mlp", global_rounds=1, streaming=True, retain_updates=True,
        )


def test_trace_summary_cli_main(tmp_path, capsys):
    import trace_summary

    path = str(tmp_path / "t.jsonl")
    rec = Recorder(enabled=True, path=path)
    with rec.span("round"):
        pass
    rec.round_record(1, wall_s=0.5)
    rec.close()
    assert trace_summary.main([path]) == 0
    out = capsys.readouterr().out
    assert "round" in out and "rounds: 1" in out
    assert trace_summary.main([path, "--json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["rounds"]["count"] == 1
    empty = str(tmp_path / "empty.jsonl")
    open(empty, "w").close()
    assert trace_summary.main([empty]) == 1  # no records -> error exit


def test_real_trace_validates_against_schema(tmp_path):
    """Schema lint acceptance: every record a REAL run writes (spans,
    rounds, compiles, defense forensics, in-graph metrics, the measured
    program profile) validates against docs/telemetry_schema.json —
    record drift fails here, not in a consumer weeks later."""
    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic
    from blades_tpu.telemetry.schema import load_schema, validate_records

    ds = Synthetic(num_clients=6, train_size=240, test_size=60, cache=False)
    log = str(tmp_path / "out")
    sim = Simulator(ds, log_path=log, seed=0, aggregator="trimmedmean",
                    aggregator_kws={"num_byzantine": 2},
                    num_byzantine=2, attack="signflipping")
    sim.run("mlp", global_rounds=2, local_steps=1, train_batch_size=8,
            validate_interval=2, collect_diagnostics=True,
            round_metrics=True,
            fault_model={"dropout_rate": 0.3})
    records = load_records(os.path.join(log, "telemetry.jsonl"))
    types = {r["t"] for r in records}
    # the new record families are actually present in what we validated
    assert {"metrics", "memory", "round", "span", "faults"} <= types
    assert validate_records(records) == []

    # drift detection: unknown types and undeclared fields on closed
    # types are errors
    schema = load_schema()
    errs = validate_records(
        [{"t": "brand_new_record"}, {"t": "faults", "round": 1}], schema
    )
    assert any("unknown record type" in e for e in errs)
    assert any("missing required" in e for e in errs)
    errs = validate_records(
        [{"t": "run_end", "rounds_completed": 1, "surprise": 2}], schema
    )
    assert any("undeclared field" in e for e in errs)


def test_schema_cli_main(tmp_path, capsys):
    from blades_tpu.telemetry import schema as schema_mod

    good = tmp_path / "good.jsonl"
    good.write_text('{"t": "compile", "dur_s": 1.5}\n')
    assert schema_mod.main([str(good)]) == 0
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"t": "nope"}\n')
    assert schema_mod.main([str(bad)]) == 1
    assert "unknown record type" in capsys.readouterr().out
    # a lint that validated nothing must not pass
    empty = tmp_path / "empty.jsonl"
    empty.write_text("not json at all\n")
    assert schema_mod.main([str(empty)]) == 1
    assert "no parseable" in capsys.readouterr().out


def test_flush_discipline_under_block_streaming_metrics(tmp_path, monkeypatch):
    """Recorder flush discipline under the new record volume: a
    block+streaming run with MetricPack enabled still flushes once per
    block boundary (plus the documented fixed points: the post-meta
    flush, run_end), performs NO per-record I/O, and the buffered size
    stays bounded (nothing dropped)."""
    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic

    flushes = []
    real_flush = Recorder.flush

    def counting_flush(self):
        if self.path is not None:  # only the run's file-backed recorder
            flushes.append(len(self._pending))
        return real_flush(self)

    monkeypatch.setattr(Recorder, "flush", counting_flush)
    ds = Synthetic(num_clients=6, train_size=240, test_size=60, cache=False)
    log = str(tmp_path / "out")
    sim = Simulator(ds, log_path=log, seed=0, aggregator="median")
    sim.run("mlp", global_rounds=4, local_steps=1, train_batch_size=8,
            validate_interval=4, round_metrics=True, streaming=True,
            client_chunks=3, block_size=2)
    rec = sim.telemetry
    assert rec.dropped == 0
    # 4 rounds in 2 blocks: one flush after the meta record, one per
    # block boundary, one at run_end (+ at most one from recorder swap)
    assert len(flushes) <= 5
    # per-round records batched per block: at least one flush carried a
    # multi-round batch (metrics + round + span records for 2 rounds)
    assert max(flushes) >= 4
    # buffer stayed far below the bound (flushes actually drained it)
    assert all(n < rec.max_buffer // 2 for n in flushes)
    # and the trace really carries per-round metrics for all 4 rounds
    recs = load_records(os.path.join(log, "telemetry.jsonl"))
    assert [r["round"] for r in recs if r["t"] == "metrics"] == [1, 2, 3, 4]


def test_heartbeat_margin_gauge_and_warning(tmp_path, monkeypatch):
    """The heartbeat-margin satellite: beats gauge their interval, and a
    beat landing within 25% of BLADES_HEARTBEAT_TIMEOUT emits a
    schema-valid heartbeat_margin warning record."""
    import time as _time

    from blades_tpu.supervision import heartbeat as hb
    from blades_tpu.telemetry.schema import load_schema, validate_record

    rec = Recorder(enabled=True)
    set_recorder(rec)
    hb_file = str(tmp_path / "hb")
    monkeypatch.setattr(hb, "_last_beat_ts", None)
    monkeypatch.setenv(hb.HEARTBEAT_ENV, hb_file)
    monkeypatch.setenv(hb.TIMEOUT_ENV, "0.02")
    hb.beat(round_idx=1)
    assert rec.gauges.get("heartbeat.interval_s") is None  # first beat: no gap
    _time.sleep(0.03)  # eat >75% of the 20ms budget
    hb.beat(round_idx=2)
    assert rec.gauges["heartbeat.interval_s"] >= 0.02
    assert rec.gauges["heartbeat.margin_s"] <= 0.0
    margins = [r for r in rec.records if r["t"] == "heartbeat_margin"]
    assert len(margins) == 1 and margins[0]["round"] == 2
    assert validate_record(margins[0], load_schema()) == []
    # the heartbeat FILE body carries the measured interval too
    body = hb.read(hb_file)
    assert body["round"] == 2 and body["interval_s"] >= 0.02
    assert validate_record(body, load_schema()) == []

    # far from the threshold: gauge updates, no warning record
    monkeypatch.setenv(hb.TIMEOUT_ENV, "1000")
    hb.beat(round_idx=3)
    assert len([r for r in rec.records if r["t"] == "heartbeat_margin"]) == 1
    # unsupervised (no timeout env): beats never warn
    monkeypatch.delenv(hb.TIMEOUT_ENV)
    hb.beat(round_idx=4)
    assert len([r for r in rec.records if r["t"] == "heartbeat_margin"]) == 1


def test_trace_summary_compare_cli(tmp_path, capsys):
    """--compare A B: the two-terminal perf diff as one command — side by
    side per-stage costs (per-round normalized) and counters."""
    import trace_summary

    def mk(path, wall, compiles):
        rec = Recorder(enabled=True, path=path)
        with rec.span("round"):
            with rec.span("dispatch"):
                pass
        rec.counter("xla.compiles", compiles)
        rec.round_record(1, wall_s=wall)
        rec.close()

    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    mk(a, 0.4, 5)
    mk(b, 0.1, 3)
    assert trace_summary.main(["--compare", a, b]) == 0
    out = capsys.readouterr().out
    assert "round/dispatch" in out and "xla.compiles" in out
    assert "B/A" in out
    # wrong arity is a usage error, not a crash
    assert trace_summary.main(["--compare", a]) == 2
    assert trace_summary.main([a, b]) == 2
    # machine-readable variant
    assert trace_summary.main(["--compare", a, b, "--json"]) == 0
    both = json.loads(capsys.readouterr().out)
    assert both["a"]["rounds"]["count"] == 1
    # summarize surfaces the new sections on a metrics-bearing trace
    rec = Recorder(enabled=True, path=str(tmp_path / "m.jsonl"))
    rec.event("metrics", round=1, cos_honest=0.9, cos_byz=0.1,
              norm_median=0.5, masked_out=1)
    rec.event("memory", program="round", flops=1e9, temp_bytes=123)
    rec.round_record(1, wall_s=0.1)
    rec.close()
    s = trace_summary.summarize(
        trace_summary.load_records(str(tmp_path / "m.jsonl"))
    )
    assert s["metrics"]["mean_cos_honest"] == pytest.approx(0.9)
    assert s["programs"]["round"]["temp_bytes"] == 123
    table = trace_summary.format_table(s)
    assert "program[round]" in table and "metrics:" in table


def test_trace_summary_normalizes_block_spans(tmp_path, capsys):
    """Round-block traces carry `block`-rooted spans covering several
    rounds each; the summary normalizes them to per-round averages (using
    the per-round round records as the denominator) so the per-stage cost
    table stays comparable with pre-block, per-round traces."""
    import trace_summary

    path = str(tmp_path / "t.jsonl")
    rec = Recorder(enabled=True, path=path)
    for block, rounds in ((0, (1, 2, 3)), (1, (4, 5))):
        with rec.span("block", rounds=len(rounds)):
            with rec.span("dispatch"):
                pass
        for r in rounds:
            rec.round_record(r, wall_s=0.2)
    rec.close()
    summary = trace_summary.summarize(trace_summary.load_records(path))
    blk = summary["block"]
    assert blk["blocks"] == 2 and blk["rounds"] == 5
    assert blk["rounds_per_block"] == 2.5
    assert set(blk["per_round_mean_s"]) == {"block", "block/dispatch"}
    # per-round normalization: total block time / 5 rounds
    assert blk["per_round_mean_s"]["block"] == pytest.approx(
        summary["spans"]["block"]["total_s"] / 5
    )
    assert trace_summary.main([path]) == 0
    assert "block execution" in capsys.readouterr().out
    # a per-round trace has no block section (and the table omits it)
    rec2 = Recorder(enabled=True, path=str(tmp_path / "r.jsonl"))
    with rec2.span("round"):
        pass
    rec2.round_record(1, wall_s=0.1)
    rec2.close()
    s2 = trace_summary.summarize(
        trace_summary.load_records(str(tmp_path / "r.jsonl"))
    )
    assert s2["block"] == {}
