"""Aggregator unit tests: closed-form expectations, robustness harness,
state threading, and jit-compatibility.

The reference ships no tests (SURVEY.md section 4); the 2-D Gaussian harness
below generalizes its only sanity check
(``examples/plot_comparing_aggregation_schemes.py:20-41``) into assertions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.aggregators import (
    AGGREGATORS,
    Autogm,
    Centeredclipping,
    Clippedclustering,
    Clustering,
    Dnc,
    Fltrust,
    Geomed,
    Krum,
    Mean,
    Median,
    Multikrum,
    Signguard,
    Trimmedmean,
    get_aggregator,
)


def rand_updates(k=10, d=7, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(k, d)).astype(np.float32))


# ---------------------------------------------------------------- closed forms


def test_mean_closed_form():
    u = rand_updates()
    np.testing.assert_allclose(Mean()(u), np.asarray(u).mean(0), rtol=1e-6)


@pytest.mark.parametrize("k", [9, 10])
def test_median_matches_numpy(k):
    u = rand_updates(k=k)
    np.testing.assert_allclose(Median()(u), np.median(np.asarray(u), axis=0), rtol=1e-6)


def test_trimmedmean_closed_form():
    u = rand_updates(k=10)
    b = 2
    expected = np.mean(np.sort(np.asarray(u), axis=0)[b : 10 - b], axis=0)
    np.testing.assert_allclose(Trimmedmean(num_byzantine=b)(u), expected, rtol=1e-5)


def test_trimmedmean_autoshrink():
    # reference shrinks b until K - 2b > 0 (trimmedmean.py:29-36)
    u = rand_updates(k=4)
    got = Trimmedmean(num_byzantine=5)(u)  # shrinks to b=1
    expected = np.mean(np.sort(np.asarray(u), axis=0)[1:3], axis=0)
    np.testing.assert_allclose(got, expected, rtol=1e-5)


def test_krum_selects_closest_vector():
    # 5 tightly clustered vectors + 2 far outliers; krum must return one of
    # the clustered vectors (it returns exactly one row for m=1)
    rng = np.random.default_rng(1)
    benign = rng.normal(size=(5, 4)).astype(np.float32) * 0.1
    outliers = np.full((2, 4), 50.0, dtype=np.float32)
    u = jnp.asarray(np.vstack([benign, outliers]))
    out = np.asarray(Krum(num_byzantine=2)(u))
    dists = np.linalg.norm(benign - out, axis=1)
    assert dists.min() < 1e-5


def test_krum_scores_match_numpy():
    u = rand_updates(k=8, d=5, seed=3)
    f = 2
    un = np.asarray(u)
    d2 = ((un[:, None, :] - un[None, :, :]) ** 2).sum(-1)
    np.fill_diagonal(d2, np.inf)
    expected = np.sort(d2, axis=1)[:, : 8 - f - 2].sum(1)
    got = np.asarray(Krum(num_byzantine=f).scores(u))
    # |a|^2+|b|^2-2ab^T loses a few bits to cancellation in fp32 vs the
    # direct difference formula; ranking is what matters for Krum
    np.testing.assert_allclose(got, expected, rtol=5e-3)
    assert (np.argsort(got) == np.argsort(expected)).all()


def test_multikrum_averages_selected():
    # the Multi-Krum paper AVERAGES the m best-scoring updates; the
    # reference's sum (`krum.py:120`) only ever runs at m=1 where the two
    # coincide. Summing at m>1 would scale the pseudo-gradient by m.
    u = rand_updates(k=8, d=5, seed=4)
    agg = Multikrum(num_byzantine=2, num_selected=3)
    scores = np.asarray(agg.scores(u))
    sel = np.argsort(scores)[:3]
    np.testing.assert_allclose(
        agg(u), np.asarray(u)[sel].mean(0), rtol=1e-4
    )


def test_geomed_median_property():
    # geometric median of symmetric points is the center
    pts = jnp.asarray(
        [[1.0, 0.0], [-1.0, 0.0], [0.0, 1.0], [0.0, -1.0]], dtype=jnp.float32
    )
    np.testing.assert_allclose(Geomed()(pts), np.zeros(2), atol=1e-4)


def test_geomed_robust_to_outlier():
    benign = np.zeros((9, 3), dtype=np.float32)
    outlier = np.full((1, 3), 1000.0, dtype=np.float32)
    out = np.asarray(Geomed()(jnp.asarray(np.vstack([benign, outlier]))))
    assert np.linalg.norm(out) < 1.0


def test_autogm_downweights_outliers():
    rng = np.random.default_rng(5)
    benign = rng.normal(size=(8, 3)).astype(np.float32) * 0.1
    outlier = np.full((2, 3), 100.0, dtype=np.float32)
    out = np.asarray(Autogm()(jnp.asarray(np.vstack([benign, outlier]))))
    assert np.linalg.norm(out - benign.mean(0)) < 1.0


def test_centeredclipping_momentum_math():
    # one call, n_iter=1, zero momentum: result = mean(clip(u, tau))
    u = jnp.asarray([[3.0, 4.0], [0.3, 0.4]], dtype=jnp.float32)  # norms 5, .5
    agg = Centeredclipping(tau=1.0, n_iter=1)
    got = np.asarray(agg(u))
    clipped = np.array([[0.6, 0.8], [0.3, 0.4]])  # first row scaled to norm 1
    np.testing.assert_allclose(got, clipped.mean(0), rtol=1e-5)


def test_centeredclipping_state_persists():
    u = rand_updates(k=4, d=3)
    agg = Centeredclipping(tau=10.0, n_iter=5)
    first = np.asarray(agg(u))
    second = np.asarray(agg(u))
    # with tau large, first call converges to the mean; momentum then persists
    assert not np.allclose(first, np.zeros(3))
    np.testing.assert_allclose(second, np.asarray(u).mean(0), rtol=1e-3, atol=1e-4)


def test_fltrust_weighted_by_cosine():
    trusted = np.array([1.0, 0.0], dtype=np.float32)
    aligned = np.array([2.0, 0.0], dtype=np.float32)  # cos=1, rescaled to norm 1
    opposed = np.array([-3.0, 0.0], dtype=np.float32)  # relu(cos)=0
    u = jnp.asarray(np.vstack([trusted, aligned, opposed]))
    mask = jnp.asarray([True, False, False])
    out = np.asarray(Fltrust()(u, trusted_mask=mask))
    np.testing.assert_allclose(out, [1.0, 0.0], atol=1e-5)


def test_clustering_majority_cluster():
    rng = np.random.default_rng(7)
    benign = rng.normal(size=(7, 4)).astype(np.float32) + 5.0
    attackers = -(rng.normal(size=(3, 4)).astype(np.float32) + 5.0)
    u = jnp.asarray(np.vstack([benign, attackers]))
    out = np.asarray(Clustering(metric="distance")(u))
    np.testing.assert_allclose(out, benign.mean(0), rtol=1e-4)


def test_clippedclustering_clips_and_clusters():
    rng = np.random.default_rng(8)
    benign = rng.normal(size=(8, 4)).astype(np.float32)
    huge = np.full((2, 4), 1e4, dtype=np.float32)
    agg = Clippedclustering()
    out = np.asarray(agg(jnp.asarray(np.vstack([benign, huge]))))
    assert np.linalg.norm(out) < 10 * np.linalg.norm(benign.mean(0)) + 10


def test_clippedclustering_history_state():
    agg = Clippedclustering()
    u = rand_updates(k=6, d=4)
    agg(u)
    assert int(agg._state["count"]) == 6
    agg(u)
    assert int(agg._state["count"]) == 12


def test_dnc_filters_colluding_outliers():
    rng = np.random.default_rng(9)
    benign = rng.normal(size=(8, 50)).astype(np.float32)
    attack = np.full((2, 50), 30.0, dtype=np.float32)
    u = jnp.asarray(np.vstack([benign, attack]))
    out = np.asarray(Dnc(num_byzantine=2, sub_dim=50, num_iters=3)(u, key=jax.random.key(0)))
    assert np.linalg.norm(out - benign.mean(0)) < 2.0


def test_signguard_filters_signflipped():
    rng = np.random.default_rng(10)
    benign = np.abs(rng.normal(size=(8, 40))).astype(np.float32)
    flipped = -np.abs(rng.normal(size=(2, 40))).astype(np.float32) * 1.0
    u = jnp.asarray(np.vstack([benign, flipped]))
    out = np.asarray(Signguard()(u))
    assert (out > 0).mean() > 0.9  # aggregate keeps benign (positive) direction


# ------------------------------------------------- sklearn cross-validation


def test_complete_linkage_matches_sklearn():
    sklearn = pytest.importorskip("sklearn.cluster")
    from blades_tpu.ops.clustering import complete_linkage_two_clusters

    rng = np.random.default_rng(11)
    for seed in range(3):
        pts = rng.normal(size=(12, 3))
        pts[:4] += 6.0
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        ours = np.asarray(complete_linkage_two_clusters(jnp.asarray(d, dtype=jnp.float32)))
        ref = sklearn.AgglomerativeClustering(
            metric="precomputed", linkage="complete", n_clusters=2
        ).fit(d).labels_
        # partitions must agree up to label swap
        agree = (ours == ref).mean()
        assert agree in (0.0, 1.0) or agree > 0.99, (ours, ref)


# -------------------------------------------------- 2-D Gaussian harness


ROBUST = ["median", "trimmedmean", "krum", "geomed", "autogm", "dnc"]


@pytest.mark.parametrize("name", ROBUST)
def test_robust_aggregators_resist_outliers(name):
    """60 benign samples around (1, 1), 40 colluding outliers at (10, 10):
    robust schemes must land near the benign center; mean must not."""
    rng = np.random.default_rng(12)
    benign = rng.normal(loc=1.0, scale=0.5, size=(60, 2)).astype(np.float32)
    outliers = rng.normal(loc=10.0, scale=0.1, size=(40, 2)).astype(np.float32)
    u = jnp.asarray(np.vstack([benign, outliers]))
    kwargs = {}
    if name in ("trimmedmean", "krum", "dnc"):
        kwargs["num_byzantine"] = 40
    agg = get_aggregator(name, **kwargs)
    ctx = {"key": jax.random.key(0)} if name == "dnc" else {}
    out = np.asarray(agg(u, **ctx))
    assert np.linalg.norm(out - benign.mean(0)) < 1.5, (name, out)
    # sanity: plain mean is pulled toward the outliers
    pulled = np.asarray(Mean()(u))
    assert np.linalg.norm(pulled - benign.mean(0)) > 3.0


# ------------------------------------------------------------ framework API


def test_registry_names_cover_reference():
    # names the reference resolves via dynamic import (simulator.py:110-116)
    for name in [
        "mean", "median", "trimmedmean", "krum", "geomed", "autogm",
        "centeredclipping", "clustering", "clippedclustering", "fltrust",
    ]:
        assert name in AGGREGATORS


def test_custom_callable_aggregator():
    agg = get_aggregator(lambda u: jnp.min(u, axis=0))
    u = rand_updates(k=5, d=3)
    np.testing.assert_allclose(agg(u), np.asarray(u).min(0))


def test_accepts_list_of_vectors():
    u = [jnp.ones(3), jnp.zeros(3)]
    np.testing.assert_allclose(Mean()(u), [0.5, 0.5, 0.5])


@pytest.mark.parametrize(
    "name", ["mean", "median", "trimmedmean", "krum", "geomed", "centeredclipping"]
)
def test_aggregators_jit_compile(name):
    kwargs = {"num_byzantine": 2} if name in ("trimmedmean", "krum") else {}
    agg = get_aggregator(name, **kwargs)
    u = rand_updates(k=8, d=16)
    state = agg.init_state(8, 16)

    @jax.jit
    def run(u, state):
        return agg.aggregate(u, state)

    vec, _ = run(u, state)
    assert vec.shape == (16,)
    assert np.isfinite(np.asarray(vec)).all()


# -------------------------------------------------- registry-wide properties

# fltrust needs a trusted_mask ctx; handled separately below
_PROP_AGGS = sorted(set(AGGREGATORS) - {"fltrust"})


def _prop_agg(name):
    kwargs = {"num_byzantine": 2} if name in ("trimmedmean", "krum",
                                              "multikrum", "dnc") else {}
    return get_aggregator(name, **kwargs)


def _prop_ctx(name, d=11):
    if name == "dnc":
        return {"key": jax.random.key(3)}
    if name == "byzantinesgd":
        return {"params_flat": jnp.zeros(d)}
    return {}


@pytest.mark.parametrize("name", _PROP_AGGS)
def test_permutation_invariance(name):
    """Client order carries no information — every defense must be
    row-permutation invariant on its FIRST call (stateless view).

    byzantinesgd is exempt: its vector median takes the FIRST row within
    threshold of a majority (reference ``byzantinesgd.py:35-43`` scans in
    index order), so the choice among equally eligible rows is
    order-sensitive by construction.
    """
    if name == "byzantinesgd":
        pytest.skip("first-eligible vector median is order-sensitive by design")
    u = rand_updates(k=9, d=11, seed=7)
    perm = np.random.default_rng(1).permutation(9)
    a = _prop_agg(name)(u, **_prop_ctx(name))
    b = _prop_agg(name)(u[jnp.asarray(perm)], **_prop_ctx(name))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("name", _PROP_AGGS)
def test_output_is_finite_and_shaped(name):
    u = rand_updates(k=9, d=11, seed=8)
    out = np.asarray(_prop_agg(name)(u, **_prop_ctx(name)))
    assert out.shape == (11,)
    assert np.isfinite(out).all()


@pytest.mark.parametrize("name", _PROP_AGGS)
def test_unanimous_updates_are_identity(name):
    """If every client sends the same vector, any sane aggregate IS that
    vector (selection, trimming, clustering, and averaging all agree).
    Stateful EMA-style defenses reach it after a few identical rounds."""
    if name == "byzantinesgd":
        pytest.skip("A/B accumulator filter, not an estimator — unanimity "
                    "maps to its pass-through regime only")
    v = np.arange(1.0, 12.0, dtype=np.float32)
    u = jnp.asarray(np.tile(v, (9, 1)))
    agg = _prop_agg(name)
    for _ in range(8):  # stateless aggs converge on call 1; EMA ones within 8
        out = np.asarray(agg(u, **_prop_ctx(name)))
    np.testing.assert_allclose(out, v, rtol=1e-4, atol=1e-4)


# ------------------------------------------------------ forensic diagnostics


def test_krum_diagnostics_select_honest_clique():
    """Crafted [K, D] with 3 planted outlier rows (byzantine-first, the
    reference convention): Krum's diagnostics must score the outliers worst
    and select only honest rows, and the aggregate must equal the mean of
    the selected rows."""
    rng = np.random.default_rng(21)
    outliers = np.full((3, 6), 50.0, dtype=np.float32)
    honest = rng.normal(size=(7, 6)).astype(np.float32) * 0.1
    u = jnp.asarray(np.vstack([outliers, honest]))
    agg = Krum(num_byzantine=3, num_selected=2)
    out, _, diag = agg.aggregate_with_diagnostics(u)
    sel = np.asarray(diag["selected"])
    assert sel.shape == (2,) and (sel >= 3).all()  # honest clique only
    scores = np.asarray(diag["scores"])
    assert scores.shape == (10,)
    assert scores[:3].min() > scores[3:].max()  # planted rows scored worst
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(u)[sel].mean(0), rtol=1e-5
    )


def test_trimmedmean_diagnostics_hit_planted_rows():
    """The trim-mask summary must attribute a full row of trimmed
    coordinates to each planted byzantine row (magnitude +-100 puts them in
    the top/bottom b at EVERY coordinate)."""
    rng = np.random.default_rng(22)
    d = 33
    planted = np.stack([np.full(d, 100.0), np.full(d, -100.0)]).astype(np.float32)
    honest = rng.normal(size=(8, d)).astype(np.float32)
    u = jnp.asarray(np.vstack([planted, honest]))
    agg = Trimmedmean(num_byzantine=2)
    _, _, diag = agg.aggregate_with_diagnostics(u)
    tc = np.asarray(diag["trim_counts"])
    assert int(diag["trim_b"]) == 2
    assert (tc[:2] == d).all()  # every coordinate of both planted rows
    # exactly 2b slots trimmed per coordinate in total
    assert tc.sum() == 2 * 2 * d


def test_diagnostics_jit_compatible():
    """aggregate_with_diagnostics traces inside jit (the engine's
    collect_diagnostics path) with fixed-shape outputs."""
    u = rand_updates(k=8, d=16)
    for agg in (Krum(num_byzantine=2), Trimmedmean(num_byzantine=2)):
        state = agg.init_state(8, 16)

        @jax.jit
        def run(u, state, agg=agg):
            return agg.aggregate_with_diagnostics(u, state)

        vec, _, diag = run(u, state)
        assert vec.shape == (16,)
        assert diag  # non-empty forensic pytree
        for v in jax.tree_util.tree_leaves(diag):
            assert np.isfinite(np.asarray(v, dtype=np.float64)).all()


def test_centeredclipping_diagnostics_flag_clipped_rows():
    u = jnp.asarray([[3.0, 4.0], [0.3, 0.4]], dtype=jnp.float32)  # norms 5, .5
    agg = Centeredclipping(tau=1.0, n_iter=1)
    state = agg.init_state(2, 2)
    _, _, diag = agg.aggregate_with_diagnostics(u, state)
    np.testing.assert_allclose(np.asarray(diag["clip_norms"]), [5.0, 0.5], rtol=1e-5)
    assert np.asarray(diag["clipped"]).tolist() == [True, False]


def test_fltrust_diagnostics_trust_scores():
    trusted = np.array([1.0, 0.0], dtype=np.float32)
    aligned = np.array([2.0, 0.0], dtype=np.float32)
    opposed = np.array([-3.0, 0.0], dtype=np.float32)
    u = jnp.asarray(np.vstack([trusted, aligned, opposed]))
    mask = jnp.asarray([True, False, False])
    _, _, diag = Fltrust().aggregate_with_diagnostics(u, trusted_mask=mask)
    np.testing.assert_allclose(
        np.asarray(diag["trust_scores"]), [0.0, 1.0, 0.0], atol=1e-5
    )


def test_base_diagnostics_default_empty():
    u = rand_updates(k=4, d=3)
    agg, _, diag = Mean().aggregate_with_diagnostics(u)
    np.testing.assert_allclose(agg, np.asarray(u).mean(0), rtol=1e-6)
    assert diag == {}


def test_fltrust_permutation_invariance_with_mask():
    u = rand_updates(k=8, d=5, seed=9)
    mask = jnp.zeros(8, bool).at[3].set(True)
    perm = np.random.default_rng(2).permutation(8)
    a = get_aggregator("fltrust")(u, trusted_mask=mask)
    b = get_aggregator("fltrust")(
        u[jnp.asarray(perm)], trusted_mask=mask[jnp.asarray(perm)]
    )
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
