"""Run identity & health layer tests (``blades_tpu/telemetry/{context,
ledger,alerts}.py`` + the supervisor/simulator wiring): run-id mint/
inherit semantics, the crash-safe provenance ledger, the record envelope
on every telemetry record, the anomaly-alert rules (firing on seeded
unhealthy streams, silent on healthy ones), cross-process correlation
under the supervisor's kill -> relaunch ladder, and the ``runs.py`` /
``trace_summary.py`` query surfaces.

Reference counterpart: none — the reference's runs are anonymous by
construction (``src/blades/utils.py:67-95`` keys everything on the log
directory) and it has no runtime health signal of any kind.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

from blades_tpu.supervision.supervisor import supervise  # noqa: E402
from blades_tpu.telemetry import alerts, context, ledger  # noqa: E402
from blades_tpu.telemetry.recorder import Recorder  # noqa: E402


def _records(path):
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


@pytest.fixture()
def clean_ctx(monkeypatch):
    """A process whose run context is unset: no env id, nothing minted —
    the state every fresh top-level entry point starts from."""
    monkeypatch.delenv(context.RUN_ID_ENV, raising=False)
    monkeypatch.delenv(context.ATTEMPT_ENV, raising=False)
    monkeypatch.setattr(context, "_minted", set())
    return monkeypatch


# ------------------------------------------------------------- trace context


def test_activate_mints_and_exports(clean_ctx):
    ctx = context.activate(fresh=True)
    assert ctx.run_id and ctx.attempt == 1 and not ctx.inherited
    assert os.environ[context.RUN_ID_ENV] == ctx.run_id
    assert os.environ[context.ATTEMPT_ENV] == "1"
    assert context.envelope() == {"run_id": ctx.run_id, "attempt": 1}


def test_fresh_remints_own_id_but_keeps_inherited(clean_ctx):
    first = context.activate(fresh=True)
    # two sequential top-level runs in one process are two experiments
    second = context.activate(fresh=True)
    assert second.run_id != first.run_id
    # a non-fresh activate (the recorder) adopts whatever is active
    assert context.activate().run_id == second.run_id
    # an id exported by a PARENT process is never re-minted: sharing it
    # across the supervisor's attempts is the whole point
    clean_ctx.setenv(context.RUN_ID_ENV, "parent-id")
    clean_ctx.setenv(context.ATTEMPT_ENV, "3")
    clean_ctx.setattr(context, "_minted", set())
    ctx = context.activate(fresh=True)
    assert ctx.run_id == "parent-id" and ctx.attempt == 3 and ctx.inherited


def test_envelope_empty_without_context(clean_ctx):
    assert context.current() is None
    assert context.envelope() == {}


def test_run_ids_sort_by_mint_time(clean_ctx):
    a = context.mint_run_id()
    b = context.mint_run_id()
    assert a[:15] <= b[:15]  # UTC-timestamp prefix is human-sortable


# ---------------------------------------------------------------- run ledger


def test_config_fingerprint_stable_and_key_order_insensitive():
    a = ledger.config_fingerprint({"x": 1, "y": [2, 3]})
    b = ledger.config_fingerprint({"y": [2, 3], "x": 1})
    c = ledger.config_fingerprint({"x": 1, "y": [2, 4]})
    assert a == b != c and len(a) == 12


def test_ledger_started_finished_pair(clean_ctx, tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    clean_ctx.setenv(ledger.LEDGER_ENV, path)
    entry = ledger.run_started("simulator", config={"k": 6}, artifacts=["a"])
    entry.ended("finished", metrics={"rounds_completed": 2})
    recs = ledger.read_ledger(path)
    assert [r["event"] for r in recs] == ["started", "finished"]
    started, finished = recs
    assert started["run_id"] == finished["run_id"] == os.environ[
        context.RUN_ID_ENV
    ]
    assert started["config_fingerprint"] == ledger.config_fingerprint(
        {"k": 6}
    )
    assert started["config"] == {"k": 6} and started["artifacts"] == ["a"]
    assert "env" in started and started["env"].get("python")
    assert finished["metrics"] == {"rounds_completed": 2}
    assert finished["wall_s"] >= 0
    # terminal record is idempotent: first outcome wins (a crash handler
    # followed by the finally block must not double-record)
    assert entry.ended("finished") is None
    assert len(ledger.read_ledger(path)) == 2


def test_ledger_crash_beats_finally_finished(clean_ctx, tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    clean_ctx.setenv(ledger.LEDGER_ENV, path)
    entry = ledger.run_started("simulator")
    entry.ended("crashed", error="boom")
    entry.ended("finished")  # the finally block, after the except path
    recs = ledger.read_ledger(path)
    assert [r["event"] for r in recs] == ["started", "crashed"]
    assert recs[1]["error"] == "boom"


def test_ledger_disabled_is_inert(clean_ctx, tmp_path):
    clean_ctx.setenv(ledger.LEDGER_ENV, "0")
    entry = ledger.run_started("bench", config={"a": 1})
    assert entry.path is None
    assert entry.ended("finished") is None
    assert ledger.record_event("bench", "killed") is None
    assert ledger.ledger_path() is None


def test_read_ledger_skips_torn_lines(tmp_path):
    path = tmp_path / "ledger.jsonl"
    path.write_text(
        '{"t": "ledger", "event": "started", "run_id": "r", "attempt": 1}\n'
        '{"t": "ledger", "ev'  # a live run mid-append
    )
    recs = ledger.read_ledger(str(path))
    assert len(recs) == 1 and recs[0]["event"] == "started"


def test_pair_runs_joins_by_run_and_attempt():
    recs = [
        {"t": "ledger", "event": "started", "run_id": "r", "attempt": 1,
         "kind": "simulator", "ts": 1.0, "config_fingerprint": "fp"},
        {"t": "ledger", "event": "killed", "run_id": "r", "attempt": 1,
         "kind": "supervised"},
        {"t": "ledger", "event": "started", "run_id": "r", "attempt": 2,
         "kind": "simulator", "ts": 2.0, "config_fingerprint": "fp"},
        {"t": "ledger", "event": "finished", "run_id": "r", "attempt": 2,
         "kind": "simulator", "wall_s": 3.0,
         "metrics": {"rounds_per_sec": 4.0}},
        {"t": "ledger", "event": "started", "run_id": "other", "attempt": 1,
         "kind": "bench", "ts": 3.0},
    ]
    runs = {(r["run_id"], r["attempt"]): r for r in ledger.pair_runs(recs)}
    assert len(runs) == 3
    assert runs[("r", 1)]["outcome"] == "killed"
    assert runs[("r", 2)]["outcome"] == "finished"
    assert runs[("r", 2)]["metrics"]["rounds_per_sec"] == 4.0
    assert runs[("other", 1)]["outcome"] is None  # still open


def test_pair_runs_keeps_shared_id_entry_points_apart():
    """Review finding: one propagated run id legitimately spans several
    entry points (tpu_capture mints, its bench ladder inherits) — their
    records must pair into separate per-kind runs, not one garbage slot."""
    recs = [
        {"t": "ledger", "event": "started", "run_id": "r", "attempt": 1,
         "kind": "tpu_capture", "ts": 1.0},
        {"t": "ledger", "event": "started", "run_id": "r", "attempt": 1,
         "kind": "bench", "ts": 2.0, "config_fingerprint": "fpb"},
        {"t": "ledger", "event": "finished", "run_id": "r", "attempt": 1,
         "kind": "bench", "metrics": {"rounds_per_sec": 9.9}},
        {"t": "ledger", "event": "finished", "run_id": "r", "attempt": 1,
         "kind": "tpu_capture", "metrics": {"exit": 0}},
    ]
    runs = {r["kind"]: r for r in ledger.pair_runs(recs)}
    assert len(runs) == 2
    assert runs["bench"]["outcome"] == "finished"
    assert runs["bench"]["metrics"] == {"rounds_per_sec": 9.9}
    assert runs["bench"]["config_fingerprint"] == "fpb"
    assert runs["tpu_capture"]["metrics"] == {"exit": 0}


def test_pair_runs_sequential_same_kind_runs_stay_apart():
    """Review finding: a supervised child hosting TWO sequential runs of
    one kind under its inherited (run_id, attempt) is two runs — each
    `started` opens a new slot, terminals pair in record order."""
    base = {"t": "ledger", "run_id": "r", "attempt": 1, "kind": "simulator"}
    recs = [
        dict(base, event="started", ts=1.0, config_fingerprint="fp1"),
        dict(base, event="crashed", error="boom"),
        dict(base, event="started", ts=2.0, config_fingerprint="fp2"),
        dict(base, event="finished", metrics={"rounds_completed": 3}),
    ]
    runs = sorted(ledger.pair_runs(recs), key=lambda r: r["ts"])
    assert len(runs) == 2
    assert runs[0]["outcome"] == "crashed"
    assert runs[0]["config_fingerprint"] == "fp1"
    assert runs[1]["outcome"] == "finished"
    assert runs[1]["config_fingerprint"] == "fp2"


def test_run_started_omits_code_version_outside_git(clean_ctx, tmp_path,
                                                    monkeypatch):
    """Review finding: outside a git checkout the started record must
    OMIT code_version (the closed `ledger` schema type declares it as an
    optional string — null fails the validator)."""
    from blades_tpu.telemetry.schema import load_schema, validate_records

    path = str(tmp_path / "ledger.jsonl")
    clean_ctx.setenv(ledger.LEDGER_ENV, path)
    monkeypatch.setattr(ledger, "code_version", lambda: None)
    ledger.run_started("bench").ended("finished")
    recs = ledger.read_ledger(path)
    assert "code_version" not in recs[0]
    assert validate_records(recs, load_schema()) == []


def test_code_version_matches_git_head():
    sha = ledger.code_version()
    assert sha and len(sha) == 40
    head = subprocess.run(
        ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
        cwd=REPO,
    ).stdout.strip()
    if head:
        assert sha == head


# -------------------------------------------------------- recorder envelope


def test_recorder_stamps_envelope_on_every_record(clean_ctx, tmp_path):
    path = str(tmp_path / "t.jsonl")
    rec = Recorder(path=path, meta={"run": "x"})
    with rec.span("round"):
        pass
    rec.event("run_end", rounds_completed=0)
    rec.round_record(0, wall_s=0.1)
    rec.close()
    recs = _records(path)
    assert len(recs) >= 4
    rid = os.environ[context.RUN_ID_ENV]
    for r in recs:
        assert r["run_id"] == rid and r["attempt"] == 1, r


def test_record_own_field_wins_over_envelope(clean_ctx, tmp_path):
    """The supervisor's per-event `attempt` (attempt N of the ladder) must
    not be clobbered by the recorder process's own envelope attempt."""
    path = str(tmp_path / "t.jsonl")
    rec = Recorder(path=path)
    rec.event("supervisor", event="kill", attempt=3)
    rec.close()
    sup = [r for r in _records(path) if r.get("t") == "supervisor"]
    assert sup[0]["attempt"] == 3


def test_disabled_recorder_touches_no_context(clean_ctx, tmp_path):
    rec = Recorder(path=str(tmp_path / "t.jsonl"), enabled=False)
    rec.event("run_end")
    rec.close()
    assert context.current() is None  # no mint, no env export
    assert not os.path.exists(str(tmp_path / "t.jsonl"))


# ------------------------------------------------------------- alert engine


def _rounds(losses=(), walls=(), compiles=None, margins=None):
    recs = []
    for i, loss in enumerate(losses):
        r = {"t": "round", "round": i, "train_loss": loss,
             "counters": {}, "gauges": {}}
        if walls:
            r["wall_s"] = walls[i]
        if compiles and i in compiles:
            r["counters"]["xla.compiles"] = compiles[i]
        if margins and i < len(margins):
            r["gauges"]["heartbeat.margin_s"] = margins[i]
        recs.append(r)
    return recs


def test_alert_loss_nonfinite():
    out = alerts.evaluate_records(_rounds(losses=[1.0, float("nan")]))
    assert [a["rule"] for a in out] == ["loss_nonfinite"]
    assert out[0]["severity"] == "critical" and out[0]["t"] == "alert"


def test_alert_loss_divergence_fires_once():
    losses = [1.0, 1.0, 1.0, 5.0, 5.0, 5.0, 9.0, 9.0, 9.0]
    out = alerts.evaluate_records(_rounds(losses=losses))
    assert [a["rule"] for a in out] == ["loss_divergence"]  # once per run
    assert out[0]["severity"] == "critical"


def test_alert_silent_on_converging_loss():
    losses = [1.0, 0.9, 0.8, 0.7, 0.65, 0.6, 0.58, 0.55]
    assert alerts.evaluate_records(_rounds(losses=losses)) == []


def test_alert_norm_collapse():
    hist_bad = [0, 1, 0, 0, 9]  # 90% of mass in the top (largest) bin
    hist_ok = [2, 5, 2, 1, 0]
    out = alerts.evaluate_records(
        [{"t": "metrics", "round": 1, "norm_hist": hist_ok},
         {"t": "metrics", "round": 2, "norm_hist": hist_bad}]
    )
    assert [a["rule"] for a in out] == ["norm_collapse"]
    assert out[0]["round"] == 2


def test_alert_audit_breach_storm():
    healthy = [{"t": "audit", "round": i, "breach": 0} for i in range(8)]
    assert alerts.evaluate_records(healthy) == []
    stormy = [
        {"t": "audit", "round": i, "breach": 1 if i >= 4 else 0}
        for i in range(8)
    ]
    out = alerts.evaluate_records(stormy)
    assert [a["rule"] for a in out] == ["audit_breach_storm"]


def test_alert_compile_storm_after_warmup():
    # compiles during the first rounds are warm-up, not a storm
    warm = _rounds(losses=[1.0] * 4, compiles={0: 5, 1: 2})
    assert alerts.evaluate_records(warm) == []
    # ONE late compile-bearing round is the documented first-eval build
    late_eval = _rounds(losses=[1.0] * 6, compiles={0: 5, 4: 2})
    assert alerts.evaluate_records(late_eval) == []
    # a SECOND one is a storm
    storm = _rounds(losses=[1.0] * 8, compiles={0: 5, 4: 2, 6: 1})
    out = alerts.evaluate_records(storm)
    assert [a["rule"] for a in out] == ["compile_storm"]
    assert out[0]["round"] == 6


def test_alert_throughput_drop_vs_own_median():
    walls = [0.1] * 8 + [0.9]
    out = alerts.evaluate_records(
        _rounds(losses=[1.0] * 9, walls=walls)
    )
    assert [a["rule"] for a in out] == ["throughput_drop"]
    steady = _rounds(losses=[1.0] * 9, walls=[0.1] * 9)
    assert alerts.evaluate_records(steady) == []


def test_alert_heartbeat_margin_rules():
    out = alerts.evaluate_records(
        [{"t": "heartbeat_margin", "round": 3, "interval_s": 9.0,
          "margin_s": 1.0, "timeout_s": 10.0}]
    )
    assert [a["rule"] for a in out] == ["heartbeat_margin_low"]
    shrink = _rounds(losses=[1.0] * 4, margins=[8.0, 6.0, 4.0, 2.0])
    out = alerts.evaluate_records(shrink)
    assert [a["rule"] for a in out] == ["heartbeat_margin_shrinking"]
    steady = _rounds(losses=[1.0] * 4, margins=[8.0, 7.9, 8.1, 8.0])
    assert alerts.evaluate_records(steady) == []


def test_alert_records_ride_recorder_and_validate(clean_ctx, tmp_path):
    """Live wiring: the engine observes records as they enter the buffer,
    the alert record lands in the SAME trace behind the same envelope,
    and it validates against the committed schema."""
    from blades_tpu.telemetry.schema import load_schema, validate_records

    path = str(tmp_path / "t.jsonl")
    rec = Recorder(path=path, meta={"run": "x"})
    engine = alerts.install(rec)
    assert engine is not None
    rec.round_record(0, train_loss=float("inf"), wall_s=0.1)
    rec.close()
    recs = _records(path)
    alert = [r for r in recs if r["t"] == "alert"]
    assert len(alert) == 1 and alert[0]["rule"] == "loss_nonfinite"
    assert alert[0]["run_id"] == os.environ[context.RUN_ID_ENV]
    assert validate_records(recs, load_schema()) == []


def test_alerts_disabled_by_env(clean_ctx, tmp_path):
    clean_ctx.setenv(alerts.ALERTS_ENV, "0")
    rec = Recorder(path=str(tmp_path / "t.jsonl"))
    assert alerts.install(rec) is None
    rec.close()


def test_install_on_disabled_recorder_is_none(clean_ctx, tmp_path):
    rec = Recorder(path=str(tmp_path / "t.jsonl"), enabled=False)
    assert alerts.install(rec) is None


def test_critical_alert_touches_supervisor_hook_file(clean_ctx, tmp_path):
    hook = tmp_path / "alert"
    clean_ctx.setenv(alerts.ALERT_FILE_ENV, str(hook))
    # offline replay must NEVER signal a running supervisor
    alerts.evaluate_records(_rounds(losses=[float("nan")]))
    assert not hook.exists()
    # a live engine (recorder attached) does
    rec = Recorder(path=str(tmp_path / "t.jsonl"))
    alerts.install(rec)
    rec.round_record(0, train_loss=float("nan"))
    rec.close()
    body = json.loads(hook.read_text())
    assert body["rule"] == "loss_nonfinite" and body["severity"] == "critical"
    # warn-severity alerts never touch the hook
    hook.unlink()
    rec2 = Recorder(path=str(tmp_path / "t2.jsonl"))
    alerts.install(rec2)
    for i, w in enumerate([0.1] * 8 + [0.9]):
        rec2.round_record(i, train_loss=1.0, wall_s=w)
    rec2.close()
    assert not hook.exists()


def test_malformed_records_never_disable_alerting():
    recs = [
        {"t": "round"},  # no loss, no wall
        {"t": "metrics", "norm_hist": "not-a-list"},
        {"t": "audit", "breach": "nope"},
        {"t": "round", "round": 5, "train_loss": float("nan")},
    ]
    out = alerts.evaluate_records(recs)
    assert [a["rule"] for a in out] == ["loss_nonfinite"]


def test_alerts_silent_on_committed_artifacts():
    """The committed evidence record streams under results/ describe
    healthy runs; replaying the rule set over them must raise nothing."""
    import glob

    streams = 0
    for path in glob.glob(os.path.join(REPO, "results", "**", "*.jsonl"),
                          recursive=True):
        recs = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    recs.append(rec)
        if recs:
            streams += 1
            assert alerts.evaluate_records(recs) == [], path
    assert streams >= 2  # the committed evidence ladders exist


# ------------------------------------------- supervised cross-process runs


def test_supervised_attempts_share_run_id(clean_ctx, tmp_path):
    """The acceptance correlation property: every attempt of one
    supervised run inherits ONE run id with incrementing attempt numbers,
    and the supervisor's own telemetry records carry the same envelope."""
    probe = tmp_path / "attempts.jsonl"
    code = (
        "import json, os, sys\n"
        "with open(%r, 'a') as f:\n"
        "    f.write(json.dumps({'rid': os.environ.get('BLADES_RUN_ID'),\n"
        "        'att': os.environ.get('BLADES_ATTEMPT')}) + '\\n')\n"
        "sys.exit(1)" % str(probe)
    )
    telem = tmp_path / "telemetry.jsonl"
    result = supervise(
        [sys.executable, "-c", code],
        attempts=3, base_delay_s=0.01, poll_s=0.05,
        heartbeat_file=str(tmp_path / "hb"),
        telemetry_path=str(telem),
    )
    assert not result.ok and len(result.attempts) == 3
    rows = _records(str(probe))
    rids = {r["rid"] for r in rows}
    assert len(rids) == 1 and None not in rids
    assert [r["att"] for r in rows] == ["1", "2", "3"]
    (rid,) = rids
    for r in _records(str(telem)):
        assert r["run_id"] == rid, r


def test_watchdog_kill_writes_ledger_record(clean_ctx, tmp_path):
    """A reaped child never writes its own ledger exit — the supervisor
    records the kill under the shared run id + attempt."""
    led = str(tmp_path / "ledger.jsonl")
    clean_ctx.setenv(ledger.LEDGER_ENV, led)
    beat_then_hang = (
        "import sys, time; sys.path.insert(0, %r); "
        "from blades_tpu.supervision.heartbeat import beat; "
        "beat(round_idx=2); time.sleep(600)" % REPO
    )
    result = supervise(
        [sys.executable, "-c", beat_then_hang],
        heartbeat_timeout_s=1.0, startup_grace_s=30.0, attempts=1,
        term_grace_s=0.5, poll_s=0.1,
        heartbeat_file=str(tmp_path / "hb"),
        telemetry_path=str(tmp_path / "telemetry.jsonl"),
    )
    assert result.attempts[0].reason == "heartbeat_stale"
    kills = [r for r in ledger.read_ledger(led) if r["event"] == "killed"]
    assert len(kills) == 1
    assert kills[0]["kind"] == "supervised"
    assert kills[0]["run_id"] == os.environ[context.RUN_ID_ENV]
    assert kills[0]["attempt"] == 1
    assert kills[0]["reason"] == "heartbeat_stale"
    assert kills[0]["metrics"] == {"last_round": 2}


def test_kill_on_alert_recycles_through_degrade_ladder(clean_ctx, tmp_path):
    """The supervisor hook: a CRITICAL anomaly alert (seeded non-finite
    loss) kills the attempt with reason 'alert' — in seconds, not after a
    heartbeat-staleness window — and the relaunch walks the degrade
    ladder; both attempts' traces stitch under one run id."""
    trace = str(tmp_path / "child_trace.jsonl")
    code = (
        "import sys, time; sys.path.insert(0, %r)\n"
        "from blades_tpu.telemetry.recorder import Recorder\n"
        "from blades_tpu.telemetry import alerts\n"
        "from blades_tpu.supervision.heartbeat import beat\n"
        "rec = Recorder(path=%r, meta={'run': 'diverging'})\n"
        "alerts.install(rec)\n"
        "beat(round_idx=0)\n"
        "rec.round_record(0, train_loss=float('nan'), wall_s=0.1)\n"
        "rec.flush()\n"
        "for i in range(1, 200):\n"
        "    time.sleep(0.1); beat(round_idx=i)\n" % (REPO, trace)
    )
    telem = tmp_path / "telemetry.jsonl"
    result = supervise(
        [sys.executable, "-c", code],
        attempts=2, base_delay_s=0.01, poll_s=0.1,
        heartbeat_timeout_s=30.0, startup_grace_s=30.0, term_grace_s=0.5,
        kill_on_alert=True, degrade=["single_device"],
        heartbeat_file=str(tmp_path / "hb"),
        telemetry_path=str(telem),
    )
    assert [a.reason for a in result.attempts] == ["alert", "alert"]
    assert result.attempts[1].degrade == ("single_device",)
    # the kill event carries the triggering alert body
    kills = [r for r in _records(str(telem))
             if r.get("t") == "supervisor" and r.get("event") == "kill"]
    assert len(kills) == 2
    assert kills[0]["alert"]["rule"] == "loss_nonfinite"
    # both attempts' child traces share the supervisor's run id with
    # incrementing attempt numbers — stitchable by id, no filename games
    child = _records(trace)
    rid = os.environ[context.RUN_ID_ENV]
    assert {r["run_id"] for r in child} == {rid}
    assert {r["attempt"] for r in child} == {1, 2}
    for r in child:
        if r["t"] == "alert":
            assert r["rule"] == "loss_nonfinite"


def test_supervisor_remints_a_process_local_id(clean_ctx, tmp_path):
    """Review finding: an id a previous run in THIS process minted must
    not leak into a new supervised run; a genuinely inherited id must."""
    from blades_tpu.supervision.supervisor import Supervisor

    stale = context.activate(fresh=True)  # e.g. an earlier Simulator run
    sup = Supervisor(["true"], heartbeat_file=str(tmp_path / "hb"))
    assert sup.ctx.run_id != stale.run_id
    # inherited (parent-exported) ids are kept — sharing is the point
    clean_ctx.setenv(context.RUN_ID_ENV, "parent-id")
    clean_ctx.setattr(context, "_minted", set())
    sup2 = Supervisor(["true"], heartbeat_file=str(tmp_path / "hb2"))
    assert sup2.ctx.run_id == "parent-id"


def test_build_phase_crash_still_ledgers_crashed(tmp_path, monkeypatch):
    """Review finding: a crash in the build/warm-up span (the documented
    cold-compile crash window, before the round loop's own handlers) must
    not leave the run 'open' in the ledger forever."""
    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic

    led = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv(ledger.LEDGER_ENV, led)
    ds = Synthetic(num_clients=4, train_size=120, test_size=40, cache=False)
    sim = Simulator(ds, log_path=str(tmp_path / "out"), seed=0,
                    aggregator="mean")
    with pytest.raises(Exception):
        sim.run("no_such_model", global_rounds=1, local_steps=1,
                train_batch_size=8)
    recs = ledger.read_ledger(led)
    assert [r["event"] for r in recs] == ["started", "crashed"]
    assert recs[1]["metrics"] == {"rounds_completed": 0}


def test_kill_on_alert_off_ignores_alert_file(clean_ctx, tmp_path):
    """Without the hook the supervisor must NOT export the alert file —
    a critical alert then changes nothing about process lifetime."""
    probe = tmp_path / "env.json"
    code = (
        "import json, os; open(%r, 'w').write(json.dumps("
        "os.environ.get('BLADES_ALERT_FILE')))" % str(probe)
    )
    result = supervise(
        [sys.executable, "-c", code],
        attempts=1, poll_s=0.05,
        heartbeat_file=str(tmp_path / "hb"),
    )
    assert result.ok
    assert json.loads(probe.read_text()) is None


# ----------------------------------------------------------- query surfaces


def test_runs_cli_summarizes_ledger(tmp_path):
    led = tmp_path / "ledger.jsonl"
    recs = [
        {"t": "ledger", "event": "started", "ts": 1.0, "run_id": "r1",
         "attempt": 1, "kind": "simulator", "config_fingerprint": "fp1"},
        {"t": "ledger", "event": "finished", "ts": 2.0, "run_id": "r1",
         "attempt": 1, "kind": "simulator", "wall_s": 1.0,
         "metrics": {"rounds_per_sec": 3.0}},
        {"t": "ledger", "event": "started", "ts": 3.0, "run_id": "r2",
         "attempt": 1, "kind": "bench", "config_fingerprint": "fp2"},
    ]
    led.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "runs.py"),
         "--ledger", str(led)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1  # the one-JSON-line contract
    payload = json.loads(lines[0])
    assert payload["ok"] and payload["runs"] == 2
    assert payload["by_kind"] == {"simulator": 1, "bench": 1}
    assert payload["by_outcome"] == {"finished": 1, "open": 1}
    assert payload["distinct_configs"] == 2
    latest = {r["run_id"]: r for r in payload["latest"]}
    assert latest["r1"]["rounds_per_sec"] == 3.0
    # --run-id trail
    trail = json.loads(runs_cli_capture(["--ledger", str(led),
                                         "--run-id", "r1"]))
    assert trail["found"] and len(trail["attempts"]) == 1
    assert trail["attempts"][0]["outcome"] == "finished"


def runs_cli_capture(argv):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "runs.py"), *argv],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stdout.strip().splitlines()[-1]


def test_runs_cli_tunnel_windows(tmp_path):
    import runs as runs_cli

    t0 = 1000.0
    probes = (
        [{"t": "tunnel_probe", "ts": t0 + i * 60, "up": False}
         for i in range(3)]
        + [{"t": "tunnel_probe", "ts": t0 + 180 + i * 60, "up": True}
           for i in range(2)]
        + [{"t": "tunnel_probe", "ts": t0 + 300, "up": False}]
    )
    summary = runs_cli.summarize_tunnel(probes)
    assert summary["probes"] == 6 and summary["up_probes"] == 2
    assert summary["up_windows"] == 1 and summary["down_windows"] == 2
    # each inter-probe interval belongs to the state its STARTING probe
    # observed, so windows tile the full observed span: down owns
    # [0, 180), up owns [180, 300), the final down probe is a point
    assert summary["longest_up_s"] == 120.0
    assert summary["longest_down_s"] == 180.0
    assert summary["observed_s"] == 300.0
    assert summary["up_time_frac"] == 0.4
    assert summary["last_up"] is False
    assert runs_cli.summarize_tunnel([]) == {"probes": 0}
    # an alternating flaky log must still attribute every interval
    flaky = [{"t": "tunnel_probe", "ts": t0 + i * 60, "up": bool(i % 2)}
             for i in range(5)]
    s = runs_cli.summarize_tunnel(flaky)
    assert s["observed_s"] == 240.0
    assert s["up_time_frac"] == 0.5 and s["longest_down_s"] == 60.0


def test_runs_cli_missing_probe_log_is_empty_not_error():
    """Review finding: no probe log is a valid observation (the vigil has
    not run yet) — the CLI degrades to an empty tunnel summary."""
    line = json.loads(subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "runs.py"),
         "--tunnel", "/nonexistent/probes.jsonl"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    ).stdout.strip())
    assert line["ok"] is True and line["tunnel"] == {"probes": 0}


def test_runs_cli_error_is_one_json_line(monkeypatch, capsys):
    """A bug in the query itself still reaches the driver as one
    parseable error line (the JSON001 catch-all)."""
    import runs as runs_cli

    monkeypatch.setattr(
        runs_cli, "summarize_runs",
        lambda records: (_ for _ in ()).throw(RuntimeError("boom")),
    )
    assert runs_cli.main(["--ledger", "/nonexistent/ledger.jsonl"]) == 1
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    assert len(lines) == 1
    payload = json.loads(lines[0])
    assert payload["ok"] is False and "boom" in payload["error"]


def test_tpu_capture_probe_record(tmp_path, monkeypatch):
    """record_probe persists timestamped up/down evidence and never
    raises, even against an unwritable destination."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import tpu_capture

    dest = str(tmp_path / "probes.jsonl")
    monkeypatch.setattr(tpu_capture, "PROBES", dest)
    tpu_capture.record_probe(True, wall_s=1.5, source="watch")
    tpu_capture.record_probe(False, source="capture")
    recs = _records(dest)
    assert [r["up"] for r in recs] == [True, False]
    assert recs[0]["t"] == "tunnel_probe" and recs[0]["wall_s"] == 1.5
    assert recs[0]["source"] == "watch"
    monkeypatch.setattr(tpu_capture, "PROBES", "/nonexistent/dir/p.jsonl")
    tpu_capture.record_probe(True)  # must not raise


def test_perf_report_ingests_ledger_rows(tmp_path):
    import perf_report

    results = tmp_path / "results"
    results.mkdir()
    recs = [
        {"t": "ledger", "event": "started", "ts": 1.0, "run_id": "rid-1",
         "attempt": 1, "kind": "bench", "config_fingerprint": "fp",
         "code_version": "a" * 40},
        {"t": "ledger", "event": "finished", "ts": 2.0, "run_id": "rid-1",
         "attempt": 1, "kind": "bench",
         "metrics": {"rounds_per_sec": 7.5}},
        # a run without throughput metrics contributes no row
        {"t": "ledger", "event": "started", "ts": 3.0, "run_id": "rid-2",
         "attempt": 1, "kind": "chaos"},
    ]
    (results / "ledger.jsonl").write_text(
        "\n".join(json.dumps(r) for r in recs) + "\n"
    )
    rows = perf_report.ingest_ledger(str(tmp_path))
    assert len(rows) == 1
    (row,) = rows
    assert row["name"] == "ledger/bench/rid-1"
    assert row["run_id"] == "rid-1" and row["rounds_per_sec"] == 7.5
    assert row["config"] == "fp" and row["code_version"] == "a" * 12


def test_trace_summary_compare_refuses_fingerprint_mismatch(tmp_path,
                                                            capsys):
    import trace_summary

    def mk(path, rid, fp):
        recs = [
            {"t": "meta", "ts": 1.0, "pid": 1, "run_id": rid, "attempt": 1,
             "config_fingerprint": fp},
            {"t": "round", "round": 0, "wall_s": 0.1, "counters": {},
             "gauges": {}, "run_id": rid, "attempt": 1},
        ]
        with open(path, "w") as f:
            f.write("\n".join(json.dumps(r) for r in recs))

    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    mk(a, "ra", "fp-a")
    mk(b, "rb", "fp-b")
    assert trace_summary.main(["--compare", a, b]) == 2
    assert "REFUSING" in capsys.readouterr().err
    assert trace_summary.main(["--compare", "--force", a, b]) == 0
    captured = capsys.readouterr()
    assert "WARNING" in captured.err
    assert "run_id ra" in captured.out and "run_id rb" in captured.out
    # same fingerprint: clean compare, no warning
    c = str(tmp_path / "c.jsonl")
    mk(c, "rc", "fp-a")
    assert trace_summary.main(["--compare", a, c]) == 0
    assert "WARNING" not in capsys.readouterr().err


# -------------------------------------------------- simulator acceptance


@pytest.fixture(scope="module")
def healthy_run(tmp_path_factory):
    """ONE tiny healthy Simulator run shared by the acceptance asserts:
    ledger pair, envelope on every trace record, alert silence."""
    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic

    tmp = tmp_path_factory.mktemp("run_identity")
    led = str(tmp / "ledger.jsonl")
    old = os.environ.get(ledger.LEDGER_ENV)
    os.environ[ledger.LEDGER_ENV] = led
    try:
        ds = Synthetic(num_clients=6, train_size=240, test_size=60,
                       cache=False)
        log = str(tmp / "out")
        sim = Simulator(ds, log_path=log, seed=0, aggregator="mean")
        sim.run("mlp", global_rounds=3, local_steps=1, train_batch_size=8,
                validate_interval=3, round_metrics=True)
    finally:
        if old is None:
            os.environ.pop(ledger.LEDGER_ENV, None)
        else:
            os.environ[ledger.LEDGER_ENV] = old
    return {
        "ledger": ledger.read_ledger(led),
        "trace": _records(os.path.join(log, "telemetry.jsonl")),
    }


def test_simulator_run_writes_ledger_pair(healthy_run):
    recs = healthy_run["ledger"]
    assert [r["event"] for r in recs] == ["started", "finished"]
    started, finished = recs
    assert started["kind"] == "simulator"
    assert started["run_id"] == finished["run_id"]
    assert started["config_fingerprint"]
    assert started["config"]["num_clients"] == 6
    assert started["env"].get("jax")  # env fingerprint saw the live jax
    assert started["env"].get("n_devices") == 8  # conftest virtual mesh
    assert started["code_version"] == ledger.code_version()
    assert any("telemetry.jsonl" in a for a in started["artifacts"])
    assert finished["metrics"]["rounds_completed"] == 3
    assert finished["metrics"]["rounds_per_sec"] > 0


def test_simulator_trace_carries_envelope_on_every_record(healthy_run):
    trace = healthy_run["trace"]
    rid = healthy_run["ledger"][0]["run_id"]
    assert len(trace) > 10
    meta = trace[0]
    assert meta["t"] == "meta" and meta["run_id"] == rid
    assert meta["config_fingerprint"] == (
        healthy_run["ledger"][0]["config_fingerprint"]
    )
    for r in trace:
        assert r.get("run_id") == rid and r.get("attempt") == 1, r


def test_healthy_run_raises_zero_alerts(healthy_run):
    trace = healthy_run["trace"]
    assert [r for r in trace if r["t"] == "alert"] == []
    # offline replay over the same records agrees
    assert alerts.evaluate_records(trace) == []


def test_interrupted_run_ledgers_killed_not_finished(tmp_path, monkeypatch):
    """Review finding: a BaseException exit (Ctrl-C on a hung compile,
    SupervisorTermination) bypasses the `except Exception` crash path —
    the finally block must record it as `killed`, never `finished`."""
    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic

    led = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv(ledger.LEDGER_ENV, led)

    def interrupt(rnd, state, m):
        raise KeyboardInterrupt

    ds = Synthetic(num_clients=4, train_size=120, test_size=40, cache=False)
    sim = Simulator(ds, log_path=str(tmp_path / "out"), seed=0,
                    aggregator="mean")
    with pytest.raises(KeyboardInterrupt):
        sim.run("mlp", global_rounds=3, local_steps=1, train_batch_size=8,
                validate_interval=5, on_round_end=interrupt)
    recs = ledger.read_ledger(led)
    assert [r["event"] for r in recs] == ["started", "killed"]
    assert "KeyboardInterrupt" in recs[1]["error"]


def test_telemetry_disabled_is_complete_noop(tmp_path, monkeypatch):
    """BLADES_TELEMETRY=0: no trace, no alert engine — and the run still
    completes. (The ledger has its own independent BLADES_LEDGER=0.)"""
    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic

    monkeypatch.setenv("BLADES_TELEMETRY", "0")
    monkeypatch.setenv(ledger.LEDGER_ENV, "0")
    ds = Synthetic(num_clients=4, train_size=120, test_size=40, cache=False)
    log = str(tmp_path / "out")
    sim = Simulator(ds, log_path=log, seed=0, aggregator="mean")
    sim.run("mlp", global_rounds=1, local_steps=1, train_batch_size=8,
            validate_interval=5)
    assert not os.path.exists(os.path.join(log, "telemetry.jsonl"))
    assert sim.alert_engine is None
