"""Streaming (chunk-scanned) aggregation tests — the contracts behind
``RoundEngine(streaming=True)`` and the ``Aggregator.streaming_*`` protocol:

1. **Registry lint** — every registered aggregator either implements the
   streaming protocol or documents WHY it cannot (``streaming_optouts``);
   a new defense cannot silently ship without a position on large-K.
2. **Parity** — exact-form aggregators (``streaming_exact``) reproduce the
   dense estimator across chunk counts {1, 2, K} up to floating-point
   re-association of the chunk partial sums; two-level forms stay inside
   the participants' per-coordinate envelope and within the update
   diameter of the dense result (their documented bound), and collapse to
   the dense result on concentrated honest updates.
3. **Mask semantics** — a masked-out row's payload is inert bit-exactly
   (NaN/Inf/1e30 garbage), matching the dense mask-API contract.
4. **Engine equivalence** — the streaming round program matches the dense
   round (mean: tight; robust: documented tolerance), composes with the
   padded final chunk, fault masks, audit monitor + streaming fallback,
   and ``run_block`` (block-of-streaming-rounds bit-exact vs sequential).
5. **Streaming audit certificates** — singleton chunks reproduce the dense
   certificates exactly; interval bounds bracket the dense statistics.

Reference counterpart: none — the reference's client axis is a host-side
Python list (``src/blades/aggregators/mean.py:21-28``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.aggregators import AGGREGATORS, get_aggregator
from blades_tpu.attackers import get_attack
from blades_tpu.audit.monitor import AuditMonitor
from blades_tpu.core import ClientOptSpec, RoundEngine
from blades_tpu.faults import FaultModel
from blades_tpu.ops.pytree import ravel

K, D = 12, 7


def _agg(name):
    kw = {"num_byzantine": 2} if name in (
        "trimmedmean", "krum", "multikrum", "dnc"
    ) else {}
    return get_aggregator(name, **kw)


def _ctx(name, k=K, d=D):
    if name == "dnc":
        return {"key": jax.random.key(3)}
    if name == "byzantinesgd":
        return {"params_flat": jnp.zeros(d)}
    if name == "fltrust":
        return {"trusted_mask": jnp.zeros(k, bool).at[3].set(True)}
    return {}


def rand_updates(seed=0, k=K, d=D):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(k, d)).astype(np.float32)


STREAMING = sorted(
    n for n in AGGREGATORS if _agg(n).supports_streaming()
)
EXACT = sorted(n for n in STREAMING if _agg(n).streaming_exact)
TWO_LEVEL = sorted(n for n in STREAMING if not _agg(n).streaming_exact)


# ------------------------------------------------------------ registry lint


@pytest.mark.parametrize("name", sorted(AGGREGATORS))
def test_registry_streaming_lint(name):
    """CI lint: streaming path implemented OR a documented opt-out reason —
    the large-K story of every registered defense is explicit."""
    agg = _agg(name)
    if agg.supports_streaming():
        return
    reason = agg.streaming_optouts.get("streaming")
    assert isinstance(reason, str) and len(reason) > 20, (
        f"{name} neither implements streaming aggregation nor documents "
        "a streaming_optouts reason"
    )


def test_streaming_coverage_is_what_we_think():
    """13 streaming defenses / 3 documented dense-only holdouts — this
    pins the split so a regression (an aggregator silently dropping its
    streaming form) shows up as a diff here, not as a silent opt-out."""
    assert set(AGGREGATORS) - set(STREAMING) == {
        "fltrust", "byzantinesgd", "dnc"
    }


# ------------------------------------------------------------------- parity


@pytest.mark.parametrize("chunks", [1, 2, K])
@pytest.mark.parametrize("name", EXACT)
def test_exact_streaming_matches_dense(name, chunks):
    """Exact-form aggregators produce the dense estimator: any deviation is
    floating-point re-association of chunk partial sums (machine-epsilon
    scale), never an approximation."""
    u = jnp.asarray(rand_updates(seed=1))
    a = _agg(name)
    dense, _ = a.aggregate(u, a.init_state(K, D), **_ctx(name))
    got, _ = a.aggregate_streaming(
        u, a.init_state(K, D), num_chunks=chunks, **_ctx(name)
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(dense), rtol=1e-5, atol=1e-6
    )


@pytest.mark.parametrize("chunks", [2, 3, K])
@pytest.mark.parametrize("name", TWO_LEVEL)
def test_two_level_streaming_bounds(name, chunks):
    """The documented two-level bound: the streaming aggregate stays inside
    the participants' per-coordinate envelope (union with 0 for the
    clipping/filter family, whose members shrink rows toward the origin),
    and within the update diameter of the dense result."""
    u = rand_updates(seed=2)
    a = _agg(name)
    dense, _ = a.aggregate(jnp.asarray(u), a.init_state(K, D), **_ctx(name))
    got, _ = a.aggregate_streaming(
        jnp.asarray(u), a.init_state(K, D), num_chunks=chunks, **_ctx(name)
    )
    got = np.asarray(got)
    assert np.isfinite(got).all()
    lo = np.minimum(u.min(axis=0), 0.0) - 1e-5
    hi = np.maximum(u.max(axis=0), 0.0) + 1e-5
    assert (got >= lo).all() and (got <= hi).all(), (
        f"{name}: two-level result left the participants' envelope"
    )
    diam = np.sqrt(
        ((u[:, None, :] - u[None, :, :]) ** 2).sum(-1)
    ).max()
    assert np.linalg.norm(got - np.asarray(dense)) <= diam + 1e-5


@pytest.mark.parametrize("name", STREAMING)
def test_streaming_concentrated_matches_dense(name):
    """On concentrated honest updates (spread << scale) every streaming
    form — exact or two-level — agrees with the dense path to the update
    diameter: the error of 'aggregate the chunk-aggregates' is bounded by
    the honest spread, so it vanishes exactly when defenses matter least."""
    rng = np.random.default_rng(5)
    mu = rng.normal(size=(1, D)).astype(np.float32)
    u = mu + 0.01 * rng.normal(size=(K, D)).astype(np.float32)
    a = _agg(name)
    dense, _ = a.aggregate(jnp.asarray(u), a.init_state(K, D), **_ctx(name))
    got, _ = a.aggregate_streaming(
        jnp.asarray(u), a.init_state(K, D), num_chunks=3, **_ctx(name)
    )
    diam = np.sqrt(((u[:, None, :] - u[None, :, :]) ** 2).sum(-1)).max()
    assert np.linalg.norm(np.asarray(got) - np.asarray(dense)) <= diam + 1e-6


def test_clippedclustering_ring_ingests_exactly_k_per_round():
    """The norm-history ring advances by exactly K entries per streaming
    round — the padded final chunk's zero rows write no phantom history
    (K=10 @ 4 chunks of 3: pad 2), matching the dense path's write count."""
    u = jnp.asarray(rand_updates(seed=12, k=10))
    a = get_aggregator("clippedclustering")
    _, new_state = a.aggregate_streaming(u, a.init_state(10, D), num_chunks=4)
    assert int(new_state["count"]) == 10
    assert int(new_state["pos"]) == 10


def test_chunk_count_clamps_to_population():
    """num_chunks > K clamps to K (singleton chunks) instead of dying."""
    u = jnp.asarray(rand_updates(seed=3))
    a = _agg("median")
    big, _ = a.aggregate_streaming(u, num_chunks=50)
    ref, _ = a.aggregate_streaming(u, num_chunks=K)
    np.testing.assert_array_equal(np.asarray(big), np.asarray(ref))


def test_streaming_stateful_rounds_advance_state():
    """Centered clipping's momentum threads through streaming rounds: two
    streaming rounds (n_iter=1, the exact regime) track two dense rounds."""
    a = get_aggregator("centeredclipping", n_iter=1)
    b = get_aggregator("centeredclipping", n_iter=1)
    st_a, st_b = a.init_state(K, D), b.init_state(K, D)
    for seed in (7, 8):
        u = jnp.asarray(rand_updates(seed=seed))
        dense, st_a = a.aggregate(u, st_a)
        got, st_b = b.aggregate_streaming(u, st_b, num_chunks=3)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(dense), rtol=1e-5, atol=1e-6
        )
    np.testing.assert_allclose(
        np.asarray(st_b), np.asarray(st_a), rtol=1e-5, atol=1e-6
    )


# ---------------------------------------------------------- mask semantics


@pytest.mark.parametrize("garbage", [np.nan, np.inf, 1e30])
@pytest.mark.parametrize("name", STREAMING)
def test_streaming_masked_out_rows_inert(name, garbage):
    """Masked-out payloads cannot change the streaming result in any bit —
    the slabs are sanitized before any reduction, same rule as the dense
    mask API (tests/test_faults.py)."""
    base = rand_updates(seed=4)
    mask = jnp.asarray([True] * 7 + [False] * 5)
    poisoned = base.copy()
    poisoned[7:] = garbage

    a_ref = _agg(name)
    ref, _ = a_ref.aggregate_streaming(
        jnp.asarray(base), a_ref.init_state(K, D), num_chunks=3, mask=mask,
        **_ctx(name),
    )
    a_poi = _agg(name)
    got, _ = a_poi.aggregate_streaming(
        jnp.asarray(poisoned), a_poi.init_state(K, D), num_chunks=3,
        mask=mask, **_ctx(name),
    )
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
    assert np.isfinite(np.asarray(got)).all()


@pytest.mark.parametrize("name", STREAMING)
def test_streaming_zero_participants_finite(name):
    """An all-masked stream still finalizes to a finite vector (the engine
    additionally zeroes it — graceful skip)."""
    u = jnp.asarray(rand_updates(seed=6))
    a = _agg(name)
    got, _ = a.aggregate_streaming(
        u, a.init_state(K, D), num_chunks=3, mask=jnp.zeros(K, bool),
        **_ctx(name),
    )
    assert np.isfinite(np.asarray(got)).all()


# ------------------------------------------------------- engine equivalence


BLOCK_K, BLOCK_F, BLOCK_C = 6, 12, 4


def _tiny_loss(p, x, y, key):
    logits = x.reshape(x.shape[0], -1) @ p["w"]
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
    top1 = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
    return loss, {"top1": top1}


def _tiny_logits(p, x):
    return x.reshape(x.shape[0], -1) @ p["w"]


def _tiny_fixture(k=BLOCK_K, seed=0):
    from blades_tpu.datasets.fl import FLDataset

    rng = np.random.RandomState(seed)
    ds = FLDataset(
        rng.randn(k, 20, BLOCK_F).astype(np.float32),
        rng.randint(0, BLOCK_C, (k, 20)).astype(np.int32),
        np.full(k, 20, np.int32),
        rng.randn(30, BLOCK_F).astype(np.float32),
        rng.randint(0, BLOCK_C, 30).astype(np.int32),
    )
    W0 = {"w": jnp.asarray(rng.randn(BLOCK_F, BLOCK_C).astype(np.float32) * 0.1)}
    return ds, W0


def _tiny_engine(W0, k=BLOCK_K, **kw):
    defaults = dict(num_clients=k, num_classes=BLOCK_C,
                    aggregator=get_aggregator("mean"))
    defaults.update(kw)
    return RoundEngine(_tiny_loss, _tiny_logits, W0, **defaults)


def _one_round(eng, ds, W0, rounds=1):
    st = eng.init(W0)
    key = jax.random.PRNGKey(7)
    for r in range(rounds):
        cx, cy = ds.sample_round(jax.random.fold_in(key, r), 2, 4)
        st, m = eng.run_round(st, cx, cy, 0.2, 1.0, key)
    return st, m


@pytest.mark.parametrize("chunks", [1, 3])
def test_engine_streaming_matches_dense_mean(chunks):
    """Streaming round == dense round for the exact-form mean (chunks=3:
    6 clients in 3 chunks of 2; the padded-chunk case is covered at K=7
    below)."""
    ds, W0 = _tiny_fixture()
    dense = _tiny_engine(W0)
    stream = _tiny_engine(W0, client_chunks=chunks, streaming=True)
    sd, md = _one_round(dense, ds, W0)
    ss, ms = _one_round(stream, ds, W0)
    np.testing.assert_allclose(
        np.asarray(ravel(ss.params)), np.asarray(ravel(sd.params)),
        rtol=1e-5, atol=1e-7,
    )
    # losses/top1s are exact in streaming; variance is one-pass moments
    np.testing.assert_allclose(float(ms.train_loss), float(md.train_loss),
                               rtol=1e-6)
    np.testing.assert_allclose(
        float(ms.update_variance), float(md.update_variance),
        rtol=1e-4, atol=1e-8,
    )


def test_engine_padded_final_chunk_dense():
    """K=7 with client_chunks=2 (chunk_size 4, pad 1) matches the
    unchunked round — the old divisibility ValueError is gone and the
    zero-padded row is exactly inert. K=6 @ chunks=4 additionally pins
    the chunk-count renormalization: no chunk is ever 100% padding."""
    ds, W0 = _tiny_fixture(k=7)
    whole = _tiny_engine(W0, k=7)
    padded = _tiny_engine(W0, k=7, client_chunks=2)
    assert padded.chunk_size == 4 and padded._pad == 1
    sw, _ = _one_round(whole, ds, W0)
    sp, _ = _one_round(padded, ds, W0)
    np.testing.assert_allclose(
        np.asarray(ravel(sp.params)), np.asarray(ravel(sw.params)),
        rtol=1e-5, atol=1e-7,
    )
    # renormalization: ceil(6/4)=2-sized chunks need only 3 chunks — a
    # 4th all-pad chunk would be trained and thrown away every round
    renorm = _tiny_engine(_tiny_fixture()[1], client_chunks=4)
    assert renorm.client_chunks == 3 and renorm.chunk_size == 2
    assert renorm._pad == 0


def test_engine_chunks_clamp_to_population():
    ds, W0 = _tiny_fixture()
    eng = _tiny_engine(W0, client_chunks=64)
    assert eng.client_chunks == BLOCK_K and eng.chunk_size == 1
    st, m = _one_round(eng, ds, W0)
    assert np.isfinite(float(m.train_loss))


def test_engine_streaming_robust_agg_under_attack():
    """Streaming trimmed-mean under sign-flipping: the two-level defense
    tracks the dense one within the per-round update diameter (documented
    bound), and training still descends."""
    ds, W0 = _tiny_fixture()
    kw = dict(
        num_byzantine=2,
        attack=get_attack("signflipping"),
        aggregator=get_aggregator("trimmedmean", num_byzantine=2),
    )
    dense = _tiny_engine(W0, **kw)
    stream = _tiny_engine(W0, client_chunks=3, streaming=True, **kw)
    sd, md = _one_round(dense, ds, W0, rounds=3)
    ss, ms = _one_round(stream, ds, W0, rounds=3)
    assert np.isfinite(float(ms.train_loss))
    # 3 rounds of server steps on a 0.1-scale linear model: the two-level
    # trim stays within the honest cloud, so params stay close
    np.testing.assert_allclose(
        np.asarray(ravel(ss.params)), np.asarray(ravel(sd.params)),
        rtol=0.2, atol=0.05,
    )


def test_engine_streaming_fault_masks_match_dense():
    """Dropout + NaN corruption draws are bit-identical between the dense
    fault pass and the streaming plan (same key splits), so the per-round
    fault counters agree exactly."""
    ds, W0 = _tiny_fixture()
    fm = FaultModel(dropout_rate=0.3, corrupt_rate=0.3, corrupt_mode="nan")
    dense = _tiny_engine(W0, fault_model=fm)
    stream = _tiny_engine(W0, client_chunks=3, streaming=True, fault_model=fm)
    _, _ = _one_round(dense, ds, W0)
    _, _ = _one_round(stream, ds, W0)
    d_diag = {k: int(v) for k, v in dense.last_fault_diag.items()}
    s_diag = {k: int(v) for k, v in stream.last_fault_diag.items()}
    assert d_diag == s_diag
    assert s_diag["participants"] <= BLOCK_K


def test_engine_streaming_audit_breach_swaps_fallback():
    """Streaming audit: a mean aggregate dragged out by sign-flipped rows
    breaches the streaming certificates and the round applies the
    (streaming) median fallback in-graph; the attack-free twin certifies
    clean."""
    ds, W0 = _tiny_fixture()
    mon = AuditMonitor(fallback_aggregator="median")
    clean = _tiny_engine(W0, client_chunks=3, streaming=True,
                         audit_monitor=mon)
    _one_round(clean, ds, W0)
    assert int(clean.last_audit_diag["breach"]) == 0

    attacked = _tiny_engine(
        W0, client_chunks=3, streaming=True, audit_monitor=mon,
        num_byzantine=2,
        attack=get_attack("noise", mean=50.0, std=1.0),
        aggregator=get_aggregator("mean"),
    )
    _, m = _one_round(attacked, ds, W0)
    assert int(attacked.last_audit_diag["breach"]) == 1
    assert int(attacked.last_audit_diag["fallback_used"]) == 1
    assert np.isfinite(float(m.agg_norm))


def test_engine_streaming_block_bit_exact():
    """A block of streaming rounds is bit-identical to sequential streaming
    rounds — run_block scans the SAME streaming body, so the round-block
    invariant carries over unchanged."""
    ds, W0 = _tiny_fixture()
    key = jax.random.PRNGKey(7)
    dk = jax.random.fold_in(key, 23)
    eng = _tiny_engine(W0, client_chunks=3, streaming=True,
                       aggregator=get_aggregator("median"))
    st = eng.init(W0)
    for r in range(1, 3):
        cx, cy = ds.sample_round(jax.random.fold_in(dk, r), 2, 4)
        st, _ = eng.run_round(st, cx, cy, 0.2, 1.0, key)

    st2 = eng.init(W0)
    keys = jnp.stack([jax.random.fold_in(dk, r) for r in range(1, 3)])
    st2, ms, _ = eng.run_block(
        st2, keys, [0.2, 0.2], [1.0, 1.0], key,
        sampler=ds.traceable_sampler(2, 4),
    )
    np.testing.assert_array_equal(
        np.asarray(ravel(st.params)), np.asarray(ravel(st2.params))
    )


def test_engine_streaming_build_time_validation():
    """Misconfigurations fail at engine build with the documented reason,
    never at trace time."""
    _, W0 = _tiny_fixture()
    with pytest.raises(ValueError, match="does not implement streaming"):
        _tiny_engine(W0, streaming=True, aggregator=get_aggregator("fltrust"))
    with pytest.raises(ValueError, match="full-population"):
        _tiny_engine(
            W0, streaming=True, num_byzantine=2,
            attack=get_attack("alie", num_clients=BLOCK_K, num_byzantine=2),
        )
    with pytest.raises(ValueError, match="straggler"):
        _tiny_engine(W0, streaming=True,
                     fault_model=FaultModel(straggler_rate=0.5))
    with pytest.raises(ValueError, match="collect_diagnostics"):
        _tiny_engine(W0, streaming=True, collect_diagnostics=True)
    with pytest.raises(ValueError, match="fallback"):
        _tiny_engine(
            W0, streaming=True,
            audit_monitor=AuditMonitor(fallback_aggregator=_agg("dnc")),
        )
    # conditional support: the async clipper's single-pass form exists
    # only at n_iter=1 — n_iter>1 must be rejected at BUILD time too
    with pytest.raises(ValueError, match="n_iter"):
        _tiny_engine(
            W0, streaming=True,
            aggregator=get_aggregator("asynccenteredclipping", n_iter=2),
        )


def test_peak_update_bytes_estimates():
    """The memory gauge: dense rounds account the (padded) [K, D] matrix,
    streaming rounds one [chunk, D] slab — the quantity the K-scaling
    evidence rows commit."""
    _, W0 = _tiny_fixture()
    d = sum(int(np.prod(v.shape)) for v in jax.tree_util.tree_leaves(W0))
    dense = _tiny_engine(W0, client_chunks=4)  # renormalized: 3 chunks of 2
    assert dense.peak_update_bytes == 6 * d * 4
    padded = _tiny_engine(W0, k=7, client_chunks=2)  # chunk 4, pad 1
    assert padded.peak_update_bytes == 8 * d * 4
    stream = _tiny_engine(W0, client_chunks=3, streaming=True)
    assert stream.peak_update_bytes == 2 * d * 4


def test_engine_streaming_persistent_client_opt():
    """Per-client Adam moments ride the chunk scan: stacked [K, ...] state
    survives a streaming round (and matches the dense round tightly — the
    optimizer math is per-client, only the aggregate differs by
    re-association)."""
    ds, W0 = _tiny_fixture()
    kw = dict(client_opt=ClientOptSpec(name="adam", persist=True))
    dense = _tiny_engine(W0, **kw)
    stream = _tiny_engine(W0, client_chunks=3, streaming=True, **kw)
    sd, _ = _one_round(dense, ds, W0)
    ss, _ = _one_round(stream, ds, W0)
    leaves = jax.tree_util.tree_leaves(ss.client_opt_state)
    assert leaves[0].shape[0] == BLOCK_K
    np.testing.assert_allclose(
        np.asarray(ravel(ss.params)), np.asarray(ravel(sd.params)),
        rtol=1e-5, atol=1e-7,
    )


# ------------------------------------------- streaming audit certificates


def _stream_certify(mon, updates, agg, num_chunks, mask=None):
    """Drive the monitor's streaming protocol the way the engine does."""
    from blades_tpu.ops.streaming import chunk_layout

    k, d = updates.shape
    c, chunk, pad = chunk_layout(k, num_chunks)
    mask = jnp.ones(k, bool) if mask is None else jnp.asarray(mask)
    u = jnp.pad(jnp.asarray(updates), ((0, pad), (0, 0)))
    m = jnp.pad(mask, (0, pad))
    ss = mon.streaming_init(k, c, chunk, d)
    for j in range(c):
        rows = slice(j * chunk, (j + 1) * chunk)
        mc = m[rows]
        safe = jnp.where(mc[:, None], u[rows], 0.0)
        ss = mon.streaming_update(
            ss, safe, chunk_mask=mc, chunk_index=jnp.asarray(j, jnp.int32)
        )
    return mon.streaming_apply(ss, jnp.asarray(agg))


def test_streaming_certificates_singleton_chunks_equal_dense():
    """chunk_size=1 collapses every interval bound to a point: the
    streaming certificates ARE the dense ones."""
    u = rand_updates(seed=9)
    agg = u.mean(axis=0)
    mon = AuditMonitor()
    breach_d, diag_d = mon.certify(jnp.asarray(u), jnp.asarray(agg))
    _, diag_s = _stream_certify(mon, u, agg, num_chunks=K)
    assert bool(breach_d) == bool(diag_s["breach"])
    np.testing.assert_allclose(
        float(diag_s["dev_median"]), float(diag_d["dev_median"]), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(diag_s["spread_median"]), float(diag_d["spread_median"]),
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        float(diag_s["diameter"]), float(diag_d["diameter"]), rtol=1e-5
    )


def test_streaming_certificate_bounds_bracket_dense():
    """With real chunks the lo/hi interval forensics bracket the dense
    statistics (the certificates evaluate on the tolerant side of each)."""
    u = rand_updates(seed=10)
    agg = u.mean(axis=0)
    mon = AuditMonitor()
    _, diag_d = mon.certify(jnp.asarray(u), jnp.asarray(agg))
    _, diag_s = _stream_certify(mon, u, agg, num_chunks=3)
    eps = 1e-5
    assert (
        float(diag_s["spread_median_lo"]) - eps
        <= float(diag_d["spread_median"])
        <= float(diag_s["spread_median"]) + eps
    )
    assert (
        float(diag_s["diameter_lo"]) - eps
        <= float(diag_d["diameter"])
        <= float(diag_s["diameter"]) + eps
    )


def test_streaming_certificates_flag_gross_breach():
    """A far-out aggregate breaches even under the tolerant interval
    evaluation; a benign aggregate certifies clean."""
    rng = np.random.default_rng(11)
    u = (rng.normal(size=(K, D)) * 0.1).astype(np.float32)
    mon = AuditMonitor()
    _, diag_ok = _stream_certify(mon, u, u.mean(axis=0), num_chunks=3)
    assert int(diag_ok["breach"]) == 0
    _, diag_bad = _stream_certify(
        mon, u, u.mean(axis=0) + 100.0, num_chunks=3
    )
    assert int(diag_bad["breach"]) == 1
