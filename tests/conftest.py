"""Test config: force an 8-device virtual CPU mesh before JAX backend init.

Multi-chip sharding logic is validated on fake XLA CPU devices (the strategy
the reference could not have: it has no tests at all — SURVEY.md section 4).
The flag recipe lives in ``blades_tpu.utils.platform`` (single owner);
importing it pulls in jax, which is safe — only the first *backend touch*
freezes the platform, and ``force_virtual_cpu`` runs before that.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Run-provenance hygiene: hundreds of tests construct Simulators (and
# subprocess children inherit this env), and their ledger records must
# land in a throwaway per-session file — never the repo's committed
# results/ledger.jsonl. Tests that assert ledger behavior pass their own
# explicit path (or override BLADES_LEDGER themselves).
if "BLADES_LEDGER" not in os.environ:
    import tempfile

    os.environ["BLADES_LEDGER"] = os.path.join(
        tempfile.mkdtemp(prefix="blades_test_ledger_"), "ledger.jsonl"
    )

from blades_tpu.utils.platform import force_virtual_cpu  # noqa: E402

force_virtual_cpu(8)

import jax  # noqa: E402

jax.config.update("jax_enable_x64", False)


def pytest_report_header(config):
    return f"jax {jax.__version__}, devices: {jax.device_count()} ({jax.devices()[0].platform})"


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy end-to-end scenarios (full chaos sweep, supervised "
        "subprocess runs) excluded from tier-1 via -m 'not slow'",
    )
