"""Test config: force an 8-device virtual CPU mesh before JAX import.

Multi-chip sharding logic is validated on fake XLA CPU devices (the strategy
the reference could not have: it has no tests at all — SURVEY.md section 4).
"""

import os

# hard assignment, not setdefault: the TPU plugin's sitecustomize plants
# JAX_PLATFORMS=axon at interpreter start when the var is unset
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
if "collective_call_terminate_timeout" not in _flags:
    # 8 virtual devices can timeshare a single physical core; XLA's 40s
    # rendezvous termination timeout hard-aborts under that contention
    _flags += " --xla_cpu_collective_call_terminate_timeout_seconds=600"
os.environ["XLA_FLAGS"] = _flags

import jax  # noqa: E402

# The axon TPU plugin's sitecustomize forces jax_platforms="axon,cpu" at
# interpreter start, overriding the env var — override it back after import.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)


def pytest_report_header(config):
    return f"jax {jax.__version__}, devices: {jax.device_count()} ({jax.devices()[0].platform})"
