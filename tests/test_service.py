"""Simulation service (`blades_tpu/service`, `scripts/serve.py`): the
long-lived crash-tolerant experiment server — spool/journal durability
under concurrent writers, request-level fault isolation (poison
quarantine with sibling+neighbor salvage, deadline-tripped hangs,
backpressure), drain-with-zero-loss, the supervised SIGKILL → resume →
content-identical e2e, warm-cache serving with a zero-new-compiles pin,
the request-path accounting surfaces (PR 15: in-flight id/age on
`op: status`, `op: metrics` + `serve.py metrics`, warm/cold
classification and the queue-wait/build/execute split on the finished
records and metrics snapshots), and the health surfaces
(`sweep_status`, `runs.py`) + perf-gate guards (warm cell wall, warm
p99, queue-wait share — fire and pass directions).

Probe-request scenarios run against REAL server subprocesses and never
import jax (the server is up in ~1s), so the tier-1 slice stays cheap;
the one jitted-path test (`test_warm_serving_zero_compiles`) uses a
minimal 1-cell simulate request in-process.

Reference counterpart: none — the reference runs one configuration per
cold process and has no serving surface (`src/blades/simulator.py`).
"""

import glob
import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from blades_tpu.service.client import ServiceClient, ServiceError  # noqa: E402
from blades_tpu.service.protocol import (  # noqa: E402
    mint_request_id,
    socket_path_for,
)
from blades_tpu.service.spool import RequestSpool  # noqa: E402
from blades_tpu.telemetry.schema import validate_trace  # noqa: E402

CHAOS = os.path.join(REPO, "scripts", "chaos.py")
SERVE = os.path.join(REPO, "scripts", "serve.py")

_spec = importlib.util.spec_from_file_location("chaos_for_service", CHAOS)
chaos = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(chaos)


def _start(tmp_path, name, *extra, env=None):
    out = str(tmp_path / name)
    e = dict(os.environ, BLADES_LEDGER=str(tmp_path / f"{name}_ledger.jsonl"))
    e.update(env or {})
    proc = subprocess.Popen(
        [sys.executable, SERVE, "start", "--out", out,
         "--base-delay", "0.05", *extra],
        env=e, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    client = ServiceClient(
        socket_path_for(out), timeout=60,
        connect_retries=50, connect_delay_s=0.2,
    )
    return out, proc, client


def _finish(proc, client):
    try:
        if proc.poll() is None:
            client.drain()
    except ServiceError:
        pass
    out, err = proc.communicate(timeout=60)
    return proc.returncode, out, err


# -- spool --------------------------------------------------------------------


def test_spool_roundtrip_pending_and_fresh_truncation(tmp_path):
    path = str(tmp_path / "spool.jsonl")
    s = RequestSpool(path)
    r1 = s.admit({"kind": "probe", "cells": [{"op": "ok"}]})
    r2 = s.admit({"kind": "probe", "cells": [{"op": "ok"}]}, request_id="my-id")
    assert r2 == "my-id"
    s.complete(r1, {"ok": True, "id": r1})
    s.close()

    # resume recovers: r1 done (reply fetchable), r2 pending in order
    r = RequestSpool(path, resume=True)
    assert r.resumed
    assert r.reply(r1) == {"ok": True, "id": r1}
    assert r.reply(r2) is None
    assert [rid for rid, _ in r.pending()] == [r2]
    assert r.counts() == {"admitted": 2, "done": 1, "pending": 1}
    r.close()

    # a fresh (non-resume) start truncates: old requests belong to the
    # previous service lifetime
    f = RequestSpool(path)
    assert not f.resumed and not f.has(r1) and len(f) == 0
    f.close()


def test_spool_tolerates_torn_tail(tmp_path):
    path = str(tmp_path / "spool.jsonl")
    s = RequestSpool(path)
    rid = s.admit({"kind": "probe", "cells": [{"op": "ok"}]})
    s.close()
    with open(path, "a") as f:
        f.write('{"kind": "done", "id": "x", "reply": {"tr')  # torn
    r = RequestSpool(path, resume=True)
    assert r.resumed and r.has(rid) and r.reply("x") is None
    r.close()


# -- concurrent-append safety (journal + ledger) -------------------------------

# a record payload comfortably larger than the default stdio buffer:
# a buffered writer WOULD split it across write(2) calls, so two
# concurrent writers interleaving would tear neighbors' lines — the
# O_APPEND single-write discipline must keep every line whole
_BIG = 9000


def _parse_all_lines(path):
    whole, torn = [], 0
    with open(path) as fh:
        for line in fh:
            try:
                whole.append(json.loads(line))
            except ValueError:
                torn += 1
    return whole, torn


def test_interleaved_journal_writers(tmp_path):
    """Two processes appending large cells to ONE journal concurrently:
    every line stays whole (no interleaved/torn lines), every record
    lands."""
    path = str(tmp_path / "j.jsonl")
    from blades_tpu.sweeps.journal import SweepJournal

    SweepJournal(path, fingerprint="fp").close()  # meta line, then writers
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "from blades_tpu.sweeps.journal import SweepJournal\n"
        "j = SweepJournal(%r, fingerprint='fp', resume=True)\n"
        "for i in range(40):\n"
        "    j.record('%%s-%%03d' %% (sys.argv[1], i), {'pad': 'x' * %d})\n"
        "j.close()\n"
    ) % (REPO, path, _BIG)
    procs = [
        subprocess.Popen([sys.executable, "-c", code, tag], cwd=REPO)
        for tag in ("a", "b")
    ]
    for p in procs:
        assert p.wait(timeout=120) == 0
    records, torn = _parse_all_lines(path)
    assert torn == 0
    cells = {r["cell"] for r in records if r.get("kind") == "cell"}
    assert len(cells) == 80
    assert all(len(r.get("result", {}).get("pad", "")) == _BIG
               for r in records if r.get("kind") == "cell")


def test_interleaved_ledger_writers(tmp_path):
    """Two processes appending large ledger records concurrently: no torn
    lines, all records land (the supervisor-vs-child and service-vs-
    supervisor append races)."""
    path = str(tmp_path / "ledger.jsonl")
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "from blades_tpu.telemetry import ledger\n"
        "for i in range(40):\n"
        "    ledger.record_event('race', 'killed', run_id='%%s-%%03d'\n"
        "                        %% (sys.argv[1], i), path=%r,\n"
        "                        error='x' * %d)\n"
    ) % (REPO, path, _BIG)
    procs = [
        subprocess.Popen([sys.executable, "-c", code, tag], cwd=REPO)
        for tag in ("a", "b")
    ]
    for p in procs:
        assert p.wait(timeout=120) == 0
    records, torn = _parse_all_lines(path)
    assert torn == 0
    assert len({r["run_id"] for r in records}) == 80


# -- request-level fault isolation (real server subprocesses, probe-only) ------


def test_service_chaos_reduced(tmp_path):
    """The reduced chaos service slice against real servers: poison
    request quarantined (attributable error) while siblings and a
    concurrent request complete; backpressure rejects with an explicit
    reply; a hung cell trips the deadline without wedging the server;
    drain exits 0 with zero lost requests; a flooding tenant is contained
    by its quota (every reject tenant-attributed, the victim untouched);
    a preempted batch request resumes to a reply content-identical to an
    uninterrupted run; a worker-process crash mid-cell is contained (the
    replacement executes only the unjournaled cells, reply
    content-identical); a hung worker is parent-killed within the
    deadline ladder and its request completes on the replacement."""
    summary = chaos.service_chaos(str(tmp_path), full=False)
    assert summary["ok"], json.dumps(summary, indent=1)
    assert [s["name"] for s in summary["scenarios"]] == [
        "poison_isolated", "backpressure", "deadline_hang", "drain_no_loss",
        "tenant_flood", "preempt_resume", "worker_crash", "worker_hang",
    ]


def test_sigkill_resume_content_identical(tmp_path):
    """The acceptance e2e: SIGKILL the supervised server mid-request (the
    journal saboteur fires after the 2nd journaled cell), relaunch under
    BLADES_RESUME=1 replays the spool, executes ONLY the remaining
    cells, and the client-visible reply is content-identical to an
    uninterrupted run's."""
    row = chaos._scn_sigkill_resume(str(tmp_path))
    assert row["ok"], json.dumps(row)
    assert row["supervisor_rc"] == 0
    assert row["content_identical"]
    assert row["resumed_skipped"] == 2  # the 2 journaled cells, recovered
    assert row["executed"] == 2         # ONLY the remainder ran


def test_idempotent_resubmit_served_from_spool(tmp_path):
    """Submitting a completed request id again returns the spooled reply
    without re-executing (and a fresh id does execute)."""
    out, proc, client = _start(tmp_path, "svc")
    try:
        rid = mint_request_id()
        req = {"kind": "probe", "cells": [{"label": "a", "op": "ok",
                                           "value": 5}]}
        first = client.submit(req, request_id=rid)
        again = client.submit(req, request_id=rid)
        assert again["served"] == "spool"
        assert again["reply"]["cells"] == first["cells"]
        status = client.status()
        assert status["served"] == 1  # the resubmit executed nothing
    finally:
        rc, _, _ = _finish(proc, client)
    assert rc == 0


def test_trace_schema_and_health_surfaces(tmp_path):
    """One served+one quarantined request: the service trace validates
    against the committed schema, `sweep_status` reports the service
    block, and `runs.py --run-id` reports service_health from the
    ledger's registered artifacts."""
    out, proc, client = _start(tmp_path, "svc")
    ledger = str(tmp_path / "svc_ledger.jsonl")
    try:
        client.submit({"kind": "probe",
                       "cells": [{"label": "a", "op": "ok"}]})
        client.submit({"kind": "probe",
                       "cells": [{"label": "b", "op": "fail"}]})
        run_id = client.ping()["run_id"]
    finally:
        rc, _, _ = _finish(proc, client)
    assert rc == 0

    trace = os.path.join(out, "service_trace.jsonl")
    assert validate_trace(trace) == []

    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "sweep_status.py"),
         out],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    payload = json.loads(p.stdout)
    assert payload["ok"] and p.returncode == 0
    svc = payload["service"]
    assert svc["served"] == 2 and svc["quarantined_requests"] == 1
    assert svc["requests"]["admitted"] == 2
    assert svc["requests"]["pending"] == 0
    assert svc["requests"]["by_outcome"] == {"ok": 1, "quarantined": 1}
    # the per-cell accounting rides ordinary sweep records
    assert payload["sweeps"]["service"]["cells"] == 2
    # request-path metrics from the trace's metrics_snapshot records:
    # probe requests classify warm, the split is live
    assert svc["warm_requests"] == 2
    assert svc["warm_p99_s"] is not None
    assert 0.0 <= svc["queue_wait_share"] <= 1.0

    # trace_summary's service section reads the same trace
    import trace_summary
    s = trace_summary.summarize(trace_summary.load_records(trace))
    assert s["service"]["requests_finished"] == 2
    assert s["service"]["warm_requests"] == 2
    assert s["service"]["warm_p99_s"] is not None
    assert "queue_wait_share" in s["service"]
    assert s["service"]["served"] == 2
    # and the section renders (table + compare paths stay exception-free)
    assert "service:" in trace_summary.format_table(s)
    assert "service warm p99" in trace_summary.compare_format(s, s)

    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "runs.py"),
         "--run-id", run_id, "--ledger", ledger],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    payload = json.loads(p.stdout)
    assert payload["ok"] and payload["found"]
    health = payload["service_health"]
    assert health["served"] == 2
    assert health["requests"]["finished"] == 2
    # per-request ledger entries under the inherited run id
    kinds = {r["kind"] for r in payload["attempts"]}
    assert {"service", "request"} <= kinds


def test_unsafe_request_ids_and_labels_rejected(tmp_path):
    """Request ids and cell labels become filesystem path segments (the
    per-request journal dir, each simulate cell's log dir — which the
    Simulator WIPES at construction), so a '/'-carrying or absolute
    value must be rejected at the door, never spooled or executed."""
    from blades_tpu.service.handlers import build_cells, safe_name

    for bad in ("/root/repo/results", "../escape", "a/b", "", ".hidden"):
        with pytest.raises(ValueError):
            safe_name(bad, "request id")
        with pytest.raises(ValueError):
            build_cells({"kind": "probe", "cells": [{"label": bad or "x/y",
                                                     "op": "ok"}]})
    assert safe_name("req-20260805T0-abc123", "request id")

    out, proc, client = _start(tmp_path, "svc")
    try:
        reply = client.submit(
            {"kind": "probe", "cells": [{"label": "a", "op": "ok"}]},
            request_id="../../escape",
        )
        assert reply["ok"] is False and "safe name" in reply["error"]
        # never admitted: nothing spooled, nothing executed
        assert client.status()["served"] == 0
        assert not (tmp_path / "escape").exists()
    finally:
        rc, _, _ = _finish(proc, client)
    assert rc == 0


def test_status_reports_in_flight_id_and_age(tmp_path):
    """`op: status` carries the in-flight request's id and age, not a
    bare 0/1 — a wedged request is attributable from the health surface
    alone."""
    import time as _time

    out, proc, client = _start(tmp_path, "svc")
    try:
        busy = client.submit(
            {"kind": "probe",
             "cells": [{"label": "s", "op": "sleep", "sleep_s": 2.0}]},
            wait=False,
        )
        _time.sleep(0.5)  # let the worker pick the sleeper up
        status = client.status()
        assert status["in_flight"] == 1
        assert status["in_flight_id"] == busy["id"]
        assert status["in_flight_age_s"] >= 0.0
        client.wait_result(busy["id"], timeout=30)
        idle = client.status()
        assert idle["in_flight"] == 0 and "in_flight_id" not in idle
    finally:
        rc, _, _ = _finish(proc, client)
    assert rc == 0


def test_op_metrics_live_and_cli_one_line(tmp_path):
    """`op: metrics` against a live server: request counters match what
    was served, the split tiles per-request totals, probe requests
    classify warm (no jax, no compiles) — and the `serve.py metrics`
    subcommand keeps the one-JSON-line contract against both a live and
    an unreachable socket."""
    out, proc, client = _start(tmp_path, "svc")
    try:
        client.submit({"kind": "probe", "client": "tenant-a",
                       "cells": [{"label": "a", "op": "ok"}]})
        client.submit({"kind": "probe",
                       "cells": [{"label": "b", "op": "fail"}]})
        m = client.metrics()
        assert m["ok"]
        assert m["requests"]["served"] == 2
        assert m["requests"]["quarantined"] == 1
        assert m["requests"]["warm"] == 2  # probe cells never compile
        assert m["cells"]["quarantined"] == 1
        assert m["by_client"]["tenant-a"]["served"] == 1
        assert m["by_op"]["probe"]["admitted"] == 2
        split = m["split"]
        assert split["total_s"] > 0
        assert abs(
            split["queue_wait_s"] + split["build_s"] + split["execute_s"]
            - split["total_s"]
        ) < 1e-4
        assert m["latency"]["warm"]["count"] == 2
        assert m["latency"]["warm"]["p99_s"] is not None

        p = subprocess.run(
            [sys.executable, SERVE, "metrics",
             "--socket", socket_path_for(out)],
            capture_output=True, text=True, cwd=REPO, timeout=120,
        )
        lines = [l for l in p.stdout.splitlines() if l.strip()]
        assert len(lines) == 1 and p.returncode == 0
        payload = json.loads(lines[0])
        assert payload["metric"] == "service_metrics"
        assert payload["requests"]["served"] == 2
    finally:
        rc, _, _ = _finish(proc, client)
    assert rc == 0

    p = subprocess.run(
        [sys.executable, SERVE, "metrics",
         "--socket", str(tmp_path / "nope.sock"), "--timeout", "5"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    lines = [l for l in p.stdout.splitlines() if l.strip()]
    assert len(lines) == 1 and p.returncode != 0
    assert json.loads(lines[0])["ok"] is False


def test_unsafe_client_label_rejected(tmp_path):
    """The tenant label keys the per-client metrics tables (and may
    become a path segment under per-tenant scheduling): an unsafe one is
    rejected at the door like an unsafe id."""
    out, proc, client = _start(tmp_path, "svc")
    try:
        reply = client.submit(
            {"kind": "probe", "client": "../escape",
             "cells": [{"label": "a", "op": "ok"}]},
        )
        assert reply["ok"] is False and "safe name" in reply["error"]
        assert client.status()["served"] == 0
    finally:
        rc, _, _ = _finish(proc, client)
    assert rc == 0


# -- multi-tenant scheduling & deadline-aware admission (PR 17) ----------------


def test_admission_cold_start_admits_then_infeasible_rejected(tmp_path):
    """The admission estimator's failure modes, e2e: a fresh server has
    NO history, so a deadline-carrying request is admitted under the
    `no_estimate` verdict (cold start must admit — the estimator is
    advisory); once history exists, an unmeetable deadline is rejected
    `deadline_infeasible` BEFORE spooling (the id never enters the spool
    and can be reused), and a feasible one admits as `estimated`."""
    out, proc, client = _start(tmp_path, "svc")
    try:
        req = {"kind": "probe", "cells": [{"label": "a", "op": "ok",
                                           "value": 1}]}
        # cold start: no completed cells -> no estimate -> admitted
        first = client.submit(req, deadline_s=1e-9)
        assert first["ok"], first

        # with history, an impossible deadline is rejected pre-spool
        rid = mint_request_id()
        rej = client.submit(req, request_id=rid, deadline_s=1e-9)
        assert rej["ok"] is False
        assert rej["rejected"] == "deadline_infeasible"
        est = rej["est"]
        assert est["eta_s"] > est["deadline_s"] == 1e-9
        assert est["cells"] == 1 and est["est_s"] >= 0.0
        spooled = open(os.path.join(out, "spool.jsonl")).read()
        assert rid not in spooled  # rejected before admission, not after
        assert client.status()["served"] == 1

        # the same id resubmitted with a sane deadline executes normally
        # (nothing about the rejection was persisted)
        ok = client.submit(req, request_id=rid, deadline_s=60.0)
        assert ok["ok"] and ok["cells"][0]["result"]["value"] == 1

        m = client.metrics()
        assert m["sched"]["admission"] == {
            "no_estimate": 1, "infeasible": 1, "estimated": 1,
        }
        assert m["rejected_by_reason"]["deadline_infeasible"] == 1
    finally:
        rc, _, _ = _finish(proc, client)
    assert rc == 0


def test_wrong_estimate_bounded_by_cell_deadline_ladder(tmp_path):
    """A WRONG admission estimate (history says cells are instant, the
    request actually hangs) must not wedge the server: the estimator
    admits, and the PR 13 per-cell deadline ladder — the hard layer —
    quarantines the hung cell with an attributable error."""
    out, proc, client = _start(
        tmp_path, "svc", "--cell-deadline", "0.3", "--attempts", "1",
    )
    try:
        # history: one instant cell -> warm_cell_s is microseconds
        client.submit({"kind": "probe",
                       "cells": [{"label": "fast", "op": "ok"}]})
        # estimator predicts ~0s, so a 20s deadline admits `estimated` —
        # but the cell sleeps 60s: the estimate is wrong by 5 orders
        reply = client.submit(
            {"kind": "probe",
             "cells": [{"label": "hang", "op": "sleep", "sleep_s": 60}]},
            deadline_s=20.0, timeout=60,
        )
        assert reply["status"] == "done"
        cell = reply["cells"][0]
        assert cell["quarantined"]
        assert cell["error_type"] == "DeadlineExceeded"
        m = client.metrics()
        assert m["sched"]["admission"].get("estimated") == 1
        # the server is still serving after the bad estimate
        assert client.submit({"kind": "probe", "cells": [
            {"label": "alive", "op": "ok"}]})["ok"]
    finally:
        rc, _, _ = _finish(proc, client)
    assert rc == 0


def test_status_reports_tenant_composition_and_queue_by_class(tmp_path):
    """`op: status` surfaces the per-tenant queue composition and the
    per-class depths while requests are queued: a starved tenant (and a
    backed-up class) is attributable from the health surface alone."""
    import time as _time

    out, proc, client = _start(tmp_path, "svc")
    try:
        busy = client.submit(
            {"kind": "probe", "client": "miner", "priority": "batch",
             "cells": [{"label": "s", "op": "sleep", "sleep_s": 2.0}]},
            wait=False,
        )
        _time.sleep(0.4)  # let the worker pick the sleeper up
        q1 = client.submit(
            {"kind": "probe", "client": "alice", "priority": "interactive",
             "cells": [{"label": "a", "op": "ok"}]},
            wait=False,
        )
        q2 = client.submit(
            {"kind": "probe", "client": "bob",
             "cells": [{"label": "b", "op": "ok"}]},
            wait=False,
        )
        status = client.status()
        assert status["queue_by_class"]["interactive"] == 1
        assert status["queue_by_class"]["normal"] == 1
        assert status["queue_by_class"]["batch"] == 0
        tenants = status["tenants"]
        assert tenants["alice"]["depth"] == 1
        assert tenants["alice"]["priority"] == "interactive"
        assert tenants["bob"]["depth"] == 1
        assert tenants["alice"]["oldest_age_s"] >= 0.0
        assert status["preemptions"] == 0  # single-cell sleeper: no yield
        for r in (busy, q1, q2):
            assert client.wait_result(r["id"], timeout=30)["ok"]
        idle = client.status()
        assert idle["tenants"] == {} or "tenants" not in idle
    finally:
        rc, _, _ = _finish(proc, client)
    assert rc == 0


def test_summarize_service_sched_and_tenant_fields():
    """The sweep_status service block surfaces the scheduler rollup from
    the latest metrics_snapshot (preemptions, admission verdicts,
    per-class depth HWM) and the per-tenant composition from the newest
    health record."""
    import sweep_status

    records = [
        {"t": "service", "event": "health", "ts": 100.0, "served": 3,
         "queue_depth": 2, "queue_by_class": {"interactive": 1,
                                              "normal": 1, "batch": 0},
         "tenants": {"flood": {"depth": 2, "oldest_age_s": 1.5,
                               "priority": "normal"}},
         "preemptions": 2},
        {"t": "metrics_snapshot", "ts": 101.0, "uptime_s": 52.0,
         "requests": {"warm": 4}, "queue": {"depth_hwm": 6},
         "latency": {"warm": {"count": 4, "p99_s": 0.5}},
         "split": {"queue_wait_share": 0.4},
         "sched": {"preemptions": 2,
                   "admission": {"estimated": 3, "infeasible": 1},
                   "queue_depth_by_class_hwm": {"interactive": 1,
                                                "normal": 2, "batch": 0}}},
    ]
    out = sweep_status.summarize_service(records, now=120.0)
    assert out["queue_by_class"] == {"interactive": 1, "normal": 1,
                                     "batch": 0}
    assert out["tenants"]["flood"]["depth"] == 2
    assert out["preemptions"] == 2
    assert out["sched"]["preemptions"] == 2
    assert out["sched"]["admission"] == {"estimated": 3, "infeasible": 1}
    assert out["sched"]["queue_depth_by_class_hwm"]["normal"] == 2
    # a pre-scheduler trace (no sched block) keeps the old shape
    legacy = sweep_status.summarize_service(
        [{"t": "service", "event": "health", "ts": 100.0, "served": 1}],
        now=120.0,
    )
    assert "sched" not in legacy and "tenants" not in legacy


def test_summarize_service_metrics_snapshot_fields():
    """The sweep_status service block surfaces the latest
    metrics_snapshot's headline numbers (queue-wait share, warm p99,
    depth high-water mark) plus the in-flight id/age from the newest
    health record."""
    import sweep_status

    records = [
        {"t": "service", "event": "health", "ts": 100.0, "served": 1,
         "queue_depth": 2, "in_flight": 1, "in_flight_id": "req-x",
         "in_flight_age_s": 4.2},
        {"t": "metrics_snapshot", "ts": 99.0, "uptime_s": 50.0,
         "requests": {"warm": 3}, "queue": {"depth_hwm": 5},
         "latency": {"warm": {"count": 3, "p99_s": 0.2}},
         "split": {"queue_wait_share": 0.25}},
        {"t": "metrics_snapshot", "ts": 101.0, "uptime_s": 52.0,
         "requests": {"warm": 4}, "queue": {"depth_hwm": 6},
         "latency": {"warm": {"count": 4, "p99_s": 0.5}},
         "split": {"queue_wait_share": 0.4}},
    ]
    out = sweep_status.summarize_service(records, now=120.0)
    assert out["in_flight_id"] == "req-x"
    assert out["in_flight_age_s"] == 4.2
    # the LAST snapshot stands
    assert out["queue_wait_share"] == 0.4
    assert out["warm_p99_s"] == 0.5 and out["warm_requests"] == 4
    assert out["queue_depth_hwm"] == 6
    # metrics_snapshot records count toward trace liveness
    assert out["last_event_ts"] == 101.0


def test_summarize_service_pending_age_trend():
    """A wedged server's oldest-pending age GROWS across health records;
    a draining one's shrinks — the trend field carries the sign."""
    import sweep_status

    def recs(ages):
        return [
            {"t": "service", "event": "health", "ts": 100.0 + i,
             "served": 0, "oldest_pending_age_s": a}
            for i, a in enumerate(ages)
        ]

    wedged = sweep_status.summarize_service(recs([10.0, 40.0]), now=200.0)
    assert wedged["pending_age_trend_s"] == 30.0
    draining = sweep_status.summarize_service(recs([40.0, 5.0]), now=200.0)
    assert draining["pending_age_trend_s"] == -35.0
    single = sweep_status.summarize_service(recs([10.0]), now=200.0)
    assert "pending_age_trend_s" not in single
    # an idle server whose NEWEST snapshot omits the age must not
    # resurrect a stale trend from the busy past (same last-snapshot-
    # stands discipline as oldest_pending_age_s itself)
    idle = sweep_status.summarize_service(
        recs([10.0, 40.0])
        + [{"t": "service", "event": "health", "ts": 110.0, "served": 2}],
        now=200.0,
    )
    assert "pending_age_trend_s" not in idle
    assert "oldest_pending_age_s" not in idle


def test_summarize_service_no_stale_pending_age():
    """An idle server whose LATEST health snapshot omits
    oldest_pending_age_s must not resurrect the value from an older,
    busier snapshot (per-field last-wins would) — the wedged-vs-idle
    signal depends on it."""
    import sweep_status

    records = [
        {"t": "service", "event": "health", "ts": 100.0, "served": 1,
         "queue_depth": 1, "oldest_pending_age_s": 42.0},
        {"t": "request", "event": "admitted", "id": "r1", "ts": 90.0},
        {"t": "request", "event": "finished", "id": "r1", "ts": 101.0,
         "outcome": "ok"},
        {"t": "service", "event": "health", "ts": 110.0, "served": 2,
         "queue_depth": 0},
    ]
    out = sweep_status.summarize_service(records, now=120.0)
    assert "oldest_pending_age_s" not in out
    assert out["served"] == 2 and out["queue_depth"] == 0
    assert out["requests"]["pending"] == 0


def test_summarize_service_pending_age_and_wedge_signal():
    """A wedged server — admitted request, no finish, stale records —
    surfaces a growing oldest-pending age from the request trail alone
    (no health record needed)."""
    import sweep_status

    now = 1000.0
    records = [
        {"t": "service", "event": "start", "ts": 900.0, "queue_depth": 0},
        {"t": "request", "event": "admitted", "id": "r1", "ts": 940.0},
        {"t": "request", "event": "started", "id": "r1", "ts": 941.0},
    ]
    out = sweep_status.summarize_service(records, now=now)
    assert out["requests"] == {"admitted": 1, "finished": 0, "pending": 1}
    assert out["oldest_pending_age_s"] == 60.0
    assert out["last_event_age_s"] == 59.0
    assert sweep_status.summarize_service(
        [{"t": "sweep", "sweep": "certify", "cell": "x", "wall_s": 1.0}]
    ) is None


def test_serve_cli_one_json_line_on_error(tmp_path):
    """The JSON001 contract end-to-end: an unreachable socket still
    yields exactly one parseable error line, rc != 0."""
    p = subprocess.run(
        [sys.executable, SERVE, "status",
         "--socket", str(tmp_path / "nope.sock"), "--timeout", "5"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    lines = [l for l in p.stdout.splitlines() if l.strip()]
    assert len(lines) == 1 and p.returncode != 0
    payload = json.loads(lines[0])
    assert payload["ok"] is False and "error" in payload


# -- warm serving (the one jitted-path test) -----------------------------------


def test_warm_serving_zero_compiles(tmp_path):
    """A repeated identical simulate request is served entirely from the
    warm EngineCache/dataset caches: zero new XLA compiles (the
    perf-gate pin, in-process form), bit-identical results — and the
    request-path accounting classifies the pair cold-then-warm with a
    split that tiles each request's wall."""
    from blades_tpu.service.server import SimulationService
    from blades_tpu.telemetry import programs as _programs
    from blades_tpu.telemetry import recorder as _trec

    svc = SimulationService(str(tmp_path / "svc"))
    req = {"kind": "simulate", "cells": [
        {"label": "m", "agg": "mean", "rounds": 1, "seed": 3,
         "train_size": 64, "test_size": 32},
    ]}
    first = svc._execute("r1", req)
    assert first["ok"], first
    before = _trec.process_counters()
    prov_before = len(_programs.events())
    second = svc._execute("r2", req)
    delta = _trec.process_counters().get("xla.compiles", 0) - before.get(
        "xla.compiles", 0)
    assert delta == 0
    # compile provenance (telemetry/programs.py): the warm repeat emits
    # ZERO cold-outcome program records — the in-process form of the
    # perf_report warm_program_builds pin (a tiny eager re-trace may
    # close as persistent-cache-hit; only a real compile is a violation)
    warm_builds = [
        e for e in _programs.events()[prov_before:]
        if e.get("outcome") == "cold"
    ]
    assert not warm_builds, warm_builds
    assert second["cells"] == first["cells"]
    assert svc._engine_cache.stats()["hits"] >= 1
    # warm/cold classification pinned on the zero-new-compiles fixture:
    # the first request paid compiles (cold), the repeat paid none (warm)
    m = svc.metrics.snapshot()
    assert m["requests"]["cold"] == 1 and m["requests"]["warm"] == 1
    assert m["latency"]["cold"]["count"] == 1
    assert m["latency"]["warm"]["count"] == 1
    split = m["split"]
    assert abs(
        split["queue_wait_s"] + split["build_s"] + split["execute_s"]
        - split["total_s"]
    ) < 1e-4
    assert split["build_s"] > 0  # the cold request's trace+compile
    # the finished request records carry the per-request split
    recs = [json.loads(l) for l in
            open(os.path.join(str(tmp_path / "svc"), "service_trace.jsonl"))
            if l.strip()]
    fin = {r["id"]: r for r in recs
           if r.get("t") == "request" and r.get("event") == "finished"}
    assert fin["r1"]["warm"] is False and fin["r1"]["compiles"] > 0
    assert fin["r2"]["warm"] is True and fin["r2"]["compiles"] == 0
    for r in fin.values():
        assert abs(
            r["queue_wait_s"] + r["build_s"] + r["execute_s"] - r["total_s"]
        ) < 1e-4
    # a health beat flushes the per-fingerprint cache stats; the hit
    # counter must match the engine_cache hit records the warm cells
    # emitted into their per-request Simulator traces exactly
    svc._health()
    recs = [json.loads(l) for l in
            open(os.path.join(str(tmp_path / "svc"), "service_trace.jsonl"))
            if l.strip()]
    cache_stats = [r for r in recs if r.get("t") == "cache_stats"]
    assert cache_stats, "health beat emitted no cache_stats record"
    hit_records = [
        json.loads(l)
        for p in glob.glob(os.path.join(
            str(tmp_path / "svc"), "requests", "*", "*", "telemetry.jsonl"))
        for l in open(p) if l.strip()
        and json.loads(l).get("t") == "engine_cache"
    ]
    assert cache_stats[-1]["hits"] == len(hit_records) == 1
    assert cache_stats[-1]["entries"] == 1
    (per_key,) = cache_stats[-1]["by_key"].values()
    assert per_key["hits"] == 1 and per_key["build_s"] is not None


# -- perf-gate guard (fire + pass directions) ----------------------------------


def test_check_warm_serving_directions():
    import perf_report

    thresholds = dict(perf_report.DEFAULT_THRESHOLDS)
    baseline = {
        "derived": {"service_warm_cell_s": 0.06},
        "rows": {"dispatch/cert_slice_batched": {
            "per_cell_overhead_s": 0.10}},
    }
    good = {"warm_compiles": 0, "warm_per_cell_overhead_s": 0.001,
            "warm_mean_cell_s": 0.06}
    assert perf_report.check_warm_serving(good, baseline, thresholds) == []

    # fire: compiles crept back in / overhead above the batched baseline /
    # per-cell wall grew past threshold / evidence missing
    bad = {"warm_compiles": 3, "warm_per_cell_overhead_s": 0.2,
           "warm_mean_cell_s": 0.2}
    msgs = perf_report.check_warm_serving(bad, baseline, thresholds)
    assert len(msgs) == 3
    assert any("XLA compiles" in m for m in msgs)
    assert any("batched-sweep baseline" in m for m in msgs)
    assert any("warm_mean_cell_s" in m for m in msgs)
    missing = perf_report.check_warm_serving(None, baseline, thresholds)
    assert missing and "evidence missing" in missing[0]
    # dormant before the baseline records the claim
    assert perf_report.check_warm_serving(bad, {"derived": {}},
                                          thresholds) == []


def test_check_warm_serving_p99_and_queue_wait_directions():
    """The serving-path SLO gates (PR 15), both directions: warm p99
    within service_p99_frac of baseline and queue-wait share within
    queue_wait_share_abs pass; a synthetic p99 regression / share creep
    / missing p99 evidence each fire; both gates stay dormant until the
    baseline records them."""
    import perf_report

    thresholds = dict(perf_report.DEFAULT_THRESHOLDS)
    baseline = {
        "derived": {
            "service_warm_cell_s": 0.06,
            "service_warm_p99_s": 0.2,
            "service_queue_wait_share": 0.0,
        },
        "rows": {},
    }
    good = {"warm_compiles": 0, "warm_mean_cell_s": 0.06,
            "warm_p99_s": 0.2, "queue_wait_share": 0.05}
    assert perf_report.check_warm_serving(good, baseline, thresholds) == []
    # at the threshold exactly: still passing (the gate fires on >)
    edge = dict(good, warm_p99_s=0.2 * thresholds["service_p99_frac"])
    assert perf_report.check_warm_serving(edge, baseline, thresholds) == []

    regressed = dict(good, warm_p99_s=5.0, queue_wait_share=0.6)
    msgs = perf_report.check_warm_serving(regressed, baseline, thresholds)
    assert len(msgs) == 2
    assert any("warm-request p99" in m for m in msgs)
    assert any("queue_wait_share" in m for m in msgs)

    # evidence regenerated by an old script (no p99 field): the armed
    # gate reports the hole instead of silently passing
    stale = {"warm_compiles": 0, "warm_mean_cell_s": 0.06}
    msgs = perf_report.check_warm_serving(stale, baseline, thresholds)
    assert any("p99 evidence missing" in m for m in msgs)

    # dormant: a baseline without the SLO keys never fires them
    old_baseline = {"derived": {"service_warm_cell_s": 0.06}, "rows": {}}
    assert perf_report.check_warm_serving(
        regressed, old_baseline, thresholds) == []


def test_check_contention_gate_directions():
    """The tenant-isolation gates (PR 17), both directions: a healthy
    contention ladder (victim p99 within victim_p99_frac, zero victim
    rejects, >= 1 flood reject, preempt-resume merged identical with
    exactly the remainder executed) passes; each regressed pin fires its
    own message; the gates stay dormant until the baseline records the
    victim's contended p99."""
    import perf_report

    thresholds = dict(perf_report.DEFAULT_THRESHOLDS)
    baseline = {
        "derived": {
            "service_warm_cell_s": 0.06,
            "service_victim_warm_p99_s": 0.5,
        },
        "rows": {},
    }
    good_cont = {
        "victim": {"p99_s": 0.5, "rejected": 0},
        "flood": {"rejected": 3},
        "preempt": {"merged_identical": True, "preemptions": 1,
                    "cells": 6, "resumed_skipped": 2,
                    "executed_after_resume": 4},
    }
    good = {"warm_compiles": 0, "warm_mean_cell_s": 0.06,
            "contention": good_cont}
    assert perf_report.check_warm_serving(good, baseline, thresholds) == []
    # exactly at the threshold: the gate fires on >
    edge = dict(good, contention=dict(
        good_cont, victim={"p99_s": 0.5 * thresholds["victim_p99_frac"],
                           "rejected": 0}))
    assert perf_report.check_warm_serving(edge, baseline, thresholds) == []

    # every pin regressed at once: each fires its own message
    bad = dict(good, contention={
        "victim": {"p99_s": 50.0, "rejected": 2},
        "flood": {"rejected": 0},
        "preempt": {"merged_identical": False, "preemptions": 0,
                    "cells": 6, "resumed_skipped": 2,
                    "executed_after_resume": 6},
    })
    msgs = perf_report.check_warm_serving(bad, baseline, thresholds)
    assert len(msgs) == 6
    assert any("victim-tenant warm p99 under contention" in m for m in msgs)
    assert any("victim tenant absorbed 2 backpressure" in m for m in msgs)
    assert any("flooding tenant absorbed 0" in m for m in msgs)
    assert any("NOT content-identical" in m for m in msgs)
    assert any("0 preemptions" in m for m in msgs)
    assert any("executed 6 cells != remainder 6 - 2" in m for m in msgs)

    # evidence regenerated without the contention ladder: the armed gate
    # reports the hole instead of silently passing
    stale = {"warm_compiles": 0, "warm_mean_cell_s": 0.06}
    msgs = perf_report.check_warm_serving(stale, baseline, thresholds)
    assert any("contention evidence missing" in m for m in msgs)
    hollow = dict(good, contention={"preempt": good_cont["preempt"],
                                    "flood": {"rejected": 3}})
    msgs = perf_report.check_warm_serving(hollow, baseline, thresholds)
    assert any("victim-tenant warm p99 missing" in m for m in msgs)

    # dormant before the baseline records the contended p99
    old_baseline = {"derived": {"service_warm_cell_s": 0.06}, "rows": {}}
    assert perf_report.check_warm_serving(bad, old_baseline,
                                          thresholds) == []


def test_committed_warm_serving_evidence_passes_gate():
    """The committed measurement (results/service/warm_serving.json) must
    satisfy the armed guard against the committed baseline."""
    import perf_report

    stats = perf_report.service_warm_stats(REPO)
    assert stats is not None and stats["ok"]
    baseline = json.load(open(
        os.path.join(REPO, "results", "perf_report", "baseline.json")))
    thresholds = dict(perf_report.DEFAULT_THRESHOLDS)
    thresholds.update(baseline.get("thresholds") or {})
    assert perf_report.check_warm_serving(stats, baseline, thresholds) == []
    assert baseline["derived"]["service_warm_cell_s"] == stats[
        "warm_mean_cell_s"]
    # the serving-path SLOs are armed: the committed baseline pins the
    # committed evidence's p99 and queue-wait share
    assert baseline["derived"]["service_warm_p99_s"] == stats["warm_p99_s"]
    assert baseline["derived"]["service_queue_wait_share"] == stats[
        "queue_wait_share"]
