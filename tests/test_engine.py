"""Round-engine tests: update semantics, attack wiring, optimizer modes,
sharding, and seeded convergence (SURVEY.md section 4 test strategy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.aggregators import AGGREGATORS, get_aggregator
from blades_tpu.attackers import get_attack
from blades_tpu.core import ClientOptSpec, RoundEngine, ServerOptSpec
from blades_tpu.core.engine import multistep_lr
from blades_tpu.datasets import Synthetic
from blades_tpu.ops.pytree import ravel
from blades_tpu.parallel.mesh import make_mesh, make_plan

K = 8


def _mlp_params(key, d_in=784, h=16, classes=10):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (d_in, h)) * 0.05,
        "b1": jnp.zeros(h),
        "w2": jax.random.normal(k2, (h, classes)) * 0.05,
        "b2": jnp.zeros(classes),
    }


def _logits(p, x):
    h = jax.nn.relu(x.reshape(x.shape[0], -1) @ p["w1"] + p["b1"])
    return h @ p["w2"] + p["b2"]


def _loss(p, x, y, key):
    lg = _logits(p, x)
    lp = jax.nn.log_softmax(lg)
    loss = -jnp.mean(jnp.sum(jax.nn.one_hot(y, lg.shape[-1]) * lp, -1))
    top1 = jnp.mean((jnp.argmax(lg, -1) == y).astype(jnp.float32))
    return loss, {"top1": top1}


@pytest.fixture(scope="module")
def ds():
    return Synthetic(
        num_clients=K, train_size=400, test_size=100, noise=0.3, cache=False
    ).get_dls()


@pytest.fixture(scope="module")
def params():
    return _mlp_params(jax.random.PRNGKey(0))


def _engine(params, **kw):
    defaults = dict(
        num_clients=K,
        num_byzantine=0,
        aggregator=get_aggregator("mean"),
        client_opt=ClientOptSpec(),
        server_opt=ServerOptSpec(),
        num_classes=10,
    )
    defaults.update(kw)
    return RoundEngine(_loss, _logits, params, **defaults)


def test_fedsgd_single_step_equals_sgd(params, ds):
    """With K clients on identical data, 1 local step, mean agg and plain
    SGD everywhere, the round must equal one global SGD step with client_lr
    * server_lr scaling: update = -client_lr * grad; server: p += server_lr
    * update (pseudo-gradient SGD)."""
    cx, cy = ds.sample_round(jax.random.PRNGKey(1), 1, 8)
    # make all clients see client 0's batch
    cx = jnp.tile(cx[:1], (K, 1, 1, 1, 1, 1))
    cy = jnp.tile(cy[:1], (K, 1, 1))
    eng = _engine(params)
    st = eng.init(params)
    st2, m = eng.run_round(st, cx, cy, 0.5, 1.0, jax.random.PRNGKey(2))

    x0, y0 = cx[0, 0], cy[0, 0]
    g = jax.grad(lambda p: _loss(p, x0, y0, None)[0])(params)
    expect = jax.tree_util.tree_map(lambda p, gg: p - 0.5 * gg, params, g)
    np.testing.assert_allclose(
        np.asarray(ravel(st2.params)), np.asarray(ravel(expect)), rtol=2e-4, atol=1e-6
    )


def test_update_is_param_delta(params, ds):
    cx, cy = ds.sample_round(jax.random.PRNGKey(1), 2, 8)
    eng = _engine(params)
    st = eng.init(params)
    p_before = ravel(st.params)
    st2, _ = eng.run_round(st, cx, cy, 0.1, 1.0, jax.random.PRNGKey(2))
    updates = eng.last_updates
    assert updates.shape == (K, p_before.shape[0])
    # mean aggregation + SGD server with lr=1: p_new = p_old + mean(updates)
    np.testing.assert_allclose(
        np.asarray(ravel(st2.params)),
        np.asarray(p_before + updates.mean(0)),
        rtol=2e-4,
        atol=1e-6,
    )


def test_byzantine_mask_is_first_f(params, ds):
    eng = _engine(params, num_byzantine=3)
    np.testing.assert_array_equal(
        np.asarray(eng.byz_mask), [True] * 3 + [False] * (K - 3)
    )


def test_attack_changes_only_byz_rows(params, ds):
    cx, cy = ds.sample_round(jax.random.PRNGKey(1), 1, 8)
    clean = _engine(params)
    st = clean.init(params)
    clean.run_round(st, cx, cy, 0.1, 1.0, jax.random.PRNGKey(2))
    honest_rows = np.asarray(clean.last_updates[3:])

    attacked = _engine(
        params, num_byzantine=3, attack=get_attack("ipm", epsilon=0.5)
    )
    st = attacked.init(params)
    attacked.run_round(st, cx, cy, 0.1, 1.0, jax.random.PRNGKey(2))
    np.testing.assert_allclose(
        np.asarray(attacked.last_updates[3:]), honest_rows, rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(attacked.last_updates[:3]),
        np.tile(-0.5 * honest_rows.mean(0), (3, 1)),
        rtol=1e-4,
        atol=1e-7,
    )


def test_labelflipping_degrades_byz_loss_not_honest(params, ds):
    cx, cy = ds.sample_round(jax.random.PRNGKey(1), 1, 8)
    atk = _engine(
        params,
        num_byzantine=4,
        attack=get_attack("labelflipping", num_classes=10),
        aggregator=get_aggregator("median"),
    )
    st = atk.init(params)
    _, m = atk.run_round(st, cx, cy, 0.1, 1.0, jax.random.PRNGKey(2))
    # honest clients' updates unchanged vs clean run
    clean = _engine(params, aggregator=get_aggregator("median"))
    st2 = clean.init(params)
    clean.run_round(st2, cx, cy, 0.1, 1.0, jax.random.PRNGKey(2))
    np.testing.assert_allclose(
        np.asarray(atk.last_updates[4:]),
        np.asarray(clean.last_updates[4:]),
        rtol=1e-5,
    )
    # byzantine updates differ (they trained on flipped labels)
    assert not np.allclose(atk.last_updates[:4], clean.last_updates[:4])


def test_persistent_adam_state_evolves(params, ds):
    eng = _engine(params, client_opt=ClientOptSpec(name="adam", persist=True))
    st = eng.init(params)
    nu0 = jax.tree_util.tree_leaves(st.client_opt_state)[0].copy()
    cx, cy = ds.sample_round(jax.random.PRNGKey(1), 2, 8)
    st, _ = eng.run_round(st, cx, cy, 1e-3, 1.0, jax.random.PRNGKey(2))
    nu1 = jax.tree_util.tree_leaves(st.client_opt_state)[0]
    assert nu1.shape[0] == K  # stacked per-client
    assert not np.allclose(nu0, nu1)


def test_momentum_sgd_differs_from_plain(params, ds):
    cx, cy = ds.sample_round(jax.random.PRNGKey(1), 3, 8)
    plain = _engine(params)
    st = plain.init(params)
    st_p, _ = plain.run_round(st, cx, cy, 0.1, 1.0, jax.random.PRNGKey(2))
    mom = _engine(params, client_opt=ClientOptSpec(momentum=0.9))
    st = mom.init(params)
    st_m, _ = mom.run_round(st, cx, cy, 0.1, 1.0, jax.random.PRNGKey(2))
    assert not np.allclose(ravel(st_p.params), ravel(st_m.params))


def test_round_deterministic(params, ds):
    cx, cy = ds.sample_round(jax.random.PRNGKey(1), 2, 8)
    eng = _engine(params, num_byzantine=2, attack=get_attack("noise"))
    s1, _ = eng.run_round(eng.init(params), cx, cy, 0.1, 1.0, jax.random.PRNGKey(9))
    s2, _ = eng.run_round(eng.init(params), cx, cy, 0.1, 1.0, jax.random.PRNGKey(9))
    np.testing.assert_array_equal(np.asarray(ravel(s1.params)), np.asarray(ravel(s2.params)))


@pytest.mark.parametrize(
    "agg,attack",
    [
        ("trimmedmean", None),
        ("trimmedmean", "alie"),  # cross-client omniscient stats sharded
        ("clippedclustering", None),
        ("dnc", None),
        ("geomed", None),
        ("krum", None),
        ("signguard", None),
    ],
)
def test_sharded_matches_unsharded(params, ds, agg, attack):
    """Sharding must not change the round's result — across the full
    defense family (selection, clustering, spectral, sign-statistics) and
    with a cross-client omniscient attack in-graph. This is the invariant
    that makes single-device matrix artifacts comparable to mesh runs
    (docs/convergence.md)."""
    cx, cy = ds.sample_round(jax.random.PRNGKey(1), 1, 8)
    plan = make_plan(make_mesh())  # 8 CPU devices from conftest
    agg_kws = {"num_byzantine": 2} if agg in ("krum", "trimmedmean", "dnc") else {}
    atk_kws = {"num_clients": K, "num_byzantine": 3} if attack == "alie" else {}
    kw = dict(
        aggregator=get_aggregator(agg, **agg_kws),
        attack=get_attack(attack, **atk_kws) if attack else None,
        num_byzantine=3 if attack else 0,
    )
    un = _engine(params, **kw)
    sh = _engine(params, plan=plan, **kw)
    s_un, m_un = un.run_round(un.init(params), cx, cy, 0.1, 1.0, jax.random.PRNGKey(2))
    s_sh, m_sh = sh.run_round(sh.init(params), cx, cy, 0.1, 1.0, jax.random.PRNGKey(2))
    np.testing.assert_allclose(
        np.asarray(ravel(s_un.params)), np.asarray(ravel(s_sh.params)), rtol=1e-5, atol=1e-7
    )


def test_sharded_2d_mesh_matches_unsharded(params, ds):
    """Regression: on a mesh with a >1 ``model`` axis (auto_mesh_shape picks
    one whenever gcd(devices, K) < devices), constraining the fresh [K, D]
    update matrix straight to P(clients, model) miscompiled under some XLA
    SPMD-partitioner versions — every row silently came out as
    ``update + ravel(params)`` and multi-round training collapsed the
    params to ~0. The engine therefore constrains the matrix along the
    clients axis ONLY (a two-hop P(clients)->P(clients, model) chain
    collapses to the same miscompiled program — do not "restore" the
    model-axis reshard); this pins single-round equality AND the two
    summary norms that exposed the bug."""
    cx, cy = ds.sample_round(jax.random.PRNGKey(1), 1, 8)
    plan = make_plan(make_mesh(jax.devices(), (2, 4)))  # model axis width 4
    un = _engine(params, keep_updates=True)
    sh = _engine(params, plan=plan, keep_updates=True)
    s_un, m_un = un.run_round(un.init(params), cx, cy, 0.1, 1.0, jax.random.PRNGKey(2))
    s_sh, m_sh = sh.run_round(sh.init(params), cx, cy, 0.1, 1.0, jax.random.PRNGKey(2))
    np.testing.assert_allclose(
        np.asarray(un.last_updates), np.asarray(sh.last_updates),
        rtol=1e-4, atol=1e-6,
    )
    np.testing.assert_allclose(
        float(m_un.agg_norm), float(m_sh.agg_norm), rtol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(ravel(s_un.params)), np.asarray(ravel(s_sh.params)),
        rtol=1e-5, atol=1e-7,
    )


def test_client_chunks_match_single_vmap(params, ds):
    cx, cy = ds.sample_round(jax.random.PRNGKey(1), 2, 8)
    whole = _engine(params)
    chunked = _engine(params, client_chunks=4, remat=True)
    s_w, _ = whole.run_round(whole.init(params), cx, cy, 0.1, 1.0, jax.random.PRNGKey(2))
    s_c, _ = chunked.run_round(chunked.init(params), cx, cy, 0.1, 1.0, jax.random.PRNGKey(2))
    np.testing.assert_allclose(
        np.asarray(ravel(s_w.params)), np.asarray(ravel(s_c.params)), rtol=1e-5, atol=1e-7
    )


def test_client_chunks_with_persistent_opt(params, ds):
    cx, cy = ds.sample_round(jax.random.PRNGKey(1), 1, 8)
    eng = _engine(
        params,
        client_chunks=2,
        client_opt=ClientOptSpec(name="adam", persist=True),
    )
    st = eng.init(params)
    st, m = eng.run_round(st, cx, cy, 1e-3, 1.0, jax.random.PRNGKey(2))
    assert jax.tree_util.tree_leaves(st.client_opt_state)[0].shape[0] == K
    assert np.isfinite(float(m.train_loss))


def test_seeded_convergence_under_alie(params):
    """Robust aggregation must learn under ALIE; the de-facto reference smoke
    test is mini_example.py (MNIST, 4/10 ALIE + mean); trimmedmean variant
    per BASELINE config 1."""
    ds = Synthetic(
        num_clients=10, train_size=1500, test_size=300, noise=0.2, cache=False, seed=3
    ).get_dls()
    eng = RoundEngine(
        _loss,
        _logits,
        params,
        num_clients=10,
        num_byzantine=4,
        attack=get_attack("alie", num_clients=10, num_byzantine=4),
        aggregator=get_aggregator("trimmedmean", num_byzantine=4),
        num_classes=10,
    )
    st = eng.init(params)
    key = jax.random.PRNGKey(11)
    for r in range(40):
        cx, cy = ds.sample_round(jax.random.fold_in(key, r), 2, 16)
        st, m = eng.run_round(st, cx, cy, 0.5, 1.0, key)
    ev = eng.evaluate(st, ds.test_x, ds.test_y, batch_size=64)
    assert ev["top1"] > 0.5, f"no learning under ALIE: {ev}"


def test_multistep_lr():
    lr = multistep_lr(0.1, milestones=(2, 4), gamma=0.5)
    assert lr(0) == 0.1 and lr(1) == 0.1
    assert lr(2) == pytest.approx(0.05)
    assert lr(4) == pytest.approx(0.025)


def test_eval_padded_tail(params, ds):
    eng = _engine(params)
    st = eng.init(params)
    ev = eng.evaluate(st, ds.test_x[:70], ds.test_y[:70], batch_size=32)
    assert 0.0 <= ev["top1"] <= 1.0
    assert np.isfinite(ev["Loss"])


def test_keep_updates_off_matches_and_drops_output():
    """keep_updates=False must produce the bit-identical round (same state,
    same metrics — the matrix is still consumed in-graph by aggregation)
    while last_updates becomes None instead of a [K, D] output buffer."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from blades_tpu.aggregators import get_aggregator
    from blades_tpu.attackers import get_attack
    from blades_tpu.core import RoundEngine

    def loss_fn(params, x, y, key):
        logits = x.reshape(x.shape[0], -1) @ params["w"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, y[:, None], axis=1).mean(), {}

    rng = np.random.RandomState(0)
    W0 = {"w": jnp.asarray(rng.randn(12, 4).astype(np.float32))}
    cx = jnp.asarray(rng.randn(6, 1, 8, 12).astype(np.float32))
    cy = jnp.asarray(rng.randint(0, 4, (6, 1, 8)).astype(np.int32))

    outs = {}
    for keep in (True, False):
        eng = RoundEngine(
            loss_fn, lambda p, x: x.reshape(x.shape[0], -1) @ p["w"], W0,
            num_clients=6, num_byzantine=2, attack=get_attack("ipm"),
            aggregator=get_aggregator("trimmedmean", num_byzantine=2),
            num_classes=4, keep_updates=keep,
        )
        state = eng.init(W0)
        state, m = eng.run_round(state, cx, cy, 0.1, 1.0, jax.random.PRNGKey(5))
        outs[keep] = (np.asarray(state.params["w"]), float(m.train_loss),
                      eng.last_updates)

    np.testing.assert_array_equal(outs[True][0], outs[False][0])
    assert outs[True][1] == outs[False][1]
    assert outs[True][2] is not None and outs[True][2].shape == (6, 48)
    assert outs[False][2] is None


def test_donate_batches_matches_and_consumes_inputs():
    """donate_batches=True: identical round results on fresh batches; a
    caller that reuses a donated batch buffer gets JAX's deleted-buffer
    error instead of silent corruption."""

    def loss_fn(params, x, y, key):
        logits = x.reshape(x.shape[0], -1) @ params["w"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, y[:, None], axis=1).mean(), {}

    rng = np.random.RandomState(1)
    W0 = {"w": jnp.asarray(rng.randn(10, 3).astype(np.float32))}
    cx_np = rng.randn(4, 1, 6, 10).astype(np.float32)
    cy_np = rng.randint(0, 3, (4, 1, 6)).astype(np.int32)

    def build(donate):
        eng = RoundEngine(
            loss_fn, lambda p, x: x.reshape(x.shape[0], -1) @ p["w"], W0,
            num_clients=4, aggregator=get_aggregator("mean"),
            num_classes=3, donate_batches=donate,
        )
        return eng, eng.init(W0)

    eng_d, st_d = build(True)
    cx, cy = jnp.asarray(cx_np), jnp.asarray(cy_np)
    st_d, m_d = eng_d.run_round(st_d, cx, cy, 0.1, 1.0, jax.random.PRNGKey(2))

    eng_p, st_p = build(False)
    st_p, m_p = eng_p.run_round(st_p, jnp.asarray(cx_np), jnp.asarray(cy_np),
                                0.1, 1.0, jax.random.PRNGKey(2))
    np.testing.assert_array_equal(np.asarray(st_d.params["w"]),
                                  np.asarray(st_p.params["w"]))
    assert float(m_d.train_loss) == float(m_p.train_loss)

    # on backends that honor donation (TPU), the donated buffers are
    # consumed and reuse raises; XLA:CPU ignores donation, so only assert
    # the strict behavior when the buffer was actually deleted
    if cx.is_deleted():
        with pytest.raises(RuntimeError, match="[Dd]elet|[Dd]onat"):
            eng_d.run_round(st_d, cx, cy, 0.1, 1.0, jax.random.PRNGKey(3))
    else:
        assert jax.default_backend() == "cpu"  # donation is a CPU no-op


# -- round-block execution (run_block: sampler fused + lax.scan) ---------------

BLOCK_K, BLOCK_F, BLOCK_C = 6, 12, 4


def _tiny_loss(p, x, y, key):
    logits = x.reshape(x.shape[0], -1) @ p["w"]
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
    top1 = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
    return loss, {"top1": top1}


def _tiny_logits(p, x):
    return x.reshape(x.shape[0], -1) @ p["w"]


def _tiny_fixture(seed=0):
    """Tiny FLDataset + linear params: registry-wide block tests stay
    compile-cheap (D = 48)."""
    from blades_tpu.datasets.fl import FLDataset

    rng = np.random.RandomState(seed)
    ds = FLDataset(
        rng.randn(BLOCK_K, 20, BLOCK_F).astype(np.float32),
        rng.randint(0, BLOCK_C, (BLOCK_K, 20)).astype(np.int32),
        np.full(BLOCK_K, 20, np.int32),
        rng.randn(30, BLOCK_F).astype(np.float32),
        rng.randint(0, BLOCK_C, 30).astype(np.int32),
    )
    W0 = {"w": jnp.asarray(rng.randn(BLOCK_F, BLOCK_C).astype(np.float32) * 0.1)}
    return ds, W0


def _block_vs_sequential(engine_kw, rounds=3, lrs=(0.2, 0.1, 0.05)):
    """Assert an R-round block is BIT-identical to R sequential run_round
    calls: params, round_idx, every metric column, and (when surfaces are
    installed) the final-round diagnostics."""
    from blades_tpu.core import RoundEngine

    ds, W0 = _tiny_fixture()
    key = jax.random.PRNGKey(7)
    dk = jax.random.fold_in(key, 23)
    S, B = 2, 4

    eng = RoundEngine(
        _tiny_loss, _tiny_logits, W0, num_clients=BLOCK_K,
        num_classes=BLOCK_C, **engine_kw,
    )
    st = eng.init(W0)
    seq_metrics = []
    for r in range(1, rounds + 1):
        cx, cy = ds.sample_round(jax.random.fold_in(dk, r), S, B)
        st, m = eng.run_round(st, cx, cy, lrs[r - 1], 1.0, key)
        seq_metrics.append(m)

    st2 = eng.init(W0)
    keys = jnp.stack([jax.random.fold_in(dk, r) for r in range(1, rounds + 1)])
    st2, ms, diags = eng.run_block(
        st2, keys, list(lrs[:rounds]), [1.0] * rounds, key,
        sampler=ds.traceable_sampler(S, B),
    )

    np.testing.assert_array_equal(
        np.asarray(ravel(st.params)), np.asarray(ravel(st2.params))
    )
    assert int(st.round_idx) == int(st2.round_idx) == rounds
    for i, m in enumerate(seq_metrics):
        for field, col in zip(m, ms):
            np.testing.assert_array_equal(np.asarray(field), np.asarray(col[i]))
    # carried aggregator/fault state must match bit-for-bit too (the scan
    # carry is the whole RoundState)
    for a, b in zip(
        jax.tree_util.tree_leaves(st), jax.tree_util.tree_leaves(st2)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    return eng, diags


@pytest.mark.parametrize("agg", sorted(AGGREGATORS))
def test_block_matches_sequential_across_registry(agg):
    """The load-bearing round-block invariant, across the FULL aggregator
    registry (stateful defenses included — centeredclipping's momentum,
    byzantinesgd's trajectory accumulators ride the scan carry): an R-round
    block is bit-identical to R sequential rounds, so blocks are purely a
    scheduling choice."""
    agg_kws = (
        {"num_byzantine": 2}
        if agg in ("trimmedmean", "krum", "multikrum", "dnc")
        else {}
    )
    kw = dict(
        aggregator=get_aggregator(agg, **agg_kws),
        num_byzantine=2,
        attack=get_attack("ipm", epsilon=0.5),
    )
    if agg == "fltrust":
        trusted = np.zeros(BLOCK_K, bool)
        trusted[-1] = True
        kw["trusted_mask"] = jnp.asarray(trusted)
    _block_vs_sequential(kw)


def test_block_matches_sequential_with_persisted_opt_faults_audit():
    """Composition case: persisted per-client Adam moments, a straggler
    fault model with a stale-replay buffer, and an enforced audit monitor
    with in-graph fallback — every carried surface at once, block vs
    sequential bit-exact, with the stacked per-round fault/audit
    diagnostics present."""
    from blades_tpu.audit.monitor import AuditMonitor
    from blades_tpu.faults import FaultModel

    kw = dict(
        aggregator=get_aggregator("median"),
        num_byzantine=2,
        attack=get_attack("signflipping"),
        client_opt=ClientOptSpec(name="adam", persist=True),
        fault_model=FaultModel(
            dropout_rate=0.3, straggler_rate=0.4, max_staleness=2,
            corrupt_rate=0.2, corrupt_mode="nan",
        ),
        audit_monitor=AuditMonitor(
            envelope_factor=1e-6, fallback_aggregator="median"
        ),  # degenerate envelope: breaches fire, fallback swaps in-graph
    )
    eng, diags = _block_vs_sequential(kw)
    assert diags["faults"] is not None and diags["audit"] is not None
    assert np.asarray(diags["faults"]["participants"]).shape == (3,)
    assert np.asarray(diags["audit"]["breach"]).sum() >= 1  # breaches fired


def test_block_compile_count_pinned():
    """A run schedules at most 2 block programs (full blocks + remainder):
    re-running both shapes must add ZERO backend compiles — pinned through
    the compile-counter telemetry, the same signal the driver gate reads."""
    from blades_tpu.core import RoundEngine
    from blades_tpu.telemetry import (
        Recorder,
        install_jax_monitoring,
        set_recorder,
    )

    ds, W0 = _tiny_fixture(seed=3)
    eng = RoundEngine(
        _tiny_loss, _tiny_logits, W0, num_clients=BLOCK_K,
        num_classes=BLOCK_C, aggregator=get_aggregator("mean"),
    )
    key = jax.random.PRNGKey(11)
    dk = jax.random.fold_in(key, 23)
    sampler = ds.traceable_sampler(1, 4)

    def run_block(st, first, r):
        keys = jnp.stack(
            [jax.random.fold_in(dk, x) for x in range(first, first + r)]
        )
        st, ms, _ = eng.run_block(
            st, keys, [0.1] * r, [1.0] * r, key, sampler=sampler
        )
        return st

    rec = Recorder(enabled=True)
    prev = set_recorder(rec)
    try:
        install_jax_monitoring()
        st = eng.init(W0)
        st = run_block(st, 1, 3)  # full block: compile 1
        st = run_block(st, 4, 2)  # remainder block: compile 2
        after_two_shapes = rec.counters.get("xla.compiles", 0)
        st = run_block(st, 6, 3)  # same shapes again: no new programs
        st = run_block(st, 9, 2)
        assert rec.counters.get("xla.compiles", 0) == after_two_shapes
    finally:
        set_recorder(prev)


def test_traceable_sampler_matches_sample_round():
    """The fused (in-graph) sampler and the standalone jitted sampler are
    the same function: identical draws for identical keys."""
    ds, _ = _tiny_fixture(seed=5)
    key = jax.random.PRNGKey(2)
    cx_a, cy_a = ds.sample_round(key, 2, 4)
    cx_b, cy_b = jax.jit(ds.traceable_sampler(2, 4))(key)
    np.testing.assert_array_equal(np.asarray(cx_a), np.asarray(cx_b))
    np.testing.assert_array_equal(np.asarray(cy_a), np.asarray(cy_b))


def test_warm_eval_builds_the_eval_executable():
    ds, W0 = _tiny_fixture()
    from blades_tpu.core import RoundEngine

    eng = RoundEngine(
        _tiny_loss, _tiny_logits, W0, num_clients=BLOCK_K,
        num_classes=BLOCK_C, aggregator=get_aggregator("mean"),
    )
    st = eng.init(W0)
    eng.warm_eval(st.params, ds.test_x, ds.test_y, batch_size=16)
    ev = eng.evaluate(st, ds.test_x, ds.test_y, batch_size=16)
    assert np.isfinite(ev["Loss"]) and 0.0 <= ev["top1"] <= 1.0
