"""Experiment-axis batch tests (blades_tpu/core/experiments.py): the
load-bearing invariant — an S-experiment batch is BIT-identical to S
sequential runs across the full aggregator registry, composes with
run_block (scan-of-batched-rounds), and the whole batch is ONE compiled
program (pinned via the telemetry compile counters)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.aggregators import AGGREGATORS, get_aggregator
from blades_tpu.attackers import get_attack
from blades_tpu.core import (
    ClientOptSpec,
    ExperimentBatch,
    RoundEngine,
    stack_experiments,
    unstack_experiments,
)
from blades_tpu.ops.pytree import ravel

EK, EF, EC = 6, 12, 4  # tiny linear fixture: registry-wide stays cheap


def _tiny_loss(p, x, y, key):
    logits = x.reshape(x.shape[0], -1) @ p["w"]
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
    top1 = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
    return loss, {"top1": top1}


def _tiny_logits(p, x):
    return x.reshape(x.shape[0], -1) @ p["w"]


def _tiny_fixture(seed=0):
    from blades_tpu.datasets.fl import FLDataset

    rng = np.random.RandomState(seed)
    ds = FLDataset(
        rng.randn(EK, 20, EF).astype(np.float32),
        rng.randint(0, EC, (EK, 20)).astype(np.int32),
        np.full(EK, 20, np.int32),
        rng.randn(30, EF).astype(np.float32),
        rng.randint(0, EC, 30).astype(np.int32),
    )
    W0 = {"w": jnp.asarray(rng.randn(EF, EC).astype(np.float32) * 0.1)}
    return ds, W0


def _engine(W0, **kw):
    defaults = dict(num_clients=EK, num_classes=EC)
    defaults.update(kw)
    return RoundEngine(_tiny_loss, _tiny_logits, W0, **defaults)


def _flat(params):
    return np.asarray(ravel(params))


def test_stack_unstack_roundtrip():
    trees = [{"a": jnp.arange(3) + i, "b": (jnp.ones(2) * i,)} for i in range(4)]
    stacked = stack_experiments(trees)
    assert stacked["a"].shape == (4, 3)
    back = unstack_experiments(stacked)
    for t, b in zip(trees, back):
        for x, y in zip(jax.tree_util.tree_leaves(t),
                        jax.tree_util.tree_leaves(b)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_batch_matches_sequential_across_registry():
    """The acceptance invariant: for EVERY registered aggregator (stateful
    ones included — their state rides the stacked RoundState), an
    S-experiment map-mode batch with per-experiment keys/lrs equals S
    isolated run_round calls bit-for-bit: params, every carried state
    leaf, every metric column."""
    ds, W0 = _tiny_fixture()
    S = 2
    key = jax.random.PRNGKey(7)
    keys = jax.random.split(key, S)
    lrs = jnp.asarray([0.2, 0.05], jnp.float32)
    slrs = jnp.ones(S, jnp.float32)
    cx, cy = ds.sample_round(jax.random.fold_in(key, 23), 2, 4)

    for name in sorted(AGGREGATORS):
        agg_kws = (
            {"num_byzantine": 2}
            if name in ("trimmedmean", "krum", "multikrum", "dnc")
            else {}
        )
        kw = dict(
            aggregator=get_aggregator(name, **agg_kws),
            num_byzantine=2,
            attack=get_attack("ipm", epsilon=0.5),
        )
        if name == "fltrust":
            trusted = np.zeros(EK, bool)
            trusted[-1] = True
            kw["trusted_mask"] = jnp.asarray(trusted)
        eng = _engine(W0, **kw)

        seq_states, seq_metrics = [], []
        for s in range(S):
            st = eng.init(W0)
            st, m = eng.run_round(st, cx, cy, float(lrs[s]), 1.0, keys[s])
            seq_states.append(st)
            seq_metrics.append(m)

        eb = ExperimentBatch(eng, S)
        states = eb.init_batch(W0)
        states, ms, _ = eb.run_round_batch(
            states, cx, cy, lrs, slrs, keys, shared_data=True
        )
        outs = unstack_experiments(states, S)
        for s in range(S):
            np.testing.assert_array_equal(
                _flat(seq_states[s].params), _flat(outs[s].params),
                err_msg=f"{name}: experiment {s} params diverged",
            )
            for a, b in zip(jax.tree_util.tree_leaves(seq_states[s]),
                            jax.tree_util.tree_leaves(outs[s])):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            for field, col in zip(seq_metrics[s], ms):
                np.testing.assert_array_equal(
                    np.asarray(field), np.asarray(col[s])
                )


def test_block_batch_matches_per_experiment_run_block():
    """Scan-of-batched-rounds: run_block_batch column s equals that
    experiment's own run_block (which is itself pinned bit-exact against
    sequential rounds) — batch x block composition is a pure scheduling
    choice."""
    ds, W0 = _tiny_fixture(seed=1)
    S, R = 3, 3
    eng = _engine(W0, aggregator=get_aggregator("median"), num_byzantine=2,
                  attack=get_attack("signflipping"))
    key = jax.random.PRNGKey(5)
    keys = jax.random.split(key, S)
    dk = jax.random.fold_in(key, 23)
    sample_keys = jnp.stack([
        jnp.stack([jax.random.fold_in(jax.random.fold_in(dk, r), s)
                   for s in range(S)])
        for r in range(R)
    ])
    lrs = jnp.full((R, S), 0.1, jnp.float32)
    sampler = ds.traceable_sampler(2, 4)

    seq = []
    for s in range(S):
        st = eng.init(W0)
        st, mm, _ = eng.run_block(
            st, sample_keys[:, s], [0.1] * R, [1.0] * R, keys[s],
            sampler=sampler,
        )
        seq.append((st, mm))

    eb = ExperimentBatch(eng, S)
    states = eb.init_batch(W0)
    states, ms, _ = eb.run_block_batch(
        states, sample_keys, lrs, jnp.ones((R, S), jnp.float32), keys,
        sampler=sampler,
    )
    outs = unstack_experiments(states, S)
    for s in range(S):
        np.testing.assert_array_equal(_flat(seq[s][0].params),
                                      _flat(outs[s].params))
        for field, col in zip(seq[s][1], ms):
            np.testing.assert_array_equal(
                np.asarray(field), np.asarray(col[:, s])
            )


def test_batch_is_one_program_compile_pinned():
    """The amortization contract: the S-experiment batch compiles ONE
    program (vs S sequential programs it replaces), and a same-shape
    recall adds ZERO compiles — the telemetry counters are the same
    signal the Tier-B audit and the driver gate read."""
    from blades_tpu.telemetry import (
        Recorder,
        get_recorder,
        install_jax_monitoring,
        set_recorder,
    )

    ds, W0 = _tiny_fixture(seed=2)
    S = 3
    eng = _engine(W0, aggregator=get_aggregator("mean"))
    eb = ExperimentBatch(eng, S)
    key = jax.random.PRNGKey(3)
    keys = jax.random.split(key, S)
    lrs = jnp.full((S,), 0.1, jnp.float32)
    cx, cy = ds.sample_round(jax.random.fold_in(key, 1), 1, 4)

    install_jax_monitoring()
    prev = get_recorder()
    rec = Recorder(path=None, enabled=True)
    set_recorder(rec)
    try:
        def compiles():
            return rec.counters.get("xla.compiles", 0)

        before = compiles()
        states = eb.init_batch(W0)
        states, _, _ = eb.run_round_batch(
            states, cx, cy, lrs, jnp.ones(S, jnp.float32), keys,
            shared_data=True,
        )
        jax.block_until_ready(states.params)
        first = compiles() - before
        assert first >= 1  # the one batched program build

        before = compiles()
        states, _, _ = eb.run_round_batch(
            states, cx, cy, lrs, jnp.ones(S, jnp.float32), keys,
            shared_data=True,
        )
        jax.block_until_ready(states.params)
        assert compiles() - before == 0  # warm recall: zero compiles
        assert eb._round_jits[True]._cache_size() == 1
    finally:
        set_recorder(prev)


def test_vmap_mode_allclose_and_one_program():
    """The vmap schedule is numerically equivalent (NOT bit-identical —
    batched training matmuls reassociate; measured on this backend) and
    still one program per batch."""
    ds, W0 = _tiny_fixture(seed=3)
    S = 2
    eng = _engine(W0, aggregator=get_aggregator("mean"))
    key = jax.random.PRNGKey(11)
    keys = jax.random.split(key, S)
    lrs = jnp.asarray([0.1, 0.2], jnp.float32)
    cx, cy = ds.sample_round(jax.random.fold_in(key, 1), 1, 4)

    seq = []
    for s in range(S):
        st = eng.init(W0)
        st, _ = eng.run_round(st, cx, cy, float(lrs[s]), 1.0, keys[s])
        seq.append(_flat(st.params))

    eb = ExperimentBatch(eng, S, mode="vmap")
    states = eb.init_batch(W0)
    states, _, _ = eb.run_round_batch(
        states, cx, cy, lrs, jnp.ones(S, jnp.float32), keys,
        shared_data=True,
    )
    outs = unstack_experiments(states, S)
    for s in range(S):
        np.testing.assert_allclose(
            seq[s], _flat(outs[s].params), rtol=1e-5, atol=1e-6
        )


def test_per_experiment_data_axis():
    """[S, K, ...] per-experiment batches: each experiment trains on its
    own draw, bit-identical to its isolated run."""
    ds, W0 = _tiny_fixture(seed=4)
    S = 2
    eng = _engine(W0, aggregator=get_aggregator("median"))
    key = jax.random.PRNGKey(13)
    keys = jax.random.split(key, S)
    draws = [ds.sample_round(jax.random.fold_in(key, 100 + s), 1, 4)
             for s in range(S)]
    lrs = jnp.full((S,), 0.1, jnp.float32)

    seq = []
    for s in range(S):
        st = eng.init(W0)
        st, _ = eng.run_round(st, *draws[s], 0.1, 1.0, keys[s])
        seq.append(_flat(st.params))

    eb = ExperimentBatch(eng, S)
    cx = jnp.stack([d[0] for d in draws])
    cy = jnp.stack([d[1] for d in draws])
    states = eb.init_batch(W0)
    states, _, _ = eb.run_round_batch(
        states, cx, cy, lrs, jnp.ones(S, jnp.float32), keys,
        shared_data=False,
    )
    outs = unstack_experiments(states, S)
    for s in range(S):
        np.testing.assert_array_equal(seq[s], _flat(outs[s].params))


def test_diags_unstack_like_run_block():
    """Installed surfaces (fault model here) come back stacked [S]-leading
    and unstack per experiment, mirroring run_block's per-round diags."""
    from blades_tpu.faults import FaultModel

    ds, W0 = _tiny_fixture(seed=5)
    S = 2
    eng = _engine(
        W0, aggregator=get_aggregator("median"),
        fault_model=FaultModel(dropout_rate=0.3),
    )
    key = jax.random.PRNGKey(17)
    keys = jax.random.split(key, S)
    cx, cy = ds.sample_round(jax.random.fold_in(key, 1), 1, 4)
    eb = ExperimentBatch(eng, S)
    states = eb.init_batch(W0)
    _, _, diags = eb.run_round_batch(
        states, cx, cy, jnp.full((S,), 0.1, jnp.float32),
        jnp.ones(S, jnp.float32), keys, shared_data=True,
    )
    assert diags["faults"] is not None
    assert np.asarray(diags["faults"]["participants"]).shape == (S,)
    assert diags["audit"] is None and diags["defense"] is None
    per_exp = unstack_experiments(diags["faults"], S)
    assert np.asarray(per_exp[0]["participants"]).shape == ()


def test_validation_errors():
    ds, W0 = _tiny_fixture(seed=6)
    eng = _engine(W0, aggregator=get_aggregator("mean"))
    with pytest.raises(ValueError, match="mode"):
        ExperimentBatch(eng, 2, mode="pmap")
    with pytest.raises(ValueError, match="num_experiments"):
        ExperimentBatch(eng, 0)
    eb = ExperimentBatch(eng, 2, mode="vmap")
    with pytest.raises(ValueError, match="map"):
        eb.run_block_batch((), jnp.zeros((1, 2, 2), jnp.uint32), (), (), (),
                           sampler=lambda k: (k, k))
    # S == K makes the shared-data inference ambiguous: must be explicit
    eng6 = _engine(W0, aggregator=get_aggregator("mean"))
    eb6 = ExperimentBatch(eng6, EK)
    cx, cy = ds.sample_round(jax.random.PRNGKey(0), 1, 4)
    with pytest.raises(ValueError, match="ambiguous"):
        eb6.run_round_batch(
            eb6.init_batch(W0), cx, cy,
            jnp.full((EK,), 0.1, jnp.float32), jnp.ones(EK, jnp.float32),
            jax.random.split(jax.random.PRNGKey(1), EK),
        )
