"""Buffered-asynchronous rounds (``blades_tpu/asyncfl``): degenerate
sync-equivalence across the full aggregator registry, buffer/staleness
semantics, version-lagged training, block scheduling, compile-count pins,
kill -> resume bit-exactness with a non-empty buffer, the registry's
``asyncmean`` semantics pin, and the staleness-aware attack-search
templates.

Reference counterpart: none — the reference simulator is strictly
synchronous (``src/blades/simulator.py:203-247``); protocol semantics
follow FedBuff (Nguyen et al., AISTATS 2022)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.aggregators import AGGREGATORS, get_aggregator
from blades_tpu.asyncfl import ArrivalProcess, AsyncConfig
from blades_tpu.attackers import get_attack
from blades_tpu.core import ClientOptSpec, RoundEngine
from blades_tpu.ops.pytree import ravel
from blades_tpu.utils.checkpoint import restore_state, save_state

K, F, C = 6, 12, 4
D = F * C  # flat dim of the linear model


def _loss(p, x, y, key):
    logits = x.reshape(x.shape[0], -1) @ p["w"]
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
    top1 = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
    return loss, {"top1": top1}


def _logits(p, x):
    return x.reshape(x.shape[0], -1) @ p["w"]


def _fixture(seed=0):
    rng = np.random.RandomState(seed)
    W0 = {"w": jnp.asarray(rng.randn(F, C).astype(np.float32) * 0.1)}
    cx = jnp.asarray(rng.randn(K, 1, 8, F).astype(np.float32))
    cy = jnp.asarray(rng.randint(0, C, (K, 1, 8)).astype(np.int32))
    return W0, cx, cy


def _engine(W0, **kw):
    defaults = dict(
        num_clients=K, num_byzantine=2,
        attack=get_attack("ipm", epsilon=0.5),
        aggregator=get_aggregator("mean"), num_classes=C,
    )
    defaults.update(kw)
    return RoundEngine(_loss, _logits, W0, **defaults)


def _degenerate_cfg():
    return AsyncConfig(
        buffer_m=K, arrivals=ArrivalProcess(kind="zero"),
        staleness="constant",
    )


# ------------------------------------------------ degenerate equivalence


@pytest.mark.parametrize("agg", sorted(AGGREGATORS))
def test_degenerate_matches_sync_across_registry(agg):
    """THE async invariant (the analogue of the all-ones-mask and
    block-vs-sequential contracts): buffer_m=K + zero-delay arrivals +
    constant weighting makes the buffered round BIT-identical to the sync
    round — params, round_idx, every metric column, carried aggregator/
    attack state — for every registered aggregator, over multiple rounds."""
    W0, cx, cy = _fixture()
    key = jax.random.PRNGKey(7)
    agg_kws = (
        {"num_byzantine": 2}
        if agg in ("trimmedmean", "krum", "multikrum", "dnc")
        else {}
    )
    kw = dict(aggregator=get_aggregator(agg, **agg_kws))
    if agg == "fltrust":
        trusted = np.zeros(K, bool)
        trusted[-1] = True
        kw["trusted_mask"] = jnp.asarray(trusted)
    sync = _engine(W0, **kw)
    asy = _engine(W0, async_config=_degenerate_cfg(), **kw)
    st_s, st_a = sync.init(W0), asy.init(W0)
    for _ in range(3):
        st_s, m_s = sync.run_round(st_s, cx, cy, 0.1, 1.0, key)
        st_a, m_a = asy.run_round(st_a, cx, cy, 0.1, 1.0, key)
    for f_s, f_a in zip(m_s, m_a):
        np.testing.assert_array_equal(np.asarray(f_s), np.asarray(f_a))
    # every carried leaf except the async bookkeeping itself
    st_a_cmp = st_a._replace(async_state=())
    st_s_cmp = st_s._replace(async_state=())
    for a, b in zip(
        jax.tree_util.tree_leaves(st_s_cmp), jax.tree_util.tree_leaves(st_a_cmp)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and the degenerate tick fires every round with zero staleness
    d = asy.last_async_diag
    assert int(d["fired"]) == 1 and int(d["fires_total"]) == 3
    assert float(d["mean_staleness"]) == 0.0


def test_degenerate_equivalence_composes_with_faults_and_audit():
    """Degenerate arrivals + a buffer_m low enough to fire every round:
    the async tick under dropout faults + an enforced audit monitor stays
    bit-identical to the sync round (deposit mask == the sync
    participation mask, weights identity, gating a no-op on fired ticks)."""
    from blades_tpu.audit.monitor import AuditMonitor
    from blades_tpu.faults import FaultModel

    W0, cx, cy = _fixture(1)
    key = jax.random.PRNGKey(3)
    kw = dict(
        aggregator=get_aggregator("median"),
        fault_model=FaultModel(dropout_rate=0.3),
        audit_monitor=AuditMonitor(
            envelope_factor=1e-6, fallback_aggregator="median"
        ),
    )
    sync = _engine(W0, **kw)
    asy = _engine(
        W0,
        async_config=AsyncConfig(
            buffer_m=1, arrivals=ArrivalProcess(kind="zero"),
            staleness="constant",
        ),
        **kw,
    )
    st_s, st_a = sync.init(W0), asy.init(W0)
    for _ in range(3):
        st_s, m_s = sync.run_round(st_s, cx, cy, 0.1, 1.0, key)
        st_a, m_a = asy.run_round(st_a, cx, cy, 0.1, 1.0, key)
    np.testing.assert_array_equal(
        np.asarray(ravel(st_s.params)), np.asarray(ravel(st_a.params))
    )
    # the zero-delay buffer drains fully every tick, so the deposit set
    # IS the sync participation set and both sides saw the same rows
    assert int(asy.last_async_diag["fired"]) == 1


# -------------------------------------------------- buffer & staleness


def test_no_fire_below_threshold_keeps_model_and_states():
    """A tick whose buffer stays under first-M must leave params, the
    server-opt state, and the aggregator state bit-untouched (explicit
    no-step, not a zero-aggregate step for stateful surfaces)."""
    W0, cx, cy = _fixture(2)
    key = jax.random.PRNGKey(9)
    # centeredclipping carries momentum state -> pins the agg-state gate
    asy = _engine(
        W0,
        aggregator=get_aggregator("centeredclipping"),
        client_opt=ClientOptSpec(momentum=0.9),
        async_config=AsyncConfig(
            # warm start fires at round 0; afterwards only delay-0 clients
            # arrive and the threshold K is unreachable -> never fires again
            buffer_m=K,
            arrivals=ArrivalProcess(kind="fixed", delays=(1, 2, 3, 1, 2, 3)),
            staleness="constant",
        ),
    )
    st = asy.init(W0)
    st, _ = asy.run_round(st, cx, cy, 0.1, 1.0, key)  # warm start: fires
    assert int(asy.last_async_diag["fired"]) == 1
    p1 = np.asarray(ravel(st.params))
    agg_state1 = np.asarray(st.agg_state)
    so1 = [np.asarray(x) for x in jax.tree_util.tree_leaves(st.server_opt_state)]
    for _ in range(2):
        st, m = asy.run_round(st, cx, cy, 0.1, 1.0, key)
        assert int(asy.last_async_diag["fired"]) == 0
        assert float(m.agg_norm) == 0.0
    np.testing.assert_array_equal(p1, np.asarray(ravel(st.params)))
    np.testing.assert_array_equal(agg_state1, np.asarray(st.agg_state))
    for a, b in zip(
        so1, jax.tree_util.tree_leaves(st.server_opt_state)
    ):
        np.testing.assert_array_equal(a, np.asarray(b))
    # but the buffer kept filling
    assert int(asy.last_async_diag["buffer_count"]) > 0


def test_staleness_weighted_fire_matches_hand_computation():
    """One staggered fire with HETEROGENEOUS staleness, polynomial
    weighting, mean aggregator: the applied pseudo-gradient equals the
    hand-computed normalized-weighted mean of the buffered rows (FedBuff's
    ``sum(w_i d_i) / sum(w_i)``), with the newest-wins per-client slot and
    the download-version staleness base mirrored host-side."""
    W0, cx, cy = _fixture(3)
    key = jax.random.PRNGKey(11)
    delays = (0, 1, 2, 0, 1, 2)
    alpha = 0.7
    asy = _engine(
        W0,
        num_byzantine=0, attack=None,
        aggregator=get_aggregator("mean"),
        keep_updates=True,
        async_config=AsyncConfig(
            buffer_m=K, arrivals=ArrivalProcess(kind="fixed", delays=delays),
            staleness="polynomial", alpha=alpha,
        ),
    )
    st = asy.init(W0)
    # host-side mirror of the arrival bookkeeping (the semantics oracle):
    # newest-wins deposits, download-version staleness base, drain on fire
    countdown, version = [0] * K, [0] * K
    buf_rows, buf_ver = {}, {}
    p_before_fire, fire_t = None, None
    for t in range(5):
        arriving = [countdown[i] <= 0 for i in range(K)]
        prev_params = np.asarray(ravel(st.params))
        st, _ = asy.run_round(st, cx, cy, 0.1, 1.0, key)
        for i in range(K):
            if arriving[i]:
                buf_rows[i] = np.asarray(asy.last_updates[i])
                buf_ver[i] = version[i]
                version[i] = t + 1
                countdown[i] = delays[i]
            else:
                countdown[i] -= 1
        if int(asy.last_async_diag["fired"]):
            if t > 0:
                fire_t = t
                p_before_fire = prev_params
                break
            buf_rows, buf_ver = {}, {}  # the t=0 warm fire drains the buffer
    assert fire_t is not None and len(buf_rows) == K
    tau = np.asarray([fire_t - buf_ver[i] for i in range(K)], float)
    assert len(set(tau.tolist())) > 1, "scenario must mix staleness"
    w_raw = (1.0 + tau) ** (-alpha)
    w = w_raw * K / w_raw.sum()
    mat = np.stack([buf_rows[i] for i in range(K)])
    expected = (mat * w[:, None]).mean(axis=0)  # == sum(w d) / sum(w) / 1
    np.testing.assert_allclose(
        np.asarray(ravel(st.params)), p_before_fire + expected,
        rtol=1e-5, atol=1e-7,
    )
    d_diag = asy.last_async_diag
    assert float(d_diag["mean_staleness"]) == pytest.approx(tau.mean())
    assert float(d_diag["weight_min"]) == pytest.approx(w.min(), rel=1e-5)


def test_cutoff_excludes_stale_rows():
    """cutoff staleness: buffered updates staler than the bound are
    excluded from the aggregated set (mask exclusion, not down-weighting)
    and counted in the diag."""
    W0, cx, cy = _fixture(4)
    key = jax.random.PRNGKey(13)
    asy = _engine(
        W0,
        num_byzantine=0, attack=None,
        aggregator=get_aggregator("mean"),
        async_config=AsyncConfig(
            buffer_m=K,
            arrivals=ArrivalProcess(kind="fixed", delays=(0, 0, 0, 0, 0, 3)),
            staleness="cutoff", cutoff=1,
        ),
    )
    st = asy.init(W0)
    st, _ = asy.run_round(st, cx, cy, 0.1, 1.0, key)  # warm fire
    fired_rounds = 0
    for _ in range(4):
        st, _ = asy.run_round(st, cx, cy, 0.1, 1.0, key)
        d = asy.last_async_diag
        if int(d["fired"]):
            fired_rounds += 1
            # the delay-3 client's buffered update is 3 ticks stale at the
            # fire -> excluded by the cutoff
            assert int(d["stale_excluded"]) >= 1
            assert int(d["aggregated"]) == K - int(d["stale_excluded"])
            assert int(d["max_staleness"]) <= 1
    assert fired_rounds >= 1


def test_version_lagged_training_uses_downloaded_params():
    """A delayed client's update is computed against the params it
    DOWNLOADED, not the live ones: with one slow client and a moving
    model, its deposited row equals the update a sync engine would have
    produced from the older params (same batch, same key)."""
    W0, cx, cy = _fixture(5)
    key = jax.random.PRNGKey(17)
    delays = (0, 0, 0, 0, 0, 2)  # client 5 lags 2 rounds
    asy = _engine(
        W0, num_byzantine=0, attack=None,
        aggregator=get_aggregator("mean"), keep_updates=True,
        async_config=AsyncConfig(
            buffer_m=1,  # fire every tick that has a deposit
            arrivals=ArrivalProcess(kind="fixed", delays=delays),
            staleness="constant",
        ),
    )
    st = asy.init(W0)
    params_at = {0: np.asarray(ravel(st.params))}
    snaps = {}
    for r in range(4):
        st, _ = asy.run_round(st, cx, cy, 0.1, 1.0, key)
        params_at[r + 1] = np.asarray(ravel(st.params))
        snaps[r] = np.asarray(asy.last_updates[5])
    # client 5 re-downloads at round 0 (warm arrival) -> version 1, trains
    # against params_at[1], arrives at round 1 + 2 = 3: its row in the
    # round-3 trained matrix must equal a fresh sync engine's update for
    # client 5 from params_at[1] with round-3 keys. Reproduce via a
    # one-round sync engine whose round_idx is forced to 3.
    sync = _engine(
        W0, num_byzantine=0, attack=None,
        aggregator=get_aggregator("mean"), keep_updates=True,
    )
    st_s = sync.init(sync.unravel(jnp.asarray(params_at[1])))
    st_s = st_s._replace(round_idx=jnp.asarray(3, jnp.int32))
    st_s, _ = sync.run_round(st_s, cx, cy, 0.1, 1.0, key)
    np.testing.assert_allclose(
        snaps[3], np.asarray(sync.last_updates[5]), rtol=1e-5, atol=1e-7
    )


# ------------------------------------------------ block scheduling


def test_async_block_matches_sequential():
    """The buffered-async body rides run_block's lax.scan bit-exactly —
    async_state (buffer, versions, countdowns, the lag ring) is carried in
    the scan like every other RoundState leaf."""
    from blades_tpu.datasets.fl import FLDataset

    rng = np.random.RandomState(0)
    ds = FLDataset(
        rng.randn(K, 20, F).astype(np.float32),
        rng.randint(0, C, (K, 20)).astype(np.int32),
        np.full(K, 20, np.int32),
        rng.randn(30, F).astype(np.float32),
        rng.randint(0, C, 30).astype(np.int32),
    )
    W0 = {"w": jnp.asarray(rng.randn(F, C).astype(np.float32) * 0.1)}
    key = jax.random.PRNGKey(7)
    dk = jax.random.fold_in(key, 23)
    cfg = AsyncConfig(
        buffer_m=3, arrivals=ArrivalProcess(kind="uniform", max_delay=2),
        staleness="polynomial", alpha=0.5,
    )
    kw = dict(
        aggregator=get_aggregator("median"),
        attack=get_attack("signflipping"),
        async_config=cfg,
    )
    eng = _engine(W0, **kw)
    st = eng.init(W0)
    for r in range(1, 4):
        cx, cy = ds.sample_round(jax.random.fold_in(dk, r), 2, 4)
        st, m = eng.run_round(st, cx, cy, 0.2, 1.0, key)

    eng2 = _engine(W0, **kw)
    st2 = eng2.init(W0)
    keys = jnp.stack([jax.random.fold_in(dk, r) for r in range(1, 4)])
    st2, ms, diags = eng2.run_block(
        st2, keys, [0.2] * 3, [1.0] * 3, key,
        sampler=ds.traceable_sampler(2, 4),
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(st), jax.tree_util.tree_leaves(st2)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert diags["async"] is not None
    assert np.asarray(diags["async"]["fired"]).shape == (3,)
    # the block's stacked diag matches the engine's last-round view
    assert int(np.asarray(diags["async"]["fires_total"])[-1]) == int(
        eng.last_async_diag["fires_total"]
    )


# ------------------------------------------------ compile accounting


def test_async_compile_count_pinned():
    """The async program is ONE jitted program: at most sync+1 programs
    per run, and a same-shape recall adds ZERO compiles — pinned via the
    telemetry compile counters (the Tier-B/driver-gate signal)."""
    from blades_tpu.telemetry import (
        Recorder,
        install_jax_monitoring,
        set_recorder,
    )

    W0, cx, cy = _fixture(6)
    key = jax.random.PRNGKey(2)
    rec = Recorder(enabled=True)
    prev = set_recorder(rec)
    try:
        install_jax_monitoring()

        def compiles():
            return rec.counters.get("xla.compiles", 0)

        sync = _engine(W0)
        st = sync.init(W0)
        before = compiles()
        st, _ = sync.run_round(st, cx, cy, 0.1, 1.0, key)
        jax.block_until_ready(st.params)
        sync_programs = compiles() - before

        asy = _engine(
            W0,
            async_config=AsyncConfig(
                buffer_m=3,
                arrivals=ArrivalProcess(kind="uniform", max_delay=2),
                staleness="polynomial",
            ),
        )
        st_a = asy.init(W0)
        before = compiles()
        st_a, _ = asy.run_round(st_a, cx, cy, 0.1, 1.0, key)
        jax.block_until_ready(st_a.params)
        async_programs = compiles() - before
        assert async_programs <= sync_programs + 1, (
            sync_programs, async_programs,
        )
        # zero recompiles on same-shape recall
        before = compiles()
        for _ in range(2):
            st_a, _ = asy.run_round(st_a, cx, cy, 0.1, 1.0, key)
        jax.block_until_ready(st_a.params)
        assert compiles() == before
    finally:
        set_recorder(prev)


# ------------------------------------------------ resume bit-exactness


def test_kill_resume_bit_exact_with_nonempty_buffer(tmp_path):
    """Checkpoint mid-run with updates SITTING IN THE BUFFER (and clients
    mid-flight); restoring and continuing matches the uninterrupted run
    bit-for-bit — the async analogue of the straggler-replay resume
    contract."""
    W0, cx, cy = _fixture(7)
    key = jax.random.PRNGKey(19)
    cfg = AsyncConfig(
        buffer_m=5, arrivals=ArrivalProcess(kind="fixed",
                                            delays=(0, 1, 2, 3, 1, 2)),
        staleness="polynomial", alpha=0.5,
    )

    def build():
        return _engine(W0, async_config=cfg)

    ref = build()
    st = ref.init(W0)
    mid = None
    for r in range(6):
        st, _ = ref.run_round(st, cx, cy, 0.1, 1.0, key)
        if r == 2:
            # non-empty buffer at the checkpoint: the partial fill is the
            # state a crash must not lose. Materialize to host copies —
            # the next run_round DONATES the state buffers
            assert int(ref.last_async_diag["buffer_count"]) > 0
            assert int(ref.last_async_diag["fired"]) == 0
            mid = jax.tree_util.tree_map(lambda a: np.asarray(a), st)
            save_state(str(tmp_path / "ck"), st)
    p_ref = np.asarray(ravel(st.params))

    res = build()
    st2 = res.init(W0)  # template for shapes
    st2 = res.place_state(restore_state(str(tmp_path / "ck"), st2))
    for a, b in zip(
        jax.tree_util.tree_leaves(mid), jax.tree_util.tree_leaves(st2)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for r in range(3, 6):
        st2, _ = res.run_round(st2, cx, cy, 0.1, 1.0, key)
    np.testing.assert_array_equal(p_ref, np.asarray(ravel(st2.params)))


# ------------------------------------------------ asyncmean semantics


def test_asyncmean_is_constant_weighted_buffered_mean():
    """The registry's ``asyncmean`` under the async engine: each fire
    applies ``sum(buffered rows) / K`` — the constant-staleness-weighted
    FedBuff mean with the deliberate n/K damping — and degenerates to
    plain Mean at buffer_m=K + zero delays (the documented semantics,
    aggregators/decentralized.py)."""
    W0, cx, cy = _fixture(8)
    key = jax.random.PRNGKey(23)
    # damped case: only 4 of 6 clients in the fire
    asy = _engine(
        W0, num_byzantine=0, attack=None,
        aggregator=get_aggregator("asyncmean"), keep_updates=True,
        async_config=AsyncConfig(
            buffer_m=4,
            arrivals=ArrivalProcess(kind="fixed", delays=(0, 0, 0, 0, 2, 2)),
            staleness="constant",
        ),
    )
    st = asy.init(W0)
    st, _ = asy.run_round(st, cx, cy, 0.1, 1.0, key)  # warm fire, all 6
    p1 = np.asarray(ravel(st.params))
    st, _ = asy.run_round(st, cx, cy, 0.1, 1.0, key)
    d = asy.last_async_diag
    assert int(d["fired"]) == 1 and int(d["aggregated"]) == 4
    # applied step = sum(4 deposited rows) / K  (1/K damping, NOT 1/4)
    rows = np.asarray(asy.last_updates[:4])
    np.testing.assert_allclose(
        np.asarray(ravel(st.params)), p1 + rows.sum(axis=0) / K,
        rtol=1e-5, atol=1e-7,
    )
    # degenerate case: asyncmean's step equals plain Mean's (both compute
    # the full-population average; `mean(u)` and `sum(u)/K` are different
    # XLA expressions, so the equality contract here is numerical, while
    # asyncmean-vs-SYNC-asyncmean bit-exactness is the registry-wide
    # parametrized test's job)
    for agg in ("mean", "asyncmean"):
        eng = _engine(
            W0, num_byzantine=0, attack=None,
            aggregator=get_aggregator(agg),
            async_config=_degenerate_cfg(),
        )
        s = eng.init(W0)
        s, _ = eng.run_round(s, cx, cy, 0.1, 1.0, key)
        if agg == "mean":
            p_mean = np.asarray(ravel(s.params))
        else:
            np.testing.assert_allclose(
                p_mean, np.asarray(ravel(s.params)), rtol=1e-6, atol=1e-8
            )


# ------------------------------------------------ arrivals unit tests


def test_arrival_draws_seeded_and_bounded():
    k = 16
    key = jax.random.PRNGKey(0)
    for ap in (
        ArrivalProcess(kind="uniform", max_delay=3),
        ArrivalProcess(kind="geometric", mean_delay=2.0, max_delay=5),
    ):
        a = np.asarray(ap.draw(key, k))
        b = np.asarray(ap.draw(key, k))
        np.testing.assert_array_equal(a, b)  # pure function of the key
        assert a.min() >= 0 and a.max() <= ap.max_delay
        c = np.asarray(ap.draw(jax.random.PRNGKey(1), k))
        assert not np.array_equal(a, c)  # the key matters
    z = np.asarray(ArrivalProcess(kind="zero").draw(key, k))
    np.testing.assert_array_equal(z, np.zeros(k))
    fx = ArrivalProcess(kind="fixed", delays=tuple(range(k)))
    np.testing.assert_array_equal(np.asarray(fx.draw(key, k)), np.arange(k))
    assert fx.max_delay == k - 1 and fx.history_len == k


def test_config_validation():
    with pytest.raises(ValueError, match="unknown arrival kind"):
        ArrivalProcess(kind="nope")
    with pytest.raises(ValueError, match="delays"):
        ArrivalProcess(kind="fixed")
    with pytest.raises(ValueError, match="staleness"):
        AsyncConfig(buffer_m=2, staleness="nope")
    with pytest.raises(ValueError, match="cutoff"):
        AsyncConfig(buffer_m=2, staleness="cutoff")
    with pytest.raises(ValueError, match="cutoff must be >= 0"):
        # a negative bound would exclude fresh rows — and silently diverge
        # from the zero-delay static specialization
        AsyncConfig(buffer_m=2, staleness="cutoff", cutoff=-1)
    with pytest.raises(ValueError, match="buffer_m"):
        AsyncConfig(buffer_m=0)
    W0, _, _ = _fixture()
    with pytest.raises(ValueError, match="streaming"):
        _engine(
            W0, streaming=True, client_chunks=2,
            async_config=_degenerate_cfg(),
        )
    from blades_tpu.faults import FaultModel

    with pytest.raises(ValueError, match="straggler"):
        _engine(
            W0, fault_model=FaultModel(straggler_rate=0.5),
            async_config=_degenerate_cfg(),
        )


def test_normalized_weights_mean_one():
    cfg = AsyncConfig(buffer_m=2, staleness="polynomial", alpha=0.8)
    tau = jnp.asarray([0, 1, 2, 3, 4, 5], jnp.int32)
    mask = jnp.asarray([True, True, True, False, True, True])
    m, w = cfg.staleness_mask_weights(tau, mask)
    np.testing.assert_array_equal(np.asarray(m), np.asarray(mask))
    wm = np.asarray(w)[np.asarray(mask)]
    assert wm.mean() == pytest.approx(1.0, rel=1e-6)
    assert (np.diff(wm) < 0).all()  # staler -> smaller weight


# ------------------------------------------------ staleness attack search


def test_staleness_search_mean_breaks_median_certifies():
    """The async cert columns' semantics at unit scale: the
    weight-compensating adversary still breaks mean (fresh_byz scenario)
    while median certifies over the staleness-distorted honest geometry."""
    from blades_tpu.audit import (
        DEFAULT_C,
        QUICK_GRIDS,
        battery_ctx,
        search_cell_staleness,
        synthetic_honest,
    )

    k, d = 8, 16
    trials = synthetic_honest(jax.random.PRNGKey(0), 1, k, d)
    ctx = battery_ctx(None, k, d)
    mean_cell = search_cell_staleness(
        get_aggregator("mean"), trials, 1, mode="polynomial",
        tau_max=3, tau_byz=0, ctx=ctx, grids=QUICK_GRIDS,
    )
    assert mean_cell["worst_ratio"] > DEFAULT_C
    assert mean_cell["staleness"]["tau_byz"] == 0
    med_cell = search_cell_staleness(
        get_aggregator("median"), trials, 2, mode="polynomial",
        tau_max=3, tau_byz=3, ctx=ctx, grids=QUICK_GRIDS,
    )
    assert med_cell["worst_ratio"] <= DEFAULT_C
    # cutoff mode: maximal-staleness byzantines are EXCLUDED entirely ->
    # the attack surface collapses to the honest-only aggregate
    cut_cell = search_cell_staleness(
        get_aggregator("mean"), trials, 2, mode="cutoff", cutoff=1,
        tau_max=3, tau_byz=3, ctx=ctx, grids=QUICK_GRIDS,
    )
    assert cut_cell["worst_ratio"] <= DEFAULT_C


def test_committed_cert_matrix_has_async_columns():
    """The committed evidence artifact carries the staleness-aware async
    columns: both scenarios for every pooled (agg, f) cell, mean broken
    under staleness at every f >= 1, the robust headliners certified at
    nominal f in both scenarios."""
    import json
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "results", "certification", "cert_matrix.json",
    )
    m = json.load(open(path))
    assert m["ok"] is True
    cells = m["async_cells"]
    assert cells, "cert matrix has no async columns"
    by = {(c["agg"], c["f"], c["scenario"]): c for c in cells}
    f_max = m["f_max"]
    scenarios = {c["scenario"] for c in cells}
    assert scenarios == {"fresh_byz", "stale_byz"}
    for f in range(1, f_max + 1):
        assert not by[("mean", f, "fresh_byz")]["certified"]
    from blades_tpu.audit import nominal_f

    for name in ("median", "krum", "centeredclipping"):
        for f in range(nominal_f(name, m["clients"]) + 1):
            for scen in ("fresh_byz", "stale_byz"):
                assert by[(name, f, scen)]["certified"], (name, f, scen)


# ------------------------------------------------ simulator integration


def test_simulator_async_run_emits_schema_valid_records(tmp_path):
    """Simulator.run(async_config=...) end to end: async telemetry records
    present (one per round, schema-valid), round gauges carry the buffer
    state, and the run learns nothing non-finite."""
    import json
    import os

    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic
    from blades_tpu.telemetry import schema

    sim = Simulator(
        dataset=Synthetic(num_clients=8, train_size=200, test_size=40,
                          noise=0.3, cache=False),
        aggregator="median",
        log_path=str(tmp_path / "run"),
        seed=2,
    )
    sim.run(
        "mlp", global_rounds=3, local_steps=1, train_batch_size=8,
        client_lr=0.2, server_lr=1.0, validate_interval=3,
        async_config=dict(
            buffer_m=3, arrivals=dict(kind="uniform", max_delay=2),
            staleness="polynomial", alpha=0.5,
        ),
    )
    trace = tmp_path / "run" / "telemetry.jsonl"
    recs = [json.loads(l) for l in open(trace)]
    assert schema.validate_trace(str(trace)) == []
    asy = [r for r in recs if r.get("t") == "async"]
    assert len(asy) == 3
    assert asy[0]["arrivals"] == 8  # warm start
    rounds = [r for r in recs if r.get("t") == "round"]
    assert all("async.buffer_count" in r["gauges"] for r in rounds)
    assert all(
        r["gauges"].get("engine.async_buffer_m") == 3 for r in rounds
    )
