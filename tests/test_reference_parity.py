"""Differential parity against the reference's own code.

Unlike the closed-form unit tests (test_aggregators.py), these tests load the
actual reference implementation from /root/reference/src (see
``reference_loader`` — only ``ray`` is faked) and feed IDENTICAL inputs to
both stacks:

- every aggregator: same [K, D] matrices -> same aggregate (documented
  deviations asserted under their parity flags: ``Krum(distance_power=4)``
  mirrors the reference's accidental d^4 ranking, multikrum m>1 mirrors
  sum-vs-mean, clustering's similarity-as-distance metric);
- every omniscient attack: same honest updates -> same malicious rows
  (reference path: real ``omniscient_callback`` on real ``ByzantineClient``
  objects);
- the client runtime end to end: the reference's real
  ``BladesClient.local_training`` + update extraction on a torch linear
  model vs ``RoundEngine``'s vmapped local step on the identical model —
  honest and signflipping clients.

Tolerances: both stacks are fp32; matmul-vs-direct pairwise distances and
reduction orders differ at ~1e-5 relative, so comparisons use allclose with
rtol 1e-4 (selection-based aggregators are additionally checked for picking
the identical row).
"""

from __future__ import annotations

import os
import sys

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import reference_loader  # noqa: E402
from reference_loader import load_reference  # noqa: E402

from blades_tpu.aggregators import get_aggregator  # noqa: E402

if not os.path.isdir(reference_loader.REF_SRC):
    # differential parity needs the read-only reference checkout; containers
    # without it must skip, not die at collection
    pytest.skip(
        f"reference source tree not present at {reference_loader.REF_SRC}",
        allow_module_level=True,
    )
ref = load_reference()


# --------------------------------------------------------------------------
# fixtures: matched random matrices
# --------------------------------------------------------------------------

def gaussian(k=12, d=33, seed=0, scale=1.0):
    rng = np.random.RandomState(seed)
    return (rng.randn(k, d) * scale).astype(np.float32)


def clustered(k=12, d=33, n_out=4, seed=0):
    """Benign cluster near the origin + a tight outlier cluster at +5."""
    rng = np.random.RandomState(seed)
    m = rng.randn(k, d).astype(np.float32) * 0.3
    m[:n_out] += 5.0
    return m


def t(m):
    return torch.from_numpy(np.asarray(m).copy())


def allclose(ours, theirs, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(
        np.asarray(ours), theirs.detach().numpy(), rtol=rtol, atol=atol
    )


# --------------------------------------------------------------------------
# stateless aggregators
# --------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("k", [10, 13])
def test_mean_matches_reference(seed, k):
    m = gaussian(k=k, seed=seed)
    allclose(get_aggregator("mean")(jnp.asarray(m)), ref.aggregators.Mean()(t(m)))


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("k", [10, 13])
def test_median_matches_reference(seed, k):
    m = gaussian(k=k, seed=seed)
    allclose(
        get_aggregator("median")(jnp.asarray(m)), ref.aggregators.Median()(t(m))
    )


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("k,b", [(12, 3), (12, 5), (8, 5)])  # (8,5) auto-shrinks
def test_trimmedmean_matches_reference(seed, k, b):
    m = gaussian(k=k, seed=seed)
    allclose(
        get_aggregator("trimmedmean", num_byzantine=b)(jnp.asarray(m)),
        ref.aggregators.Trimmedmean(nb=b)(t(m)),
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_krum_matches_reference(seed):
    # the reference ranks by d^4 (squares the already-squared distances,
    # krum.py:22 on top of krum.py:91); our parity flag mirrors that
    m = clustered(k=12, seed=seed)
    ours = get_aggregator("krum", num_byzantine=3, distance_power=4)(
        jnp.asarray(m)
    )
    theirs = ref.aggregators.Krum(num_clients=12, num_byzantine=3)(t(m))
    allclose(ours, theirs, rtol=1e-6, atol=1e-7)  # both return an input row


@pytest.mark.parametrize("m_sel", [2, 3])
def test_multikrum_deviation_is_exactly_sum_vs_mean(m_sel):
    """Reference ``_multi_krum`` SUMS the m selected rows (krum.py:120, only
    ever run at m=1); we follow the Multi-Krum paper and average. Assert the
    deviation is exactly that factor: same selection, ours * m == theirs."""
    mat = clustered(k=12, seed=3)
    r = ref.aggregators.Krum(num_clients=12, num_byzantine=3)
    r.m = m_sel
    theirs = r(t(mat))
    ours = get_aggregator(
        "multikrum", num_byzantine=3, num_selected=m_sel, distance_power=4
    )(jnp.asarray(mat))
    np.testing.assert_allclose(
        np.asarray(ours) * m_sel, theirs.numpy(), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_geomed_matches_reference(seed):
    m = gaussian(k=11, seed=seed)
    allclose(
        get_aggregator("geomed")(jnp.asarray(m)),
        ref.aggregators.Geomed()(t(m)),
        rtol=1e-3,
        atol=1e-4,
    )


@pytest.mark.parametrize("seed", [0, 1])
def test_autogm_matches_reference(seed):
    m = clustered(k=10, n_out=3, seed=seed)
    allclose(
        get_aggregator("autogm")(jnp.asarray(m)),
        ref.aggregators.Autogm()(t(m)),
        rtol=1e-3,
        atol=1e-3,
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_clustering_matches_reference(seed):
    # reference feeds the cosine-SIMILARITY matrix (diag 1) to complete
    # linkage as if it were a distance (clustering.py:28-39); our default
    # metric='similarity' mirrors exactly that quirk
    m = clustered(k=12, n_out=4, seed=seed)
    allclose(
        get_aggregator("clustering")(jnp.asarray(m)),
        ref.aggregators.Clustering()(t(m)),
    )


# --------------------------------------------------------------------------
# stateful aggregators: compare whole call sequences
# --------------------------------------------------------------------------

def test_centeredclipping_sequence_matches_reference():
    theirs = ref.aggregators.centeredclipping.Centeredclipping()
    ours = get_aggregator("centeredclipping")
    for seed in range(4):
        m = gaussian(k=10, seed=seed, scale=3.0)
        clients = []
        for row in t(m):
            c = ref.client.BladesClient(id="x")
            c.save_update(row)
            clients.append(c)
        allclose(ours(jnp.asarray(m)), theirs(clients), rtol=1e-4, atol=1e-4)


def test_clippedclustering_sequence_matches_reference():
    # stateful: clips to the median of the HISTORICAL norms accumulated
    # across rounds (clippedclustering.py:38-48); norms grow each round so
    # the threshold actually binds
    theirs = ref.aggregators.clippedclustering.Clippedclustering()
    ours = get_aggregator("clippedclustering")
    for seed in range(4):
        m = clustered(k=12, n_out=4, seed=seed) * (1.0 + seed)
        # the reference mutates its input rows in place when clipping —
        # hand it a private copy
        allclose(
            ours(jnp.asarray(m)),
            theirs(t(m).clone()),
            rtol=1e-3,
            atol=1e-3,
        )


def test_fltrust_matches_reference():
    for seed in range(3):
        m = gaussian(k=9, seed=seed)
        clients = []
        for i, row in enumerate(t(m)):
            c = ref.client.BladesClient(id=str(i))
            c.save_update(row)
            if i == 4:
                c.trust()
            clients.append(c)
        theirs = ref.aggregators.fltrust.Fltrust()(clients)
        mask = np.zeros(9, bool)
        mask[4] = True
        ours = get_aggregator("fltrust")(jnp.asarray(m), trusted_mask=jnp.asarray(mask))
        allclose(ours, theirs, rtol=1e-4, atol=1e-4)


def test_byzantinesgd_sequence_matches_reference():
    dim = 17
    k = 9
    p0 = np.zeros(dim, np.float32)
    tp = torch.nn.Parameter(torch.from_numpy(p0.copy()))
    opt = torch.optim.SGD([tp], lr=1.0)
    theirs = ref.aggregators.byzantinesgd.ByzantineSGD(
        m=k, th_A=50.0, th_B=50.0, th_V=50.0, optimizer=opt
    )
    ours = get_aggregator("byzantinesgd", th_A=50.0, th_B=50.0, th_V=50.0)

    params = p0
    for seed in range(3):
        m = gaussian(k=k, d=dim, seed=seed)
        out_theirs = theirs(list(t(m)))
        out_ours = ours(jnp.asarray(m), params_flat=jnp.asarray(params))
        np.testing.assert_allclose(
            np.asarray(out_ours), out_theirs.numpy(), rtol=1e-4, atol=1e-4
        )
        # move the model between rounds so the A accumulator sees a real
        # model_diff on both sides
        params = params + 0.1 * np.asarray(out_ours)
        with torch.no_grad():
            tp.copy_(torch.from_numpy(params.copy()))


# --------------------------------------------------------------------------
# omniscient attacks: reference callbacks on real ByzantineClient objects
# --------------------------------------------------------------------------

class _FakeSimulator:
    """Duck-typed stand-in for the two simulator surfaces the reference
    omniscient callbacks read (``simulator._clients`` /``get_clients()``)."""

    def __init__(self, clients):
        self._clients = {c.id(): c for c in clients}

    def get_clients(self):
        return list(self._clients.values())


def _make_population(m, n_byz, attacker_cls, **kw):
    clients = []
    for i, row in enumerate(t(m)):
        if i < n_byz:
            c = attacker_cls(**kw)
            c.set_id(str(i))
        else:
            c = ref.client.BladesClient(id=str(i))
        c.save_update(row)
        clients.append(c)
    return clients


def test_alie_matches_reference():
    from blades_tpu.attackers import get_attack

    n, f = 12, 4
    m = gaussian(k=n, d=40, seed=0)
    byz = np.arange(n) < f

    a_ref = ref.attackers.alieclient.AlieClient(num_clients=n, num_byzantine=f)
    clients = _make_population(m, f, lambda: a_ref)
    sim = _FakeSimulator(clients)
    a_ref.omniscient_callback(sim)
    theirs = a_ref.get_update()

    ours = get_attack("alie")
    out, _ = ours.on_updates(jnp.asarray(m), jnp.asarray(byz), jax.random.PRNGKey(0))
    np.testing.assert_allclose(
        np.asarray(out[0]), theirs.numpy(), rtol=1e-4, atol=1e-5
    )
    # z_max itself
    np.testing.assert_allclose(ours._z_max(n, f), a_ref.z_max, rtol=1e-9)
    # honest rows untouched
    np.testing.assert_array_equal(np.asarray(out[f:]), m[f:])


def test_ipm_matches_reference():
    from blades_tpu.attackers import get_attack

    n, f = 10, 3
    m = gaussian(k=n, d=25, seed=1)
    byz = np.arange(n) < f

    a_ref = ref.attackers.ipmclient.IpmClient(epsilon=0.5)
    clients = _make_population(m, f, lambda: a_ref)
    sim = _FakeSimulator(clients)
    a_ref.omniscient_callback(sim)
    theirs = a_ref.get_update()

    out, _ = get_attack("ipm").on_updates(
        jnp.asarray(m), jnp.asarray(byz), jax.random.PRNGKey(0)
    )
    np.testing.assert_allclose(
        np.asarray(out[0]), theirs.numpy(), rtol=1e-5, atol=1e-6
    )


def test_noise_matches_reference_distribution():
    """Noise draws are RNG-backend-specific; parity is distributional:
    same N(0.1, 0.1) parameters on both sides (noiseclient.py:21-25)."""
    from blades_tpu.attackers import get_attack

    d = 200_000
    m = gaussian(k=4, d=d, seed=2)
    byz = np.array([True, False, False, False])

    a_ref = ref.attackers.noiseclient.NoiseClient()
    a_ref.save_update(t(m[0]))
    a_ref.omniscient_callback(None)
    theirs = a_ref.get_update().numpy()

    out, _ = get_attack("noise").on_updates(
        jnp.asarray(m), jnp.asarray(byz), jax.random.PRNGKey(3)
    )
    row = np.asarray(out[0])
    assert abs(row.mean() - theirs.mean()) < 5e-3
    assert abs(row.std() - theirs.std()) < 5e-3


def test_labelflipping_matches_reference():
    from blades_tpu.attackers import get_attack

    a_ref = ref.attackers.labelflippingclient.LabelflippingClient(num_classes=10)
    data = torch.zeros(6, 3)
    target = torch.tensor([0, 1, 2, 7, 8, 9])
    _, flipped = a_ref.on_train_batch_begin(data, target)

    ours = get_attack("labelflipping")
    x = jnp.zeros((6, 3))
    y = jnp.asarray(target.numpy())
    _, y2 = ours.on_batch(x, y, jnp.asarray(True), num_classes=10,
                          key=jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(y2), flipped.numpy())
    # honest clients see unmodified labels
    _, y3 = ours.on_batch(x, y, jnp.asarray(False), num_classes=10,
                          key=jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(y3), np.asarray(y))


# --------------------------------------------------------------------------
# client runtime end to end: reference local_training vs RoundEngine
# --------------------------------------------------------------------------

def _torch_linear_client(W0, data, labels, lr, client_cls):
    """Run the reference's real local-training path on a bias-free linear
    softmax classifier; return its extracted update reshaped to W0's
    [din, dout] layout (torch Linear stores the transpose)."""
    din, dout = W0.shape
    model = torch.nn.Linear(din, dout, bias=False)
    with torch.no_grad():
        model.weight.copy_(torch.from_numpy(W0.T.copy()))
    c = client_cls(id="0")
    c.set_model(model, torch.optim.SGD, lr=lr)
    c.set_loss()
    c.on_train_round_begin()
    batches = [
        (torch.from_numpy(x.copy()), torch.from_numpy(y.copy()).long())
        for x, y in zip(data, labels)
    ]
    c.local_training(batches)
    c.on_train_round_end()
    return c.get_update().numpy().reshape(dout, din).T


def _engine_updates(W0, cx, cy, lr, num_byzantine, attack):
    """The same workload through RoundEngine: K clients, S steps, identical
    linear model, SGD, cross-entropy with the reference's loss clamp."""
    from blades_tpu.attackers import get_attack
    from blades_tpu.core import ClientOptSpec, RoundEngine, ServerOptSpec

    def train_loss_fn(params, x, y, key):
        logits = x @ params["w"]
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
        return loss, {}

    engine = RoundEngine(
        train_loss_fn,
        lambda params, x: x @ params["w"],
        {"w": jnp.asarray(W0)},
        num_clients=cx.shape[0],
        num_byzantine=num_byzantine,
        attack=get_attack(attack) if attack else None,
        aggregator=get_aggregator("mean"),
        client_opt=ClientOptSpec(),
        server_opt=ServerOptSpec(),
        num_classes=W0.shape[1],
    )
    state = engine.init({"w": jnp.asarray(W0)})
    engine.run_round(state, jnp.asarray(cx), jnp.asarray(cy), lr, 1.0,
                     jax.random.PRNGKey(0))
    return np.asarray(engine.last_updates)


@pytest.mark.parametrize("attack_first", [None, "signflipping"])
def test_client_local_training_matches_reference(attack_first):
    """2 clients x 3 local SGD steps on identical data: the reference's real
    ``BladesClient.local_training`` / ``SignflippingClient.local_training``
    (loaded verbatim) against the vmapped engine. Checks step semantics,
    update extraction (client.py:127-131,216-228) and the sign-flip
    transform (signflippingclient.py:10-20) in one shot."""
    rng = np.random.RandomState(0)
    k, s, b, din, dout = 2, 3, 8, 5, 4
    W0 = (rng.randn(din, dout) * 0.3).astype(np.float32)
    cx = rng.randn(k, s, b, din).astype(np.float32)
    cy = rng.randint(0, dout, (k, s, b)).astype(np.int32)
    lr = 0.05

    expected = []
    for i in range(k):
        cls = (
            ref.attackers.signflippingclient.SignflippingClient
            if (attack_first and i == 0)
            else ref.client.BladesClient
        )
        expected.append(
            _torch_linear_client(W0, cx[i], cy[i], lr, lambda id: cls(id=id))
        )
    n_byz = 1 if attack_first else 0
    ours = _engine_updates(W0, cx, cy, lr, n_byz, attack_first)

    assert ours.shape == (k, W0.size)
    for i in range(k):
        np.testing.assert_allclose(
            ours[i].reshape(din, dout), expected[i], rtol=1e-4, atol=1e-5,
        )


def test_mixed_registered_omniscients_match_reference():
    """A mixed REGISTERED population (2 x ALIE + 1 x IPM via
    ``register_attackers``) against the reference's callback loop
    (``simulator.py:239-241``) on the same population: every omniscient
    callback must exclude ALL byzantine clients from its honest statistics
    (``alieclient.py:27-31``) and read the pre-attack uploads — never another
    registered attacker's corrupted row. Guards the ``_CompositeAttack``
    masking fix (one-hot submasks made ALIE treat the other attackers' rows
    as honest)."""
    from blades_tpu.attackers import get_attack
    from blades_tpu.client import ByzantineClient
    from blades_tpu.simulator import _CompositeAttack

    n, f = 10, 3
    m = gaussian(k=n, d=30, seed=4)
    byz = np.arange(n) < f

    ref_attackers = [
        ref.attackers.alieclient.AlieClient(num_clients=n, num_byzantine=f),
        ref.attackers.alieclient.AlieClient(num_clients=n, num_byzantine=f),
        ref.attackers.ipmclient.IpmClient(epsilon=0.5),
    ]
    clients = []
    for i, row in enumerate(t(m)):
        c = ref_attackers[i] if i < f else ref.client.BladesClient(id=str(i))
        c.set_id(str(i))
        c.save_update(row)
        clients.append(c)
    sim = _FakeSimulator(clients)
    for c in ref_attackers:
        c.omniscient_callback(sim)
    theirs = np.stack([c.get_update().numpy() for c in clients])

    comp = _CompositeAttack(
        [
            (0, ByzantineClient(
                attack=get_attack("alie", num_clients=n, num_byzantine=f))),
            (1, ByzantineClient(
                attack=get_attack("alie", num_clients=n, num_byzantine=f))),
            (2, ByzantineClient(attack=get_attack("ipm", epsilon=0.5))),
        ]
    )
    state = comp.init_state(n, m.shape[1])
    out, _ = comp.on_updates(
        jnp.asarray(m), jnp.asarray(byz), jax.random.PRNGKey(0), state
    )
    np.testing.assert_allclose(np.asarray(out), theirs, rtol=1e-4, atol=1e-5)
    # honest rows bit-untouched
    np.testing.assert_array_equal(np.asarray(out[f:]), m[f:])
